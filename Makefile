# One-command local check: the same static gates tier-1 runs.
#   make lint          - daftlint invariants (DTL001-DTL012, incl. the
#                        interprocedural lock-order/blocking/ledger/thread
#                        rules), emits daftlint.sarif, + bytecode-compile
#                        daft_tpu + profile smoke (QueryProfile schema gate)
#                        + obs smoke (flight-recorder schema gate)
#                        + chaos smoke (distributed-runner kill survival gate)
#   make precommit     - fast pre-commit path: daftlint --changed-only
#                        (git-dirty files only; unchanged-file summaries
#                        served from the content-hash cache)
#   make profile-smoke - tiny profiled query; validates the QueryProfile JSON,
#                        chrome trace, and metrics dump end to end
#   make obs-smoke     - flight recorder end to end: query log, health
#                        snapshot, forced slow-query bundle, health gauges
#   make chaos-smoke   - mixed workload through the distributed runner under
#                        seeded random worker SIGKILLs: every query terminal,
#                        zero leaked worker processes
#   make cache-smoke   - plan/program cache cold->warm->invalidate->warm
#                        cycle: hit counters, byte-identity, prefix replay,
#                        gauge surfaces
#   make batch-smoke   - dynamic-batching executor end to end: cross-morsel
#                        coalesce, budget/timer/end flushes, byte-identity
#                        with the knob off, warm pinned actors, zero leaks
#   make bench-compare - diff the two newest BENCH_r*.json, flag per-metric
#                        regressions beyond the noise threshold
#   make test          - full tier-1 test suite (CPU jax)

PY ?= python

.PHONY: lint precommit test profile-smoke obs-smoke chaos-smoke cache-smoke batch-smoke bench-compare

lint: profile-smoke obs-smoke chaos-smoke cache-smoke batch-smoke
	$(PY) -m tools.daftlint --jobs 8 --sarif daftlint.sarif
	$(PY) -m compileall -q daft_tpu

precommit:
	$(PY) -m tools.daftlint --changed-only --jobs 8

cache-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.cache_smoke

batch-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.batch_smoke

profile-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.profile_smoke

obs-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.obs_smoke

chaos-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.chaos_smoke

bench-compare:
	$(PY) -m tools.bench_compare

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
