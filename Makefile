# One-command local check: the same static gates tier-1 runs.
#   make lint          - daftlint invariants (DTL001-DTL006) + bytecode-compile
#                        daft_tpu + profile smoke (QueryProfile schema gate)
#   make profile-smoke - tiny profiled query; validates the QueryProfile JSON,
#                        chrome trace, and metrics dump end to end
#   make test          - full tier-1 test suite (CPU jax)

PY ?= python

.PHONY: lint test profile-smoke

lint: profile-smoke
	$(PY) -m tools.daftlint
	$(PY) -m compileall -q daft_tpu

profile-smoke:
	JAX_PLATFORMS=cpu $(PY) -m tools.profile_smoke

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
