# One-command local check: the same static gates tier-1 runs.
#   make lint   - daftlint invariants (DTL001-DTL005) + bytecode-compile daft_tpu
#   make test   - full tier-1 test suite (CPU jax)

PY ?= python

.PHONY: lint test

lint:
	$(PY) -m tools.daftlint
	$(PY) -m compileall -q daft_tpu

test:
	JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow'
