"""Benchmark harness: TPC-H Q1 wall-clock vs the pyarrow oracle baseline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"} where value is
lineitem rows/sec through the full daft_tpu engine (lazy plan -> optimizer ->
physical plan -> streaming executor) for TPC-H Q1, and vs_baseline is the
speedup vs a hand-written pyarrow.compute implementation of the same query
(>1.0 = faster than baseline). Result parity vs the oracle is asserted before
timing; a parity failure prints value 0.

Reference role-equivalent: tests/benchmarks/test_local_tpch.py +
benchmarking/tpch (SURVEY.md §6).
"""

from __future__ import annotations

import json
import sys
import time


def _best_of(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def main() -> int:
    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 0.1
    from benchmarks import tpch

    tables = tpch.generate_tables(scale=scale, seed=42)
    lineitem = tables["lineitem"]
    rows = lineitem.num_rows

    import daft_tpu as dt
    from daft_tpu.context import set_execution_config

    def run_daft():
        # rebuild the plan each run: .collect() caches its materialized result
        return tpch.q1(dt.from_arrow(lineitem)).collect().to_pydict()

    def run_oracle():
        return tpch.oracle_q1(lineitem)

    # pick the faster executor mode for this host (morsel-parallel pays off on
    # many-core hosts; sequential wins on small ones)
    timings = {}
    for threads in (1, 0):
        set_execution_config(executor_threads=threads)
        timings[threads], _ = _best_of(run_daft, n=2)
    best_mode = min(timings, key=timings.get)
    set_execution_config(executor_threads=best_mode)

    # warm-up + parity check
    got = run_daft()
    want = run_oracle()
    ok = set(got) == set(want)
    if ok:
        for k in want:
            for a, b in zip(got[k], want[k]):
                if isinstance(b, float):
                    ok = ok and abs(a - b) <= max(1e-9 * abs(b), 1e-6)
                else:
                    ok = ok and a == b
    if not ok:
        print(json.dumps({"metric": f"tpch_q1_sf{scale:g}_rows_per_sec",
                          "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
                          "error": "parity_mismatch"}))
        return 1

    t_daft, _ = _best_of(run_daft)
    t_oracle, _ = _best_of(run_oracle)
    print(json.dumps({
        "metric": f"tpch_q1_sf{scale:g}_rows_per_sec",
        "value": round(rows / t_daft, 1),
        "unit": "rows/s",
        "vs_baseline": round(t_oracle / t_daft, 3),
    }))
    return 0


if __name__ == "__main__":
    sys.exit(main())
