"""Benchmark harness: TPC-H through the engine, host path vs TPU device path.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline: TPC-H Q1 rows/sec through the DEVICE path of the full engine
(lazy plan -> optimizer -> fused physical plan -> jitted filter+segment-agg
kernels on the TPU) over HBM-resident data — the deployment shape this
framework targets (stage once, query many; the host<->device link is the
bottleneck, compute is not). vs_baseline is the speedup vs a hand-written
pyarrow.compute oracle of the same query on this host (>1.0 = faster).

Extras report the host-path engine, Q6, and first-query (cold staging) cost
so the staging amortization is visible, not hidden; q1_device_hbm_gbps
models achieved HBM read bandwidth (touched column bytes / wall time) so
"fast on TPU" is a number trackable across rounds against v5e peak
(~819 GB/s).

Result parity vs the oracle is asserted before timing (device money sums run
reduced-precision float32 with Kahan-compensated combines; parity tolerance
is relative 1e-6). A parity failure prints value 0.

The accelerator tunnel is intermittent: when it is wedged at bench time,
the freshest mid-round BENCH_device_snapshot.json (written by
tools/bench_snapshot.py whenever the tunnel breathes) is reported instead,
marked source=mid_round_snapshot. The honest {value: 0, tpu_unreachable}
only appears when the TPU was unreachable for the entire round.

Reference role-equivalent: tests/benchmarks/test_local_tpch.py +
benchmarking/tpch (SURVEY.md §6); baseline targets in BASELINE.md.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

SNAPSHOT_PATH = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                             "BENCH_device_snapshot.json")


def _best_of(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def _parity(got: dict, want: dict, rtol: float) -> bool:
    if set(got) != set(want):
        return False
    for k in want:
        if len(got[k]) != len(want[k]):
            return False
        for a, b in zip(got[k], want[k]):
            if isinstance(b, float):
                if abs(a - b) > max(rtol * abs(b), 1e-6):
                    return False
            elif a != b:
                return False
    return True


def _tpu_alive(timeout_s: int = 180) -> bool:
    """Probe the device with a tiny jit IN A SUBPROCESS: a wedged accelerator
    tunnel blocks inside the PJRT client's C init where no Python signal can
    interrupt, so the only safe watchdog is a killable child process."""
    import subprocess

    try:
        import jax

        platforms = jax.config.jax_platforms  # honor a parent cpu-pin
    except Exception:
        platforms = None
    pin = (f"jax.config.update('jax_platforms', {platforms!r}); "
           if platforms else "")
    code = ("import jax; " + pin + "import jax.numpy as jnp; "
            "jax.jit(lambda a: (a * 2).sum())(jnp.arange(128))"
            ".block_until_ready(); print('alive')")
    try:
        out = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                             capture_output=True, text=True)
        return out.returncode == 0 and "alive" in out.stdout
    except Exception:
        return False


# the child's result-line marker. Plain ASCII on purpose: control chars
# like \x1e are LINE BOUNDARIES to str.splitlines() and would be consumed
# as separators instead of surviving as a prefix
_JSON_MARK = "##BENCH_JSON##"


def _child_json(args, timeout_s: int):
    """Run a child process and parse the single _JSON_MARK-prefixed JSON
    line from its stdout; None on timeout/crash/no line. The same
    killable-child discipline as _tpu_alive — anything that might touch a
    wedged tunnel must be killable from outside. A deterministic child
    crash is NOT silent: its stderr tail echoes to our stderr so a
    regression in the rungs stays debuggable."""
    import subprocess

    try:
        r = subprocess.run(args, timeout=timeout_s, capture_output=True,
                           text=True)
    except subprocess.TimeoutExpired:
        print(f"bench child timed out after {timeout_s}s (wedged tunnel?)",
              file=sys.stderr)
        return None
    except Exception as e:
        print(f"bench child failed to launch: {e!r}", file=sys.stderr)
        return None
    for line in (r.stdout or "").splitlines():
        if line.startswith(_JSON_MARK):
            try:
                return json.loads(line[len(_JSON_MARK):])
            except ValueError:
                break
    err = (r.stderr or "")[-2000:]
    if err:
        print(err, file=sys.stderr)
    return None


def _run_device_rungs_guarded(scale: float, timeout_s: int = 2400,
                              repo: Optional[str] = None):
    """run_device_rungs in a KILLABLE child. The liveness probe can pass
    and the tunnel still wedge MID-RUNG — inside a PJRT C call no Python
    signal fires, so an in-process run could hang the whole bench (and the
    driver's round-end collection with it). Timeout/crash -> None; the
    caller falls back to the snapshot/host path as if the probe had
    failed. The parent's jax_platforms config pin forwards into the child
    (same as _tpu_alive: env-var routes are too late on this image), so
    the run targets exactly the platform the probe proved alive."""
    repo = repo or os.path.dirname(os.path.abspath(__file__))
    try:
        import jax

        platforms = jax.config.jax_platforms
    except Exception:
        platforms = None
    pin = (f"import jax; jax.config.update('jax_platforms', {platforms!r})\n"
           if platforms else "")
    code = (
        "import json, sys\n"
        f"sys.path.insert(0, {repo!r})\n"
        + pin +
        "import bench\n"
        "out = bench.run_device_rungs(float(sys.argv[1]))\n"
        f"print({_JSON_MARK!r} + json.dumps(out))\n")
    return _child_json([sys.executable, "-c", code, str(scale)], timeout_s)


# Q1 touches these lineitem columns on device (f32/i32 after 32-bit staging):
# quantity, extendedprice, discount, tax, returnflag, linestatus, shipdate.
_Q1_DEVICE_COLS = 7
_Q1_BYTES_PER_VAL = 4



class _Setup:
    """Tables + resident frame + query runners + host measurement, shared by
    the device rungs and the wedged-tunnel host fallback so the two paths
    cannot drift (same thread tuning, same parity gates, same oracles)."""

    def __init__(self, scale: float):
        from benchmarks import tpch

        import daft_tpu as dt
        from daft_tpu.context import get_context

        self.tpch, self.dt = tpch, dt
        self.tables = tpch.generate_tables(scale=scale, seed=42)
        self.lineitem = self.tables["lineitem"]
        self.rows = self.lineitem.num_rows
        self.cfg = get_context().execution_config
        self.cfg.enable_result_cache = False  # measure execution, not cache hits
        # one resident frame reused across runs: partitions carry the HBM
        # staging cache, so device-path warm runs skip the host->device copy
        self.frame = dt.from_arrow(self.lineitem).collect()
        self.want_q1 = tpch.oracle_q1(self.lineitem)
        self.want_q6 = {"revenue": [tpch.oracle_q6(self.lineitem)]}

    def run_q1(self):
        return self.tpch.q1(self.frame).collect().to_pydict()

    def run_q6(self):
        return self.tpch.q6(self.frame).collect().to_pydict()

    def measure_host(self):
        """Tune executor threads on the host path, parity-gate, time Q1/Q6.
        Returns (t_q1, t_q6) or None on parity failure."""
        from daft_tpu.context import get_context, set_execution_config

        self.cfg.use_device_kernels = False
        timings = {}
        for threads in (1, 0):
            set_execution_config(executor_threads=threads)
            timings[threads], _ = _best_of(self.run_q1, n=2)
        set_execution_config(executor_threads=min(timings, key=timings.get))
        self.cfg = get_context().execution_config
        self.cfg.enable_result_cache = False
        if not _parity(self.run_q1(), self.want_q1, rtol=1e-9):
            return None
        t1, _ = _best_of(self.run_q1)
        t6, _ = _best_of(self.run_q6)
        return t1, t6

    def join_frames(self):
        """Resident customer/orders/nation frames for the Q3/Q5 rungs."""
        dt, tables = self.dt, self.tables
        return (dt.from_arrow(tables["customer"]).collect(),
                dt.from_arrow(tables["orders"]).collect(),
                dt.from_arrow(tables["nation"]).collect())


def _save_rung_profile(out: dict, rung: str, build_query) -> None:
    """Run one profiled execution of a rung's query and save the
    QueryProfile JSON next to the BENCH snapshot, recording
    `<rung>_critical_path_op` + the top-3 ops by self-time in the rung's
    metrics — perf regressions become diagnosable from artifacts alone.
    Best-effort: a profiling failure never costs the rung its numbers."""
    try:
        path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            f"PROFILE_{rung}.json")
        q = build_query()
        q.collect(profile=path)
        qp = q.profile()
        from daft_tpu.profile import validate_profile

        errs = validate_profile(qp.to_dict())
        if errs:
            out[f"{rung}_profile_error"] = f"schema: {errs[0]}"[:120]
            return
        out[f"{rung}_critical_path_op"] = qp.critical_path_op
        out[f"{rung}_top_ops"] = [
            {"op": o["op"], "self_ms": round(o["self_ns"] / 1e6, 2),
             "io_ms": round(o["io_wait_ns"] / 1e6, 2)}
            for o in qp.top_ops(3)]
        out[f"{rung}_profile_file"] = os.path.basename(path)
    except Exception as e:
        out[f"{rung}_profile_error"] = f"{type(e).__name__}: {e}"[:120]


def measure_sketch_exchange(n_rows: int = 50_000, n_parts: int = 8) -> dict:
    """Before/after rows-exchanged comparison for the sketch subsystem: the
    SAME grouped approx_count_distinct with sketch_aggregations off (raw
    rows hash-shuffled by key, the pre-subsystem plan) vs on (stage-1
    sketch rows — one Binary row per partition x group — ride the
    exchange). Reads the engine's exchange_rows counter, so the number is
    what actually crossed the boundary, not a model."""
    import numpy as np

    import daft_tpu as dt
    from daft_tpu import col

    rng = np.random.RandomState(7)
    data = {"k": (np.arange(n_rows) % 16).tolist(),
            "v": rng.randint(0, n_rows // 2, n_rows).tolist()}
    cfg = dt.context.get_context().execution_config
    out: dict = {"rows": n_rows, "partitions": n_parts}
    prev = cfg.sketch_aggregations
    try:
        for label, flag in (("raw", False), ("sketch", True)):
            cfg.sketch_aggregations = flag
            q = (dt.from_pydict(data).into_partitions(n_parts)
                 .groupby("k").agg(col("v").approx_count_distinct()))
            q.collect()
            counters = q.stats.snapshot()["counters"]
            out[f"{label}_rows_exchanged"] = counters.get("exchange_rows", 0)
            out[f"{label}_bytes_exchanged"] = counters.get("exchange_bytes", 0)
    finally:
        cfg.sketch_aggregations = prev
    if out.get("sketch_rows_exchanged"):
        out["exchange_reduction_x"] = round(
            out["raw_rows_exchanged"] / out["sketch_rows_exchanged"], 1)
    if out.get("sketch_bytes_exchanged"):
        out["bytes_reduction_x"] = round(
            out["raw_bytes_exchanged"] / out["sketch_bytes_exchanged"], 1)
    return out


def measure_exchange(n_rows: int = 400_000, n_parts: int = 8,
                     n_keys: int = 40_000, selectivity: float = 0.05,
                     n_groups: int = 4_000) -> dict:
    """Exchange v2 rung (ISSUE 9): before/after A/B of the exchange-
    reduction legs, reading the engine's own counters so the numbers are
    what actually crossed the exchange. Every leg is an interleaved
    best-of A/B (the spill rung's discipline) so the build host's drifting
    memory bandwidth cancels.

    Leg 1 — selective join (q3 shape): a small dimension keeping
    ``selectivity`` of the key space inner-joins a wide fact (float
    measures + a comment-like string payload, the part of a q3 row that
    makes its exchange expensive) across the co-partitioned hash exchange.
    With ``runtime_join_filters`` on, the probe side prunes before
    bucketing — ``exchange_join_rows`` collapses and
    ``exchange_join_rows_pruned`` counts the rows that never
    bucketed/spilled/merged.

    Leg 2 — high-cardinality group-by: a count+int-sum aggregation whose
    stage-2 combine is reassociation-exact, so ``hierarchical_exchange_
    combine`` folds the P-per-bucket map-side pieces to ~1 —
    ``exchange_groupby_rows`` drops by ~n_parts.

    Leg 3 — budgeted (out-of-core) exchange: a hash repartition of
    low-cardinality payload under a memory budget small enough to spill.
    ``exchange_payload_encoding`` engages only on budgeted queries (the
    unbudgeted in-memory exchange would pay the encode pass for nothing),
    shrinking both the ledgered and the spilled bytes
    (``exchange_spill_bytes`` vs ``_raw``).
    """
    import string

    import numpy as np

    import daft_tpu as dt
    from daft_tpu import col

    rng = np.random.RandomState(13)
    dim_keys = rng.choice(n_keys, size=int(n_keys * selectivity),
                          replace=False)
    dim = {"k": dim_keys.tolist(), "seg": (dim_keys % 7).tolist()}
    alpha = np.array(list(string.ascii_lowercase))
    comments = ["".join(alpha[rng.randint(0, 26, 32)]) for _ in range(4096)]
    fact = {"k": rng.randint(0, n_keys, n_rows).tolist(),
            "price": rng.rand(n_rows).tolist(),
            "disc": rng.rand(n_rows).tolist(),
            "comment": [comments[i % 4096] for i in range(n_rows)]}
    gb = {"g": rng.randint(0, n_groups, n_rows).tolist(),
          "c": rng.randint(0, 1000, n_rows).tolist()}
    status = ["PENDING", "SHIPPED", "DELIVERED", "RETURNED"]
    enc_rows = n_rows // 4
    encd = {"k": rng.randint(0, 500, enc_rows).tolist(),
            "s": [status[i % 4] for i in range(enc_rows)],
            "v": rng.rand(enc_rows).tolist()}

    cfg = dt.context.get_context().execution_config
    knobs = ("runtime_join_filters", "exchange_payload_encoding",
             "hierarchical_exchange_combine")
    prev = {k: getattr(cfg, k) for k in knobs}
    prev_cache = cfg.enable_result_cache
    prev_budget = cfg.memory_budget_bytes
    cfg.enable_result_cache = False

    def run_join():
        d = dt.from_pydict(dim).into_partitions(n_parts).collect()
        f = dt.from_pydict(fact).into_partitions(n_parts).collect()
        q = (d.join(f, on="k", how="inner", strategy="hash")
             .groupby("seg")
             .agg((col("price") * (1 - col("disc"))).sum().alias("rev"),
                  col("comment").count().alias("nc")))
        t0 = time.perf_counter()
        q.collect()
        return time.perf_counter() - t0, q.stats.snapshot()["counters"]

    def run_groupby():
        f = dt.from_pydict(gb).into_partitions(n_parts).collect()
        q = f.groupby("g").agg(col("c").sum().alias("s"),
                               col("c").count().alias("n"))
        t0 = time.perf_counter()
        q.collect()
        return time.perf_counter() - t0, q.stats.snapshot()["counters"]

    def run_encode():
        f = dt.from_pydict(encd).into_partitions(n_parts).collect()
        # budget sized well under the ~30 B/row payload so the exchange
        # ALWAYS spills, whatever scale the rung runs at
        cfg.memory_budget_bytes = max(64 * 1024, enc_rows * 8)
        try:
            q = f.repartition(n_parts, "k")
            t0 = time.perf_counter()
            q.collect()
            return time.perf_counter() - t0, q.stats.snapshot()["counters"]
        finally:
            cfg.memory_budget_bytes = prev_budget

    legs = {"join": run_join, "groupby": run_groupby, "encode": run_encode}
    out: dict = {"rows": n_rows, "partitions": n_parts,
                 "join_selectivity": selectivity}
    try:
        walls: dict = {(leg, m): [] for leg in legs for m in (False, True)}
        counters: dict = {}
        for _ in range(3):  # interleaved best-of
            for mode in (False, True):
                for k in knobs:
                    setattr(cfg, k, mode)
                for leg, fn in legs.items():
                    w, c = fn()
                    walls[(leg, mode)].append(w)
                    counters[(leg, mode)] = c
        for leg in legs:
            on = counters[(leg, True)]
            off = counters[(leg, False)]
            rows_on = on.get("exchange_rows", 0)
            rows_off = off.get("exchange_rows", 0)
            out[f"exchange_{leg}_rows"] = rows_on
            out[f"exchange_{leg}_rows_raw"] = rows_off
            if rows_on:
                out[f"exchange_{leg}_reduction_x"] = round(
                    rows_off / rows_on, 2)
            out[f"{leg}_exchange_bytes"] = on.get("exchange_bytes", 0)
            t_on = min(walls[(leg, True)])
            t_off = min(walls[(leg, False)])
            out[f"exchange_{leg}_speedup_x"] = round(t_off / t_on, 3)
            out[f"exchange_{leg}_wall_s"] = round(t_on, 4)
        out["exchange_join_rows_pruned"] = counters[("join", True)].get(
            "join_filter_rows_pruned", 0)
        out["exchange_precombined_rows"] = counters[("groupby", True)].get(
            "exchange_precombined_rows", 0)
        enc_on = counters[("encode", True)]
        enc_off = counters[("encode", False)]
        out["exchange_bytes_encoded"] = enc_on.get("exchange_bytes_encoded", 0)
        out["exchange_spill_bytes"] = enc_on.get("spill_write_bytes", 0)
        out["exchange_spill_bytes_raw"] = enc_off.get("spill_write_bytes", 0)
    finally:
        for k, v in prev.items():
            setattr(cfg, k, v)
        cfg.enable_result_cache = prev_cache
        cfg.memory_budget_bytes = prev_budget
    return out


def measure_serving(scale: float = 0.01, offered_qps: float = 6.0,
                    duration_s: float = 8.0, slots: int = 4,
                    queue_depth: int = 4) -> dict:
    """Serving rung (ISSUE 8): sustained MIXED workload — TPC-H q1 + q3 +
    a multimodal-style python-UDF query — submitted to the ServingRuntime
    at a FIXED offered load. Emits achieved throughput (serving_qps),
    latency quantiles over completed queries (serving_p50_s /
    serving_p99_s), and how many submissions admission control shed
    (serving_shed_count — 0 while the host keeps up with the offered
    load; a sustained regression shows up as rising p99 and then a
    nonzero shed count, both flagged by bench_compare's suffix rules)."""
    import hashlib

    import numpy as np

    import daft_tpu as dt
    from daft_tpu import DataType, col
    from daft_tpu.errors import DaftOverloadedError
    from benchmarks import tpch

    tables = tpch.generate_tables(scale=scale)
    lineitem = dt.from_arrow(tables["lineitem"]).collect()
    cust = dt.from_arrow(tables["customer"]).collect()
    orders = dt.from_arrow(tables["orders"]).collect()
    # multimodal-style stage: a per-row python "decode" over binary blobs
    rng = np.random.RandomState(11)
    blobs = [rng.bytes(2048) for _ in range(512)]

    @dt.udf(return_dtype=DataType.string())
    def digest(b):
        return [hashlib.sha1(v).hexdigest() if v is not None else None
                for v in b.to_pylist()]

    blob_df = dt.from_pydict({"b": blobs}).collect()
    templates = [
        lambda: tpch.q1(lineitem),
        lambda: tpch.q3(cust, orders, lineitem),
        lambda: blob_df.select(digest(col("b")).alias("h")),
    ]
    cfg = dt.context.get_context().execution_config
    prev_cache = cfg.enable_result_cache
    cfg.enable_result_cache = False  # measure execution, not lookups
    from daft_tpu.serve import ServingRuntime

    rt = ServingRuntime(max_concurrent_queries=slots,
                        queue_depth=queue_depth, admission_timeout_s=None)
    handles = []
    shed = 0
    interval = 1.0 / offered_qps
    t0 = time.perf_counter()
    i = 0
    try:
        while time.perf_counter() - t0 < duration_s:
            target = t0 + i * interval
            now = time.perf_counter()
            if target > now:
                time.sleep(target - now)
            try:
                handles.append(rt.submit(templates[i % len(templates)]()))
            except DaftOverloadedError:
                shed += 1
            i += 1
        lat = []
        completed = 0
        for h in handles:
            err = h.exception(120)
            # a query still not terminal after the wait (wedged) is NOT
            # completed — exception() returns None in that case too
            if err is None and h.done():
                completed += 1
                # queue wait + execution: what a caller actually sees
                lat.append(h.latency_s())
        wall = time.perf_counter() - t0
        lat.sort()

        def q(p):
            if not lat:
                return 0.0
            return lat[min(len(lat) - 1, int(p * len(lat)))]

        out = {
            "serving_offered_qps": offered_qps,
            "serving_qps": round(completed / wall, 2),
            "serving_p50_s": round(q(0.50), 4),
            "serving_p99_s": round(q(0.99), 4),
            "serving_shed_count": shed,
            "serving_completed": completed,
            "serving_submitted": i,
        }
        out.update(_measure_repeat_shapes(rt, [
            lambda: tpch.q1(lineitem),
            lambda: tpch.q3(cust, orders, lineitem),
        ]))
        try:
            out.update(_measure_persist_legs())
        except Exception as e:  # persist legs must not sink the rung
            out["serving_persist_error"] = f"{type(e).__name__}: {e}"[:200]
        return out
    finally:
        rt.shutdown(timeout_s=30)
        cfg.enable_result_cache = prev_cache


def _measure_repeat_shapes(rt, shapes, runs_per_shape: int = 12) -> dict:
    """Repeat-shape leg (ISSUE 13): each plan shape submitted
    ``runs_per_shape`` times sequentially through the serving runtime —
    run 1 plans cold, runs 2..N serve the cached plan. Emits warm-vs-cold
    p50, the plan-cache hit rate over the leg, and the planning share of
    wall before/after (the compile-time share the cache removes)."""
    from daft_tpu.adapt.history import HISTORY
    from daft_tpu.adapt.plancache import PLAN_CACHE

    PLAN_CACHE.clear()
    HISTORY.clear()
    pc0 = PLAN_CACHE.snapshot()
    cold_lat, warm_lat = [], []
    cold_share, warm_share = [], []
    for shape in shapes:
        for j in range(runs_per_shape):
            h = rt.submit(shape())
            h.result(120)
            lat = h.latency_s() or 0.0
            rec = h.record() or {}
            share = 0.0
            if rec.get("wall_s"):
                share = rec.get("planning_ms", 0.0) / (
                    rec["wall_s"] * 1000.0)
            if j == 0:
                cold_lat.append(lat)
                cold_share.append(share)
            else:
                warm_lat.append(lat)
                warm_share.append(share)
    pc1 = PLAN_CACHE.snapshot()
    hits = pc1["hits"] - pc0["hits"]
    misses = pc1["misses"] - pc0["misses"]

    def p50(xs):
        xs = sorted(xs)
        return xs[len(xs) // 2] if xs else 0.0

    return {
        "serving_cold_p50_s": round(p50(cold_lat), 4),
        "serving_warm_p50_s": round(p50(warm_lat), 4),
        "serving_plan_cache_hit_rate": round(
            hits / max(1, hits + misses), 4),
        "serving_planning_share_cold_pct": round(
            100.0 * sum(cold_share) / max(1, len(cold_share)), 2),
        "serving_planning_share_warm_pct": round(
            100.0 * sum(warm_share) / max(1, len(warm_share)), 2),
    }


_PERSIST_CHILD = r"""
import json, os, sys, time
sys.path.insert(0, sys.argv[1])
os.environ.setdefault("JAX_PLATFORMS", "cpu")
path, cache_dir = sys.argv[2], sys.argv[3]
import daft_tpu as dt
from daft_tpu import col, persist
from daft_tpu.adapt.plancache import PLAN_CACHE
dt.set_execution_config(cache_dir=cache_dir)
walls = []
for thresh in (0.0, 10.0, 20.0):
    t0 = time.perf_counter()
    (dt.read_parquet(path)
     .select((col("v") * 2.0).alias("w"), col("k"))
     .where(col("w") >= thresh)
     .groupby("k").agg(col("w").sum().alias("s")).sort("k")).collect()
    walls.append(time.perf_counter() - t0)
pc = PLAN_CACHE.snapshot()
ps = persist.snapshot()
dt.shutdown(timeout_s=10)
print(json.dumps({"walls": walls, "plan_hits": pc["hits"],
                  "plan_misses": pc["misses"],
                  "persist_hits": ps["hits"],
                  "persist_misses": ps["misses"]}))
"""


def _measure_persist_legs() -> dict:
    """Persistent-cache legs (daft_tpu/persist/): restart warm-start —
    two real interpreters over one cache_dir, each planning/serving three
    distinct shapes once; the warm interpreter replays plans and prefix
    results straight from disk — and a 2-worker fleet A/B where the
    second identical distributed run reuses worker-hosted prefix results
    (``result_store_fleet_warm_x`` = cold wall / warm wall)."""
    import json
    import shutil
    import subprocess
    import tempfile

    import pyarrow as pa
    import pyarrow.parquet as pq

    root = os.path.dirname(os.path.abspath(__file__))
    d = tempfile.mkdtemp(prefix="bench_persist_")
    out: dict = {}
    try:
        path = os.path.join(d, "t.parquet")
        pq.write_table(pa.table(
            {"k": [i % 7 for i in range(20000)],
             "v": [float(i) for i in range(20000)]}), path)
        cache_dir = os.path.join(d, "cache")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        runs = []
        for _leg in ("cold", "warm"):
            p = subprocess.run(
                [sys.executable, "-c", _PERSIST_CHILD, root, path,
                 cache_dir],
                capture_output=True, text=True, timeout=300, env=env)
            if p.returncode != 0:
                raise RuntimeError(
                    f"persist leg interpreter died: {p.stderr[-500:]}")
            runs.append(json.loads(p.stdout.strip().splitlines()[-1]))
        cold, warm = runs

        def p50(xs):
            xs = sorted(xs)
            return xs[len(xs) // 2] if xs else 0.0

        out["serving_restart_cold_p50_s"] = round(p50(cold["walls"]), 4)
        out["serving_restart_warm_p50_s"] = round(p50(warm["walls"]), 4)
        lookups = warm["persist_hits"] + warm["persist_misses"]
        out["persist_hit_rate"] = round(
            warm["persist_hits"] / max(1, lookups), 4)
        out.update(_measure_fleet_warm(d))
    finally:
        shutil.rmtree(d, ignore_errors=True)
    return out


def _measure_fleet_warm(d: str, workers: int = 2, parts: int = 8) -> dict:
    """2-worker prefix reuse: the same file-backed map-chain query run
    twice on a warmed fleet with a shared cache_dir — run 1 populates the
    per-worker result stores, run 2 (driver memory tiers cleared) serves
    the scan+map prefix from worker disk / peer fetch instead of
    recomputing it."""
    import pyarrow as pa
    import pyarrow.parquet as pq

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.adapt.resultcache import RESULT_CACHE
    from daft_tpu.context import get_context
    from daft_tpu.runners import partition_set_cache

    cfg = get_context().execution_config
    saved = {k: getattr(cfg, k) for k in
             ("distributed_workers", "cache_dir", "scan_tasks_min_size_bytes")}
    fdir = os.path.join(d, "fleet")
    os.makedirs(fdir, exist_ok=True)
    paths = []
    for i in range(parts):
        p = os.path.join(fdir, f"part{i}.parquet")
        pq.write_table(pa.table(
            {"k": [j % 5 for j in range(4000)],
             "v": [float(i * 4000 + j) for j in range(4000)]}), p)
        paths.append(p)
    try:
        cfg.cache_dir = os.path.join(d, "fleet_cache")
        cfg.scan_tasks_min_size_bytes = 0  # one task per file
        cfg.distributed_workers = workers

        def q(mult: float = 3.0):
            return (dt.read_parquet(paths)
                    .select((col("v") * mult).alias("w"), col("k"))
                    .where(col("w") >= 0.0))

        # fleet spawn + worker warmup, untimed — a DIFFERENT literal, so
        # the measured shape's store entries don't exist yet at run 1
        _ = q(mult=5.0).collect()
        walls = []
        for _run in range(2):
            RESULT_CACHE.clear()
            partition_set_cache().clear()
            t0 = time.perf_counter()
            q().collect()
            walls.append(time.perf_counter() - t0)
        return {"result_store_fleet_warm_x": round(
            walls[0] / max(walls[1], 1e-9), 3)}
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)


def measure_distributed(scale: float = 0.02, workers: int = 2,
                        trials: int = 2) -> dict:
    """Distributed-runner rung (ISSUE 11): interleaved best-of A/B of the
    local runner vs the N-worker multi-process runner on the q1 shape
    (same data, same plan — the A/B isolates transport+supervision
    overhead), plus a RECOVERY leg: the same distributed query with one
    worker SIGKILLed mid-query via the deterministic ``worker.exec``
    chaos fault. Emits the walls, the distributed-vs-local ratio, and
    ``distributed_recovery_overhead_pct`` — what surviving a worker loss
    costs relative to the undisturbed distributed run. Event counts from
    the recovery leg (losses/redispatches) are recorded as pins, not
    perf metrics."""
    from benchmarks import tpch

    import daft_tpu as dt
    from daft_tpu import faults
    from daft_tpu.context import get_context
    from daft_tpu.dist import supervisor as sup

    tables = tpch.generate_tables(scale=scale)
    frame = dt.from_arrow(tables["lineitem"]).repartition(8).collect()
    cfg = get_context().execution_config
    saved = {k: getattr(cfg, k) for k in ("distributed_workers",
                                          "enable_result_cache",
                                          "partition_integrity",
                                          "cluster_telemetry",
                                          "speculative_execution",
                                          "speculation_min_s",
                                          "speculation_quantile_factor",
                                          "peer_shuffle",
                                          "distributed_workers_min",
                                          "distributed_workers_max",
                                          "scan_tasks_min_size_bytes")}
    cfg.enable_result_cache = False
    walls = {"local": [], "dist": []}
    out = {"distributed_workers": workers}
    try:
        # pool spawn AND the workers' first-query warmup (imports, acero
        # kernel init, op-cache fill) are one-time costs: pay both OUTSIDE
        # the timed region so the A/B measures steady-state dispatch
        cfg.distributed_workers = workers
        _ = tpch.q1(frame).collect()
        # q1's float sums reassociate in the threaded acero grouped agg
        # (nondeterministic even local-vs-local at seed), so the parity
        # gate is the oracle tolerance the other q1 rungs use, not
        # byte-equality (the dist/ identity matrix test pins byte-identity
        # on deterministic plans)
        want = tpch.oracle_q1(tables["lineitem"])
        for _t in range(trials):
            for mode in ("local", "dist"):
                cfg.distributed_workers = 0 if mode == "local" else workers
                t0 = time.perf_counter()
                got = tpch.q1(frame).collect()
                walls[mode].append(time.perf_counter() - t0)
                if not _parity(got.to_pydict(), want, rtol=1e-6):
                    raise AssertionError(
                        f"distributed rung parity broke in mode {mode}")
        local_wall = min(walls["local"])
        dist_wall = min(walls["dist"])
        out["distributed_local_wall_s"] = round(local_wall, 4)
        out["distributed_wall_s"] = round(dist_wall, 4)
        out["distributed_speedup_x"] = round(local_wall / dist_wall, 3)
        # ---- recovery leg: kill one worker mid-query ---------------------
        cfg.distributed_workers = workers
        faults.arm("worker.exec", "nth", n=2)
        try:
            t0 = time.perf_counter()
            got = tpch.q1(frame).collect()
            recovery_wall = time.perf_counter() - t0
        finally:
            faults.disarm()
        if not _parity(got.to_pydict(), want, rtol=1e-6):
            raise AssertionError("recovery leg parity broke")
        c = got.stats.snapshot()["counters"]
        out["distributed_recovery_wall_s"] = round(recovery_wall, 4)
        out["distributed_recovery_overhead_pct"] = round(
            (recovery_wall - dist_wall) / dist_wall * 100.0, 1)
        out["distributed_worker_losses"] = c.get("worker_losses", 0)
        out["distributed_task_redispatches"] = c.get(
            "task_redispatches", 0)
        # ---- integrity A/B: checksums on vs off, interleaved ------------
        # (ISSUE 12 gate: end-to-end partition integrity — spill crc,
        # transport frame crc, encode crc — must cost < 3% on this leg)
        # interleaved on the SHARED warmed fleet (a fresh pool per mode
        # swings ±100ms on this host — far above the measured cost);
        # workers MIRROR the driver's per-frame checksum flag, so the
        # toggle flips both directions of frame traffic without respawn
        walls_i = {"on": [], "off": []}
        deltas = []
        for _t in range(max(24, trials)):
            # alternate the in-pair order (a fixed order systematically
            # taxes whichever mode runs first on this host) and estimate
            # from the MEDIAN of time-adjacent paired deltas over many
            # pairs: the 1-2 core build hosts drift in multi-second
            # phases and single pair deltas swing +-15%, an order of
            # magnitude above the ~1-2% true checksum cost (striped bulk
            # frames sample ~1.6% of the bytes; micro-measured 0.14 ms
            # per 3 MB frame per side) — the median over ~24 pairs is
            # the estimator that empirically centers on it
            order = ("on", "off") if _t % 2 == 0 else ("off", "on")
            pair = {}
            for mode in order:
                cfg.partition_integrity = (mode == "on")
                t0 = time.perf_counter()
                got = tpch.q1(frame).collect()
                pair[mode] = time.perf_counter() - t0
                walls_i[mode].append(pair[mode])
                if not _parity(got.to_pydict(), want, rtol=1e-6):
                    raise AssertionError(
                        f"integrity A/B parity broke (checksums {mode})")
            deltas.append((pair["on"] - pair["off"]) / pair["off"])
        cfg.partition_integrity = True
        deltas.sort()
        mid = len(deltas) // 2
        med = (deltas[mid] if len(deltas) % 2
               else (deltas[mid - 1] + deltas[mid]) / 2)
        out["integrity_wall_on_s"] = round(min(walls_i["on"]), 4)
        out["integrity_wall_off_s"] = round(min(walls_i["off"]), 4)
        out["integrity_overhead_pct"] = round(med * 100.0, 2)
        # ---- telemetry A/B: fragments on vs off, interleaved ------------
        # (ISSUE 15 gate: the cluster observability plane — per-task
        # fragment build on the worker, piggyback on the reply frame,
        # driver-side merge — must cost < 3% on this leg. Unprofiled
        # queries piggyback only the counters delta + log tail, so the
        # steady-state cost is one small dict per task per direction.
        # Same estimator as the integrity A/B: order-alternated pairs,
        # median of time-adjacent paired deltas.)
        walls_tel = {"on": [], "off": []}
        deltas_tel = []
        for _t in range(max(24, trials)):
            order = ("on", "off") if _t % 2 == 0 else ("off", "on")
            pair = {}
            for mode in order:
                cfg.cluster_telemetry = (mode == "on")
                t0 = time.perf_counter()
                got = tpch.q1(frame).collect()
                pair[mode] = time.perf_counter() - t0
                walls_tel[mode].append(pair[mode])
                if not _parity(got.to_pydict(), want, rtol=1e-6):
                    raise AssertionError(
                        f"telemetry A/B parity broke (fragments {mode})")
            deltas_tel.append((pair["on"] - pair["off"]) / pair["off"])
        cfg.cluster_telemetry = True
        deltas_tel.sort()
        mid = len(deltas_tel) // 2
        med_tel = (deltas_tel[mid] if len(deltas_tel) % 2
                   else (deltas_tel[mid - 1] + deltas_tel[mid]) / 2)
        out["dist_telemetry_wall_on_s"] = round(min(walls_tel["on"]), 4)
        out["dist_telemetry_wall_off_s"] = round(min(walls_tel["off"]), 4)
        out["dist_telemetry_overhead_pct"] = round(med_tel * 100.0, 2)
        # ---- straggler leg: one worker slowed, speculation on vs off ----
        from collections import deque

        from daft_tpu.faults import ENV_FAULT_SPEC

        sup.shutdown_worker_pool()
        os.environ[ENV_FAULT_SPEC] = json.dumps(
            {"site": "worker.task", "mode": "always", "delay_s": 0.5,
             "worker_id": 0})
        cfg.speculation_min_s = 0.15
        cfg.speculation_quantile_factor = 2.0
        try:
            walls_s = {}
            for mode in ("off", "on"):
                cfg.speculative_execution = (mode == "on")
                got = tpch.q1(frame).collect()  # (re)spawn + warm, slowly
                pool = sup._POOL
                if pool is not None:
                    # seed the p75 history with healthy walls so the
                    # straggler threshold does not drift with the
                    # warmup's straggled samples
                    with pool._cond:
                        for op in list(pool._op_walls):
                            pool._op_walls[op] = deque([0.01] * 8,
                                                       maxlen=64)
                t0 = time.perf_counter()
                got = tpch.q1(frame).collect()
                walls_s[mode] = time.perf_counter() - t0
                if not _parity(got.to_pydict(), want, rtol=1e-6):
                    raise AssertionError(
                        f"straggler leg parity broke (speculation {mode})")
            out["straggler_wall_off_s"] = round(walls_s["off"], 4)
            out["straggler_wall_on_s"] = round(walls_s["on"], 4)
            out["straggler_mitigation_speedup_x"] = round(
                walls_s["off"] / walls_s["on"], 3)
        finally:
            os.environ.pop(ENV_FAULT_SPEC, None)
        # restore straggler-leg tuning before the peer-plane legs
        for k in ("speculative_execution", "speculation_min_s",
                  "speculation_quantile_factor"):
            setattr(cfg, k, saved[k])
        _peer_plane_legs(out, cfg)
        return out
    finally:
        for k, v in saved.items():
            setattr(cfg, k, v)
        sup.shutdown_worker_pool()


def _peer_plane_legs(out: dict, cfg) -> None:
    """Peer-to-peer shuffle legs of the distributed rung (ISSUE 16).

    Driver-bytes leg — WEAK scaling (rows grow with N): parquet-backed
    shuffle+groupby at 2 and 4 workers, star (peer_shuffle off) vs p2p,
    reading each query's ``dist_driver_bytes`` counter (task payload +
    op bytes dispatched plus result bytes returned). The gate:
    ``dist_p2p_growth_x`` stays flat (within 10%) going 2 -> 4 workers
    while ``dist_star_growth_x`` tracks the ~2x data growth — on the p2p
    plane the driver ships scan-task metadata and piece-location maps,
    never payload, so its bytes do not scale with the data.

    Preemption leg — ``peer_preemption_overhead_pct``: SIGTERM one worker
    mid-shuffle (graceful drain: quiesce, let peers re-source its pieces,
    exit) on an elastic min==max pool that respawns the slot, vs the
    undisturbed run. Order-alternated pairs, median of time-adjacent
    paired deltas (same estimator as the integrity/telemetry A/Bs)."""
    import shutil
    import signal as _signal
    import tempfile
    import threading

    import pyarrow as pa
    import pyarrow.parquet as papq

    import daft_tpu as dt
    from daft_tpu.dist import supervisor as sup

    tmp = tempfile.mkdtemp(prefix="daft-peer-bench-")
    cfg.scan_tasks_min_size_bytes = 0
    # weak scaling: ROWS grow with N; the plan SHAPE (file count, bucket
    # count) stays fixed so the A/B isolates payload-byte growth from
    # task-count growth — star must ship the 2x payload through the
    # driver, p2p ships the same number of (tiny) scan tasks and
    # location maps either way
    n_files, n_buckets, rows_per_worker = 8, 8, 40_000
    try:
        # ---- driver-bytes leg: star vs p2p at 2 and 4 workers -----------
        def dataset(n_workers: int) -> str:
            d = os.path.join(tmp, f"n{n_workers}")
            if not os.path.isdir(d):
                os.makedirs(d)
                per_file = rows_per_worker * n_workers // n_files
                for i in range(n_files):
                    base = i * per_file
                    papq.write_table(
                        pa.table({"a": list(range(base, base + per_file)),
                                  "b": [v % 997 for v in
                                        range(base, base + per_file)]}),
                        os.path.join(d, f"part{i}.parquet"))
            return os.path.join(d, "*.parquet")

        def driver_bytes(n_workers: int, p2p: bool) -> int:
            sup.shutdown_worker_pool()
            cfg.distributed_workers = n_workers
            cfg.peer_shuffle = p2p
            pat = dataset(n_workers)
            q = (dt.read_parquet(pat)
                 .repartition(n_buckets, "b").groupby("b")
                 .agg(dt.col("a").sum().alias("s")).sort("b"))
            _ = q.collect()  # spawn + warm outside the measured query
            res = (dt.read_parquet(pat)
                   .repartition(n_buckets, "b").groupby("b")
                   .agg(dt.col("a").sum().alias("s")).sort("b").collect())
            c = res.stats.snapshot()["counters"]
            return int(c.get("dist_driver_bytes", 0))

        star = {n: driver_bytes(n, p2p=False) for n in (2, 4)}
        p2p = {n: driver_bytes(n, p2p=True) for n in (2, 4)}
        out["dist_driver_bytes_star"] = star[4]
        out["dist_driver_bytes_p2p"] = p2p[4]
        if star[2]:
            out["dist_star_growth_x"] = round(star[4] / star[2], 3)
        if p2p[2]:
            out["dist_p2p_growth_x"] = round(p2p[4] / p2p[2], 3)
        # ---- preemption leg: SIGTERM one worker mid-shuffle -------------
        sup.shutdown_worker_pool()
        workers = 2
        cfg.distributed_workers = workers
        cfg.distributed_workers_min = workers
        cfg.distributed_workers_max = workers
        cfg.peer_shuffle = True
        pat = dataset(workers)

        def run_query():
            return (dt.read_parquet(pat)
                    .repartition(n_buckets, "b").groupby("b")
                    .agg(dt.col("a").sum().alias("s")).sort("b")
                    .collect())

        want = run_query().to_pydict()  # spawn + warm

        def heal(timeout_s: float = 15.0):
            # wait for the elastic controller to respawn the drained slot
            deadline = time.time() + timeout_s
            while time.time() < deadline:
                pool = sup._POOL
                if pool is not None:
                    with pool._cond:
                        ready = sum(1 for w in pool.workers
                                    if w.state == "ready"
                                    and not w.draining)
                    if ready >= workers:
                        return
                time.sleep(0.1)

        def sigterm_one(after_s: float):
            time.sleep(after_s)
            pool = sup._POOL
            if pool is None:
                return
            with pool._cond:
                pids = [w.proc.pid for w in pool.workers
                        if w.proc is not None and w.state == "ready"]
            if pids:
                try:
                    os.kill(pids[0], _signal.SIGTERM)
                except OSError:
                    pass

        base = run_query()  # steady-state wall estimate for kill timing
        t0 = time.perf_counter()
        _ = run_query()
        est_wall = time.perf_counter() - t0
        deltas = []
        for t in range(8):
            pair = {}
            order = (("ctl", "kill") if t % 2 == 0 else ("kill", "ctl"))
            for mode in order:
                heal()
                killer = None
                if mode == "kill":
                    killer = threading.Thread(
                        target=sigterm_one, args=(est_wall * 0.3,),
                        daemon=True)
                    killer.start()
                t0 = time.perf_counter()
                got = run_query()
                pair[mode] = time.perf_counter() - t0
                if killer is not None:
                    killer.join()
                if got.to_pydict() != want:
                    raise AssertionError(
                        f"peer preemption leg parity broke ({mode})")
            deltas.append((pair["kill"] - pair["ctl"]) / pair["ctl"])
        deltas.sort()
        mid = len(deltas) // 2
        med = (deltas[mid] if len(deltas) % 2
               else (deltas[mid - 1] + deltas[mid]) / 2)
        out["peer_preemption_overhead_pct"] = round(med * 100.0, 1)
        del base
    finally:
        sup.shutdown_worker_pool()
        shutil.rmtree(tmp, ignore_errors=True)


def measure_streaming(scale: Optional[float] = None) -> dict:
    """Streaming-executor rung (ISSUE 10): interleaved best-of A/B of the
    morsel-driven pipeline vs partition-granular execution, on parquet ON
    DISK so the decode really streams. Two legs:

    - **first-row latency**: ``scan -> project -> limit`` (the interactive
      shape — the computed column blocks limit pushdown into the scan, so
      the partition-granular engine must decode+project a whole partition
      before the first row surfaces, while the streaming sink emits as
      soon as enough morsels exist and short-circuits the rest). Emits
      ``streaming_ttfr_s`` / ``streaming_serial_ttfr_s`` /
      ``streaming_ttfr_speedup_x`` from the engine's own
      time_to_first_row counter, results gated byte-identical.
    - **out-of-core q1-shape**: filter -> narrow projection ->
      hash repartition -> grouped agg under a memory budget of a quarter
      of the on-disk bytes. Three rungs: streaming and serial at the SAME
      budget (walls, spill events, and each mode's ledger-visible
      working-set peak — ``streaming_peak_mb`` stays bounded by the
      budget while ``streaming_serial_peak_mb`` overshoots it by the
      partition-granular path's parked whole-partition working set,
      honestly measured since MemoryLedger.exec_inflight), plus a
      **matched-memory serial rung**: serial re-run with its budget
      shrunk by the measured overshoot, so both executors live in the
      same real-memory envelope. That is where the spill-reduction claim
      is honest — at equal budgets the spill count is pinned by
      arithmetic (buckets alone exceed the budget; every append past the
      fill spills in any mode), but at equal MEMORY the serial run must
      hand the overshoot back to the buckets and provably spills more
      (``streaming_spill_reduction_x`` = matched-serial events /
      streaming events). Parity is gated with the spill rung's tolerance
      (the threaded acero grouped float sum is 1-ulp nondeterministic run
      to run, streaming or not)."""
    import shutil
    import tempfile

    import pyarrow.parquet as papq

    from benchmarks import tpch

    import daft_tpu as dt
    from daft_tpu import col
    from daft_tpu.context import get_context
    from daft_tpu.spill import MEMORY_LEDGER

    if scale is None:
        # the ttfr claim is about big partitions (first-row wait scales
        # with partition size on the partition-granular path, with
        # row-group size on the streaming path): use the largest scale
        # the host comfortably holds
        ram = _avail_ram_gb()
        scale = 1.0 if ram >= 16 else (0.5 if ram >= 6 else 0.1)
    big = tpch.generate_lineitem_only(scale=scale, seed=42)
    rows = big.num_rows
    tmp = tempfile.mkdtemp(prefix="bench_stream_")
    out: dict = {"streaming_rows": rows}
    try:
        nfiles = 8
        per = (rows + nfiles - 1) // nfiles
        for i in range(nfiles):
            sl = big.slice(i * per, per)
            if sl.num_rows:
                # 32Ki-row groups: the streaming decode grain (first morsel
                # = first row group); the whole-file read is unaffected
                # (pyarrow decodes all groups in one threaded call)
                papq.write_table(sl, os.path.join(tmp, f"part-{i:02d}.parquet"),
                                 row_group_size=32 * 1024)
        data_bytes = sum(os.path.getsize(os.path.join(tmp, f))
                         for f in os.listdir(tmp))
        del big
        cfg = get_context().execution_config
        saved = {k: getattr(cfg, k) for k in (
            "streaming_execution", "morsel_size_rows", "memory_budget_bytes",
            "enable_result_cache", "scan_tasks_min_size_bytes",
            "executor_threads", "exchange_payload_encoding",
            "parallel_shuffle_fanout", "use_device_kernels")}
        cfg.enable_result_cache = False
        cfg.scan_tasks_min_size_bytes = 1  # per-file tasks, both modes
        # host path only: try_stream declines under device kernels (whole
        # resident partitions feed one fused dispatch there), so leaving
        # the device-rung setting on would A/B serial-vs-serial
        cfg.use_device_kernels = False
        cfg.executor_threads = 4
        cfg.morsel_size_rows = 32 * 1024
        # the exchange encoder shrinks the ledger charge enough to stop the
        # small-scale budget engaging the spill machinery (same stand-down
        # as the spill rung — the exchange rung owns that measurement)
        cfg.exchange_payload_encoding = False
        glob_path = os.path.join(tmp, "*.parquet")

        # ---- leg 1: time-to-first-row on the interactive limit shape ----
        def ttfr_query():
            # the filter references a COMPUTED column, so neither the
            # predicate nor the limit can push into the scan — the
            # partition-granular engine must decode + map a whole
            # partition before its first row surfaces; the streaming sink
            # emits after the first few morsels. ONE merged scan task =
            # one big partition: the interactive-latency shape the claim
            # is about (first-row wait scales with partition size on the
            # partition-granular path, with ROW-GROUP size on the
            # streaming path)
            return (dt.read_parquet(glob_path)
                    .with_column("disc_price", col("l_extendedprice")
                                 * (1 - col("l_discount")))
                    .where(col("disc_price") > 0)
                    .limit(2000))

        def run_ttfr(streaming):
            cfg.streaming_execution = streaming
            cfg.memory_budget_bytes = None
            cfg.scan_tasks_min_size_bytes = 1 << 30  # merge into ONE task
            q = ttfr_query()
            got = q.collect().to_pydict()
            c = q.stats.snapshot()["counters"]
            return got, c.get("time_to_first_row_ns", 0) / 1e9, c

        best = {True: float("inf"), False: float("inf")}
        counters = {}
        want = None
        for pair in ((False, True), (True, False)):
            for mode in pair:
                got, ttfr, c = run_ttfr(mode)
                if want is None:
                    want = got
                elif got != want:
                    out["streaming_error"] = "ttfr_parity_mismatch"
                    return out
                if ttfr < best[mode]:
                    best[mode] = ttfr
                    counters[mode] = c
        out["streaming_ttfr_s"] = round(best[True], 4)
        out["streaming_serial_ttfr_s"] = round(best[False], 4)
        out["streaming_ttfr_speedup_x"] = round(
            best[False] / max(best[True], 1e-9), 2)
        out["streaming_ttfr_short_circuited"] = counters[True].get(
            "morsels_short_circuited", 0)

        # ---- leg 2: out-of-core q1-shape pipeline under budget ----------
        budget = max(16 * 1024 * 1024, data_bytes // 4)
        cfg.memory_budget_bytes = budget
        cfg.scan_tasks_min_size_bytes = 1  # back to per-file tasks
        # the parallel fanout stage parks split outputs identically in
        # both modes; inline it so the A/B isolates the scan->map segment
        # the streaming knob actually changes
        cfg.parallel_shuffle_fanout = False

        def ooc_query():
            return (dt.read_parquet(glob_path)
                    .where(col("l_shipdate") <= _dt_date(1998, 9, 2))
                    .select("l_returnflag", "l_linestatus", "l_quantity",
                            "l_extendedprice", "l_discount")
                    .with_column("disc_price", col("l_extendedprice")
                                 * (1 - col("l_discount")))
                    .repartition(8, "l_returnflag", "l_linestatus")
                    .groupby("l_returnflag", "l_linestatus")
                    .agg(col("l_quantity").sum().alias("sum_qty"),
                         col("disc_price").sum().alias("sum_disc_price"),
                         col("l_quantity").count().alias("count_order"))
                    .sort(["l_returnflag", "l_linestatus"]))

        def run_ooc(streaming, budget_bytes):
            cfg.streaming_execution = streaming
            cfg.memory_budget_bytes = budget_bytes
            MEMORY_LEDGER.reset()
            q = ooc_query()
            t0 = time.perf_counter()
            got = q.collect().to_pydict()
            wall = time.perf_counter() - t0
            led = MEMORY_LEDGER.snapshot()
            c = q.stats.snapshot()["counters"]
            return got, wall, led, c

        ooc_best: dict = {}

        def keep_best(key, mode, budget_bytes):
            import gc

            gc.collect()
            got, wall, led, c = run_ooc(mode, budget_bytes)
            if "want" not in ooc_best:
                ooc_best["want"] = got
            elif not _parity(got, ooc_best["want"], rtol=1e-9):
                raise _OocParityError(key)
            if wall < ooc_best.get(key, (float("inf"),))[0]:
                ooc_best[key] = (wall, led, c)

        try:
            for pair in ((False, True), (True, False)):
                for mode in pair:
                    keep_best("stream" if mode else "serial", mode, budget)
        except _OocParityError as e:
            out["streaming_error"] = f"ooc_parity_mismatch_{e}"
            return out
        s_wall, s_led, s_c = ooc_best["stream"]
        n_wall, n_led, n_c = ooc_best["serial"]
        out["streaming_wall_s"] = round(s_wall, 2)
        out["streaming_serial_wall_s"] = round(n_wall, 2)
        # ledger-visible working set = buffers + streaming channels +
        # parked task outputs (exec_inflight); the spill decision charges
        # all of them against the budget, so the streaming peak is bounded
        # by it (+ the documented one-working-unit slack — same contract
        # as the prefetcher's one-in-flight allowance; the serial peak
        # honestly overshoots by the parked whole-partition window)
        peak = s_led["working_set_high_water"]
        n_peak = n_led["working_set_high_water"]
        out["streaming_peak_mb"] = round(peak / 2**20, 1)
        out["streaming_serial_peak_mb"] = round(n_peak / 2**20, 1)
        out["streaming_budget_mb"] = round(budget / 2**20, 1)
        # designed bound: buffers spill past the budget (current <= B) and
        # the bounded channels own a B/4 byte share (stream/pipeline.py),
        # so the streaming working set peaks at ~1.25x B + one morsel
        out["streaming_under_budget"] = bool(
            peak <= budget * 1.05 + budget // 4)
        out["streaming_spilled_partitions"] = s_c.get(
            "spilled_partitions", 0)
        out["streaming_serial_spilled_partitions"] = n_c.get(
            "spilled_partitions", 0)
        out["streaming_morsels"] = s_c.get("stream_morsels", 0)
        out["streaming_backpressure_stalls"] = s_c.get(
            "stream_backpressure_stalls", 0)
        out["streaming_channel_high_water"] = s_c.get(
            "stream_channel_high_water", 0)
        out["streaming_data_mb"] = round(data_bytes / 2**20, 1)

        # ---- leg 3: matched-memory serial rung --------------------------
        # At the SAME budget the spill count is pinned by arithmetic (the
        # buckets alone exceed it; every append past the fill spills,
        # whatever the mode), so equal budgets cannot show the streaming
        # claim. Equal MEMORY can: the serial run's peak overshoots the
        # budget by its parked whole-partition working set, so re-run it
        # with the budget shrunk by that overshoot — both executors now
        # live in the same real-memory envelope, and the serial run must
        # hand the overshoot back to the buckets: strictly more spill
        # events for byte-identical output.
        overshoot = max(0, n_peak - budget)
        matched = max(4 * 1024 * 1024, budget - overshoot)
        try:
            keep_best("matched", False, matched)
            keep_best("matched", False, matched)
        except _OocParityError as e:
            out["streaming_error"] = f"ooc_parity_mismatch_{e}"
            return out
        m_wall, m_led, m_c = ooc_best["matched"]
        out["streaming_matched_budget_mb"] = round(matched / 2**20, 1)
        out["streaming_matched_wall_s"] = round(m_wall, 2)
        out["streaming_matched_peak_mb"] = round(
            m_led["working_set_high_water"] / 2**20, 1)
        out["streaming_matched_spilled_partitions"] = m_c.get(
            "spilled_partitions", 0)
        out["streaming_speedup_x"] = round(m_wall / max(s_wall, 1e-9), 3)
        if m_c.get("spilled_partitions", 0) or s_c.get(
                "spilled_partitions", 0):
            # either mode spilling makes the ratio meaningful — including
            # the inverted case (streaming spilled, matched-serial did
            # not), which must surface as < 1, not vanish. Only degenerate
            # hosts (budget floor > data: NEITHER mode spills) omit it —
            # emitting 0.0 there would read as a phantom regression
            out["streaming_spill_reduction_x"] = round(
                m_c.get("spilled_partitions", 0)
                / max(1, s_c.get("spilled_partitions", 0)), 3)
        return out
    finally:
        try:
            for k, v in saved.items():
                setattr(cfg, k, v)
        except NameError:
            pass  # failed before the config snapshot
        MEMORY_LEDGER.reset()
        shutil.rmtree(tmp, ignore_errors=True)


class _OocParityError(Exception):
    """Streaming-rung parity gate tripped (leg + mode in args)."""


def _dt_date(y: int, m: int, d: int):
    import datetime

    return datetime.date(y, m, d)


def run_device_rungs(scale: float) -> dict:
    """Measure everything: host path, device path, oracle, Q3/Q5 join rungs.
    Assumes the accelerator is reachable (caller probes via _tpu_alive).
    Returns the output dict; value == 0 + "error" key on any failure."""
    s = _Setup(scale)
    tpch, dt = s.tpch, s.dt
    tables, lineitem, frame, rows = s.tables, s.lineitem, s.frame, s.rows
    run_q1, run_q6 = s.run_q1, s.run_q6
    want_q1, want_q6 = s.want_q1, s.want_q6
    metric = f"tpch_q1_sf{scale:g}_device_rows_per_sec"

    def _fail(err):
        return {"metric": metric, "value": 0, "unit": "rows/s",
                "vs_baseline": 0.0, "error": err}

    # ---- host path (engine, pyarrow kernels) -----------------------------
    host = s.measure_host()
    if host is None:
        return _fail("host_parity_mismatch")
    t_host_q1, t_host_q6 = host
    cfg = s.cfg

    # ---- device path (engine, fused jitted kernels, resident data) -------
    cfg.use_device_kernels = True
    t0 = time.perf_counter()
    got_q1 = run_q1()
    cold_q1 = time.perf_counter() - t0  # staging + jit compile, amortized cost
    got_q6 = run_q6()
    if not (_parity(got_q1, want_q1, rtol=1e-6)
            and _parity(got_q6, want_q6, rtol=1e-6)):
        return _fail("device_parity_mismatch")
    t_dev_q1, _ = _best_of(run_q1)
    t_dev_q6, _ = _best_of(run_q6)
    q1_stats = tpch.q1(frame).collect().stats
    dev_counters = q1_stats.snapshot()["counters"]
    if not dev_counters.get("device_aggregations"):
        return _fail("device_path_not_taken")

    # ---- oracle baseline (hand-written pyarrow.compute) ------------------
    t_oracle_q1, _ = _best_of(lambda: tpch.oracle_q1(lineitem))
    t_oracle_q6, _ = _best_of(lambda: tpch.oracle_q6(lineitem))

    q1_bytes = rows * _Q1_DEVICE_COLS * _Q1_BYTES_PER_VAL
    out = {
        "metric": metric,
        "value": round(rows / t_dev_q1, 1),
        "unit": "rows/s",
        "vs_baseline": round(t_oracle_q1 / t_dev_q1, 3),
        "host_rows_per_sec": round(rows / t_host_q1, 1),
        "host_vs_baseline": round(t_oracle_q1 / t_host_q1, 3),
        "device_vs_host": round(t_host_q1 / t_dev_q1, 3),
        "q6_device_rows_per_sec": round(rows / t_dev_q6, 1),
        "q6_vs_baseline": round(t_oracle_q6 / t_dev_q6, 3),
        "q6_device_vs_host": round(t_host_q6 / t_dev_q6, 3),
        "q1_cold_first_query_s": round(cold_q1, 3),
        # modeled achieved HBM read bandwidth: touched column bytes / wall
        # time (lower bound — excludes intermediates); v5e peak ~819 GB/s
        "q1_device_hbm_gbps": round(q1_bytes / t_dev_q1 / 1e9, 3),
        # per-operator throughput of the instrumented q1 run (RuntimeStats
        # rows/sec + bytes/sec, VERDICT item 1): the first real-TPU snapshot
        # carries the operator-level picture, not just end-to-end walls
        "q1_op_throughput": {
            name: {m: round(v, 1) for m, v in t.items()}
            for name, t in q1_stats.op_throughput().items()},
        # expression-fusion visibility (ISSUE 5): how many map chains the
        # fusion compiler collapsed in the instrumented q1 run
        "q1_fused_chains": dev_counters.get("fused_chains", 0),
        "q1_fused_ops_eliminated": dev_counters.get("fused_ops_eliminated", 0),
        "rows": rows,
    }
    # profiled device q1: critical path + top ops land in the rung metrics,
    # the full QueryProfile JSON next to the BENCH snapshot
    _save_rung_profile(out, "q1_device", lambda: tpch.q1(frame))

    # ---- deep-fused pallas kernel A/B (r4 verdict weak #5): Q1 with the
    # predicate + derived money columns evaluated INSIDE the pallas kernel
    # vs the composed XLA + batched-kernel program. Ratio > 1 means the
    # deep kernel wins; it stays opt-in until this number says otherwise.
    try:
        from daft_tpu.kernels import pallas_ops

        cfg.use_pallas_deep_fusion = True
        traces0 = pallas_ops.DEEP_FUSED_TRACES[0]
        got_deep = run_q1()  # compile the deep variant
        # a Mosaic compile failure at first EXECUTION silently recomputes
        # on host (executor fallback): the device counter must confirm the
        # aggregation actually ran on device, same gate as the main rung
        deep_counters = tpch.q1(frame).collect().stats.snapshot()["counters"]
        if (pallas_ops.DEEP_FUSED_TRACES[0] <= traces0
                or not deep_counters.get("device_aggregations")):
            out["q1_deep_pallas_error"] = "deep_kernel_not_engaged"
        elif not _parity(got_deep, want_q1, rtol=1e-6):
            out["q1_deep_pallas_error"] = "parity_mismatch"
        else:
            t_deep_q1, _ = _best_of(run_q1)
            # re-time the COMPOSED variant adjacent to the deep timing: the
            # keep-only-if-it-wins ratio must not compare across minutes of
            # machine drift (t_dev_q1 was measured much earlier)
            cfg.use_pallas_deep_fusion = False
            t_composed_adj, _ = _best_of(run_q1)
            out["q1_deep_pallas_s"] = round(t_deep_q1, 4)
            out["q1_deep_pallas_vs_composed"] = round(
                t_composed_adj / t_deep_q1, 3)
    except Exception as e:
        out["q1_deep_pallas_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_pallas_deep_fusion = False

    # ---- device-residency A/B (ISSUE 19 acceptance): the SAME q1 shape
    # with the plan-segment compiler off (staged per-op handoffs: every
    # map->agg boundary gathers to Arrow and re-stages) vs on (one
    # HBM-resident pipeline per segment: stage once, gather once),
    # interleaved best-of, parity gating the timing. Headlines:
    # q1_residency_speedup_x plus the elided host<->device handoff count
    # that explains it.
    saved_res = getattr(cfg, "device_residency", True)
    try:
        from daft_tpu.fuse import segment as _seg

        res_walls = {False: float("inf"), True: float("inf")}
        for pair in ((False, True), (True, False)):  # interleaved best-of
            for mode in pair:
                cfg.device_residency = mode
                if not _parity(run_q1(), want_q1, rtol=1e-6):
                    raise RuntimeError(f"parity_mismatch(residency={mode})")
                t, _ = _best_of(run_q1, n=2)
                res_walls[mode] = min(res_walls[mode], t)
        cfg.device_residency = True
        q1r = tpch.q1(frame)
        q1r.collect()
        res_c = q1r.stats.snapshot()["counters"]
        if not res_c.get("device_resident_segments"):
            out["q1_residency_error"] = "resident_path_not_taken"
        else:
            out["q1_residency_speedup_x"] = round(
                res_walls[False] / max(res_walls[True], 1e-9), 3)
            out["q1_device_handoffs_elided"] = res_c.get(
                "device_handoffs_elided", 0)
            out["q1_residency_hbm_high_water_mb"] = round(
                _seg.process_counters()["hbm_resident_bytes_high_water"]
                / 1e6, 1)
    except Exception as e:
        out["q1_residency_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.device_residency = saved_res

    # ---- Q3 (3-way join + agg + top-k): the device join-probe rung --------
    cust = orders = nat = None
    try:
        cust, orders, nat = s.join_frames()

        def run_q3():
            return tpch.q3(cust, orders, frame).collect().to_pydict()

        cfg.use_device_kernels = True
        got3 = run_q3()  # cold: staging + compile
        want3 = tpch.oracle_q3(tables["customer"], tables["orders"], lineitem)
        if _parity(got3, want3, rtol=1e-6):
            q3q = tpch.q3(cust, orders, frame)
            q3q.collect()
            probes = q3q.stats.snapshot()["counters"].get("device_join_probes", 0)
            t_dev_q3, _ = _best_of(run_q3, n=2)
            t_orc_q3, _ = _best_of(
                lambda: tpch.oracle_q3(tables["customer"], tables["orders"], lineitem),
                n=2)
            out["q3_device_s"] = round(t_dev_q3, 3)
            out["q3_vs_baseline"] = round(t_orc_q3 / t_dev_q3, 3)
            out["q3_device_join_probes"] = probes
        else:
            out["q3_vs_baseline"] = 0.0
            out["q3_error"] = "parity_mismatch"
    except Exception as e:  # a regression here must be visible, not silent
        out["q3_vs_baseline"] = 0.0
        out["q3_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_device_kernels = True

    # ---- Q5 (4-way join + agg): the deepest BASELINE.md join rung ---------
    try:
        if cust is None or orders is None or nat is None:
            raise RuntimeError("q3 inputs unavailable")

        def run_q5():
            return tpch.q5(cust, orders, frame, nat).collect().to_pydict()

        def run_oracle_q5():
            return tpch.oracle_q5(tables["customer"], tables["orders"],
                                  lineitem, tables["nation"])

        cfg.use_device_kernels = True
        got5 = run_q5()  # cold: staging + compile
        if _parity(got5, run_oracle_q5(), rtol=1e-6):
            t_dev_q5, _ = _best_of(run_q5, n=2)
            t_orc_q5, _ = _best_of(run_oracle_q5, n=2)
            out["q5_device_s"] = round(t_dev_q5, 3)
            out["q5_vs_baseline"] = round(t_orc_q5 / t_dev_q5, 3)
        else:
            out["q5_vs_baseline"] = 0.0
            out["q5_error"] = "parity_mismatch"
    except Exception as e:
        out["q5_vs_baseline"] = 0.0
        out["q5_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_device_kernels = True

    # ---- Q12 (string is_in filter + string group key): the device
    # dictionary-code surface end to end — LUT filter, device group codes,
    # fused segment aggs ----------------------------------------------------
    try:
        def run_q12():
            return tpch.q12(frame).collect().to_pydict()

        cfg.use_device_kernels = True
        got12 = run_q12()  # cold: staging + compile
        if _parity(got12, tpch.oracle_q12(lineitem), rtol=1e-6):
            q12q = tpch.q12(frame)
            q12q.collect()
            c12 = q12q.stats.snapshot()["counters"]
            if not c12.get("device_aggregations"):
                out["q12_vs_baseline"] = 0.0
                out["q12_error"] = "device_path_not_taken"
                raise StopIteration  # handled by the except below
            t_dev_q12, _ = _best_of(run_q12, n=2)
            t_orc_q12, _ = _best_of(lambda: tpch.oracle_q12(lineitem), n=2)
            out["q12_device_rows_per_sec"] = round(rows / t_dev_q12, 1)
            out["q12_vs_baseline"] = round(t_orc_q12 / t_dev_q12, 3)
            out["q12_device_group_codes"] = c12.get("device_group_codes", 0)
        else:
            out["q12_vs_baseline"] = 0.0
            out["q12_error"] = "parity_mismatch"
    except StopIteration:
        pass  # device_path_not_taken already recorded
    except Exception as e:
        out["q12_vs_baseline"] = 0.0
        out["q12_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_device_kernels = True

    # ---- LAION multimodal rung (BASELINE.md config): url.download ->
    # image.decode -> device-batched resize(224,224) -> tensor, vs a
    # hand-written same-algorithm oracle. Exercises the upload/download
    # concurrency budget and the batched image program on the accelerator.
    try:
        from benchmarks import laion

        out.update(laion.run_rung(n=1000))
    except Exception as e:
        out["laion_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- LAION expression-fusion A/B (ISSUE 5 acceptance): the SAME
    # dedupe-style multimodal chain with expr_fusion off (per-op
    # interpretation; pushdown re-downloads every kept row) vs on (one
    # FusedMap pass, cross-segment CSE), interleaved best-of, byte-identical
    # tensors gating the timing.
    try:
        from benchmarks import laion

        out.update(laion.run_fusion_ab(n=_laion_fusion_n()))
    except Exception as e:
        out["laion_fusion_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- LAION dynamic-batching A/B (ISSUE 18 acceptance): the SAME
    # stateful scoring chain with the batching knob off (one UDF call per
    # partition) vs on (cross-partition coalescer feeding a pinned model
    # actor), interleaved best-of, byte-identical scores gating the
    # timing. Headlines: laion_batched_speedup_x (gate >= 1.2x) and
    # laion_batch_fill_pct (gate >= 70%).
    try:
        from benchmarks import laion

        out.update(laion.run_batching_ab())
    except Exception as e:
        out["laion_batching_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- device join at scale: 100k-build x 1M-probe, PK and N:M flavors
    # (r4 verdict weak #4 — the N:M host-expansion cost measured, not
    # theoretical). Device-gated like every rung here, so the snapshot tool
    # lands it whenever the tunnel breathes. -------------------------------
    try:
        from benchmarks import join_bench

        # run_rung toggles use_device_kernels per phase and restores it
        out.update(join_bench.run_rung())
    except Exception as e:
        out["join_rung_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- out-of-core rung: Q1 from parquet ON DISK with forced spill ------
    if scale <= 1.0:
        try:
            _parquet_spill_rung(out, _spill_rung_scale(), rtol=1e-6)
        except Exception as e:
            out["spill_rung_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- Q6 at SF10 (BASELINE.md rung): the pure filter+reduce query needs
    # enough rows that the tunnel's fixed ~60-130ms result-fetch latency
    # amortizes; the oracle scales linearly while the device query cost is
    # flat, so this is where the no-shuffle rung is actually decided.
    import jax as _jax

    if scale <= 1.0 and _avail_ram_gb() >= 32 and _jax.default_backend() != "cpu":
        try:
            big = tpch.generate_lineitem_only(scale=10.0, seed=42)
            brows = big.num_rows
            bframe = dt.from_arrow(big).collect()
            cfg.use_device_kernels = True

            def run_big_q6():
                return tpch.q6(bframe).collect().to_pydict()

            got = run_big_q6()  # cold: staging + compile
            if _parity(got, {"revenue": [tpch.oracle_q6(big)]}, rtol=1e-6):
                t_dev, _ = _best_of(run_big_q6)
                t_orc, _ = _best_of(lambda: tpch.oracle_q6(big))
                out["q6_sf10_device_rows_per_sec"] = round(brows / t_dev, 1)
                out["q6_sf10_vs_baseline"] = round(t_orc / t_dev, 3)
            else:
                out["q6_sf10_vs_baseline"] = 0.0
        except MemoryError:
            pass

    # ---- sketch-exchange rung (host path; before/after the two-phase
    # approx-agg decomposition, ISSUE 3 acceptance) -------------------------
    try:
        out["sketch_exchange"] = measure_sketch_exchange()
    except Exception as e:
        out["sketch_exchange_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- exchange rung (host path; join-filter + encode + hierarchical-
    # combine interleaved A/B, ISSUE 9 acceptance) --------------------------
    try:
        out["exchange"] = measure_exchange()
    except Exception as e:
        out["exchange_rung_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- serving rung (host path; sustained mixed load through the
    # ServingRuntime, ISSUE 8 acceptance) -----------------------------------
    try:
        out["serving"] = measure_serving()
    except Exception as e:
        out["serving_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- streaming rung (host path; morsel-driven executor A/B,
    # ISSUE 10 acceptance) --------------------------------------------------
    try:
        out["streaming"] = measure_streaming()
    except Exception as e:
        out["streaming_rung_error"] = f"{type(e).__name__}: {e}"[:200]

    # ---- distributed rung (host path; local vs N-worker A/B + worker-loss
    # recovery leg, ISSUE 11 acceptance) ------------------------------------
    try:
        out["distributed"] = measure_distributed()
    except Exception as e:
        out["distributed_rung_error"] = f"{type(e).__name__}: {e}"[:200]

    return out


def _laion_fusion_n() -> int:
    """Fusion-A/B row count, RAM-guarded like the laion host rung: both
    modes hold the decoded+resized tensor working set — degrade rather
    than risk an OOM kill that loses the round's JSON line."""
    return 1000 if _avail_ram_gb() >= 8 else 300


def _parquet_spill_rung(out: dict, scale: float, rtol: float) -> None:
    """Q1 at `scale` read from parquet ON DISK through a hash shuffle under
    a memory budget that forces the shuffle buffers to spill — measures the
    IO+compute overlap and the out-of-core machinery instead of resident
    toys (reference discipline: SF1000 single-node at 16x data-to-memory,
    docs/source/faq/benchmarks.rst:111-124).

    Runs the SAME query in two configurations, interleaved (two trials
    each, best-of — the host's memory bandwidth drifts 3-4x with neighbor
    load): `serial` = pipelined IO off (prefetch 0, sync spill writes, no
    readahead — the pre-pipelining engine) and `pipelined` = the defaults
    (bounded scan prefetch + async spill writeback + unspill readahead).
    Extras land under q1_sf{scale}_parquet_*: wall/rows-per-sec for the
    pipelined config, the serial wall, the speedup, the io_wait-vs-compute
    share of both, spill write/read MB/s, prefetch hit/miss, and
    spilled_partitions."""
    import shutil
    import tempfile

    import pyarrow.parquet as papq

    from benchmarks import tpch

    import daft_tpu as dt
    from daft_tpu.context import get_context

    tag = f"q1_sf{scale:g}_parquet"
    big = tpch.generate_lineitem_only(scale=scale, seed=42)
    rows = big.num_rows
    want = tpch.oracle_q1(big)
    tmp = tempfile.mkdtemp(prefix="bench_spill_")
    try:
        nfiles = 16
        per = (rows + nfiles - 1) // nfiles
        for i in range(nfiles):
            sl = big.slice(i * per, per)
            if sl.num_rows:
                papq.write_table(sl, os.path.join(tmp, f"part-{i:02d}.parquet"),
                                 row_group_size=512 * 1024)
        data_bytes = sum(os.path.getsize(os.path.join(tmp, f))
                         for f in os.listdir(tmp))
        del big  # the point is OUT-of-core: no resident copy
        cfg = get_context().execution_config
        saved = {k: getattr(cfg, k) for k in (
            "memory_budget_bytes", "executor_threads", "scan_prefetch_depth",
            "async_spill_writes", "unspill_readahead",
            "parallel_shuffle_fanout", "scan_tasks_min_size_bytes",
            "exchange_payload_encoding")}
        # this rung measures the SPILL pipeline (IO overlap A/B), so the
        # exchange encoder stands down: lineitem's low-cardinality columns
        # encode ~2x and at small scales the shrunken ledger charge stops
        # the buffers spilling at all — the exchange rung measures encoding
        cfg.exchange_payload_encoding = False
        # per-file scan tasks (no merging), BOTH modes: 16 x ~36MB units
        # instead of 6 x ~108MB merged ones. Finer grain pipelines better
        # AND collapses run-to-run variance — with merged tasks the same
        # config swung 13..29s on this host; per-file runs repeat within
        # ~5% (r6 measurement)
        cfg.scan_tasks_min_size_bytes = 1
        # the out-of-core rung is IO-heavy: parquet decode, IPC spill writes
        # and acero all release the GIL, so deep oversubscription overlaps
        # their waits even on the 1-core host — including the dominant page-
        # fault stalls (fresh pages fault at ~300 MB/s on this ballooned VM;
        # faults inside GIL-released arrow calls let other workers run).
        # Measured r5 at SF10: 1 thread 40s, 4 threads 28-42s, 8 threads
        # 28-45s with the best runs at 8.
        cfg.executor_threads = 8
        # budget ~ a quarter of the on-disk bytes (arrow in-memory is ~4x
        # parquet): the shuffle buffers CANNOT fit, so spill must engage at
        # every scale — a fixed budget would silently stop spilling on
        # small-RAM fallback scales
        cfg.memory_budget_bytes = max(16 * 1024 * 1024, data_bytes // 4)
        modes = {"serial": dict(scan_prefetch_depth=0,
                                async_spill_writes=False,
                                unspill_readahead=False,
                                parallel_shuffle_fanout=False),
                 "pipelined": dict(scan_prefetch_depth=2,
                                   async_spill_writes=True,
                                   unspill_readahead=True,
                                   parallel_shuffle_fanout=True)}
        try:
            def run(mode):
                for k, v in modes[mode].items():
                    setattr(cfg, k, v)
                df = dt.read_parquet(os.path.join(tmp, "*.parquet"))
                shuffled = df.repartition(8, "l_returnflag", "l_linestatus")
                q = tpch.q1(shuffled)
                t0 = time.perf_counter()
                got = q.collect().to_pydict()
                return got, time.perf_counter() - t0, q.stats

            best = {}
            stats = {}
            # alternate the order across trials: walls degrade monotonically
            # over a long bench process (allocator growth + page-cache
            # pressure on the ballooned host), so a fixed order would bias
            # the A/B against whichever config always ran later
            for pair in (("serial", "pipelined"), ("pipelined", "serial")):
                for mode in pair:
                    import gc

                    import pyarrow as _pa

                    gc.collect()
                    _pa.default_memory_pool().release_unused()
                    got, wall, st = run(mode)
                    if not _parity(got, want, rtol=rtol):
                        out[f"{tag}_error"] = f"parity_mismatch_{mode}"
                        return
                    if mode not in best or wall < best[mode]:
                        best[mode] = wall
                        stats[mode] = st
            wall = best["pipelined"]
            out[f"{tag}_wall_s"] = round(wall, 2)
            out[f"{tag}_rows_per_sec"] = round(rows / wall, 1)
            out[f"{tag}_serial_wall_s"] = round(best["serial"], 2)
            out[f"{tag}_pipelined_speedup_x"] = round(best["serial"] / wall, 3)
            io = stats["pipelined"].io_breakdown()
            out[f"{tag}_io_wait_share"] = io["io_wait_share"]
            out[f"{tag}_serial_io_wait_share"] = (
                stats["serial"].io_breakdown()["io_wait_share"])
            out[f"{tag}_spill_write_mbps"] = io["spill_write_mbps"]
            out[f"{tag}_spill_read_mbps"] = io["spill_read_mbps"]
            out[f"{tag}_prefetch_hits"] = io["prefetch_hits"]
            out[f"{tag}_prefetch_misses"] = io["prefetch_misses"]
            c = stats["pipelined"].snapshot()["counters"]
            out[f"{tag}_spilled_partitions"] = c.get("spilled_partitions", 0)
            out[f"{tag}_data_mb"] = round(data_bytes / 2**20, 1)
            # profiled re-run of the PIPELINED config: background spill /
            # prefetch attribution for this rung rides the artifact
            for k, v in modes["pipelined"].items():
                setattr(cfg, k, v)
            _save_rung_profile(
                out, tag,
                lambda: tpch.q1(
                    dt.read_parquet(os.path.join(tmp, "*.parquet"))
                    .repartition(8, "l_returnflag", "l_linestatus")))
        finally:
            for k, v in saved.items():
                setattr(cfg, k, v)
    finally:
        shutil.rmtree(tmp, ignore_errors=True)


def _spill_rung_scale() -> float:
    """SF10 when the host affords it (arrow working set ~2.6 GB + shuffle),
    a smaller honest rung otherwise — never silently skipped."""
    ram = _avail_ram_gb()
    if ram >= 48:
        return 10.0
    if ram >= 12:
        return 2.0
    return 0.5


def _load_snapshot(metric: str) -> dict | None:
    try:
        with open(SNAPSHOT_PATH) as f:
            snap = json.load(f)
    except (OSError, ValueError):
        return None
    if snap.get("metric") != metric or not snap.get("value"):
        return None
    # Staleness guard: a snapshot committed in a PREVIOUS round must never be
    # reported as this round's number. The driver writes BENCH_r*.json at
    # each round's end, so those files' mtimes (reset to checkout time at
    # round start) bound "this round began"; a genuine mid-round snapshot's
    # internal timestamp is newer, a leftover from an earlier round is older.
    taken = snap.get("snapshot_unix_time")
    if not taken:
        return None
    here = os.path.dirname(os.path.abspath(__file__))
    prior = [os.path.join(here, f) for f in os.listdir(here)
             if f.startswith("BENCH_r") and f.endswith(".json")]
    if prior:
        round_start = max(os.path.getmtime(p) for p in prior)
    else:
        # no driver artifacts to anchor on (fresh repo / cleaned workspace):
        # still bound staleness so an arbitrarily old leftover can't be
        # reported as current
        round_start = time.time() - 24 * 3600
    if taken < round_start:
        return None
    return snap


def _host_fallback(scale: float) -> dict:
    """Accelerator unreachable for the whole round: honest value 0 with the
    full host-path rung set as extras for the post-mortem."""
    import jax

    # The tunnel is wedged by definition on this path: pin jax to CPU
    # BEFORE anything can trigger backend init — the LAION rung's resize
    # (and any stray jnp call) would otherwise block inside the PJRT
    # client's C init where no Python signal can interrupt, losing the
    # whole round's JSON line. (The image preloads jax pinned to
    # 'axon,cpu'; the env var alone cannot override it.)
    try:
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass  # backend already initialized: only possible if a device ran

    s = _Setup(scale)
    tpch = s.tpch
    tables, lineitem, frame, rows = s.tables, s.lineitem, s.frame, s.rows
    out = {"metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
           "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
           "error": "tpu_unreachable", "rows": rows}
    host = s.measure_host()
    if host is None:
        out["error"] = "host_parity_mismatch"
        return out
    t_host_q1, t_host_q6 = host
    t_oracle_q1, _ = _best_of(lambda: tpch.oracle_q1(lineitem))
    t_oracle_q6, _ = _best_of(lambda: tpch.oracle_q6(lineitem))
    out["host_rows_per_sec"] = round(rows / t_host_q1, 1)
    out["host_vs_baseline"] = round(t_oracle_q1 / t_host_q1, 3)
    out["q6_host_vs_baseline"] = round(t_oracle_q6 / t_host_q6, 3)
    try:  # flight-recorder steady-state cost on the q1 rung (must be noise)
        out["q1_query_log_overhead_pct"] = _query_log_overhead_pct(s)
    except Exception as e:
        out["q1_query_log_error"] = f"{type(e).__name__}: {e}"[:120]
    # residency rung, counters-only: with no accelerator a resident wall
    # time would be fiction, but the segment compiler, the decline path,
    # and the parity invariant run the same on CPU and must stay visible
    # in the round's JSON (CI asserts counters + parity, not speedups)
    cfg = s.cfg
    saved_udk = cfg.use_device_kernels
    saved_res = getattr(cfg, "device_residency", True)
    try:
        cfg.use_device_kernels = True
        cfg.device_residency = True
        q1r = tpch.q1(frame)
        got_res = q1r.collect().to_pydict()
        res_c = q1r.stats.snapshot()["counters"]
        out["q1_residency_counters"] = {
            k: res_c.get(k, 0) for k in (
                "segment_compiles", "segment_fallbacks",
                "device_resident_segments", "device_handoffs_elided")}
        if not _parity(got_res, s.want_q1, rtol=1e-6):
            out["q1_residency_error"] = "parity_mismatch"
    except Exception as e:
        out["q1_residency_error"] = f"{type(e).__name__}: {e}"[:120]
    finally:
        cfg.use_device_kernels = saved_udk
        cfg.device_residency = saved_res
    # one profiled run per rung: the QueryProfile artifact lands next to
    # the BENCH snapshot and the headline metrics carry the critical path
    _save_rung_profile(out, "q1_host", lambda: tpch.q1(frame))
    try:
        cust, orders, nat = s.join_frames()
    except Exception as e:
        out["host_rungs_error"] = f"{type(e).__name__}: {e}"[:120]
        return out
    rungs = [
        ("q3", lambda: tpch.q3(cust, orders, frame).collect().to_pydict(),
         lambda: tpch.oracle_q3(tables["customer"], tables["orders"],
                                lineitem),
         lambda: tpch.q3(cust, orders, frame)),
        ("q5", lambda: tpch.q5(cust, orders, frame, nat).collect()
         .to_pydict(),
         lambda: tpch.oracle_q5(tables["customer"], tables["orders"],
                                lineitem, tables["nation"]),
         lambda: tpch.q5(cust, orders, frame, nat)),
        ("q12", lambda: tpch.q12(frame).collect().to_pydict(),
         lambda: tpch.oracle_q12(lineitem),
         lambda: tpch.q12(frame)),
    ]
    for name, engine_fn, oracle_fn, build_q in rungs:
        try:  # parity gates timing, as everywhere else in this file
            if _parity(engine_fn(), oracle_fn(), rtol=1e-6):
                # sub-second rungs: best-of-3 rides out the host's drifting
                # memory bandwidth (bench_env records it)
                t_eng, _ = _best_of(engine_fn, n=3)
                t_orc, _ = _best_of(oracle_fn, n=3)
                out[f"{name}_host_vs_baseline"] = round(t_orc / t_eng, 3)
                _save_rung_profile(out, f"{name}_host", build_q)
            else:
                out[f"{name}_host_vs_baseline"] = 0.0
        except Exception as e:
            out[f"{name}_host_error"] = f"{type(e).__name__}: {e}"[:120]
    try:  # the multimodal rung still measures on host (resize runs on CPU)
        from benchmarks import laion

        # n=10,000 approaches the BASELINE.md shape. best_of=2 (interleaved
        # engine/oracle rounds) rides out the host's drifting memory
        # bandwidth — a single round landed 0.97..1.37 for identical code.
        # Peak RSS is ~10 GB of float32 intermediates across engine+oracle —
        # degrade n on a loaded host rather than risk an OOM kill that loses
        # the whole JSON line (same discipline as the q1 RAM gate above).
        avail = _avail_ram_gb()
        laion_n = 10000 if avail >= 24 else (2000 if avail >= 8 else 500)
        host_laion = laion.run_rung(n=laion_n, best_of=2)
        out["laion_host_rows_per_sec"] = host_laion.get(
            "laion_device_rows_per_sec", 0.0)
        out["laion_host_vs_baseline"] = host_laion.get("laion_vs_baseline", 0.0)
        if "laion_error" in host_laion:
            out["laion_error"] = host_laion["laion_error"]
    except Exception as e:
        out["laion_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # fusion A/B is pure host work: it rides the fallback too
        from benchmarks import laion

        out.update(laion.run_fusion_ab(n=_laion_fusion_n()))
    except Exception as e:
        out["laion_fusion_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # batching A/B is pure host work too: it rides the fallback
        from benchmarks import laion

        out.update(laion.run_batching_ab())
    except Exception as e:
        out["laion_batching_error"] = f"{type(e).__name__}: {e}"[:200]
    if scale <= 1.0:
        try:  # out-of-core rung rides the host fallback too
            _parquet_spill_rung(out, _spill_rung_scale(), rtol=1e-9)
        except Exception as e:
            out["spill_rung_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # sketch-exchange rung is pure host work: it rides the fallback too
        out["sketch_exchange"] = measure_sketch_exchange()
    except Exception as e:
        out["sketch_exchange_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # exchange rung (ISSUE 9) is pure host work: fallback too
        out["exchange"] = measure_exchange()
    except Exception as e:
        out["exchange_rung_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # serving rung is pure host work: it rides the fallback too
        out["serving"] = measure_serving()
    except Exception as e:
        out["serving_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # streaming rung (ISSUE 10) is pure host work: fallback too
        out["streaming"] = measure_streaming()
    except Exception as e:
        out["streaming_rung_error"] = f"{type(e).__name__}: {e}"[:200]
    try:  # distributed rung (ISSUE 11) is pure host work: fallback too
        out["distributed"] = measure_distributed()
    except Exception as e:
        out["distributed_rung_error"] = f"{type(e).__name__}: {e}"[:200]
    return out


def _query_log_overhead_pct(s: "_Setup") -> float:
    """Interleaved best-of A/B of TPC-H Q1 with the always-on query log
    enabled vs disabled — the flight-recorder acceptance gate's 'q1 smoke
    A/B within noise'. Interleaving rides out the build host's drifting
    memory bandwidth the same way the spill rung's A/B does."""
    from daft_tpu.context import get_context

    cfg = get_context().execution_config
    prev = cfg.enable_query_log
    walls = {True: [], False: []}
    try:
        for _ in range(3):
            for flag in (False, True):
                cfg.enable_query_log = flag
                t0 = time.perf_counter()
                s.run_q1()
                walls[flag].append(time.perf_counter() - t0)
    finally:
        cfg.enable_query_log = prev
    t_on, t_off = min(walls[True]), min(walls[False])
    return round((t_on - t_off) / t_off * 100, 2)


def _bench_env() -> dict:
    """Machine-state fingerprint recorded with every artifact: the 1-CPU
    build host's effective memory bandwidth drifts 3-4x with neighbor load
    (observed r5: a 528 MB copy 0.14s..1.4s), so round-over-round host
    deltas are only attributable with the load AND measured bandwidth
    pinned next to the numbers (VERDICT r4 weak #3)."""
    import numpy as np

    try:
        la1, la5, _ = os.getloadavg()
    except OSError:
        la1 = la5 = -1.0
    try:
        nproc = sum(1 for p in os.listdir("/proc") if p.isdigit())
    except OSError:
        nproc = -1
    a = np.empty(256 * 1024 * 1024 // 8, dtype=np.float64)
    a[::512] = 1.0  # touch every 4 KiB page (512 f64) before timing
    t0 = time.perf_counter()
    a.copy()
    dt = time.perf_counter() - t0
    return {"cpu_count": os.cpu_count(), "load_1m": round(la1, 2),
            "load_5m": round(la5, 2), "processes": nproc,
            "mem_available_gb": round(_avail_ram_gb(), 1),
            "memcpy_gbps": round(2 * a.nbytes / dt / 1e9, 2)}


def main() -> int:
    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    metric = f"tpch_q1_sf{scale:g}_device_rows_per_sec"
    env = _bench_env()

    if _tpu_alive():
        out = _run_device_rungs_guarded(scale)
        if out is not None:
            out["bench_env"] = env
            print(json.dumps(out))
            return 0 if out.get("value") else 1
        # the tunnel wedged MID-RUNG after a live probe: fall through to
        # the snapshot/host path exactly as if the probe had failed

    # tunnel wedged at bench time: report the freshest mid-round device
    # snapshot (measured on the real chip by tools/bench_snapshot.py while
    # the tunnel was alive) rather than losing the round's perf axis
    snap = _load_snapshot(metric)
    if snap is not None:
        snap["source"] = "mid_round_snapshot"
        if snap.get("snapshot_unix_time"):
            snap["snapshot_age_s"] = round(
                time.time() - snap["snapshot_unix_time"], 1)
        # the snapshot's own bench_env describes the machine AT MEASUREMENT
        # time — keep it; the replaying host's state goes under its own key
        snap["bench_env_replay"] = env
        print(json.dumps(snap))
        return 0

    out = _host_fallback(scale)
    out["bench_env"] = env
    print(json.dumps(out))
    return 1


def _avail_ram_gb() -> float:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable"):
                    return int(line.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


if __name__ == "__main__":
    sys.exit(main())
