"""Benchmark harness: TPC-H through the engine, host path vs TPU device path.

Prints ONE JSON line {"metric", "value", "unit", "vs_baseline", ...extras}.

Headline: TPC-H Q1 rows/sec through the DEVICE path of the full engine
(lazy plan -> optimizer -> fused physical plan -> jitted filter+segment-agg
kernels on the TPU) over HBM-resident data — the deployment shape this
framework targets (stage once, query many; the host<->device link is the
bottleneck, compute is not). vs_baseline is the speedup vs a hand-written
pyarrow.compute oracle of the same query on this host (>1.0 = faster).

Extras report the host-path engine, Q6, and first-query (cold staging) cost
so the staging amortization is visible, not hidden.

Result parity vs the oracle is asserted before timing (device money sums run
reduced-precision float32 with Kahan-compensated combines; parity tolerance
is relative 1e-6). A parity failure prints value 0.

Reference role-equivalent: tests/benchmarks/test_local_tpch.py +
benchmarking/tpch (SURVEY.md §6); baseline targets in BASELINE.md.
"""

from __future__ import annotations

import json
import sys
import time


def _best_of(fn, n=3):
    best = float("inf")
    for _ in range(n):
        t0 = time.perf_counter()
        out = fn()
        best = min(best, time.perf_counter() - t0)
    return best, out


def _parity(got: dict, want: dict, rtol: float) -> bool:
    if set(got) != set(want):
        return False
    for k in want:
        if len(got[k]) != len(want[k]):
            return False
        for a, b in zip(got[k], want[k]):
            if isinstance(b, float):
                if abs(a - b) > max(rtol * abs(b), 1e-6):
                    return False
            elif a != b:
                return False
    return True


def _tpu_alive(timeout_s: int = 180) -> bool:
    """Probe the device with a tiny jit IN A SUBPROCESS: a wedged accelerator
    tunnel blocks inside the PJRT client's C init where no Python signal can
    interrupt, so the only safe watchdog is a killable child process."""
    import subprocess

    try:
        import jax

        platforms = jax.config.jax_platforms  # honor a parent cpu-pin
    except Exception:
        platforms = None
    pin = (f"jax.config.update('jax_platforms', {platforms!r}); "
           if platforms else "")
    code = ("import jax; " + pin + "import jax.numpy as jnp; "
            "jax.jit(lambda a: (a * 2).sum())(jnp.arange(128))"
            ".block_until_ready(); print('alive')")
    try:
        out = subprocess.run([sys.executable, "-c", code], timeout=timeout_s,
                             capture_output=True, text=True)
        return out.returncode == 0 and "alive" in out.stdout
    except Exception:
        return False


def main() -> int:
    scale = float(sys.argv[1]) if len(sys.argv) > 1 else 1.0
    from benchmarks import tpch

    tables = tpch.generate_tables(scale=scale, seed=42)
    lineitem = tables["lineitem"]
    rows = lineitem.num_rows

    import daft_tpu as dt
    from daft_tpu.context import get_context, set_execution_config

    cfg = get_context().execution_config
    cfg.enable_result_cache = False  # measure execution, not cache hits

    # one resident frame reused across runs: partitions carry the HBM staging
    # cache, so device-path warm runs skip the host->device transfer
    frame = dt.from_arrow(lineitem).collect()

    def run_q1():
        return tpch.q1(frame).collect().to_pydict()

    def run_q6():
        return tpch.q6(frame).collect().to_pydict()

    want_q1 = tpch.oracle_q1(lineitem)
    want_q6 = {"revenue": [tpch.oracle_q6(lineitem)]}

    out = {}

    # ---- host path (engine, pyarrow kernels) -----------------------------
    cfg.use_device_kernels = False
    timings = {}
    for threads in (1, 0):
        set_execution_config(executor_threads=threads)
        timings[threads], _ = _best_of(run_q1, n=2)
    best_mode = min(timings, key=timings.get)
    set_execution_config(executor_threads=best_mode)
    cfg = get_context().execution_config
    cfg.enable_result_cache = False
    if not _parity(run_q1(), want_q1, rtol=1e-9):
        print(json.dumps({"metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
                          "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
                          "error": "host_parity_mismatch"}))
        return 1
    t_host_q1, _ = _best_of(run_q1)
    t_host_q6, _ = _best_of(run_q6)

    if not _tpu_alive():
        # accelerator unreachable (tunnel wedged / no device): fail like the
        # other error branches (value 0, exit 1) so trackers never record a
        # host number under the device metric; the full host-path rung set
        # rides along as extras for the post-mortem
        t_oracle_q1, _ = _best_of(lambda: tpch.oracle_q1(lineitem))
        t_oracle_q6, _ = _best_of(lambda: tpch.oracle_q6(lineitem))
        out = {
            "metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
            "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
            "host_rows_per_sec": round(rows / t_host_q1, 1),
            "host_vs_baseline": round(t_oracle_q1 / t_host_q1, 3),
            "q6_host_vs_baseline": round(t_oracle_q6 / t_host_q6, 3),
            "error": "tpu_unreachable", "rows": rows}
        try:
            cust = dt.from_arrow(tables["customer"]).collect()
            orders = dt.from_arrow(tables["orders"]).collect()
            nat = dt.from_arrow(tables["nation"]).collect()
        except Exception as e:
            cust = None
            out["host_rungs_error"] = f"{type(e).__name__}: {e}"[:120]
        if cust is not None:
            rungs = [
                ("q3", lambda: tpch.q3(cust, orders, frame).collect().to_pydict(),
                 lambda: tpch.oracle_q3(tables["customer"], tables["orders"],
                                        lineitem)),
                ("q5", lambda: tpch.q5(cust, orders, frame, nat).collect()
                 .to_pydict(),
                 lambda: tpch.oracle_q5(tables["customer"], tables["orders"],
                                        lineitem, tables["nation"])),
            ]
            for name, engine_fn, oracle_fn in rungs:
                try:  # parity gates timing, as everywhere else in this file
                    if _parity(engine_fn(), oracle_fn(), rtol=1e-6):
                        t_eng, _ = _best_of(engine_fn, n=2)
                        t_orc, _ = _best_of(oracle_fn, n=2)
                        out[f"{name}_host_vs_baseline"] = round(t_orc / t_eng, 3)
                    else:
                        out[f"{name}_host_vs_baseline"] = 0.0
                except Exception as e:
                    out[f"{name}_host_error"] = f"{type(e).__name__}: {e}"[:120]
        print(json.dumps(out))
        return 1

    # ---- device path (engine, fused jitted kernels, resident data) -------
    cfg.use_device_kernels = True
    t0 = time.perf_counter()
    got_q1 = run_q1()
    cold_q1 = time.perf_counter() - t0  # staging + jit compile, amortized cost
    got_q6 = run_q6()
    dev_ok = _parity(got_q1, want_q1, rtol=1e-6) and _parity(got_q6, want_q6, rtol=1e-6)
    if not dev_ok:
        print(json.dumps({"metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
                          "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
                          "error": "device_parity_mismatch"}))
        return 1
    t_dev_q1, _ = _best_of(run_q1)
    t_dev_q6, _ = _best_of(run_q6)
    dev_counters = tpch.q1(frame).collect().stats.snapshot()["counters"]
    if not dev_counters.get("device_aggregations"):
        print(json.dumps({"metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
                          "value": 0, "unit": "rows/s", "vs_baseline": 0.0,
                          "error": "device_path_not_taken"}))
        return 1

    # ---- oracle baseline (hand-written pyarrow.compute) ------------------
    t_oracle_q1, _ = _best_of(lambda: tpch.oracle_q1(lineitem))
    t_oracle_q6, _ = _best_of(lambda: tpch.oracle_q6(lineitem))

    out = {
        "metric": f"tpch_q1_sf{scale:g}_device_rows_per_sec",
        "value": round(rows / t_dev_q1, 1),
        "unit": "rows/s",
        "vs_baseline": round(t_oracle_q1 / t_dev_q1, 3),
        "host_rows_per_sec": round(rows / t_host_q1, 1),
        "host_vs_baseline": round(t_oracle_q1 / t_host_q1, 3),
        "device_vs_host": round(t_host_q1 / t_dev_q1, 3),
        "q6_device_rows_per_sec": round(rows / t_dev_q6, 1),
        "q6_vs_baseline": round(t_oracle_q6 / t_dev_q6, 3),
        "q6_device_vs_host": round(t_host_q6 / t_dev_q6, 3),
        "q1_cold_first_query_s": round(cold_q1, 3),
        "rows": rows,
    }

    # ---- Q3 (3-way join + agg + top-k): the device join-probe rung --------
    cust = orders = None
    try:
        cust = dt.from_arrow(tables["customer"]).collect()
        orders = dt.from_arrow(tables["orders"]).collect()

        def run_q3():
            return tpch.q3(cust, orders, frame).collect().to_pydict()

        cfg.use_device_kernels = True
        got3 = run_q3()  # cold: staging + compile
        want3 = tpch.oracle_q3(tables["customer"], tables["orders"], lineitem)
        if _parity(got3, want3, rtol=1e-6):
            q3q = tpch.q3(cust, orders, frame)
            q3q.collect()
            probes = q3q.stats.snapshot()["counters"].get("device_join_probes", 0)
            t_dev_q3, _ = _best_of(run_q3, n=2)
            t_orc_q3, _ = _best_of(
                lambda: tpch.oracle_q3(tables["customer"], tables["orders"], lineitem),
                n=2)
            out["q3_device_s"] = round(t_dev_q3, 3)
            out["q3_vs_baseline"] = round(t_orc_q3 / t_dev_q3, 3)
            out["q3_device_join_probes"] = probes
        else:
            out["q3_vs_baseline"] = 0.0
            out["q3_error"] = "parity_mismatch"
    except Exception as e:  # a regression here must be visible, not silent
        out["q3_vs_baseline"] = 0.0
        out["q3_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_device_kernels = True

    # ---- Q5 (4-way join + agg): the deepest BASELINE.md join rung ---------
    try:
        if cust is None or orders is None:
            raise RuntimeError("q3 inputs unavailable")
        nat = dt.from_arrow(tables["nation"]).collect()

        def run_q5():
            return tpch.q5(cust, orders, frame, nat).collect().to_pydict()

        def run_oracle_q5():
            return tpch.oracle_q5(tables["customer"], tables["orders"],
                                  lineitem, tables["nation"])

        cfg.use_device_kernels = True
        got5 = run_q5()  # cold: staging + compile
        if _parity(got5, run_oracle_q5(), rtol=1e-6):
            t_dev_q5, _ = _best_of(run_q5, n=2)
            t_orc_q5, _ = _best_of(run_oracle_q5, n=2)
            out["q5_device_s"] = round(t_dev_q5, 3)
            out["q5_vs_baseline"] = round(t_orc_q5 / t_dev_q5, 3)
        else:
            out["q5_vs_baseline"] = 0.0
            out["q5_error"] = "parity_mismatch"
    except Exception as e:
        out["q5_vs_baseline"] = 0.0
        out["q5_error"] = f"{type(e).__name__}: {e}"[:200]
    finally:
        cfg.use_device_kernels = True

    # ---- Q6 at SF10 (BASELINE.md rung): the pure filter+reduce query needs
    # enough rows that the tunnel's fixed ~60-130ms result-fetch latency
    # amortizes; the oracle scales linearly while the device query cost is
    # flat, so this is where the no-shuffle rung is actually decided.
    import jax as _jax

    if scale <= 1.0 and _avail_ram_gb() >= 32 and _jax.default_backend() != "cpu":
        try:
            big = tpch.generate_lineitem_only(scale=10.0, seed=42)
            brows = big.num_rows
            bframe = dt.from_arrow(big).collect()
            cfg.use_device_kernels = True

            def run_big_q6():
                return tpch.q6(bframe).collect().to_pydict()

            got = run_big_q6()  # cold: staging + compile
            if _parity(got, {"revenue": [tpch.oracle_q6(big)]}, rtol=1e-6):
                t_dev, _ = _best_of(run_big_q6)
                t_orc, _ = _best_of(lambda: tpch.oracle_q6(big))
                out["q6_sf10_device_rows_per_sec"] = round(brows / t_dev, 1)
                out["q6_sf10_vs_baseline"] = round(t_orc / t_dev, 3)
            else:
                out["q6_sf10_vs_baseline"] = 0.0
        except MemoryError:
            pass

    print(json.dumps(out))
    return 0


def _avail_ram_gb() -> float:
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemAvailable"):
                    return int(line.split()[1]) / 1e6
    except OSError:
        pass
    return 0.0


if __name__ == "__main__":
    sys.exit(main())
