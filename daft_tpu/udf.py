"""Python UDFs: the batch trampoline and the @udf decorator.

Role-equivalent to the reference's daft/udf.py (StatelessUDF/StatefulUDF, :272/:308,
run_udf trampoline :82-200). UDFs receive Series (or scalars for literal args) in
batches and return a Series/list/numpy array; `batch_size` splits long columns;
class UDFs (stateful) are instantiated once per executor worker and reused —
the TPU analog of actor pools for `.embed()`-style model UDFs.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from .datatypes import DataType
from .series import Series

_STATEFUL_INSTANCES: dict = {}


def _coerce_result(out: Any, name: str, dtype: DataType, n: int) -> Series:
    if isinstance(out, Series):
        s = out
    elif isinstance(out, np.ndarray):
        s = Series.from_numpy(out, name)
    elif isinstance(out, (list, tuple)):
        s = Series.from_pylist(list(out), name, dtype)
    else:
        try:
            import pyarrow as pa

            if isinstance(out, (pa.Array, pa.ChunkedArray)):
                s = Series.from_arrow(out, name)
            else:
                raise TypeError
        except TypeError:
            raise ValueError(
                f"UDF must return Series/list/numpy/arrow, got {type(out).__name__}"
            ) from None
    if len(s) != n:
        raise ValueError(f"UDF returned {len(s)} rows, expected {n}")
    if s.dtype != dtype:
        s = s.cast(dtype)
    return s


def run_udf(fn: Callable, args: List[Series], return_dtype: DataType, n: int,
            batch_size: Optional[int] = None, init_args: Optional[tuple] = None,
            concurrency: Optional[int] = None) -> Series:
    """Evaluate a UDF over column batches (reference: daft/udf.py run_udf).

    Stateful (class) UDFs with concurrency>1 run on a persistent actor pool
    (actor_pool.py): one instance per worker, batches dispatched across them,
    results re-assembled in order."""
    from .series import _broadcast_to

    name = args[0].name if args else "udf"
    args = [_broadcast_to(a, n) if len(a) != n else a for a in args]

    if inspect.isclass(fn) and concurrency and concurrency > 1:
        from .actor_pool import get_pool

        pool = get_pool(fn, init_args, concurrency)
        bs = batch_size or max(1, -(-n // concurrency))  # ceil-split across actors
        bounds = [(s, min(s + bs, n)) for s in range(0, n, bs)] or [(0, 0)]
        batches = [tuple(a.slice(s, e) for a in args) for s, e in bounds]
        outs = pool.map_batches(batches)
        coerced = [_coerce_result(o, name, return_dtype, e - s)
                   for o, (s, e) in zip(outs, bounds)]
        return Series.concat(coerced) if len(coerced) > 1 else coerced[0]

    if inspect.isclass(fn):
        key = (fn, repr(init_args))
        if key not in _STATEFUL_INSTANCES:
            a, kw = (init_args or ((), {}))
            _STATEFUL_INSTANCES[key] = fn(*a, **kw)
        fn = _STATEFUL_INSTANCES[key].__call__

    if not batch_size or n <= batch_size:
        return _coerce_result(fn(*args), name, return_dtype, n)
    outs = []
    for start in range(0, n, batch_size):
        end = min(start + batch_size, n)
        chunk = [a.slice(start, end) for a in args]
        outs.append(_coerce_result(fn(*chunk), name, return_dtype, end - start))
    return Series.concat(outs)


class UDF:
    """A wrapped user function callable over expressions."""

    def __init__(self, fn: Callable, return_dtype: DataType,
                 batch_size: Optional[int] = None, concurrency: Optional[int] = None,
                 init_args: Optional[tuple] = None, num_cpus: Optional[float] = None,
                 num_gpus: Optional[float] = None, memory_bytes: Optional[int] = None):
        self.fn = fn
        self.return_dtype = return_dtype
        self.batch_size = batch_size
        self.concurrency = concurrency
        self.init_args = init_args
        self.num_cpus = num_cpus
        self.num_gpus = num_gpus
        self.memory_bytes = memory_bytes
        self.__name__ = getattr(fn, "__name__", "udf")

    def __call__(self, *exprs):
        from .expressions import Expression, PyUdf, _as_expr_node

        nodes = [_as_expr_node(e) for e in exprs]
        rr = None
        if self.num_cpus or self.num_gpus or self.memory_bytes:
            rr = (self.num_cpus, self.num_gpus, self.memory_bytes)
        return Expression(PyUdf(self.fn, self.return_dtype, nodes, fn_name=self.__name__,
                                batch_size=self.batch_size, concurrency=self.concurrency,
                                init_args=self.init_args, resource_request=rr))

    def with_init_args(self, *args, **kwargs) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, self.concurrency,
                   (args, kwargs), self.num_cpus, self.num_gpus, self.memory_bytes)

    def with_concurrency(self, concurrency: int) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, concurrency,
                   self.init_args, self.num_cpus, self.num_gpus, self.memory_bytes)

    def override_options(self, *, num_cpus=None, num_gpus=None, memory_bytes=None) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, self.concurrency,
                   self.init_args, num_cpus or self.num_cpus, num_gpus or self.num_gpus,
                   memory_bytes or self.memory_bytes)


def udf(*, return_dtype: DataType, batch_size: Optional[int] = None,
        concurrency: Optional[int] = None, num_cpus: Optional[float] = None,
        num_gpus: Optional[float] = None, memory_bytes: Optional[int] = None):
    """Decorator creating a UDF (reference: @daft.udf, daft/udf.py:441).

    def/class targets both work; class targets are stateful (one instance per
    worker, like the reference's actor-pool UDFs).
    """

    def wrap(fn):
        return UDF(fn, return_dtype, batch_size, concurrency,
                   num_cpus=num_cpus, num_gpus=num_gpus, memory_bytes=memory_bytes)

    return wrap
