"""Python UDFs: the batch trampoline and the @udf decorator.

Role-equivalent to the reference's daft/udf.py (StatelessUDF/StatefulUDF, :272/:308,
run_udf trampoline :82-200). UDFs receive Series (or scalars for literal args) in
batches and return a Series/list/numpy array; `batch_size` splits long columns;
class UDFs (stateful) are instantiated once per executor worker and reused —
the TPU analog of actor pools for `.embed()`-style model UDFs.

Batch-declared UDFs (`@daft_tpu.batch_udf` or `udf(..., batching=...)`) opt in
to the dynamic-batching subsystem (daft_tpu/batch/): the declaration is the
user's contract that the fn is row-local, so the engine may coalesce morsels
into device-friendly batches and re-split the output. Class-target batch UDFs
route through ModelActorPool (batch/actors.py) so weights load once per
process and stay resident across queries.
"""

from __future__ import annotations

import inspect
from typing import Any, Callable, List, Optional, Sequence, Union

import numpy as np

from .datatypes import DataType
from .series import Series

_STATEFUL_INSTANCES: dict = {}

_BATCHING_KEYS = ("max_rows", "max_bytes", "flush_ms", "mode", "device")


def _normalize_batching(batching: Any) -> Optional[dict]:
    """Validate a batching declaration into a plain dict (or None).

    True means "batch with config defaults"; a dict may override any of
    max_rows / max_bytes / flush_ms / mode ("ragged"|"padded") / device."""
    if batching is None or batching is False:
        return None
    if batching is True:
        return {}
    if not isinstance(batching, dict):
        raise ValueError(
            f"batching must be True/False or a dict, got {type(batching).__name__}"
        )
    bad = [k for k in batching if k not in _BATCHING_KEYS]
    if bad:
        raise ValueError(
            f"unknown batching key(s) {bad!r}; valid keys: {list(_BATCHING_KEYS)}"
        )
    mode = batching.get("mode")
    if mode is not None and mode not in ("ragged", "padded"):
        raise ValueError(f'batching mode must be "ragged" or "padded", got {mode!r}')
    return dict(batching)


def _coerce_result(out: Any, name: str, dtype: DataType, n: int) -> Series:
    if isinstance(out, Series):
        s = out
    elif isinstance(out, np.ndarray):
        s = Series.from_numpy(out, name)
    elif isinstance(out, (list, tuple)):
        s = Series.from_pylist(list(out), name, dtype)
    else:
        try:
            import pyarrow as pa

            if isinstance(out, (pa.Array, pa.ChunkedArray)):
                s = Series.from_arrow(out, name)
            else:
                raise TypeError
        except TypeError:
            raise ValueError(
                f"UDF must return Series/list/numpy/arrow, got {type(out).__name__}"
            ) from None
    if len(s) != n:
        raise ValueError(f"UDF returned {len(s)} rows, expected {n}")
    if s.dtype != dtype:
        s = s.cast(dtype)
    return s


def run_udf(fn: Callable, args: List[Series], return_dtype: DataType, n: int,
            batch_size: Optional[int] = None, init_args: Optional[tuple] = None,
            concurrency: Optional[int] = None,
            batching: Optional[dict] = None) -> Series:
    """Evaluate a UDF over column batches (reference: daft/udf.py run_udf).

    Stateful (class) UDFs with concurrency>1 run on a persistent actor pool
    (actor_pool.py): one instance per worker, batches dispatched across them,
    results re-assembled in order. Batch-declared class UDFs instead pin one
    instance per process via ModelActorPool (weights resident across queries,
    LRU-evicted under the ledger's model_cache_bytes account)."""
    from .series import _broadcast_to

    name = args[0].name if args else "udf"
    args = [_broadcast_to(a, n) if len(a) != n else a for a in args]

    if batching is not None and inspect.isclass(fn):
        from .batch.actors import get_model_pool

        pool = get_model_pool(fn, init_args)
        out = None
        if batching.get("device"):
            from .batch.device import device_apply

            out = device_apply(pool, args, n)  # None = host fallback
        if out is None:
            out = pool.apply(args, n)
        return _coerce_result(out, name, return_dtype, n)

    if inspect.isclass(fn) and concurrency and concurrency > 1:
        from .actor_pool import get_pool

        pool = get_pool(fn, init_args, concurrency)
        bs = batch_size or max(1, -(-n // concurrency))  # ceil-split across actors
        bounds = [(s, min(s + bs, n)) for s in range(0, n, bs)] or [(0, 0)]
        batches = [tuple(a.slice(s, e) for a in args) for s, e in bounds]
        outs = pool.map_batches(batches)
        coerced = [_coerce_result(o, name, return_dtype, e - s)
                   for o, (s, e) in zip(outs, bounds)]
        return Series.concat(coerced) if len(coerced) > 1 else coerced[0]

    if inspect.isclass(fn):
        key = (fn, repr(init_args))
        if key not in _STATEFUL_INSTANCES:
            a, kw = (init_args or ((), {}))
            _STATEFUL_INSTANCES[key] = fn(*a, **kw)
        fn = _STATEFUL_INSTANCES[key].__call__

    if not batch_size or n <= batch_size:
        return _coerce_result(fn(*args), name, return_dtype, n)
    outs = []
    for start in range(0, n, batch_size):
        end = min(start + batch_size, n)
        chunk = [a.slice(start, end) for a in args]
        outs.append(_coerce_result(fn(*chunk), name, return_dtype, end - start))
    return Series.concat(outs)


class UDF:
    """A wrapped user function callable over expressions."""

    def __init__(self, fn: Callable, return_dtype: DataType,
                 batch_size: Optional[int] = None, concurrency: Optional[int] = None,
                 init_args: Optional[tuple] = None, num_cpus: Optional[float] = None,
                 num_gpus: Optional[float] = None, memory_bytes: Optional[int] = None,
                 batching: Optional[dict] = None):
        self.fn = fn
        self.return_dtype = return_dtype
        self.batch_size = batch_size
        self.concurrency = concurrency
        self.init_args = init_args
        self.num_cpus = num_cpus
        self.num_gpus = num_gpus
        self.memory_bytes = memory_bytes
        self.batching = batching
        self.__name__ = getattr(fn, "__name__", "udf")

    def __call__(self, *exprs):
        from .expressions import Expression, PyUdf, _as_expr_node

        nodes = [_as_expr_node(e) for e in exprs]
        rr = None
        if self.num_cpus or self.num_gpus or self.memory_bytes:
            rr = (self.num_cpus, self.num_gpus, self.memory_bytes)
        return Expression(PyUdf(self.fn, self.return_dtype, nodes, fn_name=self.__name__,
                                batch_size=self.batch_size, concurrency=self.concurrency,
                                init_args=self.init_args, resource_request=rr,
                                batching=self.batching))

    def with_init_args(self, *args, **kwargs) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, self.concurrency,
                   (args, kwargs), self.num_cpus, self.num_gpus, self.memory_bytes,
                   self.batching)

    def with_concurrency(self, concurrency: int) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, concurrency,
                   self.init_args, self.num_cpus, self.num_gpus, self.memory_bytes,
                   self.batching)

    def override_options(self, *, num_cpus=None, num_gpus=None, memory_bytes=None) -> "UDF":
        return UDF(self.fn, self.return_dtype, self.batch_size, self.concurrency,
                   self.init_args, num_cpus or self.num_cpus, num_gpus or self.num_gpus,
                   memory_bytes or self.memory_bytes, self.batching)


def udf(*, return_dtype: DataType, batch_size: Optional[int] = None,
        concurrency: Optional[int] = None, num_cpus: Optional[float] = None,
        num_gpus: Optional[float] = None, memory_bytes: Optional[int] = None,
        batching: Any = None):
    """Decorator creating a UDF (reference: @daft.udf, daft/udf.py:441).

    def/class targets both work; class targets are stateful (one instance per
    worker, like the reference's actor-pool UDFs). Pass `batching=True` (or a
    dict of overrides) to opt into the dynamic-batching executor — see
    batch_udf for the dedicated declaration.
    """

    def wrap(fn):
        return UDF(fn, return_dtype, batch_size, concurrency,
                   num_cpus=num_cpus, num_gpus=num_gpus, memory_bytes=memory_bytes,
                   batching=_normalize_batching(batching))

    return wrap


def batch_udf(*, return_dtype: DataType, max_rows: Optional[int] = None,
              max_bytes: Optional[int] = None, flush_ms: Optional[float] = None,
              mode: Optional[str] = None, device: bool = False,
              concurrency: Optional[int] = None,
              num_cpus: Optional[float] = None, num_gpus: Optional[float] = None,
              memory_bytes: Optional[int] = None):
    """Declare a dynamically-batched UDF (daft_tpu/batch/).

    The declaration is a contract that the fn is ROW-LOCAL: output row i
    depends only on input row i. Under that contract the engine coalesces
    morsels (and partitions) into device-friendly batches under a byte/row
    budget with a max-latency flush timer, then re-splits results to exact
    source boundaries — outputs are byte-identical to the unbatched path.

    Class targets become pinned model actors: __init__ runs once per process
    (weights loaded once), the instance stays resident across queries keyed
    by model fingerprint, and eviction is LRU under the ledger's
    model_cache_bytes budget. `device=True` additionally requests the jit'd
    apply path behind the device breaker (host fallback on trip).
    """
    batching = {}
    if max_rows is not None:
        batching["max_rows"] = max_rows
    if max_bytes is not None:
        batching["max_bytes"] = max_bytes
    if flush_ms is not None:
        batching["flush_ms"] = flush_ms
    if mode is not None:
        batching["mode"] = mode
    if device:
        batching["device"] = True

    def wrap(fn):
        return UDF(fn, return_dtype, None, concurrency,
                   num_cpus=num_cpus, num_gpus=num_gpus, memory_bytes=memory_bytes,
                   batching=_normalize_batching(batching or True))

    return wrap
