"""Deterministic, seeded fault injection for resilience testing.

Every recovery path in the engine — scan retries, object-store retries,
device→host kernel fallbacks, the device circuit breaker, collective→host
shuffle fallback, spill-failure hold-in-memory — guards real production
behavior, yet none of it triggers under healthy tests. This registry makes
those paths deterministically exercisable (HPTMT's per-operator failure
semantics; arxiv 2604.21275's reproducible transient-fault replay): code at
a fault *site* calls ``check(site)``, and a test/config arms a plan that
decides, per call, whether to raise.

Sites wired into the engine are declared in ``SITES`` below — the
machine-readable registry daftlint's DTL004 rule cross-checks against every
``check()`` caller (a registered site with no caller is dead resilience
surface; a caller with an unregistered site can never be armed by name).

Plans are deterministic: ``always`` / ``first_n`` / ``nth`` fire by call
count; ``rate`` hashes (seed, site, call#) so the same seed reproduces the
same failure sequence on every run — no wall-clock, no global RNG state.

The disarmed fast path is one module-global boolean check, so production
code pays nothing when no plan is armed.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Dict, Optional

from .errors import DaftTransientError, DaftValueError

# The engine's fault-site registry: site name -> where/when it fires. This
# is the contract daftlint (tools/daftlint, rule DTL004) enforces statically
# — every entry must have a check() caller in the engine, and engine code
# must not check() unregistered names. Arbitrary names stay legal at
# runtime (tests arm synthetic sites to exercise plan mechanics).
SITES = {
    "io.get": "each object-store read attempt (inside the retry loop)",
    "scan.read": "each scan-task read attempt (inside the retry loop)",
    "device.kernel": "each device-kernel attempt (sync and async launch)",
    "collective.exchange": "each mesh all_to_all shuffle attempt",
    "spill.write": "each partition spill write (sync, or on the async "
                   "writer thread — failure holds the partition in memory)",
    "spill.readback": "each spilled-partition re-materialization "
                      "(consumer-thread read or unspill readahead; errors "
                      "propagate to the drain consumer)",
    "prefetch.fetch": "each background scan-prefetch fetch "
                      "(io/prefetch.py; errors re-raise from the "
                      "partition's read on the execution thread)",
    "sketch.merge": "each stage-2 sketch merge (HLL register max / "
                    "quantile-sample concat, daft_tpu/sketch/)",
    "collective.sketch": "each mesh register-array sketch-merge collective "
                         "(all_gather+max, parallel/mesh_exec.py)",
    "fuse.compile": "each map-chain fusion compile (daft_tpu/fuse/; a "
                    "compile-time failure falls back to the unfused op "
                    "chain, never a query failure)",
    "fuse.segment": "each plan-segment compile AND each resident handoff "
                    "(daft_tpu/fuse/segment.py; either failure degrades to "
                    "the staged per-op device path, never a query failure)",
    "join.filter": "each runtime-join-filter build feed / probe prune "
                   "(daft_tpu/exchange/joinfilter.py; any failure degrades "
                   "to the unfiltered exchange, never a query failure)",
    "exchange.encode": "each exchange-payload encode attempt "
                       "(daft_tpu/exchange/encode.py; a failure ships the "
                       "piece raw, never a query failure)",
    "worker.spawn": "each distributed-worker process spawn attempt "
                    "(daft_tpu/dist/supervisor.py; a failure consumes "
                    "restart budget and the pool degrades, never hangs)",
    "worker.exec": "each task dispatch to a distributed worker "
                   "(daft_tpu/dist/supervisor.py; an injected fault "
                   "SIGKILLs the target worker — the deterministic "
                   "kill-a-worker-mid-query chaos hook — and the task "
                   "re-dispatches to a surviving worker)",
    "worker.heartbeat": "each supervision-loop heartbeat check of one "
                        "worker (daft_tpu/dist/supervisor.py; an injected "
                        "fault reads as a missed heartbeat deadline — the "
                        "worker is declared dead and its in-flight tasks "
                        "re-dispatch)",
    "transport.send": "each length-prefixed frame send on the worker "
                      "transport (daft_tpu/dist/transport.py; a failed "
                      "send marks the connection dead and the supervision "
                      "layer re-dispatches)",
    "spill.corrupt": "each landed spill IPC write (sync or async writer "
                     "thread, daft_tpu/spill.py; an injected fault FLIPS "
                     "A REAL BIT in the written file AFTER its checksum "
                     "was recorded — the deterministic disk-corruption "
                     "hook behind detection + lineage recompute)",
    "transport.corrupt": "each checksummed transport frame send "
                         "(daft_tpu/dist/transport.py; an injected fault "
                         "flips a real bit in the payload AFTER its crc "
                         "was computed — the receiver's verify raises "
                         "DaftCorruptionError and the supervision layer "
                         "re-dispatches)",
    "worker.task": "each task execution on a distributed worker "
                   "(daft_tpu/dist/worker.py; armable per worker via "
                   "DAFT_TPU_DIST_FAULT_SPEC — a delay_s plan SLOWS the "
                   "worker instead of failing it, the deterministic "
                   "straggler hook behind speculative execution)",
    "telemetry.fragment": "each worker telemetry-fragment merge at the "
                          "driver (daft_tpu/obs/cluster.py; an injected "
                          "fault DROPS the fragment — telemetry_dropped "
                          "counts it, the task's result is untouched and "
                          "the task is never re-dispatched: telemetry is "
                          "fail-open end to end)",
    "plancache.lookup": "each plan-cache consult "
                        "(daft_tpu/adapt/plancache.py; a failure degrades "
                        "to uncached planning — the warm path fails OPEN, "
                        "never a query failure)",
    "resultcache.lookup": "each sub-plan result-cache consult "
                          "(daft_tpu/adapt/resultcache.py; a failure "
                          "degrades to plain execution of the prefix — "
                          "fails open, never a query failure)",
    "peer.fetch": "each peer-shuffle piece fetch at the read site "
                  "(daft_tpu/dist/peerplane.py; an injected fault reads "
                  "as a dead/severed peer — the fetcher fails over to "
                  "the piece's lineage recipe and recomputes just the "
                  "lost piece (peer_refetches), never a hung query)",
    "worker.drain": "each graceful worker drain request "
                    "(daft_tpu/dist/supervisor.py; an injected fault "
                    "degrades the drain to the SIGKILL/redispatch loss "
                    "path — the already-proven recovery machinery — "
                    "never a hung quiesce)",
    "batch.coalesce": "each dynamic-batching coalesce step "
                      "(daft_tpu/batch/coalesce.py; a failure settles the "
                      "buffered charge and degrades the op to the "
                      "per-partition UDF path — byte-identical, never a "
                      "query failure)",
    "actor.load": "each pinned-model actor-pool construction "
                  "(daft_tpu/batch/actors.py; a failed model load "
                  "surfaces as a typed DaftError naming the model — "
                  "never a hang, never a leaked half-initialized pool)",
    "persist.load": "each persistent-store read — warm-start artifact "
                    "load and result disk-tier lookup "
                    "(daft_tpu/persist/; an injected fault reads as a "
                    "COLD MISS counted in persist_load_failures — the "
                    "query plans/executes for real, never an error)",
    "persist.store": "each persistent-store write — artifact save and "
                     "result disk-tier insert (daft_tpu/persist/; an "
                     "injected fault drops the write, counted in "
                     "persist_store_failures — the query's own result "
                     "is never affected)",
    "persist.refresh": "each incremental-refresh splice of a disk-tier "
                       "entry (daft_tpu/persist/resultstore.py; an "
                       "injected fault degrades the refresh to a full "
                       "cold miss — plain recompute, never a stale or "
                       "partial entry)",
}


class InjectedFault(DaftTransientError):
    """Raised by an armed fault plan. Subclasses the engine's transient
    error (an IOError/OSError) so retry policies and device fallbacks treat
    it exactly like a real transient failure."""


class FaultPlan:
    """Decides, per call, whether an armed site fires.

    Modes:
      - ``always``:      every call fails
      - ``first_n``:     calls 1..n fail, then the site heals
                         (n=1 is fail-once-then-heal)
      - ``nth``:         exactly call #n fails (1-based)
      - ``rate``:        each call fails with probability ``rate``, decided
                         by sha256(seed, site, call#) — deterministic
    """

    __slots__ = ("mode", "n", "rate", "seed", "exc", "message", "delay_s")

    def __init__(self, mode: str = "always", n: int = 1, rate: float = 0.0,
                 seed: int = 0, exc: type = InjectedFault,
                 message: str = "", delay_s: float = 0.0):
        if mode not in ("always", "first_n", "nth", "rate"):
            # a misconfigured plan is a caller bug, never a retryable fault
            raise DaftValueError(f"unknown fault mode {mode!r}")
        self.mode = mode
        self.n = n
        self.rate = rate
        self.seed = seed
        self.exc = exc
        self.message = message
        # delay plans SLOW the site instead of failing it (the straggler
        # hook): a firing call sleeps delay_s and returns — the one
        # deliberate wall-clock dependency in this module, because a
        # straggler IS a wall-clock phenomenon
        self.delay_s = float(delay_s)

    def should_fire(self, site: str, call_no: int) -> bool:
        """call_no is 1-based: the first check() at an armed site is #1."""
        if self.mode == "always":
            return True
        if self.mode == "first_n":
            return call_no <= self.n
        if self.mode == "nth":
            return call_no == self.n
        digest = hashlib.sha256(
            f"{self.seed}:{site}:{call_no}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64) < self.rate

    def __repr__(self) -> str:
        return (f"FaultPlan({self.mode}, n={self.n}, rate={self.rate}, "
                f"seed={self.seed})")


_lock = threading.Lock()
_plans: Dict[str, FaultPlan] = {}
_calls: Dict[str, int] = {}
_injected: Dict[str, int] = {}
# fast-path flag: check() returns immediately when nothing is armed, so the
# hot loops (every io read, every device attempt) pay one boolean test
_armed = False


def arm(site: str, mode: str = "always", **kwargs) -> FaultPlan:
    """Arm a plan at a site (replacing any existing plan and resetting the
    site's call AND injected counters). Returns the plan for introspection."""
    global _armed
    plan = FaultPlan(mode, **kwargs)
    with _lock:
        _plans[site] = plan
        _calls[site] = 0
        _injected[site] = 0
        _armed = True
    return plan


def any_armed() -> bool:
    """True while ANY fault plan is armed. The adapt/ caches consult this
    and stand down entirely under an armed registry: fault injection is a
    determinism surface (a cached plan or replayed prefix would let an
    armed site silently never fire), so chaos runs always execute for
    real."""
    return _armed


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site, or every site (and clear all counters) when None."""
    global _armed
    with _lock:
        if site is None:
            _plans.clear()
            _calls.clear()
            _injected.clear()
        else:
            _plans.pop(site, None)
        _armed = bool(_plans)


@contextmanager
def inject(site: str, mode: str = "always", **kwargs):
    """Scoped arming: ``with faults.inject("scan.read", "first_n", n=2): ...``"""
    arm(site, mode, **kwargs)
    try:
        yield
    finally:
        disarm(site)


def check(site: str, stats=None) -> None:
    """Call at a fault site. Raises the armed plan's exception when the plan
    decides this call fails; otherwise a no-op. ``stats`` (a RuntimeStats)
    gets a ``faults_injected`` counter bump per fired fault; sites without a
    per-query stats handle (the IO layer) pass None and are still counted in
    ``snapshot()['injected']``."""
    if not _armed:
        return
    with _lock:
        plan = _plans.get(site)
        if plan is None:
            return
        _calls[site] = call_no = _calls.get(site, 0) + 1
        fire = plan.should_fire(site, call_no)
        if fire:
            _injected[site] = _injected.get(site, 0) + 1
    if not fire:
        return
    if stats is not None:
        stats.bump("faults_injected")
        if stats.profiler.armed:
            stats.profiler.event("fault_injected", site=site, call=call_no)
    from . import tracing

    tracing.add_instant(f"fault:{site}", {"call": call_no})
    if plan.delay_s > 0:
        # straggler plan: the site is slowed, not failed
        import time

        time.sleep(plan.delay_s)
        return
    raise plan.exc(plan.message or f"injected fault at {site} (call #{call_no})")


# env var a parent process sets BEFORE spawning workers so fault plans
# cross the process boundary (module-global plans do not): a JSON object
# (or list of objects) with site/mode/n/rate/seed/delay_s and an optional
# worker_id that scopes the plan to one worker slot — how the chaos/bench
# tooling slows exactly one worker into a straggler
ENV_FAULT_SPEC = "DAFT_TPU_DIST_FAULT_SPEC"


def arm_from_env(worker_id: Optional[int] = None) -> int:
    """Arm plans from :data:`ENV_FAULT_SPEC` (called by the distributed
    worker entrypoint at startup). Returns how many plans were armed; a
    malformed spec arms nothing — chaos tooling must never be able to
    turn a worker into a startup crash."""
    import json
    import os

    raw = os.environ.get(ENV_FAULT_SPEC)
    if not raw:
        return 0
    try:
        specs = json.loads(raw)
    except ValueError:
        return 0
    if isinstance(specs, dict):
        specs = [specs]
    armed = 0
    for spec in specs:
        if not isinstance(spec, dict) or "site" not in spec:
            continue
        target = spec.get("worker_id")
        if target is not None and worker_id is not None \
                and int(target) != int(worker_id):
            continue
        try:
            arm(spec["site"], spec.get("mode", "always"),
                n=int(spec.get("n", 1)), rate=float(spec.get("rate", 0.0)),
                seed=int(spec.get("seed", 0)),
                delay_s=float(spec.get("delay_s", 0.0)))
            armed += 1
        except Exception:
            continue
    return armed


def snapshot() -> dict:
    """Registry introspection: armed plans, per-site call and injection
    counts (tests assert against these)."""
    with _lock:
        return {
            "armed": {site: repr(p) for site, p in _plans.items()},
            "calls": dict(_calls),
            "injected": dict(_injected),
        }
