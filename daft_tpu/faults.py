"""Deterministic, seeded fault injection for resilience testing.

Every recovery path in the engine — scan retries, object-store retries,
device→host kernel fallbacks, the device circuit breaker, collective→host
shuffle fallback, spill-failure hold-in-memory — guards real production
behavior, yet none of it triggers under healthy tests. This registry makes
those paths deterministically exercisable (HPTMT's per-operator failure
semantics; arxiv 2604.21275's reproducible transient-fault replay): code at
a fault *site* calls ``check(site)``, and a test/config arms a plan that
decides, per call, whether to raise.

Sites wired into the engine are declared in ``SITES`` below — the
machine-readable registry daftlint's DTL004 rule cross-checks against every
``check()`` caller (a registered site with no caller is dead resilience
surface; a caller with an unregistered site can never be armed by name).

Plans are deterministic: ``always`` / ``first_n`` / ``nth`` fire by call
count; ``rate`` hashes (seed, site, call#) so the same seed reproduces the
same failure sequence on every run — no wall-clock, no global RNG state.

The disarmed fast path is one module-global boolean check, so production
code pays nothing when no plan is armed.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Dict, Optional

from .errors import DaftTransientError, DaftValueError

# The engine's fault-site registry: site name -> where/when it fires. This
# is the contract daftlint (tools/daftlint, rule DTL004) enforces statically
# — every entry must have a check() caller in the engine, and engine code
# must not check() unregistered names. Arbitrary names stay legal at
# runtime (tests arm synthetic sites to exercise plan mechanics).
SITES = {
    "io.get": "each object-store read attempt (inside the retry loop)",
    "scan.read": "each scan-task read attempt (inside the retry loop)",
    "device.kernel": "each device-kernel attempt (sync and async launch)",
    "collective.exchange": "each mesh all_to_all shuffle attempt",
    "spill.write": "each partition spill write (sync, or on the async "
                   "writer thread — failure holds the partition in memory)",
    "spill.readback": "each spilled-partition re-materialization "
                      "(consumer-thread read or unspill readahead; errors "
                      "propagate to the drain consumer)",
    "prefetch.fetch": "each background scan-prefetch fetch "
                      "(io/prefetch.py; errors re-raise from the "
                      "partition's read on the execution thread)",
    "sketch.merge": "each stage-2 sketch merge (HLL register max / "
                    "quantile-sample concat, daft_tpu/sketch/)",
    "collective.sketch": "each mesh register-array sketch-merge collective "
                         "(all_gather+max, parallel/mesh_exec.py)",
    "fuse.compile": "each map-chain fusion compile (daft_tpu/fuse/; a "
                    "compile-time failure falls back to the unfused op "
                    "chain, never a query failure)",
    "join.filter": "each runtime-join-filter build feed / probe prune "
                   "(daft_tpu/exchange/joinfilter.py; any failure degrades "
                   "to the unfiltered exchange, never a query failure)",
    "exchange.encode": "each exchange-payload encode attempt "
                       "(daft_tpu/exchange/encode.py; a failure ships the "
                       "piece raw, never a query failure)",
    "worker.spawn": "each distributed-worker process spawn attempt "
                    "(daft_tpu/dist/supervisor.py; a failure consumes "
                    "restart budget and the pool degrades, never hangs)",
    "worker.exec": "each task dispatch to a distributed worker "
                   "(daft_tpu/dist/supervisor.py; an injected fault "
                   "SIGKILLs the target worker — the deterministic "
                   "kill-a-worker-mid-query chaos hook — and the task "
                   "re-dispatches to a surviving worker)",
    "worker.heartbeat": "each supervision-loop heartbeat check of one "
                        "worker (daft_tpu/dist/supervisor.py; an injected "
                        "fault reads as a missed heartbeat deadline — the "
                        "worker is declared dead and its in-flight tasks "
                        "re-dispatch)",
    "transport.send": "each length-prefixed frame send on the worker "
                      "transport (daft_tpu/dist/transport.py; a failed "
                      "send marks the connection dead and the supervision "
                      "layer re-dispatches)",
}


class InjectedFault(DaftTransientError):
    """Raised by an armed fault plan. Subclasses the engine's transient
    error (an IOError/OSError) so retry policies and device fallbacks treat
    it exactly like a real transient failure."""


class FaultPlan:
    """Decides, per call, whether an armed site fires.

    Modes:
      - ``always``:      every call fails
      - ``first_n``:     calls 1..n fail, then the site heals
                         (n=1 is fail-once-then-heal)
      - ``nth``:         exactly call #n fails (1-based)
      - ``rate``:        each call fails with probability ``rate``, decided
                         by sha256(seed, site, call#) — deterministic
    """

    __slots__ = ("mode", "n", "rate", "seed", "exc", "message")

    def __init__(self, mode: str = "always", n: int = 1, rate: float = 0.0,
                 seed: int = 0, exc: type = InjectedFault,
                 message: str = ""):
        if mode not in ("always", "first_n", "nth", "rate"):
            # a misconfigured plan is a caller bug, never a retryable fault
            raise DaftValueError(f"unknown fault mode {mode!r}")
        self.mode = mode
        self.n = n
        self.rate = rate
        self.seed = seed
        self.exc = exc
        self.message = message

    def should_fire(self, site: str, call_no: int) -> bool:
        """call_no is 1-based: the first check() at an armed site is #1."""
        if self.mode == "always":
            return True
        if self.mode == "first_n":
            return call_no <= self.n
        if self.mode == "nth":
            return call_no == self.n
        digest = hashlib.sha256(
            f"{self.seed}:{site}:{call_no}".encode()).digest()
        return int.from_bytes(digest[:8], "big") / float(1 << 64) < self.rate

    def __repr__(self) -> str:
        return (f"FaultPlan({self.mode}, n={self.n}, rate={self.rate}, "
                f"seed={self.seed})")


_lock = threading.Lock()
_plans: Dict[str, FaultPlan] = {}
_calls: Dict[str, int] = {}
_injected: Dict[str, int] = {}
# fast-path flag: check() returns immediately when nothing is armed, so the
# hot loops (every io read, every device attempt) pay one boolean test
_armed = False


def arm(site: str, mode: str = "always", **kwargs) -> FaultPlan:
    """Arm a plan at a site (replacing any existing plan and resetting the
    site's call AND injected counters). Returns the plan for introspection."""
    global _armed
    plan = FaultPlan(mode, **kwargs)
    with _lock:
        _plans[site] = plan
        _calls[site] = 0
        _injected[site] = 0
        _armed = True
    return plan


def disarm(site: Optional[str] = None) -> None:
    """Disarm one site, or every site (and clear all counters) when None."""
    global _armed
    with _lock:
        if site is None:
            _plans.clear()
            _calls.clear()
            _injected.clear()
        else:
            _plans.pop(site, None)
        _armed = bool(_plans)


@contextmanager
def inject(site: str, mode: str = "always", **kwargs):
    """Scoped arming: ``with faults.inject("scan.read", "first_n", n=2): ...``"""
    arm(site, mode, **kwargs)
    try:
        yield
    finally:
        disarm(site)


def check(site: str, stats=None) -> None:
    """Call at a fault site. Raises the armed plan's exception when the plan
    decides this call fails; otherwise a no-op. ``stats`` (a RuntimeStats)
    gets a ``faults_injected`` counter bump per fired fault; sites without a
    per-query stats handle (the IO layer) pass None and are still counted in
    ``snapshot()['injected']``."""
    if not _armed:
        return
    with _lock:
        plan = _plans.get(site)
        if plan is None:
            return
        _calls[site] = call_no = _calls.get(site, 0) + 1
        fire = plan.should_fire(site, call_no)
        if fire:
            _injected[site] = _injected.get(site, 0) + 1
    if not fire:
        return
    if stats is not None:
        stats.bump("faults_injected")
        if stats.profiler.armed:
            stats.profiler.event("fault_injected", site=site, call=call_no)
    from . import tracing

    tracing.add_instant(f"fault:{site}", {"call": call_no})
    raise plan.exc(plan.message or f"injected fault at {site} (call #{call_no})")


def snapshot() -> dict:
    """Registry introspection: armed plans, per-site call and injection
    counts (tests assert against these)."""
    with _lock:
        return {
            "armed": {site: repr(p) for site, p in _plans.items()},
            "calls": dict(_calls),
            "injected": dict(_injected),
        }
