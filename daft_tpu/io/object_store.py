"""Object-store IO: sources, client, range-reads, retry, glob.

TPU-native counterpart of the reference's daft-io crate: the `ObjectSource`
trait (/root/reference/src/daft-io/src/object_io.rs), the S3 client with
retry modes and per-connection caps (s3_like.rs:452-468), and store-aware
glob (object_store_glob.rs). Pure stdlib (http.client + hashlib/hmac SigV4)
— the zero-egress build can't take on SDK dependencies, and the hot compute
path never touches this layer; scans and url.download do.

Scheme routing: `s3://bucket/key` (endpoint override via AWS_ENDPOINT_URL for
S3-compatible stores and tests), `http(s)://`, `file://`/bare paths.
Every read funnels through IOClient: a process-wide connection budget
(semaphore, like the reference's max_connections_per_io_thread), a retry
policy with exponential backoff + jitter on transient failures (5xx,
timeouts, connection resets), and IO_STATS counters.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import io
import os
import random
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..errors import (DaftIOError, DaftNotFoundError, DaftTransientError,
                      DaftValueError)
from ..obs.log import get_logger
from .scan import IO_STATS

logger = get_logger("object_store")


@dataclass
class ObjectMeta:
    path: str
    size: Optional[int] = None


class TransientIOError(DaftTransientError):
    """Retryable failure (5xx, timeout, connection reset). Subclasses
    DaftTransientError so one retry discipline covers real and injected
    transient failures engine-wide."""


class NotFoundIOError(DaftNotFoundError):
    """The listed container/prefix does not exist (HTTP 404). Distinct from
    transient/auth failures so callers like Storage.list_names can treat
    only genuine absence as an empty directory — an outage or expired
    credential must propagate, never read as 'table does not exist'.
    A DaftNotFoundError (and so a FileNotFoundError/IOError)."""


@dataclass
class RetryPolicy:
    """Mirrors the reference's S3 retry config (attempts + exponential
    backoff; jitter avoids thundering herds on shared endpoints). The ONE
    retry discipline in the engine: scan-task retries reuse it with their
    own `retryable`/`permanent` classes instead of hand-rolling uncapped,
    jitterless backoff."""

    attempts: int = 4
    backoff_s: float = 0.1
    max_backoff_s: float = 4.0
    # which exceptions retry; DaftTransientError covers the object-store
    # TransientIOError AND injected faults
    retryable: tuple = (DaftTransientError,)
    # subclasses of `retryable` that must propagate immediately (a missing
    # file inside a retryable OSError net, say) — checked first
    permanent: tuple = ()

    def run(self, fn):
        last = None
        for attempt in range(max(1, self.attempts)):
            try:
                return fn()
            except self.permanent:
                raise
            except self.retryable as e:
                last = e
                IO_STATS.bump(retries=1)
                if attempt + 1 >= self.attempts:
                    break
                delay = min(self.backoff_s * (2 ** attempt), self.max_backoff_s)
                time.sleep(delay * (0.5 + random.random() / 2))
        raise last


class ObjectSource:
    """get/get_range/put/ls/glob over one scheme (reference: ObjectSource
    trait, object_io.rs — incl. the put path used by s3_like.rs)."""

    def get(self, path: str, range: Optional[Tuple[int, int]] = None,
            timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def get_size(self, path: str) -> int:
        raise NotImplementedError

    def put(self, path: str, data: bytes, if_none_match: bool = False) -> None:
        """Write an object. `if_none_match` requests put-if-absent semantics
        (HTTP `If-None-Match: *` / local O_EXCL); raises FileExistsError when
        the object already exists — the atomic-commit primitive the Delta/
        Iceberg writers build on."""
        raise NotImplementedError

    def delete(self, path: str) -> None:
        raise NotImplementedError

    def ls(self, prefix: str) -> List[ObjectMeta]:
        raise NotImplementedError

    def glob(self, pattern: str) -> List[ObjectMeta]:
        raise NotImplementedError


class LocalSource(ObjectSource):
    def _p(self, path: str) -> str:
        return path[len("file://"):] if path.startswith("file://") else path

    def get(self, path, range=None, timeout=None):
        with open(self._p(path), "rb") as f:
            if range is None:
                return f.read()
            f.seek(range[0])
            return f.read(range[1] - range[0])

    def get_size(self, path):
        return os.path.getsize(self._p(path))

    def ls(self, prefix):
        p = self._p(prefix)
        if os.path.isfile(p):
            return [ObjectMeta(p, os.path.getsize(p))]
        out = []
        for root, _dirs, files in os.walk(p):
            for f in sorted(files):
                fp = os.path.join(root, f)
                out.append(ObjectMeta(fp, os.path.getsize(fp)))
        return out

    def put(self, path, data, if_none_match=False):
        p = self._p(path)
        os.makedirs(os.path.dirname(p) or ".", exist_ok=True)
        if if_none_match:
            fd = os.open(p, os.O_WRONLY | os.O_CREAT | os.O_EXCL)
            try:
                os.write(fd, data)
            finally:
                os.close(fd)
        else:
            with open(p, "wb") as f:
                f.write(data)

    def delete(self, path):
        os.unlink(self._p(path))

    def glob(self, pattern):
        import glob as _glob

        return [ObjectMeta(p, os.path.getsize(p))
                for p in sorted(_glob.glob(self._p(pattern), recursive=True))
                if os.path.isfile(p)]


def _http_request(url: str, method: str = "GET",
                  headers: Optional[Dict[str, str]] = None,
                  body: Optional[bytes] = None,
                  timeout: float = 30.0) -> Tuple[int, Dict[str, str], bytes]:
    """One HTTP exchange; maps transport failures and 5xx/429 to
    TransientIOError so the retry policy can act."""
    u = urllib.parse.urlsplit(url)
    conn_cls = http.client.HTTPSConnection if u.scheme == "https" else http.client.HTTPConnection
    conn = conn_cls(u.hostname, u.port, timeout=timeout)
    target = (u.path or "/") + (f"?{u.query}" if u.query else "")
    try:
        conn.request(method, target, body=body, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        status = resp.status
        rheaders = {k.lower(): v for k, v in resp.getheaders()}
    except (OSError, http.client.HTTPException) as e:
        raise TransientIOError(f"{method} {url}: {e}") from e
    finally:
        conn.close()
    if status >= 500 or status == 429:
        raise TransientIOError(f"{method} {url}: HTTP {status}")
    return status, rheaders, data


def _raise_http(op: str, path: str, status: int):
    """Map a terminal HTTP status to the typed error discipline: 404 is the
    distinguishable not-found (so exists() can answer False without
    swallowing auth/transient failures); everything else stays IOError."""
    if status == 404:
        raise NotFoundIOError(f"{op} {path}: HTTP 404")
    raise DaftIOError(f"{op} {path}: HTTP {status}")


class HttpSource(ObjectSource):
    """http(s) objects with Range reads and redirect following
    (reference: http.rs)."""

    MAX_REDIRECTS = 5

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def _request(self, url, method="GET", headers=None, timeout=None,
                 body=None):
        """Follow up to MAX_REDIRECTS 3xx hops (presigned urls, CDNs, and
        http->https upgrades all redirect; urllib used to do this for us)."""
        t = timeout if timeout is not None else self.timeout
        for _ in range(self.MAX_REDIRECTS + 1):
            status, h, data = _http_request(url, method=method,
                                            headers=headers, body=body,
                                            timeout=t)
            if status in (301, 302, 303, 307, 308) and "location" in h:
                url = urllib.parse.urljoin(url, h["location"])
                continue
            return status, h, data
        raise DaftIOError(f"{method} {url}: too many redirects")

    def get(self, path, range=None, timeout=None):
        headers = {}
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        status, _h, data = self._request(path, headers=headers, timeout=timeout)
        if status not in (200, 206):
            _raise_http("GET", path, status)
        if range is not None and status == 200:
            return data[range[0]:range[1]]  # server ignored Range
        return data

    def get_size(self, path):
        status, h, _ = self._request(path, method="HEAD")
        if status != 200:
            _raise_http("HEAD", path, status)
        if "content-length" not in h:
            # 200 without Content-Length (chunked/dynamic HEAD): the object
            # exists but its size is only learnable by reading it
            return len(self.get(path))
        return int(h["content-length"])

    def put(self, path, data, if_none_match=False):
        headers = {"If-None-Match": "*"} if if_none_match else {}
        status, _h, _b = self._request(path, method="PUT", headers=headers,
                                       body=data)
        if status in (409, 412):
            raise FileExistsError(f"PUT {path}: exists (HTTP {status})")
        if status not in (200, 201, 204):
            raise DaftIOError(f"PUT {path}: HTTP {status}")

    def ls(self, prefix):
        raise DaftIOError("http source cannot list; pass explicit urls")

    def glob(self, pattern):
        if any(ch in pattern for ch in "*?["):
            raise DaftIOError("http source cannot glob; pass explicit urls")
        return [ObjectMeta(pattern)]


@dataclass
class S3Config:
    """Reference: common/io-config S3Config. Pulled from the environment by
    default; endpoint_url points S3-compatible stores (and tests) anywhere."""

    endpoint_url: Optional[str] = None
    region: str = "us-east-1"
    key_id: Optional[str] = None
    secret_key: Optional[str] = None
    session_token: Optional[str] = None
    anonymous: bool = False
    timeout: float = 30.0

    @staticmethod
    def from_env() -> "S3Config":
        return S3Config(
            endpoint_url=os.environ.get("AWS_ENDPOINT_URL"),
            region=os.environ.get("AWS_REGION", "us-east-1"),
            key_id=os.environ.get("AWS_ACCESS_KEY_ID"),
            secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY"),
            session_token=os.environ.get("AWS_SESSION_TOKEN"),
        )


def _sigv4_headers(cfg: S3Config, method: str, url: str,
                   payload_hash: str = "UNSIGNED-PAYLOAD",
                   extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
    """AWS Signature V4 (pure stdlib). Skipped for anonymous access.
    `extra` headers (e.g. If-None-Match on conditional writes) are folded
    into the signed set."""
    u = urllib.parse.urlsplit(url)
    now = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    datestamp = time.strftime("%Y%m%d", now)
    host = u.hostname + (f":{u.port}" if u.port else "")
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    for k, v in (extra or {}).items():
        headers[k.lower()] = v
    if cfg.session_token:
        headers["x-amz-security-token"] = cfg.session_token
    signed = ";".join(sorted(headers))
    canonical_q = "&".join(sorted(u.query.split("&"))) if u.query else ""
    # u.path is already percent-encoded by the caller (_url quotes the key);
    # re-quoting would double-encode and break the signature for keys with
    # spaces/'+'/'=' (SignatureDoesNotMatch)
    canonical = "\n".join([
        method, u.path or "/", canonical_q,
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)), signed,
        payload_hash])
    scope = f"{datestamp}/{cfg.region}/s3/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + cfg.secret_key).encode(), datestamp)
    k = _hmac(_hmac(_hmac(k, cfg.region), "s3"), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={cfg.key_id}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    out.pop("host")  # http.client sets it
    return out


class S3Source(ObjectSource):
    """Minimal S3 REST dialect: GET object (+Range), HEAD, ListObjectsV2 with
    pagination (reference: s3_like.rs). Path-style addressing against
    endpoint_url; virtual-host style against AWS proper."""

    scheme = "s3"

    def __init__(self, cfg: Optional[S3Config] = None):
        self.cfg = cfg or S3Config.from_env()

    def _url(self, bucket: str, key: str = "", query: str = "") -> str:
        if self.cfg.endpoint_url:
            base = self.cfg.endpoint_url.rstrip("/")
            url = f"{base}/{bucket}"
        else:
            url = f"https://{bucket}.s3.{self.cfg.region}.amazonaws.com"
        if key:
            url += "/" + urllib.parse.quote(key)
        if query:
            url += "?" + query
        return url

    def _will_sign(self) -> bool:
        return not self.cfg.anonymous and bool(
            self.cfg.key_id and self.cfg.secret_key)

    def _payload_hash(self, data) -> str:
        """sha256 of the body, but only when a signature will carry it —
        hashing a 512 MB part on the 1-CPU host is seconds of pure waste
        for anonymous/bearer-auth uploads."""
        if not self._will_sign():
            return "UNSIGNED-PAYLOAD"
        return hashlib.sha256(data).hexdigest()

    def _headers(self, method: str, url: str,
                 payload_hash: str = "UNSIGNED-PAYLOAD",
                 extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        if not self._will_sign():
            return dict(extra or {})
        return _sigv4_headers(self.cfg, method, url, payload_hash, extra)

    @classmethod
    def _split(cls, path: str) -> Tuple[str, str]:
        rest = path[len(cls.scheme) + 3:]
        bucket, _, key = rest.partition("/")
        return bucket, key

    def get(self, path, range=None, timeout=None):
        bucket, key = self._split(path)
        url = self._url(bucket, key)
        headers = self._headers("GET", url)
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        status, _h, data = _http_request(
            url, headers=headers,
            timeout=timeout if timeout is not None else self.cfg.timeout)
        if status not in (200, 206):
            _raise_http("GET", path, status)
        if range is not None and status == 200:
            return data[range[0]:range[1]]  # endpoint ignored Range
        return data

    def get_size(self, path):
        bucket, key = self._split(path)
        url = self._url(bucket, key)
        status, h, _ = _http_request(url, method="HEAD",
                                     headers=self._headers("HEAD", url),
                                     timeout=self.cfg.timeout)
        if status != 200 or "content-length" not in h:
            _raise_http("HEAD", path, status)
        return int(h["content-length"])

    # Multipart kicks in above this size (instance attrs so tests can force
    # the multipart path with small objects); S3's own floor is 5 MiB parts.
    multipart_threshold = 64 * 1024 * 1024
    part_size = 32 * 1024 * 1024

    def put(self, path, data, if_none_match=False):
        """PUT object; conditional via `If-None-Match: *` (S3 put-if-absent,
        2024 API — the atomic-commit primitive; reference: s3_like.rs put).
        Objects past multipart_threshold go through CreateMultipartUpload/
        UploadPart/CompleteMultipartUpload."""
        bucket, key = self._split(path)
        if len(data) > self.multipart_threshold:
            return self._put_multipart(bucket, key, path, data, if_none_match)
        url = self._url(bucket, key)
        extra = {"If-None-Match": "*"} if if_none_match else None
        headers = self._headers("PUT", url,
                                payload_hash=self._payload_hash(data),
                                extra=extra)
        status, _h, body = _http_request(url, method="PUT", headers=headers,
                                         body=data, timeout=self.cfg.timeout)
        if status in (409, 412):
            raise FileExistsError(f"PUT {path}: object exists (HTTP {status})")
        if status not in (200, 201):
            raise DaftIOError(f"PUT {path}: HTTP {status}")

    def _put_multipart(self, bucket, key, path, data, if_none_match):
        import xml.etree.ElementTree as ET

        url = self._url(bucket, key, query="uploads=")
        status, _h, body = _http_request(
            url, method="POST", headers=self._headers("POST", url,
            payload_hash=self._payload_hash(b"")),
            timeout=self.cfg.timeout)
        if status != 200:
            raise DaftIOError(f"CreateMultipartUpload {path}: HTTP {status}")
        root = ET.fromstring(body)
        ns = root.tag[:root.tag.index("}") + 1] if root.tag.startswith("{") else ""
        uid_el = root.find(f"{ns}UploadId")
        if uid_el is None or not uid_el.text:
            raise DaftIOError(f"CreateMultipartUpload {path}: no UploadId")
        uid = urllib.parse.quote(uid_el.text, safe="")
        try:
            etags: List[str] = []
            for n, start in enumerate(range(0, len(data), self.part_size), 1):
                part = data[start:start + self.part_size]
                purl = self._url(bucket, key,
                                 query=f"partNumber={n}&uploadId={uid}")
                status, h, _b = _http_request(
                    purl, method="PUT",
                    headers=self._headers("PUT", purl,
                    payload_hash=self._payload_hash(part)),
                    body=part, timeout=self.cfg.timeout)
                if status != 200:
                    raise DaftIOError(f"UploadPart {n} {path}: HTTP {status}")
                etags.append(h.get("etag", ""))
            manifest = ("<CompleteMultipartUpload>" + "".join(
                f"<Part><PartNumber>{n}</PartNumber><ETag>{e}</ETag></Part>"
                for n, e in enumerate(etags, 1)) +
                "</CompleteMultipartUpload>").encode()
            curl = self._url(bucket, key, query=f"uploadId={uid}")
            extra = {"If-None-Match": "*"} if if_none_match else None
            status, _h, _b = _http_request(
                curl, method="POST", headers=self._headers("POST", curl,
                payload_hash=self._payload_hash(manifest), extra=extra),
                body=manifest, timeout=self.cfg.timeout)
            if status in (409, 412):
                raise FileExistsError(f"PUT {path}: object exists (HTTP {status})")
            if status != 200:
                raise DaftIOError(f"CompleteMultipartUpload {path}: HTTP {status}")
        except BaseException:
            try:  # abort so the store reclaims staged parts; best-effort
                aurl = self._url(bucket, key, query=f"uploadId={uid}")
                _http_request(aurl, method="DELETE",
                              headers=self._headers("DELETE", aurl),
                              timeout=self.cfg.timeout)
            except Exception as abort_err:
                # the original upload failure is what propagates; a failed
                # abort only leaves staged parts for the store's GC
                logger.warning("multipart_abort_failed", path=path,
                               error=repr(abort_err),
                               note="staged parts await bucket lifecycle GC")
            raise

    def delete(self, path):
        bucket, key = self._split(path)
        url = self._url(bucket, key)
        status, _h, _b = _http_request(url, method="DELETE",
                                       headers=self._headers("DELETE", url),
                                       timeout=self.cfg.timeout)
        if status not in (200, 204):
            raise DaftIOError(f"DELETE {path}: HTTP {status}")

    def ls(self, prefix):
        bucket, key = self._split(prefix)
        out: List[ObjectMeta] = []
        token = None
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote(key, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token, safe="")
            url = self._url(bucket, query=q)
            status, _h, data = _http_request(url, headers=self._headers("GET", url),
                                             timeout=self.cfg.timeout)
            if status == 404:
                raise NotFoundIOError(f"LIST {prefix}: HTTP 404")
            if status != 200:
                raise DaftIOError(f"LIST {prefix}: HTTP {status}")
            keys, token = _parse_list_objects(data)
            out.extend(ObjectMeta(f"{self.scheme}://{bucket}/{k}", sz)
                       for k, sz in keys)
            if not token:
                return out

    def glob(self, pattern):
        bucket, key = self._split(pattern)
        return _glob_via_ls(f"{self.scheme}://{bucket}", key, self.ls)


def _glob_via_ls(base: str, key: str, ls_fn) -> List[ObjectMeta]:
    """Shared store-glob: list from the longest wildcard-free prefix, then
    match with path-aware glob semantics — '*'/'?' stay within one path
    segment, '**' crosses segments — matching local glob and the reference's
    object_store_glob.rs (fnmatch would let '*' swallow '/'). A wildcard-free
    key returns the exact object, else a directory-style listing."""
    cut = len(key)
    for i, ch in enumerate(key):
        if ch in "*?[":
            cut = i
            break
    listed = ls_fn(f"{base}/{key[:cut]}")
    if cut == len(key):
        exact = [m for m in listed if m.path == f"{base}/{key}"]
        if exact:
            return exact
        dirp = f"{base}/{key.rstrip('/')}/"
        return [m for m in listed if m.path.startswith(dirp)]
    rx = _glob_to_regex(key)
    return [m for m in listed if rx.fullmatch(m.path[len(base) + 1:])]


def _glob_to_regex(pattern: str):
    """Translate a path glob to a regex where '*'/'?' do not cross '/' and
    '**' does (local-filesystem glob semantics)."""
    import re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
                if i < len(pattern) and pattern[i] == "/":
                    i += 1  # '**/' also matches zero directories
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                out.append(re.escape(c))
            else:
                out.append(pattern[i:j + 1])
                i = j
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out))


def _parse_list_objects(xml: bytes) -> Tuple[List[Tuple[str, Optional[int]]], Optional[str]]:
    import xml.etree.ElementTree as ET

    root = ET.fromstring(xml)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[:root.tag.index("}") + 1]
    keys = []
    for c in root.iter(f"{ns}Contents"):
        k = c.find(f"{ns}Key")
        s = c.find(f"{ns}Size")
        if k is not None:
            keys.append((k.text, int(s.text) if s is not None and s.text else None))
    trunc = root.find(f"{ns}IsTruncated")
    token = None
    if trunc is not None and (trunc.text or "").lower() == "true":
        t = root.find(f"{ns}NextContinuationToken")
        token = t.text if t is not None else None
    return keys, token


# ---------------------------------------------------------------------------
# GCS / Azure / HuggingFace sources
# ---------------------------------------------------------------------------

@dataclass
class GCSConfig:
    """Reference: common/io-config GCSConfig + google_cloud.rs. Auth is an
    OAuth2 bearer token (service-account JWT flows need RS256 signing, which
    stdlib can't do zero-egress) or anonymous; endpoint override for tests
    and fake-gcs servers."""

    endpoint_url: str = "https://storage.googleapis.com"
    token: Optional[str] = None
    anonymous: bool = False
    timeout: float = 30.0

    @staticmethod
    def from_env() -> "GCSConfig":
        return GCSConfig(
            endpoint_url=os.environ.get("GCS_ENDPOINT_URL",
                                        "https://storage.googleapis.com"),
            token=os.environ.get("GCS_TOKEN")
            or os.environ.get("GOOGLE_OAUTH_TOKEN"),
        )


class GCSSource(S3Source):
    """gs:// objects over the GCS XML API — which is S3-wire-compatible
    (path-style addressing, Range gets, list-type=2 listings), so the whole
    S3Source machinery (ranged reads, pagination, glob, multipart) is reused
    with bearer-token auth swapped in (reference: google_cloud.rs, 470 LoC,
    which likewise wraps an S3-compatible client when given an XML
    endpoint)."""

    scheme = "gs"

    def __init__(self, cfg: Optional[GCSConfig] = None):
        self.gcs = cfg or GCSConfig.from_env()
        # S3Source internals read endpoint/timeout off self.cfg
        super().__init__(S3Config(endpoint_url=self.gcs.endpoint_url,
                                  anonymous=True, timeout=self.gcs.timeout))

    def _will_sign(self):
        return False  # bearer token, never SigV4 -> skip payload hashing

    def _headers(self, method, url, payload_hash="UNSIGNED-PAYLOAD",
                 extra=None):
        out = dict(extra or {})
        # GCS does not honor S3's `If-None-Match: *` on uploads; its
        # put-if-absent is `x-goog-if-generation-match: 0` (docs: XML API
        # request headers). Translate so Delta commits on gs:// keep the
        # atomic contract instead of silently overwriting.
        if out.pop("If-None-Match", None) == "*":
            out["x-goog-if-generation-match"] = "0"
        if self.gcs.token and not self.gcs.anonymous:
            out["Authorization"] = f"Bearer {self.gcs.token}"
        return out


@dataclass
class AzureConfig:
    """Reference: common/io-config AzureConfig + azure_blob.rs. Shared-key
    auth (the SigV2-style HMAC the reference's azure SDK computes), a SAS
    token query suffix, or anonymous."""

    account: Optional[str] = None
    key: Optional[str] = None          # base64 shared key
    sas_token: Optional[str] = None    # pre-signed query string
    endpoint_url: Optional[str] = None  # override: http://host:port for tests
    anonymous: bool = False
    timeout: float = 30.0

    @staticmethod
    def from_env() -> "AzureConfig":
        return AzureConfig(
            account=os.environ.get("AZURE_STORAGE_ACCOUNT"),
            key=os.environ.get("AZURE_STORAGE_KEY"),
            sas_token=os.environ.get("AZURE_STORAGE_SAS_TOKEN"),
            endpoint_url=os.environ.get("AZURE_ENDPOINT_URL"),
        )


class AzureSource(ObjectSource):
    """az:// (and abfs[s]://) blobs over the Blob REST API: GET (+Range),
    HEAD, PUT, List Blobs with marker pagination, shared-key signing
    (reference: azure_blob.rs, 656 LoC)."""

    def __init__(self, cfg: Optional[AzureConfig] = None):
        self.cfg = cfg or AzureConfig.from_env()

    def _split(self, path: str) -> Tuple[str, str]:
        p = str(path)
        for pre in ("az://", "abfs://", "abfss://"):
            if p.startswith(pre):
                rest = p[len(pre):]
                break
        else:
            raise DaftValueError(f"not an azure path: {path}")
        container, _, key = rest.partition("/")
        # abfs://container@account.dfs.core.windows.net/key names the
        # account in the authority: honor it, never silently target a
        # DIFFERENT configured account (cross-account data corruption)
        if "@" in container:
            container, authority = container.split("@", 1)
            account = authority.split(".", 1)[0]
            if self.cfg.account and account != self.cfg.account:
                raise DaftIOError(
                    f"azure path names account {account!r} but the client "
                    f"is configured for {self.cfg.account!r}: {path}")
            if not self.cfg.account:
                self.cfg.account = account
        return container, key

    def _base(self) -> str:
        if self.cfg.endpoint_url:
            base = self.cfg.endpoint_url.rstrip("/")
            # test endpoints (azurite-style) scope urls by account
            if self.cfg.account and not base.endswith(self.cfg.account):
                base = f"{base}/{self.cfg.account}"
            return base
        if not self.cfg.account:
            raise DaftIOError("azure: AZURE_STORAGE_ACCOUNT is not set")
        return f"https://{self.cfg.account}.blob.core.windows.net"

    def _url(self, container: str, key: str = "", query: str = "") -> str:
        url = f"{self._base()}/{container}"
        if key:
            url += "/" + urllib.parse.quote(key)
        q = query
        if self.cfg.sas_token:
            sas = self.cfg.sas_token.lstrip("?")
            q = f"{q}&{sas}" if q else sas
        if q:
            url += "?" + q
        return url

    def _headers(self, method: str, url: str, content_length: int = 0,
                 extra: Optional[Dict[str, str]] = None) -> Dict[str, str]:
        headers = dict(extra or {})
        headers["x-ms-version"] = "2021-08-06"
        headers["x-ms-date"] = time.strftime("%a, %d %b %Y %H:%M:%S GMT",
                                             time.gmtime())
        if self.cfg.anonymous or not (self.cfg.account and self.cfg.key):
            return headers
        import base64

        u = urllib.parse.urlsplit(url)
        # canonicalized x-ms-* headers, sorted, lowercase
        canon_headers = "".join(
            f"{k.lower()}:{v}\n" for k, v in sorted(headers.items())
            if k.lower().startswith("x-ms-"))
        # canonicalized resource: /account/path plus sorted query params
        path = u.path or "/"
        # canonical resource = "/" + account + url-path. Against azurite-style
        # test endpoints the url path itself already starts with /account (the
        # emulator scopes urls by account), so the canonical string legitimately
        # names the account twice — once from this prefix, once inside `path`.
        # That matches what azurite canonicalizes server-side; do NOT "fix" it
        # by stripping the duplicate or signing breaks.
        resource = f"/{self.cfg.account}{path}"
        if u.query:
            params = sorted(p.split("=", 1) for p in u.query.split("&"))
            resource += "".join(
                f"\n{k}:{urllib.parse.unquote(v[0] if isinstance(v, list) else v)}"
                for k, *v in [(p[0], p[1] if len(p) > 1 else "")
                              for p in params])
        cl = str(content_length) if content_length else ""
        to_sign = "\n".join([
            method, "", "", cl, "", "", "", "", "",
            headers.get("If-None-Match", ""), "", "",
            canon_headers + resource])
        # shared-key-lite is simpler but shared key proper is what SDKs send;
        # the string-to-sign layout above is the Blob shared-key order:
        # VERB, Content-Encoding, Content-Language, Content-Length, MD5,
        # Content-Type, Date, If-Mod, If-Match, If-None-Match, If-Unmod,
        # Range, then canonicalized headers + resource
        sig = base64.b64encode(
            hmac.new(base64.b64decode(self.cfg.key), to_sign.encode(),
                     hashlib.sha256).digest()).decode()
        headers["Authorization"] = f"SharedKey {self.cfg.account}:{sig}"
        return headers

    def get(self, path, range=None, timeout=None):
        container, key = self._split(path)
        url = self._url(container, key)
        extra = {}
        if range is not None:
            extra["x-ms-range"] = f"bytes={range[0]}-{range[1] - 1}"
        headers = self._headers("GET", url, extra=extra)
        status, _h, data = _http_request(
            url, headers=headers,
            timeout=timeout if timeout is not None else self.cfg.timeout)
        if status not in (200, 206):
            _raise_http("GET", path, status)
        if range is not None and status == 200:
            return data[range[0]:range[1]]
        return data

    def get_size(self, path):
        container, key = self._split(path)
        url = self._url(container, key)
        status, h, _ = _http_request(url, method="HEAD",
                                     headers=self._headers("HEAD", url),
                                     timeout=self.cfg.timeout)
        if status != 200 or "content-length" not in h:
            _raise_http("HEAD", path, status)
        return int(h["content-length"])

    def put(self, path, data, if_none_match=False):
        container, key = self._split(path)
        url = self._url(container, key)
        extra = {"x-ms-blob-type": "BlockBlob"}
        if if_none_match:
            extra["If-None-Match"] = "*"
        headers = self._headers("PUT", url, content_length=len(data),
                                extra=extra)
        status, _h, _b = _http_request(url, method="PUT", headers=headers,
                                       body=data, timeout=self.cfg.timeout)
        if status in (409, 412):
            raise FileExistsError(f"PUT {path}: blob exists (HTTP {status})")
        if status not in (200, 201):
            raise DaftIOError(f"PUT {path}: HTTP {status}")

    def delete(self, path):
        container, key = self._split(path)
        url = self._url(container, key)
        status, _h, _b = _http_request(url, method="DELETE",
                                       headers=self._headers("DELETE", url),
                                       timeout=self.cfg.timeout)
        if status not in (200, 202, 204):
            raise DaftIOError(f"DELETE {path}: HTTP {status}")

    def ls(self, prefix):
        container, key = self._split(prefix)
        scheme = str(prefix).split("://", 1)[0]
        out: List[ObjectMeta] = []
        marker = None
        while True:
            q = ("restype=container&comp=list&prefix="
                 + urllib.parse.quote(key, safe=""))
            if marker:
                q += "&marker=" + urllib.parse.quote(marker, safe="")
            url = self._url(container, query=q)
            status, _h, data = _http_request(
                url, headers=self._headers("GET", url),
                timeout=self.cfg.timeout)
            if status == 404:
                raise NotFoundIOError(f"LIST {prefix}: HTTP 404")
            if status != 200:
                raise DaftIOError(f"LIST {prefix}: HTTP {status}")
            blobs, marker = _parse_azure_list(data)
            out.extend(ObjectMeta(f"{scheme}://{container}/{name}", size)
                       for name, size in blobs)
            if not marker:
                return out

    def glob(self, pattern):
        container, key = self._split(pattern)
        scheme = str(pattern).split("://", 1)[0]
        return _glob_via_ls(f"{scheme}://{container}", key, self.ls)


def _parse_azure_list(xml: bytes) -> Tuple[List[Tuple[str, Optional[int]]], Optional[str]]:
    import xml.etree.ElementTree as ET

    root = ET.fromstring(xml)
    blobs: List[Tuple[str, Optional[int]]] = []
    for b in root.iter("Blob"):
        name = b.find("Name")
        size = None
        props = b.find("Properties")
        if props is not None:
            cl = props.find("Content-Length")
            if cl is not None and cl.text:
                size = int(cl.text)
        if name is not None and name.text:
            blobs.append((name.text, size))
    nm = root.find("NextMarker")
    marker = nm.text if nm is not None and nm.text else None
    return blobs, marker


@dataclass
class HFConfig:
    """Reference: common/io-config HTTPConfig token + huggingface.rs."""

    endpoint_url: str = "https://huggingface.co"
    token: Optional[str] = None
    revision: str = "main"
    timeout: float = 30.0

    @staticmethod
    def from_env() -> "HFConfig":
        return HFConfig(
            endpoint_url=os.environ.get("HF_ENDPOINT",
                                        "https://huggingface.co"),
            token=os.environ.get("HF_TOKEN"),
        )


class HuggingFaceSource(ObjectSource):
    """hf:// paths resolved through the Hub's HTTP surface (reference:
    huggingface.rs, 633 LoC). Layout:
        hf://datasets/{repo_id}/{path}  (also hf://{user}/{model}/{path})
    get  -> {endpoint}/{repo}/resolve/{revision}/{path}  (302s to a CDN)
    ls   -> {endpoint}/api/{kind}/{repo_id}/tree/{revision}/{dir}?recursive=true
    """

    def __init__(self, cfg: Optional[HFConfig] = None):
        self.cfg = cfg or HFConfig.from_env()
        self._http = HttpSource(timeout=self.cfg.timeout)

    def _auth(self) -> Dict[str, str]:
        if self.cfg.token:
            return {"Authorization": f"Bearer {self.cfg.token}"}
        return {}

    def _split(self, path: str) -> Tuple[str, str, str]:
        """-> (api_kind, repo_id, inner_path)"""
        rest = str(path)[len("hf://"):]
        parts = rest.split("/")
        if parts[0] in ("datasets", "spaces"):
            kind, repo, inner = parts[0], "/".join(parts[1:3]), "/".join(parts[3:])
        else:  # models live at the url root
            kind, repo, inner = "models", "/".join(parts[0:2]), "/".join(parts[2:])
        if not repo or "/" not in repo:
            raise DaftValueError(f"hf path needs user/repo: {path}")
        return kind, repo, inner

    def _resolve_url(self, path: str) -> str:
        kind, repo, inner = self._split(path)
        prefix = "" if kind == "models" else f"{kind}/"
        return (f"{self.cfg.endpoint_url}/{prefix}{repo}/resolve/"
                f"{self.cfg.revision}/{urllib.parse.quote(inner)}")

    def get(self, path, range=None, timeout=None):
        url = self._resolve_url(path)
        headers = self._auth()
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        status, _h, data = self._http._request(url, headers=headers,
                                               timeout=timeout)
        if status not in (200, 206):
            _raise_http("GET", path, status)
        if range is not None and status == 200:
            return data[range[0]:range[1]]
        return data

    def get_size(self, path):
        url = self._resolve_url(path)
        status, h, _ = self._http._request(url, method="HEAD",
                                           headers=self._auth())
        # the Hub reports the LFS object size in x-linked-size on redirects
        size = h.get("x-linked-size") or h.get("content-length")
        if status != 200 or not size:
            _raise_http("HEAD", path, status)
        return int(size)

    def ls(self, prefix):
        kind, repo, inner = self._split(prefix)
        url = (f"{self.cfg.endpoint_url}/api/{kind}/{repo}/tree/"
               f"{self.cfg.revision}/{urllib.parse.quote(inner)}"
               f"?recursive=true")
        status, _h, data = self._http._request(url, headers=self._auth())
        if status == 404:
            raise NotFoundIOError(f"LIST {prefix}: HTTP 404")
        if status != 200:
            raise DaftIOError(f"LIST {prefix}: HTTP {status}")
        import json as _json

        base = f"hf://{kind}/{repo}" if kind != "models" else f"hf://{repo}"
        out = []
        for entry in _json.loads(data):
            if entry.get("type") == "file":
                out.append(ObjectMeta(f"{base}/{entry['path']}",
                                      entry.get("size")))
        return out

    def glob(self, pattern):
        kind, repo, inner = self._split(pattern)
        base = f"hf://{kind}/{repo}" if kind != "models" else f"hf://{repo}"
        # the tree API wants a directory, not a partial-filename prefix:
        # trim the listing path back to its parent dir (recursive listing
        # covers everything below it)
        return _glob_via_ls(base, inner,
                            lambda p: self.ls(p.rsplit("/", 1)[0]))


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

@dataclass
class IOClient:
    """Scheme-routing facade with a process-wide connection budget and retry
    (reference: IOClient, daft-io/src/lib.rs:183)."""

    s3_config: Optional[S3Config] = None
    gcs_config: Optional[GCSConfig] = None
    azure_config: Optional[AzureConfig] = None
    hf_config: Optional[HFConfig] = None
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    max_connections: int = 64

    def __post_init__(self):
        self._sem = threading.BoundedSemaphore(max(1, self.max_connections))
        self._sources: Dict[str, ObjectSource] = {}
        self._lock = threading.Lock()

    def source_for(self, path: str) -> ObjectSource:
        scheme = path.split("://", 1)[0] if "://" in path else "file"
        if scheme in ("http", "https"):
            scheme = "http"
        if scheme in ("abfs", "abfss"):
            scheme = "az"
        with self._lock:
            src = self._sources.get(scheme)
            if src is None:
                if scheme == "s3":
                    src = S3Source(self.s3_config)
                elif scheme == "gs":
                    src = GCSSource(self.gcs_config)
                elif scheme == "az":
                    src = AzureSource(self.azure_config)
                elif scheme == "hf":
                    src = HuggingFaceSource(self.hf_config)
                elif scheme == "http":
                    src = HttpSource()
                elif scheme == "file":
                    src = LocalSource()
                else:
                    raise DaftValueError(f"unsupported scheme {scheme}:// in {path}")
                self._sources[scheme] = src
        return src

    def get(self, path: str, range: Optional[Tuple[int, int]] = None,
            timeout: Optional[float] = None) -> bytes:
        from .. import faults

        src = self.source_for(path)

        def attempt() -> bytes:
            # fault site inside the retry loop: each ATTEMPT checks, so an
            # armed first_n plan exercises retry-then-heal deterministically
            faults.check("io.get")
            return src.get(path, range, timeout)

        with self._sem:
            data = self.retry.run(attempt)
        IO_STATS.bump(bytes_read=len(data))
        return data

    def get_size(self, path: str) -> int:
        src = self.source_for(path)
        with self._sem:
            return self.retry.run(lambda: src.get_size(path))

    def put(self, path: str, data: bytes, if_none_match: bool = False) -> None:
        """Write an object through the same budget/retry funnel as reads.
        A retried conditional put can observe its own first (timed-out but
        landed) attempt as FileExistsError — the standard conditional-write
        caveat; callers that need exactly-once embed a unique key instead."""
        src = self.source_for(path)
        with self._sem:
            self.retry.run(lambda: src.put(path, data, if_none_match))
        IO_STATS.bump(bytes_written=len(data))

    def delete(self, path: str) -> None:
        src = self.source_for(path)
        with self._sem:
            self.retry.run(lambda: src.delete(path))

    def exists(self, path: str) -> bool:
        """True/False only for genuine presence/absence. Auth failures and
        exhausted-retry 5xx propagate — an outage must never read as
        'object absent' (same discipline as Storage.list_names)."""
        try:
            self.get_size(path)
            return True
        except (NotFoundIOError, FileNotFoundError, NotADirectoryError):
            return False

    def ls(self, prefix: str) -> List[ObjectMeta]:
        src = self.source_for(prefix)
        with self._sem:
            return self.retry.run(lambda: src.ls(prefix))

    def glob(self, pattern: str) -> List[ObjectMeta]:
        src = self.source_for(pattern)
        with self._sem:
            return self.retry.run(lambda: src.glob(pattern))

    def open(self, path: str, size: Optional[int] = None) -> "ObjectFile":
        return ObjectFile(self, path, size)


class ObjectFile(io.RawIOBase):
    """Seekable read-only file over get_range — hands remote parquet to
    pyarrow without downloading whole objects (footer + selected row groups
    only, like the reference's range-read parquet path, read.rs:615).

    A small readahead coalesces the footer's many tiny reads."""

    READAHEAD = 256 * 1024

    def __init__(self, client: IOClient, path: str, size: Optional[int] = None):
        super().__init__()
        self.client = client
        self.path = path
        self._size = size if size is not None else client.get_size(path)
        # small objects don't benefit from deep readahead — cap it so range
        # reads stay well under a full download
        self._readahead = min(self.READAHEAD, max(self._size // 16, 8 * 1024))
        self._pos = 0
        self._buf = b""
        self._buf_start = 0

    def readable(self):
        return True

    def seekable(self):
        return True

    def size(self):
        return self._size

    def seek(self, offset, whence=0):
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self):
        return self._pos

    def read(self, n=-1):
        if n is None or n < 0:
            n = self._size - self._pos
        n = max(0, min(n, self._size - self._pos))
        if n == 0:
            return b""
        start, end = self._pos, self._pos + n
        bs, be = self._buf_start, self._buf_start + len(self._buf)
        if not (bs <= start and end <= be):
            fetch_end = min(self._size, max(end, start + self._readahead))
            self._buf = self.client.get(self.path, (start, fetch_end))
            self._buf_start = start
            bs, be = start, start + len(self._buf)
        out = self._buf[start - bs:end - bs]
        self._pos = end
        return out


_DEFAULT_CLIENT: Optional[IOClient] = None
_CLIENT_LOCK = threading.Lock()


def default_io_client() -> IOClient:
    """Process-wide client; per-store settings re-read from the environment
    when they change (tests point them at mock servers)."""
    global _DEFAULT_CLIENT
    with _CLIENT_LOCK:
        # compare the WHOLE config set: rotated credentials or a region
        # change must rebuild the client, not just an endpoint change
        s3 = S3Config.from_env()
        gcs = GCSConfig.from_env()
        az = AzureConfig.from_env()
        hf = HFConfig.from_env()
        c = _DEFAULT_CLIENT
        if (c is None or c.s3_config != s3 or c.gcs_config != gcs
                or c.azure_config != az or c.hf_config != hf):
            _DEFAULT_CLIENT = IOClient(s3_config=s3, gcs_config=gcs,
                                       azure_config=az, hf_config=hf)
        return _DEFAULT_CLIENT


def is_remote_path(path: str) -> bool:
    return str(path).startswith(
        ("s3://", "http://", "https://", "gs://", "az://", "abfs://",
         "abfss://", "hf://"))


class Storage:
    """Unified file ops over local paths AND object-store urls, so the
    tabular writers and the Delta/Iceberg commit protocols target file://
    and s3:// identically (reference: daft's writers receive an fsspec
    filesystem, daft/table/table_io.py:401+; here the IOClient plays that
    role). put_if_absent is the atomic-commit primitive: O_EXCL locally,
    `If-None-Match: *` on object stores."""

    def __init__(self, client: Optional[IOClient] = None):
        self._client = client

    @property
    def client(self) -> IOClient:
        return self._client or default_io_client()

    @staticmethod
    def is_remote(path: str) -> bool:
        return is_remote_path(path)

    def join(self, base: str, *parts: str) -> str:
        if self.is_remote(base):
            return "/".join([str(base).rstrip("/")]
                            + [p.strip("/") for p in parts])
        return os.path.join(str(base), *parts)

    def makedirs(self, path: str) -> None:
        if not self.is_remote(path):
            os.makedirs(self._local(path), exist_ok=True)

    @staticmethod
    def _local(path: str) -> str:
        p = str(path)
        return p[len("file://"):] if p.startswith("file://") else p

    def put(self, path: str, data: bytes) -> None:
        if self.is_remote(path):
            self.client.put(path, data)
        else:
            LocalSource().put(path, data)

    def put_if_absent(self, path: str, data: bytes) -> None:
        if self.is_remote(path):
            self.client.put(path, data, if_none_match=True)
        else:
            LocalSource().put(path, data, if_none_match=True)

    def get(self, path: str) -> bytes:
        if self.is_remote(path):
            return self.client.get(path)
        with open(self._local(path), "rb") as f:
            return f.read()

    def exists(self, path: str) -> bool:
        if self.is_remote(path):
            return self.client.exists(path)
        return os.path.exists(self._local(path))

    def size(self, path: str) -> int:
        if self.is_remote(path):
            return self.client.get_size(path)
        return os.path.getsize(self._local(path))

    def list_names(self, dir_path: str) -> List[str]:
        """Immediate child names under a directory-like path (os.listdir
        semantics; remote listings are recursive, so grandchildren are
        collapsed out)."""
        if not self.is_remote(dir_path):
            p = self._local(dir_path)
            return os.listdir(p) if os.path.isdir(p) else []
        prefix = str(dir_path).rstrip("/") + "/"
        names = set()
        try:
            metas = self.client.ls(prefix)
        except NotFoundIOError:
            return []  # the container/prefix genuinely does not exist
        for m in metas:
            rest = m.path[len(prefix):]
            if rest:
                names.add(rest.split("/", 1)[0])
        return sorted(names)

    def open_input(self, path: str, size: Optional[int] = None):
        """Seekable binary reader: ObjectFile for remote (range reads), a
        plain file handle locally — both satisfy pyarrow's file protocol."""
        if self.is_remote(path):
            return self.client.open(path, size)
        return open(self._local(path), "rb")


STORAGE = Storage()
