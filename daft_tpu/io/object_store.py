"""Object-store IO: sources, client, range-reads, retry, glob.

TPU-native counterpart of the reference's daft-io crate: the `ObjectSource`
trait (/root/reference/src/daft-io/src/object_io.rs), the S3 client with
retry modes and per-connection caps (s3_like.rs:452-468), and store-aware
glob (object_store_glob.rs). Pure stdlib (http.client + hashlib/hmac SigV4)
— the zero-egress build can't take on SDK dependencies, and the hot compute
path never touches this layer; scans and url.download do.

Scheme routing: `s3://bucket/key` (endpoint override via AWS_ENDPOINT_URL for
S3-compatible stores and tests), `http(s)://`, `file://`/bare paths.
Every read funnels through IOClient: a process-wide connection budget
(semaphore, like the reference's max_connections_per_io_thread), a retry
policy with exponential backoff + jitter on transient failures (5xx,
timeouts, connection resets), and IO_STATS counters.
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import io
import os
import random
import threading
import time
import urllib.parse
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from .scan import IO_STATS


@dataclass
class ObjectMeta:
    path: str
    size: Optional[int] = None


class TransientIOError(IOError):
    """Retryable failure (5xx, timeout, connection reset)."""


@dataclass
class RetryPolicy:
    """Mirrors the reference's S3 retry config (attempts + exponential
    backoff; jitter avoids thundering herds on shared endpoints)."""

    attempts: int = 4
    backoff_s: float = 0.1
    max_backoff_s: float = 4.0

    def run(self, fn):
        last = None
        for attempt in range(max(1, self.attempts)):
            try:
                return fn()
            except TransientIOError as e:
                last = e
                IO_STATS.bump(retries=1)
                if attempt + 1 >= self.attempts:
                    break
                delay = min(self.backoff_s * (2 ** attempt), self.max_backoff_s)
                time.sleep(delay * (0.5 + random.random() / 2))
        raise last


class ObjectSource:
    """get/get_range/ls/glob over one scheme (reference: ObjectSource trait)."""

    def get(self, path: str, range: Optional[Tuple[int, int]] = None,
            timeout: Optional[float] = None) -> bytes:
        raise NotImplementedError

    def get_size(self, path: str) -> int:
        raise NotImplementedError

    def ls(self, prefix: str) -> List[ObjectMeta]:
        raise NotImplementedError

    def glob(self, pattern: str) -> List[ObjectMeta]:
        raise NotImplementedError


class LocalSource(ObjectSource):
    def _p(self, path: str) -> str:
        return path[len("file://"):] if path.startswith("file://") else path

    def get(self, path, range=None, timeout=None):
        with open(self._p(path), "rb") as f:
            if range is None:
                return f.read()
            f.seek(range[0])
            return f.read(range[1] - range[0])

    def get_size(self, path):
        return os.path.getsize(self._p(path))

    def ls(self, prefix):
        p = self._p(prefix)
        if os.path.isfile(p):
            return [ObjectMeta(p, os.path.getsize(p))]
        out = []
        for root, _dirs, files in os.walk(p):
            for f in sorted(files):
                fp = os.path.join(root, f)
                out.append(ObjectMeta(fp, os.path.getsize(fp)))
        return out

    def glob(self, pattern):
        import glob as _glob

        return [ObjectMeta(p, os.path.getsize(p))
                for p in sorted(_glob.glob(self._p(pattern), recursive=True))
                if os.path.isfile(p)]


def _http_request(url: str, method: str = "GET",
                  headers: Optional[Dict[str, str]] = None,
                  body: Optional[bytes] = None,
                  timeout: float = 30.0) -> Tuple[int, Dict[str, str], bytes]:
    """One HTTP exchange; maps transport failures and 5xx/429 to
    TransientIOError so the retry policy can act."""
    u = urllib.parse.urlsplit(url)
    conn_cls = http.client.HTTPSConnection if u.scheme == "https" else http.client.HTTPConnection
    conn = conn_cls(u.hostname, u.port, timeout=timeout)
    target = (u.path or "/") + (f"?{u.query}" if u.query else "")
    try:
        conn.request(method, target, body=body, headers=headers or {})
        resp = conn.getresponse()
        data = resp.read()
        status = resp.status
        rheaders = {k.lower(): v for k, v in resp.getheaders()}
    except (OSError, http.client.HTTPException) as e:
        raise TransientIOError(f"{method} {url}: {e}") from e
    finally:
        conn.close()
    if status >= 500 or status == 429:
        raise TransientIOError(f"{method} {url}: HTTP {status}")
    return status, rheaders, data


class HttpSource(ObjectSource):
    """http(s) objects with Range reads and redirect following
    (reference: http.rs)."""

    MAX_REDIRECTS = 5

    def __init__(self, timeout: float = 30.0):
        self.timeout = timeout

    def _request(self, url, method="GET", headers=None, timeout=None):
        """Follow up to MAX_REDIRECTS 3xx hops (presigned urls, CDNs, and
        http->https upgrades all redirect; urllib used to do this for us)."""
        t = timeout if timeout is not None else self.timeout
        for _ in range(self.MAX_REDIRECTS + 1):
            status, h, data = _http_request(url, method=method,
                                            headers=headers, timeout=t)
            if status in (301, 302, 303, 307, 308) and "location" in h:
                url = urllib.parse.urljoin(url, h["location"])
                continue
            return status, h, data
        raise IOError(f"{method} {url}: too many redirects")

    def get(self, path, range=None, timeout=None):
        headers = {}
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        status, _h, data = self._request(path, headers=headers, timeout=timeout)
        if status not in (200, 206):
            raise IOError(f"GET {path}: HTTP {status}")
        if range is not None and status == 200:
            return data[range[0]:range[1]]  # server ignored Range
        return data

    def get_size(self, path):
        status, h, _ = self._request(path, method="HEAD")
        if status != 200 or "content-length" not in h:
            raise IOError(f"HEAD {path}: HTTP {status}")
        return int(h["content-length"])

    def ls(self, prefix):
        raise IOError("http source cannot list; pass explicit urls")

    def glob(self, pattern):
        if any(ch in pattern for ch in "*?["):
            raise IOError("http source cannot glob; pass explicit urls")
        return [ObjectMeta(pattern)]


@dataclass
class S3Config:
    """Reference: common/io-config S3Config. Pulled from the environment by
    default; endpoint_url points S3-compatible stores (and tests) anywhere."""

    endpoint_url: Optional[str] = None
    region: str = "us-east-1"
    key_id: Optional[str] = None
    secret_key: Optional[str] = None
    session_token: Optional[str] = None
    anonymous: bool = False
    timeout: float = 30.0

    @staticmethod
    def from_env() -> "S3Config":
        return S3Config(
            endpoint_url=os.environ.get("AWS_ENDPOINT_URL"),
            region=os.environ.get("AWS_REGION", "us-east-1"),
            key_id=os.environ.get("AWS_ACCESS_KEY_ID"),
            secret_key=os.environ.get("AWS_SECRET_ACCESS_KEY"),
            session_token=os.environ.get("AWS_SESSION_TOKEN"),
        )


def _sigv4_headers(cfg: S3Config, method: str, url: str,
                   payload_hash: str = "UNSIGNED-PAYLOAD") -> Dict[str, str]:
    """AWS Signature V4 (pure stdlib). Skipped for anonymous access."""
    u = urllib.parse.urlsplit(url)
    now = time.gmtime()
    amz_date = time.strftime("%Y%m%dT%H%M%SZ", now)
    datestamp = time.strftime("%Y%m%d", now)
    host = u.hostname + (f":{u.port}" if u.port else "")
    headers = {"host": host, "x-amz-date": amz_date,
               "x-amz-content-sha256": payload_hash}
    if cfg.session_token:
        headers["x-amz-security-token"] = cfg.session_token
    signed = ";".join(sorted(headers))
    canonical_q = "&".join(sorted(u.query.split("&"))) if u.query else ""
    # u.path is already percent-encoded by the caller (_url quotes the key);
    # re-quoting would double-encode and break the signature for keys with
    # spaces/'+'/'=' (SignatureDoesNotMatch)
    canonical = "\n".join([
        method, u.path or "/", canonical_q,
        "".join(f"{k}:{headers[k]}\n" for k in sorted(headers)), signed,
        payload_hash])
    scope = f"{datestamp}/{cfg.region}/s3/aws4_request"
    to_sign = "\n".join(["AWS4-HMAC-SHA256", amz_date, scope,
                         hashlib.sha256(canonical.encode()).hexdigest()])

    def _hmac(key, msg):
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + cfg.secret_key).encode(), datestamp)
    k = _hmac(_hmac(_hmac(k, cfg.region), "s3"), "aws4_request")
    sig = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()
    out = dict(headers)
    out["Authorization"] = (
        f"AWS4-HMAC-SHA256 Credential={cfg.key_id}/{scope}, "
        f"SignedHeaders={signed}, Signature={sig}")
    out.pop("host")  # http.client sets it
    return out


class S3Source(ObjectSource):
    """Minimal S3 REST dialect: GET object (+Range), HEAD, ListObjectsV2 with
    pagination (reference: s3_like.rs). Path-style addressing against
    endpoint_url; virtual-host style against AWS proper."""

    def __init__(self, cfg: Optional[S3Config] = None):
        self.cfg = cfg or S3Config.from_env()

    def _url(self, bucket: str, key: str = "", query: str = "") -> str:
        if self.cfg.endpoint_url:
            base = self.cfg.endpoint_url.rstrip("/")
            url = f"{base}/{bucket}"
        else:
            url = f"https://{bucket}.s3.{self.cfg.region}.amazonaws.com"
        if key:
            url += "/" + urllib.parse.quote(key)
        if query:
            url += "?" + query
        return url

    def _headers(self, method: str, url: str) -> Dict[str, str]:
        if self.cfg.anonymous or not (self.cfg.key_id and self.cfg.secret_key):
            return {}
        return _sigv4_headers(self.cfg, method, url)

    @staticmethod
    def _split(path: str) -> Tuple[str, str]:
        rest = path[len("s3://"):]
        bucket, _, key = rest.partition("/")
        return bucket, key

    def get(self, path, range=None, timeout=None):
        bucket, key = self._split(path)
        url = self._url(bucket, key)
        headers = self._headers("GET", url)
        if range is not None:
            headers["Range"] = f"bytes={range[0]}-{range[1] - 1}"
        status, _h, data = _http_request(
            url, headers=headers,
            timeout=timeout if timeout is not None else self.cfg.timeout)
        if status not in (200, 206):
            raise IOError(f"GET {path}: HTTP {status}")
        if range is not None and status == 200:
            return data[range[0]:range[1]]  # endpoint ignored Range
        return data

    def get_size(self, path):
        bucket, key = self._split(path)
        url = self._url(bucket, key)
        status, h, _ = _http_request(url, method="HEAD",
                                     headers=self._headers("HEAD", url),
                                     timeout=self.cfg.timeout)
        if status != 200 or "content-length" not in h:
            raise IOError(f"HEAD {path}: HTTP {status}")
        return int(h["content-length"])

    def ls(self, prefix):
        bucket, key = self._split(prefix)
        out: List[ObjectMeta] = []
        token = None
        while True:
            q = "list-type=2&prefix=" + urllib.parse.quote(key, safe="")
            if token:
                q += "&continuation-token=" + urllib.parse.quote(token, safe="")
            url = self._url(bucket, query=q)
            status, _h, data = _http_request(url, headers=self._headers("GET", url),
                                             timeout=self.cfg.timeout)
            if status != 200:
                raise IOError(f"LIST {prefix}: HTTP {status}")
            keys, token = _parse_list_objects(data)
            out.extend(ObjectMeta(f"s3://{bucket}/{k}", sz) for k, sz in keys)
            if not token:
                return out

    def glob(self, pattern):
        bucket, key = self._split(pattern)
        # list from the longest wildcard-free prefix, then match with
        # path-aware glob semantics: '*'/'?' stay within one path segment,
        # '**' crosses segments — matching local glob and the reference's
        # object_store_glob.rs (fnmatch would let '*' swallow '/')
        cut = len(key)
        for i, ch in enumerate(key):
            if ch in "*?[":
                cut = i
                break
        prefix = key[:cut]
        listed = self.ls(f"s3://{bucket}/{prefix}")
        if cut == len(key):
            # no wildcard: the exact object, else a directory-style listing
            exact = [m for m in listed if m.path == f"s3://{bucket}/{key}"]
            if exact:
                return exact
            dirp = f"s3://{bucket}/{key.rstrip('/')}/"
            return [m for m in listed if m.path.startswith(dirp)]
        rx = _glob_to_regex(key)
        return [m for m in listed
                if rx.fullmatch(m.path[len(f"s3://{bucket}/"):])]


def _glob_to_regex(pattern: str):
    """Translate a path glob to a regex where '*'/'?' do not cross '/' and
    '**' does (local-filesystem glob semantics)."""
    import re

    out = []
    i = 0
    while i < len(pattern):
        c = pattern[i]
        if c == "*":
            if pattern[i:i + 2] == "**":
                out.append(".*")
                i += 2
                if i < len(pattern) and pattern[i] == "/":
                    i += 1  # '**/' also matches zero directories
                continue
            out.append("[^/]*")
        elif c == "?":
            out.append("[^/]")
        elif c == "[":
            j = pattern.find("]", i + 1)
            if j == -1:
                out.append(re.escape(c))
            else:
                out.append(pattern[i:j + 1])
                i = j
        else:
            out.append(re.escape(c))
        i += 1
    return re.compile("".join(out))


def _parse_list_objects(xml: bytes) -> Tuple[List[Tuple[str, Optional[int]]], Optional[str]]:
    import xml.etree.ElementTree as ET

    root = ET.fromstring(xml)
    ns = ""
    if root.tag.startswith("{"):
        ns = root.tag[:root.tag.index("}") + 1]
    keys = []
    for c in root.iter(f"{ns}Contents"):
        k = c.find(f"{ns}Key")
        s = c.find(f"{ns}Size")
        if k is not None:
            keys.append((k.text, int(s.text) if s is not None and s.text else None))
    trunc = root.find(f"{ns}IsTruncated")
    token = None
    if trunc is not None and (trunc.text or "").lower() == "true":
        t = root.find(f"{ns}NextContinuationToken")
        token = t.text if t is not None else None
    return keys, token


# ---------------------------------------------------------------------------
# client
# ---------------------------------------------------------------------------

@dataclass
class IOClient:
    """Scheme-routing facade with a process-wide connection budget and retry
    (reference: IOClient, daft-io/src/lib.rs:183)."""

    s3_config: Optional[S3Config] = None
    retry: RetryPolicy = field(default_factory=RetryPolicy)
    max_connections: int = 64

    def __post_init__(self):
        self._sem = threading.BoundedSemaphore(max(1, self.max_connections))
        self._sources: Dict[str, ObjectSource] = {}
        self._lock = threading.Lock()

    def source_for(self, path: str) -> ObjectSource:
        scheme = path.split("://", 1)[0] if "://" in path else "file"
        if scheme in ("http", "https"):
            scheme = "http"
        with self._lock:
            src = self._sources.get(scheme)
            if src is None:
                if scheme == "s3":
                    src = S3Source(self.s3_config)
                elif scheme == "http":
                    src = HttpSource()
                elif scheme == "file":
                    src = LocalSource()
                else:
                    raise ValueError(f"unsupported scheme {scheme}:// in {path}")
                self._sources[scheme] = src
        return src

    def get(self, path: str, range: Optional[Tuple[int, int]] = None,
            timeout: Optional[float] = None) -> bytes:
        src = self.source_for(path)
        with self._sem:
            data = self.retry.run(lambda: src.get(path, range, timeout))
        IO_STATS.bump(bytes_read=len(data))
        return data

    def get_size(self, path: str) -> int:
        src = self.source_for(path)
        with self._sem:
            return self.retry.run(lambda: src.get_size(path))

    def ls(self, prefix: str) -> List[ObjectMeta]:
        src = self.source_for(prefix)
        with self._sem:
            return self.retry.run(lambda: src.ls(prefix))

    def glob(self, pattern: str) -> List[ObjectMeta]:
        src = self.source_for(pattern)
        with self._sem:
            return self.retry.run(lambda: src.glob(pattern))

    def open(self, path: str, size: Optional[int] = None) -> "ObjectFile":
        return ObjectFile(self, path, size)


class ObjectFile(io.RawIOBase):
    """Seekable read-only file over get_range — hands remote parquet to
    pyarrow without downloading whole objects (footer + selected row groups
    only, like the reference's range-read parquet path, read.rs:615).

    A small readahead coalesces the footer's many tiny reads."""

    READAHEAD = 256 * 1024

    def __init__(self, client: IOClient, path: str, size: Optional[int] = None):
        super().__init__()
        self.client = client
        self.path = path
        self._size = size if size is not None else client.get_size(path)
        # small objects don't benefit from deep readahead — cap it so range
        # reads stay well under a full download
        self._readahead = min(self.READAHEAD, max(self._size // 16, 8 * 1024))
        self._pos = 0
        self._buf = b""
        self._buf_start = 0

    def readable(self):
        return True

    def seekable(self):
        return True

    def size(self):
        return self._size

    def seek(self, offset, whence=0):
        if whence == 0:
            self._pos = offset
        elif whence == 1:
            self._pos += offset
        else:
            self._pos = self._size + offset
        return self._pos

    def tell(self):
        return self._pos

    def read(self, n=-1):
        if n is None or n < 0:
            n = self._size - self._pos
        n = max(0, min(n, self._size - self._pos))
        if n == 0:
            return b""
        start, end = self._pos, self._pos + n
        bs, be = self._buf_start, self._buf_start + len(self._buf)
        if not (bs <= start and end <= be):
            fetch_end = min(self._size, max(end, start + self._readahead))
            self._buf = self.client.get(self.path, (start, fetch_end))
            self._buf_start = start
            bs, be = start, start + len(self._buf)
        out = self._buf[start - bs:end - bs]
        self._pos = end
        return out


_DEFAULT_CLIENT: Optional[IOClient] = None
_CLIENT_LOCK = threading.Lock()


def default_io_client() -> IOClient:
    """Process-wide client; S3 settings re-read from the environment when the
    endpoint changes (tests point it at mock servers)."""
    global _DEFAULT_CLIENT
    with _CLIENT_LOCK:
        env_cfg = S3Config.from_env()
        # compare the WHOLE config: rotated credentials or a region change
        # must rebuild the client, not just an endpoint change
        if _DEFAULT_CLIENT is None or _DEFAULT_CLIENT.s3_config != env_cfg:
            _DEFAULT_CLIENT = IOClient(s3_config=env_cfg)
        return _DEFAULT_CLIENT


def is_remote_path(path: str) -> bool:
    return str(path).startswith(("s3://", "http://", "https://"))
