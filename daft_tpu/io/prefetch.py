"""Pipelined scan IO: bounded, consumption-driven readahead of scan tasks.

BENCH_r05 showed the out-of-core path fully serializing scan decode with
compute (TPC-H Q1 SF10 from parquet: 1.3M rows/s vs 17.5M in-memory). This
module overlaps them: when scan partition i is materialized, the reads for
partitions i+1..i+depth are issued on the shared executor pool, so the
decode of the next morsel rides under the compute of the current one (the
double-buffering/readahead discipline of HPTMT arxiv 2107.12807 and the
input-pipeline prefetch of arxiv 2604.21275).

Design constraints, in order:

- **Byte-identical results.** A prefetched read goes through exactly the
  same ``read_chunks``/``read`` path a synchronous read would; the wrapper
  only moves WHERE it runs. Order is preserved by the scan op, which emits
  partitions in task order regardless of fetch completion order.
- **Consumption-driven.** Fetches for i+1.. are triggered by the read of
  partition i, never by plan construction or emission — a metadata-only
  query, a narrowed (head/select) partition, or a pruned stream starts no
  background IO at all, so pushdown IO-reduction guarantees survive.
- **Budget-charged.** Each in-flight fetch charges its size estimate to the
  process MemoryLedger; submission stops (prefetch_throttled) while the
  charge would cross memory_budget_bytes, so readahead can never blow the
  spill budget it exists to serve.
- **Deadline/cancel-aware.** No new fetch is submitted after the query's
  deadline passed or its stats handle was cancelled.
- **Deadlock-free on the shared pool.** A consumer never blocks on a fetch
  that is still QUEUED: it cancels the future and reads synchronously
  (prefetch_misses). Only running fetches — which occupy a worker and wait
  on nothing — are awaited, so pool starvation cannot form a cycle.
- **Errors propagate to the consumer.** A failed background fetch re-raises
  from the partition's read on the execution thread — never lost in a dead
  worker. The ``prefetch.fetch`` fault site (DTL004-registered) makes that
  path deterministically testable.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Any, List, Optional

from ..obs.log import current_query_id, get_logger, query_context

logger = get_logger("prefetch")

_IDLE, _SUBMITTED, _TAKEN, _ABANDONED = "idle", "submitted", "taken", "abandoned"


class _Slot:
    """Per-task prefetch state, guarded by the owning queue's lock."""

    __slots__ = ("task", "est_bytes", "state", "future", "charged")

    def __init__(self, task, est_bytes: int):
        self.task = task
        self.est_bytes = est_bytes
        self.state = _IDLE
        self.future = None
        self.charged = False


class ScanPrefetcher:
    """Bounded readahead queue over one scan's locally-readable task list."""

    def __init__(self, tasks, ctx, depth: int):
        from ..spill import MEMORY_LEDGER

        self._lock = threading.Lock()
        self._slots: List[_Slot] = [
            _Slot(t, t.size_bytes() or 0) for t in tasks]
        self._ctx = ctx
        self._stats = ctx.stats
        self._deadline = getattr(ctx, "deadline", None)
        # the query's budget share and ledger (child of the process root
        # under the serving runtime): readahead is bounded per QUERY, so
        # one query's prefetch can never eat a neighbor's headroom
        self._budget = getattr(ctx, "memory_budget",
                               ctx.cfg.memory_budget_bytes)
        self._depth = max(0, int(depth))
        self._ledger = getattr(ctx, "ledger", MEMORY_LEDGER)
        self._ninflight = 0  # submitted fetches not yet consumed/settled
        self._closed = False

    def wrap(self, idx: int) -> "PrefetchedScanTask":
        return PrefetchedScanTask(self, idx)

    # ------------------------------------------------------------- submission
    def _may_submit(self) -> bool:
        if self._closed or self._stats.is_cancelled():
            return False
        if self._deadline is not None and time.monotonic() > self._deadline:
            return False
        return True

    def ensure_ahead(self, from_idx: int) -> None:
        """Submit background fetches for tasks [from_idx, from_idx+depth)
        that are still idle. Called by a partition's read, so readahead only
        follows actual consumption."""
        if self._depth <= 0 or not self._may_submit():
            return
        with self._lock:
            hi = min(from_idx + self._depth, len(self._slots))
            for j in range(max(from_idx, 0), hi):
                s = self._slots[j]
                if s.state != _IDLE:
                    continue
                # one in-flight fetch is always allowed: it is the same
                # "one working partition" of slack the spill budget already
                # grants the consumer's own synchronous read — depth-1
                # double buffering survives even a budget pinned at its
                # floor. Beyond that, readahead must fit the headroom.
                if (self._ninflight > 0 and self._budget is not None
                        and self._ledger.current + self._ledger.prefetch_inflight
                        + s.est_bytes > self._budget):
                    self._stats.bump("prefetch_throttled")
                    if self._stats.profiler.armed:
                        self._stats.profiler.event("throttle",
                                                   what="scan_prefetch",
                                                   bytes=s.est_bytes)
                    return  # budget headroom gone: stop, retry on next read
                prof = self._stats.profiler
                token = prof.capture() if prof.armed else None
                qid = current_query_id()
                try:
                    fut = self._ctx.pool().submit(self._fetch, j, token, qid)
                except RuntimeError:
                    # pool already shut down (query finished; a cached
                    # partition is being read late): degrade to sync reads
                    logger.debug("prefetch_degraded_sync", task=j)
                    self._closed = True
                    return
                s.state = _SUBMITTED
                s.future = fut
                s.charged = True
                self._ninflight += 1
                # daftlint: ledger-escape settled-by=_release_locked
                self._ledger.prefetch_started(s.est_bytes)
                self._stats.bump("prefetch_submitted")

    def _fetch(self, idx: int, span_token=None, qid=None) -> List[Any]:
        """Background fetch body (runs on a pool worker). ``span_token`` is
        the submitting thread's captured span and ``qid`` its query-log
        context, so the fetch interval — and any log line it emits — is
        attributed to the scan read that triggered the readahead."""
        from .. import faults

        prof = self._stats.profiler
        sp = None
        qctx = query_context(qid)
        qctx.__enter__()
        if span_token is not None and prof.armed:
            act = prof.activate(span_token)
            act.__enter__()
            sp = prof.begin("prefetch.fetch", part=idx, kind="bg")
        try:
            faults.check("prefetch.fetch", self._stats)
            t0 = time.perf_counter_ns()
            chunks = _read_task_chunks(self._slots[idx].task)
            self._stats.bump("prefetch_read_ns", time.perf_counter_ns() - t0)
            return chunks
        finally:
            if sp is not None:
                prof.end(sp)
                act.__exit__(None, None, None)
            qctx.__exit__(None, None, None)

    # ------------------------------------------------------------ consumption
    def _release_locked(self, s: _Slot) -> None:
        # runs under self._lock (every caller holds it); the lock-discipline
        # rule is lexical and cannot see through the helper
        if s.charged:
            s.charged = False
            self._ninflight -= 1  # daftlint: disable=DTL002
            self._ledger.prefetch_done(s.est_bytes)

    def fetch_now(self, idx: int) -> List[Any]:
        """Materialize task ``idx`` (from its prefetch future when one is in
        flight, synchronously otherwise) and trigger readahead past it.

        On a POOL WORKER (parallel map / pooled shuffle fanout) the
        prefetcher stands down: the dispatch window already overlaps
        worker reads, so driving readahead from here would only queue a
        second copy of the same work and turn this worker into a handoff
        waiting on another. Worker reads also stay out of io_wait_ns —
        that counter means consumer-thread blocked time."""
        from ..scheduler import on_pool_worker

        worker = on_pool_worker()
        if not worker:
            self.ensure_ahead(idx + 1)
        with self._lock:
            s = self._slots[idx]
            fut = s.future
            s.future = None
            s.state = _TAKEN
        if fut is None:
            t0 = time.perf_counter_ns()
            try:
                return _read_task_chunks(s.task)
            finally:
                if not worker:
                    self._stats.bump("prefetch_misses")
                    self._stats.io_wait(time.perf_counter_ns() - t0)
        try:
            if fut.cancelled():
                # cancelled from outside (query teardown closed the pool
                # client): not an error — read synchronously like a miss
                self._stats.bump("prefetch_misses")
                t0 = time.perf_counter_ns()
                try:
                    return _read_task_chunks(s.task)
                finally:
                    if not worker:
                        self._stats.io_wait(time.perf_counter_ns() - t0)
            if fut.done():
                self._stats.bump("prefetch_hits")
                return fut.result()
            if fut.cancel():
                # still queued behind other pool work: never wait on a fetch
                # that hasn't started (pool-starvation deadlock) — read here
                self._stats.bump("prefetch_misses")
                t0 = time.perf_counter_ns()
                try:
                    return _read_task_chunks(s.task)
                finally:
                    if not worker:
                        self._stats.io_wait(time.perf_counter_ns() - t0)
            else:
                # running on a worker right now: it will complete — wait
                t0 = time.perf_counter_ns()
                try:
                    return fut.result()
                finally:
                    self._stats.bump("prefetch_hits")
                    if not worker:
                        self._stats.io_wait(time.perf_counter_ns() - t0)
        finally:
            with self._lock:
                self._release_locked(s)

    def abandon(self, idx: int) -> None:
        """The wrapper for ``idx`` was narrowed or died unconsumed: stop its
        fetch if possible and return its ledger charge."""
        with self._lock:
            s = self._slots[idx]
            if s.state == _TAKEN or s.state == _ABANDONED:
                return
            s.state = _ABANDONED
            fut, s.future = s.future, None
            if fut is None or fut.cancel():
                self._release_locked(s)
                return

        def _settle(f):
            f.exception()  # retrieve, so abandoned failures don't warn
            with self._lock:
                self._release_locked(s)

        fut.add_done_callback(_settle)


def _read_task_chunks(task) -> List[Any]:
    """One scan task -> its reader-chunk Tables, via the identical path a
    direct materialization takes (chunk structure preserved for the
    shuffle map side; plain tasks read as a single chunk)."""
    read_chunks = getattr(task, "read_chunks", None)
    if read_chunks is not None:
        return list(read_chunks())
    return [task.read()]


class PrefetchedScanTask:
    """A scan task whose read may be served by a completed background fetch.

    Everything except the read/readahead surface delegates to the wrapped
    task, so metadata (num_rows/size_bytes/stats/schema) and planning never
    change. Narrowing (``with_pushdowns``) returns the UNDERLYING task
    narrowed — a narrowed read is a different read and must not consume the
    full-task fetch."""

    def __init__(self, queue: ScanPrefetcher, idx: int):
        self._queue = queue
        self._idx = idx
        self._task = queue._slots[idx].task
        # a wrapper that dies unread (limit early-stop, abandoned stream)
        # returns its ledger charge and frees its future's result
        weakref.finalize(self, queue.abandon, idx)

    # --- read surface ----------------------------------------------------
    def read(self):
        from ..table import Table

        chunks = self._queue.fetch_now(self._idx)
        return chunks[0] if len(chunks) == 1 else Table.concat(chunks)

    def read_chunks(self):
        return self._queue.fetch_now(self._idx)

    def with_pushdowns(self, pushdowns):
        self._queue.abandon(self._idx)
        return self._task.with_pushdowns(pushdowns)

    # --- metadata delegates ----------------------------------------------
    @property
    def materialized_schema(self):
        return self._task.materialized_schema

    @property
    def pushdowns(self):
        return self._task.pushdowns

    def num_rows(self) -> Optional[int]:
        return self._task.num_rows()

    def size_bytes(self) -> Optional[int]:
        return self._task.size_bytes()

    def can_prune(self) -> bool:
        return self._task.can_prune()

    def __getattr__(self, name):
        # anything else (path, format, schema, stats, storage_options, ...)
        # answers from the wrapped task
        return getattr(self._task, name)

    def __repr__(self) -> str:
        return f"PrefetchedScanTask#{self._idx}({self._task!r})"


def pipeline_scan_parts(parts, ctx):
    """Wrap a scan's emitted partitions for prefetch: locally-readable tasks
    go through one ScanPrefetcher (depth = cfg.scan_prefetch_depth);
    foreign-owned partitions (multi-host scan locality) pass through
    untouched — this process must never issue their reads. Depth 0 leaves
    the stream exactly as built."""
    from ..micropartition import MicroPartition

    depth = getattr(ctx.cfg, "scan_prefetch_depth", 0)
    if depth <= 0 or not parts:
        return parts
    local = [p for p in parts if not ctx.foreign_owned(p)]
    if not local:
        return parts
    queue = ScanPrefetcher([p.scan_task() for p in local], ctx, depth)
    by_id = {id(p): i for i, p in enumerate(local)}
    out = []
    for p in parts:
        i = by_id.get(id(p))
        if i is None:
            out.append(p)
            continue
        wrapped = MicroPartition.from_scan_task(queue.wrap(i))
        wrapped.owner_process = p.owner_process
        out.append(wrapped)
    return out
