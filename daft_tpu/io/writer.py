"""Tabular writers: Table/MicroPartition → parquet/csv/json files.

Role-equivalent to the reference's daft/table/table_io.py:401 (write_tabular):
writes one or more files per partition (splitting at a target file size),
optionally hive-partitioned by key columns, and returns a manifest Table of
written file paths (the reference's write result schema).

Targets are local paths OR object-store urls (s3://...): every byte goes
through io.object_store.Storage, so the same SigV4 client that serves reads
serves writes (reference: the put path of s3_like.rs; cloud-target writes
via daft/table/table_io.py:401+).
"""

from __future__ import annotations

import io
import json
import uuid
from typing import Any, Dict, List, Optional, Sequence

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.parquet as papq

from ..schema import Field, Schema
from ..series import Series
from ..table import Table
from .object_store import STORAGE

TARGET_FILE_SIZE_BYTES = 512 * 1024 * 1024


def write_parquet_any(path: str, arrow_tbl: pa.Table) -> int:
    """ONE parquet file to a local path (streamed to disk) or an
    object-store url (buffered once, zero-copy put); returns the encoded
    byte size. Shared with the Delta/Iceberg data-file writers so the
    buffer-vs-stream dispatch lives once."""
    import os

    if STORAGE.is_remote(path):
        buf = io.BytesIO()
        papq.write_table(arrow_tbl, buf)
        view = buf.getbuffer()
        STORAGE.put(path, view)
        return len(view)
    lp = STORAGE._local(path)
    os.makedirs(os.path.dirname(lp) or ".", exist_ok=True)
    papq.write_table(arrow_tbl, lp)
    return os.path.getsize(lp)


def _encode_to(sink, arrow_tbl: pa.Table, format: str,
               compression: Optional[str]) -> None:
    """`sink` is a path (streams to disk) or a file-like (buffers)."""
    if format == "parquet":
        papq.write_table(arrow_tbl, sink, compression=compression or "snappy")
    elif format == "csv":
        pacsv.write_csv(arrow_tbl, sink)
    elif format == "json":
        cols = arrow_tbl.to_pydict()
        names = list(cols)
        text = "".join(json.dumps(dict(zip(names, row)), default=str) + "\n"
                       for row in (zip(*cols.values()) if names else []))
        data = text.encode()
        if isinstance(sink, str):
            with open(sink, "wb") as f:
                f.write(data)
        else:
            sink.write(data)
    else:
        raise ValueError(f"unknown write format {format!r}")


def _write_one(arrow_tbl: pa.Table, root: str, format: str,
               compression: Optional[str], idx: int) -> str:
    name = f"{uuid.uuid4().hex[:16]}-{idx}.{format}"
    path = STORAGE.join(root, name)
    if STORAGE.is_remote(path):
        buf = io.BytesIO()
        _encode_to(buf, arrow_tbl, format, compression)
        # getbuffer(): zero-copy view; multipart slices of a memoryview are
        # views too, so peak memory stays ~one encoded file, not two
        STORAGE.put(path, buf.getbuffer())
    else:
        # stream straight to disk: no full-file RAM buffering locally
        _encode_to(STORAGE._local(path), arrow_tbl, format, compression)
    return path


def write_tabular(tbl: Table, root_dir: str, format: str = "parquet",
                  compression: Optional[str] = None,
                  partition_cols: Optional[Sequence] = None,
                  target_file_size: int = TARGET_FILE_SIZE_BYTES) -> Table:
    """Write a table; returns a manifest table with a 'path' column (plus the
    partition key columns when hive-partitioning)."""
    STORAGE.makedirs(root_dir)
    paths: List[str] = []
    part_vals: List[Dict[str, Any]] = []

    if partition_cols:
        parts, uniq = tbl.partition_by_value(list(partition_cols))
        key_names = uniq.column_names
        uniq_rows = uniq.to_pylist()
        for part, keyrow in zip(parts, uniq_rows):
            subdir = STORAGE.join(
                root_dir,
                *[f"{k}={_hive_value(v)}" for k, v in keyrow.items()],
            )
            STORAGE.makedirs(subdir)
            drop = [c for c in part.column_names if c not in key_names] or part.column_names
            body = part.select_columns(drop)
            for i, chunk in enumerate(_split_by_size(body, target_file_size)):
                p = _write_one(chunk.to_arrow(), subdir, format, compression, i)
                paths.append(p)
                part_vals.append(keyrow)
        cols = [Series.from_pylist(paths, "path")]
        fields = [Field("path", cols[0].dtype)]
        for k in key_names:
            s = Series.from_pylist([pv[k] for pv in part_vals], k)
            cols.append(s)
            fields.append(Field(k, s.dtype))
        return Table(Schema(fields), cols)

    for i, chunk in enumerate(_split_by_size(tbl, target_file_size)):
        paths.append(_write_one(chunk.to_arrow(), root_dir, format, compression, i))
    s = Series.from_pylist(paths, "path")
    return Table(Schema([Field("path", s.dtype)]), [s])


def _split_by_size(tbl: Table, target: int):
    n = len(tbl)
    if n == 0:
        yield tbl
        return
    total = max(tbl.size_bytes(), 1)
    n_files = max(1, (total + target - 1) // target)
    rows_per = (n + n_files - 1) // n_files
    for start in range(0, n, rows_per):
        yield tbl.slice(start, min(start + rows_per, n))


def _hive_value(v: Any) -> str:
    if v is None:
        return "__HIVE_DEFAULT_PARTITION__"
    return str(v).replace("/", "%2F")
