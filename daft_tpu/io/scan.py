"""Scan layer: Pushdowns, ScanTask, file globbing, IO stats.

Role-equivalent to the reference's src/daft-scan/src/lib.rs (ScanTask :342,
Pushdowns :839) and glob scan operator (glob.rs). A ScanTask describes one unit
of IO work — a file (or slice of one) plus the pushdowns to apply while
reading — and is the payload of an Unloaded MicroPartition.
"""

from __future__ import annotations

import glob as _glob
import os
import threading
from typing import Any, Dict, List, Optional, Sequence

from ..errors import DaftNotFoundError, DaftValueError
from ..schema import Schema
from ..stats import TableStats, filter_may_match


class IOStats:
    """Process-wide IO counters (reference: daft-io IOStatsContext). Tests use
    these to verify pushdowns actually reduce IO."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        with getattr(self, "_lock", threading.Lock()):
            self.files_opened = 0
            self.bytes_read = 0
            self.rows_read = 0
            self.row_groups_read = 0
            self.row_groups_pruned = 0
            self.columns_read = 0
            self.retries = 0
            self.bytes_written = 0

    def bump(self, **kw: int) -> None:
        with self._lock:
            for k, v in kw.items():
                setattr(self, k, getattr(self, k) + v)

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return {
                "files_opened": self.files_opened,
                "bytes_read": self.bytes_read,
                "rows_read": self.rows_read,
                "row_groups_read": self.row_groups_read,
                "row_groups_pruned": self.row_groups_pruned,
                "columns_read": self.columns_read,
                "retries": self.retries,
                "bytes_written": self.bytes_written,
            }


IO_STATS = IOStats()


class FileFormat:
    PARQUET = "parquet"
    CSV = "csv"
    JSON = "json"
    # engine-internal spill format: uncompressed arrow IPC re-materializes at
    # memcpy speed where parquet would pay encode+decode per spilled partition
    ARROW_IPC = "arrow_ipc"


class Pushdowns:
    """Pushed-down operations a reader may honor: column projection, row
    filters, and a row limit (reference: daft-scan Pushdowns, lib.rs:839)."""

    __slots__ = ("columns", "filters", "limit")

    def __init__(self, columns: Optional[List[str]] = None,
                 filters: Optional[Any] = None,  # ExprNode
                 limit: Optional[int] = None):
        self.columns = columns
        self.filters = filters
        self.limit = limit

    def is_empty(self) -> bool:
        return self.columns is None and self.filters is None and self.limit is None

    def __repr__(self) -> str:
        parts = []
        if self.columns is not None:
            parts.append(f"columns={self.columns}")
        if self.filters is not None:
            parts.append(f"filters={self.filters.display()}")
        if self.limit is not None:
            parts.append(f"limit={self.limit}")
        return f"Pushdowns({', '.join(parts)})"

    def with_columns(self, columns: Optional[List[str]]) -> "Pushdowns":
        return Pushdowns(columns, self.filters, self.limit)

    def with_filters(self, filters) -> "Pushdowns":
        return Pushdowns(self.columns, filters, self.limit)

    def with_limit(self, limit: Optional[int]) -> "Pushdowns":
        return Pushdowns(self.columns, self.filters, limit)


class ScanTask:
    """One unit of scan work: a file + format + schema + pushdowns.

    `materialized_schema` is the post-pushdown schema (column projection
    applied). `stats`/`num_rows`/`size_bytes` come from file metadata where the
    format provides it (parquet), powering pruning and planning estimates.
    """

    __slots__ = ("path", "format", "schema", "pushdowns", "storage_options",
                 "_num_rows", "_size_bytes", "stats", "row_group_ids",
                 "partition_values")

    def __init__(self, path: str, format: str, schema: Schema,
                 pushdowns: Optional[Pushdowns] = None,
                 storage_options: Optional[Dict[str, Any]] = None,
                 num_rows: Optional[int] = None, size_bytes: Optional[int] = None,
                 stats: Optional[TableStats] = None,
                 row_group_ids: Optional[List[int]] = None,
                 partition_values: Optional[Dict[str, Any]] = None):
        self.path = path
        self.format = format
        self.schema = schema
        self.pushdowns = pushdowns or Pushdowns()
        self.storage_options = storage_options or {}
        self._num_rows = num_rows
        self._size_bytes = size_bytes
        self.stats = stats
        self.row_group_ids = row_group_ids
        # hive/delta-style partition columns: constant per file, materialized
        # as columns after the read (values live in the catalog, not the file)
        self.partition_values = partition_values

    def __repr__(self) -> str:
        return f"ScanTask({self.format}:{self.path}, {self.pushdowns!r})"

    @property
    def materialized_schema(self) -> Schema:
        if self.pushdowns.columns is None:
            return self.schema
        return self.schema.select([c for c in self.pushdowns.columns if c in self.schema])

    def num_rows(self) -> Optional[int]:
        """Exact row count after pushdowns, when knowable without IO."""
        if self.pushdowns.filters is not None:
            return None
        if self._num_rows is None:
            return None
        if self.pushdowns.limit is not None:
            return min(self._num_rows, self.pushdowns.limit)
        return self._num_rows

    def size_bytes(self) -> Optional[int]:
        return self._size_bytes

    def with_pushdowns(self, pushdowns: Pushdowns) -> "ScanTask":
        return ScanTask(self.path, self.format, self.schema, pushdowns,
                        self.storage_options, self._num_rows, self._size_bytes,
                        self.stats, self.row_group_ids, self.partition_values)

    def can_prune(self) -> bool:
        """True if file-level stats prove the pushdown filter matches no rows."""
        if self.pushdowns.filters is None or self.stats is None:
            return False
        return not filter_may_match(self.pushdowns.filters, self.stats)

    def read(self):
        """Materialize this scan task into a Table (applies pushdowns).

        Transient IO errors retry through the shared RetryPolicy — jittered
        exponential backoff with a cap, so scan tasks hammering a shared
        endpoint don't form synchronized retry herds (reference: the
        IO-layer retry policies of daft-io s3_like.rs:452-468, applied here
        at task granularity); permanent errors (missing file, permissions)
        raise immediately."""
        from .. import faults

        policy = self._retry_policy()

        def attempt():
            faults.check("scan.read")
            return self._read_with_partition_values()

        return policy.run(attempt)

    def _retry_policy(self):
        from ..context import get_context
        from .object_store import RetryPolicy

        cfg = get_context().execution_config
        return RetryPolicy(
            attempts=max(1, cfg.scan_retry_attempts),
            backoff_s=cfg.scan_retry_backoff_s,
            retryable=(OSError,),
            permanent=(FileNotFoundError, PermissionError, IsADirectoryError))

    def iter_chunks(self):
        """Lazily yield the chunk tables ``read()`` would produce, decoding
        parquet one row group at a time — the streaming executor's first
        morsel flows after ONE row-group decode instead of the whole file.
        The footer/plan open and each row-group decode run inside the same
        retry policy + ``scan.read`` fault contract as ``read()``; the
        shared ``plan_parquet_chunks`` guarantees chunk-wise reads choose
        exactly the row groups the whole-file read would (pruning and the
        limit early stop included), so concatenated chunks are
        byte-identical content. Non-parquet formats and deferred
        partition-value filters collapse to a single whole-read chunk."""
        if (self.format != FileFormat.PARQUET
                or (self.partition_values
                    and self.pushdowns.filters is not None)):
            yield self.read()
            return
        from .. import faults
        from .readers import plan_parquet_chunks, read_parquet_chunk

        policy = self._retry_policy()

        def plan():
            faults.check("scan.read")
            return plan_parquet_chunks(self.path, self.pushdowns,
                                       self.schema, self.row_group_ids)

        pf, chosen, columns, _ = policy.run(plan)
        handle = {"pf": pf, "fresh": True}
        for rg in chosen:
            handle["fresh"] = True

            def attempt(rg=rg):
                if not handle["fresh"]:
                    # retrying a failed decode: the failure may live in the
                    # open file handle (stale/broken fd on a network fs) —
                    # reopen before retrying, matching the whole-file path
                    # where open+read retry together under one policy.run
                    from .readers import open_parquet_file

                    handle["pf"] = open_parquet_file(self.path)
                    IO_STATS.bump(files_opened=1)
                handle["fresh"] = False
                faults.check("scan.read")
                return read_parquet_chunk(handle["pf"], rg, columns,
                                          self.pushdowns, self.schema)

            tbl = policy.run(attempt)
            if self.partition_values:
                tbl = self._append_partition_columns(tbl)
            yield tbl

    def _read_with_partition_values(self):
        """Catalog partition columns don't exist in the file, so a pushed-down
        filter touching them must wait until they're appended — the file-level
        reader would otherwise evaluate them against the reader's null fill."""
        if not self.partition_values or self.pushdowns.filters is None:
            return self._read_once()
        from ..expressions import Expression
        from ..logical import expr_input_columns

        pred = Expression(self.pushdowns.filters)
        need = expr_input_columns(pred)
        if not set(need) & set(self.partition_values):
            return self._read_once()
        # the limit must also wait: a reader-side early-stop would truncate
        # BEFORE the deferred filter, dropping matching rows in unread ranges
        pd2 = self.pushdowns.with_filters(None).with_limit(None)
        if pd2.columns is not None:
            pd2 = pd2.with_columns(
                list(pd2.columns) + [c for c in need
                                     if c not in pd2.columns and c in self.schema])
        tbl = self.with_pushdowns(pd2)._read_once().filter(pred)
        want = self.materialized_schema
        if tbl.schema.field_names() != want.field_names():
            tbl = tbl.select_columns(want.field_names())
        if self.pushdowns.limit is not None:
            tbl = tbl.head(self.pushdowns.limit)
        return tbl

    def _read_once(self):
        from .readers import read_csv_table, read_json_table, read_parquet_table

        if self.format == FileFormat.PARQUET:
            tbl = read_parquet_table(self.path, self.pushdowns, schema=self.schema,
                                     row_group_ids=self.row_group_ids)
        elif self.format == FileFormat.CSV:
            tbl = read_csv_table(self.path, self.pushdowns, schema=self.schema,
                                 **self.storage_options)
        elif self.format == FileFormat.JSON:
            tbl = read_json_table(self.path, self.pushdowns, schema=self.schema)
        elif self.format == FileFormat.ARROW_IPC:
            from .readers import read_arrow_ipc_table

            tbl = read_arrow_ipc_table(self.path, self.pushdowns,
                                       schema=self.schema)
        else:
            raise DaftValueError(f"unknown scan format {self.format!r}")
        if self.partition_values:
            tbl = self._append_partition_columns(tbl)
        return tbl

    def _append_partition_columns(self, tbl):
        from ..series import Series
        from ..table import Table

        want = self.materialized_schema
        cols = list(tbl.columns())
        fields = [f for f in tbl.schema]
        for name, value in self.partition_values.items():
            if name not in want:
                continue
            f = want[name]
            s = Series.from_pylist([value] * len(tbl), name, f.dtype)
            if name in tbl.schema:
                # the file reader fills catalog-only columns with nulls;
                # overwrite with the partition value from the log
                cols[tbl.schema.index(name)] = s
            else:
                cols.append(s)
                fields.append(f)
        from ..schema import Schema as _S

        return Table(_S(fields), cols).cast_to_schema(want)


class MergedScanTask(ScanTask):
    """Several small files read as ONE unit of scan work.

    The reference merges adjacent small ScanTasks into one task up to a size
    window so tiny files don't each become a partition (daft-scan
    `scan_task_iters.rs:29` merge_by_sizes); this is the same idea with the
    children kept whole so per-file pushdown narrowing and stats pruning
    still apply file-by-file at read time.
    """

    __slots__ = ("children",)

    def __init__(self, children: Sequence[ScanTask]):
        first = children[0]
        st: Optional[TableStats] = first.stats
        for c in children[1:]:
            st = st.merge(c.stats) if (st is not None and c.stats is not None) else None
        nrows: Optional[int] = 0
        for c in children:
            if c._num_rows is None:
                nrows = None
                break
            nrows += c._num_rows
        sizes = [c._size_bytes for c in children]
        size = sum(sizes) if all(s is not None for s in sizes) else None
        super().__init__(first.path, first.format, first.schema, first.pushdowns,
                         first.storage_options, nrows, size, st)
        self.children = list(children)

    def __repr__(self) -> str:
        return (f"MergedScanTask({self.format}:{len(self.children)} files, "
                f"{self.pushdowns!r})")

    def with_pushdowns(self, pushdowns: Pushdowns) -> "MergedScanTask":
        return MergedScanTask([c.with_pushdowns(pushdowns) for c in self.children])

    def can_prune(self) -> bool:
        return all(c.can_prune() for c in self.children)

    def read(self):
        from ..table import Table

        chunks = self.read_chunks()
        return chunks[0] if len(chunks) == 1 else Table.concat(chunks)

    def read_chunks(self):
        """Per-child tables, cast to the merged schema but NOT concatenated —
        the chunk-preserving shuffle path (MicroPartition.chunk_tables) splits
        each piece independently, so merged small files never pay the
        O(task-bytes) concat on the map side."""
        from ..table import Table

        tables = []
        remaining = self.pushdowns.limit
        for c in self.children:
            if c.can_prune():
                continue
            if remaining is not None:
                c = c.with_pushdowns(c.pushdowns.with_limit(remaining))
            t = c.read()
            tables.append(t)
            if remaining is not None:
                remaining -= len(t)
                if remaining <= 0:
                    break
        if not tables:
            return [Table.empty(self.materialized_schema)]
        want = self.materialized_schema
        return [t.cast_to_schema(want) for t in tables]

    def iter_chunks(self):
        """Lazy counterpart of ``read_chunks``: children decode one at a
        time (and parquet children one row group at a time), with the same
        per-child pruning, limit narrowing, and merged-schema cast — the
        running limit decrements per CHUNK, stopping at the same child the
        eager path would."""
        remaining = self.pushdowns.limit
        want = self.materialized_schema
        for c in self.children:
            if c.can_prune():
                continue
            if remaining is not None:
                c = c.with_pushdowns(c.pushdowns.with_limit(remaining))
            for t in c.iter_chunks():
                yield t.cast_to_schema(want)
                if remaining is not None:
                    remaining -= len(t)
            if remaining is not None and remaining <= 0:
                break


def merge_scan_tasks_by_size(tasks: Sequence[ScanTask],
                             min_bytes: int, max_bytes: int) -> List[ScanTask]:
    """Pack runs of adjacent small tasks into MergedScanTasks: accumulate while
    below `min_bytes`, never exceeding `max_bytes` per merged task. Tasks of
    unknown size or already at/above `min_bytes` pass through unmerged.
    Reference: daft-scan `scan_task_iters.rs:29` (merge window 96-384MB)."""
    out: List[ScanTask] = []
    cur: List[ScanTask] = []
    cur_bytes = 0

    def flush():
        nonlocal cur, cur_bytes
        if len(cur) == 1:
            out.append(cur[0])
        elif cur:
            out.append(MergedScanTask(cur))
        cur, cur_bytes = [], 0

    for t in tasks:
        sz = t.size_bytes()
        if sz is None or sz >= min_bytes:
            flush()
            out.append(t)
            continue
        if cur and cur_bytes + sz > max_bytes:
            flush()
        cur.append(t)
        cur_bytes += sz
        if cur_bytes >= min_bytes:
            flush()
    flush()
    return out


def glob_paths(path) -> List[str]:
    """Expand a path / glob / directory / list thereof into concrete file paths.

    Reference: daft-scan glob.rs + daft/io common path handling. Local
    filesystem only; object stores are routed through fsspec-style options in
    storage_options (gated: zero-egress environment).
    """
    if isinstance(path, (list, tuple)):
        out: List[str] = []
        for p in path:
            out.extend(glob_paths(p))
        return out
    p = str(path)
    from .object_store import is_remote_path

    if is_remote_path(p):
        from .object_store import default_io_client

        metas = default_io_client().glob(p)
        if not metas:
            raise DaftNotFoundError(f"{p!r} matched no objects")
        return [m.path for m in metas]
    if p.startswith("file://"):
        p = p[len("file://"):]
    if os.path.isdir(p):
        files = sorted(
            os.path.join(p, f) for f in os.listdir(p)
            if not f.startswith(".") and not f.startswith("_")
            and os.path.isfile(os.path.join(p, f))
        )
        if not files:
            raise DaftNotFoundError(f"no files found in directory {p!r}")
        return files
    if any(ch in p for ch in "*?["):
        files = sorted(f for f in _glob.glob(p, recursive=True) if os.path.isfile(f))
        if not files:
            raise DaftNotFoundError(f"glob {p!r} matched no files")
        return files
    if not os.path.exists(p):
        raise DaftNotFoundError(f"path {p!r} does not exist")
    return [p]
