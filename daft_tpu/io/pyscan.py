"""User-extensible Python scan sources.

Role-equivalent to the reference's Python `ScanOperator` ABC
(`daft/io/scan.py:20-50`) and the `DataSource::PythonFactoryFunction` scan-task
payload (`src/daft-scan/src/lib.rs:121-141`): a third-party catalog or storage
client exposes its fragments as scan tasks whose bytes are produced by a plain
Python callable, and those tasks flow through the same lazy MicroPartition /
pushdown machinery as file scans. `read_lance` (io/catalogs.py) is built on
this layer, matching the reference's lance integration
(`daft/io/_lance.py:68`).
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from typing import Any, Callable, Iterator, List, Optional

from ..schema import Schema
from ..stats import TableStats
from .scan import Pushdowns, ScanTask


class ScanOperator(ABC):
    """A pluggable source of scan tasks (reference: daft/io/scan.py:20-50).

    Implementations enumerate their fragments as `FactoryScanTask`s. The
    `can_absorb_*` flags declare which pushdowns the operator's factories
    honor themselves — `from_scan_operator` copies them onto tasks that don't
    set `absorbs` explicitly; anything not absorbed is re-applied by the
    engine after materialization, so a conservative `False` is always correct.
    """

    def display_name(self) -> str:
        return type(self).__name__

    @abstractmethod
    def schema(self) -> Schema: ...

    def partitioning_keys(self) -> List[str]:
        return []

    def can_absorb_filter(self) -> bool:
        return False

    def can_absorb_limit(self) -> bool:
        return False

    def can_absorb_select(self) -> bool:
        return False

    @abstractmethod
    def to_scan_tasks(self, pushdowns: Pushdowns) -> Iterator["FactoryScanTask"]: ...

    def multiline_display(self) -> List[str]:
        return [self.display_name(), f"Schema = {self.schema().field_names()}"]


class FactoryScanTask(ScanTask):
    """A scan task whose bytes come from a Python callable, not a file.

    The factory is invoked as `factory(pushdowns)` and may return a pyarrow
    Table/RecordBatch, an iterable of RecordBatches, or a daft_tpu Table. All
    pushdowns are re-applied after materialization unless `absorbs` names them
    (("columns", "filters", "limit") subset) — double-applying a projection,
    filter, or limit is idempotent, so a factory that partially honors its
    pushdowns stays correct.
    """

    __slots__ = ("factory", "absorbs")

    def __init__(self, factory: Callable[[Pushdowns], Any], schema: Schema,
                 pushdowns: Optional[Pushdowns] = None,
                 num_rows: Optional[int] = None,
                 size_bytes: Optional[int] = None,
                 stats: Optional[TableStats] = None,
                 label: str = "python-factory",
                 absorbs: tuple = ()):
        super().__init__(label, "python", schema, pushdowns, None,
                         num_rows, size_bytes, stats)
        self.factory = factory
        self.absorbs = tuple(absorbs)

    def __repr__(self) -> str:
        return f"FactoryScanTask({self.path}, {self.pushdowns!r})"

    def with_pushdowns(self, pushdowns: Pushdowns) -> "FactoryScanTask":
        return FactoryScanTask(self.factory, self.schema, pushdowns,
                               self._num_rows, self._size_bytes, self.stats,
                               self.path, self.absorbs)

    def read(self):
        import pyarrow as pa

        from ..expressions import Expression
        from ..table import Table

        pd = self.pushdowns
        factory_pd = pd
        if pd.filters is not None and pd.columns is not None:
            # a factory honoring the column pushdown must still produce the
            # filter's input columns, or the engine-side re-filter would lose
            # them (same union the file readers do in readers._project_columns)
            from ..logical import expr_input_columns

            need = expr_input_columns(Expression(pd.filters))
            extra = [c for c in need if c not in pd.columns and c in self.schema]
            if extra:
                factory_pd = pd.with_columns(list(pd.columns) + extra)
        raw = self.factory(factory_pd)
        if isinstance(raw, Table):
            tbl = raw
        elif isinstance(raw, (pa.Table, pa.RecordBatch)):
            tbl = Table.from_arrow(raw)
        else:  # iterator of record batches (reference factory-function shape)
            batches = list(raw)
            if not batches:
                return Table.empty(self.materialized_schema)
            tbl = Table.from_arrow(pa.Table.from_batches(batches))
        if pd.filters is not None and "filters" not in self.absorbs:
            tbl = tbl.filter(Expression(pd.filters))
        if pd.limit is not None and "limit" not in self.absorbs:
            tbl = tbl.head(pd.limit)
        want = self.materialized_schema
        if tbl.schema.field_names() != want.field_names():
            tbl = tbl.select_columns([c for c in want.field_names()
                                      if c in tbl.schema])
        return tbl.cast_to_schema(want)


def from_scan_operator(op: ScanOperator):
    """Build a DataFrame over a custom ScanOperator (reference:
    `ScanOperatorHandle.from_python_scan_operator` + `from_tabular_scan`).

    The operator's `can_absorb_*` flags become the default `absorbs` of its
    tasks: a task that did not set `absorbs` itself inherits them, so the
    engine skips re-applying the pushdowns the operator declared it honors.
    """
    from ..dataframe import DataFrame
    from ..logical import ScanSource

    flags = (("columns",) if op.can_absorb_select() else ()) \
        + (("filters",) if op.can_absorb_filter() else ()) \
        + (("limit",) if op.can_absorb_limit() else ())
    schema = op.schema()
    tasks = []
    for t in op.to_scan_tasks(Pushdowns()):
        if isinstance(t, FactoryScanTask) and not t.absorbs and flags:
            t = FactoryScanTask(t.factory, t.schema, t.pushdowns, t._num_rows,
                                t._size_bytes, t.stats, t.path, flags)
        tasks.append(t)
    return DataFrame(ScanSource(schema, tasks))
