"""IO layer: scan tasks, pushdowns, format readers/writers, IO stats.

Role-equivalent to the reference's daft-scan (ScanTask/Pushdowns/glob,
src/daft-scan/src/lib.rs:342,839), daft-parquet/daft-csv/daft-json readers, and
daft/table/table_io.py writers. Host engine is pyarrow (the Arrow C++ datasets
stack); the TPU path stages decoded Arrow batches onto device via
kernels/device.py.
"""

from .scan import (
    FileFormat,
    IOStats,
    IO_STATS,
    Pushdowns,
    ScanTask,
    glob_paths,
)
from .readers import read_csv_table, read_json_table, read_parquet_table
from .writer import write_tabular

__all__ = [
    "FileFormat",
    "IOStats",
    "IO_STATS",
    "Pushdowns",
    "ScanTask",
    "glob_paths",
    "read_csv_table",
    "read_json_table",
    "read_parquet_table",
    "write_tabular",
]
