"""Catalog readers: Delta Lake (native log parser) and DB-API SQL scans.

Reference role-equivalents:
- `read_deltalake` (daft/delta_lake/delta_lake_scan.py:26): the reference uses
  the deltalake client to list files; here the Delta transaction log is parsed
  directly — `_delta_log/*.json` add/remove actions fold into the live file
  set, which becomes parquet ScanTasks with per-file size + partition values,
  so pushdowns and pruning ride the normal scan layer.
- `read_sql` (daft/sql/sql_scan.py:35): executes a query through any DB-API
  connection (or a sqlite:// / file path shortcut) and materializes the result
  as arrow. Partitioning a SQL source by percentile bounds requires server
  round-trips; this host path reads in one shot like the reference's
  fallback (single ScanTask) mode.

- `read_iceberg` (daft/iceberg/iceberg_scan.py:84): manifest-list -> manifest
  replay decoded with the native avro codec (io/avro.py); copy-on-write only.
- `read_hudi` (daft/hudi/hudi_scan.py:22): .hoodie commit-timeline replay,
  latest file slice per file group; copy-on-write only.
- `write_deltalake` (daft/dataframe/dataframe.py write_deltalake): parquet
  files + an atomic put-if-absent JSON commit on the next log version.
Lance needs its own columnar format codec which is not in this image; its
entry points raise a clear error at api.py.
"""

from __future__ import annotations

import json
import os
import time
from typing import Any, Callable, List, Optional, Union

import pyarrow as pa

from ..datatypes import DataType
from ..errors import DaftNotFoundError
from ..schema import Field, Schema
from .object_store import STORAGE
from .writer import write_parquet_any
from .scan import FileFormat, Pushdowns, ScanTask


def _schema_from_parquet(path: str) -> Schema:
    """Engine Schema from a parquet footer (shared by the catalog readers).
    Remote paths read the footer through ranged gets, not a full download."""
    import pyarrow.parquet as papq

    src = STORAGE.open_input(path) if STORAGE.is_remote(path) else path
    arrow_schema = papq.read_schema(src)
    return Schema([Field(n, DataType.from_arrow(arrow_schema.field(n).type))
                   for n in arrow_schema.names])


def _delta_live_files(table_uri: str) -> List[dict]:
    """Fold the Delta transaction log into the set of live data files.

    Honors checkpoints: when _delta_log/_last_checkpoint exists, the add/remove
    state is seeded from the checkpoint parquet (single or multi-part) and only
    commits AFTER the checkpoint version are replayed — required for tables
    whose older JSON commits were vacuumed by log retention.

    All log IO goes through Storage, so s3:// table uris read exactly like
    local ones (reference: delta_lake_scan.py over an fsspec filesystem)."""
    log_dir = STORAGE.join(table_uri, "_delta_log")
    log_names = set(STORAGE.list_names(log_dir))
    if not log_names:
        raise DaftNotFoundError(f"not a Delta table (no _delta_log): {table_uri}")
    live: dict = {}
    start_after = -1
    if "_last_checkpoint" in log_names:
        lc = json.loads(STORAGE.get(STORAGE.join(log_dir, "_last_checkpoint")))
        version = int(lc["version"])
        parts = int(lc.get("parts", 0) or 0)
        if parts:
            cp_names = [f"{version:020d}.checkpoint.{i:010d}.{parts:010d}.parquet"
                        for i in range(1, parts + 1)]
        else:
            cp_names = [f"{version:020d}.checkpoint.parquet"]
        missing = [n for n in cp_names if n not in log_names]
        if missing:
            raise FileNotFoundError(
                f"Delta checkpoint v{version} referenced by _last_checkpoint is "
                f"missing files: {missing}")
        import pyarrow.parquet as papq

        for cp in cp_names:
            t = papq.read_table(STORAGE.open_input(STORAGE.join(log_dir, cp)),
                                columns=["add", "remove"])
            for row in t.to_pylist():
                a, r = row.get("add"), row.get("remove")
                if a and a.get("path"):
                    live[a["path"]] = a
                elif r and r.get("path"):
                    live.pop(r["path"], None)
        start_after = version
    commits = sorted(f for f in log_names if f.endswith(".json"))
    commits = [c for c in commits if int(c.split(".")[0]) > start_after]
    if not commits and start_after < 0:
        raise DaftNotFoundError(f"Delta table has no commits: {table_uri}")
    for name in commits:
        for line in STORAGE.get(STORAGE.join(log_dir, name)).decode().splitlines():
            line = line.strip()
            if not line:
                continue
            action = json.loads(line)
            if "add" in action:
                a = action["add"]
                live[a["path"]] = a
            elif "remove" in action:
                live.pop(action["remove"]["path"], None)
    return [dict(v, path=STORAGE.join(table_uri, k)) for k, v in live.items()]


def read_deltalake_scan(table_uri: str):
    """-> (Schema, [ScanTask]) for a local Delta Lake table."""
    import pyarrow.parquet as papq

    files = _delta_live_files(table_uri)
    if not files:
        raise ValueError(f"Delta table {table_uri} has no live files")
    fields = list(_schema_from_parquet(files[0]["path"]))
    # hive-style partition columns live in the log's partitionValues, not the files
    part_cols: List[str] = []
    for f in files:
        for k in (f.get("partitionValues") or {}):
            if k not in part_cols:
                part_cols.append(k)
    for k in part_cols:
        fields.append(Field(k, DataType.string()))
    schema = Schema(fields)
    tasks = []
    for f in files:
        tasks.append(ScanTask(
            f["path"], FileFormat.PARQUET, schema, Pushdowns(),
            size_bytes=f.get("size"),
            partition_values={k: (f.get("partitionValues") or {}).get(k)
                              for k in part_cols} or None,
        ))
    return schema, tasks


# ---------------------------------------------------------------------------
# Iceberg (native manifest replay via io/avro.py)
# ---------------------------------------------------------------------------

_ICEBERG_PRIMITIVES = {
    "boolean": "bool", "int": "int32", "long": "int64", "float": "float32",
    "double": "float64", "string": "string", "date": "date",
    "binary": "binary", "uuid": "string",
}


def _metadata_version_of(name: str) -> int:
    """Version number of a vN.metadata.json / N-uuid.metadata.json name
    (shared by the metadata resolver and the writer's next-version pick)."""
    stem = name.split(".metadata.json")[0].lstrip("v")
    for tok in (stem, stem.split("-")[0]):
        try:
            return int(tok)
        except ValueError:
            continue
    return -1


def _iceberg_metadata_path(table_uri: str) -> str:
    """Resolve the current metadata json (hadoop-catalog layout): honor
    version-hint.text, else the highest-versioned *.metadata.json. Works
    over local paths and object-store uris alike (Storage)."""
    mdir = STORAGE.join(table_uri, "metadata")
    names = STORAGE.list_names(mdir)
    if not names:
        raise DaftNotFoundError(f"not an Iceberg table (no metadata/): {table_uri}")
    if "version-hint.text" in names:
        v = STORAGE.get(STORAGE.join(mdir, "version-hint.text")).decode().strip()
        for cand in (f"v{v}.metadata.json", f"{v}.metadata.json"):
            if cand in names:
                return STORAGE.join(mdir, cand)
    metas = [f for f in names if f.endswith(".metadata.json")]
    if not metas:
        raise DaftNotFoundError(f"Iceberg table has no metadata json: {table_uri}")
    return STORAGE.join(mdir, max(metas, key=_metadata_version_of))


def _iceberg_resolve(table_uri: str, uri: str) -> str:
    """Manifest/data paths are absolute URIs written at table-creation time;
    resolve them against the CURRENT table location so vendored/moved
    fixtures still read."""
    p = uri
    if p.startswith("file://"):
        p = p[len("file://"):]
    # in-place tables (the common case): manifest paths already live under
    # the current location — skip the per-file existence probe, which on
    # object stores would cost one HEAD round-trip per manifest/data file
    if p.startswith(str(table_uri).rstrip("/") + "/"):
        return p
    if STORAGE.is_remote(p):
        # external data paths are spec-legal (write.data.path / add_files
        # imports): probe with ONE non-retried HEAD — honoring them without
        # paying a backoff loop per file against an unreachable store. A
        # store root that times out is remembered DEAD for this process so a
        # relocated table with thousands of files pays one timeout, not one
        # per file.
        root = "/".join(p.split("/", 3)[:3])
        condemned_at = _DEAD_EXTERNAL_ROOTS.get(root)
        if condemned_at is not None and \
                time.monotonic() - condemned_at > _DEAD_ROOT_TTL_S:
            # a blip must not remap paths for the process lifetime: after the
            # TTL the next file re-probes the root and can resurrect it
            # (pop: two threads may expire the same root concurrently)
            _DEAD_EXTERNAL_ROOTS.pop(root, None)
            condemned_at = None
        if condemned_at is None:
            from .object_store import TransientIOError

            try:
                STORAGE.client.source_for(p).get_size(p)
                return p
            except TransientIOError as e:
                # only CONNECTION-level failures (timeout, refused, reset —
                # surfaced as an OSError cause) condemn the root; a 429/5xx
                # is the store talking to us, and must not silently remap
                # 999 remaining files after one throttle
                if isinstance(e.__cause__, OSError):
                    _DEAD_EXTERNAL_ROOTS[root] = time.monotonic()
            except Exception:
                pass  # absent (404 etc.): remap this file, keep probing root
    elif STORAGE.exists(p):
        return p
    # remap by the stable tail: .../metadata/<x> or .../data/<x>
    for anchor in ("/metadata/", "/data/"):
        if anchor in p:
            # rsplit: the table's ORIGINAL location may itself contain
            # /data/ or /metadata/ segments
            return STORAGE.join(table_uri, anchor.strip("/"),
                                p.rsplit(anchor, 1)[1])
    return STORAGE.join(table_uri, p.rsplit("/", 1)[-1])


# store root -> monotonic time it was condemned; entries expire after
# _DEAD_ROOT_TTL_S so one network blip cannot permanently redirect every
# subsequent external path to the table location (advisor r4)
_DEAD_EXTERNAL_ROOTS: dict = {}
_DEAD_ROOT_TTL_S = 60.0


def _read_avro_any(path: str):
    """Avro OCF over local paths AND object-store uris (Storage.get
    handles both)."""
    from .avro import read_avro_bytes

    return read_avro_bytes(STORAGE.get(path))


def read_iceberg_scan(table_uri: str, snapshot_id: Optional[int] = None):
    """-> (Schema, [ScanTask]) for a local Iceberg v1/v2 table by replaying
    manifest list -> manifests -> live data files (reference:
    daft/iceberg/iceberg_scan.py:84, which delegates to pyiceberg; here the
    avro manifests are decoded natively like catalogs.py's Delta log replay).
    Merge-on-read delete files are rejected (copy-on-write tables only)."""
    meta_path = _iceberg_metadata_path(table_uri)
    meta = json.loads(STORAGE.get(meta_path))
    snaps = meta.get("snapshots") or []
    sid = snapshot_id if snapshot_id is not None else meta.get("current-snapshot-id")
    snap = next((s for s in snaps if s.get("snapshot-id") == sid), None)
    if snap is None:
        if snapshot_id is not None:
            raise ValueError(f"Iceberg snapshot {snapshot_id} not found in "
                             f"{table_uri} (has {[s.get('snapshot-id') for s in snaps]})")
        if sid is not None and sid != -1 and snaps:
            raise ValueError(f"Iceberg current-snapshot-id {sid} missing from "
                             f"the snapshot log of {table_uri}")
    data_files: List[dict] = []
    if snap is not None:
        if snap.get("manifest-list"):
            _, mlist = _read_avro_any(_iceberg_resolve(table_uri, snap["manifest-list"]))
            manifest_paths = [m["manifest_path"] for m in mlist]
        else:  # v1 inline manifests
            manifest_paths = list(snap.get("manifests") or [])
        for mp in manifest_paths:
            _, entries = _read_avro_any(_iceberg_resolve(table_uri, mp))
            for e in entries:
                if e.get("status") == 2:  # deleted
                    continue
                df = e.get("data_file") or {}
                content = df.get("content") or 0
                if content != 0:
                    raise ValueError(
                        "Iceberg merge-on-read delete files are not supported "
                        "(copy-on-write tables only)")
                if (df.get("file_format") or "PARQUET").upper() != "PARQUET":
                    raise ValueError(f"unsupported Iceberg file format "
                                     f"{df.get('file_format')!r}")
                data_files.append(df)
    # schema: prefer a real data file footer (exact physical types); fall
    # back to the metadata schema for empty tables
    if data_files:
        first = _iceberg_resolve(table_uri, data_files[0]["file_path"])
        fields = list(_schema_from_parquet(first))
    else:
        schemas = meta.get("schemas")
        if schemas:
            cur = meta.get("current-schema-id", 0)
            sch = next((s for s in schemas if s.get("schema-id") == cur), schemas[-1])
        else:
            sch = meta.get("schema") or {"fields": []}
        fields = []
        for fld in sch.get("fields", []):
            t = fld.get("type")
            if not isinstance(t, str):
                raise ValueError("nested Iceberg schemas require data files "
                                 "to infer from (empty table)")
            if t.startswith("timestamp"):
                dt = DataType.timestamp("us")
            elif t.startswith("decimal"):
                dt = DataType.float64()
            elif t.startswith("fixed"):
                dt = DataType.binary()
            else:
                key = _ICEBERG_PRIMITIVES.get(t)
                if key is None:
                    raise ValueError(f"unsupported Iceberg type {t!r}")
                dt = getattr(DataType, key)()
            fields.append(Field(fld["name"], dt))
    schema = Schema(fields)
    tasks = [ScanTask(_iceberg_resolve(table_uri, df["file_path"]),
                      FileFormat.PARQUET, schema, Pushdowns(),
                      size_bytes=df.get("file_size_in_bytes"),
                      num_rows=df.get("record_count"))
             for df in data_files]
    return schema, tasks


# ---------------------------------------------------------------------------
# Hudi copy-on-write (native timeline replay)
# ---------------------------------------------------------------------------

def read_hudi_scan(table_uri: str):
    """-> (Schema, [ScanTask]) for a local Hudi copy-on-write table: replay
    the .hoodie commit timeline and keep the LATEST file slice per file
    group (reference: daft/hudi/hudi_scan.py:22). Merge-on-read tables
    (log files) are rejected."""
    hoodie = os.path.join(table_uri, ".hoodie")
    if not os.path.isdir(hoodie):
        raise DaftNotFoundError(f"not a Hudi table (no .hoodie): {table_uri}")
    timeline = os.listdir(hoodie)
    if any(f.endswith(".deltacommit") or f.endswith(".deltacommit.requested")
           or f.endswith(".deltacommit.inflight") for f in timeline):
        raise ValueError("Hudi merge-on-read tables are not supported "
                         "(deltacommits present; copy-on-write only)")
    commits = sorted(f for f in timeline
                     if f.endswith(".commit") or f.endswith(".replacecommit"))
    if not commits:
        raise DaftNotFoundError(f"Hudi table has no completed commits: {table_uri}")
    # latest slice per file group: walk data files, parse hudi names
    # <fileId>_<writeToken>_<instantTime>.parquet
    latest: dict = {}
    replaced: set = set()
    for name in commits:
        with open(os.path.join(hoodie, name)) as f:
            try:
                commit = json.load(f)
            except json.JSONDecodeError:
                continue
        for pstats in (commit.get("partitionToWriteStats") or {}).values():
            for ws in pstats:
                path = ws.get("path")
                fid = ws.get("fileId")
                if path:
                    latest[fid or path] = path
        for part, groups in (commit.get("partitionToReplaceFileIds") or {}).items():
            for fid in groups:
                replaced.add(fid)
    files = [os.path.join(table_uri, p) for fid, p in latest.items()
             if fid not in replaced]
    files = [p for p in files if os.path.exists(p)]
    if not files:
        raise ValueError(f"Hudi table {table_uri} has no live files")
    schema = _schema_from_parquet(files[0])
    tasks = [ScanTask(p, FileFormat.PARQUET, schema, Pushdowns()) for p in files]
    return schema, tasks


# ---------------------------------------------------------------------------
# Iceberg writer (native manifests via io/avro.py)
# ---------------------------------------------------------------------------

_ARROW_TO_ICEBERG = [
    (pa.types.is_int64, "long"), (pa.types.is_int32, "int"),
    (pa.types.is_float64, "double"), (pa.types.is_float32, "float"),
    (pa.types.is_boolean, "boolean"), (pa.types.is_date, "date"),
    (pa.types.is_binary, "binary"), (pa.types.is_large_binary, "binary"),
    (pa.types.is_string, "string"), (pa.types.is_large_string, "string"),
]

_MANIFEST_ENTRY_SCHEMA = {
    "type": "record", "name": "manifest_entry", "fields": [
        {"name": "status", "type": "int"},
        {"name": "snapshot_id", "type": ["null", "long"]},
        {"name": "data_file", "type": {"type": "record", "name": "r2", "fields": [
            {"name": "content", "type": "int"},
            {"name": "file_path", "type": "string"},
            {"name": "file_format", "type": "string"},
            {"name": "partition", "type": {"type": "record", "name": "r102",
                                           "fields": []}},
            {"name": "record_count", "type": "long"},
            {"name": "file_size_in_bytes", "type": "long"},
        ]}},
    ]}

_MANIFEST_LIST_SCHEMA = {
    "type": "record", "name": "manifest_file", "fields": [
        {"name": "manifest_path", "type": "string"},
        {"name": "manifest_length", "type": "long"},
        {"name": "partition_spec_id", "type": "int"},
        {"name": "content", "type": "int"},
        {"name": "added_snapshot_id", "type": "long"},
    ]}


def _iceberg_type(t: pa.DataType) -> str:
    if pa.types.is_timestamp(t):
        return "timestamp"
    for pred, name in _ARROW_TO_ICEBERG:
        if pred(t):
            return name
    raise ValueError(f"no Iceberg type for arrow {t}")


def write_iceberg_table(table_uri: str, arrow_tables: List[pa.Table],
                        mode: str = "append") -> List[str]:
    """Native Iceberg v2 commit: data parquet files, a manifest for the new
    files, a manifest list (append keeps prior manifests), and a new
    metadata json published put-if-absent (O_EXCL) with version-hint update —
    the hadoop-catalog commit protocol. mode: append | overwrite | error.
    Reference: the write path behind daft's write_iceberg
    (daft/dataframe/dataframe.py), which delegates to pyiceberg; here the
    manifests are encoded natively by io/avro.py."""
    import time as _time
    import uuid as _uuid

    from .avro import encode_avro_bytes

    if mode not in ("append", "overwrite", "error"):
        raise ValueError(f"invalid mode {mode!r}")
    if not arrow_tables:
        raise ValueError("write_iceberg needs at least one partition")
    mdir = STORAGE.join(table_uri, "metadata")
    ddir = STORAGE.join(table_uri, "data")
    mdir_names = STORAGE.list_names(mdir)
    exists = any(f.endswith(".metadata.json") for f in mdir_names)
    if exists and mode == "error":
        raise FileExistsError(f"Iceberg table already exists: {table_uri}")
    STORAGE.makedirs(mdir)
    STORAGE.makedirs(ddir)

    prev_meta = None
    prev_version = 0
    prior_manifests: List[dict] = []
    if exists:
        prev_meta = json.loads(STORAGE.get(_iceberg_metadata_path(table_uri)))
        prev_version = max(
            (v for v in (_metadata_version_of(n) for n in mdir_names
                         if n.endswith(".metadata.json")) if v >= 0),
            default=0)
        if mode == "append":
            sid = prev_meta.get("current-snapshot-id")
            snap = next((s for s in (prev_meta.get("snapshots") or [])
                         if s.get("snapshot-id") == sid), None)
            if snap is not None and snap.get("manifest-list"):
                _, raw = _read_avro_any(
                    _iceberg_resolve(table_uri, snap["manifest-list"]))
                # v1 manifest_file records predate the 'content' field (and
                # may omit others): normalize so re-encoding under the v2
                # schema never sees None ints
                prior_manifests = [{
                    "manifest_path": r["manifest_path"],
                    "manifest_length": r.get("manifest_length") or 0,
                    "partition_spec_id": r.get("partition_spec_id") or 0,
                    "content": r.get("content") or 0,
                    "added_snapshot_id": r.get("added_snapshot_id") or 0,
                } for r in raw]
            elif snap is not None and snap.get("manifests"):
                # v1 inline manifest paths: lift into manifest_file records
                # so the appended table's view keeps the existing data
                for mp in snap["manifests"]:
                    resolved = _iceberg_resolve(table_uri, mp)
                    prior_manifests.append({
                        "manifest_path": mp,
                        "manifest_length": STORAGE.size(resolved),
                        "partition_spec_id": 0, "content": 0,
                        "added_snapshot_id": sid or 0})

    # random 63-bit id (the spec's convention): same-millisecond commits and
    # concurrent writers must never collide on snap-<id>.avro
    snapshot_id = int.from_bytes(os.urandom(8), "big") >> 1
    commit_ts = int(_time.time() * 1000)
    added: List[str] = []
    entries: List[dict] = []
    remote = STORAGE.is_remote(table_uri)
    # written URIs carry the table's real scheme; local keeps the file://
    # prefix the resolver strips (spec: absolute URIs in manifests)
    uri_base = str(table_uri).rstrip("/") if remote else f"file://{table_uri}"
    for t in arrow_tables:
        if t.num_rows == 0:
            continue
        rel = f"data/{_uuid.uuid4()}.parquet"
        full = STORAGE.join(table_uri, rel)
        size = write_parquet_any(full, t)
        added.append(full)
        entries.append({"status": 1, "snapshot_id": snapshot_id,
                        "data_file": {"content": 0,
                                      "file_path": f"{uri_base}/{rel}",
                                      "file_format": "PARQUET", "partition": {},
                                      "record_count": t.num_rows,
                                      "file_size_in_bytes": size}})
    manifest_rel = f"metadata/{_uuid.uuid4()}-m0.avro"
    manifest_bytes = encode_avro_bytes(_MANIFEST_ENTRY_SCHEMA, entries)
    STORAGE.put(STORAGE.join(table_uri, manifest_rel), manifest_bytes)
    mlist_records = list(prior_manifests) if mode == "append" else []
    mlist_records.append({
        "manifest_path": f"{uri_base}/{manifest_rel}",
        "manifest_length": len(manifest_bytes),
        "partition_spec_id": 0, "content": 0,
        "added_snapshot_id": snapshot_id})
    mlist_rel = f"metadata/snap-{snapshot_id}.avro"
    STORAGE.put(STORAGE.join(table_uri, mlist_rel),
                encode_avro_bytes(_MANIFEST_LIST_SCHEMA, mlist_records))

    schema_src = next((t for t in arrow_tables if t.num_rows), arrow_tables[0])
    fields = [{"id": i + 1, "name": f.name, "type": _iceberg_type(f.type),
               "required": False} for i, f in enumerate(schema_src.schema)]
    version = prev_version + 1
    meta = {
        "format-version": 2,
        "table-uuid": (prev_meta or {}).get("table-uuid", str(_uuid.uuid4())),
        "location": table_uri,
        "current-snapshot-id": snapshot_id,
        "snapshots": ((prev_meta or {}).get("snapshots") or []) + [{
            "snapshot-id": snapshot_id,
            "timestamp-ms": commit_ts,
            "manifest-list": f"{uri_base}/{mlist_rel}"}],
        "schemas": [{"schema-id": 0, "type": "struct", "fields": fields}],
        "current-schema-id": 0,
        "partition-specs": [{"spec-id": 0, "fields": []}],
    }
    meta_path = STORAGE.join(mdir, f"v{version}.metadata.json")
    # put-if-absent commit: a concurrent writer racing to the same version
    # loses (O_EXCL locally, conditional put on object stores)
    STORAGE.put_if_absent(meta_path, json.dumps(meta).encode())
    STORAGE.put(STORAGE.join(mdir, "version-hint.text"), str(version).encode())
    return added


# ---------------------------------------------------------------------------
# Delta Lake writer (native transactional commit)
# ---------------------------------------------------------------------------

_ARROW_TO_DELTA = [
    (pa.types.is_int64, "long"), (pa.types.is_int32, "integer"),
    (pa.types.is_int16, "short"), (pa.types.is_int8, "byte"),
    (pa.types.is_float64, "double"), (pa.types.is_float32, "float"),
    (pa.types.is_boolean, "boolean"), (pa.types.is_date, "date"),
    (pa.types.is_binary, "binary"), (pa.types.is_large_binary, "binary"),
    (pa.types.is_string, "string"), (pa.types.is_large_string, "string"),
]


def _delta_type(t: pa.DataType) -> str:
    if pa.types.is_timestamp(t):
        return "timestamp"
    if pa.types.is_decimal(t):
        return f"decimal({t.precision},{t.scale})"
    for pred, name in _ARROW_TO_DELTA:
        if pred(t):
            return name
    raise ValueError(f"no Delta Lake type for arrow {t}")


def _delta_schema_string(arrow_schema: pa.Schema) -> str:
    fields = [{"name": f.name, "type": _delta_type(f.type),
               "nullable": True, "metadata": {}} for f in arrow_schema]
    return json.dumps({"type": "struct", "fields": fields})


def write_deltalake_table(table_uri: str, arrow_tables: List[pa.Table],
                          mode: str = "append") -> List[str]:
    """Transactional Delta Lake write: data files + an atomic JSON commit.

    The commit uses the Delta protocol's put-if-absent contract on the next
    version file (O_EXCL locally, `If-None-Match: *` on object stores — a
    concurrent writer loses and raises), the same guarantee the reference
    gets from the deltalake client (daft/dataframe/dataframe.py
    write_deltalake). Works against local paths and s3:// uris alike; all
    bytes ride Storage/IOClient. mode: append | overwrite | error. Returns
    the added file paths."""
    import time as _time
    import uuid as _uuid

    if mode not in ("append", "overwrite", "error"):
        raise ValueError(f"invalid mode {mode!r}")
    if not arrow_tables:
        raise ValueError("write_deltalake needs at least one (possibly "
                         "empty) partition to derive the table schema")
    log_dir = STORAGE.join(table_uri, "_delta_log")
    log_names = STORAGE.list_names(log_dir)
    versions: List[int] = [int(f.split(".")[0]) for f in log_names
                           if f.endswith(".json")]
    # a checkpointed table whose older JSON commits were vacuumed is
    # still an existing table: the checkpoint carries its version
    if "_last_checkpoint" in log_names:
        lc = json.loads(STORAGE.get(STORAGE.join(log_dir, "_last_checkpoint")))
        versions.append(int(lc["version"]))
    exists = bool(versions)
    if exists and mode == "error":
        raise FileExistsError(f"Delta table already exists: {table_uri}")
    STORAGE.makedirs(log_dir)
    schema_src = next((t for t in arrow_tables if t.num_rows), arrow_tables[0])
    now_ms = int(_time.time() * 1000)
    actions: List[dict] = []
    version = 0
    if exists:
        version = max(versions) + 1
        if mode == "overwrite":
            base = str(table_uri).rstrip("/") + "/"
            for f in _delta_live_files(table_uri):
                p = f["path"]
                rel = (p[len(base):] if str(p).startswith(base)
                       else os.path.relpath(p, table_uri))
                actions.append({"remove": {
                    "path": rel, "deletionTimestamp": now_ms,
                    "dataChange": True}})
    else:
        actions.append({"protocol": {"minReaderVersion": 1,
                                     "minWriterVersion": 2}})
        actions.append({"metaData": {
            "id": str(_uuid.uuid4()),
            "format": {"provider": "parquet", "options": {}},
            "schemaString": _delta_schema_string(schema_src.schema),
            "partitionColumns": [],
            "configuration": {},
            "createdTime": now_ms,
        }})
    added = []
    for t in arrow_tables:
        if t.num_rows == 0:
            continue
        rel = f"part-{len(added):05d}-{_uuid.uuid4()}.parquet"
        full = STORAGE.join(table_uri, rel)
        size = write_parquet_any(full, t)
        actions.append({"add": {
            "path": rel, "partitionValues": {},
            "size": size, "modificationTime": now_ms,
            "dataChange": True,
        }})
        added.append(full)
    actions.append({"commitInfo": {"timestamp": now_ms,
                                   "operation": "WRITE",
                                   "operationParameters": {"mode": mode.upper()}}})
    commit_path = STORAGE.join(log_dir, f"{version:020d}.json")
    payload = "\n".join(json.dumps(a) for a in actions) + "\n"
    STORAGE.put_if_absent(commit_path, payload.encode())
    return added


def read_sql_arrow(sql: str, conn: Union[str, Callable[[], Any]],
                   params: Optional[tuple] = None) -> pa.Table:
    """Run `sql` through a DB-API connection and return an arrow table.

    `conn` is a sqlite URL/path ("sqlite:///path/db.sqlite" or a .db path) or
    a zero-arg callable returning a DB-API connection (the reference's
    create_connection factory)."""
    close_after = False
    if hasattr(conn, "cursor"):  # a live DB-API connection: borrow, don't close
        connection = conn
    elif callable(conn):
        connection = conn()
        close_after = True
    else:
        import sqlite3

        path = conn
        if path.startswith("sqlite://"):
            path = path[len("sqlite://"):]
            while path.startswith("/") and not os.path.exists(path) and os.path.exists(path.lstrip("/")):
                path = path.lstrip("/")
        connection = sqlite3.connect(path)
        close_after = True
    try:
        cur = connection.cursor()
        cur.execute(sql, params or ())
        names = [d[0] for d in cur.description]
        descr = list(cur.description)
        rows = cur.fetchall()
    finally:
        if close_after:
            connection.close()
    cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
    if rows:
        return pa.table(cols)
    # zero rows: recover column types from the DB-API description for drivers
    # that expose type codes (psycopg, mysql connectors, ...). sqlite3 never
    # fills description[1:] (only the name is set), so empty sqlite results
    # are unavoidably null-typed — documented limitation.
    return pa.table({d[0]: pa.array([], _dbapi_arrow_type(d)) for d in descr})


# longest-match-first: DATETIME/TIMESTAMP must win over the DATE substring
_SQL_TYPENAME_TO_ARROW = [
    ("DATETIME", pa.timestamp("us")), ("TIMESTAMP", pa.timestamp("us")),
    ("SMALLINT", pa.int64()), ("TINYINT", pa.int64()), ("BIGINT", pa.int64()),
    ("INTEGER", pa.int64()), ("INT", pa.int64()),
    ("VARBINARY", pa.binary()), ("BINARY", pa.binary()), ("BLOB", pa.binary()),
    ("VARCHAR", pa.string()), ("CHAR", pa.string()), ("TEXT", pa.string()),
    ("CLOB", pa.string()), ("STRING", pa.string()),
    ("DOUBLE", pa.float64()), ("FLOAT", pa.float64()), ("REAL", pa.float64()),
    ("NUMERIC", pa.float64()), ("DECIMAL", pa.float64()),
    ("BOOLEAN", pa.bool_()), ("BOOL", pa.bool_()),
    ("DATE", pa.date32()),
]


def _dbapi_arrow_type(descr_entry) -> pa.DataType:
    """Best-effort arrow type from a DB-API cursor.description entry's type
    code. Returns null for drivers that report no code (sqlite3)."""
    code = descr_entry[1] if len(descr_entry) > 1 else None
    if code is None:
        return pa.null()
    name = str(code).upper()
    for decl, at in _SQL_TYPENAME_TO_ARROW:
        if decl in name:
            return at
    return pa.null()


# ---------------------------------------------------------------------------
# Lance (via the optional `lance` package, reference daft/io/_lance.py:68 —
# the reference likewise delegates to the LanceDB client and raises when the
# extra dependency is missing; the data format itself is lance-internal)
# ---------------------------------------------------------------------------

def _import_lance():
    try:
        import lance
    except ImportError as e:
        raise ImportError(
            "read_lance/write_lance require the optional `lance` package "
            "(the reference ships it as the getdaft[lance] extra); it is not "
            "installed in this environment") from e
    return lance


def read_lance_scan(url: str, storage_options=None):
    """DataFrame over a LanceDB dataset: one FactoryScanTask per lance
    fragment, batches pulled through the fragment reader (reference:
    LanceDBScanOperator.to_scan_tasks, daft/io/_lance.py:97+)."""
    lance = _import_lance()

    from ..schema import Schema
    from .pyscan import FactoryScanTask, ScanOperator, from_scan_operator

    ds = lance.dataset(url, storage_options=storage_options)
    schema = Schema.from_arrow(ds.schema)

    class _LanceScanOperator(ScanOperator):
        def display_name(self):
            return f"LanceScanOperator({url})"

        def schema(self):
            return schema

        def can_absorb_select(self):
            return True  # fragment.to_batches honors a column projection

        def to_scan_tasks(self, pushdowns):
            for frag in ds.get_fragments():
                def factory(pd, _frag=frag):
                    cols = pd.columns if pd.columns is not None else None
                    return _frag.to_batches(columns=cols)

                yield FactoryScanTask(
                    factory, schema, pushdowns,
                    label=f"{url}#fragment-{frag.fragment_id}",
                    absorbs=("columns",))

    return from_scan_operator(_LanceScanOperator())


def write_lance_table(table_uri: str, arrow_tables, mode: str = "append"):
    """Write arrow tables as a lance dataset (reference: daft writes lance via
    `lance.write_dataset` in table_io.py). mode: append | overwrite | error."""
    import pyarrow as pa

    lance = _import_lance()
    if mode not in ("append", "overwrite", "error"):
        raise ValueError(f"unknown write_lance mode {mode!r}")
    tbl = pa.concat_tables([t for t in arrow_tables if t.num_rows]) \
        if any(t.num_rows for t in arrow_tables) else arrow_tables[0]
    import os
    exists = os.path.exists(table_uri)
    if mode == "error" and exists:
        raise FileExistsError(f"lance dataset already exists at {table_uri!r}")
    # lance rejects append when no dataset exists yet; first write creates
    lance_mode = {"append": "append" if exists else "create",
                  "overwrite": "overwrite", "error": "create"}[mode]
    ds = lance.write_dataset(tbl, table_uri, mode=lance_mode)
    paths = []
    for frag in ds.get_fragments():
        for df_ in frag.data_files():
            p = df_.path() if callable(getattr(df_, "path", None)) else df_.path
            paths.append(str(p))
    return paths
