"""Catalog readers: Delta Lake (native log parser) and DB-API SQL scans.

Reference role-equivalents:
- `read_deltalake` (daft/delta_lake/delta_lake_scan.py:26): the reference uses
  the deltalake client to list files; here the Delta transaction log is parsed
  directly — `_delta_log/*.json` add/remove actions fold into the live file
  set, which becomes parquet ScanTasks with per-file size + partition values,
  so pushdowns and pruning ride the normal scan layer.
- `read_sql` (daft/sql/sql_scan.py:35): executes a query through any DB-API
  connection (or a sqlite:// / file path shortcut) and materializes the result
  as arrow. Partitioning a SQL source by percentile bounds requires server
  round-trips; this host path reads in one shot like the reference's
  fallback (single ScanTask) mode.

Iceberg/Hudi/Lance need their manifest codecs (avro etc.) which are not in
this image; their entry points raise a clear error at api.py.
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, List, Optional, Union

import pyarrow as pa

from ..schema import Schema
from .scan import FileFormat, Pushdowns, ScanTask


def _delta_live_files(table_uri: str) -> List[dict]:
    """Fold the Delta transaction log into the set of live data files.

    Honors checkpoints: when _delta_log/_last_checkpoint exists, the add/remove
    state is seeded from the checkpoint parquet (single or multi-part) and only
    commits AFTER the checkpoint version are replayed — required for tables
    whose older JSON commits were vacuumed by log retention."""
    log_dir = os.path.join(table_uri, "_delta_log")
    if not os.path.isdir(log_dir):
        raise FileNotFoundError(f"not a Delta table (no _delta_log): {table_uri}")
    live: dict = {}
    start_after = -1
    lc_path = os.path.join(log_dir, "_last_checkpoint")
    if os.path.exists(lc_path):
        with open(lc_path) as f:
            lc = json.load(f)
        version = int(lc["version"])
        parts = int(lc.get("parts", 0) or 0)
        if parts:
            cp_files = [os.path.join(
                log_dir, f"{version:020d}.checkpoint.{i:010d}.{parts:010d}.parquet")
                for i in range(1, parts + 1)]
        else:
            cp_files = [os.path.join(log_dir, f"{version:020d}.checkpoint.parquet")]
        missing = [p for p in cp_files if not os.path.exists(p)]
        if missing:
            raise FileNotFoundError(
                f"Delta checkpoint v{version} referenced by _last_checkpoint is "
                f"missing files: {missing}")
        import pyarrow.parquet as papq

        for cp in cp_files:
            t = papq.read_table(cp, columns=["add", "remove"])
            for row in t.to_pylist():
                a, r = row.get("add"), row.get("remove")
                if a and a.get("path"):
                    live[a["path"]] = a
                elif r and r.get("path"):
                    live.pop(r["path"], None)
        start_after = version
    commits = sorted(f for f in os.listdir(log_dir) if f.endswith(".json"))
    commits = [c for c in commits if int(c.split(".")[0]) > start_after]
    if not commits and start_after < 0:
        raise FileNotFoundError(f"Delta table has no commits: {table_uri}")
    for name in commits:
        with open(os.path.join(log_dir, name)) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                action = json.loads(line)
                if "add" in action:
                    a = action["add"]
                    live[a["path"]] = a
                elif "remove" in action:
                    live.pop(action["remove"]["path"], None)
    return [dict(v, path=os.path.join(table_uri, k)) for k, v in live.items()]


def read_deltalake_scan(table_uri: str):
    """-> (Schema, [ScanTask]) for a local Delta Lake table."""
    import pyarrow.parquet as papq

    files = _delta_live_files(table_uri)
    if not files:
        raise ValueError(f"Delta table {table_uri} has no live files")
    from ..datatypes import DataType
    from ..schema import Field

    arrow_schema = papq.read_schema(files[0]["path"])
    fields = [Field(n, DataType.from_arrow(arrow_schema.field(n).type))
              for n in arrow_schema.names]
    # hive-style partition columns live in the log's partitionValues, not the files
    part_cols: List[str] = []
    for f in files:
        for k in (f.get("partitionValues") or {}):
            if k not in part_cols:
                part_cols.append(k)
    for k in part_cols:
        fields.append(Field(k, DataType.string()))
    schema = Schema(fields)
    tasks = []
    for f in files:
        tasks.append(ScanTask(
            f["path"], FileFormat.PARQUET, schema, Pushdowns(),
            size_bytes=f.get("size"),
            partition_values={k: (f.get("partitionValues") or {}).get(k)
                              for k in part_cols} or None,
        ))
    return schema, tasks


def read_sql_arrow(sql: str, conn: Union[str, Callable[[], Any]],
                   params: Optional[tuple] = None) -> pa.Table:
    """Run `sql` through a DB-API connection and return an arrow table.

    `conn` is a sqlite URL/path ("sqlite:///path/db.sqlite" or a .db path) or
    a zero-arg callable returning a DB-API connection (the reference's
    create_connection factory)."""
    close_after = False
    if hasattr(conn, "cursor"):  # a live DB-API connection: borrow, don't close
        connection = conn
    elif callable(conn):
        connection = conn()
        close_after = True
    else:
        import sqlite3

        path = conn
        if path.startswith("sqlite://"):
            path = path[len("sqlite://"):]
            while path.startswith("/") and not os.path.exists(path) and os.path.exists(path.lstrip("/")):
                path = path.lstrip("/")
        connection = sqlite3.connect(path)
        close_after = True
    try:
        cur = connection.cursor()
        cur.execute(sql, params or ())
        names = [d[0] for d in cur.description]
        descr = list(cur.description)
        rows = cur.fetchall()
    finally:
        if close_after:
            connection.close()
    cols = {n: [r[i] for r in rows] for i, n in enumerate(names)}
    if rows:
        return pa.table(cols)
    # zero rows: recover column types from the DB-API description for drivers
    # that expose type codes (psycopg, mysql connectors, ...). sqlite3 never
    # fills description[1:] (only the name is set), so empty sqlite results
    # are unavoidably null-typed — documented limitation.
    return pa.table({d[0]: pa.array([], _dbapi_arrow_type(d)) for d in descr})


# longest-match-first: DATETIME/TIMESTAMP must win over the DATE substring
_SQL_TYPENAME_TO_ARROW = [
    ("DATETIME", pa.timestamp("us")), ("TIMESTAMP", pa.timestamp("us")),
    ("SMALLINT", pa.int64()), ("TINYINT", pa.int64()), ("BIGINT", pa.int64()),
    ("INTEGER", pa.int64()), ("INT", pa.int64()),
    ("VARBINARY", pa.binary()), ("BINARY", pa.binary()), ("BLOB", pa.binary()),
    ("VARCHAR", pa.string()), ("CHAR", pa.string()), ("TEXT", pa.string()),
    ("CLOB", pa.string()), ("STRING", pa.string()),
    ("DOUBLE", pa.float64()), ("FLOAT", pa.float64()), ("REAL", pa.float64()),
    ("NUMERIC", pa.float64()), ("DECIMAL", pa.float64()),
    ("BOOLEAN", pa.bool_()), ("BOOL", pa.bool_()),
    ("DATE", pa.date32()),
]


def _dbapi_arrow_type(descr_entry) -> pa.DataType:
    """Best-effort arrow type from a DB-API cursor.description entry's type
    code. Returns null for drivers that report no code (sqlite3)."""
    code = descr_entry[1] if len(descr_entry) > 1 else None
    if code is None:
        return pa.null()
    name = str(code).upper()
    for decl, at in _SQL_TYPENAME_TO_ARROW:
        if decl in name:
            return at
    return pa.null()
