"""Minimal Apache Avro Object Container File codec (read + write).

Iceberg manifests and manifest lists are Avro OCF files; this image carries
no avro library, so the subset of the spec those need is implemented here:
records, unions, arrays, maps, strings, bytes, fixed, enums, all primitive
types, and the null/deflate block codecs. Schema resolution is writer-schema
only (no reader-schema evolution) — exactly what a manifest replay needs.

Reference role-equivalent: the iceberg-rust/avro dependency behind
/root/reference/daft/iceberg/iceberg_scan.py:84 (the reference delegates to
pyiceberg; here the format is decoded directly, like catalogs.py does for
the Delta transaction log).
"""

from __future__ import annotations

import io
import json
import os
import struct
import zlib
from typing import Any, Dict, List, Optional, Tuple

MAGIC = b"Obj\x01"


# ---------------------------------------------------------------------------
# decoding
# ---------------------------------------------------------------------------

class _Reader:
    __slots__ = ("buf", "pos")

    def __init__(self, data: bytes):
        self.buf = data
        self.pos = 0

    def read(self, n: int) -> bytes:
        b = self.buf[self.pos:self.pos + n]
        if len(b) != n:
            raise EOFError("truncated avro data")
        self.pos += n
        return b

    def read_long(self) -> int:
        shift = 0
        acc = 0
        while True:
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not (b & 0x80):
                break
            shift += 7
        return (acc >> 1) ^ -(acc & 1)  # zigzag

    def read_bytes(self) -> bytes:
        return self.read(self.read_long())

    def read_utf8(self) -> str:
        return self.read_bytes().decode("utf-8")


def _decode(r: _Reader, schema) -> Any:
    """Decode one value of `schema` (parsed JSON form) from r."""
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return None
        if t == "boolean":
            return r.read(1) == b"\x01"
        if t in ("int", "long"):
            return r.read_long()
        if t == "float":
            return struct.unpack("<f", r.read(4))[0]
        if t == "double":
            return struct.unpack("<d", r.read(8))[0]
        if t == "bytes":
            return r.read_bytes()
        if t == "string":
            return r.read_utf8()
        raise ValueError(f"unknown avro type {t!r}")
    if isinstance(schema, list):  # union
        idx = r.read_long()
        return _decode(r, schema[idx])
    t = schema["type"]
    if t == "record":
        return {f["name"]: _decode(r, f["type"]) for f in schema["fields"]}
    if t == "array":
        out = []
        while True:
            cnt = r.read_long()
            if cnt == 0:
                break
            if cnt < 0:
                cnt = -cnt
                r.read_long()  # block byte size, unused
            for _ in range(cnt):
                out.append(_decode(r, schema["items"]))
        return out
    if t == "map":
        out = {}
        while True:
            cnt = r.read_long()
            if cnt == 0:
                break
            if cnt < 0:
                cnt = -cnt
                r.read_long()
            for _ in range(cnt):
                k = r.read_utf8()  # NB: must read key BEFORE value (python
                out[k] = _decode(r, schema["values"])  # evaluates RHS first)
        return out
    if t == "fixed":
        return r.read(schema["size"])
    if t == "enum":
        return schema["symbols"][r.read_long()]
    # logical types / named references wrap the underlying type string
    return _decode(r, t)


def read_avro_file(path: str) -> Tuple[dict, List[dict]]:
    """-> (writer schema JSON, list of decoded records)."""
    with open(path, "rb") as f:
        data = f.read()
    return read_avro_bytes(data)


def read_avro_bytes(data: bytes) -> Tuple[dict, List[dict]]:
    r = _Reader(data)
    if r.read(4) != MAGIC:
        raise ValueError("not an avro object container file")
    meta: Dict[str, bytes] = {}
    while True:
        cnt = r.read_long()
        if cnt == 0:
            break
        if cnt < 0:
            cnt = -cnt
            r.read_long()
        for _ in range(cnt):
            k = r.read_utf8()
            meta[k] = r.read_bytes()
    sync = r.read(16)
    schema = json.loads(meta["avro.schema"].decode("utf-8"))
    codec = meta.get("avro.codec", b"null").decode("utf-8")
    records: List[dict] = []
    while r.pos < len(r.buf):
        n_items = r.read_long()
        size = r.read_long()
        block = r.read(size)
        if codec == "deflate":
            block = zlib.decompress(block, -15)
        elif codec != "null":
            raise ValueError(f"unsupported avro codec {codec!r}")
        if r.read(16) != sync:
            raise ValueError("avro sync marker mismatch")
        br = _Reader(block)
        for _ in range(n_items):
            records.append(_decode(br, schema))
    return schema, records


# ---------------------------------------------------------------------------
# encoding
# ---------------------------------------------------------------------------

class _Writer:
    __slots__ = ("out",)

    def __init__(self):
        self.out = io.BytesIO()

    def write(self, b: bytes) -> None:
        self.out.write(b)

    def write_long(self, v: int) -> None:
        # zigzag then varint; python's arithmetic >> keeps this exact for the
        # full 64-bit range
        u = ((v << 1) ^ (v >> 63)) & 0xFFFFFFFFFFFFFFFF
        while True:
            b = u & 0x7F
            u >>= 7
            if u:
                self.out.write(bytes([b | 0x80]))
            else:
                self.out.write(bytes([b]))
                break

    def write_bytes(self, b: bytes) -> None:
        self.write_long(len(b))
        self.out.write(b)

    def write_utf8(self, s: str) -> None:
        self.write_bytes(s.encode("utf-8"))


def _zigzag(v: int) -> int:
    return (v << 1) ^ (v >> 63)


def _encode(w: _Writer, schema, value) -> None:
    if isinstance(schema, str):
        t = schema
        if t == "null":
            return
        if t == "boolean":
            w.write(b"\x01" if value else b"\x00")
            return
        if t in ("int", "long"):
            w.write_long(int(value))
            return
        if t == "float":
            w.write(struct.pack("<f", float(value)))
            return
        if t == "double":
            w.write(struct.pack("<d", float(value)))
            return
        if t == "bytes":
            w.write_bytes(bytes(value))
            return
        if t == "string":
            w.write_utf8(value)
            return
        raise ValueError(f"unknown avro type {t!r}")
    if isinstance(schema, list):  # union: pick the first matching branch
        for i, branch in enumerate(schema):
            if _matches(branch, value):
                w.write_long(i)
                _encode(w, branch, value)
                return
        raise ValueError(f"no union branch of {schema} matches {value!r}")
    t = schema["type"]
    if t == "record":
        for f in schema["fields"]:
            _encode(w, f["type"], (value or {}).get(f["name"]))
        return
    if t == "array":
        items = list(value or [])
        if items:
            w.write_long(len(items))
            for it in items:
                _encode(w, schema["items"], it)
        w.write_long(0)
        return
    if t == "map":
        entries = dict(value or {})
        if entries:
            w.write_long(len(entries))
            for k, v in entries.items():
                w.write_utf8(k)
                _encode(w, schema["values"], v)
        w.write_long(0)
        return
    if t == "fixed":
        b = bytes(value)
        if len(b) != schema["size"]:
            raise ValueError("fixed size mismatch")
        w.write(b)
        return
    if t == "enum":
        w.write_long(schema["symbols"].index(value))
        return
    _encode(w, t, value)


def _matches(branch, value) -> bool:
    if branch == "null" or branch is None:
        return value is None
    if value is None:
        return False
    if isinstance(branch, str):
        return {
            "boolean": lambda v: isinstance(v, bool),
            "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
            "long": lambda v: isinstance(v, int) and not isinstance(v, bool),
            "float": lambda v: isinstance(v, float),
            "double": lambda v: isinstance(v, float),
            "bytes": lambda v: isinstance(v, (bytes, bytearray)),
            "string": lambda v: isinstance(v, str),
        }.get(branch, lambda v: True)(value)
    t = branch.get("type")
    if t == "record":
        return isinstance(value, dict)
    if t == "array":
        return isinstance(value, (list, tuple))
    if t == "map":
        return isinstance(value, dict)
    if t in ("fixed",):
        return isinstance(value, (bytes, bytearray))
    if t == "enum":
        return isinstance(value, str)
    return True


def encode_avro_bytes(schema: dict, records: List[dict],
                      meta: Optional[Dict[str, bytes]] = None) -> bytes:
    """Records as one null-codec OCF block (plenty for manifests)."""
    w = _Writer()
    w.write(MAGIC)
    m = {"avro.schema": json.dumps(schema).encode(), "avro.codec": b"null"}
    m.update(meta or {})
    w.write_long(len(m))
    for k, v in m.items():
        w.write_utf8(k)
        w.write_bytes(v)
    w.write_long(0)
    sync = os.urandom(16)
    w.write(sync)
    body = _Writer()
    for rec in records:
        _encode(body, schema, rec)
    data = body.out.getvalue()
    w.write_long(len(records))
    w.write_long(len(data))
    w.write(data)
    w.write(sync)
    return w.out.getvalue()


def write_avro_file(path: str, schema: dict, records: List[dict],
                    meta: Optional[Dict[str, bytes]] = None) -> None:
    with open(path, "wb") as f:
        f.write(encode_avro_bytes(schema, records, meta))
