"""Format readers: parquet / CSV / JSON → Table, honoring pushdowns.

Role-equivalent to the reference's src/daft-parquet/src/read.rs:615 (row-group
pruned, column-projected parquet read), daft-csv, and daft-json. The host
decode engine is pyarrow (Arrow C++); decoded batches are the staging source
for the device kernel layer.
"""

from __future__ import annotations

import os
from typing import Any, Dict, List, Optional

import pyarrow as pa
import pyarrow.csv as pacsv
import pyarrow.json as pajson
import pyarrow.parquet as papq

from ..schema import Schema
from ..stats import ColumnStats, TableStats, filter_may_match
from ..table import Table
from .scan import IO_STATS, Pushdowns


def _residual_filter(tbl: Table, pushdowns: Pushdowns) -> Table:
    if pushdowns.filters is not None:
        from ..expressions import Expression

        tbl = tbl.filter([Expression(pushdowns.filters)])
    if pushdowns.limit is not None:
        tbl = tbl.head(pushdowns.limit)
    return tbl


def _project_columns(names: List[str], pushdowns: Pushdowns) -> Optional[List[str]]:
    """Columns to read: the pushdown projection plus any filter dependencies."""
    if pushdowns.columns is None:
        return None
    need = [c for c in pushdowns.columns if c in names]
    if pushdowns.filters is not None:
        for c in _filter_columns(pushdowns.filters):
            if c in names and c not in need:
                need.append(c)
    return need


def _filter_columns(node) -> List[str]:
    from ..expressions import Column

    out: List[str] = []

    def walk(n):
        if isinstance(n, Column):
            if n.cname not in out:
                out.append(n.cname)
        for c in n.children():
            walk(c)

    walk(node)
    return out


def _drop_filter_only_columns(tbl: Table, pushdowns: Pushdowns) -> Table:
    if pushdowns.columns is None:
        return tbl
    keep = [c for c in pushdowns.columns if c in tbl.schema]
    return tbl.select_columns(keep)


# ---------------------------------------------------------------------------
# Parquet
# ---------------------------------------------------------------------------

def open_parquet_file(path: str) -> "papq.ParquetFile":
    """ParquetFile over a local path or a remote object: remote parquet reads
    through ObjectFile range-reads (footer + selected row groups only — the
    reference's native parquet path, read.rs:615 — never a full download)."""
    from .object_store import default_io_client, is_remote_path

    if is_remote_path(path):
        return papq.ParquetFile(default_io_client().open(path))
    return papq.ParquetFile(path)


def open_input_bytes(path: str):
    """Whole-object file handle for record-oriented formats (csv/json)."""
    import io as _io

    from .object_store import default_io_client, is_remote_path

    if is_remote_path(path):
        return _io.BytesIO(default_io_client().get(path))
    return path


def open_prefix_bytes(path: str, nbytes: int = 1 << 20):
    """A record-aligned PREFIX of the object for schema inference — a remote
    5GB csv must not be fully downloaded twice (once to infer, once to read).
    The ranged fetch is trimmed to the last newline so the parser never sees
    a truncated record; objects smaller than `nbytes` come back whole."""
    import io as _io

    from .object_store import default_io_client, is_remote_path

    if not is_remote_path(path):
        return path
    client = default_io_client()
    size = client.get_size(path)
    if size <= nbytes:
        return _io.BytesIO(client.get(path))
    chunk = client.get(path, (0, nbytes))
    head, nl, _tail = chunk.rpartition(b"\n")
    return _io.BytesIO(head + nl if nl else chunk)


def file_size(path: str) -> int:
    from .object_store import default_io_client, is_remote_path

    if is_remote_path(path):
        return default_io_client().get_size(path)
    return os.path.getsize(path)


def parquet_metadata(path: str) -> "papq.FileMetaData":
    return open_parquet_file(path).metadata


def row_group_stats(md, rg_idx: int, schema: Schema) -> TableStats:
    """Extract min/max/null_count bounds for one row group from parquet footer
    metadata (reference: read_parquet_metadata + daft-stats conversion)."""
    rg = md.row_group(rg_idx)
    cols: Dict[str, ColumnStats] = {}
    for ci in range(rg.num_columns):
        cc = rg.column(ci)
        name = cc.path_in_schema.split(".")[0]
        if name in cols:  # nested leaves: only top-level bounds are usable
            cols[name] = ColumnStats()
            continue
        st = cc.statistics
        if st is None or not st.has_min_max:
            cols[name] = ColumnStats(null_count=getattr(st, "null_count", None) if st else None)
        else:
            cols[name] = ColumnStats(st.min, st.max, st.null_count)
    return TableStats(cols, num_rows=rg.num_rows, size_bytes=rg.total_byte_size)


def plan_parquet_chunks(path: str, pushdowns: Optional[Pushdowns] = None,
                        schema: Optional[Schema] = None,
                        row_group_ids: Optional[List[int]] = None):
    """Everything ``read_parquet_table`` does BEFORE decoding: open the
    footer, project columns, prune row groups by stats, apply the
    limit-aware early stop. Returns ``(pf, chosen_row_groups, columns,
    file_schema)``. The chunk-wise streaming read and the whole-file read
    share this, so both choose exactly the same row groups."""
    pushdowns = pushdowns or Pushdowns()
    pf = open_parquet_file(path)
    md = pf.metadata
    IO_STATS.bump(files_opened=1)
    file_schema = Schema.from_arrow(pf.schema_arrow) if schema is None else schema
    columns = _project_columns(file_schema.field_names(), pushdowns)
    if columns is not None:
        IO_STATS.bump(columns_read=len(columns))
    else:
        IO_STATS.bump(columns_read=md.num_columns)

    candidates = list(range(md.num_row_groups)) if row_group_ids is None else list(row_group_ids)
    chosen: List[int] = []
    rows_taken = 0
    pruned = 0
    for rg in candidates:
        if pushdowns.filters is not None:
            st = row_group_stats(md, rg, file_schema)
            if not filter_may_match(pushdowns.filters, st):
                pruned += 1
                continue
        chosen.append(rg)
        rows_taken += md.row_group(rg).num_rows
        if pushdowns.limit is not None and pushdowns.filters is None and rows_taken >= pushdowns.limit:
            break
    IO_STATS.bump(row_groups_read=len(chosen), row_groups_pruned=pruned)
    return pf, chosen, columns, file_schema


def _finish_parquet_decode(arrow_tbl: "pa.Table", columns,
                           pushdowns: Pushdowns,
                           schema: Optional[Schema]) -> Table:
    """The decode tail shared by the whole-file and chunk-wise parquet
    reads (IO accounting, schema cast, residual filter, filter-only-column
    drop). ONE copy on purpose: the streaming executor's byte-identity
    invariant needs chunk-wise reads to concatenate to exactly the
    whole-file content, so any tweak here applies to both paths."""
    IO_STATS.bump(bytes_read=arrow_tbl.nbytes, rows_read=arrow_tbl.num_rows)
    tbl = Table.from_arrow(arrow_tbl)
    if schema is not None:
        want = [f for f in (schema.select(columns) if columns is not None else schema)]
        tbl = tbl.cast_to_schema(Schema(want))
    tbl = _residual_filter(tbl, pushdowns)
    return _drop_filter_only_columns(tbl, pushdowns)


def read_parquet_chunk(pf, rg: int, columns, pushdowns: Pushdowns,
                       schema: Optional[Schema]) -> Table:
    """Decode ONE planned row group, applying the same schema cast,
    residual filter, and filter-only-column drop as the whole-file read —
    chunk-wise reads concatenate to byte-identical content."""
    arrow_tbl = pf.read_row_group(rg, columns=columns, use_threads=True)
    return _finish_parquet_decode(arrow_tbl, columns, pushdowns, schema)


def read_parquet_table(path: str, pushdowns: Optional[Pushdowns] = None,
                       schema: Optional[Schema] = None,
                       row_group_ids: Optional[List[int]] = None) -> Table:
    """Read one parquet file with pushdowns: column projection at the IO layer,
    row-group pruning via footer stats, limit-aware early stop, residual filter
    on the decoded batch."""
    pushdowns = pushdowns or Pushdowns()
    pf, chosen, columns, file_schema = plan_parquet_chunks(
        path, pushdowns, schema, row_group_ids)

    if not chosen:
        empty = file_schema if columns is None else file_schema.select(columns)
        out = Table.empty(empty)
        return _drop_filter_only_columns(_residual_filter(out, pushdowns), pushdowns)

    arrow_tbl = pf.read_row_groups(chosen, columns=columns, use_threads=True)
    return _finish_parquet_decode(arrow_tbl, columns, pushdowns, schema)


# ---------------------------------------------------------------------------
# CSV
# ---------------------------------------------------------------------------

def read_csv_table(path: str, pushdowns: Optional[Pushdowns] = None,
                   schema: Optional[Schema] = None,
                   delimiter: str = ",", has_headers: bool = True,
                   double_quote: bool = True, quote: str = '"',
                   escape_char: Optional[str] = None,
                   comment: Optional[str] = None,
                   allow_variable_columns: bool = False,
                   column_names: Optional[List[str]] = None, **_kw) -> Table:
    pushdowns = pushdowns or Pushdowns()
    read_opts = pacsv.ReadOptions(
        column_names=column_names if not has_headers and column_names else None,
        autogenerate_column_names=(not has_headers and not column_names),
    )
    parse_opts = pacsv.ParseOptions(
        delimiter=delimiter, double_quote=double_quote, quote_char=quote,
        escape_char=escape_char or False,
    )
    convert_opts = pacsv.ConvertOptions()
    if schema is not None:
        convert_opts.column_types = {f.name: f.dtype.to_arrow() for f in schema
                                     if not f.dtype.is_null()}
    columns = None
    if schema is not None and pushdowns.columns is not None:
        columns = _project_columns(schema.field_names(), pushdowns)
        convert_opts.include_columns = columns
    arrow_tbl = pacsv.read_csv(open_input_bytes(path), read_options=read_opts,
                               parse_options=parse_opts, convert_options=convert_opts)
    IO_STATS.bump(files_opened=1, bytes_read=arrow_tbl.nbytes, rows_read=arrow_tbl.num_rows,
                  columns_read=arrow_tbl.num_columns)
    tbl = Table.from_arrow(arrow_tbl)
    if schema is None and pushdowns.columns is not None:
        columns = _project_columns(tbl.column_names, pushdowns)
        tbl = tbl.select_columns([c for c in columns if c in tbl.schema])
    if schema is not None:
        want = schema.select(columns) if columns is not None else schema
        tbl = tbl.cast_to_schema(want)
    tbl = _residual_filter(tbl, pushdowns)
    return _drop_filter_only_columns(tbl, pushdowns)


def infer_csv_schema(path: str, delimiter: str = ",", has_headers: bool = True,
                     column_names: Optional[List[str]] = None, **_kw) -> Schema:
    read_opts = pacsv.ReadOptions(
        column_names=column_names if not has_headers and column_names else None,
        autogenerate_column_names=(not has_headers and not column_names),
        block_size=1 << 20,
    )
    parse_opts = pacsv.ParseOptions(delimiter=delimiter)
    with pacsv.open_csv(open_prefix_bytes(path), read_options=read_opts, parse_options=parse_opts) as rd:
        batch = rd.read_next_batch()
    return Schema.from_arrow(batch.schema)


# ---------------------------------------------------------------------------
# JSON (newline-delimited)
# ---------------------------------------------------------------------------

def read_json_table(path: str, pushdowns: Optional[Pushdowns] = None,
                    schema: Optional[Schema] = None, **_kw) -> Table:
    """Streaming newline-delimited JSON reader (reference: the block-streamed
    daft-json reader, src/daft-json/src/read.rs): parses fixed-size blocks,
    DECODES ONLY the projected + filter columns (explicit_schema with
    unexpected fields ignored), applies the residual filter per block, and
    stops as soon as the limit is satisfied — a limit N query over a huge
    file parses only its head."""
    pushdowns = pushdowns or Pushdowns()
    columns = None
    parse_options = None
    if schema is not None:
        # decode exactly the known/projected fields: unexpected fields are
        # ignored (fields appearing only in later blocks would otherwise be
        # a parse error under block streaming)
        if pushdowns.columns is not None:
            columns = _project_columns(schema.field_names(), pushdowns)
            want_names = [c for c in columns if c in schema]
        else:
            want_names = schema.field_names()
        want_fields = [(c, schema[c].dtype.to_arrow()) for c in want_names]
        if want_fields:
            parse_options = pajson.ParseOptions(
                explicit_schema=pa.schema(want_fields),
                unexpected_field_behavior="ignore")
    want = None
    if schema is not None:
        want = (schema.select([c for c in columns if c in schema])
                if columns is not None else schema)
    limit = pushdowns.limit
    chunks = []
    rows = 0
    parsed = 0
    nbytes = 0
    with pajson.open_json(open_input_bytes(path),
                          parse_options=parse_options) as reader:
        for batch in reader:
            t = Table.from_arrow(pa.Table.from_batches([batch]))
            parsed += len(t)
            nbytes += batch.nbytes
            if want is not None:
                t = t.cast_to_schema(want)
            t = _residual_filter(t, pushdowns)
            chunks.append(t)
            rows += len(t)
            if limit is not None and rows >= limit:
                break
    if not chunks:
        tbl = Table.empty(want)
    else:
        tbl = Table.concat(chunks) if len(chunks) != 1 else chunks[0]
    if limit is not None and len(tbl) > limit:
        tbl = tbl.slice(0, limit)
    # rows_read = rows PARSED (pre-filter), matching the CSV/parquet readers
    IO_STATS.bump(files_opened=1, bytes_read=nbytes, rows_read=parsed,
                  columns_read=tbl.num_columns())
    return _drop_filter_only_columns(tbl, pushdowns)


def read_arrow_ipc_table(path: str, pushdowns: Optional[Pushdowns] = None,
                         schema: Optional[Schema] = None, **_kw) -> Table:
    """Arrow IPC (feather v2) reader — the engine's SPILL format. Spilled
    partitions re-materialize at memcpy speed through a memory-mapped file:
    no parquet decode, and the page cache serves repeated reads directly
    (reference role: the reference streams spilled state back through arrow
    buffers rather than re-encoding, daft-local-execution spill handling)."""
    pushdowns = pushdowns or Pushdowns()
    columns = None
    if schema is not None and pushdowns.columns is not None:
        columns = [c for c in _project_columns(schema.field_names(), pushdowns)
                   if c in schema]
    # NOT a context manager: the table's buffers are zero-copy views onto
    # the map; the file stays open until the buffers drop their references
    source = pa.memory_map(path)
    arrow_tbl = pa.ipc.open_file(source).read_all()
    if columns is not None:
        arrow_tbl = arrow_tbl.select(columns)
    tbl = Table.from_arrow(arrow_tbl)
    tbl = _residual_filter(tbl, pushdowns)
    if pushdowns.limit is not None and len(tbl) > pushdowns.limit:
        tbl = tbl.slice(0, pushdowns.limit)
    IO_STATS.bump(files_opened=1, bytes_read=arrow_tbl.nbytes,
                  rows_read=len(arrow_tbl), columns_read=tbl.num_columns())
    return _drop_filter_only_columns(tbl, pushdowns)


def infer_json_schema(path: str, **_kw) -> Schema:
    # read a prefix block only
    arrow_tbl = pajson.read_json(open_prefix_bytes(path), read_options=pajson.ReadOptions(block_size=1 << 20))
    return Schema.from_arrow(arrow_tbl.schema)
