"""Unity Catalog client (reference: daft/unity_catalog/unity_catalog.py).

Unity tables are Delta Lake tables behind a REST catalog: the client resolves
a three-part name to the table's storage location, and reading goes through
the native Delta log replay (`read_deltalake`). Like the reference, the REST
client itself is the optional `unitycatalog` package — absent here, so the
HTTP calls go through a minimal urllib shim against the same
`/api/2.1/unity-catalog/` endpoints (self-hostable OSS server), keeping the
public surface identical without the dependency.
"""

from __future__ import annotations

import dataclasses
import json
import urllib.parse
import urllib.request
from typing import List, Optional


@dataclasses.dataclass(frozen=True)
class UnityCatalogTable:
    """Resolved Unity table: its storage location (reference:
    UnityCatalogTable dataclass; the reference additionally carries an
    io_config of temporary credentials — local/zero-egress builds read the
    location directly)."""

    table_uri: str


class UnityCatalog:
    """Client for a Unity Catalog server (Databricks-hosted or the OSS
    `unitycatalog` server). `load_table` resolves a `catalog.schema.table`
    name to a UnityCatalogTable, which `read_deltalake` accepts."""

    def __init__(self, endpoint: str, token: Optional[str] = None):
        self._base = endpoint.rstrip("/") + "/api/2.1/unity-catalog/"
        self._token = token

    def _get(self, path: str, params: Optional[dict] = None) -> dict:
        url = self._base + path
        if params:
            url += "?" + urllib.parse.urlencode(
                {k: v for k, v in params.items() if v is not None})
        req = urllib.request.Request(url)
        if self._token:
            req.add_header("Authorization", f"Bearer {self._token}")
        with urllib.request.urlopen(req, timeout=30) as resp:
            return json.loads(resp.read().decode("utf-8"))

    def _paginate(self, path: str, key: str, params: Optional[dict] = None) -> List[str]:
        params = dict(params or {})
        out: List[str] = []
        token = None
        while True:
            if token:
                params["page_token"] = token
            body = self._get(path, params)
            for item in body.get(key) or []:
                out.append(item["name"])
            token = body.get("next_page_token")
            if not token:
                return out

    def list_catalogs(self) -> List[str]:
        return self._paginate("catalogs", "catalogs")

    def list_schemas(self, catalog_name: str) -> List[str]:
        return [f"{catalog_name}.{s}" for s in self._paginate(
            "schemas", "schemas", {"catalog_name": catalog_name})]

    def list_tables(self, schema_name: str) -> List[str]:
        catalog, schema = schema_name.split(".", 1)
        return [f"{schema_name}.{t}" for t in self._paginate(
            "tables", "tables",
            {"catalog_name": catalog, "schema_name": schema})]

    def load_table(self, table_name: str) -> UnityCatalogTable:
        body = self._get(f"tables/{urllib.parse.quote(table_name)}")
        loc = body.get("storage_location")
        if not loc:
            raise ValueError(
                f"Unity table {table_name!r} has no storage_location "
                f"(only external/managed tables with a location are readable)")
        return UnityCatalogTable(table_uri=loc)
