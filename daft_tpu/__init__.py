"""daft_tpu: a TPU-native distributed dataframe / query engine.

A ground-up redesign of the capabilities of the reference engine (Daft) for TPU
hardware: lazy DataFrame + SQL over an Arrow-backed columnar core, with the hot
execution path compiled to jax.jit/XLA kernels on HBM-resident device arrays, and
partition parallelism mapped onto a jax.sharding Mesh (shuffles = all_to_all over ICI).
"""

from .datatypes import DataType, TypeKind
from .errors import (
    DaftError,
    DaftIOError,
    DaftNotFoundError,
    DaftOverloadedError,
    DaftResourceError,
    DaftSchemaError,
    DaftTimeoutError,
    DaftTransientError,
    DaftTypeError,
    DaftValueError,
)
from . import faults
from .schema import Field, Schema
from .series import Series

__version__ = "0.1.0"

__all__ = [
    "DataType",
    "TypeKind",
    "Field",
    "Schema",
    "Series",
    "DaftError",
    "DaftTypeError",
    "DaftValueError",
    "DaftSchemaError",
    "DaftNotFoundError",
    "DaftIOError",
    "DaftOverloadedError",
    "DaftResourceError",
]


# The full public API (DataFrame, col, lit, udf, read_*, sql, context) lives in api.py.
try:
    from .api import *  # noqa: F401,F403
    from .api import __all__ as _api_all

    __all__ += list(_api_all)
except ModuleNotFoundError as _e:  # only tolerate api.py itself being absent (bootstrap)
    if _e.name != f"{__name__}.api":
        raise

# Registers the image.* / url.* kernels (SQL and Function("image.decode")-style
# callers need them even before any expression namespace property is touched).
from . import multimodal  # noqa: E402,F401

# The sql SUBMODULE shares its name with the sql() entry point: the first
# REAL submodule import (api.sql does it lazily) rebinds the package
# attribute to the module, breaking every later daft_tpu.sql("SELECT ...").
# `from . import sql` cannot force that import here — the package already
# has a `sql` attribute (the function, from `from .api import *` above), so
# the from-list machinery skips the submodule entirely. importlib imports
# it for real; re-pinning the function afterwards makes the attribute
# stable because later submodule imports hit sys.modules and never setattr.
import importlib as _importlib  # noqa: E402

_importlib.import_module(f"{__name__}.sql")
from .api import sql  # noqa: E402,F401

from .viz import register_viz_hook  # noqa: E402,F401

__all__ += ["register_viz_hook"]
