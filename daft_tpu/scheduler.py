"""Partition-task scheduling: explicit tasks + a bounded dispatch loop.

Role-equivalent to the reference's task layer: `PartitionTask`
(`daft/execution/execution_step.py:31-166` — one unit of per-partition work
with its resource request), the PyRunner admission/dispatch loop
(`daft/runners/pyrunner.py:352-370`), and the RayRunner's dynamic backlog of
`cores + max_task_backlog` in-flight tasks (`ray_runner.py:504-685`). The TPU
build keeps the same structure on one host: tasks are dispatched to a thread
pool while the in-flight window has room, results are yielded in task order,
and a task's resource request is admitted before dispatch and released when
its work (or cancellation) finishes.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Iterator, Optional

from .micropartition import MicroPartition
from .obs.log import current_query_id, query_context

# marks threads currently executing a dispatched partition task: the scan
# prefetcher uses this to stand down on pool workers (the dispatch window
# already overlaps their reads; a prefetch future would only add a
# worker-to-worker handoff) and to keep io_wait_ns meaning CONSUMER-thread
# blocked time
_WORKER_TL = threading.local()


def on_pool_worker() -> bool:
    return getattr(_WORKER_TL, "active", False)


# process-wide count of dispatched-but-unfinished partition tasks: the
# health snapshot's view of the scheduler's in-flight window
_inflight_lock = threading.Lock()
_inflight = 0


def inflight_tasks() -> int:
    with _inflight_lock:
        return _inflight


def _inflight_add(n: int) -> None:
    global _inflight
    with _inflight_lock:
        _inflight += n


def _result_bytes(out) -> int:
    """Ledger charge for a parked task output — loaded partitions only, so
    accounting never triggers IO or forces a deferred op. Some dispatch
    users (exchange split stages) return a LIST of partitions per task."""
    if isinstance(out, MicroPartition):
        parts = (out,)
    elif isinstance(out, (list, tuple)):
        parts = tuple(p for p in out if isinstance(p, MicroPartition))
    else:
        return 0
    total = 0
    for p in parts:
        if p.is_loaded():
            b = p.size_bytes()
            total += b or 0
    return total


class DispatchBackend:
    """Where a map-class partition task's work actually runs. The implicit
    default backend is the in-process pool (dispatch() submits run_task to
    ``ctx.pool()``); attaching an object with this shape to
    ``ExecutionContext.dist_backend`` (dist/supervisor.WorkerPool) routes
    eligible tasks to worker PROCESSES instead — through the same
    dispatch window, ``_run_with_retry``, deadline, and cancellation
    machinery, because the backend call happens INSIDE the task function
    the in-process pool runs."""

    def capacity(self) -> int:  # concurrent tasks the backend can absorb
        raise NotImplementedError

    def try_execute(self, op, part, ctx, op_name: str, seq: int):
        """Execute one map task remotely; return (out, rows, wall_ns), or
        None when the task is ineligible / the backend is degraded (the
        caller runs it in-process). Raises the task's terminal error.

        Dispatch locality: a backend MAY inspect the partition for a
        placement hint — a peer-backed shuffle partition
        (dist/peerplane.peer_preference) prefers the workers already
        hosting its piece bytes, turning peer fetches into local store
        reads. The hint is advisory: any ready worker remains a legal
        target, so preference never blocks progress."""
        raise NotImplementedError

    # Peer-shuffle extension (dist/peerplane.py) — OPTIONAL: ShuffleOp
    # probes for these with getattr and keeps the star path when absent.
    #   execute_fanout(part, spec, ctx, op_name, seq)
    #       -> (wid, (host, port), metas) | None
    #     Ship one source partition as a fanout task: the worker splits it
    #     and HOSTS the pieces on its piece-server; metas are
    #     (bucket, rows, nbytes, crc) location entries. None = declined
    #     (caller splits driver-side, byte-identical).
    #   peer_ready() -> bool      # any ready worker serving pieces?
    #   new_shuffle_id() -> int   # unique per shuffle, scopes piece keys
    #   peer_token() -> bytes     # transport auth token for peer fetches
    #   drop_shuffles(sids)       # fleet-wide piece drop at query finish


def run_map_task(op, part, ctx, op_name: str, seq: int):
    """One map-class partition execution, routed through the context's
    dispatch backend when present and willing, in-process otherwise.
    Returns ``(out_partition, rows, wall_ns)`` where wall_ns is the real
    work time (the worker's own measurement on the remote path).

    The remote path runs under a driver-side ``dist.remote`` phase span:
    the backend stamps its submit -> sent -> reply split onto it and
    splices the worker's telemetry fragment under it, so remote queue/
    transport time stays visible even when a worker's fragment is lost
    (the span is driver-local truth, not worker-reported)."""
    backend = getattr(ctx, "dist_backend", None)
    if backend is not None:
        prof = ctx.stats.profiler
        sp = prof.begin("dist.remote", op=op_name, part=seq,
                        kind="phase") if prof.armed else None
        try:
            res = backend.try_execute(op, part, ctx, op_name, seq)
        except BaseException:
            if sp is not None:
                prof.end(sp)  # a remote error is still a remote execution
            raise
        if sp is not None:
            # a decline (ineligible task / degraded pool) was not a
            # remote execution: close the span unrecorded so profiles
            # never show phantom remote phases
            (prof.end if res is not None else prof.cancel)(sp)
        if res is not None:
            return res
    t0 = time.perf_counter_ns()
    out = op.map_partition(part, ctx)
    return out, None, time.perf_counter_ns() - t0


def _run_with_retry(task: "PartitionTask", ctx) -> MicroPartition:
    """Per-task transient retry: a partition task that raises
    DaftTransientError — e.g. an injected io.get/scan.read fault that
    exhausted the IO-layer's own retries — re-runs through the shared
    RetryPolicy up to ``cfg.task_retry_attempts`` extra times instead of
    failing the whole query on the first transient. Cancellation and the
    query deadline are re-checked before every re-attempt; retries are
    counted in RuntimeStats (``task_retries``) and surface in the
    QueryRecord's event rollup."""
    extra = max(0, getattr(ctx.cfg, "task_retry_attempts", 0))
    if extra == 0:
        return task.run()
    from .errors import DaftTransientError
    from .execution import QueryCancelledError
    from .io.object_store import RetryPolicy
    from .obs.log import get_logger

    tries = [0]

    def attempt() -> MicroPartition:
        if tries[0]:
            if ctx.stats.is_cancelled():
                raise QueryCancelledError(
                    f"query cancelled (retrying {task.op_name})")
            ctx.check_deadline()
            ctx.stats.bump("task_retries")
            get_logger("scheduler").warning(
                "task_retry", op=task.op_name, seq=task.seq,
                attempt=tries[0])
        tries[0] += 1
        return task.run()

    return RetryPolicy(
        attempts=extra + 1,
        backoff_s=getattr(ctx.cfg, "task_retry_backoff_s", 0.05),
        retryable=(DaftTransientError,)).run(attempt)


def _await_result(task: "PartitionTask", fut, ctx) -> MicroPartition:
    """Resolve a head-of-line task future, attributing blocked time to the
    dispatcher (dispatch_wait_ns, and the queue_wait phase of the pulling
    op's span) so the io_wait-vs-compute split can tell a starved pipeline
    from a compute-bound one. A task cancelled from outside (the serving
    runtime cancelling a shed/cancelled query's queued work) never ran:
    its reservations are returned here and the wait surfaces as query
    cancellation, not a raw concurrent.futures error."""
    from concurrent.futures import CancelledError

    from .execution import QueryCancelledError

    try:
        if fut.done():
            out = fut.result()
        else:
            t0 = time.perf_counter_ns()
            try:
                out = fut.result()
            finally:
                ctx.stats.dispatch_wait(time.perf_counter_ns() - t0)
        if task.held_bytes:
            # the output leaves the dispatch window: it is the consumer's
            # working unit now (the documented one-unit slack), not parked
            # between-steps memory
            ctx.ledger.exec_done(task.held_bytes)
            task.held_bytes = 0
        return out
    except CancelledError:
        _inflight_add(-1)
        if task.resource_request:
            ctx.accountant.release(task.resource_request)
        progress = getattr(ctx, "progress", None)
        if progress is not None:
            progress.task_finished()
        raise QueryCancelledError(
            "query cancelled (queued task cancelled)") from None


class PartitionTask:
    """One unit of per-partition work: a partition, the function to run on
    it, and the resource request the accountant must admit first.
    ``span_token``/``submit_ns`` carry the dispatching thread's profiler
    context across the pool hop (set by dispatch when profiling is armed);
    ``query_id`` carries the ALWAYS-ON structured-log query context the
    same way, so worker-side log lines stay attributed."""

    __slots__ = ("partition", "fn", "resource_request", "op_name", "seq",
                 "span_token", "submit_ns", "query_id", "held_bytes")

    def __init__(self, partition: MicroPartition, fn: Callable,
                 resource_request=None, op_name: str = "task", seq: int = 0):
        self.partition = partition
        self.fn = fn
        self.resource_request = resource_request
        self.op_name = op_name
        self.seq = seq
        self.span_token = None
        self.submit_ns = 0
        self.query_id = None
        # ledger exec_inflight charge for this task's materialized output
        # while it waits in the dispatch window (set by run_task on
        # success, settled when the consumer pulls — or at teardown)
        self.held_bytes = 0

    def run(self) -> MicroPartition:
        return self.fn(self.partition)

    def __repr__(self) -> str:
        return f"PartitionTask({self.op_name}#{self.seq})"


def dispatch(tasks: Iterator[PartitionTask], ctx,
             window: Optional[int] = None) -> Iterator[MicroPartition]:
    """Run tasks on the context's worker pool with a bounded in-flight window,
    yielding results IN TASK ORDER.

    - window defaults to `num_workers + max_task_backlog` (reference:
      RayRunner's `cores + max_task_backlog` dynamic dispatch bound).
    - a task's resource_request is admitted on the DISPATCH thread (so
      admitted tasks always hold a worker and make progress) and released by
      the worker when the task finishes — or by the dispatcher if a queued
      task is cancelled before it ever ran.
    - cancellation is honored between dispatches.
    - on a BUDGETED query the window exerts backpressure: materialized
      task outputs parked behind the head-of-line task are working-set
      memory (MemoryLedger.exec_inflight), so while they exceed their
      budget slice (budget/4 — the same share the streaming channels get)
      no new task is submitted and the head is drained instead. The head
      task always runs, so a single oversized partition stalls the window,
      never the query — partition-granular backpressure, the coarse
      cousin of the streaming channels' morsel-granular byte cap.
    """
    from .execution import QueryCancelledError

    if window is None:
        backlog = ctx.cfg.max_task_backlog
        if backlog < 0:  # auto: one backlog slot per worker
            backlog = ctx.num_workers
        window = ctx.num_workers + backlog
    window = max(1, window)
    budget = getattr(ctx, "memory_budget", None)
    exec_cap = None if budget is None else max(1, budget // 4)
    pool = ctx.pool()
    pending: deque = deque()
    # the live-progress tracker (obs/cluster.QueryProgress) counts this
    # query's dispatched-but-unfinished tasks; O(1) per task, absent when
    # the plan ran through execute_plan without one (direct tests)
    progress = getattr(ctx, "progress", None)

    def run_task(task: PartitionTask) -> MicroPartition:
        _WORKER_TL.active = True
        prof = ctx.stats.profiler
        sp = None
        # the dispatching thread's query binds on this worker for the
        # task's duration: log lines from worker-side work carry it
        qctx = query_context(task.query_id)
        qctx.__enter__()
        if prof.armed:
            # adopt the dispatching thread's span context, then open this
            # task's worker-side op span — background work is attributed to
            # the op that caused it, and queue/dispatch wait (submit ->
            # worker start) is a phase, not lost time
            act = prof.activate(task.span_token)
            act.__enter__()
            sp = prof.begin(task.op_name, op=task.op_name, part=task.seq)
            if task.submit_ns:
                sp.add_phase("queue_wait",
                             time.perf_counter_ns() - task.submit_ns)
        else:
            act = None
        try:
            out = _run_with_retry(task, ctx)
            held = _result_bytes(out)
            if held:
                # the materialized output now waits in `pending` behind the
                # head-of-line task: charge it to the query's working set
                # (MemoryLedger.exec_inflight) so pipeline-breaker spill
                # decisions see the partition-granular path's real
                # between-steps memory — the streaming path's bounded
                # channels charge stream_inflight instead
                task.held_bytes = held
                # daftlint: ledger-escape settled-by=_await_result,_settle
                ctx.ledger.exec_started(held)
            return out
        finally:
            _WORKER_TL.active = False
            if sp is not None:
                prof.end(sp)
            if act is not None:
                act.__exit__(None, None, None)
            qctx.__exit__(None, None, None)
            # drop the input partition as soon as the work is done — the
            # result may wait in `pending` behind a slow head-of-line task,
            # and holding input + output would double peak partition memory
            task.partition = None
            if task.resource_request:
                ctx.accountant.release(task.resource_request)
            _inflight_add(-1)
            if progress is not None:
                progress.task_finished()

    prof = ctx.stats.profiler
    try:
        for task in tasks:
            if ctx.stats.is_cancelled():
                raise QueryCancelledError(
                    f"query cancelled (at {task.op_name})")
            ctx.check_deadline()
            if task.resource_request:
                ctx.accountant.admit(task.resource_request)
            if prof.armed:
                task.span_token = prof.capture()
                task.submit_ns = time.perf_counter_ns()
            task.query_id = current_query_id()
            _inflight_add(1)
            if progress is not None:
                progress.task_started()
            pending.append((task, pool.submit(run_task, task)))
            while len(pending) >= window or (
                    exec_cap is not None and pending
                    and ctx.ledger.exec_inflight > exec_cap):
                if exec_cap is not None and len(pending) < window \
                        and ctx.ledger.exec_inflight > exec_cap:
                    ctx.stats.bump("dispatch_backpressure_stalls")
                yield _await_result(*pending.popleft(), ctx)
        while pending:
            # the deadline stays cooperative through the drain: in-flight
            # results are yielded, but an expired budget stops the query at
            # the next partition boundary instead of finishing the backlog
            # (check_deadline is also the barrier where async-spill writer
            # errors surface on the dispatching thread)
            ctx.check_deadline()
            yield _await_result(*pending.popleft(), ctx)
    finally:
        for task, fut in pending:
            # a queued task that never ran still holds its admission
            # reservation: return it, or a later admit() waits forever
            if fut.cancel():
                _inflight_add(-1)
                if task.resource_request:
                    ctx.accountant.release(task.resource_request)
                if progress is not None:
                    progress.task_finished()
            else:
                # running or completed but never pulled (early close): its
                # parked-output ledger charge settles when the task is done
                # — fires immediately for already-done futures
                def _settle(f, t=task):
                    if t.held_bytes:
                        ctx.ledger.exec_done(t.held_bytes)
                        t.held_bytes = 0

                fut.add_done_callback(_settle)
