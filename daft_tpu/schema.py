"""Field and Schema (reference: daft/logical/schema.py, src/daft-core/src/schema)."""

from __future__ import annotations

from typing import Dict, Iterator, List, Optional

import pyarrow as pa

from .datatypes import DataType


class Field:
    __slots__ = ("name", "dtype", "metadata")

    def __init__(self, name: str, dtype: DataType, metadata: Optional[dict] = None):
        self.name = name
        self.dtype = dtype
        self.metadata = metadata or {}

    def rename(self, name: str) -> "Field":
        return Field(name, self.dtype, self.metadata)

    def with_dtype(self, dtype: DataType) -> "Field":
        return Field(self.name, dtype, self.metadata)

    def __eq__(self, other) -> bool:
        return isinstance(other, Field) and self.name == other.name and self.dtype == other.dtype

    def __hash__(self) -> int:
        return hash((self.name, self.dtype))

    def __repr__(self) -> str:
        return f"Field({self.name!r}, {self.dtype!r})"


class Schema:
    """Ordered mapping name → Field. Duplicate names are rejected."""

    __slots__ = ("_fields",)

    def __init__(self, fields: List[Field]):
        names = [f.name for f in fields]
        if len(set(names)) != len(names):
            dup = sorted({n for n in names if names.count(n) > 1})
            raise ValueError(f"duplicate field names in schema: {dup}")
        self._fields: Dict[str, Field] = {f.name: f for f in fields}

    # --- constructors -----------------------------------------------------
    @staticmethod
    def from_pairs(pairs) -> "Schema":
        return Schema([Field(n, dt) for n, dt in (pairs.items() if isinstance(pairs, dict) else pairs)])

    @staticmethod
    def from_arrow(schema: pa.Schema) -> "Schema":
        return Schema([Field(f.name, DataType.from_arrow(f.type)) for f in schema])

    @staticmethod
    def empty() -> "Schema":
        return Schema([])

    # --- accessors --------------------------------------------------------
    def field_names(self) -> List[str]:
        return list(self._fields)

    @property
    def column_names(self) -> List[str]:
        return list(self._fields)

    def fields(self) -> List[Field]:
        return list(self._fields.values())

    def __getitem__(self, name: str) -> Field:
        if name not in self._fields:
            raise KeyError(f"column {name!r} not found in schema; available: {self.field_names()}")
        return self._fields[name]

    def __contains__(self, name: str) -> bool:
        return name in self._fields

    def __len__(self) -> int:
        return len(self._fields)

    def __iter__(self) -> Iterator[Field]:
        return iter(self._fields.values())

    def index(self, name: str) -> int:
        for i, n in enumerate(self._fields):
            if n == name:
                return i
        raise KeyError(name)

    # --- ops --------------------------------------------------------------
    def to_arrow(self) -> pa.Schema:
        return pa.schema([pa.field(f.name, f.dtype.to_arrow()) for f in self])

    def union(self, other: "Schema") -> "Schema":
        return Schema(self.fields() + [f for f in other if f.name not in self])

    def non_distinct_union(self, other: "Schema") -> "Schema":
        out = list(self.fields())
        for f in other:
            if f.name not in self:
                out.append(f)
        return Schema(out)

    def apply_hints(self, hints: "Schema") -> "Schema":
        return Schema([hints[f.name] if f.name in hints else f for f in self])

    def select(self, names: List[str]) -> "Schema":
        return Schema([self[n] for n in names])

    def rename(self, mapping: Dict[str, str]) -> "Schema":
        return Schema([f.rename(mapping.get(f.name, f.name)) for f in self])

    def __eq__(self, other) -> bool:
        return isinstance(other, Schema) and self.fields() == other.fields()

    def __hash__(self) -> int:
        return hash(tuple(self.fields()))

    def __repr__(self) -> str:
        inner = ", ".join(f"{f.name}: {f.dtype!r}" for f in self)
        return f"Schema({inner})"

    def _truncated_table_string(self) -> str:
        parts = [f"{f.name} ({f.dtype!r})" for f in self]
        return " | ".join(parts)

    def short_repr(self, max_fields: int = 6) -> str:
        parts = [f"{f.name}" for f in self]
        if len(parts) > max_fields:
            parts = parts[:max_fields] + [f"... +{len(parts) - max_fields}"]
        return ", ".join(parts)
