# daftlint: migrated
"""Always-on QueryLog: a bounded ring of QueryRecords, one per completed
plan execution.

``execution.execute_plan`` appends a record on EVERY completion — success,
DaftError, deadline kill, cancellation, or an abandoned stream — built
exclusively from data the stats stack already collected (RuntimeStats
counters/op rollups, the MemoryLedger snapshot, the ExecutionConfig
snapshot), so the steady-state cost is one dict build + ring append per
query and passes the same zero-allocation-style guard test the DISARMED
profiler does (tests/test_flight_recorder.py).

Notes on semantics:

- One record per *plan execution*: an AQE query finishes one execute_plan
  per stage and logs one record per stage (matching the
  ``daft_tpu_queries_total`` metric); counters are cumulative across the
  stages of one stats handle.
- Result-cache hits never reach execute_plan and are not recorded — the
  log is a record of executions, not lookups.
- ``plan_fingerprint`` is a stable hash of the physical plan's display
  tree: the slow-query auto-capture path uses it to arm the profiler for
  the NEXT run of the same plan shape.
"""

from __future__ import annotations

import dataclasses
import hashlib
import threading
import time
from collections import deque
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["RECORD_SCHEMA_VERSION", "QueryLog", "QUERY_LOG", "build_record",
           "plan_signature", "config_delta", "validate_record",
           "OUTCOMES", "DEFAULT_DEPTH"]

RECORD_SCHEMA_VERSION = 1
DEFAULT_DEPTH = 256

OUTCOMES = ("ok", "error", "timeout", "cancelled", "abandoned", "shed")

# RuntimeStats counters surfaced as the record's resilience-event rollup
_EVENT_COUNTERS = (
    "device_breaker_trips", "device_breaker_reopens",
    "device_breaker_recoveries", "collective_breaker_trips",
    "collective_breaker_reopens", "collective_breaker_recoveries",
    "faults_injected", "degraded_completions", "deadline_expired",
    "prefetch_throttled", "preload_throttled", "spill_write_failures",
    "task_retries", "dispatch_backpressure_stalls",
    "task_redispatches", "worker_losses", "dist_local_fallbacks",
    "corruption_detected", "partitions_recomputed", "lineage_truncated",
    "spill_disk_full", "tasks_speculated", "speculation_wins",
    "telemetry_dropped", "telemetry_truncated",
    "peer_fetches", "peer_refetches", "workers_drained",
    "batches_formed", "batch_flushes_timer", "batch_rows_padded",
    "segment_fallbacks",
    "persist_hits", "persist_inserts", "persist_refreshes",
    "persist_partitions_refreshed", "persist_peer_fetches",
    "persist_load_failures", "persist_store_failures",
    "persist_artifact_loads", "persist_artifact_saves",
)


class QueryLog:
    """Thread-safe bounded ring of QueryRecord dicts (newest last)."""

    def __init__(self, depth: int = DEFAULT_DEPTH):
        self._lock = threading.Lock()
        self._records: deque = deque(maxlen=max(1, depth))
        self.total = 0  # appended ever, including evicted

    @property
    def capacity(self) -> int:
        with self._lock:
            return self._records.maxlen or 0

    def append(self, rec: dict) -> None:
        with self._lock:
            self._records.append(rec)
            self.total += 1

    def records(self, limit: Optional[int] = None) -> List[dict]:
        with self._lock:
            recs = list(self._records)
        if limit is not None:
            return recs[-limit:]
        return recs

    def last(self) -> Optional[dict]:
        with self._lock:
            return self._records[-1] if self._records else None

    def resize(self, depth: int) -> None:
        """Apply a changed ``cfg.query_log_depth`` (keeps the newest)."""
        with self._lock:
            if (self._records.maxlen or 0) == max(1, depth):
                return
            old = list(self._records)
            self._records = deque(old[-depth:] if depth > 0 else [],
                                  maxlen=max(1, depth))

    def clear(self) -> None:
        with self._lock:
            self._records.clear()
            self.total = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._records)


QUERY_LOG = QueryLog()


def plan_signature(root) -> Tuple[str, Dict[str, int]]:
    """(fingerprint, op-name counts) for a physical plan — computed once
    per plan object (cached on the root) so repeated executions of a
    collected plan pay one dict lookup."""
    sig = getattr(root, "_obs_signature", None)
    if sig is not None:
        return sig
    ops: Dict[str, int] = {}

    def walk(op):
        name = op.name()
        ops[name] = ops.get(name, 0) + 1
        for c in op.children:
            walk(c)

    walk(root)
    fp = hashlib.sha256(root.display_tree().encode()).hexdigest()[:16]
    root._obs_signature = (fp, ops)
    return fp, ops


def config_delta(cfg) -> Dict[str, Any]:
    """The ExecutionConfig fields that differ from their defaults — the
    record carries what was TUNED, not the whole config."""
    out: Dict[str, Any] = {}
    for f in dataclasses.fields(cfg):
        if f.default is dataclasses.MISSING:
            continue
        v = getattr(cfg, f.name)
        if v != f.default:
            out[f.name] = v
    return out


def build_record(query_id: str, fingerprint: str, plan_ops: Dict[str, int],
                 cfg, stats, wall_ns: int, outcome: str,
                 error: Optional[BaseException] = None,
                 profiled: bool = False,
                 rows_emitted: int = 0,
                 canonical: str = "") -> dict:
    """One QueryRecord from already-collected state. Never raises on a
    degraded environment (ledger unavailable at teardown -> {}).

    ``canonical`` is the literal-masked shape fingerprint
    (adapt/fingerprint.py): ``WHERE x > 5`` and ``WHERE x > 9`` share it
    while ``plan_fingerprint`` keeps them apart — the plan cache and FDO
    history key on the former, auto-capture identity on the latter.
    Empty when the execution bypassed the planner (direct execute_plan)."""
    snap = stats.snapshot()
    counters = snap["counters"]
    try:
        from ..spill import MEMORY_LEDGER

        led = MEMORY_LEDGER.snapshot()
        ledger = {k: led[k] for k in (
            "current", "high_water", "spilled_bytes", "spilled_partitions",
            "prefetch_inflight", "async_spill_inflight", "stream_inflight",
            "exec_inflight", "dist_inflight", "negative_releases",
            "disk_full_events")}
    except Exception:
        ledger = {}
    events = {k: counters[k] for k in _EVENT_COUNTERS if counters.get(k)}
    rec = {
        "schema_version": RECORD_SCHEMA_VERSION,
        "query_id": query_id,
        "unix_time": round(time.time(), 3),
        "wall_s": round(wall_ns / 1e9, 6),
        "outcome": outcome,
        "plan_fingerprint": fingerprint,
        "plan_fingerprint_canonical": canonical,
        "plan_ops": dict(plan_ops),
        "config_delta": config_delta(cfg),
        # planning time made visible (the very cost the plan cache
        # removes): optimize+translate+fuse wall on a cold plan, cache
        # lookup+rehydrate wall on a warm one; compile_ms is the
        # fuse-compile share
        "planning_ms": round(counters.get("planning_wall_ns", 0) / 1e6, 3),
        "compile_ms": round(counters.get("compile_wall_ns", 0) / 1e6, 3),
        "rows_emitted": int(rows_emitted),
        "op_rows": dict(snap["op_rows"]),
        "op_wall_ms": {k: round(v / 1e6, 3)
                       for k, v in snap["op_wall_ns"].items()},
        "counters": dict(counters),
        "exchange_rows": counters.get("exchange_rows", 0),
        "exchange_bytes": counters.get("exchange_bytes", 0),
        "io_wait_ms": round(counters.get("io_wait_ns", 0) / 1e6, 3),
        "events": events,
        "ledger": ledger,
        "profiled": bool(profiled),
    }
    if counters.get("stream_morsels"):
        # the streaming-executor rollup (README "Streaming execution");
        # optional: absent when no morsel streamed, so schema_version 1
        # records stay valid
        rec["streaming"] = {
            "morsels": counters.get("stream_morsels", 0),
            "channel_high_water": counters.get(
                "stream_channel_high_water", 0),
            "backpressure_stalls": counters.get(
                "stream_backpressure_stalls", 0),
            # duration, not just count: 40 stalls of 1 ms vs 500 ms must
            # be tellable apart from the captured bundle alone
            "backpressure_ms": round(
                counters.get("stream_backpressure_ns", 0) / 1e6, 3),
            "short_circuited": counters.get("morsels_short_circuited", 0),
            "ttfr_ms": round(
                counters.get("time_to_first_row_ns", 0) / 1e6, 3),
        }
    if counters.get("batches_formed"):
        # the dynamic-batching rollup (README "Batched inference");
        # optional like "streaming": absent when no batch formed
        rec["batching"] = {
            "batches": counters.get("batches_formed", 0),
            "rows": counters.get("batch_rows", 0),
            "capacity_rows": counters.get("batch_capacity_rows", 0),
            "rows_padded": counters.get("batch_rows_padded", 0),
            "flushes_budget": counters.get("batch_flushes_budget", 0),
            "flushes_timer": counters.get("batch_flushes_timer", 0),
            "flushes_end": counters.get("batch_flushes_end", 0),
            "coalesce_faults": counters.get("batch_coalesce_faults", 0),
        }
    if counters.get("device_resident_segments"):
        # the device-residency rollup (README "Device residency");
        # optional like "streaming": absent when no segment ran resident
        rec["residency"] = {
            "resident_segments": counters.get("device_resident_segments", 0),
            "handoffs_elided": counters.get("device_handoffs_elided", 0),
            "hbm_high_water_bytes": counters.get(
                "hbm_resident_bytes_high_water", 0),
            "segment_compiles": counters.get("segment_compiles", 0),
            "segment_fallbacks": counters.get("segment_fallbacks", 0),
        }
    if error is not None:
        rec["error_type"] = type(error).__name__
        rec["error_message"] = str(error)[:400]
    return rec


# required top-level keys -> type checks for validate_record
_TOP_KEYS = {
    "schema_version": int,
    "query_id": str,
    "unix_time": (int, float),
    "wall_s": (int, float),
    "outcome": str,
    "plan_fingerprint": str,
    "plan_fingerprint_canonical": str,
    "planning_ms": (int, float),
    "compile_ms": (int, float),
    "plan_ops": dict,
    "config_delta": dict,
    "op_rows": dict,
    "op_wall_ms": dict,
    "counters": dict,
    "events": dict,
    "ledger": dict,
    "profiled": bool,
}


def validate_record(d: dict) -> List[str]:
    """Schema check for a QueryRecord dict (as stored or JSON-loaded).
    Returns violation strings — empty means valid (the contract
    ``make obs-smoke`` and the diagnostics bundles are validated against)."""
    errs: List[str] = []
    if not isinstance(d, dict):
        return ["record is not an object"]
    for key, typ in _TOP_KEYS.items():
        if key not in d:
            errs.append(f"missing key {key!r}")
        elif not isinstance(d[key], typ):
            errs.append(f"{key!r} has type {type(d[key]).__name__}")
    if errs:
        return errs
    if d["schema_version"] != RECORD_SCHEMA_VERSION:
        errs.append(f"schema_version {d['schema_version']} != "
                    f"{RECORD_SCHEMA_VERSION}")
    if d["outcome"] not in OUTCOMES:
        errs.append(f"outcome {d['outcome']!r} not in {OUTCOMES}")
    if d["outcome"] in ("error", "timeout") and "error_type" not in d:
        errs.append(f"outcome {d['outcome']!r} carries no error_type")
    for k, v in d["plan_ops"].items():
        if not isinstance(k, str) or not isinstance(v, int):
            errs.append(f"plan_ops[{k!r}] mistyped")
    return errs
