# daftlint: migrated
"""Engine health snapshot: breakers, ledger, scheduler, pools, query log.

``daft_tpu.health()`` returns one validated JSON-able dict answering "is
the engine healthy right now?" without running a query: per-kind circuit
breaker states (the runner registers each query's breakers here), the
MemoryLedger balances, the scheduler's in-flight task window, actor-pool
and leaked-thread counts, query-log depth and last outcome, and the
structured-log ring status.

The same snapshot is mirrored into the process metrics registry as gauges
(``refresh_health_gauges``) so ``daft_tpu.metrics_text()`` exports it —
the serving layer scrapes one endpoint for both throughput counters and
health state.
"""

from __future__ import annotations

import threading
import time
import weakref
from typing import Dict, List, Optional

__all__ = ["HEALTH_SCHEMA_VERSION", "engine_health", "register_breaker",
           "register_admission", "register_cluster", "breaker_states",
           "admission_state", "cluster_state", "refresh_health_gauges",
           "validate_health"]

HEALTH_SCHEMA_VERSION = 1

_lock = threading.Lock()
# breaker kind -> weakref to the most recently registered DeviceHealth of
# that kind (per-query objects; a dead ref reads as "idle")
_breakers: Dict[str, "weakref.ref"] = {}
# the most recently created ServingRuntime's AdmissionController (weak: a
# dropped runtime reads as an idle admission layer)
_admission: Optional["weakref.ref"] = None
# the most recently created dist/ WorkerPool (weak: a dropped/shut-down
# pool reads as an idle cluster)
_cluster: Optional["weakref.ref"] = None

_ADMISSION_IDLE = {"slots": 0, "queue_depth": 0, "active_queries": 0,
                   "queued_queries": 0, "admitted_total": 0,
                   "shed_total": 0, "draining": False}

# peer-shuffle data plane (dist/peerplane.py): piece-store occupancy and
# transfer totals, aggregated driver + worker pong reports
_PEER_IDLE = {"pieces_hosted": 0, "piece_bytes_hosted": 0,
              "pieces_stored_total": 0, "pieces_served_total": 0,
              "peer_bytes_served_total": 0, "pieces_fetched_total": 0,
              "pieces_refetched_total": 0, "peer_bytes_fetched_total": 0,
              "shuffles_dropped_total": 0, "shuffles_active": 0}

# elastic pool controller (dist/supervisor._elastic_step): target within
# [min, max], drain/scale totals, and the last decision as a human string
_ELASTIC_IDLE = {"enabled": 0, "workers_target": 0, "workers_min": 0,
                 "workers_max": 0, "draining": 0, "workers_drained_total": 0,
                 "scale_ups_total": 0, "scale_downs_total": 0,
                 "last_scale_decision": "idle"}

_CLUSTER_IDLE = {"workers": 0, "workers_alive": 0, "workers_restarting": 0,
                 "workers_tripped": 0, "tasks_inflight": 0,
                 "tasks_dispatched_total": 0, "tasks_completed_total": 0,
                 "task_redispatches_total": 0, "worker_losses_total": 0,
                 "tasks_speculated_total": 0, "speculation_wins_total": 0,
                 "speculation_inflight": 0, "telemetry_dropped_total": 0,
                 "local_fallbacks_total": 0, "restarts_used": 0,
                 "restart_budget": 0, "restart_budget_remaining": 0,
                 "driver_payload_bytes_total": 0, "workers_drained_total": 0,
                 "peer_plane": dict(_PEER_IDLE),
                 "elastic": dict(_ELASTIC_IDLE),
                 "degraded": False, "worker_detail": {}}

# breaker state -> gauge value (0 healthy .. 2 open)
_BREAKER_GAUGE = {"closed": 0.0, "half_open": 1.0, "open": 2.0, "idle": 0.0}


def register_breaker(breaker) -> None:
    """Track the latest breaker per kind (called by the runner once per
    query; weakly held so health never pins a finished query's state)."""
    with _lock:
        _breakers[breaker.kind] = weakref.ref(breaker)


def register_admission(controller) -> None:
    """Track the latest serving runtime's admission controller (weakly) so
    ``dt.health()`` answers queue depth / active queries without a runtime
    reference."""
    global _admission
    with _lock:
        _admission = weakref.ref(controller)


def register_cluster(pool) -> None:
    """Track the latest distributed WorkerPool (weakly) so ``dt.health()``
    answers worker/task/restart state without a pool reference."""
    global _cluster
    with _lock:
        _cluster = weakref.ref(pool)


def admission_state() -> dict:
    with _lock:
        ref = _admission
    ctl = ref() if ref is not None else None
    if ctl is None:
        return dict(_ADMISSION_IDLE)
    return ctl.snapshot()


def cluster_state() -> dict:
    with _lock:
        ref = _cluster
    pool = ref() if ref is not None else None
    if pool is None or getattr(pool, "_closed", False):
        return dict(_CLUSTER_IDLE)
    try:
        return pool.snapshot()
    except Exception:
        return dict(_CLUSTER_IDLE)  # pool mid-teardown


def breaker_states() -> Dict[str, str]:
    with _lock:
        items = list(_breakers.items())
    out: Dict[str, str] = {}
    for kind, ref in items:
        b = ref()
        out[kind] = b.state if b is not None else "idle"
    return out


_PLAN_CACHE_IDLE = {"entries": 0, "bindings": 0, "bytes": 0, "hits": 0,
                    "misses": 0, "evictions": 0, "demotions": 0,
                    "errors": 0, "result_entries": 0, "result_bytes": 0,
                    "result_hits": 0, "result_misses": 0,
                    "result_evictions": 0, "history_sites": 0,
                    "history_queries": 0, "history_mispredicts": 0}


def _plan_cache_snapshot() -> dict:
    """Plan-cache + sub-plan result-cache + FDO-history view shared by the
    health snapshot and the gauge mirror (one fallback shape, like
    streaming's)."""
    try:
        from ..adapt.history import HISTORY
        from ..adapt.plancache import PLAN_CACHE
        from ..adapt.resultcache import RESULT_CACHE

        pc = PLAN_CACHE.snapshot()
        rc = RESULT_CACHE.snapshot()
        h = HISTORY.snapshot()
        return {
            "entries": pc["entries"], "bindings": pc["bindings"],
            "bytes": pc["bytes"], "hits": pc["hits"],
            "misses": pc["misses"], "evictions": pc["evictions"],
            "demotions": pc["demotions"], "errors": pc["errors"],
            "result_entries": rc["entries"], "result_bytes": rc["bytes"],
            "result_hits": rc["hits"], "result_misses": rc["misses"],
            "result_evictions": rc["evictions"],
            "history_sites": h["sites"], "history_queries": h["queries"],
            "history_mispredicts": h["mispredicts"],
        }
    except Exception:
        return dict(_PLAN_CACHE_IDLE)


def _streaming_snapshot() -> dict:
    """Channel-occupancy view shared by the health snapshot and the gauge
    mirror — one fallback shape, so a new channels_snapshot key can never
    leave the two sides disagreeing."""
    try:
        from ..stream.channel import channels_snapshot

        return channels_snapshot()
    except Exception:
        return {"active_channels": 0, "queued_morsels": 0,
                "queued_bytes": 0}


_BATCHING_IDLE = {
    "active_actor_pools": 0, "pinned_models": 0, "resident_weight_bytes": 0,
    "batch_inflight_bytes": 0, "batches_formed": 0, "flushes_budget": 0,
    "flushes_timer": 0, "flushes_end": 0, "coalesce_faults": 0,
}


def _batching_snapshot() -> dict:
    """Dynamic-batching view (daft_tpu/batch/) shared by the health
    snapshot and the gauge mirror — one fallback shape, same contract as
    ``_streaming_snapshot``."""
    try:
        from ..actor_pool import pool_count
        from ..batch.actors import pinned_model_count, resident_weight_bytes
        from ..batch.executor import process_counters
        from ..spill import MEMORY_LEDGER

        c = process_counters()
        return {
            "active_actor_pools": pool_count(),
            "pinned_models": pinned_model_count(),
            "resident_weight_bytes": resident_weight_bytes(),
            "batch_inflight_bytes": int(MEMORY_LEDGER.snapshot().get(
                "batch_inflight", 0)),
            "batches_formed": c["batches_formed"],
            "flushes_budget": c["flushes_budget"],
            "flushes_timer": c["flushes_timer"],
            "flushes_end": c["flushes_end"],
            "coalesce_faults": c["coalesce_faults"],
        }
    except Exception:
        return dict(_BATCHING_IDLE)


_DEVICE_IDLE = {
    "resident_segments": 0, "handoffs_elided": 0, "segment_fallbacks": 0,
    "segment_compiles": 0, "hbm_resident_bytes_high_water": 0,
}


_PERSIST_IDLE = {
    "artifact_entries": 0, "artifact_bytes": 0, "artifact_loads": 0,
    "artifact_saves": 0, "load_failures": 0, "store_failures": 0,
    "evictions": 0, "disk_entries": 0, "disk_bytes": 0, "hits": 0,
    "misses": 0, "inserts": 0, "refreshes": 0, "partitions_refreshed": 0,
    "peer_serves": 0, "peer_fetches": 0,
}


def _persist_snapshot() -> dict:
    """Persistent cache-store view (daft_tpu/persist/): warm-start
    artifact traffic plus the durable result tier — one fallback shape,
    same contract as ``_batching_snapshot``."""
    try:
        from ..persist import snapshot

        s = snapshot()
        return {k: int(s.get(k, 0)) for k in _PERSIST_IDLE}
    except Exception:
        return dict(_PERSIST_IDLE)


def _device_snapshot() -> dict:
    """Device-residency view (daft_tpu/fuse/segment.py) shared by the
    health snapshot and the gauge mirror — one fallback shape, same
    contract as ``_batching_snapshot``."""
    try:
        from ..fuse.segment import process_counters

        c = process_counters()
        return {k: int(c[k]) for k in _DEVICE_IDLE}
    except Exception:
        return dict(_DEVICE_IDLE)


def engine_health() -> dict:
    """One validated snapshot of engine-wide state (see module docstring).
    The metrics-registry mirror is maintained separately by
    ``refresh_health_gauges`` (called at every query end and at every
    ``metrics_text()`` scrape), so this stays a single pass over the
    sources."""
    from . import log as obs_log
    from .querylog import QUERY_LOG

    try:
        from ..spill import MEMORY_LEDGER

        ledger = MEMORY_LEDGER.snapshot()
    except Exception:
        ledger = {}
    try:
        from ..actor_pool import leaked_thread_count, pool_count

        pools = {"actor_pools": pool_count(),
                 "leaked_threads": leaked_thread_count()}
    except Exception:
        pools = {"actor_pools": 0, "leaked_threads": 0}
    try:
        from ..scheduler import inflight_tasks

        sched = {"inflight_tasks": inflight_tasks()}
    except Exception:
        sched = {"inflight_tasks": 0}
    streaming = _streaming_snapshot()
    try:
        from .cluster import queries_snapshot

        queries = queries_snapshot()
    except Exception:
        queries = []  # progress registry mid-teardown
    last = QUERY_LOG.last()
    from ..profile.metrics import METRICS

    snap = METRICS.snapshot()
    data = {
        "schema_version": HEALTH_SCHEMA_VERSION,
        "unix_time": round(time.time(), 3),
        "breakers": breaker_states(),
        "ledger": ledger,
        "scheduler": sched,
        "pools": pools,
        "admission": admission_state(),
        "cluster": cluster_state(),
        "streaming": streaming,
        "batching": _batching_snapshot(),
        "device": _device_snapshot(),
        "queries": queries,
        "plan_cache": _plan_cache_snapshot(),
        "persist": _persist_snapshot(),
        "query_log": {
            "depth": len(QUERY_LOG),
            "capacity": QUERY_LOG.capacity,
            "total": QUERY_LOG.total,
            "last_outcome": last["outcome"] if last else None,
        },
        "log": {
            "records": obs_log.ring_size(),
            "dropped": obs_log.dropped_records(),
        },
        "queries_total": int(snap.get("daft_tpu_queries_total", 0)),
    }
    return data


def refresh_health_gauges(registry=None) -> None:
    """Mirror the health snapshot as gauges in the metrics registry (also
    folds the MemoryLedger balances — the memory-pressure view
    ``metrics_text()`` exposes without any profiled run)."""
    from ..profile.metrics import METRICS

    reg = registry if registry is not None else METRICS
    try:
        from ..spill import MEMORY_LEDGER

        led = MEMORY_LEDGER.snapshot()
    except Exception:
        led = None
    if led is not None:
        reg.gauge("daft_tpu_memory_ledger_bytes",
                  "engine-held partition bytes").set(led["current"])
        reg.gauge("daft_tpu_memory_ledger_high_water_bytes",
                  "peak engine-held partition bytes").set(led["high_water"])
        reg.gauge("daft_tpu_memory_ledger_prefetch_inflight_bytes",
                  "scan-prefetch bytes in flight").set(
            led["prefetch_inflight"])
        reg.gauge("daft_tpu_memory_ledger_async_spill_inflight_bytes",
                  "async-spill bytes awaiting writeback").set(
            led["async_spill_inflight"])
        reg.gauge("daft_tpu_memory_ledger_negative_releases",
                  "double-release clamps (engine bugs)").set(
            led["negative_releases"])
        reg.gauge("daft_tpu_memory_ledger_stream_inflight_bytes",
                  "streaming-channel morsel bytes in flight").set(
            led.get("stream_inflight", 0))
        reg.gauge("daft_tpu_memory_ledger_exec_inflight_bytes",
                  "materialized task outputs parked in the dispatch "
                  "window").set(led.get("exec_inflight", 0))
        reg.gauge("daft_tpu_spill_disk_full_events",
                  "ENOSPC spill writes degraded to hold-in-memory").set(
            led.get("disk_full_events", 0))
    for kind, st in breaker_states().items():
        reg.gauge(f"daft_tpu_{kind}_breaker_state",
                  "circuit breaker: 0 closed, 1 half-open, 2 open").set(
            _BREAKER_GAUGE.get(st, 0.0))
    try:
        from ..scheduler import inflight_tasks

        inflight = inflight_tasks()
    except Exception:
        inflight = 0  # scheduler mid-teardown: report an empty window
    reg.gauge("daft_tpu_scheduler_inflight_tasks",
              "partition tasks dispatched, not yet finished").set(inflight)
    try:
        from ..actor_pool import leaked_thread_count, pool_count

        pools, leaked = pool_count(), leaked_thread_count()
    except Exception:
        pools, leaked = 0, 0  # actor layer mid-teardown
    reg.gauge("daft_tpu_actor_pools", "live actor pools").set(pools)
    reg.gauge("daft_tpu_leaked_threads",
              "actor workers that outlived shutdown").set(leaked)
    strm = _streaming_snapshot()
    reg.gauge("daft_tpu_stream_channels",
              "live streaming channels (undrained)").set(
        strm["active_channels"])
    reg.gauge("daft_tpu_stream_queued_morsels",
              "morsels queued in streaming channels").set(
        strm["queued_morsels"])
    reg.gauge("daft_tpu_stream_queued_bytes",
              "bytes queued in streaming channels").set(
        strm["queued_bytes"])
    bat = _batching_snapshot()
    reg.gauge("daft_tpu_batch_actor_pools",
              "live actor pools (batching view)").set(
        bat["active_actor_pools"])
    reg.gauge("daft_tpu_batch_pinned_models",
              "model actor pools pinned across queries").set(
        bat["pinned_models"])
    reg.gauge("daft_tpu_batch_resident_weight_bytes",
              "declared weight bytes resident in pinned models").set(
        bat["resident_weight_bytes"])
    reg.gauge("daft_tpu_batch_inflight_bytes",
              "coalesce-buffer bytes awaiting a batch flush").set(
        bat["batch_inflight_bytes"])
    reg.gauge("daft_tpu_batch_batches_formed_total",
              "dynamic batches formed by the coalescer").set(
        bat["batches_formed"])
    reg.gauge("daft_tpu_batch_flushes_budget_total",
              "batches flushed on the row/byte budget").set(
        bat["flushes_budget"])
    reg.gauge("daft_tpu_batch_flushes_timer_total",
              "batches flushed by the max-latency timer").set(
        bat["flushes_timer"])
    reg.gauge("daft_tpu_batch_flushes_end_total",
              "batches flushed at source end").set(bat["flushes_end"])
    reg.gauge("daft_tpu_batch_coalesce_faults_total",
              "coalesce failures degraded to the per-partition path").set(
        bat["coalesce_faults"])
    dev = _device_snapshot()
    reg.gauge("daft_tpu_device_resident_segments_total",
              "plan segments executed HBM-resident end to end").set(
        dev["resident_segments"])
    reg.gauge("daft_tpu_device_handoffs_elided_total",
              "operator-boundary Arrow round-trips elided by residency"
              ).set(dev["handoffs_elided"])
    reg.gauge("daft_tpu_device_segment_fallbacks_total",
              "resident attempts degraded to the staged per-op path").set(
        dev["segment_fallbacks"])
    reg.gauge("daft_tpu_device_segment_compiles_total",
              "plan-segment compiles (warm plan-cache runs add zero)").set(
        dev["segment_compiles"])
    reg.gauge("daft_tpu_device_hbm_resident_high_water_bytes",
              "largest resident intermediate env of any segment").set(
        dev["hbm_resident_bytes_high_water"])
    clu = cluster_state()
    reg.gauge("daft_tpu_cluster_workers_alive",
              "distributed workers currently serving tasks").set(
        clu["workers_alive"])
    reg.gauge("daft_tpu_cluster_workers_restarting",
              "distributed worker slots awaiting respawn").set(
        clu["workers_restarting"])
    reg.gauge("daft_tpu_cluster_workers_tripped",
              "worker slots with an open WorkerHealth breaker").set(
        clu["workers_tripped"])
    reg.gauge("daft_tpu_cluster_tasks_inflight",
              "tasks currently executing on distributed workers").set(
        clu["tasks_inflight"])
    reg.gauge("daft_tpu_cluster_task_redispatches_total",
              "tasks re-dispatched after a worker loss").set(
        clu["task_redispatches_total"])
    reg.gauge("daft_tpu_cluster_worker_losses_total",
              "worker deaths observed by the supervisor").set(
        clu["worker_losses_total"])
    reg.gauge("daft_tpu_cluster_restart_budget_remaining",
              "worker respawns the pool may still spend").set(
        clu["restart_budget_remaining"])
    reg.gauge("daft_tpu_cluster_tasks_speculated_total",
              "straggler tasks that got a speculative duplicate").set(
        clu.get("tasks_speculated_total", 0))
    reg.gauge("daft_tpu_cluster_speculation_wins_total",
              "speculative duplicates that beat the original").set(
        clu.get("speculation_wins_total", 0))
    reg.gauge("daft_tpu_cluster_telemetry_dropped_total",
              "worker telemetry fragments lost in flight (pong-gap + "
              "worker-death detections; fail-open by contract)").set(
        clu.get("telemetry_dropped_total", 0))
    peer = clu.get("peer_plane") or _PEER_IDLE
    reg.gauge("daft_tpu_cluster_peer_pieces_hosted",
              "shuffle pieces currently hosted on worker piece-servers"
              ).set(peer.get("pieces_hosted", 0))
    reg.gauge("daft_tpu_cluster_peer_piece_bytes_hosted",
              "bytes currently hosted on worker piece-servers").set(
        peer.get("piece_bytes_hosted", 0))
    reg.gauge("daft_tpu_cluster_peer_pieces_served_total",
              "piece fetches served to peers").set(
        peer.get("pieces_served_total", 0))
    reg.gauge("daft_tpu_cluster_peer_pieces_fetched_total",
              "pieces pulled from peer workers").set(
        peer.get("pieces_fetched_total", 0))
    reg.gauge("daft_tpu_cluster_peer_pieces_refetched_total",
              "pieces recomputed from lineage after a failed peer fetch"
              ).set(peer.get("pieces_refetched_total", 0))
    reg.gauge("daft_tpu_cluster_peer_bytes_served_total",
              "payload bytes served peer-to-peer").set(
        peer.get("peer_bytes_served_total", 0))
    reg.gauge("daft_tpu_cluster_peer_bytes_fetched_total",
              "payload bytes pulled from peer workers").set(
        peer.get("peer_bytes_fetched_total", 0))
    ela = clu.get("elastic") or _ELASTIC_IDLE
    reg.gauge("daft_tpu_cluster_elastic_workers_target",
              "elastic controller's current worker target").set(
        ela.get("workers_target", 0))
    reg.gauge("daft_tpu_cluster_elastic_workers_min",
              "elastic pool floor (distributed_workers_min)").set(
        ela.get("workers_min", 0))
    reg.gauge("daft_tpu_cluster_elastic_workers_max",
              "elastic pool ceiling (distributed_workers_max)").set(
        ela.get("workers_max", 0))
    reg.gauge("daft_tpu_cluster_elastic_draining",
              "workers currently draining (graceful quiesce)").set(
        ela.get("draining", 0))
    reg.gauge("daft_tpu_cluster_elastic_workers_drained_total",
              "workers retired by graceful drain (scale-down/SIGTERM)"
              ).set(ela.get("workers_drained_total", 0))
    reg.gauge("daft_tpu_cluster_elastic_scale_ups_total",
              "elastic scale-up decisions taken").set(
        ela.get("scale_ups_total", 0))
    reg.gauge("daft_tpu_cluster_elastic_scale_downs_total",
              "elastic scale-down decisions taken").set(
        ela.get("scale_downs_total", 0))
    try:
        from .cluster import queries_snapshot

        qsnaps = queries_snapshot()
    except Exception:
        qsnaps = []
    reg.gauge("daft_tpu_query_progress_active",
              "queries currently executing").set(len(qsnaps))
    reg.gauge("daft_tpu_query_progress_tasks_inflight",
              "partition tasks in flight across running queries").set(
        sum(q.get("tasks_inflight", 0) for q in qsnaps))
    reg.gauge("daft_tpu_query_progress_rows_flowed",
              "rows flowed through operators of running queries").set(
        sum(q.get("rows_flowed", 0) for q in qsnaps))
    pc = _plan_cache_snapshot()
    reg.gauge("daft_tpu_plan_cache_entries",
              "plan/program cache entries (canonical shapes)").set(
        pc["entries"])
    reg.gauge("daft_tpu_plan_cache_bytes",
              "estimated bytes held by the plan/program cache").set(
        pc["bytes"])
    reg.gauge("daft_tpu_plan_cache_hits_total",
              "plan-cache hits (warm plans served)").set(pc["hits"])
    reg.gauge("daft_tpu_plan_cache_misses_total",
              "plan-cache misses (cold plans built)").set(pc["misses"])
    reg.gauge("daft_tpu_plan_cache_evictions_total",
              "plan-cache entries shed by the LRU byte cap").set(
        pc["evictions"])
    reg.gauge("daft_tpu_plan_cache_demotions_total",
              "plan-cache entries demoted (FDO mispredict/"
              "revalidation)").set(pc["demotions"])
    reg.gauge("daft_tpu_subplan_cache_entries",
              "sub-plan result-cache entries (memoized prefixes)").set(
        pc["result_entries"])
    reg.gauge("daft_tpu_subplan_cache_bytes",
              "bytes held by the sub-plan result cache").set(
        pc["result_bytes"])
    reg.gauge("daft_tpu_subplan_cache_hits_total",
              "sub-plan result-cache hits (prefixes replayed)").set(
        pc["result_hits"])
    per = _persist_snapshot()
    reg.gauge("daft_tpu_persist_artifact_entries",
              "plan/FDO artifact files on disk").set(
        per["artifact_entries"])
    reg.gauge("daft_tpu_persist_artifact_bytes",
              "bytes held by plan/FDO artifact files").set(
        per["artifact_bytes"])
    reg.gauge("daft_tpu_persist_artifact_loads_total",
              "artifact files loaded into the warm-start caches").set(
        per["artifact_loads"])
    reg.gauge("daft_tpu_persist_artifact_saves_total",
              "artifact files written at query end/shutdown").set(
        per["artifact_saves"])
    reg.gauge("daft_tpu_persist_load_failures_total",
              "persist loads degraded to a cold miss (corrupt/version "
              "skew/fault; never a query failure)").set(
        per["load_failures"])
    reg.gauge("daft_tpu_persist_store_failures_total",
              "persist stores dropped (query result unaffected)").set(
        per["store_failures"])
    reg.gauge("daft_tpu_persist_evictions_total",
              "persisted entries pruned (keep-last-K / byte cap)").set(
        per["evictions"])
    reg.gauge("daft_tpu_persist_result_entries",
              "result-tier entries on disk").set(per["disk_entries"])
    reg.gauge("daft_tpu_persist_result_bytes",
              "bytes held by the durable result tier").set(
        per["disk_bytes"])
    reg.gauge("daft_tpu_persist_hits_total",
              "durable result-tier hits (prefixes replayed from disk)"
              ).set(per["hits"])
    reg.gauge("daft_tpu_persist_misses_total",
              "durable result-tier misses").set(per["misses"])
    reg.gauge("daft_tpu_persist_inserts_total",
              "entries written to the durable result tier").set(
        per["inserts"])
    reg.gauge("daft_tpu_persist_refreshes_total",
              "incremental refreshes (entries partially recomputed)"
              ).set(per["refreshes"])
    reg.gauge("daft_tpu_persist_partitions_refreshed_total",
              "partitions recomputed by incremental refresh").set(
        per["partitions_refreshed"])
    reg.gauge("daft_tpu_persist_peer_serves_total",
              "result-tier entries served to peer workers").set(
        per["peer_serves"])
    reg.gauge("daft_tpu_persist_peer_fetches_total",
              "result-tier entries pulled from peer workers").set(
        per["peer_fetches"])
    adm = admission_state()
    reg.gauge("daft_tpu_admission_active_queries",
              "queries holding an execution slot").set(
        adm["active_queries"])
    reg.gauge("daft_tpu_admission_queue_depth",
              "queries waiting for an execution slot").set(
        adm["queued_queries"])
    reg.gauge("daft_tpu_admission_slots",
              "max concurrently executing queries").set(adm["slots"])
    reg.gauge("daft_tpu_queries_shed_total",
              "queries shed by admission control (overflow/timeout/"
              "drain)").set(adm["shed_total"])
    from .querylog import QUERY_LOG

    reg.gauge("daft_tpu_query_log_depth",
              "QueryRecords currently held").set(len(QUERY_LOG))


_TOP_KEYS = {
    "schema_version": int,
    "unix_time": (int, float),
    "breakers": dict,
    "ledger": dict,
    "scheduler": dict,
    "pools": dict,
    "admission": dict,
    "cluster": dict,
    "streaming": dict,
    "batching": dict,
    "device": dict,
    "queries": list,
    "plan_cache": dict,
    "persist": dict,
    "query_log": dict,
    "log": dict,
    "queries_total": int,
}

_BREAKER_STATES = ("closed", "half_open", "open", "idle")


def validate_health(d: dict) -> List[str]:
    """Schema check for a health snapshot — empty list means valid."""
    errs: List[str] = []
    if not isinstance(d, dict):
        return ["health is not an object"]
    for key, typ in _TOP_KEYS.items():
        if key not in d:
            errs.append(f"missing key {key!r}")
        elif not isinstance(d[key], typ):
            errs.append(f"{key!r} has type {type(d[key]).__name__}")
    if errs:
        return errs
    if d["schema_version"] != HEALTH_SCHEMA_VERSION:
        errs.append(f"schema_version {d['schema_version']} != "
                    f"{HEALTH_SCHEMA_VERSION}")
    for kind, st in d["breakers"].items():
        if st not in _BREAKER_STATES:
            errs.append(f"breakers[{kind!r}] has unknown state {st!r}")
    for k in ("depth", "capacity", "total"):
        if not isinstance(d["query_log"].get(k), int):
            errs.append(f"query_log.{k} missing or non-int")
    if not isinstance(d["scheduler"].get("inflight_tasks"), int):
        errs.append("scheduler.inflight_tasks missing or non-int")
    for k in ("actor_pools", "leaked_threads"):
        if not isinstance(d["pools"].get(k), int):
            errs.append(f"pools.{k} missing or non-int")
    for k in ("slots", "active_queries", "queued_queries", "shed_total"):
        if not isinstance(d["admission"].get(k), int):
            errs.append(f"admission.{k} missing or non-int")
    for k in ("active_channels", "queued_morsels", "queued_bytes"):
        if not isinstance(d["streaming"].get(k), int):
            errs.append(f"streaming.{k} missing or non-int")
    for k in _BATCHING_IDLE:
        if not isinstance(d["batching"].get(k), int):
            errs.append(f"batching.{k} missing or non-int")
    for k in _DEVICE_IDLE:
        if not isinstance(d["device"].get(k), int):
            errs.append(f"device.{k} missing or non-int")
    for k in _PLAN_CACHE_IDLE:
        if not isinstance(d["plan_cache"].get(k), int):
            errs.append(f"plan_cache.{k} missing or non-int")
    for k in _PERSIST_IDLE:
        if not isinstance(d["persist"].get(k), int):
            errs.append(f"persist.{k} missing or non-int")
    for k in ("workers", "workers_alive", "workers_restarting",
              "workers_tripped", "tasks_inflight",
              "task_redispatches_total", "worker_losses_total",
              "tasks_speculated_total", "speculation_wins_total",
              "telemetry_dropped_total",
              "restarts_used", "restart_budget",
              "restart_budget_remaining", "driver_payload_bytes_total",
              "workers_drained_total"):
        if not isinstance(d["cluster"].get(k), int):
            errs.append(f"cluster.{k} missing or non-int")
    if not isinstance(d["cluster"].get("degraded"), bool):
        errs.append("cluster.degraded missing or non-bool")
    peer = d["cluster"].get("peer_plane")
    if not isinstance(peer, dict):
        errs.append("cluster.peer_plane missing or non-object")
    else:
        for k in _PEER_IDLE:
            if not isinstance(peer.get(k), int):
                errs.append(f"cluster.peer_plane.{k} missing or non-int")
    ela = d["cluster"].get("elastic")
    if not isinstance(ela, dict):
        errs.append("cluster.elastic missing or non-object")
    else:
        for k in _ELASTIC_IDLE:
            want = str if k == "last_scale_decision" else int
            if not isinstance(ela.get(k), want):
                errs.append(f"cluster.elastic.{k} missing or "
                            f"non-{want.__name__}")
    for i, q in enumerate(d["queries"]):
        if not isinstance(q, dict):
            errs.append(f"queries[{i}] is not an object")
            continue
        if not isinstance(q.get("query_id"), str):
            errs.append(f"queries[{i}].query_id missing or non-str")
        for k in ("ops_total", "ops_completed", "rows_flowed",
                  "bytes_flowed", "rows_emitted", "tasks_inflight"):
            if not isinstance(q.get(k), int):
                errs.append(f"queries[{i}].{k} missing or non-int")
    return errs
