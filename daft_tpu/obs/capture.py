# daftlint: migrated
"""Slow/failed-query auto-capture: diagnostics bundles + profiler re-arm.

When ``cfg.diagnostics_dir`` is set, any query that errors, hits its
deadline, or exceeds ``cfg.slow_query_threshold_s`` dumps a bundle:

    <diagnostics_dir>/<stamp>_<query_id>_<outcome>/
        record.json     the validated QueryRecord
        stats.txt       the explain_analyze runtime-stats rendering
        profile.json    the QueryProfile (only when the profiler was armed)
        log_tail.jsonl  the structured-log ring tail (this query first)
        trace_tail.json the chrome-trace ring tail (only when a trace is armed)

Retention is bounded: only the newest ``cfg.diagnostics_keep_last``
bundles survive (oldest pruned at each write), so a flapping workload can
never fill the disk.

Slow queries additionally arm the PR 6 profiler for the NEXT run of the
same plan fingerprint (``note_slow``/``take_arm``): the first slow
occurrence captures counters, the second captures a full span tree —
without anyone having to reproduce the query by hand.

Everything here is called from ``execution.execute_plan``'s completion
hook inside a try/except: a capture failure degrades to a structured error
log, never a query failure.
"""

from __future__ import annotations

import json
import os
import re
import shutil
import threading
import time
from typing import List, Optional, Set

from .log import get_logger

__all__ = ["maybe_capture", "note_slow", "take_arm", "armed_fingerprints",
           "render_runtime_stats"]

logger = get_logger("obs")

_arm_lock = threading.Lock()
_arm_next: Set[str] = set()


def note_slow(fingerprint: str) -> None:
    """Remember a slow plan shape: its next execution auto-arms the
    profiler (consumed by ``take_arm``)."""
    with _arm_lock:
        _arm_next.add(fingerprint)


def take_arm(fingerprint: str) -> bool:
    """True exactly once per ``note_slow`` of this fingerprint — the
    execute_plan entry hook that decides whether to arm the profiler."""
    with _arm_lock:
        if fingerprint in _arm_next:
            _arm_next.discard(fingerprint)
            return True
        return False


def armed_fingerprints() -> Set[str]:
    with _arm_lock:
        return set(_arm_next)


def render_runtime_stats(stats) -> str:
    """The explain_analyze 'Runtime Stats' text (per-op rows/wall/
    throughput, IO breakdown, fusion summary, counters) — shared by
    DataFrame.explain_analyze and the diagnostics bundles, so a bundle
    reads exactly like the interactive tool."""
    snap = stats.snapshot()
    rows, wall = snap["op_rows"], snap["op_wall_ns"]
    tput = stats.op_throughput()
    names = sorted(set(rows) | set(wall), key=lambda k: -wall.get(k, 0))
    w = max([len(n) for n in names] + [8])
    lines = ["== Runtime Stats ==",
             f"{'operator':<{w}}  {'rows out':>12}  {'wall ms':>10}"
             f"  {'rows/s':>12}  {'MB/s':>8}"]
    for n in names:
        t = tput.get(n, {})
        lines.append(
            f"{n:<{w}}  {rows.get(n, 0):>12,}  {wall.get(n, 0) / 1e6:>10.2f}"
            f"  {t.get('rows_per_sec', 0.0):>12,.0f}"
            f"  {t.get('bytes_per_sec', 0.0) / 1e6:>8.1f}")
    counters = snap["counters"]
    io = stats.io_breakdown()
    if io["io_wait_ms"] or io["prefetch_hits"] or io["prefetch_misses"] \
            or io["spill_write_mbps"] or io["spill_read_mbps"]:
        lines.append("")
        lines.append(
            f"io: wait {io['io_wait_share'] * 100:.1f}% of op wall "
            f"({io['io_wait_ms']:.1f} ms) · prefetch "
            f"{io['prefetch_hits']} hit / {io['prefetch_misses']} miss"
            + (f" / {io['prefetch_throttled']} throttled"
               if io["prefetch_throttled"] else "")
            + f" · spill write {io['spill_write_mbps']:.1f} MB/s"
            f" · read {io['spill_read_mbps']:.1f} MB/s")
    if counters.get("fused_chains"):
        lines.append("")
        lines.append(
            f"fusion: {counters['fused_chains']} FusedMap chain(s), "
            f"{counters.get('fused_ops_eliminated', 0)} op(s) eliminated"
            f", {counters.get('cse_hits', 0)} cse hit(s)")
    plan_line = _render_planning_line(counters)
    if plan_line:
        lines.append("")
        lines.append(plan_line)
    strm = _render_streaming_line(counters)
    if strm:
        lines.append("")
        lines.append(strm)
    bat = _render_batching_line(counters)
    if bat:
        lines.append("")
        lines.append(bat)
    exch = _render_exchange_line(counters)
    if exch:
        lines.append("")
        lines.append(exch)
    res = _render_residency_line(counters)
    if res:
        lines.append("")
        lines.append(res)
    if counters:
        lines.append("")
        lines.append("counters: " + ", ".join(
            f"{k}={v}" for k, v in sorted(counters.items())))
    return "\n".join(lines)


def _render_planning_line(counters: dict) -> str:
    """The explain_analyze 'planning:' line (README "Plan & program
    cache"): optimize+translate+fuse wall (the cost the plan cache's
    warm path removes), the fuse-compile share, cache hit/miss for this
    query, and any FDO decisions. Empty when nothing was recorded
    (direct execute_plan without a runner)."""
    ns = counters.get("planning_wall_ns", 0)
    if not ns:
        return ""
    parts = [f"{ns / 1e6:.1f} ms"]
    comp = counters.get("compile_wall_ns", 0)
    if comp:
        parts.append(f"compile {comp / 1e6:.1f} ms")
    hits = counters.get("plan_cache_hits", 0)
    misses = counters.get("plan_cache_misses", 0)
    if hits or misses:
        parts.append(f"plan cache {hits} hit / {misses} miss")
    if counters.get("subplan_cache_hits"):
        parts.append(
            f"{counters['subplan_cache_hits']} prefix replay(s)")
    fdo_bits = []
    for key, label in (("fdo_join_flips", "join flip"),
                       ("fdo_shuffle_resizes", "fan-out resize"),
                       ("fdo_stream_hints", "stream hint"),
                       ("fdo_mispredicts", "MISPREDICT")):
        n = counters.get(key, 0)
        if n:
            fdo_bits.append(f"{n} {label}(s)")
    if fdo_bits:
        parts.append("fdo: " + ", ".join(fdo_bits))
    return "planning: " + " · ".join(parts)


def _render_streaming_line(counters: dict) -> str:
    """The explain_analyze 'streaming:' line (README "Streaming
    execution"): morsels produced, channel high-water, backpressure
    stalls, limit short-circuits, and time-to-first-row. Empty when no
    morsel streamed."""
    n = counters.get("stream_morsels", 0)
    if not n:
        return ""
    parts = [f"{n:,} morsel(s)",
             f"channel high-water {counters.get('stream_channel_high_water', 0)}"]
    stalls = counters.get("stream_backpressure_stalls", 0)
    if stalls:
        parts.append(
            f"{stalls} backpressure stall(s) "
            f"({counters.get('stream_backpressure_ns', 0) / 1e6:.1f} ms)")
    short = counters.get("morsels_short_circuited", 0)
    if short:
        parts.append(f"{short} short-circuited")
    ttfr = counters.get("time_to_first_row_ns", 0)
    if ttfr:
        parts.append(f"first row {ttfr / 1e6:.1f} ms")
    return "streaming: " + " · ".join(parts)


def _render_batching_line(counters: dict) -> str:
    """The explain_analyze 'batching:' line (README "Batched inference"):
    batches formed, mean fill vs the row budget, padding overhead, and
    flush-reason split. Empty when no batch formed."""
    n = counters.get("batches_formed", 0)
    if not n:
        return ""
    rows = counters.get("batch_rows", 0)
    cap = counters.get("batch_capacity_rows", 0)
    parts = [f"{n:,} batch(es)", f"{rows:,} rows"]
    if cap:
        parts.append(f"mean fill {rows / cap * 100:.1f}%")
    padded = counters.get("batch_rows_padded", 0)
    if padded and rows:
        parts.append(f"pad overhead {padded / rows * 100:.1f}%")
    flushes = []
    for reason in ("budget", "timer", "end"):
        c = counters.get(f"batch_flushes_{reason}", 0)
        if c:
            flushes.append(f"{c} {reason}")
    if flushes:
        parts.append("flushes " + " / ".join(flushes))
    if counters.get("batch_coalesce_faults"):
        parts.append(
            f"{counters['batch_coalesce_faults']} coalesce fault(s) "
            "degraded")
    return "batching: " + " · ".join(parts)


def _render_exchange_line(counters: dict) -> str:
    """The explain_analyze 'exchange:' line (README "Exchange"): join-filter
    effectiveness ('pruned N of M probe rows'), encoded-vs-raw payload
    bytes, and pre-exchange combine folds. Empty when nothing fired."""
    parts = []
    if counters.get("join_filter_built"):
        pruned = counters.get("join_filter_rows_pruned", 0)
        probed = counters.get("join_filter_probe_rows", 0)
        parts.append(
            f"join filters: pruned {pruned:,} of {probed:,} probe rows "
            f"({counters['join_filter_built']} filter(s))")
    enc = counters.get("exchange_bytes_encoded", 0)
    # denominator = raw bytes of the pieces the encoder actually saw (NOT
    # exchange_bytes, which also counts gathers and encode-disabled paths)
    raw = counters.get("exchange_bytes_encodable", 0)
    if counters.get("exchange_pieces_encoded") and raw:
        parts.append(
            f"encode: {raw:,} -> {enc:,} B ({enc / raw:.0%}, "
            f"{counters['exchange_pieces_encoded']} piece(s))")
    if counters.get("exchange_precombined_rows"):
        parts.append(
            f"combine: {counters['exchange_precombined_rows']:,} row(s) "
            "folded pre-exchange")
    return ("exchange: " + " · ".join(parts)) if parts else ""


def _render_residency_line(counters: dict) -> str:
    """The explain_analyze 'residency:' line (README "Device residency"):
    resident segments executed, operator-boundary handoffs elided, the HBM
    high-water of the resident intermediates, and degradations to the
    staged path. Empty when no segment ran resident."""
    n = counters.get("device_resident_segments", 0)
    if not n:
        return ""
    parts = [f"{n} resident segment(s)",
             f"{counters.get('device_handoffs_elided', 0)} handoff(s) elided"]
    hw = counters.get("hbm_resident_bytes_high_water", 0)
    if hw:
        parts.append(f"HBM high-water {hw / 1e6:.1f} MB")
    fb = counters.get("segment_fallbacks", 0)
    if fb:
        parts.append(f"{fb} fallback(s) to staged")
    return "residency: " + " · ".join(parts)


# a bundle directory name: <stamp>_<query id>_<outcome>. Retention ONLY
# ever touches names matching this — diagnostics_dir may be an existing
# directory with unrelated content, which pruning must never delete
_BUNDLE_RE = re.compile(
    r"^\d{8}T\d{6}_[A-Za-z0-9_-]+_(ok|error|timeout|cancelled|abandoned)$")


def _bundle_name(rec: dict) -> str:
    stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(rec["unix_time"]))
    qid = "".join(c if c.isalnum() or c in "-_" else "_"
                  for c in rec["query_id"])
    return f"{stamp}_{qid}_{rec['outcome']}"


def _prune(root: str, keep: int) -> None:
    try:
        entries = sorted(
            e for e in os.listdir(root)
            if _BUNDLE_RE.match(e) and os.path.isdir(os.path.join(root, e)))
    except OSError:
        return
    for e in entries[:max(0, len(entries) - max(1, keep))]:
        shutil.rmtree(os.path.join(root, e), ignore_errors=True)


def maybe_capture(rec: dict, cfg, stats, profiler) -> Optional[str]:
    """Completion hook: decide slow/failed, write the bundle, arm the next
    run. Returns the bundle path (None when nothing was captured)."""
    outcome = rec["outcome"]
    failed = outcome in ("error", "timeout")
    thr = getattr(cfg, "slow_query_threshold_s", None)
    slow = thr is not None and rec["wall_s"] >= thr
    if not (failed or slow):
        return None
    if slow and not rec["profiled"]:
        # the NEXT run of this plan shape records a full span tree
        note_slow(rec["plan_fingerprint"])
    root = getattr(cfg, "diagnostics_dir", None)
    if not root:
        return None
    path = os.path.join(root, _bundle_name(rec))
    os.makedirs(path, exist_ok=True)
    with open(os.path.join(path, "record.json"), "w", encoding="utf-8") as f:
        json.dump(rec, f, indent=1, sort_keys=True, default=str)
    try:
        text = render_runtime_stats(stats)
    except Exception as e:
        text = f"(runtime-stats rendering failed: {e!r})"
    with open(os.path.join(path, "stats.txt"), "w", encoding="utf-8") as f:
        f.write(text + "\n")
    if profiler is not None and profiler.armed:
        try:
            from ..profile.export import build_profile

            build_profile(profiler, stats).to_json(
                os.path.join(path, "profile.json"))
        except Exception as e:
            logger.error("bundle_profile_failed", path=path, error=repr(e))
    _write_log_tail(path, rec["query_id"])
    _write_trace_tail(path)
    _prune(root, getattr(cfg, "diagnostics_keep_last", 20))
    logger.info("diagnostics_bundle", path=path, outcome=outcome,
                slow=slow, wall_s=rec["wall_s"])
    return path


def _write_log_tail(path: str, query_id: str) -> None:
    from . import log as obs_log

    recs = obs_log.tail(200, query_id=query_id)
    if not recs:
        recs = obs_log.tail(100)
    with open(os.path.join(path, "log_tail.jsonl"), "w",
              encoding="utf-8") as f:
        for r in recs:
            f.write(json.dumps(r, default=str) + "\n")


def _write_trace_tail(path: str) -> None:
    from .. import tracing

    if not tracing.active():
        return
    with open(os.path.join(path, "trace_tail.json"), "w",
              encoding="utf-8") as f:
        json.dump({"traceEvents": tracing.tail(2000)}, f, default=str)


def list_bundles(root: str) -> List[str]:
    """Bundle directories under ``root``, oldest first (test surface;
    same name filter retention uses, so unrelated content never counts)."""
    try:
        return sorted(e for e in os.listdir(root)
                      if _BUNDLE_RE.match(e)
                      and os.path.isdir(os.path.join(root, e)))
    except OSError:
        return []
