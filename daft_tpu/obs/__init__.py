"""Always-on flight recorder: query log, slow/failed-query auto-capture,
engine health snapshot, and structured JSON-lines logging.

PR 6's profiler is opt-in and per-query; this package is the *always-on*
counterpart a serving runtime needs: a bounded record of every query that
ran (``querylog``), automatic diagnostics bundles for the slow and failed
ones (``capture``), a one-call health view of breakers/ledger/pools
(``health``), and an engine-wide structured logger whose records carry
query_id across threads (``log``). Everything here is built from data the
stats stack already collects — the steady-state cost is guard-tested the
same way the DISARMED profiler is.
"""

from .log import EngineLogger, current_query_id, get_logger, query_context
from .querylog import (QUERY_LOG, RECORD_SCHEMA_VERSION, QueryLog,
                       build_record, plan_signature, validate_record)
from .health import engine_health, refresh_health_gauges, validate_health

__all__ = [
    "EngineLogger",
    "get_logger",
    "current_query_id",
    "query_context",
    "QueryLog",
    "QUERY_LOG",
    "RECORD_SCHEMA_VERSION",
    "build_record",
    "plan_signature",
    "validate_record",
    "engine_health",
    "refresh_health_gauges",
    "validate_health",
]
