# daftlint: migrated
"""Cluster-wide observability plane: one truthful trace per query.

The distributed runner (daft_tpu/dist/) ships map-class partition tasks to
worker PROCESSES, which puts a process boundary through the middle of the
observability stack: op walls, rows, spills, retries, breaker trips, and
log lines produced on a worker would vanish from the driver's span tree,
RuntimeStats rollups, QueryRecord, and log ring. This module closes that
boundary with three pieces:

**Telemetry fragments** (worker side, :class:`TelemetryCollector`): each
remote task runs inside a per-task scope that arms a local Profiler (when
the driver's query is profiled), snapshots the worker's RuntimeStats
before/after, and captures the log records the task emitted. The resulting
*fragment* is a bounded, versioned plain-dict (``TELEMETRY_VERSION``,
size/entry caps with truncated-not-dropped semantics) that piggybacks on
the ``result``/``task_error`` reply frame — no extra round trip.

**Driver-side merge** (:func:`merge_fragment`): fragments splice into the
query's observability state under the op span that caused the dispatch —
worker spans land in the driver Profiler's tree (chrome trace gains one
``worker-N`` lane per worker process; the zero-orphan invariant extends
cluster-wide), counter deltas fold into the driver's RuntimeStats (so
``explain_analyze``/QueryProfile/QueryRecord report the same counters under
``distributed_workers=N`` as the local runner), and worker log records land
in the driver's EngineLogger ring with ``query_id`` intact. Per-op
rows/wall rollups are NOT folded from fragments — the scheduler's
``run_one`` already records them from the worker-reported reply, and a
lost fragment must never make the rollup lie.

**Failure contract — strictly fail-open**: a dropped, oversized, corrupt,
or unparseable fragment costs a counter (``telemetry_dropped`` /
``telemetry_truncated``), never a task failure, never a re-dispatch, never
a changed query result. The ``telemetry.fragment`` fault site
(DTL004-registered) fires per merge so CI can prove it.

This module also owns **live query progress** (:class:`QueryProgress`):
a per-query tracker registered for the execution's lifetime — ops
completed/total, rows/bytes flowed, tasks in flight, per-worker dispatch
state, streaming channel depths — exposed as ``dt.health()["queries"]``,
``QueryHandle.progress()``, ``daft_tpu.query_progress()``, and
``daft_tpu_query_progress_*`` gauges, turning "is it stuck or slow?" into
a snapshot instead of a guess.
"""

from __future__ import annotations

import pickle
import threading
import time
from typing import Dict, List, Optional

from .log import get_logger, query_context

__all__ = ["TELEMETRY_VERSION", "TelemetryCollector", "build_fragment",
           "validate_fragment", "merge_fragment", "QueryProgress",
           "register_progress", "unregister_progress", "query_progress",
           "queries_snapshot", "active_query_stats"]

logger = get_logger("obs.cluster")

# fragment wire-format version: the merge drops (counts, never fails on)
# any fragment whose version it does not speak
TELEMETRY_VERSION = 1

# fragment bounds — a pathological task degrades ITS telemetry, never the
# reply frame or the driver. Spans/events are capped at collection time
# (the worker profiler's own buffer caps), logs at the sink, and the
# whole fragment is shrunk under MAX_FRAGMENT_BYTES before it rides the
# reply (logs dropped first, then events, then spans; counters last).
MAX_FRAGMENT_BYTES = 256 * 1024
MAX_FRAGMENT_SPANS = 512
MAX_FRAGMENT_EVENTS = 128
MAX_FRAGMENT_LOGS = 64


# ---------------------------------------------------------------------------
# worker side: per-task collection
# ---------------------------------------------------------------------------

class TelemetryCollector:
    """Per-task telemetry scope on a worker process.

    ``with TelemetryCollector(...)`` binds the task's query id as log
    context, snapshots the worker's RuntimeStats counters, arms a bounded
    local Profiler when the driver's query is profiled, and captures the
    log records emitted while the task ran. :meth:`fragment` then builds
    the bounded reply payload — returning ``None`` on ANY internal defect
    (fail-open: telemetry must never fail a task)."""

    def __init__(self, query_id: Optional[str], op_name: str, seq: int,
                 stats, profile: bool = False,
                 max_bytes: int = MAX_FRAGMENT_BYTES,
                 max_logs: int = MAX_FRAGMENT_LOGS):
        self.query_id = query_id
        self.op_name = op_name
        self.seq = seq
        self.stats = stats
        self.profile = profile
        self.max_bytes = max_bytes
        self.max_logs = max_logs
        self.profiler = None
        self._prev_profiler = None
        self._qctx = None
        self._snap0: Dict[str, int] = {}
        self._logs: List[dict] = []
        self._log_overflow = False
        self._t0 = 0
        self._dur_ns = 0

    # every engine log record emitted while the task runs is captured here
    # (the worker executes one task at a time, so the window is the task)
    def _on_log(self, rec: dict) -> None:
        if len(self._logs) < self.max_logs:
            self._logs.append(dict(rec))
        else:
            self._log_overflow = True

    def __enter__(self) -> "TelemetryCollector":
        from . import log as obs_log

        self._t0 = time.perf_counter_ns()
        self._qctx = query_context(self.query_id)
        self._qctx.__enter__()
        try:
            self._snap0 = dict(self.stats.snapshot()["counters"])
        except Exception:
            self._snap0 = {}
        if self.profile:
            try:
                from ..profile.spans import Profiler

                self.profiler = Profiler(
                    query_id=self.query_id or "task",
                    max_spans=MAX_FRAGMENT_SPANS,
                    max_events=MAX_FRAGMENT_EVENTS)
                self._prev_profiler = self.stats.profiler
                self.stats.profiler = self.profiler
            except Exception:
                self.profiler = None
        try:
            obs_log.add_sink(self._on_log)
        except Exception:  # daftlint: disable=DTL005
            pass  # fail-open: the fragment ships without a log tail
        return self

    def __exit__(self, *exc) -> bool:
        from . import log as obs_log

        self._dur_ns = time.perf_counter_ns() - self._t0
        try:
            obs_log.remove_sink(self._on_log)
        except Exception:  # daftlint: disable=DTL005
            pass  # fail-open: a sink that never installed has no removal
        if self._prev_profiler is not None:
            self.stats.profiler = self._prev_profiler
            self._prev_profiler = None
        if self._qctx is not None:
            self._qctx.__exit__(None, None, None)
            self._qctx = None
        return False

    def fragment(self) -> Optional[dict]:
        """The bounded telemetry fragment for the finished task, or None
        when building it failed (fail-open — the reply ships without)."""
        if not self._t0:
            return None  # scope never entered: nothing true to report
        try:
            counters: Dict[str, int] = {}
            snap1 = self.stats.snapshot()["counters"]
            for k, v in snap1.items():
                d = v - self._snap0.get(k, 0)
                if d:
                    counters[str(k)] = int(d)
            spans: List[dict] = []
            events: List[dict] = []
            if self.profiler is not None:
                spans = [s.as_dict() for s in self.profiler.spans_snapshot()]
                events = self.profiler.events_snapshot()
            logs = []
            for rec in self._logs:
                r = dict(rec)
                r.setdefault("query_id", self.query_id)
                logs.append(r)
            return build_fragment(
                self.query_id, self.op_name, self.seq, self._t0,
                self._dur_ns, counters, spans, events, logs,
                truncated=self._log_overflow, max_bytes=self.max_bytes)
        except Exception:
            return None


def build_fragment(query_id: Optional[str], op_name: str, seq: int,
                   t0_ns: int, dur_ns: int, counters: Dict[str, int],
                   spans: List[dict], events: List[dict], logs: List[dict],
                   truncated: bool = False,
                   max_bytes: int = MAX_FRAGMENT_BYTES) -> dict:
    """Assemble + bound one telemetry fragment. Oversized content is
    TRUNCATED, never fatal: logs shed first, then events, then spans —
    the counters delta (the rollup-bearing part) survives to the end."""
    frag = {
        "v": TELEMETRY_VERSION,
        "query_id": query_id,
        "op": op_name,
        "seq": int(seq),
        "t0_ns": int(t0_ns),
        "dur_ns": int(dur_ns),
        "counters": counters,
        "spans": list(spans)[:MAX_FRAGMENT_SPANS],
        "events": list(events)[:MAX_FRAGMENT_EVENTS],
        "logs": list(logs)[:MAX_FRAGMENT_LOGS],
        "truncated": bool(truncated
                          or len(spans) > MAX_FRAGMENT_SPANS
                          or len(events) > MAX_FRAGMENT_EVENTS
                          or len(logs) > MAX_FRAGMENT_LOGS),
    }
    for victim in ("logs", "events", "spans"):
        if _fragment_size(frag) <= max_bytes:
            return frag
        if frag[victim]:
            frag[victim] = []
            frag["truncated"] = True
    if _fragment_size(frag) > max_bytes:
        # even the counters are pathological: keep the envelope only
        frag["counters"] = {}
        frag["truncated"] = True
    return frag


def _fragment_size(frag: dict) -> int:
    return len(pickle.dumps(frag, protocol=pickle.HIGHEST_PROTOCOL))


# ---------------------------------------------------------------------------
# driver side: validation + merge
# ---------------------------------------------------------------------------

_SPAN_KEYS = ("id", "name", "kind", "t0_ns", "dur_ns")


def validate_fragment(frag) -> List[str]:
    """Schema check for an incoming fragment — empty list means
    mergeable. Anything else reads as corrupt and is dropped (counted)."""
    errs: List[str] = []
    if not isinstance(frag, dict):
        return ["fragment is not an object"]
    if frag.get("v") != TELEMETRY_VERSION:
        return [f"fragment version {frag.get('v')!r} != "
                f"{TELEMETRY_VERSION}"]
    if not isinstance(frag.get("counters"), dict):
        errs.append("counters missing or non-dict")
    for key in ("spans", "events", "logs"):
        if not isinstance(frag.get(key), list):
            errs.append(f"{key} missing or non-list")
    if not isinstance(frag.get("t0_ns"), int) \
            or not isinstance(frag.get("dur_ns"), int):
        errs.append("t0_ns/dur_ns missing or non-int")
    if not errs:
        for i, s in enumerate(frag["spans"]):
            if not isinstance(s, dict) or \
                    any(k not in s for k in _SPAN_KEYS):
                errs.append(f"spans[{i}] mistyped")
                break
    return errs


def merge_fragment(ctx, frag, worker_id: int) -> bool:
    """Fold one worker telemetry fragment into the driver query's
    observability state: counters into RuntimeStats, spans/events spliced
    under the causing op span (``worker-<id>`` lane), log records into
    the driver's ring with query_id intact.

    Strictly fail-open: a fault-injected, unparseable, version-skewed, or
    internally-failing merge bumps ``telemetry_dropped`` and returns
    False — the task result is untouched and nothing re-dispatches. An
    oversized fragment was already truncated at build; driver-side clips
    are counted as ``telemetry_truncated``, not dropped."""
    from .. import faults
    from ..errors import DaftTransientError

    stats = ctx.stats
    try:
        faults.check("telemetry.fragment", stats)
    except DaftTransientError:
        stats.bump("telemetry_dropped")
        return False
    try:
        errs = validate_fragment(frag)
        if errs:
            stats.bump("telemetry_dropped")
            logger.debug("telemetry_fragment_invalid", worker=worker_id,
                         errors=errs[:3])
            return False
        truncated = bool(frag.get("truncated"))
        spans = frag["spans"]
        events = frag["events"]
        logs = frag["logs"]
        if len(spans) > MAX_FRAGMENT_SPANS or \
                len(events) > MAX_FRAGMENT_EVENTS or \
                len(logs) > MAX_FRAGMENT_LOGS:
            spans = spans[:MAX_FRAGMENT_SPANS]
            events = events[:MAX_FRAGMENT_EVENTS]
            logs = logs[:MAX_FRAGMENT_LOGS]
            truncated = True
        for k, v in frag["counters"].items():
            if isinstance(k, str) and isinstance(v, int) and v:
                stats.bump(k, v)
        prof = stats.profiler
        if prof.armed and (spans or events):
            # rebase the worker's clock onto the driver's: anchor the
            # subtree so it ENDS at merge time, inside the still-open
            # dist.remote span it splices under
            offset = (time.perf_counter_ns() - frag["t0_ns"]
                      - frag["dur_ns"])
            prof.splice(spans, events, prof.capture(), offset,
                        thread=f"worker-{worker_id}")
        if logs:
            from . import log as obs_log

            qid = frag.get("query_id")
            for rec in logs:
                if not isinstance(rec, dict):
                    continue
                r = dict(rec)
                if qid is not None:
                    r.setdefault("query_id", qid)
                # distinct from the supervisor's own `worker=` field: this
                # marks a RELAYED worker-process record, the zero-orphan
                # worker-log acceptance filter
                r["relay_worker"] = worker_id
                obs_log.inject(r)
        if truncated:
            stats.bump("telemetry_truncated")
        stats.bump("telemetry_merged")
        return True
    except Exception as e:
        # observability must never fail the task it describes
        stats.bump("telemetry_dropped")
        logger.warning("telemetry_merge_failed", worker=worker_id,
                       error=repr(e))
        return False


# ---------------------------------------------------------------------------
# live query progress
# ---------------------------------------------------------------------------

class QueryProgress:
    """Live progress of one running query — registered by execute_plan for
    the execution's lifetime and snapshotted on demand by
    ``dt.health()["queries"]`` / ``QueryHandle.progress()``. Updates are
    O(1) set/int operations on the execution hot path; the snapshot does
    the aggregation work at read time."""

    __slots__ = ("query_id", "stats", "plan_ops", "ops_total", "started",
                 "_lock", "_ops_done", "rows_emitted", "_tasks_inflight")

    def __init__(self, query_id: str, stats, plan_ops: Dict[str, int]):
        self.query_id = query_id
        self.stats = stats
        self.plan_ops = dict(plan_ops) if plan_ops else {}
        self.ops_total = sum(self.plan_ops.values())
        self.started = time.monotonic()
        self._lock = threading.Lock()
        # op name -> exhausted-instance count: plans repeat op classes
        # (two ProjectOps are two plan_ops entries), so completion counts
        # INSTANCES, capped per name at what the plan actually contains
        self._ops_done: Dict[str, int] = {}
        self.rows_emitted = 0
        self._tasks_inflight = 0

    def op_done(self, name: str) -> None:
        """One operator instance's driver stream exhausted."""
        with self._lock:
            self._ops_done[name] = self._ops_done.get(name, 0) + 1

    def task_started(self) -> None:
        with self._lock:
            self._tasks_inflight += 1

    def task_finished(self) -> None:
        with self._lock:
            self._tasks_inflight = max(0, self._tasks_inflight - 1)

    def add_rows(self, n: int) -> None:
        with self._lock:
            self.rows_emitted += n

    def snapshot(self) -> dict:
        snap = self.stats.snapshot()
        counters = snap["counters"]
        with self._lock:
            done = sum(min(n, self.plan_ops.get(name, n))
                       for name, n in self._ops_done.items())
            inflight = self._tasks_inflight
            rows_emitted = self.rows_emitted
        out = {
            "query_id": self.query_id,
            "elapsed_s": round(time.monotonic() - self.started, 3),
            "ops_total": self.ops_total,
            "ops_completed": min(done, self.ops_total) if self.ops_total
            else done,
            "rows_flowed": sum(snap["op_rows"].values()),
            "bytes_flowed": sum(snap["op_bytes"].values()),
            "rows_emitted": rows_emitted,
            "tasks_inflight": inflight,
            "tasks_speculated": counters.get("tasks_speculated", 0),
            "dist_tasks": counters.get("dist_tasks", 0),
            "workers": _worker_inflight(),
            "channels": _channel_depths(),
        }
        return out


def _worker_inflight() -> Dict[str, int]:
    """Per-worker in-flight task counts from the live distributed pool
    (empty when no pool is up). Process-wide — under concurrent serving
    queries the per-worker split is shared, not per-query."""
    try:
        from ..dist.supervisor import worker_pool_snapshot

        snap = worker_pool_snapshot()
        if not snap:
            return {}
        return {wid: d.get("inflight", 0)
                for wid, d in snap.get("worker_detail", {}).items()}
    except Exception:
        return {}


def _channel_depths() -> Dict[str, int]:
    """Streaming channel occupancy (process-wide registry)."""
    try:
        from ..stream.channel import channels_snapshot

        s = channels_snapshot()
        return {"queued_morsels": s.get("queued_morsels", 0),
                "queued_bytes": s.get("queued_bytes", 0)}
    except Exception:
        return {"queued_morsels": 0, "queued_bytes": 0}


_progress_lock = threading.Lock()
_progress: "Dict[str, QueryProgress]" = {}


def register_progress(p: QueryProgress) -> None:
    """Track a running query's progress (last-wins per query id — an AQE
    query re-registers per stage under the same id)."""
    with _progress_lock:
        _progress[p.query_id] = p


def unregister_progress(p: QueryProgress) -> None:
    with _progress_lock:
        if _progress.get(p.query_id) is p:
            del _progress[p.query_id]


def query_progress(query_id: str) -> Optional[dict]:
    """One running query's progress snapshot, or None when it is not
    currently executing (finished queries read from the flight recorder)."""
    with _progress_lock:
        p = _progress.get(query_id)
    if p is None:
        return None
    try:
        return p.snapshot()
    except Exception:
        return None


def active_query_stats() -> List:
    """RuntimeStats of every currently-executing query — the supervisor's
    hook for attributing cluster-level events (a graceful worker drain)
    to the queries running while they happened."""
    with _progress_lock:
        return [p.stats for p in _progress.values()]


def queries_snapshot() -> List[dict]:
    """All currently-executing queries' progress, oldest first — the
    ``dt.health()["queries"]`` section."""
    with _progress_lock:
        items = sorted(_progress.values(), key=lambda p: p.started)
    out = []
    for p in items:
        try:
            out.append(p.snapshot())
        except Exception:
            continue  # a query mid-teardown: skip, never fail health
    return out
