"""Structured JSON-lines engine logging with cross-thread query-id context.

Every engine log line is a dict record — timestamp, level, logger name,
event, thread, free-form fields — kept in a bounded process ring (the
diagnostics bundles snapshot its tail), forwarded to the stdlib ``logging``
tree under ``daft_tpu.*`` as one JSON line (so existing handlers/caplog
keep working), and optionally appended to a JSON-lines file.

Query-id propagation mirrors the profiler's capture/activate tokens, but
is ALWAYS ON and costs one thread-local read per record:
``execution.execute_plan`` binds the query id on the driver thread for the
query's lifetime, and every background hop the engine makes — scheduler
partition tasks, the async spill writer, scan prefetches, unspill
readaheads, actor-pool batches — captures ``current_query_id()`` at submit
time and re-binds it inside the job via ``query_context``. A log line
emitted from any of those threads therefore carries the query that caused
the work (the zero-orphans acceptance mirrors PR 6's span test).

daftlint rule DTL007 (log-hygiene) enforces that engine modules log through
``get_logger`` instead of bare ``print``/``warnings``/stdlib ``logging``;
this module is the one sanctioned user of the stdlib backend.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from collections import deque
from contextlib import contextmanager
from typing import Callable, Deque, Dict, List, Optional

__all__ = ["EngineLogger", "get_logger", "current_query_id", "query_context",
           "tail", "clear", "set_ring_cap", "dropped_records",
           "log_to_file", "close_file", "add_sink", "remove_sink", "inject",
           "DEFAULT_RING_CAP"]

# bounded record ring: a record is a small dict, so the worst-case buffer
# stays low-MB; evictions are counted so a truncated tail is never mistaken
# for the whole history
DEFAULT_RING_CAP = 4096

_LEVELS = {"debug": logging.DEBUG, "info": logging.INFO,
           "warning": logging.WARNING, "error": logging.ERROR}

_lock = threading.Lock()
_ring: Deque[dict] = deque(maxlen=DEFAULT_RING_CAP)
_dropped = 0
_sinks: List[Callable[[dict], None]] = []
_file = None  # open JSON-lines file handle (log_to_file)
# writes to the shared file serialize on their own lock (never nested with
# _lock) so concurrent emits can't interleave half-written JSON lines
_file_lock = threading.Lock()

# ---------------------------------------------------------------------------
# query-id context
# ---------------------------------------------------------------------------

_qtl = threading.local()


def current_query_id() -> Optional[str]:
    """The query id bound to THIS thread (None outside any query). Capture
    it before submitting background work and re-bind inside the job with
    ``query_context`` so log lines from worker threads stay attributed."""
    return getattr(_qtl, "qid", None)


@contextmanager
def query_context(qid: Optional[str]):
    """Bind ``qid`` as this thread's current query for the block (nestable;
    restores the previous binding on exit). Passing the ``None`` a capture
    on an unbound thread returned is legal and leaves lines unattributed."""
    prev = getattr(_qtl, "qid", None)
    _qtl.qid = qid
    try:
        yield
    finally:
        _qtl.qid = prev


# ---------------------------------------------------------------------------
# the logger
# ---------------------------------------------------------------------------

def _publish(rec: dict, py_logger: logging.Logger) -> Optional[str]:
    """Ring append + sink dispatch + JSON-lines file write for one record
    — the single publish discipline ``EngineLogger._emit`` and
    :func:`inject` share, so relayed worker records and driver records
    can never diverge in eviction accounting, sink error handling, or
    file flushing. Returns the serialized line when a file is armed."""
    # the shared-ring eviction counter, same module-global pattern every
    # other ring accessor here uses (baselined for clear/set_ring_cap/...)
    global _dropped  # daftlint: disable=DTL008
    with _lock:
        if _ring.maxlen is not None and len(_ring) == _ring.maxlen:
            _dropped += 1
        _ring.append(rec)
        sinks = list(_sinks) if _sinks else None
        f = _file
    if sinks is not None:
        for s in sinks:
            try:
                s(rec)
            except Exception:
                py_logger.exception("log sink failed")
    line = None
    if f is not None:
        try:
            line = json.dumps(rec, default=str)
            with _file_lock:
                f.write(line + "\n")
                f.flush()
        except (OSError, ValueError):
            pass  # a full/closed log file must never fail the engine
    return line


class EngineLogger:
    """Named structured logger. ``logger.warning("spill_write_failed",
    path=..., error=...)`` emits one record; the ``event`` is a stable
    machine-readable slug, everything else rides as fields."""

    __slots__ = ("name", "_py")

    def __init__(self, name: str):
        self.name = name
        self._py = logging.getLogger(f"daft_tpu.{name}")

    def _emit(self, level: str, event: str, fields: dict) -> None:
        rec = {"ts": round(time.time(), 6), "level": level,
               "logger": self.name, "event": event,
               "thread": threading.current_thread().name}
        qid = getattr(_qtl, "qid", None)
        if qid is not None:
            rec["query_id"] = qid
        if fields:
            rec.update(fields)
        line = _publish(rec, self._py)
        lvl = _LEVELS[level]
        if self._py.isEnabledFor(lvl):
            self._py.log(lvl, "%s",
                         line if line is not None
                         else json.dumps(rec, default=str))

    def debug(self, event: str, **fields) -> None:
        self._emit("debug", event, fields)

    def info(self, event: str, **fields) -> None:
        self._emit("info", event, fields)

    def warning(self, event: str, **fields) -> None:
        self._emit("warning", event, fields)

    def error(self, event: str, **fields) -> None:
        self._emit("error", event, fields)


_loggers: Dict[str, EngineLogger] = {}


def get_logger(name: str) -> EngineLogger:
    with _lock:
        lg = _loggers.get(name)
        if lg is None:
            lg = _loggers[name] = EngineLogger(name)
        return lg


# ---------------------------------------------------------------------------
# ring access / sinks
# ---------------------------------------------------------------------------

def tail(n: int = 200, query_id: Optional[str] = None,
         level: Optional[str] = None) -> List[dict]:
    """The newest ``n`` records (optionally filtered by query_id / minimum
    level), oldest first — what diagnostics bundles snapshot."""
    with _lock:
        recs = list(_ring)
    if query_id is not None:
        recs = [r for r in recs if r.get("query_id") == query_id]
    if level is not None:
        floor = _LEVELS[level]
        recs = [r for r in recs if _LEVELS[r["level"]] >= floor]
    return recs[-n:]


def clear() -> None:
    global _dropped
    with _lock:
        _ring.clear()
        _dropped = 0


def set_ring_cap(cap: int) -> None:
    """Resize the ring, keeping the newest records that fit."""
    global _ring, _dropped
    with _lock:
        old = list(_ring)
        _ring = deque(old[-cap:] if cap else [], maxlen=max(1, cap))
        _dropped += max(0, len(old) - cap)


def dropped_records() -> int:
    with _lock:
        return _dropped


def ring_size() -> int:
    with _lock:
        return len(_ring)


def inject(rec: dict) -> None:
    """Publish a pre-built record to the ring (plus sinks and the
    JSON-lines file) AS RECORDED — the telemetry merge relays
    worker-process log records through here so they land in the driver's
    ring with their original timestamp/level/query_id intact. Stdlib
    forwarding is skipped: the record already went through a worker's
    stdlib tree, and re-forwarding would double every worker line for
    caplog users."""
    _publish(rec, logging.getLogger("daft_tpu.obs"))


def add_sink(fn: Callable[[dict], None]) -> None:
    """Register a per-record callback (tests, shipping to a collector)."""
    with _lock:
        _sinks.append(fn)


def remove_sink(fn: Callable[[dict], None]) -> None:
    with _lock:
        if fn in _sinks:
            _sinks.remove(fn)


def log_to_file(path: str) -> None:
    """Append every subsequent record to ``path`` as JSON lines."""
    global _file
    f = open(path, "a", encoding="utf-8")
    with _lock:
        old, _file = _file, f
    if old is not None:
        old.close()


def close_file() -> None:
    global _file
    with _lock:
        f, _file = _file, None
    if f is not None:
        f.close()


_env_path = os.environ.get("DAFT_TPU_LOG_JSON")
if _env_path:
    log_to_file(_env_path)
