"""Grouped/global mergeable quantile-sketch builds, merges, and estimates.

Stage 1 summarizes each group's numeric values into a bounded weighted
sample (deterministic compression, kernels/sketches.quantile_compress);
stage 2 concatenates samples per group and re-compresses; the final
projection interpolates the requested percentiles. Serialized form is the
fixed layout of kernels/sketches.quantile_state_to_bytes
(``<u4 cap, <u4 count, count x <f8 values, count x <f8 weights``), carried
as a Binary column.

Like the HLL side, everything internal flows through a flat entry
representation decoded/encoded straight from the arrow offset/data buffers
— builds and merges are vectorized passes, and per-group python work is
limited to the groups that actually exceed their cap (at most
total_entries/cap of them), so high group cardinality costs O(entries),
not an interpreter loop per sketch.
"""
# daftlint: migrated

from __future__ import annotations

from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import DaftValueError
from ..kernels.sketches import (
    QUANTILE_CAP,
    quantile_compress,
    weighted_quantiles,
)
from .hll import _read_u32_le, _write_u32_le


def _require_numeric(series) -> None:
    dt = series.dtype
    if not (dt.is_numeric() or dt.is_boolean() or dt.is_null()):
        raise DaftValueError(
            f"approx_percentiles needs a numeric input, got {dt}")


def _read_f8_le(data: np.ndarray, pos: np.ndarray) -> np.ndarray:
    """Gather little-endian float64 at arbitrary byte positions."""
    b = data[pos[:, None] + np.arange(8)]
    return np.ascontiguousarray(b).view("<f8")[:, 0]


def _write_f8_le(buf: np.ndarray, pos: np.ndarray, vals: np.ndarray) -> None:
    v8 = np.ascontiguousarray(vals, dtype="<f8").view(np.uint8).reshape(-1, 8)
    for k in range(8):
        buf[pos + k] = v8[:, k]


def _decode_states(arr) -> Tuple[np.ndarray, np.ndarray, np.ndarray,
                                 np.ndarray]:
    """Binary quantile-sketch column -> flat entries (rows, values,
    weights) sorted by row, plus per-ROW caps (0 for null rows). Raises
    DaftValueError on corrupt payloads."""
    if hasattr(arr, "to_arrow"):
        arr = arr.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.large_binary())
    n = len(arr)
    caps_out = np.zeros(n, dtype=np.int64)
    empty = (np.empty(0, np.int64), np.empty(0, np.float64),
             np.empty(0, np.float64), caps_out)
    if n == 0:
        return empty
    bufs = arr.buffers()
    offs = np.frombuffer(bufs[1], dtype=np.int64)[arr.offset:arr.offset + n + 1]
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None else \
        np.empty(0, np.uint8)
    lengths = np.diff(offs)
    valid = np.asarray(pc.is_valid(arr))
    lengths = np.where(valid, lengths, 0)
    rows = np.nonzero(lengths > 0)[0]
    if len(rows) == 0:
        return empty
    if (lengths[rows] < 8).any():
        raise DaftValueError("corrupt quantile sketch: bad payload length")
    caps = _read_u32_le(data, offs[rows]).astype(np.int64)
    counts = _read_u32_le(data, offs[rows] + 4).astype(np.int64)
    if (lengths[rows] != 8 + 16 * counts).any():
        raise DaftValueError("corrupt quantile sketch: bad entry count")
    caps_out[rows] = caps
    total = int(counts.sum())
    if total == 0:
        return (np.empty(0, np.int64), np.empty(0, np.float64),
                np.empty(0, np.float64), caps_out)
    row_rep = np.repeat(rows, counts)
    starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
    j = np.arange(total) - np.repeat(starts, counts)
    vpos = np.repeat(offs[rows] + 8, counts) + 8 * j
    wpos = np.repeat(offs[rows] + 8 + 8 * counts, counts) + 8 * j
    return (row_rep, _read_f8_le(data, vpos), _read_f8_le(data, wpos),
            caps_out)


def _encode_states(groups: np.ndarray, values: np.ndarray,
                   weights: np.ndarray, caps: np.ndarray,
                   num_rows: int) -> pa.Array:
    """Flat entries (sorted by group) + per-row caps -> large_binary array
    of num_rows sketches, one vectorized buffer fill."""
    counts = np.bincount(groups, minlength=num_rows) if len(groups) else \
        np.zeros(num_rows, dtype=np.int64)
    coo_offs = np.concatenate([[0], np.cumsum(counts)])
    lengths = 8 + 16 * counts
    offsets = np.concatenate([[0], np.cumsum(lengths)])
    buf = np.zeros(int(offsets[-1]), dtype=np.uint8)
    _write_u32_le(buf, offsets[:-1], caps)
    _write_u32_le(buf, offsets[:-1] + 4, counts)
    if len(groups):
        j = np.arange(len(groups)) - coo_offs[groups]
        vpos = offsets[groups] + 8 + 8 * j
        wpos = offsets[groups] + 8 + 8 * counts[groups] + 8 * j
        _write_f8_le(buf, vpos, values)
        _write_f8_le(buf, wpos, weights)
    return pa.Array.from_buffers(
        pa.large_binary(), num_rows,
        [None, pa.py_buffer(offsets.astype(np.int64).tobytes()),
         pa.py_buffer(buf.tobytes())])


def _compress_groups(groups: np.ndarray, values: np.ndarray,
                     weights: np.ndarray, caps: np.ndarray,
                     num_groups: int):
    """Compress only the groups whose entry count exceeds their cap (at
    most total/cap of them); everything else passes through untouched.
    Entries must arrive (and leave) sorted by group."""
    counts = np.bincount(groups, minlength=num_groups) if len(groups) else \
        np.zeros(num_groups, dtype=np.int64)
    over = np.nonzero(counts > caps[:num_groups])[0]
    if len(over) == 0:
        return groups, values, weights
    offs = np.concatenate([[0], np.cumsum(counts)])
    keep = np.ones(len(groups), dtype=bool)
    add_g: List[np.ndarray] = []
    add_v: List[np.ndarray] = []
    add_w: List[np.ndarray] = []
    for g in over:
        s, e = int(offs[g]), int(offs[g + 1])
        cv, cw = quantile_compress(values[s:e], weights[s:e], int(caps[g]))
        keep[s:e] = False
        add_g.append(np.full(len(cv), g, dtype=np.int64))
        add_v.append(cv)
        add_w.append(cw)
    groups = np.concatenate([groups[keep]] + add_g)
    values = np.concatenate([values[keep]] + add_v)
    weights = np.concatenate([weights[keep]] + add_w)
    order = np.argsort(groups, kind="stable")
    return groups[order], values[order], weights[order]


def build_grouped(series, codes: Optional[np.ndarray], num_groups: int):
    """One serialized quantile sketch per group (Binary Series) — the
    stage-1 kernel behind the `sketch_quantile` AggExpr kind."""
    from ..datatypes import DataType
    from ..series import Series

    _require_numeric(series)
    vals = series.cast(DataType.float64()).to_arrow()
    if isinstance(vals, pa.ChunkedArray):
        vals = vals.combine_chunks()
    v = np.asarray(pc.fill_null(vals, np.nan), dtype=np.float64)
    if codes is None:
        codes = np.zeros(len(v), dtype=np.int64)
    good = ~np.isnan(v)
    groups = np.asarray(codes, dtype=np.int64)[good]
    v = v[good]
    order = np.argsort(groups, kind="stable")
    groups, v = groups[order], v[order]
    caps = np.full(num_groups, QUANTILE_CAP, dtype=np.int64)
    groups, v, w = _compress_groups(groups, v, np.ones(len(v)), caps,
                                    num_groups)
    out = _encode_states(groups, v, w, caps, num_groups)
    return Series.from_arrow(out, series.name, DataType.binary())


def merge_grouped(series, codes: Optional[np.ndarray], num_groups: int):
    """Merge serialized quantile sketches per group (weighted-sample concat
    + deterministic re-compression) — the stage-2 kernel behind
    `merge_sketch_quantile` (fault site `sketch.merge`). A merge never
    LOWERS precision: each group keeps the max cap of its inputs."""
    from .. import faults
    from ..datatypes import DataType
    from ..series import Series

    faults.check("sketch.merge")
    rows, v, w, row_caps = _decode_states(series)
    if codes is None:
        groups = np.zeros(len(rows), dtype=np.int64)
        row_groups = np.zeros(len(row_caps), dtype=np.int64)
    else:
        codes = np.asarray(codes, dtype=np.int64)
        groups = codes[rows]
        row_groups = codes
    caps = np.full(num_groups, 0, dtype=np.int64)
    if len(row_caps):
        np.maximum.at(caps, row_groups[:len(row_caps)], row_caps)
    caps[caps == 0] = QUANTILE_CAP
    order = np.argsort(groups, kind="stable")
    groups, v, w = groups[order], v[order], w[order]
    groups, v, w = _compress_groups(groups, v, w, caps, num_groups)
    out = _encode_states(groups, v, w, caps, num_groups)
    return Series.from_arrow(out, series.name, DataType.binary())


def estimate_series(series, percentiles):
    """Per-row percentile estimates of a Binary sketch column (the final
    projection's `sketch.quantile_estimate` function). Scalar percentile ->
    float64 column; list -> list<float64> column. Empty sketches -> null."""
    from ..datatypes import DataType
    from ..series import Series

    single = isinstance(percentiles, float)
    qs = [percentiles] if single else list(percentiles)
    if not qs:
        raise DaftValueError("approx_percentiles needs at least one percentile")
    arr = series.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    rows, v, w, _caps = _decode_states(arr)
    counts = np.bincount(rows, minlength=n) if len(rows) else \
        np.zeros(n, dtype=np.int64)
    offs = np.concatenate([[0], np.cumsum(counts)])
    out_rows: List = []
    null_rows = np.asarray(pc.is_null(arr)) if arr.null_count else \
        np.zeros(n, dtype=bool)
    for i in range(n):
        if null_rows[i] or counts[i] == 0:
            out_rows.append(None)
            continue
        s, e = int(offs[i]), int(offs[i + 1])
        ests = weighted_quantiles(v[s:e], w[s:e], qs)
        out_rows.append(ests[0] if single else ests)
    if single:
        return Series.from_arrow(pa.array(out_rows, type=pa.float64()),
                                 series.name, DataType.float64())
    out = pa.array(out_rows, type=pa.large_list(pa.float64()))
    return Series.from_arrow(out, series.name,
                             DataType.list(DataType.float64()))


def percentile_estimate(series, percentiles):
    """Global approx_percentiles of one numeric Series via a single sketch:
    (value | list | None) matching the engine's approx_percentiles output."""
    from ..datatypes import DataType

    single = isinstance(percentiles, float)
    qs = [percentiles] if single else list(percentiles)
    _require_numeric(series)
    vals = series.cast(DataType.float64()).to_arrow()
    if isinstance(vals, pa.ChunkedArray):
        vals = vals.combine_chunks()
    v = np.asarray(pc.fill_null(vals, np.nan), dtype=np.float64)
    v = v[~np.isnan(v)]
    cv, cw = quantile_compress(v, np.ones(len(v)))
    ests = weighted_quantiles(cv, cw, qs)
    if single:
        return ests[0]
    return None if ests[0] is None else ests
