"""Mergeable-sketch aggregation subsystem.

Role-equivalent to the reference's src/daft-sketch/ + src/hyperloglog/ wired
through the planner's two-stage aggregation decomposition
(src/daft-plan/src/physical_planner/translate.rs:761): approximate
aggregations decompose into

  stage 1  one fixed-size sketch per partition/group
           (`sketch_hll` / `sketch_quantile` AggExpr kinds -> Binary column)
  exchange serialized sketch bytes ride the existing ShuffleOp/GatherOp as a
           Binary column — payload is O(sketch_size x partitions), never raw
           rows; on a mesh the global HLL case merges register arrays with a
           jit'd all_gather+max collective (parallel/collectives.py)
  stage 2  registers merge per group (`merge_sketch_*` kinds, elementwise
           max / weighted-sample concat) -> Binary column
  final    a scalar projection finalizes the estimate
           (functions `sketch.hll_estimate` / `sketch.quantile_estimate`)

The math lives in kernels/sketches.py (register ranks, estimates,
deterministic quantile compression); this package is the engine glue:
grouped builds/merges over arrow-backed Series (hll.py, quantile.py), the
device register-scatter (device.py), and the kind registry the planner and
Table kernels share.

Error bounds (enforced by tests/test_sketch_aggs.py, not eyeballed):
- HLL relative error <= 2 x 1.04/sqrt(HLL_M)  (~1.63% at m=16384)
- quantile rank error <= 1/QUANTILE_CAP of the total weight (~0.024%)
"""

from __future__ import annotations

from ..kernels.sketches import (  # noqa: F401  (re-exported subsystem API)
    HLL_M,
    HLL_P,
    HLL_STANDARD_ERROR,
    QUANTILE_CAP,
    estimate_from_registers,
    register_ranks,
)

#: stage-1 AggExpr kinds: build one serialized sketch per group
STAGE1_KINDS = frozenset({"sketch_hll", "sketch_quantile"})
#: stage-2 AggExpr kinds: merge serialized sketches per group
MERGE_KINDS = frozenset({"merge_sketch_hll", "merge_sketch_quantile"})
#: every sketch-stage kind (planner-internal; users write approx_*)
SKETCH_STAGE_KINDS = STAGE1_KINDS | MERGE_KINDS
#: user-facing aggregations that decompose into sketch->merge stages
SKETCH_DECOMPOSABLE = frozenset({"approx_count_distinct",
                                 "approx_percentiles"})
