"""Grouped/global HyperLogLog builds, merges, and estimates over Series.

The build path hashes rows with the engine's murmur-based host hash
(kernels/host_hash — the same hashes every shuffle uses, so all dtypes that
can be grouped can be sketched) and reduces (group, register) pairs to
their max rank; the merge path is the same reduction over decoded entries.

Serialized form is ADAPTIVE per sketch, so high group cardinality — the
SF100 regime that motivated the subsystem — never inflates the exchange:

- dense:  exactly HLL_M bytes of raw uint8 registers (compact once a
          sketch has many occupied registers);
- sparse: ``<u4 count, <u4 reserved, count x <u4 (idx << 8 | rank)`` for
          sketches with <= SPARSE_LIMIT occupied registers — a group seen
          k times costs O(min(k, m) x 4) bytes, comparable to the raw rows
          the two-phase plan replaces, instead of a fixed 16 KiB.

The two are distinguished by length alone (a sparse payload is at most
8 + 4 x SPARSE_LIMIT < HLL_M bytes). Everything internal flows through a
COO representation (rows, idxs, ranks) — builds, merges, and estimates
are vectorized and never allocate [num_groups, HLL_M] matrices.
"""
# daftlint: migrated

from __future__ import annotations

from typing import Optional, Tuple

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from ..errors import DaftValueError
from ..kernels.host_hash import hash_array
from ..kernels.sketches import (
    HLL_M,
    HLL_P,
    estimate_from_histogram,
    estimate_from_registers,
    register_ranks,
)

SKETCH_BYTES = HLL_M  # dense payload: one uint8 register per slot
MAX_RANK = 64 - HLL_P + 1
#: occupied-register count above which dense (16 KiB) is the smaller form
SPARSE_LIMIT = 2048


def _reduce_max(seg: np.ndarray, rank: np.ndarray):
    """Unique segment ids with their max rank (sorted by segment)."""
    if len(seg) == 0:
        return seg, rank
    order = np.lexsort((rank, seg))
    seg_s, rank_s = seg[order], rank[order]
    last = np.concatenate([seg_s[1:] != seg_s[:-1], [True]])
    return seg_s[last], rank_s[last]


def _write_u32_le(buf: np.ndarray, pos: np.ndarray, vals: np.ndarray) -> None:
    """Scatter little-endian uint32 values at arbitrary byte positions
    (no alignment assumption — arrow value offsets carry no guarantee)."""
    v = vals.astype(np.uint32)
    for k in range(4):
        buf[pos + k] = ((v >> np.uint32(8 * k)) & np.uint32(0xFF)).astype(np.uint8)


def _read_u32_le(data: np.ndarray, pos: np.ndarray) -> np.ndarray:
    out = data[pos].astype(np.uint32)
    for k in range(1, 4):
        out |= data[pos + k].astype(np.uint32) << np.uint32(8 * k)
    return out


def _encode_rows(groups: np.ndarray, idxs: np.ndarray, ranks: np.ndarray,
                 num_rows: int) -> pa.Array:
    """COO entries (sorted by group) -> large_binary array of num_rows
    sketches, each dense or sparse by its own occupancy. Fully vectorized:
    one output buffer, entries scattered by computed byte positions."""
    counts = np.bincount(groups, minlength=num_rows) if len(groups) else \
        np.zeros(num_rows, dtype=np.int64)
    coo_offs = np.concatenate([[0], np.cumsum(counts)])
    dense = counts > SPARSE_LIMIT
    lengths = np.where(dense, HLL_M, 8 + 4 * counts)
    offsets = np.concatenate([[0], np.cumsum(lengths)])
    buf = np.zeros(int(offsets[-1]), dtype=np.uint8)
    if len(groups):
        entry_dense = dense[groups]
        # dense rows: registers scattered straight into the payload
        if entry_dense.any():
            g = groups[entry_dense]
            buf[offsets[g] + idxs[entry_dense]] = ranks[entry_dense]
        # sparse rows: <u4 header (count), zero reserved word, packed entries
        sp_rows = np.nonzero(~dense)[0]
        _write_u32_le(buf, offsets[sp_rows], counts[sp_rows])
        sp = ~entry_dense
        if sp.any():
            g = groups[sp]
            j = (np.arange(len(groups)) - coo_offs[groups])[sp]
            pos = offsets[g] + 8 + 4 * j
            packed = (idxs[sp].astype(np.uint32) << np.uint32(8)) | ranks[sp]
            _write_u32_le(buf, pos, packed)
    else:
        sp_rows = np.arange(num_rows)
        _write_u32_le(buf, offsets[sp_rows], np.zeros(num_rows, np.int64))
    return pa.Array.from_buffers(
        pa.large_binary(), num_rows,
        [None, pa.py_buffer(offsets.astype(np.int64).tobytes()),
         pa.py_buffer(buf.tobytes())])


def _decode_rows(arr) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Binary sketch column -> COO (row, idx, rank), validated. Null rows
    contribute no entries. Raises DaftValueError on any corrupt payload."""
    if hasattr(arr, "to_arrow"):
        arr = arr.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    arr = arr.cast(pa.large_binary())
    n = len(arr)
    empty = (np.empty(0, np.int64), np.empty(0, np.int64),
             np.empty(0, np.uint8))
    if n == 0:
        return empty
    bufs = arr.buffers()
    offs = np.frombuffer(bufs[1], dtype=np.int64)[arr.offset:arr.offset + n + 1]
    data = np.frombuffer(bufs[2], dtype=np.uint8) if bufs[2] is not None else \
        np.empty(0, np.uint8)
    lengths = np.diff(offs)
    valid = np.asarray(pc.is_valid(arr))
    lengths = np.where(valid, lengths, 0)
    dense = valid & (lengths == HLL_M)
    sparse = valid & (lengths != HLL_M) & (lengths > 0)
    if ((lengths[sparse] < 8) | ((lengths[sparse] - 8) % 4 != 0)).any():
        raise DaftValueError("corrupt HLL sketch: bad payload length")
    rows_out, idx_out, rank_out = [], [], []
    d_rows = np.nonzero(dense)[0]
    if len(d_rows):
        block = data[offs[d_rows][:, None] + np.arange(HLL_M)]
        if int(block.max(initial=0)) > MAX_RANK:
            raise DaftValueError(
                f"corrupt HLL sketch: register value exceeds max rank {MAX_RANK}")
        r, i = np.nonzero(block)
        rows_out.append(d_rows[r])
        idx_out.append(i.astype(np.int64))
        rank_out.append(block[r, i])
    s_rows = np.nonzero(sparse)[0]
    if len(s_rows):
        counts = _read_u32_le(data, offs[s_rows]).astype(np.int64)
        if (counts != (lengths[s_rows] - 8) // 4).any() or \
                (counts > SPARSE_LIMIT).any():
            raise DaftValueError("corrupt HLL sketch: bad sparse entry count")
        total = int(counts.sum())
        if total:
            row_rep = np.repeat(s_rows, counts)
            starts = np.concatenate([[0], np.cumsum(counts)])[:-1]
            j = np.arange(total) - np.repeat(starts, counts)
            pos = np.repeat(offs[s_rows] + 8, counts) + 4 * j
            packed = _read_u32_le(data, pos)
            idx = (packed >> np.uint32(8)).astype(np.int64)
            rank = (packed & np.uint32(0xFF)).astype(np.uint8)
            if int(idx.max(initial=0)) >= HLL_M or \
                    int(rank.max(initial=0)) > MAX_RANK or (rank == 0).any():
                raise DaftValueError("corrupt HLL sketch: bad sparse entry")
            rows_out.append(row_rep)
            idx_out.append(idx)
            rank_out.append(rank)
    if not rows_out:
        return empty
    return (np.concatenate(rows_out), np.concatenate(idx_out),
            np.concatenate(rank_out).astype(np.uint8))


def registers_to_binary(regs: np.ndarray) -> pa.Array:
    """[G, HLL_M] uint8 register rows -> binary sketches (adaptive
    encoding, identical bytes to the COO build of the same registers)."""
    g, i = np.nonzero(regs)
    return _encode_rows(g.astype(np.int64), i.astype(np.int64),
                        np.asarray(regs)[g, i], regs.shape[0])


def binary_to_registers(arr) -> np.ndarray:
    """Binary sketch column -> DENSE [n, HLL_M] uint8 registers. For the
    few-row cases only (the mesh collective merges one row per partition);
    group-cardinality-scaled paths stay in COO form."""
    if hasattr(arr, "to_arrow"):
        arr = arr.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    rows, idxs, ranks = _decode_rows(arr)
    out = np.zeros((len(arr), HLL_M), dtype=np.uint8)
    out[rows, idxs] = ranks
    return out


def scatter_operands(arr: pa.Array, codes: Optional[np.ndarray] = None):
    """(codes, idx, rank) for the valid rows of `arr` — the register-scatter
    operands shared by the host build below and the device build
    (sketch/device.py). `codes` None means one global group (zeros)."""
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if codes is None:
        codes = np.zeros(len(arr), dtype=np.int64)
    if arr.null_count:
        valid = np.asarray(pc.is_valid(arr))
        arr = arr.drop_null()
        codes = codes[valid]
    if len(arr) == 0:
        return codes[:0], np.empty(0, np.int64), np.empty(0, np.uint8)
    idx, rank = register_ranks(hash_array(arr))
    return codes, idx, rank


def build_grouped_registers(arr: pa.Array,
                            codes: Optional[np.ndarray],
                            num_groups: int) -> np.ndarray:
    """[num_groups, HLL_M] DENSE register rows from one column + group
    codes (global estimates, tests, and the device-parity check; the
    grouped Series path uses the COO build below)."""
    regs = np.zeros((num_groups, HLL_M), dtype=np.uint8)
    gcodes, idx, rank = scatter_operands(arr, codes)
    if len(idx):
        np.maximum.at(regs, (gcodes, idx), rank)
    return regs


def build_grouped(series, codes: Optional[np.ndarray], num_groups: int):
    """One serialized HLL sketch per group (Binary Series) — the stage-1
    kernel behind the `sketch_hll` AggExpr kind. COO end to end: memory and
    payload scale with occupied registers, not num_groups x 16 KiB."""
    from ..datatypes import DataType
    from ..series import Series

    if series.is_python():
        series = series.cast(DataType.string())
    gcodes, idx, rank = scatter_operands(series.to_arrow(), codes)
    seg = gcodes.astype(np.int64) * HLL_M + idx
    useg, urank = _reduce_max(seg, rank)
    out = _encode_rows(useg // HLL_M, useg % HLL_M, urank, num_groups)
    return Series.from_arrow(out, series.name, DataType.binary())


def merge_grouped(series, codes: Optional[np.ndarray], num_groups: int):
    """Merge serialized sketches per group (register max over decoded
    entries) — the stage-2 kernel behind `merge_sketch_hll`. This is the
    subsystem's merge fault boundary (site `sketch.merge`)."""
    from .. import faults
    from ..datatypes import DataType
    from ..series import Series

    faults.check("sketch.merge")
    rows, idxs, ranks = _decode_rows(series)
    if codes is None:
        groups = np.zeros(len(rows), dtype=np.int64)
    else:
        groups = np.asarray(codes, dtype=np.int64)[rows]
    seg = groups * HLL_M + idxs
    useg, urank = _reduce_max(seg, ranks)
    out = _encode_rows(useg // HLL_M, useg % HLL_M, urank, num_groups)
    return Series.from_arrow(out, series.name, DataType.binary())


def estimate_series(series):
    """Per-row cardinality estimates of a Binary sketch column (the final
    projection's `sketch.hll_estimate` function) — histograms built
    straight from COO entries, no densification. Null sketches -> null."""
    from ..datatypes import DataType
    from ..series import Series

    arr = series.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    n = len(arr)
    rows, _idxs, ranks = _decode_rows(arr)
    hist = np.zeros((n, MAX_RANK + 1), dtype=np.float64)
    if len(rows):
        np.add.at(hist, (rows, ranks.astype(np.int64)), 1.0)
    nnz = hist[:, 1:].sum(axis=1)
    hist[:, 0] = HLL_M - nnz
    est = estimate_from_histogram(hist, HLL_M)
    mask = np.asarray(pc.is_null(arr)) if arr.null_count else None
    out = pa.array(est, type=pa.uint64(), mask=mask)
    return Series.from_arrow(out, series.name, DataType.uint64())


def grouped_estimates(series, codes: Optional[np.ndarray],
                      num_groups: int) -> np.ndarray:
    """Per-group cardinality estimates in one COO pass (build + histogram
    + estimate, no per-group 16 KiB materialization) — the grouped
    approx_count_distinct kernel for single-partition execution."""
    from ..datatypes import DataType

    if series.is_python():
        series = series.cast(DataType.string())
    gcodes, idx, rank = scatter_operands(series.to_arrow(), codes)
    seg = gcodes.astype(np.int64) * HLL_M + idx
    useg, urank = _reduce_max(seg, rank)
    hist = np.zeros((num_groups, MAX_RANK + 1), dtype=np.float64)
    if len(useg):
        np.add.at(hist, (useg // HLL_M, urank.astype(np.int64)), 1.0)
    hist[:, 0] = HLL_M - hist[:, 1:].sum(axis=1)
    return estimate_from_histogram(hist, HLL_M)


def count_distinct_estimate(series) -> int:
    """Global approx_count_distinct of one Series via a single HLL build."""
    from ..datatypes import DataType

    if series.is_python():
        series = series.cast(DataType.string())
    regs = build_grouped_registers(series.to_arrow(), None, 1)
    return int(estimate_from_registers(regs)[0])
