"""Device register-scatter for HLL sketch builds.

The control plane stays on host (murmur hashes + group codes — the same
split every shuffle uses); the data plane, scattering register ranks into
[num_groups, HLL_M] with a segment max, runs as ONE jit'd XLA program on
the device. Callers route through ExecutionContext._device_attempt, so the
scatter sits behind the existing DeviceHealth breaker and the
`device.kernel` fault site like every other device kernel.
"""
# daftlint: migrated

from __future__ import annotations

import functools
from typing import Optional

import numpy as np

from ..kernels.sketches import HLL_M

#: register-matrix ceiling for the device path: past this the [G, HLL_M]
#: scatter output (int32 on device) stops being a sensible HBM tenant
MAX_DEVICE_REGISTERS = 1 << 24


@functools.lru_cache(maxsize=32)
def _scatter_fn(num_segments: int):
    import jax
    import jax.numpy as jnp

    def body(seg, rank):
        regs = jax.ops.segment_max(rank, seg, num_segments=num_segments)
        # empty segments come back at int32 min; registers floor at 0
        return jnp.maximum(regs, 0).astype(jnp.uint8)

    return jax.jit(body)


def _segment_bucket(n: int) -> int:
    """Round the segment count up to a power of two so distinct group
    cardinalities bucket into few compilations (same discipline as
    collectives.exchange_capacity)."""
    cap = HLL_M  # at least one group
    while cap < n:
        cap <<= 1
    return cap


def aggs_all_sketch_hll(aggregations) -> bool:
    """Cheap host-side gate: every aggregation is a stage-1 `sketch_hll`.
    Callers MUST check this before touching the breaker or the device
    fault site — a declined probe for a non-sketch agg would double-count
    breaker state and shift deterministic fault plans."""
    from ..expressions import AggExpr, Alias

    if not aggregations:
        return False
    for e in aggregations:
        node = e._node
        while isinstance(node, Alias):
            node = node.child
        if not (isinstance(node, AggExpr) and node.kind == "sketch_hll"):
            return False
    return True


def hll_scatter_device_launch(codes: np.ndarray, idx: np.ndarray,
                              rank: np.ndarray, num_groups: int):
    """Dispatch the register segment-max on device WITHOUT blocking (jax
    arrays are async until fetched); returns a zero-arg resolver yielding
    [num_groups, HLL_M] uint8 rows, or None when the shape is
    device-ineligible. Raises on device failure — the caller's
    _device_attempt / finish() records it against the breaker."""
    total = num_groups * HLL_M
    if total > MAX_DEVICE_REGISTERS or total >= (1 << 31):
        return None
    import jax

    nseg = _segment_bucket(total)
    seg = (codes.astype(np.int64) * HLL_M + idx).astype(np.int32)
    fn = _scatter_fn(nseg)
    out_dev = fn(jax.numpy.asarray(seg), jax.numpy.asarray(rank.astype(np.int32)))

    def resolve() -> np.ndarray:
        out = np.asarray(jax.device_get(out_dev))
        return out[:total].reshape(num_groups, HLL_M)

    return resolve


def hll_scatter_device(codes: np.ndarray, idx: np.ndarray, rank: np.ndarray,
                       num_groups: int) -> Optional[np.ndarray]:
    """Blocking variant of hll_scatter_device_launch (tests, direct use)."""
    resolve = hll_scatter_device_launch(codes, idx, rank, num_groups)
    return None if resolve is None else resolve()


def hll_build_table_device_launch(table, aggregations, groupby):
    """Stage-1 `sketch_hll` aggregation of one partition with the register
    scatter on device, split launch/resolve so the executor stages the next
    partition while this one's scatter runs: staging (hashing, group codes,
    device dispatch) happens NOW; the returned resolver fetches the
    registers and assembles the (keys + Binary sketch columns) Table.
    Returns None when ineligible (non-HLL agg kinds, oversized group
    count). Shares _group_codes with the host path so group order is
    identical."""
    from ..datatypes import DataType
    from ..schema import Field, Schema
    from ..series import Series
    from ..table import Table, _group_codes
    from .hll import registers_to_binary, scatter_operands

    if not aggs_all_sketch_hll(aggregations):
        return None
    from ..expressions import Alias

    nodes = []
    for e in aggregations:
        node = e._node
        while isinstance(node, Alias):
            node = node.child
        nodes.append((e.name(), node))
    n = len(table)
    if groupby:
        key_tbl = table.eval_expression_list(list(groupby))
        codes, uniq = _group_codes(key_tbl)
        num_groups = len(uniq)
        out_cols = list(uniq._columns)
        out_fields = list(uniq.schema)
    else:
        codes = np.zeros(n, dtype=np.int64)
        num_groups = 1
        out_cols = []
        out_fields = []
    if num_groups * HLL_M > MAX_DEVICE_REGISTERS:
        return None
    pending = []
    for alias, node in nodes:
        child = node.child.evaluate(table)
        if child.is_python():
            child = child.cast(DataType.string())
        gcodes, idx, rank = scatter_operands(child.to_arrow(), codes)
        resolve = hll_scatter_device_launch(gcodes, idx, rank, num_groups)
        if resolve is None:
            return None
        pending.append((alias, resolve))

    def finish() -> Table:
        cols = list(out_cols)
        fields = list(out_fields)
        for alias, resolve in pending:
            s = Series.from_arrow(registers_to_binary(resolve()), alias,
                                  DataType.binary())
            cols.append(s.rename(alias))
            fields.append(Field(alias, DataType.binary()))
        return Table(Schema(fields), cols)

    return finish


def hll_build_table_device(table, aggregations, groupby):
    """Blocking variant of hll_build_table_device_launch."""
    fin = hll_build_table_device_launch(table, aggregations, groupby)
    return None if fin is None else fin()
