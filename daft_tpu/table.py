"""Table: an eager multi-column batch (schema + equal-length Series).

Role-equivalent to the reference's Table (src/daft-table/src/lib.rs) and its ops/
directory (agg.rs, groups.rs, sort.rs, partition.rs, joins/, explode.rs, pivot.rs,
unpivot.rs). Host kernels are pyarrow/acero + numpy; the executor routes
device-eligible pipelines through the jax kernel layer (kernels/device.py) instead.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .errors import DaftValueError
from .datatypes import DataType, TypeKind, try_unify
from .expressions import (
    AggExpr,
    Alias,
    Expression,
    ExpressionsProjection,
    _eval_agg_on_series,
    col,
)
from .kernels.host_hash import hash_table_columns
from .schema import Field, Schema
from .series import Series


def _downcast_key_offsets(arr):
    """large_string/large_binary -> 32-bit-offset variant when the buffer fits
    (< 2GiB): acero's hash table is ~3x slower on 64-bit-offset keys. Shared
    by the join and _acero_grouped_agg; the fused filter+agg path mirrors the
    same rule at the acero-expression level (it casts expressions, not
    arrays)."""
    if arr.nbytes < (1 << 31) - 1:
        if pa.types.is_large_string(arr.type):
            return arr.cast(pa.string())
        if pa.types.is_large_binary(arr.type):
            return arr.cast(pa.binary())
    return arr


def _as_expressions(exprs) -> List[Expression]:
    if isinstance(exprs, Expression):
        return [exprs]
    out = []
    for e in exprs:
        out.append(col(e) if isinstance(e, str) else e)
    return out


class Table:
    __slots__ = ("schema", "_columns", "_memo_by_thread", "__weakref__")

    def __init__(self, schema: Schema, columns: List[Series]):
        if len(schema) != len(columns):
            raise DaftValueError(f"schema has {len(schema)} fields but got {len(columns)} columns")
        n = len(columns[0]) if columns else 0
        for f, c in zip(schema, columns):
            if len(c) != n:
                raise DaftValueError(f"column {f.name!r} length {len(c)} != {n}")
        self.schema = schema
        self._columns = columns
        # per-thread cache of evaluated subexpressions, active only inside
        # _memo_scope (tables are immutable, so hits are always sound; the
        # scope bounds the lifetime of the cached column-sized intermediates).
        # Keyed by thread ident: the same Table may be evaluated concurrently
        # from different worker threads (shared InMemorySource partitions) and
        # the depth counter must not race across them.
        self._memo_by_thread: Dict[int, list] = {}

    @property
    def _eval_memo(self) -> Optional[Dict[Tuple, Series]]:
        state = self._memo_by_thread.get(threading.get_ident())
        return state[0] if state is not None else None

    @contextmanager
    def _memo_scope(self):
        """Share structurally-identical subexpression results across the
        evaluates of one logical pass; dropped when the outermost scope
        exits so intermediates are not pinned for the table's lifetime."""
        tid = threading.get_ident()
        state = self._memo_by_thread.get(tid)
        if state is None:
            state = self._memo_by_thread[tid] = [{}, 0]
        state[1] += 1
        try:
            yield
        finally:
            state[1] -= 1
            if state[1] == 0:
                self._memo_by_thread.pop(tid, None)

    # ------------------------------------------------------------------ ctors
    @staticmethod
    def empty(schema: Optional[Schema] = None) -> "Table":
        schema = schema or Schema.empty()
        return Table(schema, [Series.empty(f.name, f.dtype) for f in schema])

    @staticmethod
    def from_pydict(data: Dict[str, Any]) -> "Table":
        cols: List[Series] = []
        for name, vals in data.items():
            if isinstance(vals, Series):
                cols.append(vals.rename(name))
            elif isinstance(vals, (pa.Array, pa.ChunkedArray)):
                cols.append(Series.from_arrow(vals, name))
            elif isinstance(vals, np.ndarray):
                cols.append(Series.from_numpy(vals, name))
            else:
                cols.append(Series.from_pylist(list(vals), name))
        n = max((len(c) for c in cols), default=0)
        cols = [c if len(c) == n else _broadcast_series(c, n) for c in cols]
        schema = Schema([Field(c.name, c.dtype) for c in cols])
        return Table(schema, cols)

    @staticmethod
    def from_arrow(tbl: Union[pa.Table, pa.RecordBatch]) -> "Table":
        if isinstance(tbl, pa.RecordBatch):
            tbl = pa.Table.from_batches([tbl])
        tbl = tbl.combine_chunks()
        cols = [Series.from_arrow(tbl.column(i), tbl.schema.names[i]) for i in range(tbl.num_columns)]
        schema = Schema([Field(c.name, c.dtype) for c in cols])
        return Table(schema, cols)

    @staticmethod
    def from_pylist(rows: List[dict]) -> "Table":
        keys: List[str] = []
        for r in rows:
            for k in r:
                if k not in keys:
                    keys.append(k)
        return Table.from_pydict({k: [r.get(k) for r in rows] for k in keys})

    # ------------------------------------------------------------------ basics
    def __len__(self) -> int:
        return len(self._columns[0]) if self._columns else 0

    @property
    def column_names(self) -> List[str]:
        return self.schema.field_names()

    def columns(self) -> List[Series]:
        return list(self._columns)

    def get_column(self, name: str) -> Series:
        return self._columns[self.schema.index(name)]

    def num_columns(self) -> int:
        return len(self._columns)

    def size_bytes(self) -> int:
        return sum(c.size_bytes() for c in self._columns)

    def to_arrow(self) -> pa.Table:
        arrays, fields = [], []
        for f, c in zip(self.schema, self._columns):
            if c.is_python():
                raise DaftValueError(f"column {f.name!r} has python dtype; no arrow representation")
            arrays.append(c.to_arrow())
            fields.append(pa.field(f.name, c.to_arrow().type))
        return pa.Table.from_arrays(arrays, schema=pa.schema(fields))

    def to_pydict(self) -> Dict[str, list]:
        return {f.name: c.to_pylist() for f, c in zip(self.schema, self._columns)}

    def to_pylist(self) -> List[dict]:
        d = self.to_pydict()
        names = list(d)
        return [dict(zip(names, vals)) for vals in zip(*d.values())] if names else []

    def to_pandas(self):
        return self.to_arrow().to_pandas()

    def __repr__(self) -> str:
        return f"Table({self.schema!r}, rows={len(self)})"

    def select_columns(self, names: List[str]) -> "Table":
        return Table(self.schema.select(names), [self.get_column(n) for n in names])

    def rename_columns(self, mapping: Dict[str, str]) -> "Table":
        return Table(self.schema.rename(mapping),
                     [c.rename(mapping.get(c.name, c.name)) for c in self._columns])

    def cast_to_schema(self, schema: Schema) -> "Table":
        cols = []
        for f in schema:
            if f.name in self.schema:
                cols.append(self.get_column(f.name).cast(f.dtype))
            else:
                cols.append(Series.full_null(f.name, f.dtype, len(self)))
        return Table(schema, cols)

    # ------------------------------------------------------------------ eval
    def eval_expression_list(self, exprs: Sequence[Expression]) -> "Table":
        exprs = _as_expressions(exprs)
        n = len(self)
        out: List[Series] = []
        names: List[str] = []
        any_agg = any(e._node.is_aggregation() for e in exprs)
        with self._memo_scope():
            for e in exprs:
                s = e._node.evaluate(self)
                out.append(s)
                names.append(e.name())
        if any_agg:
            m = max((len(s) for s in out), default=0)
        else:
            m = n
        out = [_broadcast_series(s, m) if len(s) != m else s for s in out]
        schema = Schema([Field(nm, s.dtype) for nm, s in zip(names, out)])
        return Table(schema, [s.rename(nm) for nm, s in zip(names, out)])

    # ------------------------------------------------------------------ selection
    def filter(self, predicate: Union[Expression, Sequence[Expression]]) -> "Table":
        preds = _as_expressions(predicate)
        mask: Optional[Series] = None
        with self._memo_scope():
            for p in preds:
                s = p._node.evaluate(self)
                if not s.dtype.is_boolean() and not s.dtype.is_null():
                    raise DaftValueError(f"filter predicate must be boolean, got {s.dtype}")
                mask = s if mask is None else (mask & s)
        if mask is None:
            return self
        return self.filter_with_mask(mask)

    def filter_with_mask(self, mask: Series) -> "Table":
        """Compact rows by a precomputed boolean mask (the device filter path
        computes the predicate on the TPU and hands the mask back here)."""
        mask = _broadcast_series(mask, len(self))
        m = mask._arrow
        if m is None:
            return Table(self.schema, [c.filter(mask) for c in self._columns])
        if m.null_count:
            m = pc.fill_null(m, False)
        # one multithreaded arrow-table filter instead of a per-column pass
        arrow_idx = [i for i, c in enumerate(self._columns) if c._arrow is not None]
        ftbl = None
        if arrow_idx:
            ftbl = pa.Table.from_arrays(
                [self._columns[i]._arrow for i in arrow_idx],
                names=[str(i) for i in arrow_idx]).filter(m)
        out: List[Series] = []
        for i, c in enumerate(self._columns):
            if c._arrow is None:
                out.append(c.filter(mask))
            else:
                ch = ftbl.column(str(i))
                arr = ch.chunk(0) if ch.num_chunks == 1 else ch.combine_chunks()
                out.append(Series(c._name, c._dtype, arr))
        return Table(self.schema, out)

    def take(self, indices: Series) -> "Table":
        return Table(self.schema, [c.take(indices) for c in self._columns])

    def slice(self, start: int, end: int) -> "Table":
        return Table(self.schema, [c.slice(start, end) for c in self._columns])

    def head(self, n: int) -> "Table":
        return self.slice(0, min(n, len(self)))

    def sample(self, fraction: Optional[float] = None, size: Optional[int] = None,
               with_replacement: bool = False, seed: Optional[int] = None) -> "Table":
        if fraction is None and size is None:
            raise DaftValueError("sample requires either fraction or size")
        n = len(self)
        k = int(round(n * fraction)) if fraction is not None else int(size)
        rng = np.random.RandomState(seed if seed is not None else None)
        if with_replacement:
            idx = rng.randint(0, max(n, 1), size=k) if n else np.empty(0, np.int64)
        else:
            k = min(k, n)
            idx = rng.permutation(n)[:k]
        return self.take(Series.from_arrow(pa.array(idx.astype(np.uint64)), "idx"))

    @staticmethod
    def concat(tables: List["Table"]) -> "Table":
        if not tables:
            raise DaftValueError("concat of zero tables")
        first = tables[0]
        names = first.column_names
        for t in tables[1:]:
            if t.column_names != names:
                raise DaftValueError(f"concat schema mismatch: {names} vs {t.column_names}")
        cols = []
        for i, name in enumerate(names):
            cols.append(Series.concat([t._columns[i] for t in tables]))
        schema = Schema([Field(c.name, c.dtype) for c in cols])
        return Table(schema, cols)

    # ------------------------------------------------------------------ sort
    def argsort(self, sort_keys: Sequence[Expression], descending=None, nulls_first=None) -> Series:
        sort_keys = _as_expressions(sort_keys)
        k = len(sort_keys)
        descending = _norm_flag(descending, k, False)
        nulls_first = _norm_flag(nulls_first, k, None)
        keys = [e._node.evaluate(self) for e in sort_keys]
        arrs, sort_spec, placements = [], [], []
        for i, (s, d, nf) in enumerate(zip(keys, descending, nulls_first)):
            arrs.append(_broadcast_series(s, len(self)).to_arrow())
            placements.append("at_start" if (nf if nf is not None else d) else "at_end")
            sort_spec.append((f"k{i}", "descending" if d else "ascending"))
        # pyarrow sort_keys are (name, order) pairs with ONE global
        # null_placement (per-key 3-tuples are not part of its API); keys
        # that disagree on placement fall back to a dense-rank lexsort where
        # each key's rank bakes in its own placement
        if len(set(placements)) <= 1:
            tbl = pa.Table.from_arrays(arrs, names=[f"k{i}" for i in range(k)])
            idx = pc.sort_indices(tbl, sort_keys=sort_spec,
                                  null_placement=placements[0] if placements else "at_end")
            return Series.from_arrow(idx.cast(pa.uint64()), "indices")
        ranks = [np.asarray(pc.rank(a, sort_keys="descending" if d else "ascending",
                                    null_placement=p, tiebreaker="dense"))
                 for a, d, p in zip(arrs, descending, placements)]
        idx = np.lexsort(tuple(reversed(ranks)))  # first key = primary
        return Series.from_arrow(pa.array(idx.astype(np.uint64)), "indices")

    def sort(self, sort_keys: Sequence[Expression], descending=None, nulls_first=None) -> "Table":
        return self.take(self.argsort(sort_keys, descending, nulls_first))

    # ------------------------------------------------------------------ hashing / partitioning
    def hash_rows(self, exprs: Optional[Sequence[Expression]] = None, seed: int = 0) -> np.ndarray:
        exprs = _as_expressions(exprs) if exprs is not None else [col(n) for n in self.column_names]
        cols = []
        for e in exprs:
            s = e._node.evaluate(self)
            if s.is_python():
                s = s.cast(DataType.string())
            cols.append(_broadcast_series(s, len(self)).to_arrow())
        return hash_table_columns(cols, seed=seed)

    def partition_by_hash(self, exprs: Sequence[Expression], num_partitions: int) -> List["Table"]:
        if num_partitions <= 0:
            raise DaftValueError("num_partitions must be positive")
        h = self.hash_rows(exprs)
        buckets = (h % np.uint64(num_partitions)).astype(np.int64)
        return self._split_by_buckets(buckets, num_partitions)

    def partition_by_random(self, num_partitions: int, seed: int = 0) -> List["Table"]:
        rng = np.random.RandomState(seed & 0x7FFFFFFF)
        buckets = rng.randint(0, num_partitions, size=len(self))
        return self._split_by_buckets(buckets, num_partitions)

    def partition_by_range(self, exprs: Sequence[Expression], boundaries: "Table",
                           descending: Optional[List[bool]] = None,
                           nulls_first: Optional[List[Optional[bool]]] = None) -> List["Table"]:
        """Split rows by comparing sort keys against per-partition boundary rows.
        nulls_first[i]=None means the sort default (nulls last ascending, first
        descending)."""
        exprs = _as_expressions(exprs)
        k = len(exprs)
        descending = _norm_flag(descending, k, False)
        nulls_first = list(nulls_first) if nulls_first is not None else [None] * k
        nb = len(boundaries)
        if nb == 0:
            return [self]
        keys = [_broadcast_series(e._node.evaluate(self), len(self)) for e in exprs]
        ranks = _composite_rank(keys, [b for b in boundaries._columns], descending, nulls_first)
        return self._split_by_buckets(ranks, nb + 1)

    def partition_by_value(self, exprs: Sequence[Expression]) -> Tuple[List["Table"], "Table"]:
        """Group rows by exact key values; returns (partitions, unique_key_table)."""
        exprs = _as_expressions(exprs)
        keyed = self.eval_expression_list(exprs)
        codes, uniq = _group_codes(keyed)
        parts = self._split_by_buckets(codes, len(uniq))
        return parts, uniq

    def _split_by_buckets(self, buckets: np.ndarray, num: int) -> List["Table"]:
        if len(self) == 0:
            return [self.slice(0, 0) for _ in range(num)]
        from . import native

        if native.available():
            # one O(n) counting pass instead of an O(n log n) argsort
            counts, order = native.bucket_stable_order(buckets, num)
            offs = np.concatenate([[0], np.cumsum(counts)])
        else:
            order = np.argsort(buckets, kind="stable")
            counts = np.bincount(buckets, minlength=num)
            offs = np.concatenate([[0], np.cumsum(counts)])
        sorted_tbl = self.take(Series.from_arrow(pa.array(order.astype(np.uint64)), "idx"))
        return [sorted_tbl.slice(int(offs[i]), int(offs[i + 1])) for i in range(num)]

    # ------------------------------------------------------------------ aggregation
    def agg(self, to_agg: Sequence[Expression], group_by: Optional[Sequence[Expression]] = None) -> "Table":
        group_by = _as_expressions(group_by) if group_by else []
        to_agg = _as_expressions(to_agg)
        if not group_by:
            return self.eval_expression_list(to_agg)
        return self._grouped_agg(to_agg, group_by)

    def _grouped_agg(self, to_agg: List[Expression], group_by: List[Expression]) -> "Table":
        n = len(self)
        with self._memo_scope():
            # keys evaluated inside the scope so subtrees shared between the
            # group-by keys and the agg children are computed once
            key_tbl = self.eval_expression_list(group_by)
            fast = self._acero_grouped_agg(to_agg, key_tbl)
            if fast is not None:
                return fast
            return self._generic_grouped_agg(to_agg, key_tbl, n)

    def _generic_grouped_agg(self, to_agg: List[Expression], key_tbl: "Table", n: int) -> "Table":
        codes, uniq = _group_codes(key_tbl)
        num_groups = len(uniq)

        out_cols: List[Series] = list(uniq._columns)
        out_fields: List[Field] = list(uniq.schema)

        # Lazily sort rows by group code (only aggs that miss every vectorized
        # path need contiguous per-group segments).
        _seg = {}

        def segments():
            if not _seg:
                order = np.argsort(codes, kind="stable")
                counts = np.bincount(codes, minlength=num_groups) if n else np.zeros(num_groups, np.int64)
                offs = np.concatenate([[0], np.cumsum(counts)])
                _seg["order_s"] = Series.from_arrow(pa.array(order.astype(np.uint64)), "o")
                _seg["offs"] = offs
            return _seg["order_s"], _seg["offs"]

        for e in to_agg:
            node = e._node
            alias = e.name()
            while isinstance(node, Alias):
                node = node.child
            if not isinstance(node, AggExpr):
                raise DaftValueError(f"aggregation list contains non-aggregation {e!r}")
            child_s = _broadcast_series(node.child.evaluate(self), n)
            expected_dt = node.to_field(self.schema).dtype
            merged = _sketch_agg_fast(node, child_s, codes, num_groups)
            if merged is None:
                merged = _bincount_agg_fast(node, child_s, codes, num_groups)
            if merged is None:
                merged = _hash_agg_fast(node, child_s, codes, num_groups)
            if merged is None:
                # fallback: contiguous per-group segments after a stable sort by code
                order_s, offs = segments()
                sorted_child = child_s.take(order_s)
                outs = []
                for g in range(num_groups):
                    seg = sorted_child.slice(int(offs[g]), int(offs[g + 1]))
                    outs.append(_eval_agg_on_series(node, seg))
                merged = Series.concat(outs) if outs else _empty_agg_series(node, child_s)
            if merged.dtype != expected_dt:
                merged = merged.cast(expected_dt)
            out_cols.append(merged.rename(alias))
            out_fields.append(Field(alias, expected_dt))
        return Table(Schema(out_fields), out_cols)

    def _acero_grouped_agg(self, to_agg: List[Expression], key_tbl: "Table") -> Optional["Table"]:
        """Single multithreaded C++ hash-agg pass (arrow acero) for the whole
        aggregation list. Returns None when any key/agg needs the generic
        path. Group order (first occurrence) is recovered with a min(row_id)
        side-aggregate so results are deterministic and identical to the
        generic path."""
        n = len(self)
        if n == 0:
            return None
        cols: Dict[str, pa.Array] = {}
        key_names = []
        for i, s in enumerate(key_tbl._columns):
            if s.is_python():
                return None
            arr = s.to_arrow()
            if pa.types.is_nested(arr.type) or pa.types.is_dictionary(arr.type):
                return None
            # acero's hash table is ~3x slower on large_string keys
            cols[f"k{i}"] = _downcast_key_offsets(arr)
            key_names.append(f"k{i}")
        planned = _acero_agg_plans(to_agg)
        if planned is None:
            return None
        plans, nodes, agg_list = planned
        for j, node in enumerate(nodes):
            child_s = _broadcast_series(node.child.evaluate(self), n)
            if child_s.is_python():
                return None
            cols[f"v{j}"] = child_s.to_arrow()
        cols["__row__"] = _rowid_array(n)
        return _acero_run_group(cols, key_names, agg_list,
                                list(key_tbl.schema), plans, self.schema)

    @staticmethod
    def acero_grouped_agg_chunked(tables: List["Table"], to_agg, group_by
                                  ) -> Optional["Table"]:
        """One C++ hash-agg over a MicroPartition's chunk Tables WITHOUT
        concatenating them first: per-chunk expression evaluation feeds
        ChunkedArrays into a single acero group_by, skipping the full-width
        copy Table.concat would make (an 8-bucket SF10 shuffle concatenates
        ~3 GB of pieces just to aggregate them). Semantics identical to
        _acero_grouped_agg — same key-offset downcast, first-occurrence
        order recovery (global row ids continue across chunks in chunk
        order, exactly the concatenated order), same output casts. Returns
        None when ineligible; the caller concats and falls back."""
        tables = [t for t in tables if len(t)]
        if not tables:
            return None
        group_by = _as_expressions(group_by)
        to_agg = _as_expressions(to_agg)
        if not group_by:
            return None
        planned = _acero_agg_plans(to_agg)
        if planned is None:
            return None
        plans, nodes, agg_list = planned
        nk = len(group_by)
        key_chunks: List[List[pa.Array]] = [[] for _ in range(nk)]
        val_chunks: List[List[pa.Array]] = [[] for _ in to_agg]
        row_chunks: List[pa.Array] = []
        key_fields = None
        base = 0
        for t in tables:
            n = len(t)
            with t._memo_scope():
                kt = t.eval_expression_list(group_by)
                if key_fields is None:
                    key_fields = list(kt.schema)
                for i, s in enumerate(kt._columns):
                    if s.is_python():
                        return None
                    arr = s.to_arrow()
                    if pa.types.is_nested(arr.type) or pa.types.is_dictionary(arr.type):
                        return None
                    key_chunks[i].append(arr)
                for j, node in enumerate(nodes):
                    child_s = _broadcast_series(node.child.evaluate(t), n)
                    if child_s.is_python():
                        return None
                    val_chunks[j].append(child_s.to_arrow())
            row_chunks.append(pa.array(np.arange(base, base + n, dtype=np.int64)))
            base += n
        cols: Dict[str, Any] = {}
        for i in range(nk):
            chunks = key_chunks[i]
            # joint downcast decision: a ChunkedArray needs one uniform type
            if all(a.nbytes < (1 << 31) - 1 for a in chunks):
                chunks = [_downcast_key_offsets(a) for a in chunks]
            cols[f"k{i}"] = pa.chunked_array(chunks)
        for j in range(len(to_agg)):
            cols[f"v{j}"] = pa.chunked_array(val_chunks[j])
        cols["__row__"] = pa.chunked_array(row_chunks)
        return _acero_run_group(cols, [f"k{i}" for i in range(nk)], agg_list,
                                key_fields, plans, tables[0].schema)

    def acero_fused_agg(self, to_agg: List[Expression], group_by: List[Expression],
                        predicate: Optional[Expression]) -> Optional["Table"]:
        """Single-pass filter+project+aggregate through one acero Declaration
        (C++ exec plan): the filtered intermediate table is never
        materialized, which is the host-side analog of the reference's fused
        streaming pipeline (src/daft-local-execution/src/pipeline.rs:141-211)
        and of this engine's device-side FusedFilterAggregateOp. Returns None when
        any expression falls outside the translated subset (_to_acero_expr) —
        the caller then runs the unfused filter-then-agg path. Group output
        order is first-occurrence (hash_min row-id side-aggregate), identical
        to _acero_grouped_agg and the generic path."""
        from pyarrow import acero

        from .expressions import normalize_literals, required_columns

        n = len(self)
        if n == 0 or not group_by:
            # ungrouped reductions measure faster through the pruned
            # filter-then-agg path (see eval_agg); no fused variant exists
            return None
        exprs_all = list(group_by) + list(to_agg) + ([predicate] if predicate is not None else [])
        refs = set()
        for e in exprs_all:
            refs.update(required_columns(e))
        if "__row__" in refs:
            return None  # would collide with the order-recovery column
        by_name = {f.name: s for f, s in zip(self.schema, self._columns)}
        cols: Dict[str, Any] = {}
        for name in refs:
            s = by_name.get(name)
            if s is None or s.is_python():
                return None
            arr = s.to_arrow()
            if pa.types.is_nested(arr.type) or pa.types.is_dictionary(arr.type):
                return None
            cols[name] = arr
        try:
            pred_expr = None
            if predicate is not None:
                pred_expr = _to_acero_expr(
                    normalize_literals(predicate._node, self.schema), self.schema)
            proj_exprs, proj_names = [], []
            key_fields: List[Field] = []
            for i, e in enumerate(group_by):
                kdt = e._node.to_field(self.schema).dtype
                key_expr = _to_acero_expr(
                    normalize_literals(e._node, self.schema), self.schema)
                karrow = kdt.to_arrow()
                # same large_string downcast as _acero_grouped_agg: acero's
                # hash table is ~3x slower on 64-bit-offset keys. Offset
                # width only shrinks safely under 2GiB, which is knowable
                # here only for plain column keys.
                knode = e._node
                while isinstance(knode, Alias):
                    knode = knode.child
                src = cols.get(getattr(knode, "cname", None))
                small = src is not None and src.nbytes < (1 << 31) - 1
                if pa.types.is_large_string(karrow) and small:
                    key_expr = key_expr.cast(pa.string())
                elif pa.types.is_large_binary(karrow) and small:
                    key_expr = key_expr.cast(pa.binary())
                proj_exprs.append(key_expr)
                proj_names.append(f"k{i}")
                key_fields.append(Field(e.name(), kdt))
            plans = []
            agg_list = []
            for j, e in enumerate(to_agg):
                node = e._node
                alias = e.name()
                while isinstance(node, Alias):
                    node = node.child
                if not isinstance(node, AggExpr):
                    raise _AceroUnsupported("non-aggregation in agg list")
                spec = _acero_agg_fn(node, threaded=True)
                if spec is None:
                    raise _AceroUnsupported(f"agg kind {node.kind}")
                fname, opts = spec
                proj_exprs.append(_to_acero_expr(
                    normalize_literals(node.child, self.schema), self.schema))
                proj_names.append(f"v{j}")
                agg_list.append((f"v{j}", "hash_" + fname, opts,
                                 f"v{j}_{fname}"))
                plans.append((f"v{j}", fname, node, alias))
        except _AceroUnsupported:
            return None
        cols["__row__"] = _rowid_array(n)  # recovers first-occurrence order
        decls = [acero.Declaration("table_source",
                                   acero.TableSourceNodeOptions(pa.table(cols)))]
        if pred_expr is not None:
            decls.append(acero.Declaration("filter", acero.FilterNodeOptions(pred_expr)))
        proj_exprs.append(pc.field("__row__"))
        proj_names.append("__row__")
        agg_list.append(("__row__", "hash_min", None, "__row___min"))
        decls.append(acero.Declaration("project",
                                       acero.ProjectNodeOptions(proj_exprs, proj_names)))
        decls.append(acero.Declaration("aggregate", acero.AggregateNodeOptions(
            agg_list, keys=[f"k{i}" for i in range(len(group_by))])))
        try:
            g = acero.Declaration.from_sequence(decls).to_table(use_threads=True)
        except (pa.ArrowNotImplementedError, pa.ArrowInvalid, pa.ArrowTypeError,
                pa.ArrowKeyError):
            return None
        order = np.argsort(np.asarray(g.column("__row___min").combine_chunks()),
                           kind="stable")
        g = g.take(pa.array(order))
        return _assemble_acero_agg_output(g, key_fields, plans, self.schema)

    def distinct(self, subset: Optional[Sequence[Expression]] = None) -> "Table":
        exprs = _as_expressions(subset) if subset else [col(n) for n in self.column_names]
        key_tbl = self.eval_expression_list(exprs)
        codes, _uniq = _group_codes(key_tbl)
        if len(codes) == 0:
            return self
        first_idx = _first_occurrence(codes)
        return self.take(Series.from_arrow(pa.array(first_idx.astype(np.uint64)), "idx"))

    # ------------------------------------------------------------------ joins
    def hash_join(self, right: "Table", left_on: Sequence[Expression],
                  right_on: Sequence[Expression], how: str = "inner",
                  suffix: str = "right.") -> "Table":
        """Hash join with SQL null semantics (null keys never match).

        Output ROW ORDER IS UNSPECIFIED, as in the reference (Rust probe
        tables emit in probe-visit x hash-bucket order, acero in its own
        thread-interleaved order, and the device range probe in left-row-major
        x sorted-build-key order). Callers needing determinism sort after the
        join; tests compare sorted rows. This is the engine-wide join order
        contract — the device/host paths are free to disagree on order while
        agreeing on the multiset of rows."""
        how_map = {
            "inner": "inner", "left": "left outer", "right": "right outer",
            "outer": "full outer", "semi": "left semi", "anti": "left anti",
        }
        if how not in how_map:
            raise DaftValueError(f"unknown join type {how!r}")
        left_on = _as_expressions(left_on)
        right_on = _as_expressions(right_on)
        lk = self.eval_expression_list(left_on)
        rk = right.eval_expression_list(right_on)
        # align key dtypes
        lkc, rkc = [], []
        for a, b in zip(lk._columns, rk._columns):
            u = try_unify(a.dtype, b.dtype)
            if u is None:
                raise DaftValueError(f"cannot join on {a.dtype} vs {b.dtype}")
            lkc.append(a.cast(u))
            rkc.append(b.cast(u))

        # acero's hash table is ~3x slower on large_string keys (same effect
        # as in _acero_grouped_agg). The downcast decision is made JOINTLY per
        # key index: both sides must qualify, or acero would see mismatched
        # string vs large_string key types and raise.
        lka = [s.to_arrow() for s in lkc]
        rka = [s.to_arrow() for s in rkc]
        for i in range(len(lka)):
            la = _downcast_key_offsets(lka[i])
            ra = _downcast_key_offsets(rka[i])
            if la.type == ra.type:
                lka[i], rka[i] = la, ra

        key_names = [f"__k{i}" for i in range(len(lkc))]
        lt = pa.Table.from_arrays(
            lka + [c.to_arrow() for c in self._columns]
            + [pa.array(np.arange(len(self), dtype=np.int64))],
            names=key_names + [f"__l{i}" for i in range(len(self._columns))] + ["__lidx"],
        )
        rt = pa.Table.from_arrays(
            rka + [c.to_arrow() for c in right._columns]
            + [pa.array(np.arange(len(right), dtype=np.int64))],
            names=key_names + [f"__r{i}" for i in range(len(right._columns))] + ["__ridx"],
        )
        # acero builds its hash table on the RIGHT operand: probing 6M rows
        # against a 46k build is ~15x faster than building on the 6M side
        # (measured, TPC-H Q5 SF1). Keep the build on the smaller table by
        # swapping operands and flipping the join type; output assembly is
        # by column NAME (__l*/__r*), so orientation below stays unchanged.
        if len(self) < len(right):
            flip = {"inner": "inner", "left outer": "right outer",
                    "right outer": "left outer", "full outer": "full outer",
                    "left semi": "right semi", "left anti": "right anti"}
            joined = rt.join(lt, keys=key_names, join_type=flip[how_map[how]],
                             use_threads=True)
        else:
            joined = lt.join(rt, keys=key_names, join_type=how_map[how],
                             use_threads=True)
        # deterministic output order: by left index then right index
        sort_keys = [(c, "ascending") for c in ("__lidx", "__ridx") if c in joined.column_names]
        if sort_keys:
            joined = joined.take(pc.sort_indices(joined, sort_keys=sort_keys,
                                                 null_placement="at_end"))
        joined = joined.combine_chunks()

        if how in ("semi", "anti"):
            cols = [Series.from_arrow(joined.column(f"__l{i}"), f.name, f.dtype)
                    for i, f in enumerate(self.schema)]
            return Table(Schema(list(self.schema)), cols)

        out_cols: List[Series] = []
        out_fields: List[Field] = []
        left_names = set(self.column_names)
        # join keys: single merged column named after the left key (reference merges key cols)
        lk_names = [e.name() for e in left_on]
        rk_names = [e.name() for e in right_on]
        for i, kn in enumerate(key_names):
            name = lk_names[i]
            out_cols.append(Series.from_arrow(joined.column(kn), name))
            out_fields.append(Field(name, out_cols[-1].dtype))
        for i, f in enumerate(self.schema):
            if f.name in lk_names:
                continue
            s = Series.from_arrow(joined.column(f"__l{i}"), f.name, f.dtype)
            out_cols.append(s)
            out_fields.append(Field(f.name, s.dtype))
        for i, f in enumerate(right.schema):
            if f.name in rk_names:
                continue
            name = f.name if f.name not in left_names else f"{suffix}{f.name}"
            s = Series.from_arrow(joined.column(f"__r{i}"), name, f.dtype)
            out_cols.append(s)
            out_fields.append(Field(name, s.dtype))
        return Table(Schema(out_fields), out_cols)

    def join_from_indices(self, right: "Table", lidx: np.ndarray, ridx: np.ndarray,
                          left_on, right_on, suffix: str = "right.") -> "Table":
        """Assemble join output from precomputed row-index pairs (the device
        probe path, kernels/device_join.py). `ridx` entries of -1 emit nulls
        (left-outer misses). Output schema/naming matches hash_join exactly:
        merged key columns named after the left keys, then left columns, then
        right columns with `suffix` on collisions."""
        left_on = _as_expressions(left_on)
        right_on = _as_expressions(right_on)
        lk_names = [e.name() for e in left_on]
        rk_names = [e.name() for e in right_on]
        l_take = Series.from_arrow(pa.array(lidx.astype(np.uint64)), "i")
        r_has_null = (ridx < 0).any()
        r_take_arr = pa.array(
            np.where(ridx < 0, 0, ridx).astype(np.int64),
            pa.int64()) if not r_has_null else pa.array(
            [None if i < 0 else int(i) for i in ridx], pa.int64())
        out_cols: List[Series] = []
        out_fields: List[Field] = []
        lkeys = self.eval_expression_list(left_on)
        for i, kn in enumerate(lk_names):
            s = lkeys._columns[i].take(l_take).rename(kn)
            out_cols.append(s)
            out_fields.append(Field(kn, s.dtype))
        left_names = set(self.column_names)
        for f in self.schema:
            if f.name in lk_names:
                continue
            s = self.get_column(f.name).take(l_take)
            out_cols.append(s)
            out_fields.append(Field(f.name, s.dtype))
        for f in right.schema:
            if f.name in rk_names:
                continue
            name = f.name if f.name not in left_names else f"{suffix}{f.name}"
            arr = right.get_column(f.name).to_arrow().take(r_take_arr)
            s = Series.from_arrow(arr, name, right.get_column(f.name).dtype)
            out_cols.append(s)
            out_fields.append(Field(name, s.dtype))
        return Table(Schema(out_fields), out_cols)

    def sort_merge_join(self, right: "Table", left_on, right_on, how: str = "inner",
                        suffix: str = "right.", is_sorted: bool = False) -> "Table":
        """Join pre-sorted (or sorted here) sides; host fallback delegates to hash_join
        after sorting, preserving the sorted output property of the reference."""
        left_on = _as_expressions(left_on)
        right_on = _as_expressions(right_on)
        l = self if is_sorted else self.sort(left_on)
        r = right if is_sorted else right.sort(right_on)
        out = l.hash_join(r, left_on, right_on, how=how, suffix=suffix)
        return out.sort([col(e.name()) for e in left_on])

    # ------------------------------------------------------------------ reshaping
    def explode(self, exprs: Sequence[Expression]) -> "Table":
        exprs = _as_expressions(exprs)
        names = [e.name() for e in exprs]
        list_cols: Dict[str, Series] = {}
        for e in exprs:
            s = e._node.evaluate(self)
            if not s.dtype.is_list():
                raise DaftValueError(f"explode requires list column, got {s.dtype} for {e.name()!r}")
            list_cols[e.name()] = _broadcast_series(s, len(self))
        first = list_cols[names[0]]
        arr0 = first.to_arrow()
        lens = pc.list_value_length(arr0)
        lens_np = np.asarray(pc.fill_null(lens, 0), dtype=np.int64)
        # null/empty lists explode to a single null row (reference semantics)
        out_lens = np.maximum(lens_np, 1)
        for nm, s in list_cols.items():
            ln = np.asarray(pc.fill_null(pc.list_value_length(s.to_arrow()), 0), dtype=np.int64)
            if not np.array_equal(ln, lens_np):
                raise DaftValueError("exploded columns must have equal list lengths per row")
        repeat_idx = np.repeat(np.arange(len(self), dtype=np.int64), out_lens)
        out_cols: List[Series] = []
        out_fields: List[Field] = []
        for f, c in zip(self.schema, self._columns):
            if f.name in list_cols:
                s = list_cols[f.name]
                flat = _explode_series(s, out_lens)
                out_cols.append(flat.rename(f.name))
                out_fields.append(Field(f.name, flat.dtype))
            else:
                taken = c.take(Series.from_arrow(pa.array(repeat_idx), "i"))
                out_cols.append(taken)
                out_fields.append(f)
        return Table(Schema(out_fields), out_cols)

    def unpivot(self, ids: Sequence[Expression], values: Sequence[Expression],
                variable_name: str = "variable", value_name: str = "value") -> "Table":
        ids = _as_expressions(ids)
        values = _as_expressions(values)
        if not values:
            raise DaftValueError("unpivot requires at least one value column")
        id_tbl = self.eval_expression_list(ids) if ids else None
        n = len(self)
        val_series = [e._node.evaluate(self) for e in values]
        vdt = val_series[0].dtype
        for s in val_series[1:]:
            u = try_unify(vdt, s.dtype)
            if u is None:
                raise DaftValueError(f"unpivot value columns have incompatible types {vdt} vs {s.dtype}")
            vdt = u
        out_cols: List[Series] = []
        out_fields: List[Field] = []
        m = len(values)
        if id_tbl is not None:
            tile_idx = np.tile(np.arange(n, dtype=np.int64), m)
            idx_s = Series.from_arrow(pa.array(tile_idx), "i")
            for f, c in zip(id_tbl.schema, id_tbl._columns):
                out_cols.append(c.take(idx_s))
                out_fields.append(f)
        var_vals = np.repeat([e.name() for e in values], n)
        out_cols.append(Series.from_pylist(list(var_vals), variable_name, DataType.string()))
        out_fields.append(Field(variable_name, DataType.string()))
        value_col = Series.concat([s.cast(vdt) for s in val_series]).rename(value_name)
        out_cols.append(value_col)
        out_fields.append(Field(value_name, vdt))
        return Table(Schema(out_fields), out_cols)

    def pivot(self, group_by: Sequence[Expression], pivot_col: Expression,
              value_col: Expression, names: List[str], agg_fn: str = "sum") -> "Table":
        group_by = _as_expressions(group_by)
        pivot_e = _as_expressions(pivot_col)[0]
        value_e = _as_expressions(value_col)[0]
        agg_e = Expression(AggExpr(agg_fn, value_e._node))
        grouped = self.agg([agg_e.alias("__v")], group_by + [pivot_e])
        key_names = [e.name() for e in group_by]
        piv_name = pivot_e.name()
        base = grouped.distinct([col(n) for n in key_names]).select_columns(key_names)
        out = base
        for nm in names:
            sub = grouped.filter(col(piv_name) == nm) if nm is not None else grouped.filter(col(piv_name).is_null())
            sub = sub.select_columns(key_names + ["__v"]).rename_columns({"__v": str(nm)})
            out = out.hash_join(sub, [col(n) for n in key_names], [col(n) for n in key_names], how="left")
        return out

    def add_monotonic_id(self, partition_offset: int = 0, column_name: str = "id") -> "Table":
        ids = np.arange(len(self), dtype=np.uint64) + np.uint64(partition_offset)
        s = Series.from_arrow(pa.array(ids), column_name)
        return Table(Schema([Field(column_name, s.dtype)] + list(self.schema)), [s] + self._columns)


# ---------------------------------------------------------------------------
# helpers
# ---------------------------------------------------------------------------

def _broadcast_series(s: Series, n: int) -> Series:
    from .series import _broadcast_to

    return _broadcast_to(s, n)


def _norm_flag(v, k: int, default):
    if v is None:
        return [default] * k
    if isinstance(v, (bool, int)):
        return [bool(v)] * k
    out = list(v)
    if len(out) != k:
        raise DaftValueError(f"expected {k} flags, got {len(out)}")
    return out


def _group_codes(key_tbl: Table) -> Tuple[np.ndarray, Table]:
    """Dense group codes per row + table of unique key rows (nulls form a group)."""
    n = len(key_tbl)
    if n == 0:
        return np.empty(0, dtype=np.int64), key_tbl
    # dictionary-encode each key column, then combine codes by mixed-radix
    combined = np.zeros(n, dtype=np.int64)
    for s in key_tbl._columns:
        arr = s.to_arrow() if not s.is_python() else None
        if arr is None:
            vals = s.to_pylist()
            uniq_map: Dict[Any, int] = {}
            codes = np.empty(n, dtype=np.int64)
            for i, v in enumerate(vals):
                k = repr(v)
                codes[i] = uniq_map.setdefault(k, len(uniq_map))
            card = len(uniq_map)
        else:
            if pa.types.is_nested(arr.type):
                # nested keys: exact repr-based encoding (hash-only grouping could
                # silently merge colliding keys); nested group keys are rare enough
                # that the python path is acceptable
                vals = s.to_pylist()
                uniq_map2: Dict[Any, int] = {}
                codes = np.empty(n, dtype=np.int64)
                for i, v in enumerate(vals):
                    codes[i] = uniq_map2.setdefault(repr(v), len(uniq_map2))
                card = len(uniq_map2)
            else:
                enc = arr.dictionary_encode()
                codes = np.asarray(enc.indices.fill_null(-1)).astype(np.int64)
                codes = codes + 1  # null -> 0
                card = len(enc.dictionary) + 1
        card = max(card, 1)
        if (int(combined.max(initial=0)) + 1) * card >= (1 << 62):
            # overflow guard: re-densify intermediate codes before combining
            _, combined = np.unique(combined, return_inverse=True)
            combined = combined.astype(np.int64)
        combined = combined * np.int64(card) + codes
    # Densify the combined codes without an O(n log n) sort. Preferred: the
    # native open-addressing pass, which emits codes already in
    # first-occurrence order. Fallback: arrow's dictionary_encode (C++ hash
    # pass) + first-occurrence fixup via a reversed fancy-assignment (last
    # write wins, so a reversed index write leaves each slot holding its
    # FIRST occurrence).
    from . import native

    if native.available():
        codes, first_idx = native.dense_codes(combined)
        uniq = key_tbl.take(Series.from_arrow(pa.array(first_idx.astype(np.uint64)), "i"))
        return codes, uniq
    enc = pa.array(combined).dictionary_encode()
    codes = np.asarray(enc.indices).astype(np.int64)
    num = len(enc.dictionary)
    first_per_code = np.empty(num, dtype=np.int64)
    first_per_code[codes[::-1]] = np.arange(n - 1, -1, -1)
    order = np.argsort(first_per_code, kind="stable")
    remap = np.empty(num, dtype=np.int64)
    remap[order] = np.arange(num)
    codes = remap[codes]
    first_idx = first_per_code[order]
    uniq = key_tbl.take(Series.from_arrow(pa.array(first_idx.astype(np.uint64)), "i"))
    return codes, uniq


class _AceroUnsupported(Exception):
    """Expression shape outside the acero-translated subset; callers fall
    back to the per-op Series kernel path."""


def _acero_agg_plans(to_agg: List[Expression]):
    """Shared agg-plan building for the single-chunk and chunked acero
    paths: (plans [(vname, fname, node, alias)], nodes, agg_list) or None
    when any aggregation has no acero mapping."""
    plans, nodes, agg_list = [], [], []
    for j, e in enumerate(to_agg):
        node = e._node
        alias = e.name()
        while isinstance(node, Alias):
            node = node.child
        if not isinstance(node, AggExpr):
            raise DaftValueError(f"aggregation list contains non-aggregation {e!r}")
        spec = _acero_agg_fn(node, threaded=True)
        if spec is None:
            return None
        fname, opts = spec
        nodes.append(node)
        agg_list.append((f"v{j}", fname, opts))
        plans.append((f"v{j}", fname, node, alias))
    return plans, nodes, agg_list


def _acero_run_group(cols: Dict[str, Any], key_names: List[str], agg_list,
                     key_fields: List[Field], plans, schema: Schema
                     ) -> Optional["Table"]:
    """Shared group_by execution + first-occurrence order recovery (min
    row-id side-aggregate) + output assembly. `cols` must already contain
    the `__row__` ids (global across chunks for chunked inputs)."""
    agg_list = list(agg_list) + [("__row__", "min", None)]
    try:
        g = pa.table(cols).group_by(key_names, use_threads=True).aggregate(agg_list)
    except (pa.ArrowNotImplementedError, pa.ArrowInvalid, pa.ArrowTypeError):
        return None
    order = np.argsort(np.asarray(g.column("__row___min").combine_chunks()),
                       kind="stable")
    g = g.take(pa.array(order))
    return _assemble_acero_agg_output(g, key_fields, plans, schema)


def _assemble_acero_agg_output(g: pa.Table, key_fields: List[Field], plans,
                               schema: Schema) -> "Table":
    """Shared output assembly for the TableGroupBy and fused-Declaration agg
    paths: key columns (named k{i}) cast back to engine key dtypes, agg
    outputs (named {vname}_{fname}) cast to the planner's expected dtypes."""
    out_cols: List[Series] = []
    out_fields: List[Field] = []
    for i, f in enumerate(key_fields):
        s = Series.from_arrow(g.column(f"k{i}").combine_chunks(), f.name)
        if s.dtype != f.dtype:
            s = s.cast(f.dtype)
        out_cols.append(s)
        out_fields.append(f)
    for vname, fname, node, alias in plans:
        expected_dt = node.to_field(schema).dtype
        s = Series.from_arrow(g.column(f"{vname}_{fname}").combine_chunks(), alias)
        if s.dtype != expected_dt:
            s = s.cast(expected_dt)
        out_cols.append(s.rename(alias))
        out_fields.append(Field(alias, expected_dt))
    return Table(Schema(out_fields), out_cols)


_ROWID_CACHE: List[Optional[pa.Array]] = [None]
_ROWID_CACHE_MAX = 1 << 26  # don't pin more than 512MB of arange


def _rowid_array(n: int) -> pa.Array:
    """Cached int64 arange (grow-only) for first-occurrence order recovery."""
    cached = _ROWID_CACHE[0]
    if cached is None or len(cached) < n:
        cached = pa.array(np.arange(n, dtype=np.int64))
        if n <= _ROWID_CACHE_MAX:
            _ROWID_CACHE[0] = cached
        return cached
    return cached.slice(0, n)


def _to_acero_expr(node, schema: Schema):
    """ExprNode -> deferred pyarrow.compute Expression with the ENGINE's type
    semantics: operands are cast to the dtypes the Series kernels would unify
    to (series.py _binary_numeric/_cmp), so a fused acero plan computes
    results identical to the per-op host path. The caller must run
    normalize_literals first so weak literals already carry concrete dtypes.
    Raises _AceroUnsupported for anything outside the translated subset."""
    from .expressions import (Between, BinaryOp, Cast, Column, IsNull, Literal,
                              Not)

    if isinstance(node, Alias):
        return _to_acero_expr(node.child, schema)
    if isinstance(node, Column):
        return pc.field(node.cname)
    if isinstance(node, Literal):
        if isinstance(node.value, (list, dict)) or node.dtype.kind == TypeKind.PYTHON:
            raise _AceroUnsupported("complex literal")
        try:
            return pc.scalar(pa.scalar(node.value, node.dtype.to_arrow()))
        except Exception as e:
            raise _AceroUnsupported(f"literal: {e}")
    if isinstance(node, Cast):
        dt = node.dtype
        if not (dt.is_numeric() or dt.is_temporal() or dt.is_boolean()):
            raise _AceroUnsupported(f"cast to {dt}")
        return _to_acero_expr(node.child, schema).cast(dt.to_arrow())
    if isinstance(node, Not):
        return pc.invert(_to_acero_expr(node.child, schema))
    if isinstance(node, IsNull):
        inner = _to_acero_expr(node.child, schema)
        return pc.is_valid(inner) if node.negate else pc.is_null(inner)
    if isinstance(node, Between):
        # Series.between == (child >= lo) & (child <= hi), Kleene logic
        lo = BinaryOp(">=", node.child, node.lower)
        hi = BinaryOp("<=", node.child, node.upper)
        return pc.and_kleene(_to_acero_expr(lo, schema), _to_acero_expr(hi, schema))
    if isinstance(node, BinaryOp):
        op = node.op
        ldt = node.left.to_field(schema).dtype
        rdt = node.right.to_field(schema).dtype
        l = _to_acero_expr(node.left, schema)
        r = _to_acero_expr(node.right, schema)
        if op in ("&", "|"):
            if not (ldt.is_boolean() and rdt.is_boolean()):
                raise _AceroUnsupported("bitwise on non-bool")
            return (pc.and_kleene if op == "&" else pc.or_kleene)(l, r)
        if op in ("==", "!=", "<", "<=", ">", ">="):
            if ldt != rdt:
                sup = try_unify(ldt, rdt)
                if sup is None:
                    raise _AceroUnsupported(f"compare {ldt} vs {rdt}")
                if ldt != sup:
                    l = l.cast(sup.to_arrow())
                if rdt != sup:
                    r = r.cast(sup.to_arrow())
            fn = {"==": pc.equal, "!=": pc.not_equal, "<": pc.less,
                  "<=": pc.less_equal, ">": pc.greater, ">=": pc.greater_equal}[op]
            return fn(l, r)
        if op in ("+", "-", "*", "/"):
            numericish = (ldt.is_numeric() or ldt.is_boolean()) and (
                rdt.is_numeric() or rdt.is_boolean())
            if not numericish:
                raise _AceroUnsupported(f"{op} on {ldt}/{rdt}")
            if op == "/":
                # Series.__truediv__: both sides to float64, unchecked divide
                return pc.divide(l.cast(pa.float64()), r.cast(pa.float64()))
            u = try_unify(ldt, rdt) if ldt != rdt else ldt
            if u is None or not u.is_numeric():
                raise _AceroUnsupported(f"{op} unify {ldt}/{rdt}")
            if ldt != u:
                l = l.cast(u.to_arrow())
            if rdt != u:
                r = r.cast(u.to_arrow())
            fn = {"+": pc.add_checked, "-": pc.subtract_checked,
                  "*": pc.multiply_checked}[op]
            return fn(l, r)
        raise _AceroUnsupported(f"operator {op}")
    raise _AceroUnsupported(type(node).__name__)


def _acero_agg_fn(node: AggExpr, threaded: bool = False):
    """AggExpr -> (acero hash-agg function name, options), or None.

    With threaded=True, order-dependent aggregates (list, any_value/first) are
    rejected: pyarrow guarantees no stable ordering under a threaded exec plan,
    which would break parity with the sequential path."""
    k = node.kind
    if k in ("list", "any_value") and threaded:
        return None
    if k in ("sum", "mean", "min", "max", "count_distinct", "list"):
        return {"count_distinct": "count_distinct"}.get(k, k), None
    if k == "count":
        mode = node.extra.get("mode", "valid")
        if mode not in ("valid", "null", "all"):
            return None
        return "count", pc.CountOptions(
            mode={"valid": "only_valid", "null": "only_null", "all": "all"}[mode])
    if k == "stddev":
        return "stddev", pc.VarianceOptions(ddof=0)
    if k == "any_value":
        return "first", pc.ScalarAggregateOptions(
            skip_nulls=bool(node.extra.get("ignore_nulls", False)))
    return None


def _sketch_agg_fast(node: AggExpr, child: Series, codes: np.ndarray,
                     num_groups: int) -> Optional[Series]:
    """Vectorized grouped kernels of the sketch subsystem (daft_tpu/sketch/):
    the planner-internal stage kinds (sketch_hll/sketch_quantile build one
    Binary sketch per group; merge_sketch_* merges serialized sketches) and
    the single-partition grouped approx_* aggregations, which build+estimate
    in one pass so grouped results match the two-phase plan's estimates.
    Returns None for every other kind."""
    k = node.kind
    if k in ("sketch_hll", "merge_sketch_hll", "approx_count_distinct"):
        from .sketch import hll

        if k == "sketch_hll":
            return hll.build_grouped(child, codes, num_groups)
        if k == "merge_sketch_hll":
            return hll.merge_grouped(child, codes, num_groups)
        est = hll.grouped_estimates(child, codes, num_groups)
        return Series.from_arrow(pa.array(est, type=pa.uint64()), child.name)
    if k in ("sketch_quantile", "merge_sketch_quantile", "approx_percentiles"):
        from .sketch import quantile

        if k == "sketch_quantile":
            return quantile.build_grouped(child, codes, num_groups)
        if k == "merge_sketch_quantile":
            return quantile.merge_grouped(child, codes, num_groups)
        sketches = quantile.build_grouped(child, codes, num_groups)
        return quantile.estimate_series(
            sketches, node.extra.get("percentiles", 0.5))
    return None


def _bincount_agg_fast(node: AggExpr, child: Series, codes: np.ndarray,
                       num_groups: int) -> Optional[Series]:
    """O(n) grouped count/sum/mean via np.bincount (no hash pass, no sort).

    Floats only for sum/mean (bincount accumulates in float64; integer sums
    stay on the exact arrow hash-agg path to avoid 2^53 precision loss).
    Matches arrow hash-agg null semantics: nulls skipped, all-null/empty
    groups yield null, NaN propagates.
    """
    if child.is_python() or num_groups == 0 or len(codes) == 0:
        return None
    k = node.kind
    arr = child.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    if k == "count":
        mode = node.extra.get("mode", "valid")
        if mode == "all" or (mode == "valid" and arr.null_count == 0):
            cnt = np.bincount(codes, minlength=num_groups)
        elif mode == "valid":
            cnt = np.bincount(codes[np.asarray(arr.is_valid())], minlength=num_groups)
        elif mode == "null":
            cnt = np.bincount(codes[np.asarray(arr.is_null())], minlength=num_groups)
        else:
            return None
        return Series.from_arrow(pa.array(cnt.astype(np.uint64)), child.name)
    if k not in ("sum", "mean") or not pa.types.is_floating(arr.type):
        return None
    if arr.null_count == 0:
        vals = arr.to_numpy(zero_copy_only=False)
        sums = np.bincount(codes, weights=vals, minlength=num_groups)
        cnt = np.bincount(codes, minlength=num_groups)
    else:
        valid = np.asarray(arr.is_valid())
        vals = np.where(valid, arr.to_numpy(zero_copy_only=False), 0.0)
        sums = np.bincount(codes, weights=vals, minlength=num_groups)
        cnt = np.bincount(codes[valid], minlength=num_groups)
    empty = cnt == 0
    out = sums if k == "sum" else np.divide(sums, cnt, out=np.zeros_like(sums), where=~empty)
    return Series.from_arrow(pa.array(out, type=pa.float64(), mask=empty), child.name)


def _hash_agg_fast(node: AggExpr, child: Series, codes: np.ndarray, num_groups: int) -> Optional[Series]:
    """Vectorized grouped aggregation through arrow's hash-agg engine.

    Returns None when the (kind, dtype) combination needs the segment fallback.
    """
    if child.is_python() or num_groups == 0:
        return None
    k = node.kind
    spec = _acero_agg_fn(node)  # sequential plan: order-dependent aggs allowed
    if spec is None:
        return None
    fname, opts = spec
    arr = child.to_arrow()
    if pa.types.is_nested(arr.type) and k in ("sum", "mean", "min", "max", "stddev", "count_distinct", "list"):
        return None
    try:
        tbl = pa.table({"g": pa.array(codes), "v": arr})
        agg = tbl.group_by("g", use_threads=False).aggregate([("v", fname, opts)])
    except (pa.ArrowNotImplementedError, pa.ArrowInvalid):
        return None
    out_name = [c for c in agg.column_names if c != "g"][0]
    g = np.asarray(agg.column("g").combine_chunks())
    v = agg.column(out_name).combine_chunks()
    if isinstance(v, pa.ChunkedArray):
        v = v.combine_chunks()
    # scatter into group order 0..num_groups-1
    order = np.argsort(g, kind="stable")
    inv = np.empty(num_groups, dtype=np.int64)
    inv[g[order]] = order
    v = v.take(pa.array(inv))
    return Series.from_arrow(v, child.name)


def _first_occurrence(codes: np.ndarray) -> np.ndarray:
    _, first_idx = np.unique(codes, return_index=True)
    return np.sort(first_idx)


def _composite_rank(keys: List[Series], bounds: List[Series], descending: List[bool],
                    nulls_first: Optional[List[Optional[bool]]] = None) -> np.ndarray:
    """For each row, the number of boundary rows at-or-below it in the sort
    order (lexicographic). "Below" honors per-key descending + nulls placement,
    mirroring Table.argsort's ordering so range partitions align with sorts."""
    if nulls_first is None:
        nulls_first = [None] * len(keys)
    n = len(keys[0])
    nb = len(bounds[0])
    ge_all = np.zeros((nb, n), dtype=bool)
    for bi in range(nb):
        cmp_state = np.zeros(n, dtype=np.int8)  # -1 lt, 0 eq, +1 gt (in sort order)
        for s, b, d, nf in zip(keys, bounds, descending, nulls_first):
            bv = b.slice(bi, bi + 1)
            eq_mask = cmp_state == 0
            if not eq_mask.any():
                break
            sv = s.to_arrow()
            bscalar = bv.to_arrow()[0]
            lt = np.asarray(pc.fill_null(pc.less(sv, bscalar), False))
            gt = np.asarray(pc.fill_null(pc.greater(sv, bscalar), False))
            if d:
                lt, gt = gt, lt
            isnull = np.asarray(pc.is_null(sv))
            bnull = not bscalar.is_valid
            # argsort default: nulls at_start iff descending, overridable
            nulls_at_start = nf if nf is not None else d
            if bnull:
                # non-null rows vs a null boundary
                if nulls_at_start:
                    lt2, gt2 = np.zeros(n, dtype=bool), ~isnull
                else:
                    lt2, gt2 = ~isnull, np.zeros(n, dtype=bool)
            else:
                if nulls_at_start:
                    lt2 = np.where(isnull, True, lt)
                    gt2 = np.where(isnull, False, gt)
                else:
                    lt2 = np.where(isnull, False, lt)
                    gt2 = np.where(isnull, True, gt)
            cmp_state = np.where(eq_mask & lt2, -1, cmp_state)
            cmp_state = np.where(eq_mask & gt2, 1, cmp_state)
        ge_all[bi] = cmp_state >= 0
    rank = ge_all.sum(axis=0).astype(np.int64)
    return rank


def _explode_series(s: Series, out_lens: np.ndarray) -> Series:
    arr = s.to_arrow()
    if pa.types.is_fixed_size_list(arr.type):
        arr = arr.cast(pa.large_list(arr.type.value_type))
    offs = np.asarray(arr.offsets).astype(np.int64)
    child = arr.values
    lo = int(offs[0])
    starts, ends = offs[:-1] - lo, offs[1:] - lo
    child = child.slice(lo, int(offs[-1]) - lo)
    n = len(arr)
    idx = np.empty(int(out_lens.sum()), dtype=np.int64)
    valid = np.empty(int(out_lens.sum()), dtype=bool)
    pos = 0
    valid_row = np.asarray(pc.is_valid(arr))
    for i in range(n):
        ln = int(out_lens[i])
        real = int(ends[i] - starts[i]) if valid_row[i] else 0
        if real == 0:
            idx[pos:pos + 1] = 0
            valid[pos:pos + 1] = False
            pos += 1
        else:
            idx[pos:pos + real] = np.arange(starts[i], ends[i])
            valid[pos:pos + real] = True
            pos += real
    if len(child) == 0:
        out = pa.nulls(len(idx), arr.type.value_type)
    else:
        taken = child.take(pa.array(np.clip(idx, 0, len(child) - 1)))
        out = pc.if_else(pa.array(valid), taken, pa.nulls(len(idx), taken.type))
    return Series.from_arrow(out, s.name)


def _empty_agg_series(node: AggExpr, child: Series) -> Series:
    out_field = AggExpr(node.kind, _ConstNode(child.dtype), node.extra).to_field(Schema([]))
    return Series.empty(child.name, out_field.dtype)


class _ConstNode:
    """Internal: an ExprNode-like carrying a fixed dtype for empty-agg typing."""

    def __init__(self, dtype: DataType):
        self._dtype = dtype

    def to_field(self, _schema):
        return Field("x", self._dtype)

    def name(self):
        return "x"
