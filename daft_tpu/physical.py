"""Physical plan: executable operators over streams of MicroPartitions.

Role-equivalent to the reference's src/daft-plan/src/physical_plan.rs +
physical_planner/translate.rs (notably the two-stage aggregation decomposition
at translate.rs:761) and the partition-task generators of
daft/execution/physical_plan.py (fanout/reduce at :1365, sort at :1414).

Execution model: each operator is a generator over MicroPartitions — streaming
ops (scan/project/filter/limit) never hold more than one partition; pipeline
breakers (sort/shuffle/agg-final/join-build) buffer what they must. The same
operator tree executes single-chip today and maps onto a device mesh via the
parallel/ shuffle kernels (partition i ↔ mesh slot i).
"""

from __future__ import annotations

from typing import Any, Dict, Iterator, List, Optional, Sequence, Tuple

import numpy as np

from .expressions import AggExpr, Alias, Expression, col, expr_has_udf, lit
from .logical import (
    Aggregate,
    Concat,
    Distinct,
    Explode,
    Filter,
    InMemorySource,
    Join,
    Limit,
    LogicalPlan,
    MonotonicallyIncreasingId,
    Pivot,
    Project,
    Repartition,
    Sample,
    ScanSource,
    Sort,
    Unpivot,
    Write,
)
from .micropartition import MicroPartition
from .schema import Schema

PartStream = Iterator[MicroPartition]


def summarize_exprs(exprs, limit: int = 120) -> str:
    """Compact expression-list rendering for plan dumps: full displays up to
    `limit` chars, then a count of what was elided — a 40-column projection
    must not dump hundreds of chars into every explain line."""
    parts = []
    used = 0
    for i, e in enumerate(exprs):
        d = e._node.display()
        if parts and used + len(d) + 2 > limit:
            return ", ".join(parts) + f", ... (+{len(exprs) - i} more)"
        if not parts and len(d) > limit:
            d = d[:limit] + "…"
        parts.append(d)
        used += len(d) + 2
    return ", ".join(parts)


class PhysicalOp:
    """Base: children + a generator-producing execute().

    Ops that are pure per-partition maps set `map_partition` (a method
    (part, ctx) -> part); the executor then runs them morsel-parallel across
    a worker pool (reference: worker-per-core IntermediateOps,
    intermediate_op.rs:71) instead of calling execute()."""

    map_partition = None  # type: ignore[assignment]

    # The morsel contract (daft_tpu/stream/, README "Streaming execution"):
    # True declares map_partition ROW-LOCAL — applying it per fixed-size
    # morsel and re-chunking equals applying it per partition, byte for
    # byte — so the streaming executor may pull this op's work through
    # bounded channels. Ops that aggregate, reorder, or depend on partition
    # position must leave this False; daftlint DTL006 pins that a claiming
    # op implements map_partition (no silent whole-partition
    # materialization inside a streaming stage).
    morsel_streamable = False

    def map_empty(self, ctx):
        """Partitions to emit when the (parallel-mapped) input is empty."""
        return iter(())

    def parallel_safe(self) -> bool:
        """Whether map_partition may run concurrently across morsels.
        Function UDFs (and bare class UDFs sharing one instance) carry
        arbitrary user state with no thread-safety contract, so they force
        sequential order; class UDFs on an actor pool (concurrency > 1)
        serialize per instance and stay morsel-parallel."""
        from .expressions import expr_udfs_parallel_safe

        return all(expr_udfs_parallel_safe(e) for e in self._map_exprs())

    def _map_exprs(self):
        return ()

    def _map_execute(self, inputs, ctx, _primed=None):
        """Sequential driver over map_partition (the parallel executor has its
        own worker-pool driver over the same map_partition; device-pipelinable
        ops are routed HERE instead — see execute_plan). Honors UDF resource
        requests (fail-fast on impossible ones; reference: pyrunner.py:352-370).
        `_primed` is the already-launched resolver of a first partition the
        caller consumed while deciding the execution strategy.

        Device double-buffering: ops that implement map_partition_dispatch
        launch partition i+1's staging + compute BEFORE partition i's result
        is pulled back from the device, overlapping host↔HBM transfer with
        device compute (reference role: the channelled pipeline of
        daft-local-execution intermediate_op.rs:71+). Output order is
        preserved; a host-path partition first drains the pending device one.
        """
        from .execution import op_resource_request

        req = op_resource_request(self)
        if req:
            ctx.accountant.check(req)
        saw = _primed is not None
        pending = _primed  # deferred resolver of the previous device partition
        for part in inputs[0]:
            saw = True
            if req:
                ctx.accountant.admit(req)
            try:
                # resource-requested ops never defer: the resolver would run
                # outside the accountant's admission window
                dispatch = None if req else self.map_partition_dispatch(part, ctx)
                if dispatch is not None:
                    if pending is not None:
                        yield pending()
                    pending = dispatch
                    continue
                if pending is not None:
                    yield pending()
                    pending = None
                out = self.map_partition_declined(part, ctx)
            finally:
                if req:
                    ctx.accountant.release(req)
            yield out
        if pending is not None:
            yield pending()
        if not saw:
            yield from self.map_empty(ctx)

    def map_partition_dispatch(self, part, ctx):
        """Optional non-blocking launch for map_partition: return a zero-arg
        resolver, or None to take the synchronous path."""
        return None

    def map_partition_declined(self, part, ctx):
        """Synchronous evaluation AFTER map_partition_dispatch returned None.
        Ops whose dispatch already proved the device path ineligible override
        this to skip a doomed second device attempt."""
        return self.map_partition(part, ctx)

    def device_pipelinable(self, ctx) -> bool:
        """True when this op's kernels compile for the device against its
        child schema — execute_plan then prefers the double-buffered
        sequential driver over thread fan-out (device compute serializes on
        one chip; the pipeline keeps the host link busy instead)."""
        return False

    def __init__(self, children: List["PhysicalOp"], schema: Schema, num_partitions: int):
        self.children = children
        self.schema = schema
        self.num_partitions = num_partitions

    def name(self) -> str:
        return type(self).__name__

    def execute(self, inputs: List[PartStream], ctx) -> PartStream:
        raise NotImplementedError

    def display_tree(self, indent: str = "") -> str:
        out = [indent + ("* " if indent else "") + self.describe()]
        for c in self.children:
            out.append(c.display_tree(indent + "  "))
        return "\n".join(out)

    def describe(self) -> str:
        return f"{self.name()} [{self.num_partitions} parts]"

    def __repr__(self) -> str:
        return self.display_tree()


# ---------------------------------------------------------------------------
# sources
# ---------------------------------------------------------------------------

class ScanOp(PhysicalOp):
    def __init__(self, tasks: List[Any], schema: Schema):
        super().__init__([], schema, max(len(tasks), 1))
        self.tasks = tasks

    def plan_parts(self, ctx) -> List[MicroPartition]:
        """Prune + emit the scan's unloaded partitions (shared by the
        generator path below and the streaming pipeline driver, so both
        see identical pruning, counters, and multi-host ownership). The
        caller owns the ``scan.plan`` phase span."""
        scan_owner = getattr(ctx, "scan_owner", None)
        parts = []
        for i, task in enumerate(self.tasks):
            if task.can_prune():
                ctx.stats.bump("scan_tasks_pruned")
                continue
            ctx.stats.bump("scan_tasks_emitted")
            part = MicroPartition.from_scan_task(task)
            if scan_owner is not None:
                # multi-host: the task index over the globally-consistent
                # list assigns which process materializes (and READS) it
                part.owner_process = scan_owner(i)
            parts.append(part)
        return parts

    def execute(self, inputs, ctx) -> PartStream:
        from .io.prefetch import pipeline_scan_parts

        with ctx.stats.profiler.span("scan.plan", kind="phase"):
            parts = self.plan_parts(ctx)
        # bounded readahead: reading partition i triggers the background
        # fetch of i+1..i+depth (locally-owned tasks only); byte-identical
        # with prefetch off, order preserved by this very loop. (The
        # streaming executor bypasses this wrapper: its producer window IS
        # the readahead, reading chunk-wise on the pool.)
        yield from pipeline_scan_parts(parts, ctx)

    def describe(self):
        return f"Scan [{len(self.tasks)} tasks]"


class InMemoryOp(PhysicalOp):
    def __init__(self, parts: List[MicroPartition], schema: Schema):
        super().__init__([], schema, max(len(parts), 1))
        self.parts = parts

    def execute(self, inputs, ctx) -> PartStream:
        yield from self.parts


# ---------------------------------------------------------------------------
# streaming unary ops
# ---------------------------------------------------------------------------

class ProjectOp(PhysicalOp):
    # row-local projection: per-morsel evaluation + re-chunk is
    # byte-identical to per-partition evaluation (the streaming driver
    # still declines UDF-bearing instances — a batch-dependent UDF sees
    # whole partitions on the partition-granular path)
    morsel_streamable = True

    def __init__(self, child: PhysicalOp, exprs: List[Expression], schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.exprs = exprs

    def map_partition(self, part, ctx):
        return ctx.eval_projection(part, self.exprs)

    def map_partition_dispatch(self, part, ctx):
        return ctx.eval_projection_dispatch(part, self.exprs)

    def map_partition_declined(self, part, ctx):
        # dispatch already proved this partition device-ineligible: go
        # straight to the host kernel instead of re-staging a doomed attempt
        ctx.stats.bump("host_projections")
        return part.eval_expression_list(self.exprs)

    def device_pipelinable(self, ctx) -> bool:
        if not ctx.cfg.use_device_kernels:
            return False
        try:
            from .kernels.device import normalize_and_check

            return normalize_and_check(self.exprs,
                                       self.children[0].schema) is not None
        except Exception:
            return False

    def _map_exprs(self):
        return self.exprs

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)

    def describe(self):
        return "Project: " + summarize_exprs(self.exprs)


class BatchedUdfOp(PhysicalOp):
    """A projection containing batch-declared UDFs (daft_tpu/batch/),
    routed through the dynamic-batching executor instead of the
    per-partition UDF path.

    Deliberately NOT a ProjectOp subclass: both fuse passes match
    ``isinstance(op, (ProjectOp, FilterOp))``, so this op is a fusion
    barrier by construction — batch-declared UDFs must keep their own op
    (the batching driver owns their evaluation), while chains above and
    below still fuse normally.

    Three entry points, all byte-identical:
      execute()       — local non-streaming driver: coalesces whole
                        partitions under the budget, re-splits to source
                        partition boundaries
      map_partition() — one-partition batched apply; the degrade target,
                        AND the worker-side entry under the distributed
                        runner (the op pickles like any map op, so workers
                        host the pinned model actors process-locally)
      stream adapter  — stream/pipeline.py builds a BatchingExecutor per
                        producer and re-splits to morsel boundaries
    """

    # the batch declaration IS a row-locality contract (see batch_udf),
    # which is exactly the morsel contract
    morsel_streamable = True
    # routing marker: execute_plan sends this op to its own execute()
    # locally; stream/pipeline.py lifts the UDF decline for it
    batch_declared = True

    def __init__(self, child: PhysicalOp, exprs: List[Expression], schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.exprs = exprs

    def _map_exprs(self):
        return self.exprs

    def _settings(self, ctx):
        from .batch.executor import BatchSettings
        from .expressions import expr_batch_udfs

        decl = None
        for e in self.exprs:
            udfs = expr_batch_udfs(e)
            if udfs:
                decl = udfs[0].batching  # first declaration wins
                break
        return BatchSettings.resolve(decl, ctx.cfg)

    def map_partition(self, part, ctx):
        # whole-partition batched apply: the degrade path and the
        # distributed worker entry (pinned actors live in the worker)
        from .batch.device import exec_ctx_scope

        ctx.stats.bump("host_projections")
        with exec_ctx_scope(ctx):
            return part.eval_expression_list(self.exprs)

    def execute(self, inputs, ctx) -> PartStream:
        from .execution import op_resource_request

        if op_resource_request(self):
            # resource-requested UDFs run under the accountant's admission
            # window, which is per-partition — skip cross-partition
            # coalescing rather than hold admission across a batch
            yield from self._map_execute(inputs, ctx)
            return
        from .batch.executor import BatchingExecutor

        bx = BatchingExecutor(self.name(), self.exprs, ctx,
                              settings=self._settings(ctx))
        try:
            for part in inputs[0]:
                yield from bx.feed(part)
            yield from bx.finish()
        finally:
            # abandoned stream (limit/error above): settle buffered charges
            bx.abort()

    def describe(self):
        return "BatchedUdf: " + summarize_exprs(self.exprs)


def _route_batched_udfs(op: PhysicalOp) -> PhysicalOp:
    """Pre-fusion pass: rewrite ProjectOps whose expressions carry a
    batching declaration into BatchedUdfOp. Runs BEFORE fuse_for_device /
    fuse_map_chains (which would otherwise fold the projection into a
    fused map and strand the declaration)."""
    from .expressions import expr_has_batch_udf

    op.children = [_route_batched_udfs(c) for c in op.children]
    if type(op) is ProjectOp and any(expr_has_batch_udf(e) for e in op.exprs):
        return BatchedUdfOp(op.children[0], op.exprs, op.schema)
    return op


class FilterOp(PhysicalOp):
    # row-local predicate: a row's fate depends only on its own values, so
    # morsel-wise compaction concatenates to the partition-granular result
    morsel_streamable = True

    def __init__(self, child: PhysicalOp, predicate: Expression):
        super().__init__([child], child.schema, child.num_partitions)
        self.predicate = predicate

    def map_partition(self, part, ctx):
        return ctx.eval_filter(part, self.predicate)

    def map_partition_dispatch(self, part, ctx):
        return ctx.eval_filter_dispatch(part, self.predicate)

    def map_partition_declined(self, part, ctx):
        # dispatch already proved this partition device-ineligible
        ctx.stats.bump("host_filters")
        return part.filter([self.predicate])

    def device_pipelinable(self, ctx) -> bool:
        if not ctx.cfg.use_device_kernels:
            return False
        try:
            from .kernels.device import normalize_and_check

            return normalize_and_check([self.predicate],
                                       self.children[0].schema) is not None
        except Exception:
            return False

    def _map_exprs(self):
        return (self.predicate,)

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)

    def describe(self):
        return f"Filter: {self.predicate._node.display()}"


class LimitOp(PhysicalOp):
    """Streaming global limit with early stop (reference: global_limit,
    physical_plan.py — iterative partition takes).

    Upstream early-termination: once the limit is satisfied the child
    stream is CLOSED, not merely abandoned — a streaming pipeline below
    (daft_tpu/stream/) tears down its channels and producers immediately
    (they stop scanning/decoding partitions nobody will read, counted in
    ``morsels_short_circuited``) instead of waiting for end-of-query GC.
    When the limit sits directly atop a streamable chain the driver
    absorbs it as a morsel-consuming sink instead, and this op never
    executes."""

    def __init__(self, child: PhysicalOp, limit: int):
        super().__init__([child], child.schema, child.num_partitions)
        self.limit = limit

    def execute(self, inputs, ctx) -> PartStream:
        remaining = self.limit
        src = inputs[0]
        if remaining > 0:
            for part in src:
                n = part.num_rows_or_none()
                if n is None or n > remaining:
                    part = part.head(remaining)
                remaining -= len(part)
                yield part
                if remaining <= 0:
                    break
        close = getattr(src, "close", None)
        if close is not None:
            close()

    def describe(self):
        return f"Limit: {self.limit}"


class ExplodeOp(PhysicalOp):
    """Map-class since the DTL006 burn-down: per-partition explode runs
    through the instrumented _map_execute driver (driver/worker op spans,
    morsel parallelism) instead of a blind streaming loop."""

    def __init__(self, child: PhysicalOp, exprs: List[Expression], schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.exprs = exprs

    def map_partition(self, part, ctx):
        return part.explode(self.exprs)

    def _map_exprs(self):
        return list(self.exprs)

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)


class UnpivotOp(PhysicalOp):
    """Map-class since the DTL006 burn-down (same driver instrumentation
    as ExplodeOp)."""

    def __init__(self, child: PhysicalOp, ids, values, variable_name, value_name, schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.ids = ids
        self.values = values
        self.variable_name = variable_name
        self.value_name = value_name

    def map_partition(self, part, ctx):
        return part.unpivot(self.ids, self.values, self.variable_name,
                            self.value_name)

    def _map_exprs(self):
        return list(self.ids) + list(self.values)

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)


class SampleOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, fraction: float, with_replacement: bool, seed):
        super().__init__([child], child.schema, child.num_partitions)
        self.fraction = fraction
        self.with_replacement = with_replacement
        self.seed = seed

    def execute(self, inputs, ctx) -> PartStream:
        for i, part in enumerate(inputs[0]):
            seed = None if self.seed is None else self.seed + i
            yield part.sample(fraction=self.fraction, with_replacement=self.with_replacement,
                              seed=seed)


class MonotonicIdOp(PhysicalOp):
    """Per-partition ids offset by partition_index << 36 (reference:
    monotonically_increasing_id partition encoding)."""

    def __init__(self, child: PhysicalOp, column_name: str, schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.column_name = column_name

    def execute(self, inputs, ctx) -> PartStream:
        for i, part in enumerate(inputs[0]):
            yield part.add_monotonic_id(i << 36, self.column_name)


class WriteOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, root_dir: str, format: str,
                 compression, partition_cols, schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.root_dir = root_dir
        self.format = format
        self.compression = compression
        self.partition_cols = partition_cols

    def execute(self, inputs, ctx) -> PartStream:
        wrote = False
        for part in inputs[0]:
            wrote = True
            with ctx.stats.profiler.span("write.sink", kind="phase"):
                out = part.write_tabular(self.root_dir, self.format,
                                         self.compression,
                                         self.partition_cols)
            yield out
        if not wrote:
            yield MicroPartition.empty(self.schema)


# ---------------------------------------------------------------------------
# pipeline breakers
# ---------------------------------------------------------------------------

class CoalesceOp(PhysicalOp):
    """N partitions -> M partitions without a shuffle ('into_partitions')."""

    def __init__(self, child: PhysicalOp, num: int):
        super().__init__([child], child.schema, num)
        self.num = num

    def execute(self, inputs, ctx) -> PartStream:
        with ctx.stats.profiler.span("coalesce.gather", kind="phase"):
            parts = [p for p in inputs[0]]
        if not parts:
            return
        total = sum(len(p) for p in parts)
        if self.num >= len(parts):
            # split: rebalance rows evenly
            big = MicroPartition.concat(parts) if len(parts) > 1 else parts[0]
            per = (total + self.num - 1) // self.num if self.num else total
            for i in range(self.num):
                lo = min(i * per, total)
                hi = min((i + 1) * per, total)
                yield big.slice(lo, hi)
        else:
            # merge adjacent chunks
            per = (len(parts) + self.num - 1) // self.num
            for i in range(0, len(parts), per):
                group = parts[i:i + per]
                yield MicroPartition.concat(group) if len(group) > 1 else group[0]


class ShuffleOp(PhysicalOp):
    """Fanout+reduce all-to-all exchange (reference: FanoutInstruction +
    ReduceMerge, physical_plan.py:1365). scheme: hash | random | range.

    Exchange v2 (daft_tpu/exchange/, README "Exchange"): the translate
    wiring may attach a runtime-join-filter slot this exchange FEEDS
    (build side) or PRUNES WITH (probe side), and/or a stage-2 combine
    spec that folds map-side pieces per destination before they buffer.
    Bucket pieces additionally dictionary-encode before entering the
    spillable PartitionBuffer. Every leg is knob-gated and byte-identical
    off."""

    # exchange v2 attachments (class-level defaults keep every other
    # construction site unchanged)
    filter_feed = None    # JoinFilterSlot this build-side exchange populates
    probe_filter = None   # JoinFilterSlot whose sealed filter prunes here
    combine = None        # (stage2_aggs, key_cols) pre-exchange fold spec
    # FDO observation key (daft_tpu/adapt/): when set, the payload that
    # actually crossed this exchange is recorded under this canonical
    # subtree fingerprint at query end — the history future plans read
    fdo_obs_key = None
    # FDO fan-out resize (daft_tpu/adapt/fdo.py): emit this many output
    # partitions by concatenating ADJACENT hash buckets at reduce time.
    # Hashing stays modulo `num`, so group co-location, combine folds,
    # and — because per-bucket group sets are disjoint and
    # first-occurrence order composes — the OUTPUT ROW ORDER are all
    # byte-identical to the unresized exchange; only the partition count
    # (stage-2 invocations, downstream fan-in) shrinks. None = off.
    reduce_to = None

    def __init__(self, child: PhysicalOp, scheme: str, num: int,
                 by: Optional[List[Expression]] = None,
                 descending: Optional[List[bool]] = None,
                 nulls_first: Optional[List[Optional[bool]]] = None):
        super().__init__([child], child.schema, num)
        self.scheme = scheme
        self.num = num
        self.by = by or []
        self.descending = descending or [False] * len(self.by)
        self.nulls_first = nulls_first if nulls_first is not None else [None] * len(self.by)

    def _feed_filter(self, stream, ctx) -> PartStream:
        """Build-side pass-through: fold every streamed partition's join
        keys into the slot's builder; seal at stream end (the join op
        drains this side fully before the probe side's exchange runs).
        Any failure — including the ``join.filter`` fault site — abandons
        the filter; the exchange itself is untouched (fail-open)."""
        from . import faults

        slot = self.filter_feed
        if not getattr(ctx.cfg, "runtime_join_filters", True) \
                or not slot.eligible:
            slot.abandon()
            yield from stream
            return
        slot.begin()
        for p in stream:
            if ctx.foreign_owned(p):
                # multi-host scan locality: this process must not read the
                # partition, and a locally-built filter would miss foreign
                # build keys (a WRONG prune) — abandon entirely
                slot.abandon()
            else:
                try:
                    faults.check("join.filter", ctx.stats)
                    for t in p.chunk_tables():
                        slot.feed(t)
                except Exception:
                    slot.abandon()
                    ctx.stats.bump("join_filter_errors")
            yield p
        try:
            slot.seal()
        except Exception:
            slot.abandon()
            ctx.stats.bump("join_filter_errors")
        if slot.filter() is not None:
            ctx.stats.bump("join_filter_built")

    def _prune_stream(self, stream, ctx, obs=None) -> PartStream:
        """Probe-side pass-through: prune each partition with the sealed
        build-side filter BEFORE bucketing/spill/merge. The slot is
        consulted per partition (None — unsealed, abandoned, disabled —
        passes rows through untouched).

        ``obs`` is the shuffle's FDO observation accumulator: what
        pruning removed is added BACK there, so the side's recorded size
        is the pre-prune truth — a broadcast flip seeded from post-prune
        bytes would materialize the side UNPRUNED and mispredict."""
        from .exchange.joinfilter import prune_partition

        slot = self.probe_filter
        for p in stream:
            jf = slot.filter()
            if jf is None or (ctx.foreign_owned(p) and not p.is_loaded()):
                # foreign-owned (multi-host scan locality): pruning would
                # force this process to read a partition another host owns
                # — the mesh exchange skips it by owner instead
                yield p
            else:
                out = prune_partition(p, jf, self.by, ctx)
                if obs is not None and p.is_loaded():
                    # prune_partition forced the load; both sizes are free
                    pre_r = p.num_rows_or_none() or 0
                    pre_b = p.size_bytes() or 0
                    post_r = out.num_rows_or_none() or 0
                    post_b = out.size_bytes() or 0
                    obs[0] += max(0, pre_r - post_r)
                    obs[1] += max(0, pre_b - post_b)
                yield out

    def _peer_execute(self, stream, ctx, n, fdo_obs, backend) -> PartStream:
        """Peer-to-peer exchange: each source partition ships to a worker
        as a FANOUT task (split happens there, pieces stay hosted on that
        worker's piece-server) and each reduce output is a PeerPieceTask —
        an unloaded scan task whose payload is a piece-LOCATION map, pulled
        peer-to-peer by whichever worker lands the downstream task. The
        driver moves plan metadata and location maps only, so its payload
        bytes stay flat as the pool grows.

        Robustness contract: a worker declining a fanout (pool busy,
        ineligible partition, unroutable result) degrades THAT source to a
        driver-side split with inline pieces — mixed buckets are fine, the
        reader concatenates entries in source order either way. A peer
        dying after fanout is the reader's problem: PeerPieceTask fails
        over to the captured source task and recomputes just the lost
        piece (see peerplane.PeerPieceTask._recompute)."""
        from .dist.peerplane import PeerPieceTask, PieceRef
        from .integrity.lineage import fanout_piece_recipe, unwrap_source_task

        lineage_on = getattr(ctx.cfg, "lineage_recomputation", True)
        integrity = getattr(ctx.cfg, "partition_integrity", True)
        sid = backend.new_shuffle_id()
        ctx.register_peer_shuffle(sid)
        token = backend.peer_token()
        sources: Dict[int, Any] = {}
        entries: List[List[Any]] = [[] for _ in range(n)]
        saw = False

        def account(rows, nbytes):
            if rows:
                ctx.stats.bump("exchange_rows", rows)
            if nbytes:
                ctx.stats.bump("exchange_bytes", nbytes)
            if fdo_obs is not None:
                fdo_obs[0] += rows or 0
                fdo_obs[1] += nbytes or 0

        with ctx.stats.profiler.span("shuffle.fanout", kind="phase"):
            pool = ctx.pool()
            pending = []
            for pi, p in enumerate(stream):
                saw = True
                # capture BEFORE shipping: the recipe is the failover path
                # for every piece this source produces. A source WITHOUT a
                # recipe (loaded/derived partition, or lineage off) never
                # fans out remotely — a peer hosting unrecomputable pieces
                # would turn its death into a failed query, and the driver
                # already holds these bytes anyway.
                src_task = unwrap_source_task(p) if lineage_on else None
                if src_task is not None:
                    sources[pi] = src_task
                    spec = {"sid": sid, "src": pi, "scheme": self.scheme,
                            "num": n, "seed": pi, "by": self.by,
                            "crc": integrity}
                    pending.append((pi, p, pool.submit(
                        backend.execute_fanout, p, spec, ctx,
                        f"shuffle.{self.scheme}", pi)))
                else:
                    pending.append((pi, p, None))
            for pi, p, fut in pending:
                res = fut.result() if fut is not None else None
                if res is None:
                    # declined: split here, pieces ride inline in the map
                    if self.scheme == "hash":
                        pieces = p.partition_by_hash(self.by, n)
                    else:
                        pieces = p.partition_by_random(n, seed=pi)
                    src_task = sources.get(pi)
                    for i, piece in enumerate(pieces):
                        nrows = piece.num_rows_or_none() or 0
                        if not nrows:
                            continue
                        if src_task is not None:
                            piece.lineage_recipe = fanout_piece_recipe(
                                src_task, self.by, self.scheme, n, pi, i)
                        account(nrows, piece.size_bytes() or 0)
                        entries[i].append(piece)
                else:
                    wid, (host, port), metas = res
                    for (i, rows, nbytes, crc) in metas:
                        account(rows, nbytes)
                        entries[i].append(PieceRef(
                            wid, host, port, sid, i, pi, rows, nbytes, crc))
        if fdo_obs is not None and saw:
            ctx.stats.fdo_observe(self.fdo_obs_key, fdo_obs[0], fdo_obs[1])
        if not saw:
            return
        ctx.stats.bump("shuffles")
        split = (self.by, self.scheme, n)

        def emit(bucket_entries):
            refs = bucket_entries
            if not refs:
                return MicroPartition.empty(self.schema)
            # only the sources actually referenced by THIS bucket's remote
            # pieces ride along (inline pieces carry their own recipe)
            need = {e.src for e in refs if isinstance(e, PieceRef)}
            task = PeerPieceTask(
                self.schema, refs, token, split,
                {s: sources[s] for s in need if s in sources},
                checksum=integrity, stats=ctx.stats)
            return MicroPartition.from_scan_task(task)

        k = (self.reduce_to
             if self.reduce_to is not None and 0 < self.reduce_to < n
             else None)
        if k is None:
            for i in range(n):
                yield emit(entries[i])
            return
        groups: List[List[int]] = [[] for _ in range(k)]
        for i in range(n):
            groups[i * k // n].append(i)
        ctx.stats.bump("fdo_reduced_partitions", n - k)
        for idxs in groups:
            merged: List[Any] = []
            for i in idxs:
                merged.extend(entries[i])
            yield emit(merged)

    def execute(self, inputs, ctx) -> PartStream:
        n = self.num
        src = inputs[0]
        fdo_obs = [0, 0] if self.fdo_obs_key is not None else None
        if self.filter_feed is not None:
            src = self._feed_filter(src, ctx)
        if self.probe_filter is not None \
                and getattr(ctx.cfg, "runtime_join_filters", True):
            src = self._prune_stream(src, ctx, obs=fdo_obs)
        combine = (self.combine if self.combine is not None and
                   getattr(ctx.cfg, "hierarchical_exchange_combine", True)
                   else None)
        # Mesh path: one all_to_all collective over ICI instead of host fanout
        # (parallel/mesh_exec.py); falls through to host on ineligibility.
        # Range shuffles sample their boundaries host-side first (reference:
        # ReduceToQuantiles, execution_step.py:878) — the payload still rides
        # ICI, making device range-shuffle + per-device sort a global sort.
        dev_shuffle = getattr(ctx, "try_device_shuffle", None)
        pre_boundaries = None
        if dev_shuffle is not None and self.scheme in ("hash", "random", "range"):
            parts = [p for p in src]
            if not parts:
                return
            if self.scheme == "range":
                # cheap dtype-eligibility gate BEFORE the sampling work; the
                # sampled boundaries are reused by the host fallback below
                from .parallel.mesh_exec import exchangeable_dtype

                if all(exchangeable_dtype(f.dtype) for f in parts[0].schema):
                    samples = [sample_partition_keys(p, self.by, n,
                                                     ctx.cfg.sample_size_for_sort)
                               for p in parts]
                    pre_boundaries = boundaries_from_samples(
                        samples, self.by, n, self.descending, self.nulls_first)
            # exchange_rows/exchange_bytes are counted INSIDE the mesh
            # exchange (actual staged payload, post pre-combine) so the
            # device and host paths report the same thing
            out = dev_shuffle(parts, self.by, n, self.scheme, self.descending,
                              self.nulls_first, pre_boundaries,
                              combine=combine)
            if out is not None:
                yield from out
                return
            stream = iter(parts)
        else:
            stream = src
        # Peer-to-peer path (daft_tpu/dist/peerplane.py): hash/random
        # exchanges on a peer-capable worker pool fan out ON the workers
        # and reduce buckets become piece-location maps — payload bytes
        # never transit the driver. Exchange v2 attachments (join-filter
        # feed/prune, pre-combine) and range schemes keep the star path:
        # each is defined over driver-resident pieces, and p2p must be
        # byte-identical off, not approximately off.
        if (self.scheme in ("hash", "random")
                and self.filter_feed is None
                and self.probe_filter is None
                and combine is None
                and getattr(ctx.cfg, "peer_shuffle", True)):
            backend = getattr(ctx, "dist_backend", None)
            if (backend is not None
                    and getattr(backend, "execute_fanout", None) is not None
                    and backend.peer_ready()):
                yield from self._peer_execute(stream, ctx, n, fdo_obs,
                                              backend)
                return
        buckets = [ctx.partition_buffer() for _ in range(n)]
        # payload encoding engages on BUDGETED queries only: that is where
        # exchanged bytes gate throughput (ledger pressure -> spill IO, and
        # spilled encoded buckets stay encoded on disk). On an unbudgeted
        # in-memory exchange the encode/decode pass is pure overhead
        # (measured ~1.6x on the bench exchange rung), so it stands down.
        encode = (getattr(ctx.cfg, "exchange_payload_encoding", True)
                  and ctx.memory_budget is not None)
        comb = None
        if combine is not None:
            from .exchange.combine import BucketCombiner

            comb = BucketCombiner(combine[0], combine[1], ctx.stats,
                                  ledger=ctx.ledger,
                                  budget=ctx.memory_budget)

        def exchange_append(i: int, piece: MicroPartition) -> None:
            # every row/byte ACTUALLY crossing the exchange is counted here
            # — post filter-prune and pre-combine fold, so the counters are
            # the real exchanged payload on both the host and mesh paths
            # (the sketch subsystem's acceptance metric reads these)
            nrows = piece.num_rows_or_none()
            if nrows:
                ctx.stats.bump("exchange_rows", nrows)
            raw = piece.size_bytes() or 0
            if raw:
                ctx.stats.bump("exchange_bytes", raw)
            if fdo_obs is not None:
                fdo_obs[0] += nrows or 0
                fdo_obs[1] += raw
            if encode:
                enc_bytes = raw
                try:
                    from .exchange.encode import encode_exchange_partition

                    enc = encode_exchange_partition(
                        piece, ctx.stats,
                        integrity=getattr(ctx.cfg, "partition_integrity",
                                          True))
                except Exception:
                    enc = None
                    ctx.stats.bump("exchange_encode_failures")
                if enc is not None:
                    piece = enc
                    enc_bytes = piece.size_bytes() or raw
                    ctx.stats.bump("exchange_pieces_encoded")
                # the encoded-vs-raw ratio needs a denominator covering the
                # SAME pieces (exchange_bytes also counts gathers and
                # encode-disabled shuffles)
                if raw:
                    ctx.stats.bump("exchange_bytes_encodable", raw)
                if enc_bytes:
                    ctx.stats.bump("exchange_bytes_encoded", enc_bytes)
            buckets[i].append(piece)

        saw = False
        lineage_on = getattr(ctx.cfg, "lineage_recomputation", True)
        # the whole map-side fanout (decode + hash/split + bucket appends)
        # runs inside the FIRST pull of this op: make it a named phase on
        # the span timeline so the exchange's two halves are separable
        with ctx.stats.profiler.span("shuffle.fanout", kind="phase"):
            if self.scheme == "range":
                # Boundaries need all inputs, so partitions are buffered
                # (spillable); keys are SAMPLED AS PARTITIONS STREAM IN so a
                # spilled partition is never re-materialized for sampling,
                # and drain() drops each ref after fanout — out-of-core
                # inputs are resident once at a time.
                in_buf = ctx.partition_buffer()
                samples = []
                src_tasks = []
                for p in stream:
                    if pre_boundaries is None:
                        samples.append(sample_partition_keys(
                            p, self.by, n, ctx.cfg.sample_size_for_sort))
                    if lineage_on:
                        # scan-backed sources make every range piece
                        # recomputable (integrity/lineage.py): capture the
                        # task BEFORE the buffer/fanout materializes p
                        from .integrity.lineage import unwrap_source_task

                        src_tasks.append(unwrap_source_task(p))
                    else:
                        src_tasks.append(None)
                    in_buf.append(p)
                saw = len(in_buf) > 0
                if not saw:
                    boundaries = None
                elif pre_boundaries is not None:
                    boundaries = pre_boundaries  # sampled for device attempt
                else:
                    boundaries = boundaries_from_samples(
                        samples, self.by, n, self.descending, self.nulls_first)
                for pi, p in enumerate(in_buf.drain()):
                    pieces = p.partition_by_range(self.by, boundaries,
                                                  self.descending,
                                                  self.nulls_first)
                    for i, piece in enumerate(pieces):
                        if src_tasks[pi] is not None:
                            from .integrity.lineage import \
                                range_piece_recipe

                            piece.lineage_recipe = range_piece_recipe(
                                src_tasks[pi], self.by, boundaries,
                                self.descending, self.nulls_first, i)
                        exchange_append(min(i, n - 1), piece)
            else:
                def fanout(p, pi):
                    # lineage (integrity/lineage.py): when the SOURCE
                    # partition is scan-backed, every piece of this
                    # deterministic split can be recomputed by re-reading
                    # the source — capture the recipe BEFORE the split
                    # materializes p, so a piece spilled later survives a
                    # corrupted/missing spill file. Loaded/pruned sources
                    # decline (capturing them would pin memory): their
                    # pieces carry truncated lineage by design.
                    src_task = None
                    if lineage_on:
                        from .integrity.lineage import unwrap_source_task

                        src_task = unwrap_source_task(p)
                    if self.scheme == "hash":
                        pieces = p.partition_by_hash(self.by, n)
                    else:
                        pieces = p.partition_by_random(n, seed=pi)
                    if src_task is not None:
                        from .integrity.lineage import fanout_piece_recipe

                        for i, piece in enumerate(pieces):
                            piece.lineage_recipe = fanout_piece_recipe(
                                src_task, self.by, self.scheme, n, pi, i)
                    return pieces

                for pieces in _fanout_stream(stream, fanout, ctx,
                                             _subtree_may_yield_unloaded(self)):
                    saw = True
                    for i, piece in enumerate(pieces):
                        if comb is not None and not comb.failed:
                            flushed = comb.add(i, piece)
                            if flushed is not None:
                                # fold failed: everything staged so far is
                                # appended raw, combining stops for this
                                # shuffle (results stay correct — stage 2
                                # merges partials of any granularity)
                                for b, part in flushed:
                                    exchange_append(b, part)
                        else:
                            exchange_append(i, piece)
                if comb is not None:
                    for b, part in comb.finish():
                        exchange_append(b, part)
        if fdo_obs is not None and saw:
            ctx.stats.fdo_observe(self.fdo_obs_key, fdo_obs[0], fdo_obs[1])
        if not saw:
            return
        ctx.stats.bump("shuffles")
        k = (self.reduce_to
             if self.reduce_to is not None and 0 < self.reduce_to < n
             else None)
        if k is None:
            for i in range(n):
                if i + 1 < n:
                    # unspill readahead across the reduce side: bucket
                    # i+1's spilled pieces re-materialize on the pool
                    # while the consumer works on bucket i
                    buckets[i + 1].preload()
                if len(buckets[i]):
                    with ctx.stats.profiler.span("shuffle.merge",
                                                 kind="phase"):
                        merged = MicroPartition.concat(buckets[i].parts())
                    yield merged
                else:
                    yield MicroPartition.empty(self.schema)
                buckets[i].release()
            return
        # FDO reduce-side fan-in: adjacent buckets merge into k outputs
        # (bucket i -> output i*k//n), in bucket order — byte-identical
        # rows AND row order vs the k=None loop's concatenated outputs
        groups: List[List[int]] = [[] for _ in range(k)]
        for i in range(n):
            groups[i * k // n].append(i)
        ctx.stats.bump("fdo_reduced_partitions", n - k)
        for g, idxs in enumerate(groups):
            if g + 1 < k:
                for j in groups[g + 1]:
                    buckets[j].preload()
            parts: List[MicroPartition] = []
            for i in idxs:
                if len(buckets[i]):
                    parts.extend(buckets[i].parts())
            if parts:
                with ctx.stats.profiler.span("shuffle.merge",
                                             kind="phase"):
                    merged = (MicroPartition.concat(parts)
                              if len(parts) > 1 else parts[0])
                yield merged
            else:
                yield MicroPartition.empty(self.schema)
            for i in idxs:
                buckets[i].release()

    def describe(self):
        by = ", ".join(e._node.display() for e in self.by)
        tags = []
        if self.filter_feed is not None:
            tags.append("join-filter-feed")
        if self.probe_filter is not None:
            tags.append("join-filter-probe")
        if self.combine is not None:
            tags.append("combine")
        if self.reduce_to is not None:
            tags.append(f"fdo-reduce {self.reduce_to}")
        tag = f" <{'+'.join(tags)}>" if tags else ""
        return (f"Shuffle[{self.scheme}] -> {self.num}"
                + (f" by [{by}]" if by else "") + tag)


def _subtree_may_yield_unloaded(op: PhysicalOp) -> bool:
    """True when `op`'s stream can contain UNLOADED partitions: a ScanOp
    anywhere below it (streaming ops like Limit/Project pass scan
    partitions through un-forced). Pipeline breakers always yield loaded
    partitions, but they cannot appear BETWEEN a scan and this op without
    forcing it, so the presence test stays sound and conservative."""
    if isinstance(op, ScanOp):
        return True
    return any(_subtree_may_yield_unloaded(c) for c in op.children)


def _fanout_stream(stream: PartStream, fn, ctx, may_be_unloaded: bool):
    """Map-side shuffle fanout, yielding each partition's piece list IN
    INPUT ORDER. With parallel_shuffle_fanout on (and a real worker pool),
    the decode + hash/split of partition i+1 runs on the pool while
    partition i's pieces append to their buckets — the reference runs
    fanout as parallel partition tasks (FanoutInstruction,
    physical_plan.py:1365); inline-serial otherwise. Streams that may
    carry unloaded (out-of-core) partitions get an in-flight window of
    min(4, workers) so only a few decoded partitions exist beyond the
    buckets (4 ≈ double-buffering per core-pair; measured: window 2 left
    the SF10 fanout decode-bound, 4 closed it); fully-resident streams
    use the normal workers+backlog window. Order-preserving dispatch
    keeps bucket contents byte-identical with the inline path."""
    if not getattr(ctx.cfg, "parallel_shuffle_fanout", False) \
            or ctx.num_workers <= 1:
        for pi, p in enumerate(stream):
            yield fn(p, pi)
        return
    from .scheduler import PartitionTask, dispatch

    window = min(4, ctx.num_workers) if may_be_unloaded else None

    def tasks():
        for pi, p in enumerate(stream):
            yield PartitionTask(p, (lambda part, _pi=pi: fn(part, _pi)),
                                None, "shuffle-fanout", pi)

    yield from dispatch(tasks(), ctx, window=window)


def _counted(stream: PartStream, ctx, counter: str) -> PartStream:
    """Pass-through that counts rows AND bytes entering an exchange
    boundary (rows alone can't see payload inflation: a sketch row is
    16 KiB where a raw row is a few bytes — exchange_bytes keeps the
    before/after metric honest)."""
    bytes_counter = counter.replace("_rows", "_bytes")
    for p in stream:
        n = p.num_rows_or_none()
        if n:
            ctx.stats.bump(counter, n)
        if p.is_loaded():
            b = p.size_bytes()
            if b:
                ctx.stats.bump(bytes_counter, b)
        yield p


def sample_partition_keys(p: MicroPartition, by: List[Expression], num: int,
                          sample_size: int = 20):
    """Sampled sort-key rows of ONE partition (possibly an empty Table).
    Called while partitions stream into a spillable buffer, so boundary
    estimation never re-materializes a spilled partition (reference: sort
    sampling in physical_plan.py:1414)."""
    keys = p.table().eval_expression_list(by)
    if len(keys) == 0:
        return keys
    k = min(len(keys), max(sample_size, sample_size * num))
    return keys.sample(size=k, seed=0) if k < len(keys) else keys


def boundaries_from_samples(samples, by: List[Expression], num: int,
                            descending: List[bool],
                            nulls_first: Optional[List[Optional[bool]]] = None):
    """num-1 quantile boundary rows from per-partition key samples."""
    import pyarrow as pa

    from .series import Series
    from .table import Table

    key_tables = [s for s in samples if s is not None and len(s) > 0]
    if not key_tables:
        return next(s for s in samples if s is not None).slice(0, 0)
    allk = Table.concat(key_tables)
    skeys = [col(n) for n in allk.column_names]
    allk = allk.sort(skeys, descending=descending, nulls_first=nulls_first)
    m = len(allk)
    idxs = [int(np.floor(m * (i + 1) / num)) for i in range(num - 1)]
    idxs = [min(max(i, 0), m - 1) for i in idxs]
    return allk.take(Series.from_arrow(pa.array(np.asarray(idxs, dtype=np.uint64)), "i"))


def sample_boundaries(parts: List[MicroPartition], by: List[Expression], num: int,
                      descending: List[bool],
                      nulls_first: Optional[List[Optional[bool]]] = None,
                      sample_size: int = 20):
    """Boundary rows for already-resident partitions (mesh/host sort paths
    that never spill). Streaming consumers should sample incrementally via
    sample_partition_keys + boundaries_from_samples instead."""
    samples = [sample_partition_keys(p, by, num, sample_size) for p in parts]
    return boundaries_from_samples(samples, by, num, descending, nulls_first)


def aligned_boundaries_from_samples(sides_samples, num: int):
    """Quantile boundaries over the COMBINED per-partition key samples of
    several inputs, so all sides range-partition identically — bucket i on
    every side covers the same key interval (reference: Boundaries
    intersection, daft/runners/partitioning.py:110-166). Samples are
    collected while partitions stream into their spillable buffers."""
    import pyarrow as pa

    from .series import Series
    from .table import Table

    key_tables = []
    first_empty = None
    for samples in sides_samples:
        for keys in samples:
            if keys is None:
                continue
            if first_empty is None:
                first_empty = keys.slice(0, 0)
            if len(keys) == 0:
                continue
            # align names AND dtypes to the first side so samples concat
            if keys.schema != first_empty.schema:
                keys = Table(first_empty.schema,
                             [c.cast(f.dtype).rename(f.name)
                              for c, f in zip(keys._columns, first_empty.schema)])
            key_tables.append(keys)
    if not key_tables:
        return first_empty
    allk = Table.concat(key_tables)
    skeys = [col(n) for n in allk.column_names]
    allk = allk.sort(skeys)
    m = len(allk)
    idxs = [min(max(int(np.floor(m * (i + 1) / num)), 0), m - 1) for i in range(num - 1)]
    return allk.take(Series.from_arrow(pa.array(np.asarray(idxs, dtype=np.uint64)), "i"))


def sample_aligned_boundaries(sides, num: int, sample_size: int = 20):
    """Aligned boundaries for already-resident inputs (each `(parts,
    key_exprs)`); streaming consumers sample incrementally instead."""
    return aligned_boundaries_from_samples(
        [[sample_partition_keys(p, by, num, sample_size) for p in parts]
         for parts, by in sides], num)


class SortOp(PhysicalOp):
    """Per-partition sort; upstream ShuffleOp(range) makes it a global sort."""

    def __init__(self, child: PhysicalOp, sort_by, descending, nulls_first):
        super().__init__([child], child.schema, child.num_partitions)
        self.sort_by = sort_by
        self.descending = descending
        self.nulls_first = nulls_first

    def execute(self, inputs, ctx) -> PartStream:
        # sequential by design: the per-partition sort may route through
        # the device argsort, and device compute serializes on one chip.
        # The kernel interval gets its own phase span (DTL006) so profiles
        # split sort time from pull overhead.
        prof = ctx.stats.profiler
        for part in inputs[0]:
            with prof.span("sort.partition", kind="phase"):
                out = ctx.eval_sort(part, self.sort_by, self.descending,
                                    self.nulls_first)
            yield out

    def describe(self):
        return "Sort: " + ", ".join(e._node.display() for e in self.sort_by)


class AggregateOp(PhysicalOp):
    """Full aggregation per partition (single-partition finals and stage
    executions both use this)."""

    def __init__(self, child: PhysicalOp, aggregations: List[Expression],
                 groupby: List[Expression], schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.aggregations = aggregations
        self.groupby = groupby

    def map_partition(self, part, ctx):
        return ctx.eval_agg(part, self.aggregations, self.groupby or None)

    def map_partition_dispatch(self, part, ctx):
        return ctx.eval_agg_dispatch(part, self.aggregations,
                                     self.groupby or None)

    def device_pipelinable(self, ctx) -> bool:
        if not ctx.cfg.use_device_kernels:
            return False
        try:
            from .kernels.device_agg import agg_plan_device_compilable
        except Exception:
            return False
        return agg_plan_device_compilable(self.aggregations,
                                          self.children[0].schema)

    def map_partition_declined(self, part, ctx):
        # dispatch already proved this partition device-ineligible
        return ctx._eval_agg_host(part, self.aggregations, self.groupby or None)

    def map_empty(self, ctx):
        # global agg over zero partitions still yields one row (count=0 etc.)
        if not self.groupby:
            yield MicroPartition.empty(self.children[0].schema).agg(self.aggregations, None)

    def _map_exprs(self):
        return list(self.aggregations) + list(self.groupby)

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)

    def describe(self):
        a = ", ".join(e._node.display() for e in self.aggregations)
        g = ", ".join(e._node.display() for e in self.groupby)
        return f"Aggregate: {a}" + (f" by [{g}]" if g else "")


class FusedFilterAggregateOp(PhysicalOp):
    """Filter fused into a grouped aggregation: on the device path the
    predicate stays a mask feeding masked segment reductions — no host
    compaction or intermediate materialization (the TPU analog of the
    reference's fused streaming pipeline, pipeline.rs:141-211). The host
    fallback applies filter-then-agg per partition."""

    def __init__(self, child: PhysicalOp, predicate: Expression,
                 aggregations: List[Expression], groupby: List[Expression],
                 schema: Schema):
        super().__init__([child], schema, child.num_partitions)
        self.predicate = predicate
        self.aggregations = aggregations
        self.groupby = groupby

    def map_partition(self, part, ctx):
        return ctx.eval_agg(part, self.aggregations, self.groupby or None,
                            predicate=self.predicate)

    def map_partition_dispatch(self, part, ctx):
        return ctx.eval_agg_dispatch(part, self.aggregations,
                                     self.groupby or None,
                                     predicate=self.predicate)

    def device_pipelinable(self, ctx) -> bool:
        if not ctx.cfg.use_device_kernels:
            return False
        try:
            from .kernels.device_agg import agg_plan_device_compilable
        except Exception:
            return False
        return agg_plan_device_compilable(self.aggregations,
                                          self.children[0].schema,
                                          predicate=self.predicate)

    def map_partition_declined(self, part, ctx):
        return ctx._eval_agg_host(part, self.aggregations, self.groupby or None,
                                  predicate=self.predicate)

    def map_empty(self, ctx):
        if not self.groupby:
            yield MicroPartition.empty(self.children[0].schema).agg(self.aggregations, None)

    def _map_exprs(self):
        return [self.predicate] + list(self.aggregations) + list(self.groupby)

    def execute(self, inputs, ctx) -> PartStream:
        return self._map_execute(inputs, ctx)

    def describe(self):
        a = ", ".join(e._node.display() for e in self.aggregations)
        g = ", ".join(e._node.display() for e in self.groupby)
        return (f"FusedFilterAggregate: where {self.predicate._node.display()} agg {a}"
                + (f" by [{g}]" if g else ""))


class GatherOp(PhysicalOp):
    """All partitions -> one (global agg finals, small sorts, sort_merge)."""

    def __init__(self, child: PhysicalOp):
        super().__init__([child], child.schema, 1)

    def execute(self, inputs, ctx) -> PartStream:
        with ctx.stats.profiler.span("gather.merge", kind="phase"):
            parts = [p for p in _counted(inputs[0], ctx, "exchange_rows")]
            out = (MicroPartition.empty(self.schema) if not parts
                   else parts[0] if len(parts) == 1
                   else MicroPartition.concat(parts))
        yield out


class DistinctOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, subset: Optional[List[Expression]]):
        super().__init__([child], child.schema, child.num_partitions)
        self.subset = subset

    def execute(self, inputs, ctx) -> PartStream:
        # sequential like SortOp (the distinct may use the device group-
        # codes kernel); the kernel interval is a phase span (DTL006)
        prof = ctx.stats.profiler
        for part in inputs[0]:
            with prof.span("distinct.partition", kind="phase"):
                out = ctx.eval_distinct(part, self.subset)
            yield out


class PivotOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, groupby, pivot_col, value_col, agg_fn, names,
                 schema: Schema):
        super().__init__([child], schema, 1)
        self.groupby = groupby
        self.pivot_col = pivot_col
        self.value_col = value_col
        self.agg_fn = agg_fn
        self.names = names

    def execute(self, inputs, ctx) -> PartStream:
        # the gather is this op's blocking phase (DTL006): it buffers the
        # whole input before the single-partition pivot can run
        with ctx.stats.profiler.span("pivot.gather", kind="phase"):
            parts = [p for p in inputs[0]]
            part = MicroPartition.concat(parts) if len(parts) > 1 else (
                parts[0] if parts else MicroPartition.empty(self.children[0].schema))
        out = part.pivot(self.groupby, self.pivot_col, self.value_col, self.names, self.agg_fn)
        yield out.cast_to_schema(self.schema)


class ConcatOp(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp, schema: Schema):
        super().__init__([left, right], schema, left.num_partitions + right.num_partitions)

    def execute(self, inputs, ctx) -> PartStream:
        for part in inputs[0]:
            yield part.cast_to_schema(self.schema)
        for part in inputs[1]:
            yield part.cast_to_schema(self.schema)


def _pipelined_join(ctx, pairs, how: str, suffix: str):
    """Shared double-buffered join driver: for each (l, r, lon, ron) pair,
    pair i+1's keys stage and its probe LAUNCHES while pair i's result
    resolves (one pending slot bounds the extra HBM to one in-flight
    pair). A declined dispatch goes straight to the host join — never
    re-staging the attempt dispatch just proved doomed."""
    pending = None
    for l, r, lon, ron in pairs:
        fin = ctx.eval_join_dispatch(l, r, lon, ron, how, suffix)
        if pending is not None:
            yield pending()
            pending = None
        if fin is not None:
            pending = fin
        else:
            yield ctx.eval_join_declined(l, r, lon, ron, how, suffix)
    if pending is not None:
        yield pending()


class HashJoinOp(PhysicalOp):
    """Partition-aligned join: bucket i of left joins bucket i of right.
    Upstream ShuffleOps co-partition both sides."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp, left_on, right_on,
                 how: str, schema: Schema, suffix: str = "right."):
        super().__init__([left, right], schema, max(left.num_partitions, right.num_partitions))
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.suffix = suffix

    def execute(self, inputs, ctx) -> PartStream:
        lbuf = ctx.partition_buffer()
        rbuf = ctx.partition_buffer()
        with ctx.stats.profiler.span("join.build", kind="phase"):
            for p in inputs[0]:
                lbuf.append(p)
            for p in inputs[1]:
                rbuf.append(p)
        n = max(len(lbuf), len(rbuf))
        lschema = self.children[0].schema
        rschema = self.children[1].schema
        # drain() is lazy: a partition's held bytes leave the ledger only when
        # its pair is consumed, and the ref drops right after the join.
        liter = lbuf.drain()
        riter = rbuf.drain()

        def pairs():
            for _ in range(n):
                l = next(liter, None)
                r = next(riter, None)
                if l is None:
                    l = MicroPartition.empty(lschema)
                if r is None:
                    r = MicroPartition.empty(rschema)
                yield l, r, self.left_on, self.right_on

        yield from _pipelined_join(ctx, pairs(), self.how, self.suffix)

    def describe(self):
        return f"HashJoin[{self.how}]"


class BroadcastJoinOp(PhysicalOp):
    """Collect the small side fully, stream the large side (reference:
    broadcast join strategy, translate.rs join planning)."""

    # set by _translate_join when FDO history (not a static estimate)
    # chose this strategy: (site_fp, max_bytes) mispredict guard + the
    # observation key that keeps the side's history current
    fdo_guard = None
    fdo_obs_key = None

    def __init__(self, big: PhysicalOp, small: PhysicalOp, big_on, small_on,
                 how: str, schema: Schema, small_is_left: bool, suffix: str = "right."):
        super().__init__([big, small], schema, big.num_partitions)
        self.big_on = big_on
        self.small_on = small_on
        self.how = how
        self.small_is_left = small_is_left
        self.suffix = suffix

    def _filter_prunable(self) -> bool:
        """Whether the streamed (big) side may be pruned by a filter built
        from the replicated side's keys — the shared per-join-type gate
        (exchange.joinfilter.PRUNABLE); the probe here is the big side,
        which is the RIGHT side exactly when the small side is left."""
        from .exchange.joinfilter import prunable

        return prunable(self.how, probe_is_right=self.small_is_left)

    def _build_small_filter(self, small: MicroPartition, ctx):
        """Bloom + min-max filter over the collected small side's keys, or
        None (knob off, ineligible dtypes, any failure — fail-open; the
        ``join.filter`` fault site fires per build attempt)."""
        from . import faults

        if not getattr(ctx.cfg, "runtime_join_filters", True) \
                or not self._filter_prunable():
            return None
        from .exchange.joinfilter import JoinFilterSlot

        slot = JoinFilterSlot(self.small_on, self.big_on,
                              self.children[1].schema,
                              self.children[0].schema, self.how)
        if not slot.eligible:
            return None
        try:
            faults.check("join.filter", ctx.stats)
            slot.begin()
            for t in small.chunk_tables():
                slot.feed(t)
            slot.seal()
        except Exception:
            ctx.stats.bump("join_filter_errors")
            return None
        jf = slot.filter()
        if jf is not None:
            ctx.stats.bump("join_filter_built")
        return jf

    def execute(self, inputs, ctx) -> PartStream:
        with ctx.stats.profiler.span("join.build", kind="phase"):
            small_parts = [p for p in inputs[1]]
            small = (MicroPartition.concat(small_parts) if len(small_parts) > 1
                     else (small_parts[0] if small_parts else MicroPartition.empty(self.children[1].schema)))
            # mesh runners replicate the build keys into every device's HBM
            # here (one ICI broadcast); per-partition probes stay device-local
            small = ctx.prepare_broadcast(small, self.small_on, self.how)
            # runtime join filter: the small side IS the build side — prune
            # each streamed big partition before its probe (fewer rows into
            # the per-pair join; semantics gated per join type)
            jf = self._build_small_filter(small, ctx)
        ctx.stats.bump("broadcast_joins")
        small_bytes = small.size_bytes() or 0
        if self.fdo_obs_key is not None:
            # keep the side's history current even while the broadcast
            # plan serves — a grown side reverts the decision next plan
            ctx.stats.fdo_observe(self.fdo_obs_key, len(small), small_bytes)
        if self.fdo_guard is not None and small_bytes > self.fdo_guard[1]:
            # history said broadcast; the side arrived big. The query
            # completes on this (correct, merely slower) plan — the entry
            # is demoted and the next plan degrades to the uncached hash
            # strategy from the fresh observation above.
            from .adapt.fdo import note_broadcast_mispredict

            note_broadcast_mispredict(self.fdo_guard, small_bytes, ctx,
                                      getattr(ctx, "canonical_fp", ""))

        def pairs():
            from .exchange.joinfilter import prune_partition

            for part in inputs[0]:
                if jf is not None:
                    part = prune_partition(part, jf, self.big_on, ctx)
                if self.small_is_left:
                    yield small, part, self.small_on, self.big_on
                else:
                    yield part, small, self.big_on, self.small_on

        yield from _pipelined_join(ctx, pairs(), self.how, self.suffix)

    def describe(self):
        return f"BroadcastJoin[{self.how}]"


class SortMergeJoinOp(PhysicalOp):
    """Distributed sort-merge join with ALIGNED range boundaries: both sides
    sample their join keys into one combined quantile set, range-partition by
    the same boundaries (bucket i of left joins exactly bucket i of right),
    and merge per bucket — no single-partition gather. Reference:
    physical_plan.py:860 (sort_merge_join_aligned_boundaries) + Boundaries
    intersection (daft/runners/partitioning.py:110-166). Per-bucket sorted
    outputs concatenate to a globally key-sorted result, preserving the
    sort-merge contract."""

    def __init__(self, left: PhysicalOp, right: PhysicalOp, left_on, right_on,
                 how: str, schema: Schema, suffix: str = "right."):
        super().__init__([left, right], schema,
                         max(left.num_partitions, right.num_partitions))
        self.left_on = left_on
        self.right_on = right_on
        self.how = how
        self.suffix = suffix

    def execute(self, inputs, ctx) -> PartStream:
        lbuf = ctx.partition_buffer()
        rbuf = ctx.partition_buffer()
        lsamples, rsamples = [], []
        n = self.num_partitions
        ssize = ctx.cfg.sample_size_for_sort
        # keys sampled as partitions stream in: spilled inputs are never
        # re-materialized for boundary estimation
        with ctx.stats.profiler.span("join.build", kind="phase"):
            for p in inputs[0]:
                lsamples.append(sample_partition_keys(p, self.left_on, n,
                                                      ssize))
                lbuf.append(p)
            for p in inputs[1]:
                rsamples.append(sample_partition_keys(p, self.right_on, n,
                                                      ssize))
                rbuf.append(p)
        lschema = self.children[0].schema
        rschema = self.children[1].schema
        if n <= 1 or (len(lbuf) <= 1 and len(rbuf) <= 1):
            # concat needs every partition resident at once (the documented
            # single-partition merge); keep ledger accounting until after
            lparts = lbuf.parts()
            rparts = rbuf.parts()
            l = MicroPartition.concat(lparts) if len(lparts) > 1 else (
                lparts[0] if lparts else MicroPartition.empty(lschema))
            r = MicroPartition.concat(rparts) if len(rparts) > 1 else (
                rparts[0] if rparts else MicroPartition.empty(rschema))
            lbuf.release()
            rbuf.release()
            yield l.sort_merge_join(r, self.left_on, self.right_on, self.how, self.suffix)
            return
        k = len(self.left_on)
        bnds = aligned_boundaries_from_samples([lsamples, rsamples], n)
        ctx.stats.bump("aligned_boundary_shuffles")
        # Mesh path: BOTH sides ride the same aligned-boundary range exchange
        # over ICI; bucket i of each side lands co-partitioned on device i % n
        # with its columns left HBM-resident for the per-bucket merge.
        dev_shuffle = getattr(ctx, "try_device_shuffle", None)
        if dev_shuffle is not None:
            from .parallel.mesh_exec import exchangeable_dtype

            lparts = lbuf.parts()
            rparts = rbuf.parts()
            lrows = sum(len(p) for p in lparts)
            rrows = sum(len(p) for p in rparts)
            eligible = (lrows > 0 and rrows > 0  # empty sides: host handles
                        and all(p.is_loaded() for p in lparts + rparts)
                        and all(exchangeable_dtype(f.dtype) for f in lschema)
                        and all(exchangeable_dtype(f.dtype) for f in rschema))
            if eligible:
                zeros, nf = [False] * k, [None] * k
                # exchange the SMALLER side first: a late ineligibility only
                # detectable at staging (e.g. int64 beyond int32 range with
                # x64 off) then wastes the cheaper collective, not both
                small_left = lrows <= rrows
                first = ((lparts, self.left_on) if small_left
                         else (rparts, self.right_on))
                second = ((rparts, self.right_on) if small_left
                          else (lparts, self.left_on))
                out1 = dev_shuffle(first[0], first[1], n, "range", zeros, nf, bnds)
                out2 = (dev_shuffle(second[0], second[1], n, "range", zeros,
                                    nf, bnds) if out1 is not None else None)
                lout, rout = ((out1, out2) if small_left else (out2, out1))
                if lout is not None and rout is not None:
                    lbuf.release()
                    rbuf.release()
                    ctx.stats.bump("device_aligned_smj_exchanges")
                    for l, r in zip(lout, rout):
                        yield l.sort_merge_join(r, self.left_on, self.right_on,
                                                self.how, self.suffix)
                    return
        lbuckets = [ctx.partition_buffer() for _ in range(n)]
        rbuckets = [ctx.partition_buffer() for _ in range(n)]
        for buf, on, buckets in ((lbuf, self.left_on, lbuckets),
                                 (rbuf, self.right_on, rbuckets)):
            for p in buf.drain():
                pieces = p.partition_by_range(on, bnds, [False] * k, [None] * k)
                for i, piece in enumerate(pieces):
                    # the aligned-boundary exchange is a real exchange:
                    # count its payload at bucket append so this fallback
                    # matches the mesh path's staged-payload accounting
                    nrows = piece.num_rows_or_none()
                    if nrows:
                        ctx.stats.bump("exchange_rows", nrows)
                        pb = piece.size_bytes() or 0
                        if pb:
                            ctx.stats.bump("exchange_bytes", pb)
                    buckets[min(i, n - 1)].append(piece)
        for i in range(n):
            l = (MicroPartition.concat(lbuckets[i].parts()) if len(lbuckets[i]) > 1
                 else (lbuckets[i].parts()[0] if len(lbuckets[i]) else MicroPartition.empty(lschema)))
            r = (MicroPartition.concat(rbuckets[i].parts()) if len(rbuckets[i]) > 1
                 else (rbuckets[i].parts()[0] if len(rbuckets[i]) else MicroPartition.empty(rschema)))
            yield l.sort_merge_join(r, self.left_on, self.right_on, self.how, self.suffix)
            lbuckets[i].release()
            rbuckets[i].release()


class CrossJoinOp(PhysicalOp):
    def __init__(self, left: PhysicalOp, right: PhysicalOp, schema: Schema, suffix: str):
        super().__init__([left, right], schema, left.num_partitions)
        self.suffix = suffix

    def execute(self, inputs, ctx) -> PartStream:
        rparts = [p for p in inputs[1]]
        right = (MicroPartition.concat(rparts) if len(rparts) > 1
                 else (rparts[0] if rparts else MicroPartition.empty(self.children[1].schema)))
        key = "__cross_key"
        rk = right.eval_expression_list(
            [col(c) for c in right.column_names] + [lit(1).alias(key)])
        for part in inputs[0]:
            lk = part.eval_expression_list(
                [col(c) for c in part.column_names] + [lit(1).alias(key)])
            joined = lk.hash_join(rk, [col(key)], [col(key)], "inner", self.suffix)
            keep = [c for c in joined.column_names if c != key]
            yield joined.select_columns(keep).cast_to_schema(self.schema)


# ---------------------------------------------------------------------------
# two-stage aggregation decomposition (reference: translate.rs:761
# populate_aggregation_stages)
# ---------------------------------------------------------------------------

DECOMPOSABLE = {"sum", "count", "mean", "min", "max", "list", "concat", "any_value", "stddev"}

# approximate aggregations decompose through the sketch subsystem
# (daft_tpu/sketch/): stage 1 builds a fixed-size mergeable sketch per
# group, the exchange ships serialized sketch BYTES (a Binary column),
# stage 2 merges registers, and the final projection computes the estimate
# (reference: daft-sketch/hyperloglog stages in translate.rs:761+)
SKETCH_DECOMPOSABLE = {"approx_count_distinct", "approx_percentiles"}


def _strip_alias(e: Expression) -> AggExpr:
    n = e._node
    while isinstance(n, Alias):
        n = n.child
    if not isinstance(n, AggExpr):
        raise ValueError(f"expected aggregation expression, got {e!r}")
    return n


def aggs_decomposable(aggs: List[Expression], include_sketch: bool = False) -> bool:
    allowed = DECOMPOSABLE | (SKETCH_DECOMPOSABLE if include_sketch else set())
    try:
        return all(_strip_alias(e).kind in allowed for e in aggs)
    except ValueError:
        return False


def populate_aggregation_stages(
    aggs: List[Expression],
) -> Tuple[List[Expression], List[Expression], List[Expression]]:
    """Split aggregations into (first_stage, second_stage, final_projection).

    first_stage runs per input partition; second_stage merges partials after a
    shuffle on the group keys; final_projection computes derived results
    (mean = sum/count, stddev = sqrt(m2)). Mirrors translate.rs:761.
    """
    stage1: List[Expression] = []
    stage2: List[Expression] = []
    final: List[Expression] = []
    seen_ids: Dict[Tuple, str] = {}

    def s1(kind: str, child_expr: Expression, tag: str, extra=None) -> str:
        key = (kind, child_expr._node._key(), tag)
        if key in seen_ids:
            return seen_ids[key]
        ident = f"__s1_{len(seen_ids)}_{kind}"
        seen_ids[key] = ident
        stage1.append(Expression(AggExpr(kind, child_expr._node, extra)).alias(ident))
        merge_kind = {"sum": "sum", "count": "sum", "min": "min", "max": "max",
                      "list": "concat", "concat": "concat", "any_value": "any_value",
                      "sketch_hll": "merge_sketch_hll",
                      "sketch_quantile": "merge_sketch_quantile"}[kind]
        stage2.append(Expression(AggExpr(merge_kind, col(ident)._node,
                                         extra if kind == "any_value" else None)).alias(ident))
        return ident

    for e in aggs:
        node = _strip_alias(e)
        alias = e.name()
        child = Expression(node.child)
        k = node.kind
        if k in ("sum", "min", "max"):
            ident = s1(k, child, "")
            final.append(col(ident).alias(alias))
        elif k == "count":
            ident = s1("count", child, node.extra.get("mode", "valid"), dict(node.extra))
            final.append(col(ident).alias(alias))
        elif k == "mean":
            sid = s1("sum", child, "")
            cid = s1("count", child, "valid", {"mode": "valid"})
            final.append((col(sid) / col(cid)).alias(alias))
        elif k == "stddev":
            # population stddev via sum / sum-of-squares / count; the sum and
            # count partials are shared with any sum()/mean() of the same child
            sid = s1("sum", child, "")
            qid = s1("sum", child * child, "")
            cid = s1("count", child, "valid", {"mode": "valid"})
            mean = col(sid) / col(cid)
            var = (col(qid) / col(cid)) - (mean * mean)
            # max(var, 0): clamp tiny negative fp error before sqrt
            clamped = (var + abs(var)) / lit(2.0)
            final.append((clamped ** lit(0.5)).alias(alias))
        elif k == "list":
            ident = s1("list", child, "list")
            final.append(col(ident).alias(alias))
        elif k == "concat":
            ident = s1("concat", child, "concat")
            final.append(col(ident).alias(alias))
        elif k == "any_value":
            ident = s1("any_value", child, "any", dict(node.extra))
            final.append(col(ident).alias(alias))
        elif k == "approx_count_distinct":
            # sketch->merge->estimate: the exchange carries HLL register
            # bytes, never the counted rows (daft_tpu/sketch/hll.py)
            from .expressions import Function

            ident = s1("sketch_hll", child, "hll")
            final.append(Expression(Function(
                "sketch.hll_estimate", [col(ident)._node])).alias(alias))
        elif k == "approx_percentiles":
            from .expressions import Function

            ident = s1("sketch_quantile", child, "qsketch")
            final.append(Expression(Function(
                "sketch.quantile_estimate", [col(ident)._node],
                {"percentiles": node.extra.get("percentiles", 0.5)}))
                .alias(alias))
        else:
            raise ValueError(f"aggregation {k!r} is not decomposable")
    return stage1, stage2, final


# ---------------------------------------------------------------------------
# logical -> physical translation
# ---------------------------------------------------------------------------

def _split_morsels(parts: List[MicroPartition], cfg) -> List[MicroPartition]:
    """Split oversized in-memory partitions into morsels so the worker pool
    has parallel units even for a single-partition source (reference: the
    morsel size driving source chunking, default_morsel_size). Zero-copy
    slices; partition count is fixed here at plan time so aggregate staging
    sees the real parallelism."""
    from .context import resolve_executor_threads

    if getattr(cfg, "use_device_kernels", False):
        # the device path wants whole partitions: one fused kernel over one
        # big resident buffer beats many small dispatches, and splitting
        # would mint fresh MicroPartitions each plan — orphaning the HBM
        # residency caches that make warm queries fast
        return parts
    threads = resolve_executor_threads(cfg)
    if threads <= 1:
        return parts
    morsel = max(int(cfg.default_morsel_size), 1)
    out: List[MicroPartition] = []
    for p in parts:
        n = p.num_rows_or_none()
        if n is None or n <= 2 * morsel:
            out.append(p)
            continue
        k = min(-(-n // morsel), threads * 4)
        step = -(-n // k)
        for s in range(0, n, step):
            out.append(p.slice(s, min(s + step, n)))
    return out


def fuse_for_device(op: PhysicalOp, cfg) -> PhysicalOp:
    """Post-translation fusion: Aggregate directly over a Filter becomes
    FusedFilterAggregateOp. On the device path the predicate runs as a device-side
    mask feeding the segment reductions (no host compaction between them);
    on the host path the fused op executes as ONE acero filter+project+agg
    exec plan (Table.acero_fused_agg) so the filtered intermediate is never
    materialized — both are the analog of the reference's fused streaming
    pipeline (pipeline.rs:141-211)."""
    for i, c in enumerate(op.children):
        op.children[i] = fuse_for_device(c, cfg)
    if isinstance(op, AggregateOp):
        # splice out column-pruning Projects (pure selection, no renames or
        # compute) above or below the filter: the agg only touches its own
        # referenced columns and device staging only transfers those, while a
        # materialized prune would mint a fresh partition each query and
        # orphan the HBM residency cache
        child = op.children[0]
        if isinstance(child, ProjectOp) and _is_pure_column_selection(child.exprs):
            child = child.children[0]
        if isinstance(child, FilterOp):
            fchild = child.children[0]
            if isinstance(fchild, ProjectOp) and _is_pure_column_selection(fchild.exprs):
                fchild = fchild.children[0]
            return FusedFilterAggregateOp(fchild, child.predicate,
                                    op.aggregations, op.groupby, op.schema)
        op.children[0] = child
    return op


def _is_pure_column_selection(exprs) -> bool:
    from .expressions import Column as ColNode

    for e in exprs:
        n = e._node
        if not (isinstance(n, ColNode) and n.cname == e.name()):
            return False
    return True


def translate(plan: LogicalPlan, cfg, morsels: bool = False,
              stats=None) -> PhysicalOp:
    """Public entry: recursive translation + device-path fusion + map-chain
    fusion, so every caller (runners, explain, adaptive) sees the tree that
    actually runs. fuse_for_device runs FIRST so a filter feeding an
    aggregation folds into FusedFilterAggregateOp; fuse_map_chains then
    collapses the residual Project/Filter chains (the passes compose).

    ``stats`` (when given) receives ``compile_wall_ns`` — the fuse-compile
    share of planning, the cost the plan cache's warm path removes and
    which must therefore stay measurable (README "Plan & program cache")."""
    import time as _time

    out = _translate(plan, cfg, morsels)
    if getattr(cfg, "dynamic_batching", True):
        # before the fuse passes: a batch-declared projection must become
        # its own op (and a fusion barrier), not fold into a fused map
        out = _route_batched_udfs(out)
    out = fuse_for_device(out, cfg)
    if getattr(cfg, "expr_fusion", True):
        from .fuse import fuse_map_chains

        t0 = _time.perf_counter_ns()
        out = fuse_map_chains(out, cfg)
        if stats is not None:
            stats.bump("compile_wall_ns", _time.perf_counter_ns() - t0)
    if getattr(cfg, "use_device_kernels", False) and getattr(
            cfg, "device_residency", True):
        # LAST: the segment compiler consumes the trees the fuse passes
        # built (Aggregate-over-FusedMap), collapsing each eligible segment
        # into one HBM-resident DeviceSegmentOp (fuse/segment.py). Part of
        # the timed compile share — the plan cache's warm path skips it,
        # which is what pins warm runs at zero segment compiles.
        from .fuse import compile_plan_segments

        t0 = _time.perf_counter_ns()
        out = compile_plan_segments(out, cfg, stats)
        if stats is not None:
            stats.bump("compile_wall_ns", _time.perf_counter_ns() - t0)
    return out


def _translate(plan: LogicalPlan, cfg, morsels: bool = False) -> PhysicalOp:
    """Translate an (optimized) logical plan to a physical operator tree.

    cfg: ExecutionConfig (broadcast threshold, default partitions, etc.)
    morsels: split oversized in-memory sources into parallel morsels; set
    only under aggregate pipelines (where the two-stage decomposition turns
    extra partitions into parallel stage-1 work) and propagated through the
    transparent map ops (Project/Filter). Ops that would pay for higher
    partition counts with extra shuffles (Sort/Distinct/Join) never see it.
    """
    if isinstance(plan, InMemorySource):
        parts = _split_morsels(plan.partitions, cfg) if morsels else plan.partitions
        return InMemoryOp(parts, plan.schema)

    if isinstance(plan, ScanSource):
        return ScanOp(plan.tasks, plan.schema)

    if isinstance(plan, Project):
        return ProjectOp(_translate(plan.input, cfg, morsels), plan.exprs, plan.schema)

    if isinstance(plan, Filter):
        return FilterOp(_translate(plan.input, cfg, morsels), plan.predicate)

    if isinstance(plan, Limit):
        return LimitOp(_translate(plan.input, cfg), plan.limit)

    if isinstance(plan, Explode):
        return ExplodeOp(_translate(plan.input, cfg), plan.to_explode, plan.schema)

    if isinstance(plan, Unpivot):
        return UnpivotOp(_translate(plan.input, cfg), plan.ids, plan.values,
                         plan.variable_name, plan.value_name, plan.schema)

    if isinstance(plan, Sample):
        return SampleOp(_translate(plan.input, cfg), plan.fraction,
                        plan.with_replacement, plan.seed)

    if isinstance(plan, MonotonicallyIncreasingId):
        return MonotonicIdOp(_translate(plan.input, cfg), plan.column_name, plan.schema)

    if isinstance(plan, Write):
        return WriteOp(_translate(plan.input, cfg), plan.root_dir, plan.format,
                       plan.compression, plan.partition_cols, plan.schema)

    if isinstance(plan, Sort):
        child = _translate(plan.input, cfg)
        if child.num_partitions > 1:
            child = ShuffleOp(child, "range", child.num_partitions, plan.sort_by,
                              plan.descending, plan.nulls_first)
        return SortOp(child, plan.sort_by, plan.descending, plan.nulls_first)

    if isinstance(plan, Repartition):
        child = _translate(plan.input, cfg)
        num = plan.num if plan.num is not None else child.num_partitions
        if plan.scheme == "into":
            if num == child.num_partitions:
                return child
            return CoalesceOp(child, num)
        if plan.scheme == "hash":
            return ShuffleOp(child, "hash", num, plan.by)
        if plan.scheme == "range":
            return ShuffleOp(child, "range", num, plan.by, plan.descending)
        return ShuffleOp(child, "random", num)

    if isinstance(plan, Distinct):
        child = _translate(plan.input, cfg)
        subset = plan.subset
        out = DistinctOp(child, subset)
        if child.num_partitions > 1:
            keys = subset if subset else [col(c) for c in plan.schema.field_names()]
            out = DistinctOp(ShuffleOp(out, "hash", child.num_partitions, keys), subset)
        return out

    if isinstance(plan, Aggregate):
        return _translate_aggregate(plan, cfg)

    if isinstance(plan, Pivot):
        child = _translate(plan.input, cfg)
        return PivotOp(child, plan.groupby, plan.pivot_col, plan.value_col,
                       plan.agg_fn, plan.names, plan.schema)

    if isinstance(plan, Concat):
        l = _translate(plan.input, cfg)
        r = _translate(plan.other, cfg)
        return ConcatOp(l, r, plan.schema)

    if isinstance(plan, Join):
        return _translate_join(plan, cfg)

    raise ValueError(f"cannot translate logical node {plan.name()}")


def _translate_aggregate(plan: Aggregate, cfg) -> PhysicalOp:
    child = _translate(plan.input, cfg, morsels=True)
    nparts = child.num_partitions

    if nparts == 1:
        return AggregateOp(child, plan.aggregations, plan.groupby, plan.schema)

    include_sketch = bool(getattr(cfg, "sketch_aggregations", True))
    if not aggs_decomposable(plan.aggregations, include_sketch):
        # non-decomposable (count_distinct / skew / approx_* with the sketch
        # subsystem disabled): shuffle raw rows by key, then full agg per
        # partition
        if plan.groupby:
            shuffled = ShuffleOp(child, "hash", nparts, plan.groupby)
            return AggregateOp(shuffled, plan.aggregations, plan.groupby, plan.schema)
        cd = _global_count_distinct_plan(plan, child, nparts)
        if cd is not None:
            return cd
        gathered = GatherOp(child)
        return AggregateOp(gathered, plan.aggregations, [], plan.schema)

    stage1, stage2, final = populate_aggregation_stages(plan.aggregations)
    key_cols = [col(e.name()) for e in plan.groupby]

    p1 = AggregateOp(child, stage1, plan.groupby,
                     _stage_schema(plan.input.schema, stage1, plan.groupby))
    if plan.groupby:
        from .adapt import fdo as _fdo

        # feedback-directed fan-out: the internal exchange of a repeated
        # aggregation shape emits only as many partitions as its RECORDED
        # map-side payload needs (shrink-only; engine-chosen counts only).
        # Hash modulus stays nparts and adjacent buckets merge at reduce
        # time, so rows AND row order are byte-identical to the unresized
        # plan — only the partition count (stage-2 invocations,
        # downstream fan-in) shrinks.
        exchanged: PhysicalOp = ShuffleOp(p1, "hash", nparts, key_cols)
        resized = _fdo.agg_shuffle_fanout(plan, nparts)
        if resized:
            exchanged.reduce_to = resized
            exchanged.num_partitions = resized
        okey = _fdo.agg_observation_key(plan)
        if okey:
            exchanged.fdo_obs_key = okey
        # hierarchical exchange: fold map-side pieces headed to the same
        # destination through the stage-2 combine BEFORE they buffer
        # (intra-host combine -> inter-host all_to_all; the mesh path
        # mirrors it ahead of the ICI collective). Only when the fold is
        # schema-closed and every stage-2 kind is a known-safe merge.
        if getattr(cfg, "hierarchical_exchange_combine", True):
            from .exchange.combine import combine_spec_applicable

            if combine_spec_applicable(stage2, key_cols, p1.schema):
                exchanged.combine = (stage2, key_cols)
    else:
        exchanged = GatherOp(p1)
    p2 = AggregateOp(exchanged, stage2, key_cols,
                     _stage_schema(p1.schema, stage2, key_cols))
    final_exprs = key_cols + final
    out = ProjectOp(p2, final_exprs, plan.schema)
    # two-stage float results can drift in dtype (e.g. mean); align to plan schema
    return _cast_to(out, plan.schema)


def _global_count_distinct_plan(plan: Aggregate, child: PhysicalOp,
                                nparts: int) -> Optional[PhysicalOp]:
    """Global count_distinct without gathering raw rows: hash-shuffle rows by
    the counted VALUE (equal values co-locate), count distinct per partition,
    sum the tiny per-partition partials. Applies when every aggregation in
    the list is a count_distinct."""
    from .expressions import Expression

    specs = []
    for e in plan.aggregations:
        node = e._node
        while isinstance(node, Alias):
            node = node.child
        if not (isinstance(node, AggExpr) and node.kind == "count_distinct"):
            return None
        specs.append((e, node))
    if len(specs) != 1:
        return None  # different value columns would need different shuffles
    e, node = specs[0]
    alias = e.name()
    shuffled = ShuffleOp(child, "hash", nparts, [Expression(node.child)])
    p1 = AggregateOp(shuffled, [e], [],
                     _stage_schema(plan.input.schema, [e], []))
    gathered = GatherOp(p1)  # nparts partial counts — rows, not raw data
    p2 = AggregateOp(gathered, [col(alias).sum().alias(alias)], [],
                     _stage_schema(p1.schema, [col(alias).sum().alias(alias)], []))
    return _cast_to(p2, plan.schema)


def _stage_schema(input_schema: Schema, aggs: List[Expression], groupby: List[Expression]) -> Schema:
    from .schema import Field

    fields = []
    for e in groupby:
        f = e._node.to_field(input_schema)
        fields.append(Field(e.name(), f.dtype))
    for e in aggs:
        f = e._node.to_field(input_schema)
        fields.append(Field(e.name(), f.dtype))
    return Schema(fields)


class _CastOp(PhysicalOp):
    def __init__(self, child: PhysicalOp, schema: Schema):
        super().__init__([child], schema, child.num_partitions)

    def execute(self, inputs, ctx) -> PartStream:
        for part in inputs[0]:
            yield part.cast_to_schema(self.schema)

    def describe(self):
        return "CastToSchema"


def _cast_to(op: PhysicalOp, schema: Schema) -> PhysicalOp:
    if op.schema == schema:
        return op
    return _CastOp(op, schema)


def _translate_join(plan: Join, cfg) -> PhysicalOp:
    from .adapt import fdo as _fdo

    left = _translate(plan.left, cfg)
    right = _translate(plan.right, cfg)

    if plan.how == "cross":
        return CrossJoinOp(left, right, plan.schema, plan.suffix)

    strategy = plan.strategy
    fdo_side = None
    if strategy is None:
        # feedback-directed flip (daft_tpu/adapt/fdo.py): a side whose
        # RECORDED size sits safely under the broadcast threshold flips
        # this join on the first run of a repeated shape — no AQE
        # materialization barrier needed. Active only inside a planning
        # collector scope; declines everywhere else.
        fdo_side = _fdo.join_strategy_hint(plan)
        strategy = ("broadcast" if fdo_side is not None
                    else _choose_join_strategy(plan, cfg))
    if strategy == "broadcast" and plan.how == "outer":
        # an outer join preserves both sides; replaying the replicated side per
        # big-side partition would duplicate its unmatched rows
        strategy = "hash"

    if strategy == "broadcast":
        lsize = plan.left.approx_size_bytes()
        rsize = plan.right.approx_size_bytes()
        if fdo_side is not None:
            broadcast_left = fdo_side == "left"
        else:
            broadcast_left = _broadcast_side(plan, lsize, rsize) == "left"
        if broadcast_left:
            op = BroadcastJoinOp(right, left, plan.right_on, plan.left_on,
                                 plan.how, plan.schema, small_is_left=True,
                                 suffix=plan.suffix)
        else:
            op = BroadcastJoinOp(left, right, plan.left_on, plan.right_on,
                                 plan.how, plan.schema, small_is_left=False,
                                 suffix=plan.suffix)
        if fdo_side is not None:
            # runtime mispredict detector: the materialized small side is
            # checked against the guard; history keeps observing it so a
            # grown side reverts the decision on the next plan
            op.fdo_guard = _fdo.broadcast_guard(plan, fdo_side)
            op.fdo_obs_key = _fdo.observation_key(
                plan.left if fdo_side == "left" else plan.right)
        return op

    if strategy == "sort_merge":
        return SortMergeJoinOp(left, right, plan.left_on, plan.right_on,
                               plan.how, plan.schema, plan.suffix)

    # hash: co-partition both sides when >1 partition
    nparts = max(left.num_partitions, right.num_partitions)
    if nparts > 1:
        lshuf = ShuffleOp(left, "hash", nparts, plan.left_on)
        rshuf = ShuffleOp(right, "hash", nparts, plan.right_on)
        # FDO observation: each side's exchange records the rows/bytes
        # that actually crossed it, keyed by the side's canonical subtree
        # fingerprint — the history a future plan's broadcast flip reads
        lkey = _fdo.observation_key(plan.left)
        if lkey:
            lshuf.fdo_obs_key = lkey
        rkey = _fdo.observation_key(plan.right)
        if rkey:
            rshuf.fdo_obs_key = rkey
        # runtime join filter (sideways information passing): the left
        # exchange — drained first by HashJoinOp — builds a Bloom+min-max
        # filter from its keys; the right exchange prunes with it before
        # bucketing/spill/merge. Gated per join type: inner/semi — either
        # side prunable (we prune the one whose exchange runs second);
        # left — right side only; right/anti/outer — decline (the probe
        # side's unmatched rows are output).
        from .exchange.joinfilter import JoinFilterSlot, prunable

        # the probe side is the RIGHT exchange (drained second)
        if getattr(cfg, "runtime_join_filters", True) \
                and prunable(plan.how, probe_is_right=True):
            slot = JoinFilterSlot(plan.left_on, plan.right_on,
                                  left.schema, right.schema, plan.how)
            if slot.eligible:
                lshuf.filter_feed = slot
                rshuf.probe_filter = slot
        left, right = lshuf, rshuf
    return HashJoinOp(left, right, plan.left_on, plan.right_on, plan.how,
                      plan.schema, plan.suffix)


def _broadcast_side(plan: Join, lsize, rsize) -> str:
    """Which side to replicate. The preserved side of an outer join can't be
    broadcast (its unmatched rows must appear exactly once)."""
    if plan.how in ("left", "semi", "anti"):
        return "right"
    if plan.how == "right":
        return "left"
    # inner: smaller side
    if lsize is not None and (rsize is None or lsize <= rsize):
        return "left"
    return "right"


def _choose_join_strategy(plan: Join, cfg) -> str:
    lsize = plan.left.approx_size_bytes()
    rsize = plan.right.approx_size_bytes()
    threshold = cfg.broadcast_join_size_bytes_threshold
    if plan.how == "outer":
        return "hash"
    side = _broadcast_side(plan, lsize, rsize)
    size = lsize if side == "left" else rsize
    if size is not None and size <= threshold:
        return "broadcast"
    return "hash"
