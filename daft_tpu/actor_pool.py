"""Actor pools for stateful UDFs.

Role-equivalent to the reference's actor-pool UDF machinery
(ActorPoolProject logical/physical ops + the stateful-UDF concurrency knob,
daft/udf.py:308, logical_ops/actor_pool_project.rs): a class UDF with
`concurrency=k` gets k persistent workers, each owning ONE instance of the
class (initialized once, reused for every batch it serves) — the pattern for
`.embed()`-style model UDFs where instance construction loads weights.

Execution model: worker threads with a shared task queue. Batches are
dispatched as (index, slices) and results re-assembled in order, so output
order is deterministic regardless of which worker served which batch. Threads
(not processes) because model UDFs spend their time in jax/numpy/IO which
release the GIL; this mirrors the reference's PyRunner-side actor pool rather
than its Ray actors.

Pools are keyed by (class, init_args, concurrency) and persist across queries
— actors outlive a single plan by design. `shutdown_all()` tears them down.
"""

from __future__ import annotations

import atexit
import queue
import threading
from typing import Any, Callable, Dict, List, Optional, Tuple

from .obs.log import current_query_id, get_logger, query_context

logger = get_logger("actor_pool")

_pools: Dict[Tuple, "ActorPool"] = {}
_pools_lock = threading.Lock()

# process-wide count of worker threads that outlived their pool's shutdown
# join window (still daemon, so they die with the process — but a nonzero
# count means actor instances are pinning memory/devices past shutdown)
_leak_lock = threading.Lock()
_leaked_threads = 0


def leaked_thread_count() -> int:
    return _leaked_threads


class ActorPool:
    def __init__(self, cls: type, init_args: Optional[tuple], concurrency: int):
        self._cls = cls
        self._init_args = init_args
        self._n = max(1, concurrency)
        self._tasks: "queue.Queue" = queue.Queue()
        self._threads: List[threading.Thread] = []
        self._init_errors: List[BaseException] = []
        # no timeout: loading model weights in __init__ may legitimately take
        # minutes; workers always reach the barrier (init is wrapped)
        self._started = threading.Barrier(self._n + 1)
        for i in range(self._n):
            t = threading.Thread(target=self._worker, name=f"daft-actor-{cls.__name__}-{i}",
                                 daemon=True)
            t.start()
            self._threads.append(t)
        self._started.wait()  # all instances constructed (or failed) before first dispatch
        if self._init_errors:
            self.shutdown()  # release the workers that DID init, with their instances
            raise self._init_errors[0]

    def _worker(self) -> None:
        try:
            a, kw = self._init_args or ((), {})
            instance = self._cls(*a, **kw)
        except BaseException as e:  # noqa: BLE001
            self._init_errors.append(e)
            try:
                self._started.wait()
            except threading.BrokenBarrierError:
                pass
            return
        try:
            self._started.wait()
        except threading.BrokenBarrierError:
            return
        while True:
            item = self._tasks.get()
            if item is None:
                return
            idx, fn_args, results, errors, done, qid = item
            try:
                # the dispatching query's log context rides with the batch:
                # lines emitted by the actor stay attributed
                with query_context(qid):
                    results[idx] = instance(*fn_args)
            except BaseException as e:  # noqa: BLE001
                errors[idx] = e
            finally:
                done.release()

    def map_batches(self, batches: List[tuple]) -> List[Any]:
        """Run instance(*batch) for each batch across the pool; ordered results."""
        k = len(batches)
        results: List[Any] = [None] * k
        errors: List[Optional[BaseException]] = [None] * k
        done = threading.Semaphore(0)
        qid = current_query_id()
        for i, b in enumerate(batches):
            self._tasks.put((i, b, results, errors, done, qid))
        for _ in range(k):
            done.acquire()
        for e in errors:
            if e is not None:
                raise e
        return results

    def shutdown(self, join_timeout_s: float = 5.0) -> None:
        global _leaked_threads
        for _ in self._threads:
            self._tasks.put(None)
        leaked = 0
        for t in self._threads:
            t.join(timeout=join_timeout_s)
            if t.is_alive():
                leaked += 1
        if leaked:
            # a worker wedged mid-batch never saw its sentinel: don't block
            # shutdown forever, but say so loudly and keep the count — a
            # silent leak pins the actor instance (weights!) until exit
            with _leak_lock:
                _leaked_threads += leaked
            logger.warning("actor_pool_leak", actor=self._cls.__name__,
                           leaked=leaked, join_timeout_s=join_timeout_s)


def get_pool(cls: type, init_args: Optional[tuple], concurrency: int) -> ActorPool:
    key = (cls, repr(init_args), concurrency)
    with _pools_lock:
        pool = _pools.get(key)
        if pool is None:
            pool = ActorPool(cls, init_args, concurrency)
            _pools[key] = pool
        return pool


def pool_count() -> int:
    """Live actor pools (the health snapshot's view)."""
    with _pools_lock:
        return len(_pools)


def shutdown_all() -> None:
    with _pools_lock:
        pools = list(_pools.values())
        _pools.clear()
    for p in pools:
        p.shutdown()
    # pinned model actors (batch/actors.py) ride the same teardown paths —
    # serve shutdown, dt.shutdown(), atexit — so "engine down" always means
    # zero resident models too (lazy import: batch/ depends on this module)
    from .batch.actors import shutdown_all_models

    shutdown_all_models()


atexit.register(shutdown_all)
