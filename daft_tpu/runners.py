"""Runners: the pluggable execution backends behind DataFrame.collect().

Role-equivalent to the reference's daft/runners/runner.py:18 (Runner ABC),
pyrunner.py:117 (local bulk runner), and ray_runner.py (distributed). Here:

- NativeRunner: single-host streaming executor (host pyarrow kernels, with
  device-kernel routing per ExecutionConfig.use_device_kernels).
- MeshRunner: partitions pinned to the devices of a jax Mesh; shuffles ride
  XLA all_to_all collectives via parallel/ (multi-chip path).
"""

from __future__ import annotations

import threading
from typing import Dict, Iterator, List, Optional

from .context import get_context
from .execution import ExecutionContext, RuntimeStats, execute_plan
from .logical import LogicalPlan
from .micropartition import MicroPartition
from .schema import Schema


class PartitionSet:
    """Materialized result: an ordered list of partitions + schema
    (reference: daft/runners/partitioning.py PartitionSet)."""

    def __init__(self, schema: Schema, partitions: List[MicroPartition]):
        self.schema = schema
        self.partitions = partitions

    def __len__(self) -> int:
        return sum(len(p) for p in self.partitions)

    def num_partitions(self) -> int:
        return len(self.partitions)

    def to_micropartition(self) -> MicroPartition:
        if not self.partitions:
            return MicroPartition.empty(self.schema)
        if len(self.partitions) == 1:
            return self.partitions[0]
        return MicroPartition.concat(self.partitions)

    def to_table(self):
        return self.to_micropartition().cast_to_schema(self.schema).table()

    def size_bytes(self) -> int:
        return sum(p.size_bytes() or 0 for p in self.partitions)


class PartitionSetCache:
    """Process-wide cache of materialized results keyed by an entry id, with
    explicit refcounts (reference: PartitionSetCache, partitioning.py:307-335
    — keeps collect() results alive in the runner so later plans referencing
    the same entry reuse them instead of re-executing)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._entries: Dict[str, PartitionSet] = {}
        self._refs: Dict[str, int] = {}

    def put(self, key: str, pset: PartitionSet) -> str:
        with self._lock:
            self._entries[key] = pset
            self._refs[key] = self._refs.get(key, 0) + 1
        return key

    def get(self, key: str) -> Optional[PartitionSet]:
        with self._lock:
            return self._entries.get(key)

    def release(self, key: str) -> None:
        with self._lock:
            n = self._refs.get(key, 0) - 1
            if n <= 0:
                self._entries.pop(key, None)
                self._refs.pop(key, None)
            else:
                self._refs[key] = n

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()
            self._refs.clear()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)


_PARTITION_SET_CACHE = PartitionSetCache()


def partition_set_cache() -> PartitionSetCache:
    return _PARTITION_SET_CACHE


class _Uncacheable(Exception):
    pass


def _hasudf(e):
    from .expressions import expr_has_udf

    return expr_has_udf(e)


def plan_cache_key(plan: LogicalPlan) -> Optional[str]:
    """Structural cache key for a plan, or None when caching would be unsound:
    side effects (writes), non-determinism (seedless sampling, UDFs), or any
    attribute this walker can't prove collision-free."""
    try:
        return _plan_key(plan)
    except _Uncacheable:
        return None


_SCALARS = (str, int, float, bool, bytes, type(None))


def _file_fingerprint(path: str) -> str:
    """mtime+size fingerprint so an overwritten file invalidates cached scan
    results (the reference re-executes scans per query; we must not serve
    stale bytes). Non-stat-able paths (object stores, http) are uncacheable."""
    import os

    try:
        st = os.stat(path)
    except OSError:
        raise _Uncacheable from None
    return f"{st.st_mtime_ns}:{st.st_size}"


def _scan_task_key(t, stable: bool = False) -> str:
    from .io.pyscan import FactoryScanTask
    from .io.scan import MergedScanTask

    if isinstance(t, FactoryScanTask):
        # a Python callable's identity can't be fingerprinted; two factories
        # sharing a stat-able label must never collide in the result cache
        raise _Uncacheable
    if isinstance(t, MergedScanTask):
        # fingerprint EVERY child file: the merged task's .path is only the
        # first child, and an overwrite of any other must invalidate too
        return "+".join(_scan_task_key(c, stable=stable)
                        for c in t.children)
    # storage_options and schema are part of task identity: the same file read
    # with a different delimiter or schema_hints must not share a cache entry
    opts = sorted((k, repr(v)) for k, v in t.storage_options.items())
    sch = [(f.name, str(f.dtype)) for f in t.schema]
    # the stable variant masks the mtime/size term: it addresses the same
    # logical source across overwrites (the persist/ refresh path pairs a
    # stable address with the exact keys to find WHICH partitions moved)
    fp = "*" if stable else _file_fingerprint(t.path)
    return (f"{t.path}|{fp}|{t.format}|{t.pushdowns!r}"
            f"|{t.row_group_ids}|{t.partition_values}|{opts}|{sch}")


def _plan_key(p: LogicalPlan) -> str:
    from .expressions import Expression
    from .logical import InMemorySource, Sample, ScanSource, Write

    if isinstance(p, Write):
        raise _Uncacheable
    if isinstance(p, Sample) and getattr(p, "seed", None) is None:
        raise _Uncacheable
    if isinstance(p, InMemorySource):
        # per-object uuid assigned at source creation — unlike id(), never
        # reused after the source is GC'd (advisor: stale-hit repro)
        tok = getattr(p, "_cache_token", None)
        if tok is None:
            raise _Uncacheable
        return f"mem#{tok}"
    if isinstance(p, ScanSource):
        return "scan#" + ";".join(_scan_task_key(t) for t in p.tasks)
    items = []
    for k, v in sorted(vars(p).items()):
        # schemas are derived from children + expressions, already covered
        if k.startswith("_") or isinstance(v, (LogicalPlan, Schema)):
            continue
        if isinstance(v, Expression):
            if _hasudf(v):
                raise _Uncacheable
            items.append(f"{k}={v._node._key()!r}")
        elif isinstance(v, (list, tuple)):
            if all(isinstance(e, Expression) for e in v):
                if any(_hasudf(e) for e in v):
                    raise _Uncacheable
                items.append(f"{k}=[{','.join(repr(e._node._key()) for e in v)}]")
            elif all(isinstance(e, _SCALARS) for e in v):
                items.append(f"{k}={v!r}")
            else:
                raise _Uncacheable
        elif isinstance(v, _SCALARS):
            items.append(f"{k}={v!r}")
        else:
            raise _Uncacheable  # unknown attribute type: refuse, don't collide
    kids = ",".join(_plan_key(c) for c in p.children())
    return f"{type(p).__name__}({';'.join(items)})[{kids}]"


class Runner:
    """ABC (reference: runner.py:18)."""

    name = "abstract"

    def run(self, plan: LogicalPlan, stats: Optional[RuntimeStats] = None,
            qctx=None) -> PartitionSet:
        parts = list(self.run_iter(plan, stats=stats, qctx=qctx))
        return PartitionSet(plan.schema, parts)

    def run_iter(self, plan: LogicalPlan,
                 stats: Optional[RuntimeStats] = None,
                 qctx=None) -> Iterator[MicroPartition]:
        """AQE dispatch lives here once; backends implement _run_plain.

        The per-query mutable state — ONE absolute deadline, ONE breaker
        per kind, the MemoryLedger share — lives on a QueryContext created
        here (or handed in by the serving runtime), so AQE stages (each a
        fresh ExecutionContext) share a single time budget and a single
        trip: a dead device must not re-pay the failure threshold per
        materialized stage."""
        ctx = get_context()
        cfg = ctx.execution_config
        if qctx is None:
            from .serve.qcontext import QueryContext

            qctx = QueryContext.build(cfg, stats=stats)
        # the health snapshot tracks the latest breaker per kind (weakly:
        # a finished query's breaker reads as idle once collected)
        qctx.register_health()
        if cfg.enable_aqe:
            from .adaptive import AdaptivePlanner

            # AdaptivePlanner hands over already-optimized (sub)plans
            return AdaptivePlanner(
                lambda p: self._run_plain(p, qctx, optimized=True),
                qctx.stats, cfg=cfg).run(plan)
        return self._run_plain(plan, qctx)

    def _run_plain(self, plan: LogicalPlan, qctx,
                   optimized: bool = False) -> Iterator[MicroPartition]:
        raise NotImplementedError

    def plan_query(self, plan: LogicalPlan, optimized: bool = False,
                   stats=None):
        """FDO-informed planning, served from the process plan cache when
        possible (daft_tpu/adapt/plancache.py). Returns
        ``(optimized_plan, physical_plan, run_cfg)`` — ``run_cfg`` may
        carry a per-query history hint (e.g. streaming off). Planning
        wall (and the fuse-compile share) lands in ``stats`` as
        ``planning_wall_ns`` / ``compile_wall_ns``."""
        from .adapt.plancache import plan_query

        ctx = get_context()
        return plan_query(plan, ctx.execution_config, stats=stats,
                          optimized=optimized, runner=self.name)

    def optimize_and_translate(self, plan: LogicalPlan, optimized: bool = False,
                               stats=None):
        opt, phys, _ = self.plan_query(plan, optimized=optimized,
                                       stats=stats)
        return opt, phys


class NativeRunner(Runner):
    name = "native"

    def _run_plain(self, plan: LogicalPlan, qctx,
                   optimized: bool = False) -> Iterator[MicroPartition]:
        _, phys, run_cfg = self.plan_query(plan, optimized,
                                           stats=qctx.stats)
        exec_ctx = ExecutionContext(run_cfg, qctx=qctx)
        return execute_plan(phys, exec_ctx)


class MeshRunner(Runner):
    """Multi-chip runner: same physical plan, but shuffle/sort/agg exchanges
    execute over a jax.sharding.Mesh via parallel/mesh_exec.py."""

    name = "mesh"

    def __init__(self, mesh=None):
        self.mesh = mesh

    def _run_plain(self, plan: LogicalPlan, qctx,
                   optimized: bool = False) -> Iterator[MicroPartition]:
        _, phys, run_cfg = self.plan_query(plan, optimized,
                                           stats=qctx.stats)
        from .parallel.mesh_exec import MeshExecutionContext

        exec_ctx = MeshExecutionContext(run_cfg,
                                        mesh=self.mesh, qctx=qctx)
        return execute_plan(phys, exec_ctx)
