"""Runners: the pluggable execution backends behind DataFrame.collect().

Role-equivalent to the reference's daft/runners/runner.py:18 (Runner ABC),
pyrunner.py:117 (local bulk runner), and ray_runner.py (distributed). Here:

- NativeRunner: single-host streaming executor (host pyarrow kernels, with
  device-kernel routing per ExecutionConfig.use_device_kernels).
- MeshRunner: partitions pinned to the devices of a jax Mesh; shuffles ride
  XLA all_to_all collectives via parallel/ (multi-chip path).
"""

from __future__ import annotations

from typing import Iterator, List, Optional

from .context import get_context
from .execution import ExecutionContext, RuntimeStats, execute_plan
from .logical import LogicalPlan
from .micropartition import MicroPartition
from .optimizer import optimize
from .physical import translate
from .schema import Schema


class PartitionSet:
    """Materialized result: an ordered list of partitions + schema
    (reference: daft/runners/partitioning.py PartitionSet)."""

    def __init__(self, schema: Schema, partitions: List[MicroPartition]):
        self.schema = schema
        self.partitions = partitions

    def __len__(self) -> int:
        return sum(len(p) for p in self.partitions)

    def num_partitions(self) -> int:
        return len(self.partitions)

    def to_micropartition(self) -> MicroPartition:
        if not self.partitions:
            return MicroPartition.empty(self.schema)
        if len(self.partitions) == 1:
            return self.partitions[0]
        return MicroPartition.concat(self.partitions)

    def to_table(self):
        return self.to_micropartition().cast_to_schema(self.schema).table()

    def size_bytes(self) -> int:
        return sum(p.size_bytes() or 0 for p in self.partitions)


class Runner:
    """ABC (reference: runner.py:18)."""

    name = "abstract"

    def run(self, plan: LogicalPlan, stats: Optional[RuntimeStats] = None) -> PartitionSet:
        parts = list(self.run_iter(plan, stats=stats))
        return PartitionSet(plan.schema, parts)

    def run_iter(self, plan: LogicalPlan,
                 stats: Optional[RuntimeStats] = None) -> Iterator[MicroPartition]:
        """AQE dispatch lives here once; backends implement _run_plain."""
        ctx = get_context()
        if ctx.execution_config.enable_aqe:
            from .adaptive import AdaptivePlanner

            # AdaptivePlanner hands over already-optimized (sub)plans
            return AdaptivePlanner(
                lambda p: self._run_plain(p, stats, optimized=True), stats).run(plan)
        return self._run_plain(plan, stats)

    def _run_plain(self, plan: LogicalPlan, stats: Optional[RuntimeStats],
                   optimized: bool = False) -> Iterator[MicroPartition]:
        raise NotImplementedError

    def optimize_and_translate(self, plan: LogicalPlan, optimized: bool = False):
        ctx = get_context()
        opt = plan if optimized else optimize(plan)
        phys = translate(opt, ctx.execution_config)
        return opt, phys


class NativeRunner(Runner):
    name = "native"

    def _run_plain(self, plan: LogicalPlan, stats: Optional[RuntimeStats],
                   optimized: bool = False) -> Iterator[MicroPartition]:
        ctx = get_context()
        _, phys = self.optimize_and_translate(plan, optimized)
        exec_ctx = ExecutionContext(ctx.execution_config, stats)
        return execute_plan(phys, exec_ctx)


class MeshRunner(Runner):
    """Multi-chip runner: same physical plan, but shuffle/sort/agg exchanges
    execute over a jax.sharding.Mesh via parallel/mesh_exec.py."""

    name = "mesh"

    def __init__(self, mesh=None):
        self.mesh = mesh

    def _run_plain(self, plan: LogicalPlan, stats: Optional[RuntimeStats],
                   optimized: bool = False) -> Iterator[MicroPartition]:
        ctx = get_context()
        _, phys = self.optimize_and_translate(plan, optimized)
        from .parallel.mesh_exec import MeshExecutionContext

        exec_ctx = MeshExecutionContext(ctx.execution_config, stats, mesh=self.mesh)
        return execute_plan(phys, exec_ctx)
