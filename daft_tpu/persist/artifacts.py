# daftlint: migrated
"""Warm-start artifacts: the plan cache + FDO history as durable files.

One artifact file is one atomic snapshot of a process's planning state:
a pickled payload (header + per-binding compiled-plan blobs + history
export) followed by a crc32 footer and a magic trailer, written as
``plans-<time_ns>-<pid>.dtpa`` under ``<cache_dir>/artifacts/`` via
temp-file + ``os.replace`` — readers never see a torn write and no lock
file exists to go stale. Concurrent drivers sharing a ``cache_dir`` each
write their own file; the loader merges EVERY valid artifact newest-first
(existing keys win), and keep-last-K pruning (``cfg.persist_keep_last``)
bounds the directory.

Invalidation is entirely key-side: the payload header carries
``ARTIFACT_VERSION`` + ``plancache.CACHE_VERSION`` and the writing
process's cache generation; the entries carry the full-config cfg_key and
the exact literal/mtime-bearing bindings. A version skew, crc mismatch,
short file, or unpicklable blob reads as a cold miss (counted in
``persist_load_failures``), never an error — and in-memory bindings
(``mem#`` tokens are process-local) never persist at all.

Fault contract (mirrors the PR 13 cache stand-down): ``persist.load`` /
``persist.store`` fire first, so an armed plan for THEM degrades this
layer specifically; any OTHER armed site stands the store down silently —
chaos runs must plan and execute for real.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import time
import zlib
from typing import Optional

from ..errors import DaftCorruptionError
from ..obs.log import get_logger

__all__ = ["ARTIFACTS", "ArtifactStore", "ARTIFACT_VERSION",
           "ensure_loaded", "maybe_save", "flush"]

logger = get_logger("persist.artifacts")

# bump when the artifact payload layout changes; older files cold-miss
ARTIFACT_VERSION = 1
_MAGIC = b"DTPA"
_SUFFIX = ".dtpa"


def _artifact_dir(cfg) -> str:
    return os.path.join(os.path.abspath(cfg.cache_dir), "artifacts")


def _leg_on(cfg) -> bool:
    return (getattr(cfg, "cache_dir", None) is not None
            and getattr(cfg, "persist_artifacts", True))


class ArtifactStore:
    """Process-wide artifact-leg state: per-directory load latches, the
    dirty marker that suppresses no-op rewrites, and the counters the
    health section / querylog rollup surface."""

    def __init__(self):
        self._lock = threading.Lock()
        self._loaded: set = set()       # cache dirs already merged
        self._marker: Optional[tuple] = None
        self.artifact_entries = 0       # bindings merged at load
        self.artifact_bytes = 0         # bytes of the last write/load
        self.artifact_loads = 0         # valid files merged
        self.artifact_saves = 0
        self.load_failures = 0
        self.store_failures = 0
        self.evictions = 0              # keep-last-K prunes

    # ----------------------------------------------------------- marker
    def _current_marker(self) -> tuple:
        """Cheap fingerprint of the persistable state: a save is skipped
        while nothing was inserted/demoted/evicted and history did not
        move — query completion calls land here per query, so the no-op
        path must stay counter-reads only."""
        from ..adapt.history import HISTORY
        from ..adapt.plancache import PLAN_CACHE

        return (PLAN_CACHE.inserts, PLAN_CACHE.demotions,
                PLAN_CACHE.evictions, PLAN_CACHE.generation,
                HISTORY.mutations)

    # ------------------------------------------------------------- load
    def ensure_loaded(self, cfg, stats=None) -> None:
        """Merge every valid artifact under ``cfg.cache_dir`` into the
        live plan cache / history, once per directory per process. Never
        raises; every defect is a counted cold miss."""
        if not _leg_on(cfg):
            return
        try:
            d = _artifact_dir(cfg)
            with self._lock:
                if d in self._loaded:
                    return
            from .. import faults

            try:
                faults.check("persist.load", stats)
            except faults.InjectedFault:
                # the armed-load plan's pinned effect: this process plans
                # cold (the latch still sets — re-probing a failed store
                # every query would turn one fault into a planning tax)
                self.load_failures += 1
                if stats is not None:
                    stats.bump("persist_load_failures")
                with self._lock:
                    self._loaded.add(d)
                return
            if faults.any_armed():
                # any OTHER armed site: stand down WITHOUT latching, so a
                # later un-armed query still warm-starts
                return
            with self._lock:
                if d in self._loaded:
                    return
                self._loaded.add(d)
            self._load_dir(d, cfg, stats)
            # the just-loaded state is the on-disk state: don't rewrite it
            self._marker = self._current_marker()
        except Exception as e:
            self.load_failures += 1
            if stats is not None:
                stats.bump("persist_load_failures")
            logger.warning("persist_load_failed", error=repr(e))

    def _load_dir(self, d: str, cfg, stats) -> None:
        from ..adapt.history import HISTORY
        from ..adapt.plancache import CACHE_VERSION, PLAN_CACHE

        try:
            names = sorted((n for n in os.listdir(d)
                            if n.endswith(_SUFFIX)), reverse=True)
        except OSError:
            return  # no artifacts yet: a plain cold start
        cap = getattr(cfg, "plan_cache_bytes", 64 * 1024 * 1024)
        cur_gen = PLAN_CACHE.generation
        merged = 0
        for name in names:
            path = os.path.join(d, name)
            try:
                with open(path, "rb") as f:
                    blob = f.read()
                if len(blob) < len(_MAGIC) + 8 \
                        or not blob.endswith(_MAGIC):
                    raise DaftCorruptionError(
                        "short or unterminated artifact")
                payload = blob[:-(len(_MAGIC) + 4)]
                (want_crc,) = struct.unpack(
                    "<I", blob[-(len(_MAGIC) + 4):-len(_MAGIC)])
                if zlib.crc32(payload) & 0xFFFFFFFF != want_crc:
                    raise DaftCorruptionError("artifact crc mismatch")
                data = pickle.loads(payload)
                if data.get("version") != ARTIFACT_VERSION \
                        or data.get("cache_version") != CACHE_VERSION:
                    raise DaftCorruptionError(
                        f"artifact version skew "
                        f"({data.get('version')}/"
                        f"{data.get('cache_version')})")
            except Exception as e:
                # torn write, bit rot, stale format: THIS file cold-misses
                self.load_failures += 1
                if stats is not None:
                    stats.bump("persist_load_failures")
                logger.warning("persist_artifact_unreadable", path=path,
                               error=repr(e))
                continue
            saved_gen = data.get("generation", 0)
            entries = []
            for fp, cfg_key, blobs in data.get("entries", []):
                # the writer's generation token is process history, not
                # plan identity: rebase onto THIS process's counter so a
                # warm lookup's key matches
                if saved_gen != cur_gen:
                    cfg_key = cfg_key.replace(f"|g{saved_gen}|",
                                              f"|g{cur_gen}|")
                entries.append((fp, cfg_key, blobs))
            n = PLAN_CACHE.import_artifact(entries, cap)
            n += HISTORY.merge(data.get("history") or {})
            self.artifact_loads += 1
            self.artifact_entries += n
            self.artifact_bytes += len(blob)
            merged += n
            if stats is not None:
                stats.bump("persist_artifact_loads")
        if merged:
            logger.info("persist_warm_start", dir=d, entries=merged,
                        files=self.artifact_loads)

    # ------------------------------------------------------------- save
    def maybe_save(self, cfg, stats=None, force: bool = False) -> bool:
        """Write one artifact snapshot when the persistable state moved
        since the last write/load (``force`` skips only the dirty check,
        not the fault contract). Never raises."""
        if not _leg_on(cfg):
            return False
        try:
            marker = self._current_marker()
            if not force and marker == self._marker:
                return False
            from .. import faults

            try:
                faults.check("persist.store", stats)
            except faults.InjectedFault:
                # the query's own result is long since streamed — a store
                # fault only costs the NEXT process its warm start
                self.store_failures += 1
                if stats is not None:
                    stats.bump("persist_store_failures")
                return False
            if faults.any_armed():
                return False
            self._write(cfg, marker, stats)
            return True
        except Exception as e:
            self.store_failures += 1
            if stats is not None:
                stats.bump("persist_store_failures")
            logger.warning("persist_store_failed", error=repr(e))
            return False

    def _write(self, cfg, marker: tuple, stats) -> None:
        from ..adapt.history import HISTORY
        from ..adapt.plancache import CACHE_VERSION, PLAN_CACHE

        d = _artifact_dir(cfg)
        os.makedirs(d, exist_ok=True)
        payload = pickle.dumps({
            "version": ARTIFACT_VERSION,
            "cache_version": CACHE_VERSION,
            "generation": PLAN_CACHE.generation,
            "entries": PLAN_CACHE.export_artifact(),
            "history": HISTORY.export(),
        }, protocol=pickle.HIGHEST_PROTOCOL)
        blob = (payload
                + struct.pack("<I", zlib.crc32(payload) & 0xFFFFFFFF)
                + _MAGIC)
        # time_ns zero-padded so lexical order IS recency order; the pid
        # disambiguates concurrent drivers writing within one tick
        name = f"plans-{time.time_ns():020d}-{os.getpid()}{_SUFFIX}"
        tmp = os.path.join(d, f".{name}.tmp")
        with open(tmp, "wb") as f:
            f.write(blob)
        os.replace(tmp, os.path.join(d, name))
        self.artifact_saves += 1
        self.artifact_bytes = len(blob)
        self._marker = marker
        if stats is not None:
            stats.bump("persist_artifact_saves")
        self._prune(d, int(getattr(cfg, "persist_keep_last", 3)))

    def _prune(self, d: str, keep: int) -> None:
        """Keep the newest ``keep`` artifacts (and sweep orphaned temp
        files another writer abandoned). Races with a concurrent pruner
        are benign: the loser's unlink ENOENTs."""
        try:
            names = sorted((n for n in os.listdir(d)
                            if n.endswith(_SUFFIX)), reverse=True)
        except OSError:
            return
        for name in names[max(keep, 1):]:
            try:
                os.unlink(os.path.join(d, name))
                self.evictions += 1
            except OSError:
                pass
        for name in os.listdir(d):
            if name.endswith(".tmp"):
                path = os.path.join(d, name)
                try:
                    if time.time() - os.path.getmtime(path) > 300:
                        os.unlink(path)
                except OSError:
                    pass

    # ------------------------------------------------------------ admin
    def snapshot(self) -> dict:
        return {
            "artifact_entries": self.artifact_entries,
            "artifact_bytes": self.artifact_bytes,
            "artifact_loads": self.artifact_loads,
            "artifact_saves": self.artifact_saves,
            "load_failures": self.load_failures,
            "store_failures": self.store_failures,
            "evictions": self.evictions,
        }

    def reset(self) -> None:
        with self._lock:
            self._loaded.clear()
        self._marker = None
        self.artifact_entries = self.artifact_bytes = 0
        self.artifact_loads = self.artifact_saves = 0
        self.load_failures = self.store_failures = self.evictions = 0


ARTIFACTS = ArtifactStore()


def ensure_loaded(cfg, stats=None) -> None:
    ARTIFACTS.ensure_loaded(cfg, stats)


def maybe_save(cfg, stats=None) -> bool:
    return ARTIFACTS.maybe_save(cfg, stats)


def flush(cfg, stats=None) -> bool:
    """Shutdown-time write: force past the dirty check only when there is
    anything cached at all (an empty process must not litter artifacts)."""
    from ..adapt.history import HISTORY
    from ..adapt.plancache import PLAN_CACHE

    if not PLAN_CACHE.snapshot()["entries"] \
            and not HISTORY.snapshot()["sites"]:
        return False
    return ARTIFACTS.maybe_save(cfg, stats)
