# daftlint: migrated
"""Persistent cache store: the adapt/ caches survive process restarts.

PR 13's plan/program cache, FDO history, and sub-plan result cache are
process-level — a fleet that restarts, autoscales, or sees the same plan
shapes on every driver pays the full optimize/translate/fuse cost and
re-materializes prefixes the cluster already computed. This package makes
those three surfaces durable, behind ``cfg.cache_dir`` (default None:
everything below is inert and the in-process cold/warm contracts are
byte-for-byte unchanged):

- **warm-start artifacts** (:mod:`.artifacts`): the plan cache and FDO
  history serialize to versioned, crc-footed on-disk artifacts written on
  query completion / ``dt.shutdown()`` and loaded lazily at first
  planning — a fresh process serves warm plan-cache hits with ZERO
  optimize/translate/fuse-compile calls;
- **cluster-shared result tier** (:mod:`.resultstore`): the sub-plan
  result cache grows a spill-IPC-format disk tier addressed by scan-task
  key + chain fingerprint, served worker-to-worker through the PR 16
  ``PieceServer`` plane;
- **incremental refresh** (:mod:`.resultstore`): an overwritten source
  file recomputes only the affected partitions of a cached entry via
  lineage-style recipes instead of discarding the whole entry.

The governing discipline (PAPERS.md, reproducible pipelines): persistence
must never move bytes. Results with the store cold, absent, corrupt, or
mid-eviction are byte-identical to the store-off run — every defect,
version skew, checksum mismatch, or armed ``persist.*`` fault site reads
as a cold miss (counted), never a query failure.
"""

from __future__ import annotations

from .artifacts import ARTIFACTS, ensure_loaded, flush, maybe_save
from .resultstore import RESULT_STORE

__all__ = ["ARTIFACTS", "RESULT_STORE", "enabled", "ensure_loaded",
           "maybe_save", "flush", "snapshot", "reset"]


def enabled(cfg) -> bool:
    """Is ANY persistence leg live? Everything hangs off ``cache_dir``."""
    return getattr(cfg, "cache_dir", None) is not None


def snapshot() -> dict:
    """The validated ``dt.health()["persist"]`` section: artifact-leg and
    result-tier counters merged into one all-int dict."""
    out = ARTIFACTS.snapshot()
    rs = RESULT_STORE.snapshot()
    # shared failure counters accumulate across both legs
    for k, v in rs.items():
        out[k] = out.get(k, 0) + v if k in out else v
    return out


def reset() -> None:
    """Tests only: forget load latches and zero every counter so one
    process can exercise multiple cold/warm cycles."""
    ARTIFACTS.reset()
    RESULT_STORE.reset()
