# daftlint: migrated
"""Cluster-shared result tier: the sub-plan result cache on disk, served
worker-to-worker.

One entry is one materialized scan+map prefix, laid out under
``<root>/results/`` as a commit-point meta file plus spill-IPC partition
files:

- ``<sd>.json`` — the entry's manifest, written LAST (atomic temp +
  ``os.replace``): the exact per-task keys (mtime/size-bearing), per-file
  crc32/bytes/rows, and the chain/config parts. ``sd`` is the **stable
  digest**: sha1 over the mtime-LESS scan-task keys + the chain's
  expression keys + the float-affecting config knobs — so an exact hit
  and a refresh candidate for the same logical prefix share one address;
- ``<sd>.p<i>.arrow`` — partition ``i`` in spill-IPC format
  (``spill._write_spill_ipc``), crc-verified on every read.

Lookup semantics: meta's exact task keys match the live scan → replay
(byte-identical by the PR 13 keying discipline). Keys differ — a source
file's mtime/size moved — and ``cfg.persist_refresh`` is on → recompute
ONLY the touched partitions (``MicroPartition.from_scan_task`` + the
chain's ``map_partition`` recipe, the lineage contract of
integrity/lineage.py) and splice them in. Any read defect (missing file,
crc mismatch, torn meta) is a counted cold miss.

The worker tier reuses the same layout with single-task entries under
``<cache_dir>/w<id>/`` (one store per worker models one store per node).
The driver piggybacks each worker's hosted digests on heartbeat pongs and
attaches up to two peer addresses to eligible map tasks; a worker missing
an entry locally pulls it over the PR 16 ``PieceServer`` transport
(``("rs", sd, task_key)`` fetch keys, token-authenticated, crc-framed)
and write-throughs its own store — one worker's prefix warms the fleet.
Every failure on that path degrades to plain execution: the map task
itself is the lineage recipe.
"""

from __future__ import annotations

import hashlib
import json
import os
import threading
from typing import List, Optional, Tuple

from ..obs.log import get_logger

__all__ = ["ResultStore", "RESULT_STORE", "prefix_meta", "disk_lookup",
           "disk_store", "task_meta"]

logger = get_logger("persist.resultstore")

_META_VERSION = 1
# bounded digest list per pong: enough for real prefix reuse, small
# enough to stay heartbeat-sized
_PONG_DIGESTS = 256


def _results_dir(root: str) -> str:
    return os.path.join(root, "results")


def _sha1(s: str) -> str:
    return hashlib.sha1(s.encode("utf-8", "surrogatepass")).hexdigest()


class ResultStore:
    """Process-wide disk-tier state + counters (driver and worker alike
    run exactly one; the worker's is pointed at its per-slot root by
    ``configure``)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._root: Optional[str] = None  # worker-side configured root
        self.hits = 0
        self.misses = 0
        self.inserts = 0
        self.refreshes = 0
        self.partitions_refreshed = 0
        self.rs_evictions = 0
        self.rs_load_failures = 0
        self.rs_store_failures = 0
        self.peer_serves = 0
        self.peer_fetches = 0

    # ------------------------------------------------------ worker setup
    def configure(self, root: Optional[str]) -> None:
        with self._lock:
            self._root = root

    @property
    def root(self) -> Optional[str]:
        with self._lock:
            return self._root

    # ------------------------------------------------------------ admin
    def snapshot(self) -> dict:
        d = self.root
        if d is None:
            # driver-side: the tier roots at the session's cache_dir
            try:
                from ..context import get_context

                cd = getattr(get_context().execution_config,
                             "cache_dir", None)
                d = os.path.abspath(cd) if cd else None
            except Exception:
                d = None
        disk_entries = disk_bytes = 0
        if d is not None:
            disk_entries, disk_bytes = _disk_usage(_results_dir(d))
        return {
            "disk_entries": disk_entries,
            "disk_bytes": disk_bytes,
            "hits": self.hits,
            "misses": self.misses,
            "inserts": self.inserts,
            "refreshes": self.refreshes,
            "partitions_refreshed": self.partitions_refreshed,
            "evictions": self.rs_evictions,
            "load_failures": self.rs_load_failures,
            "store_failures": self.rs_store_failures,
            "peer_serves": self.peer_serves,
            "peer_fetches": self.peer_fetches,
        }

    def reset(self) -> None:
        with self._lock:
            self._root = None
        self.hits = self.misses = self.inserts = 0
        self.refreshes = self.partitions_refreshed = 0
        self.rs_evictions = self.rs_load_failures = 0
        self.rs_store_failures = self.peer_serves = self.peer_fetches = 0

    # ------------------------------------------------- pong / peer serve
    def pong_report(self) -> dict:
        """The heartbeat piggyback: hosted stable digests (bounded,
        newest-mtime first) + the counters the driver aggregates."""
        digests: List[str] = []
        d = self.root
        if d is not None:
            try:
                rd = _results_dir(d)
                metas = [(os.path.getmtime(os.path.join(rd, n)), n)
                         for n in os.listdir(rd) if n.endswith(".json")]
                metas.sort(reverse=True)
                digests = [n[:-5] for _, n in metas[:_PONG_DIGESTS]]
            except OSError:
                digests = []
        return {
            "digests": digests,
            "hits": self.hits,
            "misses": self.misses,
            "inserts": self.inserts,
            "peer_serves": self.peer_serves,
            "peer_fetches": self.peer_fetches,
        }

    def serve_payload(self, sd: str,
                      tk: str) -> Optional[Tuple[bytes, int]]:
        """PieceServer hook: the raw spill-IPC bytes of a hosted
        single-task entry (crc-verified against the manifest before a
        byte leaves), or None — a peer's miss is its problem, never an
        error here."""
        d = self.root
        if d is None:
            return None
        try:
            rd = _results_dir(d)
            meta = _read_meta(os.path.join(rd, sd + ".json"))
            if meta is None or meta.get("task_keys") != [tk]:
                return None
            finfo = meta["files"][0]
            path = os.path.join(rd, f"{sd}.p0.arrow")
            with open(path, "rb") as f:
                data = f.read()
            import zlib

            if zlib.crc32(data) & 0xFFFFFFFF != finfo["crc"]:
                self.rs_load_failures += 1
                return None
            self.peer_serves += 1
            return data, int(finfo.get("rows", 0))
        except Exception as e:
            self.rs_load_failures += 1
            logger.warning("persist_peer_serve_failed", sd=sd,
                           error=repr(e))
            return None


RESULT_STORE = ResultStore()


# ---------------------------------------------------------------------------
# entry IO
# ---------------------------------------------------------------------------

def _read_meta(path: str) -> Optional[dict]:
    try:
        with open(path, "r", encoding="utf-8") as f:
            meta = json.load(f)
    except (OSError, ValueError):
        return None
    if meta.get("v") != _META_VERSION:
        return None
    return meta


def _write_atomic(path: str, data: bytes) -> None:
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        f.write(data)
    os.replace(tmp, path)


def _read_part(rd: str, sd: str, i: int, finfo: dict):
    """One partition file back as an engine Table, crc-verified first —
    the spill read-back contract (spill._SpillSlot._read_file_locked)."""
    import pyarrow as pa

    from ..errors import DaftCorruptionError
    from ..integrity.checksum import crc32_file
    from ..table import Table

    path = os.path.join(rd, f"{sd}.p{i}.arrow")
    got = crc32_file(path)
    if got != finfo["crc"]:
        raise DaftCorruptionError(
            f"result-store file {path} failed its integrity check "
            f"(crc {got:#010x} != {finfo['crc']:#010x})")
    with pa.OSFile(path) as f:
        at = pa.ipc.open_file(f).read_all()
    return Table.from_arrow(at)


def _write_part(rd: str, sd: str, i: int, table) -> dict:
    from ..integrity.checksum import crc32_file
    from ..spill import _write_spill_ipc

    path = os.path.join(rd, f"{sd}.p{i}.arrow")
    tmp = f"{path}.{os.getpid()}.tmp"
    nbytes = _write_spill_ipc(tmp, [table])
    crc = crc32_file(tmp)
    os.replace(tmp, path)
    return {"crc": crc, "nbytes": nbytes, "rows": len(table)}


def _disk_usage(rd: str) -> Tuple[int, int]:
    entries = nbytes = 0
    try:
        for n in os.listdir(rd):
            if n.endswith(".json"):
                entries += 1
            if not n.endswith(".tmp"):
                try:
                    nbytes += os.path.getsize(os.path.join(rd, n))
                except OSError:
                    pass
    except OSError:
        pass
    return entries, nbytes


def _evict_over_cap(rd: str, cap_bytes: int, keep_sd: str) -> None:
    """LRU-by-meta-mtime shed down to the byte cap, never touching the
    entry just written. Unlink races with concurrent drivers ENOENT
    harmlessly."""
    _, total = _disk_usage(rd)
    if total <= cap_bytes:
        return
    try:
        metas = sorted(
            ((os.path.getmtime(os.path.join(rd, n)), n[:-5])
             for n in os.listdir(rd) if n.endswith(".json")))
    except OSError:
        return
    for _, sd in metas:
        if total <= cap_bytes:
            break
        if sd == keep_sd:
            continue
        freed = _drop_entry(rd, sd)
        if freed:
            total -= freed
            RESULT_STORE.rs_evictions += 1


def _drop_entry(rd: str, sd: str) -> int:
    freed = 0
    meta = _read_meta(os.path.join(rd, sd + ".json"))
    parts = int(meta.get("parts", 0)) if meta else 64
    try:
        freed += os.path.getsize(os.path.join(rd, sd + ".json"))
        os.unlink(os.path.join(rd, sd + ".json"))
    except OSError:
        pass
    for i in range(parts):
        path = os.path.join(rd, f"{sd}.p{i}.arrow")
        try:
            freed += os.path.getsize(path)
            os.unlink(path)
        except OSError:
            if meta is None:
                break  # unknown part count: stop at the first gap
    return freed


# ---------------------------------------------------------------------------
# driver tier: the resultcache disk hooks
# ---------------------------------------------------------------------------

def prefix_meta(chain, scan, cfg) -> Optional[dict]:
    """Address one scan+map prefix in the disk tier, or None when the
    prefix is ineligible (factory tasks, UDF chains, leg off). Raises
    nothing — callers treat None as 'memory tier only'."""
    from ..adapt.resultcache import _CFG_KEY_FIELDS, _Decline, _op_key
    from ..runners import _Uncacheable, _scan_task_key

    if getattr(cfg, "cache_dir", None) is None \
            or not getattr(cfg, "persist_result_store", True):
        return None
    try:
        exact = [_scan_task_key(t) for t in scan.tasks]
        stable = [_scan_task_key(t, stable=True) for t in scan.tasks]
        ops = "|".join(_op_key(o) for o in chain)
    except (_Uncacheable, _Decline):
        return None
    cfg_part = ",".join(f"{k}={getattr(cfg, k, None)!r}"
                        for k in _CFG_KEY_FIELDS)
    sd = _sha1(";".join(stable) + "||" + ops + "||" + cfg_part)
    return {
        "root": os.path.abspath(cfg.cache_dir),
        "sd": sd,
        "task_keys": exact,
        "n_tasks": len(scan.tasks),
        "refresh": bool(getattr(cfg, "persist_refresh", True)),
        "cap": int(getattr(cfg, "persist_result_bytes",
                           256 * 1024 * 1024)),
    }


def disk_lookup(pmeta: dict, chain, scan, ctx) -> Optional[list]:
    """The memory-miss fallthrough: exact replay, incremental refresh, or
    None (cold). Returns detached Tables (the memory tier's currency) so
    the caller can both populate ``RESULT_CACHE`` and replay."""
    from .. import faults

    stats = ctx.stats
    try:
        try:
            faults.check("persist.load", stats)
        except faults.InjectedFault:
            RESULT_STORE.rs_load_failures += 1
            stats.bump("persist_load_failures")
            return None
        rd = _results_dir(pmeta["root"])
        sd = pmeta["sd"]
        meta = _read_meta(os.path.join(rd, sd + ".json"))
        if meta is None:
            RESULT_STORE.misses += 1
            stats.bump("persist_misses")
            return None
        stored = meta.get("task_keys") or []
        live = pmeta["task_keys"]
        if stored == live:
            tables = [_read_part(rd, sd, i, meta["files"][i])
                      for i in range(int(meta["parts"]))]
            RESULT_STORE.hits += 1
            stats.bump("persist_hits")
            p = stats.profiler
            if p.armed:
                p.event("persist", kind="hit", parts=len(tables))
            return tables
        if len(stored) != len(live) or not pmeta["refresh"]:
            RESULT_STORE.misses += 1
            stats.bump("persist_misses")
            return None
        try:
            faults.check("persist.refresh", stats)
        except faults.InjectedFault:
            # the pinned degradation: a refresh fault is a FULL cold miss
            # (plain recompute re-stores the whole entry) — never a stale
            # or partially-spliced answer
            RESULT_STORE.misses += 1
            stats.bump("persist_misses")
            return None
        return _refresh(pmeta, meta, chain, scan, ctx)
    except Exception as e:
        RESULT_STORE.rs_load_failures += 1
        stats.bump("persist_load_failures")
        logger.warning("persist_result_lookup_failed", error=repr(e))
        return None


def _refresh(pmeta: dict, meta: dict, chain, scan, ctx) -> list:
    """Materialized-view maintenance: partitions whose exact task key
    moved recompute from their scan-task recipe (re-read + the chain's
    ``map_partition``s — exactly integrity/lineage's per-partition
    contract); unchanged partitions replay from disk. The spliced entry
    replaces the stale one part-file-first, manifest last."""
    from ..micropartition import MicroPartition

    rd = _results_dir(pmeta["root"])
    sd = pmeta["sd"]
    stored = meta["task_keys"]
    live = pmeta["task_keys"]
    changed = [i for i, (a, b) in enumerate(zip(stored, live)) if a != b]
    tables = []
    for i in range(int(meta["parts"])):
        if i in changed:
            mp = MicroPartition.from_scan_task(scan.tasks[i])
            for op in reversed(chain):
                mp = op.map_partition(mp, ctx)
            tables.append(mp.table())
        else:
            tables.append(_read_part(rd, sd, i, meta["files"][i]))
    files = list(meta["files"])
    for i in changed:
        files[i] = _write_part(rd, sd, i, tables[i])
    meta = dict(meta, task_keys=live, files=files)
    _write_atomic(os.path.join(rd, sd + ".json"),
                  json.dumps(meta).encode("utf-8"))
    RESULT_STORE.refreshes += 1
    RESULT_STORE.partitions_refreshed += len(changed)
    ctx.stats.bump("persist_refreshes")
    ctx.stats.bump("persist_partitions_refreshed", len(changed))
    p = ctx.stats.profiler
    if p.armed:
        p.event("persist", kind="refresh", parts=len(tables),
                recomputed=len(changed))
    logger.info("persist_refreshed", sd=sd, parts=len(tables),
                recomputed=len(changed))
    return tables


def disk_store(pmeta: dict, tables: list, nbytes: int, ctx) -> None:
    """Persist one cleanly-exhausted prefix (the ``_teeing`` commit hook).
    Declines when the partition/task 1:1 mapping broke (runtime pruning)
    — a stored entry must splice per-partition against its task list.
    Never raises."""
    from .. import faults

    stats = ctx.stats
    try:
        if len(tables) != pmeta["n_tasks"]:
            return
        if nbytes > pmeta["cap"]:
            return
        try:
            faults.check("persist.store", stats)
        except faults.InjectedFault:
            RESULT_STORE.rs_store_failures += 1
            stats.bump("persist_store_failures")
            return
        if faults.any_armed():
            return
        rd = _results_dir(pmeta["root"])
        os.makedirs(rd, exist_ok=True)
        sd = pmeta["sd"]
        files = [_write_part(rd, sd, i, t) for i, t in enumerate(tables)]
        meta = {
            "v": _META_VERSION,
            "task_keys": pmeta["task_keys"],
            "parts": len(tables),
            "files": files,
        }
        _write_atomic(os.path.join(rd, sd + ".json"),
                      json.dumps(meta).encode("utf-8"))
        RESULT_STORE.inserts += 1
        stats.bump("persist_inserts")
        _evict_over_cap(rd, pmeta["cap"], sd)
    except Exception as e:
        RESULT_STORE.rs_store_failures += 1
        stats.bump("persist_store_failures")
        logger.warning("persist_result_store_failed", error=repr(e))


# ---------------------------------------------------------------------------
# worker tier: per-task entries + peer fetch
# ---------------------------------------------------------------------------

def task_meta(op, part, cfg) -> Optional[dict]:
    """Driver-side: address ONE map task's output in the worker tier, or
    None when ineligible (loaded/unrereadable partition, non-map or
    UDF-bearing op, armed faults, leg off). The ``sd``/``tk`` pair rides
    the task envelope; the worker never re-derives keys."""
    from .. import faults

    if getattr(cfg, "cache_dir", None) is None \
            or not getattr(cfg, "persist_result_store", True):
        return None
    if faults.any_armed():
        return None
    try:
        from ..adapt.resultcache import (_CFG_KEY_FIELDS, _Decline,
                                         _op_key)
        from ..fuse.compile import FusedMapOp
        from ..integrity.lineage import unwrap_source_task
        from ..physical import FilterOp, ProjectOp
        from ..runners import _Uncacheable, _scan_task_key

        if not isinstance(op, (ProjectOp, FilterOp, FusedMapOp)):
            return None
        task = unwrap_source_task(part)
        if task is None:
            return None
        tk = _scan_task_key(task)
        stable = _scan_task_key(task, stable=True)
        okey = _op_key(op)
    except (_Uncacheable, _Decline):
        return None
    except Exception:
        return None
    cfg_part = ",".join(f"{k}={getattr(cfg, k, None)!r}"
                        for k in _CFG_KEY_FIELDS)
    return {"sd": _sha1(stable + "||" + okey + "||" + cfg_part),
            "tk": tk}


def worker_lookup(rs: dict, exec_ctx, token: str, checksum: bool):
    """Worker-side task hook: local store, then up to two peers over the
    PieceServer transport (write-through on a peer hit). Returns a loaded
    MicroPartition or None — every defect means 'execute the task', which
    IS the entry's lineage recipe. Never raises."""
    from .. import faults
    from ..micropartition import MicroPartition

    stats = exec_ctx.stats
    root = RESULT_STORE.root
    if root is None:
        return None
    try:
        faults.check("persist.load", stats)
    except faults.InjectedFault:
        RESULT_STORE.rs_load_failures += 1
        stats.bump("persist_load_failures")
        return None
    except Exception:
        return None
    if faults.any_armed():
        # a served entry would let an armed worker.task/scan.read site
        # silently never fire — chaos runs execute for real
        return None
    sd, tk = rs.get("sd"), rs.get("tk")
    if not sd or not tk:
        return None
    rd = _results_dir(root)
    try:
        meta = _read_meta(os.path.join(rd, sd + ".json"))
        if meta is not None and meta.get("task_keys") == [tk]:
            t = _read_part(rd, sd, 0, meta["files"][0])
            RESULT_STORE.hits += 1
            stats.bump("persist_hits")
            return MicroPartition.from_table(t)
    except Exception as e:
        RESULT_STORE.rs_load_failures += 1
        stats.bump("persist_load_failures")
        logger.warning("persist_worker_lookup_failed", sd=sd,
                       error=repr(e))
    table = None
    for peer in rs.get("peers", ()):
        try:
            table = _peer_fetch(peer, sd, tk, token, checksum)
        except Exception as e:
            logger.warning("persist_peer_fetch_failed", sd=sd,
                           peer=peer[0] if peer else None, error=repr(e))
            table = None
        if table is not None:
            RESULT_STORE.peer_fetches += 1
            stats.bump("persist_peer_fetches")
            try:
                worker_store(rs, MicroPartition.from_table(table),
                             exec_ctx)
            except Exception as e:
                # write-through is best-effort; the fetched table serves
                logger.warning("persist_write_through_failed", sd=sd,
                               error=repr(e))
            return MicroPartition.from_table(table)
    RESULT_STORE.misses += 1
    stats.bump("persist_misses")
    return None


def _peer_fetch(peer, sd: str, tk: str, token: str, checksum: bool):
    """One fetch round-trip: dial, ``("rs", sd, tk)`` key, parse the raw
    spill-IPC payload. The transport frames carry their own crc; the
    serving side verified its manifest crc before the bytes left."""
    import pyarrow as pa

    from ..dist.peerplane import FETCH_TIMEOUT_S
    from ..dist.transport import dial, recv_msg, send_msg
    from ..table import Table

    _wid, host, port = peer
    conn = dial(host, int(port), timeout=FETCH_TIMEOUT_S)
    try:
        send_msg(conn, {"type": "fetch", "token": token,
                        "key": ("rs", sd, tk)}, checksum=checksum)
        reply = recv_msg(conn)
        if not reply.get("found"):
            return None
        at = pa.ipc.open_file(
            pa.BufferReader(reply["payload"])).read_all()
        return Table.from_arrow(at)
    finally:
        try:
            conn.close()
        except OSError:
            pass


def worker_store(rs: dict, out, exec_ctx) -> None:
    """Write-through one executed task's output as a single-part entry.
    Never raises; a store defect only costs the fleet a warm read."""
    from .. import faults

    stats = exec_ctx.stats
    try:
        root = RESULT_STORE.root
        if root is None or out is None or not out.is_loaded():
            return
        try:
            faults.check("persist.store", stats)
        except faults.InjectedFault:
            RESULT_STORE.rs_store_failures += 1
            stats.bump("persist_store_failures")
            return
        if faults.any_armed():
            return
        sd = rs["sd"]
        rd = _results_dir(root)
        os.makedirs(rd, exist_ok=True)
        if os.path.exists(os.path.join(rd, sd + ".json")):
            return  # deterministic output: first writer wins
        finfo = _write_part(rd, sd, 0, out.table())
        meta = {"v": _META_VERSION, "task_keys": [rs["tk"]],
                "parts": 1, "files": [finfo]}
        _write_atomic(os.path.join(rd, sd + ".json"),
                      json.dumps(meta).encode("utf-8"))
        RESULT_STORE.inserts += 1
        stats.bump("persist_inserts")
    except Exception as e:
        RESULT_STORE.rs_store_failures += 1
        stats.bump("persist_store_failures")
        logger.warning("persist_worker_store_failed", error=repr(e))
