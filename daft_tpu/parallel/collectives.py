"""ICI collective kernels: the all_to_all exchange behind every shuffle.

Role-equivalent to the reference's shuffle data plane (Ray object-store
transfer of fanout outputs, daft/execution/physical_plan.py:1365-1413;
FanoutHash/FanoutRange + ReduceMerge, daft/execution/execution_step.py:834-985)
— redesigned for TPU: each device scatters its rows into per-destination send
buffers and ONE `jax.lax.all_to_all` moves every (src, dst) slab over ICI
simultaneously. No host round-trip for the payload.

XLA's all_to_all needs equal static split sizes, so the exchange is
capacity-padded: rows are scattered to `[n_dev, capacity]` send slabs with a
validity mask; capacity is negotiated host-side from exact bucket counts
(`exchange_capacity`), rounded to a power of two so each distinct capacity
compiles once.

Bucket assignment (the control plane) is computed on host — hashing via
kernels/host_hash (works for every dtype incl. strings) or range boundaries —
while the data plane ships only device-representable columns. This mirrors the
reference's split of planner-side fanout logic vs object-store movement.
"""

from __future__ import annotations

import functools
from typing import Dict, Sequence, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MIN_CAPACITY = 128


def _shard_map(body, mesh: Mesh, in_specs, out_specs):
    """Version-compat shard_map: `jax.shard_map(..., check_vma=)` on new
    jax, `jax.experimental.shard_map.shard_map(..., check_rep=)` on 0.4.x —
    one accessor so every exchange kernel builds on either."""
    try:
        return jax.shard_map(body, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=False)
    except (AttributeError, TypeError):
        from jax.experimental.shard_map import shard_map as _sm

        return _sm(body, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                   check_rep=False)


def _scatter_to_slabs(bucket, valid, cols, n: int, capacity: int):
    """Per-shard send-side scatter: route each row to its destination slab.

    Rows are stably sorted by destination; a row's slab position is its rank
    within its bucket. Invalid/padding rows go to a virtual overflow bucket n
    and out-of-capacity rows scatter out of bounds — both dropped (mode="drop").
    Returns (send_valid [n, capacity], [slab [n, capacity, *trailing] per col]).
    """
    r = bucket.shape[0]
    b = jnp.where(valid, bucket, jnp.int32(n))
    order = jnp.argsort(b, stable=True)
    sb = b[order]
    counts = jax.ops.segment_sum(jnp.ones(r, jnp.int32), sb, num_segments=n + 1)
    starts = jnp.concatenate([jnp.zeros(1, jnp.int32),
                              jnp.cumsum(counts)[:-1].astype(jnp.int32)])
    pos = jnp.arange(r, dtype=jnp.int32) - starts[sb]
    keep = (sb < n) & (pos < capacity)
    pos = jnp.where(keep, pos, capacity)
    send_valid = jnp.zeros((n, capacity), dtype=bool).at[sb, pos].set(keep, mode="drop")
    slabs = []
    for c in cols:
        slab = jnp.zeros((n, capacity) + c.shape[1:], c.dtype)
        slabs.append(slab.at[sb, pos].set(c[order], mode="drop"))
    return send_valid, slabs


def exchange_capacity(buckets: Sequence[np.ndarray], valids: Sequence[np.ndarray],
                      n_dev: int) -> int:
    """Max rows any (src shard, dst shard) pair exchanges, rounded up to a power
    of two (>= MIN_CAPACITY) so capacities bucket into few compilations."""
    worst = 0
    for b, v in zip(buckets, valids):
        bb = b[v] if v is not None else b
        if bb.size:
            worst = max(worst, int(np.bincount(bb, minlength=n_dev).max()))
    cap = MIN_CAPACITY
    while cap < worst:
        cap <<= 1
    return cap


_EXCHANGE_CACHE: Dict = {}


def build_exchange(mesh: Mesh, capacity: int, col_dtypes: Tuple,
                   col_trailing: Tuple[Tuple[int, ...], ...]):
    """Build (cached) the jitted shard_map exchange for this mesh/capacity/column
    signature.

    Returned fn: (bucket [n,R] i32, valid [n,R] bool, *cols [n,R,*trailing])
      -> (recv_valid [n, n, capacity] bool, *recv_cols [n, n, capacity, *trailing])
    where recv[d, s] holds the rows device s sent to device d (mask-compacted
    later on host or consumed masked on device).
    """
    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    key = (mesh, capacity, tuple(str(d) for d in col_dtypes), col_trailing)
    if key in _EXCHANGE_CACHE:
        return _EXCHANGE_CACHE[key]

    def body(bucket, valid, *cols):
        # per-shard views: [1, R, ...] -> [R, ...]
        bucket = bucket[0]
        valid = valid[0]
        cols = tuple(c[0] for c in cols)
        send_valid, outs = _scatter_to_slabs(bucket, valid, cols, n, capacity)
        recv_valid = lax.all_to_all(send_valid, axis, split_axis=0, concat_axis=0)
        recv = [lax.all_to_all(s, axis, split_axis=0, concat_axis=0) for s in outs]
        return (recv_valid[None], *[x[None] for x in recv])

    spec2 = P(axis, None)
    spec3 = P(axis, None, None)
    in_specs = (spec2, spec2) + tuple(
        P(axis, *([None] * (1 + len(t)))) for t in col_trailing)
    out_specs = (spec3,) + tuple(
        P(axis, *([None] * (2 + len(t)))) for t in col_trailing)
    fn = jax.jit(_shard_map(body, mesh, in_specs, out_specs))
    _EXCHANGE_CACHE[key] = fn
    return fn


def shard_to_mesh(arr: np.ndarray, mesh: Mesh) -> jax.Array:
    """Place a [n_dev, ...] host array so row i lives on mesh device i."""
    axis = mesh.axis_names[0]
    spec = P(axis, *([None] * (arr.ndim - 1)))
    return jax.device_put(arr, NamedSharding(mesh, spec))


# ---------------------------------------------------------------------------
# Fused exchange + segment-aggregate (the stage1 -> shuffle -> stage2 pipeline
# of a distributed groupby as ONE compiled program; reference semantics:
# populate_aggregation_stages, src/daft-plan/src/physical_planner/translate.rs:761)
# ---------------------------------------------------------------------------

_GROUPED_CACHE: Dict = {}


def build_exchange_groupby_sum(mesh: Mesh, capacity: int, num_segments: int):
    """Jitted: hash-exchange (codes, values) then per-device masked segment-sum.

    fn(bucket [n,R] i32, valid [n,R] bool, codes [n,R] i32, values [n,R] f)
      -> (sums [n, num_segments] f, counts [n, num_segments] i32)
    `codes` are global group codes; `bucket` must equal `codes % n_dev` (so a
    group's rows all land on one device). Device d owns segments with
    code % n == d; its `sums[d]` row is authoritative for those.
    """
    axis = mesh.axis_names[0]
    n = mesh.shape[axis]
    key = (mesh, capacity, num_segments)
    if key in _GROUPED_CACHE:
        return _GROUPED_CACHE[key]

    def body(bucket, valid, codes, values):
        bucket, valid = bucket[0], valid[0]
        codes, values = codes[0], values[0]
        sv, (sc, sx) = _scatter_to_slabs(bucket, valid, (codes, values), n, capacity)
        rv = lax.all_to_all(sv, axis, split_axis=0, concat_axis=0).reshape(-1)
        rc = lax.all_to_all(sc, axis, split_axis=0, concat_axis=0).reshape(-1)
        rx = lax.all_to_all(sx, axis, split_axis=0, concat_axis=0).reshape(-1)
        contrib = jnp.where(rv, rx, jnp.zeros_like(rx))
        sums = jax.ops.segment_sum(contrib, jnp.where(rv, rc, num_segments),
                                   num_segments=num_segments + 1)[:num_segments]
        cnts = jax.ops.segment_sum(rv.astype(jnp.int32),
                                   jnp.where(rv, rc, num_segments),
                                   num_segments=num_segments + 1)[:num_segments]
        return sums[None], cnts[None]

    spec2 = P(axis, None)
    fn = jax.jit(_shard_map(body, mesh, (spec2, spec2, spec2, spec2),
                            (spec2, spec2)))
    _GROUPED_CACHE[key] = fn
    return fn


# ---------------------------------------------------------------------------
# Sketch register merge (the global stage-2 of an approximate aggregation as
# ONE collective: per-device HLL register rows all_gather over ICI and merge
# with an elementwise max — reference semantics: the hyperloglog merge stage
# of translate.rs:761's sketch decomposition, mapped onto the mesh the way
# DrJAX maps MapReduce merge primitives onto jax meshes)
# ---------------------------------------------------------------------------

_REGISTER_MERGE_CACHE: Dict = {}


def build_register_allmerge(mesh: Mesh, m: int):
    """Build (cached) the jitted shard_map register merge for this mesh and
    register width.

    Returned fn: (regs [n_dev, m] uint8, one sketch row per device)
      -> merged [n_dev, m] uint8 where EVERY row holds the elementwise max
    (fully replicated result, like the host-side gather it replaces).
    """
    axis = mesh.axis_names[0]
    key = (mesh, m)
    if key in _REGISTER_MERGE_CACHE:
        return _REGISTER_MERGE_CACHE[key]

    def body(regs):
        r = regs[0].astype(jnp.int32)
        g = lax.all_gather(r, axis)  # [n_dev, m]
        return jnp.max(g, axis=0).astype(jnp.uint8)[None]

    spec = P(axis, None)
    fn = jax.jit(_shard_map(body, mesh, spec, spec))
    _REGISTER_MERGE_CACHE[key] = fn
    return fn
