"""Multi-host runtime: the DCN story for scaling past one process.

Role-equivalent to the reference's RayRunner control plane
(daft/runners/ray_runner.py:504-685 — driver dispatch across nodes) —
redesigned for TPU pods: jax's distributed runtime connects processes over
DCN (one process per host, each owning its local chips), a single global
`jax.sharding.Mesh` spans every chip, and the SAME collective exchange
(collectives.build_exchange) moves shuffle payloads — XLA routes
intra-slice traffic over ICI and cross-slice traffic over DCN, no NCCL/MPI
and no object store.

Topology contract (mirrors the single-process mesh runner):
- partition i lives on global device i; a process stages shards only for
  its ADDRESSABLE devices (jax.make_array_from_single_device_arrays
  assembles the global array from per-process locals);
- the control plane (bucket assignment, capacity negotiation) runs
  identically on every process from the same host-side inputs, so no extra
  coordination round is needed beyond the collective itself.

Bootstrap: call `init_distributed()` on every process (or set
DAFT_TPU_COORDINATOR / DAFT_TPU_NUM_PROCESSES / DAFT_TPU_PROCESS_ID and it
is picked up automatically), then build `global_mesh()` and hand it to
MeshRunner. On TPU pods jax infers everything from the TPU environment, so
`init_distributed()` with no arguments is enough.
"""

from __future__ import annotations

import os
from typing import Optional

import numpy as np

import jax

_INITIALIZED = [False]
_BOOTSTRAP_FAILED = [False]
# (coordinator, num_processes, process_id) of the connected cluster — the
# dist/peer.py host-side allgather plane derives its rendezvous from this
# when the collective backend cannot move bytes between processes
_CLUSTER = [None]


def cluster_info():
    """(coordinator, num_processes, process_id) once init_distributed
    connected this process, else None."""
    return _CLUSTER[0]


def init_distributed(coordinator: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None) -> bool:
    """Connect this process to the jax distributed runtime (idempotent).

    Arguments default from DAFT_TPU_COORDINATOR / DAFT_TPU_NUM_PROCESSES /
    DAFT_TPU_PROCESS_ID; on TPU pods all three may be omitted entirely
    (jax reads the TPU topology). Returns True when the distributed runtime
    is (now) initialized, False when no coordinator is configured."""
    if _INITIALIZED[0]:
        return True
    coordinator = coordinator or os.environ.get("DAFT_TPU_COORDINATOR")
    num_processes = num_processes if num_processes is not None else (
        int(os.environ["DAFT_TPU_NUM_PROCESSES"])
        if "DAFT_TPU_NUM_PROCESSES" in os.environ else None)
    process_id = process_id if process_id is not None else (
        int(os.environ["DAFT_TPU_PROCESS_ID"])
        if "DAFT_TPU_PROCESS_ID" in os.environ else None)
    if coordinator is None and num_processes is None:
        # zero-config pod bootstrap: jax infers coordinator/topology from the
        # TPU environment. A failed attempt is WARNED and cached — silently
        # degrading a pod to independent single-process meshes (or re-blocking
        # on the coordinator connect timeout every call) would be worse.
        if _BOOTSTRAP_FAILED[0]:
            return False
        try:
            jax.distributed.initialize()
        except Exception as e:
            from ..obs.log import get_logger

            _BOOTSTRAP_FAILED[0] = True
            get_logger("multihost").warning(
                "distributed_bootstrap_failed", error=repr(e),
                note="proceeding single-process — pass coordinator/"
                     "num_processes/process_id explicitly for multi-host "
                     "execution")
            return False
        _INITIALIZED[0] = True
        _CLUSTER[0] = (None, None, None)
        return True
    jax.distributed.initialize(coordinator_address=coordinator,
                               num_processes=num_processes,
                               process_id=process_id)
    _INITIALIZED[0] = True
    _CLUSTER[0] = (coordinator, num_processes, process_id)
    return True


def global_mesh(axis: str = "parts"):
    """A 1-D mesh over every device of every connected process."""
    return jax.sharding.Mesh(np.array(jax.devices()), (axis,))


def process_local_slots(mesh) -> list:
    """Global mesh slot indices whose device is addressable from this
    process — the partitions this process is responsible for staging."""
    devs = list(mesh.devices.flat)
    local = set(jax.local_devices())
    return [i for i, d in enumerate(devs) if d in local]
