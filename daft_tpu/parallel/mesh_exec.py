"""Mesh execution context: partition shuffles ride ICI collectives.

Role-equivalent to the reference's RayRunner data plane
(daft/runners/ray_runner.py:504-685 — dispatch loop + object-store transfer).
Redesign for TPU: the fanout+reduce pair of a shuffle becomes ONE all_to_all
collective (collectives.build_exchange) over a `jax.sharding.Mesh`. Host keeps
the control plane: bucket assignment (host hash kernels work for every dtype
incl. strings; range boundaries sampled host-side like the reference's
ReduceToQuantiles, execution_step.py:878), capacity negotiation, and
re-chunking partitions onto the mesh axis.

Generality (round-3):
- hash, random AND range schemes ship their payload over ICI (range buckets
  come from the same aligned-boundary ranking the host path uses, so a
  device range-shuffle + per-device sort is a global sort);
- any fanout `num` works: num < n_devices leaves trailing devices idle,
  num > n_devices packs bucket b onto device b % n and ships the bucket id
  as an extra lane so receivers split their slab;
- staging is per-device: each source shard is device_put straight onto its
  mesh device and assembled with make_array_from_single_device_arrays — the
  host never materializes the old dense [n_devices, R] global matrix.

STRING columns ride the exchange as int32 codes against a GLOBAL sorted
dictionary (r5): every process contributes its local distinct values, the
dictionaries allgather as one packed byte buffer over the jax multihost
runtime (the DCN control channel), and every process merges them into the
same sorted global dictionary — codes are then exchange-able ints and
receivers decode (or keep the codes resident for downstream device string
ops, which expect exactly this sorted-dictionary shape). High-cardinality
columns (dictionary above _STRING_DICT_CAP values / _STRING_DICT_BYTES_CAP
bytes globally) decline to the host shuffle — past that point shipping raw
bytes beats syncing dictionaries. Columns that are neither device dtypes
nor strings (lists, python objects) still force the host path — the same
Native-vs-Python storage split the reference keeps (SURVEY.md §7 step 1).
"""
# daftlint: migrated

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from ..execution import DeviceHealth, ExecutionContext, RuntimeStats
from ..kernels.device import DeviceColumn, is_device_dtype, size_bucket, stage_np, unstage
from ..micropartition import MicroPartition
from .collectives import build_exchange, exchange_capacity


import functools

# Global-dictionary caps for string exchange columns: above these the
# dictionary sync would rival shipping the raw bytes, so the host shuffle
# takes over (both sides of every process agree — the caps evaluate on
# allgathered totals).
_STRING_DICT_CAP = 1 << 18
_STRING_DICT_BYTES_CAP = 16 << 20


def _gather_global_dictionaries(local_dicts, multiproc: bool):
    """One sorted GLOBAL dictionary (pa.Array, large_string) per string
    column, or None when a cap trips. Single-process: sort the local
    distincts. Multi-process: pack every column's distinct values into one
    byte buffer + length/count arrays, allgather (2 size-agreement rounds +
    3 data rounds over the jax multihost runtime), and merge identically on
    every process — UTF-8 byte order equals code-point order, so python
    sorted() and pyarrow's binary sort agree."""
    import pyarrow as pa

    if not multiproc:
        import pyarrow.compute as pc

        out = []
        total_vals = 0
        total_bytes = 0
        for d in local_dicts:
            srt = d.take(pc.sort_indices(d)) if len(d) else d
            total_vals += len(srt)
            # value bytes only — the same UNIT the multi-process branch
            # sums (encoded payload). Note the multiproc branch sums
            # pre-merge per-process distincts, so a value present on all P
            # processes counts P times there: near the caps a cluster can
            # decline where one host proceeds (conservative, never unsound)
            total_bytes += int(pc.binary_length(srt.cast(pa.large_binary()))
                               .cast(pa.int64()).sum().as_py() or 0) \
                if len(srt) else 0
            out.append(srt)
        if total_vals > _STRING_DICT_CAP or total_bytes > _STRING_DICT_BYTES_CAP:
            return None
        return out

    from jax.experimental import multihost_utils

    enc: List[bytes] = []
    counts = []
    for d in local_dicts:
        vals = d.to_pylist()
        counts.append(len(vals))
        enc.extend(v.encode("utf-8") for v in vals)
    lens = np.array([len(b) for b in enc], dtype=np.int64)
    buf = (np.frombuffer(b"".join(enc), dtype=np.uint8)
           if enc else np.zeros(0, np.uint8))
    header = np.array([len(buf), len(lens)], dtype=np.int64)
    sizes = np.asarray(multihost_utils.process_allgather(header))  # [P, 2]
    if (int(sizes[:, 1].sum()) > _STRING_DICT_CAP
            or int(sizes[:, 0].sum()) > _STRING_DICT_BYTES_CAP):
        return None  # agreed on every process: sizes are global
    maxb = max(int(sizes[:, 0].max()), 1)
    maxn = max(int(sizes[:, 1].max()), 1)
    pb = np.zeros(maxb, np.uint8)
    pb[:len(buf)] = buf
    pl = np.full(maxn, -1, np.int64)
    pl[:len(lens)] = lens
    gb = np.asarray(multihost_utils.process_allgather(pb))
    gl = np.asarray(multihost_utils.process_allgather(pl))
    gc = np.asarray(multihost_utils.process_allgather(
        np.array(counts, dtype=np.int64)))
    ncols = len(local_dicts)
    per_col = [set() for _ in range(ncols)]
    for p in range(gb.shape[0]):
        pos = 0
        item = 0
        pbuf = gb[p].tobytes()
        for cidx in range(ncols):
            for _ in range(int(gc[p, cidx])):
                ln = int(gl[p, item])
                item += 1
                per_col[cidx].add(pbuf[pos:pos + ln].decode("utf-8"))
                pos += ln
    return [pa.array(sorted(s), type=pa.large_string()) for s in per_col]


def exchangeable_dtype(dt) -> bool:
    """Dtypes the device exchange can ship: native device dtypes, plus
    strings (as codes against a global sorted dictionary) — the same rule
    as per-partition staging, defined once."""
    from ..kernels.device import stageable_dtype

    return stageable_dtype(dt)


def _stage_global_codes(series, global_dict, r: int):
    """(vals int32 [r], valid bool [r]) for a string column as codes into
    the GLOBAL sorted dictionary (every value is present by construction —
    the dictionary is the union of all contributions)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    arr = series.to_arrow()
    if isinstance(arr, pa.ChunkedArray):
        arr = arr.combine_chunks()
    codes = pc.index_in(arr.cast(pa.large_string()), value_set=global_dict)
    vals = np.zeros(r, dtype=np.int32)
    valid = np.zeros(r, dtype=bool)
    n = len(arr)
    vals[:n] = np.asarray(pc.fill_null(codes, 0), dtype=np.int32)
    valid[:n] = np.asarray(pc.is_valid(codes), dtype=bool)
    return vals, valid


@functools.partial(jax.jit, static_argnums=(3,))
def _pack_slab(vals, nulls, sel, out_rows: int):
    """Pack a received slab's selected rows to the front (static shapes):
    returns (values [out_rows, *trailing], null_validity [out_rows]) in the
    DeviceColumn packed-prefix layout. Runs on whatever device holds `vals`."""
    import jax.numpy as jnp

    order = jnp.argsort(~sel, stable=True)
    pv = jnp.take(vals, order, axis=0)
    pn = nulls[order] & sel[order]
    r = pv.shape[0]
    if out_rows <= r:
        return pv[:out_rows], pn[:out_rows]
    pad = [(0, out_rows - r)] + [(0, 0)] * (pv.ndim - 1)
    return jnp.pad(pv, pad), jnp.pad(pn, (0, out_rows - r))


def default_mesh(n: Optional[int] = None):
    """A 1-D mesh over the first n (default: all) local devices, axis 'parts'."""
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return jax.sharding.Mesh(np.array(devs), ("parts",))


class MeshExecutionContext(ExecutionContext):
    """ExecutionContext whose shuffles use the device exchange when eligible."""

    def __init__(self, cfg, stats: Optional[RuntimeStats] = None, mesh=None,
                 deadline: Optional[float] = None, device_health=None,
                 collective_health=None, qctx=None):
        super().__init__(cfg, stats, deadline=deadline,
                         device_health=device_health, qctx=qctx)
        self.mesh = mesh if mesh is not None else default_mesh()
        # mesh collectives get the same circuit-breaker treatment as device
        # kernels: K consecutive exchange failures trip it and every later
        # shuffle goes straight to the host path until the cooldown probe
        # proves the link healthy again. The QueryContext carries one
        # instance per QUERY so AQE stages share trip/cooldown state (same
        # contract as device_health).
        self.collective_health = (collective_health
                                  or self.qctx.collective_health
                                  or DeviceHealth(
                                      cfg.device_breaker_threshold,
                                      cfg.device_breaker_cooldown_s,
                                      kind="collective"))

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    @property
    def _multiproc(self) -> bool:
        me = jax.process_index()
        return any(d.process_index != me for d in self.mesh.devices.flat)

    def scan_owner(self, idx: int) -> Optional[int]:
        """Owner process for scan task `idx` in multi-process mode — each
        host materializes (and reads) only its share (reference: per-node
        scan dispatch, ray_runner.py:504-685). None single-process."""
        if not self._multiproc:
            return None
        return idx % jax.process_count()

    def foreign_owned(self, part: MicroPartition) -> bool:
        return (part.owner_process is not None
                and self._multiproc
                and part.owner_process != jax.process_index())

    def prepare_broadcast(self, part: MicroPartition, on_exprs,
                          how: str = "inner") -> MicroPartition:
        """Replicate a broadcast-join build side's join keys into every mesh
        device's HBM with ONE fully-replicated device_put (an ICI broadcast),
        so each device probes its local replica instead of pulling the build
        keys over the link per partition (reference role: broadcast_join's
        small-side replication, daft/execution/physical_plan.py:374)."""
        if (self.cfg.use_device_kernels and self.n_devices > 1
                and how in ("inner", "left", "semi", "anti")  # eval_join's gate
                and on_exprs and len(on_exprs) == 1
                and (part.num_rows_or_none() or 0) > 0):
            try:
                from ..kernels.device_join import replicate_join_key

                if replicate_join_key(part, on_exprs[0], self.mesh):
                    self.stats.bump("broadcast_replications")
            except Exception:
                pass  # host path handles the join; replication is a fast path
        return part

    def _shard_onto_devices(self, shards: List[jax.Array], trailing, r: int):
        """Assemble n single-device [1, r, *trailing] buffers into one global
        [n, r, *trailing] array laid out one-row-per-device — per-device
        staging with no host-side global matrix."""
        n = self.n_devices
        axis = self.mesh.axis_names[0]
        shape = (n, r) + tuple(trailing)
        sharding = NamedSharding(self.mesh, P(axis, *([None] * (len(shape) - 1))))
        return jax.make_array_from_single_device_arrays(shape, sharding, shards)

    def try_device_shuffle(self, parts: List[MicroPartition], by, num: int,
                           scheme: str, descending=None, nulls_first=None,
                           boundaries=None,
                           combine=None) -> Optional[List[MicroPartition]]:
        """All-to-all shuffle over the mesh; None if ineligible (unsupported
        scheme, non-device payload dtype, empty input, missing boundaries),
        if the collective breaker is open, or if the exchange itself fails
        (the failure is recorded against the breaker and the caller's host
        shuffle path takes over).

        Multi-process caveat: a REAL mid-collective failure on one process
        can leave peers blocked in the exchange — same exposure as before
        this catch existed (the process previously crashed outright);
        injected faults fire identically on every process (the registry is
        armed SPMD) so test fallbacks stay collectively consistent."""
        from .. import faults

        if not self.collective_health.allow(self.stats):
            self.stats.bump("degraded_shuffles")
            return self._try_transport_shuffle(parts, by, num, scheme,
                                               descending, nulls_first,
                                               boundaries)
        try:
            faults.check("collective.exchange", self.stats)
            # the whole mesh exchange (staging + all_to_all + gather-back)
            # is one phase on the profile timeline
            with self.stats.profiler.span("collective.exchange",
                                          kind="phase"):
                out = self._device_shuffle_impl(parts, by, num, scheme,
                                                descending, nulls_first,
                                                boundaries, combine)
        except Exception:
            self.collective_health.record_failure(self.stats)
            # multi-process clusters whose collective backend cannot move
            # bytes between processes (the jaxlib CPU gap) still have the
            # dist/ peer transport as a data plane; single-process meshes
            # fall to the plain host shuffle as before
            return self._try_transport_shuffle(parts, by, num, scheme,
                                               descending, nulls_first,
                                               boundaries)
        if out is None:
            self.collective_health.release_probe()
        else:
            self.collective_health.record_success(self.stats)
        return out

    def _try_transport_shuffle(self, parts, by, num, scheme, descending,
                               nulls_first, boundaries):
        """Never raises: None (host path takes over) when the transport
        cannot serve or itself fails."""
        if not self._multiproc:
            return None
        try:
            return self._transport_shuffle(parts, by, num, scheme,
                                           descending, nulls_first,
                                           boundaries)
        except Exception as e:
            from ..obs.log import get_logger

            get_logger("mesh").warning("transport_shuffle_failed",
                                       error=repr(e))
            return None

    def _transport_shuffle(self, parts, by, num, scheme, descending,
                           nulls_first, boundaries):
        """Cross-process exchange over the dist/ peer allgather plane: each
        process materializes only the partitions it OWNS (per-host scan
        locality holds), allgathers the pickled contributions, and every
        process reconstitutes the full input and buckets it identically —
        the same SPMD reconvergence contract as the collective exchange's
        post-all_to_all allgather. Returns None when no peer plane exists
        or the scheme cannot be served."""
        import pickle

        from ..dist.peer import get_peer_group

        if scheme not in ("hash", "random", "range"):
            return None
        if scheme == "range" and boundaries is None:
            return None
        peer = get_peer_group()
        if peer is None:
            return None
        nproc = jax.process_count()
        my_proc = jax.process_index()
        # contribution ownership by part index — identical rule to
        # _device_shuffle_impl, so in-memory SPMD-duplicated inputs are
        # contributed exactly once and foreign scan partitions stay unread
        local = []
        sent_rows = sent_bytes = 0
        for i, p in enumerate(parts):
            owner = (p.owner_process if p.owner_process is not None
                     else i % nproc)
            if owner == my_proc:
                t = p.table()
                local.append((i, t))
                sent_rows += len(t)
                sent_bytes += t.size_bytes()
        datas = peer.allgather(
            pickle.dumps(local, protocol=pickle.HIGHEST_PROTOCOL))
        full = {}
        for d in datas:
            for i, t in pickle.loads(d):
                full[i] = t
        schema = parts[0].schema
        ordered = []
        for i in range(len(parts)):
            t = full.get(i)
            mp = (MicroPartition.from_table(t) if t is not None
                  else MicroPartition.empty(schema))
            ordered.append(mp)
        # identical bucketing to ShuffleOp's host fanout (piece i of every
        # part, concatenated in part order) so results are byte-identical
        # with the exchange the collective/host paths produce
        buckets = [[] for _ in range(num)]
        for pi, mp in enumerate(ordered):
            if scheme == "hash":
                pieces = mp.partition_by_hash(by, num)
            elif scheme == "random":
                pieces = mp.partition_by_random(num, seed=pi)
            else:
                pieces = mp.partition_by_range(by, boundaries, descending,
                                               nulls_first)
            for i, piece in enumerate(pieces):
                if len(piece):
                    buckets[min(i, num - 1)].append(piece)
        self.stats.bump("transport_shuffles")
        if sent_rows:
            self.stats.bump("exchange_rows", sent_rows)
        if sent_bytes:
            self.stats.bump("exchange_bytes", sent_bytes)
        out = []
        for b in range(num):
            out.append(MicroPartition.concat(buckets[b]) if buckets[b]
                       else MicroPartition.empty(schema))
        return out

    def _device_shuffle_impl(self, parts: List[MicroPartition], by, num: int,
                             scheme: str, descending=None, nulls_first=None,
                             boundaries=None,
                             combine=None) -> Optional[List[MicroPartition]]:
        n = self.n_devices
        if scheme not in ("hash", "random", "range"):
            return None
        if scheme == "range" and boundaries is None:
            return None
        schema = parts[0].schema
        if any(not exchangeable_dtype(f.dtype) for f in schema):
            return None
        str_idx = [j for j, f in enumerate(schema) if f.dtype.is_string()]
        from ..schema import Schema
        from ..table import Table, _composite_rank

        devs = list(self.mesh.devices.flat)
        my_proc = jax.process_index()
        multiproc = any(d.process_index != my_proc for d in devs)
        if multiproc:
            # Per-host scan locality (reference: per-node scan dispatch,
            # ray_runner.py:504-685): the part list is globally consistent
            # (SPMD control plane), so contribution ownership is assigned by
            # part INDEX — process p materializes and stages only parts with
            # i % nproc == p. An unloaded scan partition owned elsewhere is
            # never table()'d, so each host READS only its share of the
            # input files; every row is contributed exactly once whether the
            # inputs are process-duplicated (in-memory SPMD) or disjoint
            # (scan tasks). The post-exchange allgather below reconstitutes
            # full outputs on every process, reconverging the control plane.
            nproc = jax.process_count()
            tables = [p.table() for i, p in enumerate(parts)
                      if (p.owner_process if p.owner_process is not None
                          else i % nproc) == my_proc]
        else:
            tables = [p.table() for p in parts]
        total = sum(len(t) for t in tables)
        if not multiproc and total == 0:
            return None

        # Re-chunk onto the devices THIS process stages: all n in single
        # process; the process-local devices in multi-process mode.
        chunk_dev_idx = [i for i, d in enumerate(devs)
                        if not multiproc or d.process_index == my_proc]
        nchunks = len(chunk_dev_idx)
        if tables:
            merged = Table.concat(tables) if len(tables) != 1 else tables[0]
        else:
            merged = Table.empty(schema)
        precombined = 0
        if combine is not None and len(merged):
            # hierarchical exchange, mesh mirror: fold THIS process's local
            # contribution through the stage-2 combine ahead of the ICI
            # all_to_all — the local rows ride the collective pre-reduced
            # (intra-host combine -> inter-host all_to_all). Schema-closure
            # was gated at translate time; re-check and decline on drift.
            try:
                folded = merged.agg(list(combine[0]), list(combine[1]))
            except Exception:
                folded = None
            if folded is not None and folded.schema == merged.schema:
                # counted only on exchange SUCCESS (see the bumps before
                # return) — a late collective failure falls back to the
                # host path, which re-counts everything
                precombined = len(merged) - len(folded)
                merged = folded
                total = len(merged)
        step = -(-total // nchunks) if total else 0
        chunks = [merged.slice(min(i * step, total), min((i + 1) * step, total))
                  for i in range(nchunks)]
        # String columns exchange as codes against GLOBAL sorted
        # dictionaries agreed across every process (see module docstring);
        # the agreement must run on every process in the same order even
        # when this process's contribution is empty.
        global_dicts = {}
        if str_idx:
            import pyarrow as pa
            import pyarrow.compute as pc

            fields = list(schema)
            local_dicts = []
            for j in str_idx:
                arr = merged.get_column(fields[j].name).to_arrow()
                if isinstance(arr, pa.ChunkedArray):
                    arr = arr.combine_chunks()
                local_dicts.append(
                    pc.unique(arr.drop_null()).cast(pa.large_string()))
            gds = _gather_global_dictionaries(local_dicts, multiproc)
            if gds is None:
                return None  # cap tripped (agreed globally)
            global_dicts = dict(zip(str_idx, gds))
        # Control plane: per-row destination PARTITION, computed with the host
        # kernels (identical assignment to the host shuffle path).
        k = len(by or [])
        desc = list(descending) if descending is not None else [False] * k
        nf = list(nulls_first) if nulls_first is not None else [None] * k
        part_buckets, dev_buckets, inbounds = [], [], []
        for ci, c in enumerate(chunks):
            if scheme == "hash":
                h = c.hash_rows(by)
                b = (h % np.uint64(num)).astype(np.int32)
            elif scheme == "random":
                # seed by GLOBAL device index: local chunk indices repeat
                # across processes and would correlate the bucket sequences
                rng = np.random.RandomState(chunk_dev_idx[ci])
                b = rng.randint(0, num, size=len(c)).astype(np.int32)
            else:
                bnds = boundaries._columns
                if not bnds or len(bnds[0]) == 0:
                    b = np.zeros(len(c), dtype=np.int32)
                else:
                    keys = c.eval_expression_list(by)._columns
                    b = np.minimum(_composite_rank(keys, bnds, desc, nf),
                                   num - 1).astype(np.int32)
            part_buckets.append(b)
            dev_buckets.append((b % n).astype(np.int32) if num > n else b)
            inbounds.append(np.ones(len(c), dtype=bool))
        cap = exchange_capacity(dev_buckets, inbounds, n)
        maxlen = max((len(c) for c in chunks), default=1)
        if multiproc:
            # Negotiate the exchange SHAPE globally: with disjoint
            # contributions the local capacity/slab sizes differ per process,
            # and shard_map needs every process to compile the same program.
            # cap is a per-(src,dst) property so the global value is the max
            # over all sources; a zero GLOBAL row count (not local) skips.
            from jax.experimental import multihost_utils

            agreed = np.asarray(multihost_utils.process_allgather(
                np.array([cap, maxlen, total], dtype=np.int64)))
            cap = int(agreed[:, 0].max())
            maxlen = int(agreed[:, 1].max())
            if int(agreed[:, 2].sum()) == 0:
                return None
        r = size_bucket(max(maxlen, 1))
        names = [f.name for f in schema]
        ncols = len(names)
        ship_lane = num > n  # receivers need the partition id to split
        # Per-device staging: stage one source shard at a time and device_put
        # it straight onto its mesh device. Every chunk here is staged — in
        # multi-process mode `chunks` already covers exactly the LOCAL
        # devices (the global arrays assemble from addressable shards only,
        # standard jax multihost staging).
        b_shards, v_shards, lane_shards = [], [], []
        col_shards = [[] for _ in range(ncols)]
        null_shards = [[] for _ in range(ncols)]
        col_trailing = [()] * ncols
        col_dtypes = [None] * ncols
        ok = True
        try:
            for i, c in enumerate(chunks):
                dev = devs[chunk_dev_idx[i]]
                bm = np.zeros(r, dtype=np.int32)
                vm = np.zeros(r, dtype=bool)
                bm[:len(c)] = dev_buckets[i]
                vm[:len(c)] = True
                b_shards.append(jax.device_put(bm[None], dev))
                v_shards.append(jax.device_put(vm[None], dev))
                if ship_lane:
                    lm = np.zeros(r, dtype=np.int32)
                    lm[:len(c)] = part_buckets[i]
                    lane_shards.append(jax.device_put(lm[None], dev))
                for j, name in enumerate(names):
                    if j in global_dicts:
                        vals, valid = _stage_global_codes(
                            c.get_column(name), global_dicts[j], r)
                    else:
                        vals, valid, _ = stage_np(c.get_column(name), r)
                    col_trailing[j] = tuple(vals.shape[1:])
                    col_dtypes[j] = vals.dtype
                    col_shards[j].append(jax.device_put(vals[None], dev))
                    null_shards[j].append(jax.device_put(valid[None], dev))
        except ValueError:
            # stage_np rejects e.g. int64 values outside int32 range when x64
            # is off (real-TPU mode): fall back to the host shuffle, same as
            # every other device route
            ok = False
        if multiproc:
            # staging failure is DATA-dependent and contributions are
            # disjoint: one process declining while others proceed would
            # deadlock the collective, so agree on the outcome first
            from jax.experimental import multihost_utils

            oks = np.asarray(multihost_utils.process_allgather(
                np.array([1 if ok else 0], dtype=np.int64)))
            if int(oks.min()) == 0:
                return None
        if not ok:
            return None
        lane_cols = ([np.dtype(np.int32)] if ship_lane else [])
        all_dtypes = tuple(col_dtypes) + tuple(np.dtype(bool) for _ in names) + tuple(lane_cols)
        trailing = tuple(col_trailing) + tuple(() for _ in names) + tuple(
            () for _ in lane_cols)
        fn = build_exchange(self.mesh, cap, all_dtypes, trailing)
        dev_args = [self._shard_onto_devices(b_shards, (), r),
                    self._shard_onto_devices(v_shards, (), r)]
        for j in range(ncols):
            dev_args.append(self._shard_onto_devices(col_shards[j], col_trailing[j], r))
        for j in range(ncols):
            dev_args.append(self._shard_onto_devices(null_shards[j], (), r))
        if ship_lane:
            dev_args.append(self._shard_onto_devices(lane_shards, (), r))
        out = fn(*dev_args)
        import jax.numpy as jnp

        if multiproc:
            # SPMD materialization: every process needs every output
            # partition to continue the (duplicated) host control plane, so
            # the exchanged slabs allgather across processes — this IS the
            # DCN data movement (jax.experimental.multihost_utils), the
            # role the reference's Ray object store plays across nodes.
            from jax.experimental import multihost_utils

            gathered = [np.asarray(multihost_utils.process_allgather(
                o, tiled=True)) for o in out]
            valid_all = gathered[0]
            lane_all = gathered[1 + 2 * ncols] if ship_lane else None
            if ship_lane:
                cnts = np.stack([
                    np.bincount(lane_all[d].reshape(-1)[
                        valid_all[d].reshape(-1)], minlength=num)[:num]
                    for d in range(n)])
            else:
                cnts = valid_all.sum(axis=(1, 2))

            def _slab(idx: int, d: int):
                return gathered[idx][d]
        else:
            # Per-partition row counts computed ON DEVICE: one tiny
            # [n(, num)] fetch instead of pulling the full [n, n, cap]
            # valid/lane matrices through the host link (which the tunnel's
            # fixed fetch latency makes the dominant cost of small shuffles).
            if ship_lane:
                def _cnts(v, l):
                    def per_dev(vv, ll):
                        lanes = jnp.where(vv.reshape(-1), ll.reshape(-1), num)
                        return jnp.bincount(lanes, length=num + 1)[:num]
                    return jax.vmap(per_dev)(v, l)

                cnts = np.asarray(jax.device_get(
                    jax.jit(_cnts)(out[0], out[1 + 2 * ncols])))  # [n, num]
            else:
                cnts = np.asarray(jax.device_get(
                    jax.jit(lambda v: jnp.sum(v, axis=(1, 2)))(out[0])))  # [n]

            shard_maps = [
                {s.device: s.data for s in garr.addressable_shards}
                for garr in out]

            def _slab(idx: int, d: int):
                return shard_maps[idx][devs[d]][0]

        self.stats.bump("device_shuffles")

        # Unstage: per OUTPUT PARTITION, pack the received slab's real rows to
        # the front ON ITS OWNING DEVICE (b % n for num > n; b otherwise,
        # trailing devices idle when num < n), then SEED the new partition's
        # HBM residency cache with the packed columns — downstream device ops
        # (join probes, filters, segment aggs) on co-partitioned outputs run
        # without re-staging anything through the host link.
        from ..kernels.device import x64_enabled

        results: List[MicroPartition] = []
        for b in range(num):
            d = b % n
            cnt = int(cnts[d, b]) if ship_lane else int(cnts[b])
            bucket = size_bucket(max(cnt, 1))
            sel = _slab(0, d).reshape(-1)
            if ship_lane:
                sel = sel & (_slab(1 + 2 * ncols, d).reshape(-1) == np.int32(b))
            series_out = []
            staged: List[DeviceColumn] = []
            for j, f in enumerate(schema):
                slab = _slab(1 + j, d)
                flat = slab.reshape((-1,) + tuple(slab.shape[2:]))
                nulls = _slab(1 + ncols + j, d).reshape(-1)
                pv, pn = _pack_slab(flat, nulls, sel, bucket)
                # string columns arrive as codes into the GLOBAL sorted
                # dictionary — decode at unstage, and the seeded residency
                # below hands downstream device string ops exactly the
                # sorted-dictionary shape they expect
                dc = DeviceColumn(pv, pn, cnt, f.dtype,
                                  dictionary=global_dicts.get(j))
                staged.append(dc)
                series_out.append(unstage(dc).rename(f.name))
            part = MicroPartition.from_table(Table(Schema(list(schema)), series_out))
            cache = part.device_stage_cache()
            for f, dc in zip(schema, staged):
                cache[(f.name, bucket, x64_enabled())] = dc
            results.append(part)
        # actual exchanged payload, symmetric with the host path's
        # bucket-append accounting: the rows/bytes THIS process staged onto
        # the collective (post pre-combine) — not the pre-materialization
        # estimate the old device branch reported. Bumped only HERE, after
        # the whole exchange (collective + unstage) succeeded: an earlier
        # bump would double-count with the host fallback's re-count when a
        # late failure makes try_device_shuffle return None.
        if total:
            self.stats.bump("exchange_rows", total)
            mb = merged.size_bytes()
            if mb:
                self.stats.bump("exchange_bytes", mb)
        if precombined:
            self.stats.bump("exchange_precombined_rows", precombined)
        return results

    # ------------------------------------------------------------------
    # sketch subsystem: global stage-2 HLL merges ride ICI as a register
    # all_gather+max instead of a host loop over gathered sketch rows
    # ------------------------------------------------------------------

    def try_sketch_register_merge(self, regs: np.ndarray):
        """Merge [k, m] uint8 HLL register rows into one [m] row with the
        jitted all_gather+max collective (collectives.build_register_allmerge).
        Returns None when ineligible, when the collective breaker is open, or
        when the collective fails (failure recorded against the breaker; the
        caller's host merge takes over). Fault site: collective.sketch."""
        from .. import faults

        n = self.n_devices
        if self._multiproc or regs.ndim != 2 or regs.shape[0] == 0:
            # multi-process stage-2 inputs are process-local after the
            # gather; keep the collective merge single-process for now
            return None
        if not self.collective_health.allow(self.stats):
            self.stats.bump("degraded_sketch_merges")
            return None
        try:
            faults.check("collective.sketch", self.stats)
            from .collectives import build_register_allmerge, shard_to_mesh

            k, m = regs.shape
            if k > n:
                # pre-fold surplus rows so one row rides each device
                pad = (-k) % n
                folded = np.concatenate(
                    [regs, np.zeros((pad, m), np.uint8)])
                regs = folded.reshape(-1, n, m).max(axis=0)
            elif k < n:
                regs = np.concatenate(
                    [regs, np.zeros((n - k, m), np.uint8)])
            fn = build_register_allmerge(self.mesh, m)
            out = np.asarray(jax.device_get(
                fn(shard_to_mesh(np.ascontiguousarray(regs), self.mesh))))[0]
        except Exception:
            self.collective_health.record_failure(self.stats)
            return None
        self.collective_health.record_success(self.stats)
        self.stats.bump("collective_sketch_merges")
        return out

    def _collective_merge_eligible(self, groupby, predicate) -> bool:
        # no min-rows gate: a stage-2 input is one sketch row per partition
        # BY DESIGN — routing those few wide rows through ICI is the point.
        # Multi-process declines HERE, before the partition materializes and
        # the sketches decode (try_sketch_register_merge would refuse anyway)
        return (not groupby and predicate is None
                and self.cfg.use_device_kernels and not self._multiproc)

    def eval_agg(self, part, aggregations, groupby, predicate=None):
        """Global merge_sketch_hll stages (the gathered stage 2 of a
        multi-partition approx_count_distinct) merge on the mesh when
        eligible; everything else takes the base routing."""
        if self._collective_merge_eligible(groupby, predicate):
            out = self._sketch_merge_collective(part, aggregations)
            if out is not None:
                return out
        return super().eval_agg(part, aggregations, groupby,
                                predicate=predicate)

    def eval_agg_dispatch(self, part, aggregations, groupby, predicate=None):
        """The executor's non-blocking driver probes HERE first; the
        collective merge resolves synchronously (one tiny all_gather), so
        it hands back an already-resolved thunk."""
        if self._collective_merge_eligible(groupby, predicate):
            out = self._sketch_merge_collective(part, aggregations)
            if out is not None:
                return lambda: out
        return super().eval_agg_dispatch(part, aggregations, groupby,
                                         predicate=predicate)

    def _sketch_merge_collective(self, part, aggregations):
        from ..datatypes import DataType
        from ..expressions import AggExpr, Alias
        from ..schema import Field, Schema
        from ..series import Series
        from ..sketch.hll import binary_to_registers, registers_to_binary
        from ..table import Table

        nodes = []
        for e in aggregations:
            node = e._node
            while isinstance(node, Alias):
                node = node.child
            if not (isinstance(node, AggExpr)
                    and node.kind == "merge_sketch_hll"):
                return None
            nodes.append((e.name(), node))
        if not nodes:
            return None
        tbl = part.table()
        out_cols = []
        out_fields = []
        for alias, node in nodes:
            child = node.child.evaluate(tbl)
            merged = self.try_sketch_register_merge(
                binary_to_registers(child))
            if merged is None:
                return None
            s = Series.from_arrow(registers_to_binary(merged[None]), alias,
                                  DataType.binary())
            out_cols.append(s)
            out_fields.append(Field(alias, DataType.binary()))
        return MicroPartition.from_table(Table(Schema(out_fields), out_cols))
