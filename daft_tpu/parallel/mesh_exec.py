"""Mesh execution context: partition shuffles ride ICI collectives.

Role-equivalent to the reference's RayRunner data plane
(daft/runners/ray_runner.py:504-685 — dispatch loop + object-store transfer).
Redesign for TPU: the N output partitions of a shuffle live one-per-device of a
`jax.sharding.Mesh`; the fanout+reduce pair becomes ONE all_to_all collective
(collectives.build_exchange). Host keeps the control plane: bucket assignment
(host hash kernels work for every dtype incl. strings), capacity negotiation,
and re-chunking partitions onto the mesh axis.

Columns whose dtype is not device-representable (strings, lists, ...) force a
host-path shuffle for that exchange — the same Native-vs-Python storage split
the reference keeps (SURVEY.md §7 step 1).
"""

from __future__ import annotations

from typing import List, Optional

import numpy as np

import jax

from ..execution import ExecutionContext, RuntimeStats
from ..kernels.device import DeviceColumn, is_device_dtype, size_bucket, stage_np, unstage
from ..micropartition import MicroPartition
from .collectives import build_exchange, exchange_capacity, shard_to_mesh


def default_mesh(n: Optional[int] = None):
    """A 1-D mesh over the first n (default: all) local devices, axis 'parts'."""
    devs = jax.devices()
    if n is not None:
        devs = devs[:n]
    return jax.sharding.Mesh(np.array(devs), ("parts",))


class MeshExecutionContext(ExecutionContext):
    """ExecutionContext whose shuffles use the device exchange when eligible."""

    def __init__(self, cfg, stats: Optional[RuntimeStats] = None, mesh=None):
        super().__init__(cfg, stats)
        self.mesh = mesh if mesh is not None else default_mesh()

    @property
    def n_devices(self) -> int:
        return int(np.prod(list(self.mesh.shape.values())))

    def try_device_shuffle(self, parts: List[MicroPartition], by, num: int,
                           scheme: str) -> Optional[List[MicroPartition]]:
        """All-to-all hash/random shuffle over the mesh; None if ineligible
        (wrong fanout, non-device payload dtype, empty input)."""
        n = self.n_devices
        if num != n or scheme not in ("hash", "random"):
            return None
        schema = parts[0].schema
        if any(not is_device_dtype(f.dtype) for f in schema):
            return None
        tables = [p.table() for p in parts]
        total = sum(len(t) for t in tables)
        if total == 0:
            return None
        # Re-chunk onto the mesh axis: exactly n equal-ish source shards.
        from ..table import Table

        merged = Table.concat(tables) if len(tables) != 1 else tables[0]
        step = -(-total // n)
        chunks = [merged.slice(min(i * step, total), min((i + 1) * step, total))
                  for i in range(n)]
        # Control plane: per-row destination bucket, computed with the host
        # hash kernels (identical assignment to the host shuffle path).
        buckets_np, inbounds = [], []
        for ci, c in enumerate(chunks):
            if scheme == "hash":
                h = c.hash_rows(by)
                buckets_np.append((h % np.uint64(n)).astype(np.int32))
            else:
                rng = np.random.RandomState(ci)
                buckets_np.append(rng.randint(0, n, size=len(c)).astype(np.int32))
            inbounds.append(np.ones(len(c), dtype=bool))
        cap = exchange_capacity(buckets_np, inbounds, n)
        r = size_bucket(max((len(c) for c in chunks), default=1))
        # Stage: stacked [n, R] global arrays, one row of the leading axis per
        # device. Row validity (vmat) marks real vs padding rows; each column
        # additionally ships its own null mask as an extra bool lane so nulls
        # survive the exchange.
        names = [f.name for f in schema]
        bmat = np.zeros((n, r), dtype=np.int32)
        vmat = np.zeros((n, r), dtype=bool)
        col_mats: List[Optional[np.ndarray]] = [None] * len(names)
        null_lanes = [np.zeros((n, r), dtype=bool) for _ in names]
        dtypes = []
        for i, c in enumerate(chunks):
            bmat[i, :len(c)] = buckets_np[i]
            vmat[i, :len(c)] = True
            for j, name in enumerate(names):
                vals, valid, _ = stage_np(c.get_column(name), r)
                if col_mats[j] is None:
                    col_mats[j] = np.zeros((n,) + vals.shape, dtype=vals.dtype)
                    dtypes.append(vals.dtype)
                col_mats[j][i] = vals
                null_lanes[j][i] = valid

        trailing = tuple(tuple(m.shape[2:]) for m in col_mats) + tuple(
            () for _ in null_lanes)
        all_dtypes = tuple(dtypes) + tuple(np.dtype(bool) for _ in null_lanes)
        fn = build_exchange(self.mesh, cap, all_dtypes, trailing)
        dev_args = [shard_to_mesh(bmat, self.mesh), shard_to_mesh(vmat, self.mesh)]
        for m in list(col_mats) + null_lanes:
            dev_args.append(shard_to_mesh(m, self.mesh))
        out = fn(*dev_args)
        recv_valid = np.asarray(jax.device_get(out[0]))  # [n, n, cap]
        ncols = len(col_mats)
        recv_cols = [np.asarray(jax.device_get(o)) for o in out[1:1 + ncols]]
        recv_nulls = [np.asarray(jax.device_get(o)) for o in out[1 + ncols:]]
        self.stats.bump("device_shuffles")
        # Unstage: per destination device, mask-compact the received slabs.
        results: List[MicroPartition] = []
        from ..schema import Schema
        from ..table import Table as T

        for d in range(n):
            mask = recv_valid[d].reshape(-1)
            cnt = int(mask.sum())
            series_out = []
            for j, f in enumerate(schema):
                flat = recv_cols[j][d].reshape((-1,) + recv_cols[j][d].shape[2:])
                nulls = recv_nulls[j][d].reshape(-1)
                vals = flat[mask]
                col_valid = nulls[mask]
                dc = DeviceColumn(vals, col_valid, cnt, f.dtype)
                series_out.append(unstage(dc).rename(f.name))
            results.append(MicroPartition.from_table(T(Schema(list(schema)), series_out)))
        return results
