"""Multi-chip parallelism: jax.sharding.Mesh execution + ICI collectives.

This package is the TPU-native replacement for the reference's distributed
runtime (reference: daft/runners/ray_runner.py + the FanoutHash/FanoutRange/
ReduceMerge instruction pairs in daft/execution/execution_step.py:834-985 and
the generator combinators in daft/execution/physical_plan.py:1365,1414).
Where the reference moves partitions through the Ray object store, here the
exchange is a single XLA `all_to_all` collective over the mesh axis — data
plane on ICI, control plane (bucket assignment, capacity negotiation) on host.
"""

from .collectives import build_exchange, exchange_capacity
from .mesh_exec import MeshExecutionContext, default_mesh
from .multihost import global_mesh, init_distributed, process_local_slots

__all__ = [
    "build_exchange",
    "exchange_capacity",
    "MeshExecutionContext",
    "default_mesh",
    "global_mesh",
    "init_distributed",
    "process_local_slots",
]
