"""End-to-end partition integrity (README "Data integrity & speculation").

Payloads that leave compute and come back — spill IPC files, transport
frames, encoded exchange pieces — carry a fast checksum computed when the
payload is produced and verified when it re-enters compute
(:mod:`.checksum`), so silent corruption surfaces as a typed
``DaftCorruptionError`` instead of a garbled table. A bounded per-query
:class:`.lineage.LineageLog` records how spilled partitions were produced
so a corrupted (or missing) artifact is recomputed from its source
instead of failing the query."""

from .checksum import crc32_bytes, crc32_file, crc32_table, flip_file_bits
from .lineage import LineageLog

__all__ = ["crc32_bytes", "crc32_file", "crc32_table", "flip_file_bits",
           "LineageLog"]
