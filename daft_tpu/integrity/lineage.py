# daftlint: migrated
"""Lineage-based recomputation for spilled partitions.

Restarting a whole query because one spill file rotted is the
coarse-grained failure mode operator frameworks avoid by recovering
individual operator outputs from lineage (HPTMT, PAPERS.md). Here the
unit is a spilled partition: when it enters the spill layer, a RECIPE —
a zero-arg closure that re-derives the partition's exact logical tables
from stable storage — is recorded in the query's bounded
:class:`LineageLog`; when the spill read-back detects corruption (or the
file is simply gone), the slot task recomputes through the recipe and
serves the recomputed table, counted as ``partitions_recomputed``,
instead of failing the query.

Recipes must never pin partition memory (that would defeat the spill),
so only partitions re-derivable from stable sources get one:

- a spilled partition still backed by a re-readable scan task (the file
  is the source of truth — re-read it);
- a shuffle fanout piece whose SOURCE partition was scan-backed (re-read
  the source, re-run the deterministic hash/random split, take the same
  bucket — "op + input partition ref" lineage).

Everything else — loaded in-memory sources, pruned/combined exchange
pieces, deferred-op chains — is *truncated* lineage: corruption there
degrades through the transient-retry machinery to a query-level
``DaftError``, never a garbled result. The log itself is bounded
(``cfg.lineage_log_depth``); evicting a recipe is also truncation,
counted so tests can pin the degradation path."""

from __future__ import annotations

import threading
from collections import OrderedDict
from typing import Callable, List, Optional

Recipe = Callable[[], List]  # zero-arg -> the partition's chunk Tables


class LineageLog:
    """Bounded per-query recipe registry (key -> recompute closure).

    ``record`` returns an opaque key the spill slot task stores; ``get``
    returns the recipe or None when it was evicted (bounded log) — the
    caller treats None as truncated lineage and degrades."""

    def __init__(self, depth: int = 4096):
        self._lock = threading.Lock()
        self._depth = max(0, int(depth))
        self._recipes: "OrderedDict[int, Recipe]" = OrderedDict()
        self._seq = 0
        self.recorded = 0
        self.evicted = 0

    def record(self, recipe: Recipe) -> Optional[int]:
        """Register a recipe; returns its key, or None when the log is
        configured away (depth 0 — every spill is truncated lineage)."""
        if self._depth <= 0:
            return None
        with self._lock:
            self._seq += 1
            key = self._seq
            self._recipes[key] = recipe
            self.recorded += 1
            while len(self._recipes) > self._depth:
                self._recipes.popitem(last=False)
                self.evicted += 1
            return key

    def get(self, key: Optional[int]) -> Optional[Recipe]:
        if key is None:
            return None
        with self._lock:
            return self._recipes.get(key)

    def forget(self, key: Optional[int]) -> None:
        """Drop a recipe whose spill slot was consumed/recycled (keeps the
        bounded log dense with recipes that can still be needed)."""
        if key is None:
            return
        with self._lock:
            self._recipes.pop(key, None)

    def snapshot(self) -> dict:
        with self._lock:
            return {"depth": self._depth, "live": len(self._recipes),
                    "recorded": self.recorded, "evicted": self.evicted}


def rereadable_task(task) -> bool:
    """Is ``task`` a stable-storage scan task a recipe may capture?

    Spill slots re-read the (possibly corrupt) spill file itself and
    encoded exchange tasks hold their payload in memory — capturing
    either would be circular or would pin the bytes the spill exists to
    release. Anything shaped like a real scan task (reads from source
    storage on demand) qualifies."""
    if task is None:
        return False
    from ..exchange.encode import EncodedExchangeTask
    from ..spill import _SpillSlotTask, _SpillSlotView

    return not isinstance(task, (_SpillSlotTask, _SpillSlotView,
                                 EncodedExchangeTask))


def unwrap_source_task(part):
    """The re-readable scan task behind an UNLOADED partition, or None.

    Prefetch wrappers carry driver-local state (queue slot, fetched
    future) — capture the UNDERLYING task, exactly like the partition's
    own cross-process pickling does. Partitions with deferred op chains
    decline: the pending closures are part of the derivation and cannot
    be re-run from the task alone."""
    if part.is_loaded() or getattr(part, "_pending", None):
        return None
    task = part.scan_task()
    task = getattr(task, "_task", task)
    return task if rereadable_task(task) else None


def task_recipe(task) -> Recipe:
    """Recipe for a partition that IS a scan task's output: re-read it."""

    def recompute() -> List:
        if hasattr(task, "read_chunks"):
            return list(task.read_chunks())
        return [task.read()]

    return recompute


def range_piece_recipe(src_task, by, boundaries, descending, nulls_first,
                       idx: int) -> Recipe:
    """Recipe for one range-shuffle piece: re-read the SOURCE partition
    and re-run the deterministic boundary split (the boundaries are tiny
    sampled key rows, cheap to capture), keeping piece ``idx``."""

    def recompute() -> List:
        from ..micropartition import MicroPartition

        mp = MicroPartition.from_scan_task(src_task)
        pieces = mp.partition_by_range(by, boundaries, descending,
                                       nulls_first)
        return [pieces[idx].table()]

    return recompute


def fanout_piece_recipe(src_task, by, scheme: str, num: int, seed: int,
                        idx: int) -> Recipe:
    """Recipe for one shuffle fanout piece: re-read the SOURCE partition
    and re-run the deterministic split (hash bucketing or the seeded
    random split), keeping bucket ``idx``."""

    def recompute() -> List:
        from ..micropartition import MicroPartition

        mp = MicroPartition.from_scan_task(src_task)
        if scheme == "hash":
            pieces = mp.partition_by_hash(by, num)
        else:
            pieces = mp.partition_by_random(num, seed=seed)
        return [pieces[idx].table()]

    return recompute
