# daftlint: migrated
"""Fast payload checksums shared by every integrity call site.

zlib.crc32 is the engine's one checksum: ~GB/s on the host CPU, cheap
enough for the <3% bench overhead gate, and strong enough to detect the
bit-level damage the data plane actually sees (a flipped sector, a torn
write, a corrupted frame). It is NOT a cryptographic MAC — the transport
endpoints are trusted same-host processes the driver itself spawned.

Three surfaces, one algorithm:

- :func:`crc32_bytes` — raw payload bytes (transport frames);
- :func:`crc32_table` — an arrow table's buffer bytes (encoded exchange
  pieces verified in memory, where no serialization normalizes them);
- :func:`crc32_file` — a written artifact's bytes (spill IPC files:
  the checksum describes exactly what the disk must hand back, so IPC
  padding/normalization can never read as false corruption).

:func:`flip_file_bits` / :func:`flip_payload_bits` are the deterministic
damage injectors behind the ``spill.corrupt`` / ``transport.corrupt``
fault sites — they flip a real bit in the real artifact so detection (and
the recovery behind it) is testable end to end."""

from __future__ import annotations

import os
import zlib

# chunked file reads: spill files are page-cache warm right after the
# write, so the verify pass streams at memcpy speed without a big buffer
_FILE_CHUNK = 1 << 20


def crc32_bytes(data, crc: int = 0) -> int:
    """crc32 of a bytes-like payload (optionally chained via ``crc``)."""
    return zlib.crc32(data, crc) & 0xFFFFFFFF


def _crc32_array(arr, crc: int) -> int:
    for buf in arr.buffers():
        if buf is None:
            crc = zlib.crc32(b"\x00", crc)
        else:
            crc = zlib.crc32(memoryview(buf), crc)
    # DictionaryArray.buffers() covers only validity+indices: the
    # dictionary VALUES — the actual column data for encoded exchange
    # pieces — live on a separate child array and must fold in too
    dictionary = getattr(arr, "dictionary", None)
    if dictionary is not None:
        crc = _crc32_array(dictionary, crc)
    return crc


def crc32_table(atbl) -> int:
    """crc32 over an arrow table's buffer bytes, column by column, chunk
    by chunk — including dictionary value buffers (None buffers — absent
    validity bitmaps — fold as a length-0 marker so presence changes are
    detected too)."""
    crc = 0
    for col in atbl.columns:
        chunks = col.chunks if hasattr(col, "chunks") else [col]
        for chunk in chunks:
            crc = _crc32_array(chunk, crc)
    return crc & 0xFFFFFFFF


def crc32_file(path: str) -> int:
    """crc32 of a file's bytes (the spill write/read verification pair)."""
    crc = 0
    with open(path, "rb") as f:
        while True:
            chunk = f.read(_FILE_CHUNK)
            if not chunk:
                break
            crc = zlib.crc32(chunk, crc)
    return crc & 0xFFFFFFFF


def flip_file_bits(path: str) -> None:
    """Deterministically flip one byte in the middle of ``path`` (the
    ``spill.corrupt`` fault-site effect). A zero-length file is left
    alone — there is nothing to corrupt."""
    try:
        size = os.path.getsize(path)
    except OSError:
        return
    if size <= 0:
        return
    off = size // 2
    with open(path, "r+b") as f:
        f.seek(off)
        b = f.read(1)
        if not b:
            return
        f.seek(off)
        f.write(bytes([b[0] ^ 0xFF]))


def flip_payload_bits(data: bytes) -> bytes:
    """A copy of ``data`` with one byte flipped (the ``transport.corrupt``
    fault-site effect). The flip lands within the frame's FIRST 4 KiB so
    it falls inside the transport's always-covered leading stripe —
    detection stays deterministic even for bulk frames whose body is
    striped-sampled."""
    if not data:
        return data
    off = min(len(data) // 2, 4096)
    out = bytearray(data)
    out[off] ^= 0xFF
    return bytes(out)
