"""Scalar function registry: name -> (type resolution, host kernel).

Single source of truth for every namespaced scalar function the expression DSL exposes
(role-equivalent to the reference's FunctionExpr registry, src/daft-dsl/src/functions/
and src/daft-functions/). Each function declares how its return dtype derives from the
argument dtypes (used by the planner for schema inference without touching data) and a
host kernel over Series (pyarrow/numpy). Device-eligible functions are routed through
the jax kernel layer by the executor, not here.
"""

from __future__ import annotations

import re
from typing import Callable, Dict, List, NamedTuple, Optional

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .datatypes import DataType, TypeKind, try_unify
from .series import Series, _broadcast, _broadcast_to


class FunctionSpec(NamedTuple):
    name: str
    resolve: Callable[..., DataType]  # (*arg_dtypes, **kwargs) -> DataType
    evaluate: Callable[..., Series]  # (*arg_series, **kwargs) -> Series


REGISTRY: Dict[str, FunctionSpec] = {}


def register(name: str, resolve, evaluate) -> None:
    if name in REGISTRY:
        raise ValueError(f"function {name!r} already registered")
    REGISTRY[name] = FunctionSpec(name, resolve, evaluate)


def get_function(name: str) -> FunctionSpec:
    try:
        return REGISTRY[name]
    except KeyError:
        raise ValueError(f"unknown function {name!r}") from None


# ---------------------------------------------------------------------------
# resolve helpers
# ---------------------------------------------------------------------------

def _ret(dtype: DataType):
    def resolve(*_args, **_kw):
        return dtype
    return resolve


def _ret_same(*arg_dtypes, **_kw):
    return arg_dtypes[0]


def _ret_float64(*arg_dtypes, **_kw):
    dt = arg_dtypes[0]
    if not (dt.is_numeric() or dt.is_null() or dt.is_boolean()):
        raise ValueError(f"expected numeric input, got {dt}")
    return DataType.float64()


def _req_string(*arg_dtypes, **_kw):
    for dt in arg_dtypes:
        if not (dt.is_string() or dt.is_null()):
            raise ValueError(f"expected string input, got {dt}")
    return DataType.string()


def _req_string_ret(out: DataType):
    def resolve(*arg_dtypes, **_kw):
        if not (arg_dtypes[0].is_string() or arg_dtypes[0].is_null()):
            raise ValueError(f"expected string input, got {arg_dtypes[0]}")
        return out
    return resolve


def _req_temporal_ret(out: DataType, allow=("date", "timestamp")):
    def resolve(*arg_dtypes, **_kw):
        dt = arg_dtypes[0]
        ok = (dt.kind == TypeKind.DATE and "date" in allow) or (
            dt.kind == TypeKind.TIMESTAMP and "timestamp" in allow
        ) or (dt.kind == TypeKind.TIME and "time" in allow) or dt.is_null()
        if not ok:
            raise ValueError(f"expected temporal ({'/'.join(allow)}) input, got {dt}")
        return out
    return resolve


def _arrow1(fn, out_dtype: Optional[DataType] = None):
    """Lift a pyarrow.compute unary kernel to a Series function."""
    def evaluate(s: Series, **kw) -> Series:
        return Series.from_arrow(fn(s.to_arrow(), **kw), s.name, out_dtype)
    return evaluate


# ---------------------------------------------------------------------------
# numeric
# ---------------------------------------------------------------------------

for _name, _method in [
    ("abs", "abs"), ("ceil", "ceil"), ("floor", "floor"), ("sign", "sign"),
]:
    register(f"numeric.{_name}", _ret_same, (lambda m: lambda s, **kw: getattr(s, m)(**kw))(_method))

for _name in ["sqrt", "cbrt", "exp", "log2", "log10", "log1p", "sin", "cos", "tan",
              "arcsin", "arccos", "arctan", "arctanh", "arccosh", "arcsinh",
              "radians", "degrees"]:
    register(f"numeric.{_name}", _ret_float64, (lambda m: lambda s, **kw: getattr(s, m)(**kw))(_name))

register("numeric.negate", _ret_same, lambda s: -s)
register("numeric.log", _ret_float64, lambda s, base=None: s.log(base))
register("numeric.round", _ret_same, lambda s, decimals=0: s.round(decimals))
def _shift_resolve(*arg_dtypes, **_kw):
    # arrow promotes shift operands to the common integer type; declare the same
    u = try_unify(*arg_dtypes) if len(arg_dtypes) == 2 else arg_dtypes[0]
    if u is None or not u.is_integer():
        raise ValueError(f"shift requires integer operands, got {arg_dtypes}")
    return u


register("numeric.shift_left", _shift_resolve, lambda s, o: s.left_shift(o))
register("numeric.shift_right", _shift_resolve, lambda s, o: s.right_shift(o))
register("numeric.exp2", _ret_float64,
         lambda s: Series.from_pylist([2.0], "two")._binary_numeric(s.cast(DataType.float64()), pc.power, s.name))
register(
    "hash",
    lambda *a, **kw: DataType.uint64(),
    lambda s, seed=None, **kw: s.hash(seed),
)
register("murmur3_32", _ret(DataType.int32()), lambda s: s.murmur3_32())


# ---------------------------------------------------------------------------
# float namespace
# ---------------------------------------------------------------------------

register("float.is_nan", _ret(DataType.bool()), lambda s: s.float_is_nan())
register("float.is_inf", _ret(DataType.bool()), lambda s: s.float_is_inf())
register("float.not_nan", _ret(DataType.bool()), lambda s: s.float_not_nan())
register("float.fill_nan", _ret_same, lambda s, fill: s.float_fill_nan(fill))


# ---------------------------------------------------------------------------
# utf8 namespace (reference: src/daft-core/src/array/ops/utf8.rs)
# ---------------------------------------------------------------------------

def _utf8_binary_bool(fn):
    def evaluate(s: Series, pat: Series) -> Series:
        if len(pat) == 1:
            # scalar pattern BEFORE broadcasting: the vectorized pc kernel
            # applies however long `s` is (the LUT staging path feeds whole
            # dictionaries through here)
            p = pat.to_arrow()[0].as_py()
            if p is None:
                return Series.full_null(s.name, DataType.bool(), len(s))
            return Series.from_arrow(fn(s.to_arrow(), p), s.name, DataType.bool())
        l, r = _broadcast(s, pat)
        # elementwise pattern: per-row python fallback
        lv, rv = l.to_pylist(), r.to_pylist()
        pyfn = {"match_substring": lambda v, p: p in v,
                "starts_with": lambda v, p: v.startswith(p),
                "ends_with": lambda v, p: v.endswith(p)}[fn.__name__]
        out = [None if (a is None or b is None) else pyfn(a, b) for a, b in zip(lv, rv)]
        return Series.from_pylist(out, s.name, DataType.bool())
    return evaluate


register("utf8.contains", lambda *a, **k: _bool_str(a), _utf8_binary_bool(pc.match_substring))
register("utf8.startswith", lambda *a, **k: _bool_str(a), _utf8_binary_bool(pc.starts_with))
register("utf8.endswith", lambda *a, **k: _bool_str(a), _utf8_binary_bool(pc.ends_with))


def _bool_str(arg_dtypes):
    for dt in arg_dtypes:
        if not (dt.is_string() or dt.is_null()):
            raise ValueError(f"expected string input, got {dt}")
    return DataType.bool()


def _utf8_match(s: Series, pattern: Series) -> Series:
    pat = pattern.to_arrow()[0].as_py()
    return Series.from_arrow(pc.match_substring_regex(s.to_arrow(), pat), s.name, DataType.bool())


register("utf8.match", lambda *a, **k: _bool_str(a), _utf8_match)


def _utf8_split(s: Series, pat: Series, regex: bool = False) -> Series:
    p = pat.to_arrow()[0].as_py()
    fn = pc.split_pattern_regex if regex else pc.split_pattern
    out = fn(s.to_arrow().cast(pa.large_string()), p)
    return Series.from_arrow(out, s.name, DataType.list(DataType.string()))


register(
    "utf8.split",
    lambda *a, **k: (_bool_str(a), DataType.list(DataType.string()))[1],
    _utf8_split,
)

register("utf8.length", _req_string_ret(DataType.uint64()),
         lambda s: Series.from_arrow(pc.utf8_length(s.to_arrow()), s.name, DataType.uint64()))
register("utf8.length_bytes", _req_string_ret(DataType.uint64()),
         lambda s: Series.from_arrow(pc.binary_length(s.to_arrow().cast(pa.large_binary())), s.name, DataType.uint64()))
register("utf8.lower", _req_string, _arrow1(pc.utf8_lower, DataType.string()))
register("utf8.upper", _req_string, _arrow1(pc.utf8_upper, DataType.string()))
register("utf8.capitalize", _req_string, _arrow1(pc.utf8_capitalize, DataType.string()))
register("utf8.reverse", _req_string, _arrow1(pc.utf8_reverse, DataType.string()))
register("utf8.lstrip", _req_string, _arrow1(pc.utf8_ltrim_whitespace, DataType.string()))
register("utf8.rstrip", _req_string, _arrow1(pc.utf8_rtrim_whitespace, DataType.string()))


def _utf8_replace(s: Series, pat: Series, replacement: Series, regex: bool = False) -> Series:
    p = pat.to_arrow()[0].as_py()
    r = replacement.to_arrow()[0].as_py()
    fn = pc.replace_substring_regex if regex else pc.replace_substring
    return Series.from_arrow(fn(s.to_arrow(), pattern=p, replacement=r), s.name, DataType.string())


register("utf8.replace", _req_string, _utf8_replace)


def _utf8_extract(s: Series, pat: Series, index: int = 0) -> Series:
    p = pat.to_arrow()[0].as_py()
    rx = re.compile(p)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        m = rx.search(v)
        out.append(None if m is None else (m.group(index) if index <= (rx.groups) else None))
    return Series.from_pylist(out, s.name, DataType.string())


def _utf8_extract_all(s: Series, pat: Series, index: int = 0) -> Series:
    p = pat.to_arrow()[0].as_py()
    rx = re.compile(p)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        ms = [m.group(index) for m in rx.finditer(v)]
        out.append(ms)
    return Series.from_pylist(out, s.name, DataType.list(DataType.string()))


register("utf8.extract", _req_string, _utf8_extract)
register("utf8.extract_all",
         lambda *a, **k: (_req_string(*a), DataType.list(DataType.string()))[1],
         _utf8_extract_all)


def _utf8_find(s: Series, substr: Series) -> Series:
    p = substr.to_arrow()[0].as_py()
    return Series.from_arrow(pc.find_substring(s.to_arrow(), p).cast(pa.int64()), s.name, DataType.int64())


register("utf8.find", _req_string_ret(DataType.int64()), _utf8_find)


def _utf8_left(s: Series, n: Series) -> Series:
    nn = n.to_arrow()[0].as_py()
    return Series.from_arrow(pc.utf8_slice_codeunits(s.to_arrow(), 0, nn), s.name, DataType.string())


def _utf8_right(s: Series, n: Series) -> Series:
    vals = s.to_pylist()
    nn = n.to_arrow()[0].as_py()
    return Series.from_pylist([None if v is None else v[-nn:] if nn else "" for v in vals], s.name, DataType.string())


def _utf8_substr(s: Series, start: Series, length: Optional[Series] = None) -> Series:
    st = start.to_arrow()[0].as_py()
    ln = None if length is None else length.to_arrow()[0].as_py()
    stop = None if ln is None else st + ln
    return Series.from_arrow(pc.utf8_slice_codeunits(s.to_arrow(), st, stop), s.name, DataType.string())


def _req_string_int_args(*arg_dtypes, **_kw):
    """First arg string; remaining args integer (slice offsets/lengths)."""
    if not (arg_dtypes[0].is_string() or arg_dtypes[0].is_null()):
        raise ValueError(f"expected string input, got {arg_dtypes[0]}")
    for dt in arg_dtypes[1:]:
        if not (dt.is_integer() or dt.is_null()):
            raise ValueError(f"expected integer argument, got {dt}")
    return DataType.string()


register("utf8.left", _req_string_int_args, _utf8_left)
register("utf8.right", _req_string_int_args, _utf8_right)
register("utf8.substr", _req_string_int_args, _utf8_substr)


def _utf8_concat(*series: Series) -> Series:
    n = max(len(s) for s in series)
    arrs = [_broadcast_to(s, n).to_arrow().cast(pa.large_string()) for s in series]
    return Series.from_arrow(pc.binary_join_element_wise(*arrs, ""), series[0].name, DataType.string())


register("utf8.concat", _req_string, _utf8_concat)


def _utf8_join(s: Series, sep: Series) -> Series:
    """Join list-of-strings rows with a separator."""
    d = sep.to_arrow()[0].as_py()
    out = pc.binary_join(s.to_arrow(), pa.scalar(d, pa.large_string()))
    return Series.from_arrow(out, s.name, DataType.string())


register(
    "list.join",
    lambda *a, **k: DataType.string(),
    _utf8_join,
)


def _like_to_regex(p: str) -> str:
    out = []
    for ch in p:
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        else:
            out.append(re.escape(ch))
    return "^" + "".join(out) + "$"


def _utf8_like(s: Series, pat: Series, case_insensitive: bool = False) -> Series:
    p = _like_to_regex(pat.to_arrow()[0].as_py())
    flags = re.IGNORECASE if case_insensitive else 0
    rx = re.compile(p, flags)
    out = [None if v is None else bool(rx.match(v)) for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.bool())


register("utf8.like", lambda *a, **k: _bool_str(a), _utf8_like)
register("utf8.ilike", lambda *a, **k: _bool_str(a), lambda s, p: _utf8_like(s, p, True))


def _utf8_rpad(s: Series, length: Series, ch: Series) -> Series:
    ln, c = length.to_arrow()[0].as_py(), ch.to_arrow()[0].as_py()
    out = [None if v is None else (v + c * max(0, ln - len(v)))[:ln] for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.string())


def _utf8_lpad(s: Series, length: Series, ch: Series) -> Series:
    ln, c = length.to_arrow()[0].as_py(), ch.to_arrow()[0].as_py()
    out = [None if v is None else (c * max(0, ln - len(v)) + v)[-ln:] if ln else "" for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.string())


def _utf8_repeat(s: Series, n: Series) -> Series:
    nn = n.to_arrow()[0].as_py()
    return Series.from_arrow(pc.binary_repeat(s.to_arrow(), nn), s.name, DataType.string())


def _req_pad_args(*arg_dtypes, **_kw):
    """string input, integer length, string pad char."""
    if not (arg_dtypes[0].is_string() or arg_dtypes[0].is_null()):
        raise ValueError(f"expected string input, got {arg_dtypes[0]}")
    if len(arg_dtypes) > 1 and not (arg_dtypes[1].is_integer() or arg_dtypes[1].is_null()):
        raise ValueError(f"expected integer pad length, got {arg_dtypes[1]}")
    if len(arg_dtypes) > 2 and not (arg_dtypes[2].is_string() or arg_dtypes[2].is_null()):
        raise ValueError(f"expected string pad character, got {arg_dtypes[2]}")
    return DataType.string()


register("utf8.rpad", _req_pad_args, _utf8_rpad)
register("utf8.lpad", _req_pad_args, _utf8_lpad)
register("utf8.repeat", _req_string_int_args, _utf8_repeat)


def _utf8_count_matches(s: Series, patterns: Series, whole_words: bool = False,
                        case_sensitive: bool = True) -> Series:
    pats = patterns.to_pylist()
    if pats and isinstance(pats[0], list):
        pats = pats[0]
    flags = 0 if case_sensitive else re.IGNORECASE
    parts = [(r"\b" + re.escape(p) + r"\b") if whole_words else re.escape(p) for p in pats]
    rx = re.compile("|".join(parts), flags) if parts else None
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
        else:
            out.append(0 if rx is None else len(rx.findall(v)))
    return Series.from_pylist(out, s.name, DataType.uint64())


register("utf8.count_matches", _req_string_ret(DataType.uint64()), _utf8_count_matches)


def _utf8_normalize(s: Series, remove_punct: bool = False, lowercase: bool = False,
                    nfd_unicode: bool = False, white_space: bool = False) -> Series:
    import string as _string
    import unicodedata
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        if nfd_unicode:
            v = unicodedata.normalize("NFD", v)
        if lowercase:
            v = v.lower()
        if remove_punct:
            v = v.translate(str.maketrans("", "", _string.punctuation))
        if white_space:
            v = " ".join(v.split())
        out.append(v)
    return Series.from_pylist(out, s.name, DataType.string())


register("utf8.normalize", _req_string, _utf8_normalize)


def _tokenize_encode(s: Series, tokens_path: str = "bytes", **_kw) -> Series:
    from .kernels.bpe import get_encoder
    enc = get_encoder(tokens_path)
    out = [None if v is None else enc.encode(v) for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.list(DataType.int64()))


def _tokenize_decode(s: Series, tokens_path: str = "bytes", **_kw) -> Series:
    from .kernels.bpe import get_encoder
    enc = get_encoder(tokens_path)
    out = [None if v is None else enc.decode(v) for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.string())


register("utf8.tokenize_encode",
         lambda *a, **k: DataType.list(DataType.int64()), _tokenize_encode)
register("utf8.tokenize_decode", lambda *a, **k: DataType.string(), _tokenize_decode)


# ---------------------------------------------------------------------------
# temporal namespace (reference: src/daft-core/src/array/ops/date.rs)
# ---------------------------------------------------------------------------

def _dt_component(fn, out: DataType):
    def evaluate(s: Series) -> Series:
        return Series.from_arrow(fn(s.to_arrow()), s.name, out)
    return evaluate


register("dt.year", _req_temporal_ret(DataType.int32()), _dt_component(pc.year, DataType.int32()))
register("dt.month", _req_temporal_ret(DataType.uint32()), _dt_component(pc.month, DataType.uint32()))
register("dt.day", _req_temporal_ret(DataType.uint32()), _dt_component(pc.day, DataType.uint32()))
register("dt.hour", _req_temporal_ret(DataType.uint32(), ("timestamp", "time")),
         _dt_component(pc.hour, DataType.uint32()))
register("dt.minute", _req_temporal_ret(DataType.uint32(), ("timestamp", "time")),
         _dt_component(pc.minute, DataType.uint32()))
register("dt.second", _req_temporal_ret(DataType.uint32(), ("timestamp", "time")),
         _dt_component(pc.second, DataType.uint32()))
register("dt.day_of_week", _req_temporal_ret(DataType.uint32()),
         _dt_component(lambda a: pc.day_of_week(a, count_from_zero=True), DataType.uint32()))
register("dt.day_of_year", _req_temporal_ret(DataType.uint32()),
         _dt_component(pc.day_of_year, DataType.uint32()))


def _dt_date(s: Series) -> Series:
    return Series.from_arrow(s.to_arrow().cast(pa.date32()), s.name, DataType.date())


def _dt_time(s: Series) -> Series:
    arr = s.to_arrow()
    unit = s.dtype.params[0] if s.dtype.kind == TypeKind.TIMESTAMP else "us"
    unit = "us" if unit in ("s", "ms", "us") else "ns"
    return Series.from_arrow(arr.cast(pa.time64(unit)), s.name, DataType.time(unit))


register("dt.date", _req_temporal_ret(DataType.date()), _dt_date)
register(
    "dt.time",
    lambda *a, **k: DataType.time(a[0].params[0] if a[0].kind == TypeKind.TIMESTAMP and a[0].params[0] in ("us", "ns") else "us"),
    _dt_time,
)


_TRUNC_UNIT_US = {
    "microsecond": 1, "millisecond": 1_000, "second": 1_000_000, "minute": 60_000_000,
    "hour": 3_600_000_000, "day": 86_400_000_000, "week": 7 * 86_400_000_000,
}


def _dt_truncate(s: Series, interval: str, relative_to=None) -> Series:
    m = re.fullmatch(r"\s*(\d+)\s*(\w+)\s*", interval)
    if not m:
        raise ValueError(f"invalid truncate interval {interval!r}")
    mult, unit = int(m.group(1)), m.group(2).rstrip("s")
    known = set(_TRUNC_UNIT_US) | {"month", "year"}
    if unit not in known:
        raise ValueError(f"unsupported truncate unit {unit!r}")
    if relative_to is None:
        out = pc.floor_temporal(s.to_arrow(), multiple=mult, unit=unit)
        return Series.from_arrow(out, s.name, s.dtype)
    # truncate relative to an arbitrary origin: floor((t - origin) / step) * step + origin
    if unit not in _TRUNC_UNIT_US:
        raise ValueError(f"truncate with relative_to supports fixed-width units only, not {unit!r}")
    if isinstance(relative_to, Series):
        relative_to = relative_to.to_arrow()[0].as_py()
    # compute in the input's own unit so the declared dtype is preserved exactly
    in_unit = s.dtype.params[0] if s.dtype.kind == TypeKind.TIMESTAMP else "us"
    unit_us = {"s": 1 / 1_000_000, "ms": 1 / 1_000, "us": 1, "ns": 1_000}[in_unit]
    step_ticks = mult * _TRUNC_UNIT_US[unit] * unit_us
    if step_ticks < 1:
        step_ticks = 1  # sub-resolution step on coarse storage: identity
    step = np.int64(step_ticks)
    work_type = pa.timestamp(in_unit, tz=s.dtype.params[1]) if s.dtype.kind == TypeKind.TIMESTAMP else pa.timestamp("us")
    origin = pa.scalar(relative_to, type=pa.timestamp(in_unit)).value
    ts = s.to_arrow().cast(work_type)
    v = np.asarray(pc.fill_null(ts.cast(pa.int64()), 0))
    delta = v - np.int64(origin)
    floored = (delta - ((delta % step) + step) % step) + np.int64(origin)
    out = pa.array(floored).view(work_type)
    if ts.null_count:
        out = pc.if_else(pc.is_valid(ts), out, pa.nulls(len(out), out.type))
    return Series.from_arrow(out, s.name, s.dtype if s.dtype.kind == TypeKind.TIMESTAMP else DataType.timestamp("us"))


register("dt.truncate", lambda *a, **k: a[0], _dt_truncate)
register("dt.strftime",
         _req_temporal_ret(DataType.string(), ("date", "timestamp", "time")),
         lambda s, fmt=None: Series.from_arrow(
             pc.strftime(s.to_arrow(), format=fmt or "%Y-%m-%dT%H:%M:%S%f"), s.name, DataType.string()))
register("dt.to_unix_epoch",
         _req_temporal_ret(DataType.int64(), ("date", "timestamp")),
         lambda s, unit="s": Series.from_arrow(
             s.to_arrow().cast(pa.timestamp(unit if unit != "s" else "s")).cast(pa.int64()),
             s.name, DataType.int64()))


# ---------------------------------------------------------------------------
# list namespace (reference: src/daft-core/src/array/ops/list.rs)
# ---------------------------------------------------------------------------

def _req_list(*arg_dtypes, **_kw):
    dt = arg_dtypes[0]
    if not (dt.is_list() or dt.is_null() or dt.kind == TypeKind.EMBEDDING):
        raise ValueError(f"expected list input, got {dt}")
    return dt


def _list_inner(dt: DataType) -> DataType:
    return dt.inner if dt.is_list() or dt.kind == TypeKind.EMBEDDING else DataType.null()


register("list.lengths", lambda *a, **k: (_req_list(*a), DataType.uint64())[1],
         lambda s: Series.from_arrow(pc.list_value_length(s.to_arrow()).cast(pa.uint64()), s.name, DataType.uint64()))


def _list_get(s: Series, idx: Series, default: Optional[Series] = None) -> Series:
    arr = s.to_arrow()
    if isinstance(idx, Series) and len(idx) == 1:
        i = idx.to_arrow()[0].as_py()
        if pa.types.is_fixed_size_list(arr.type):
            size = arr.type.list_size
            offs = (np.arange(len(arr) + 1, dtype=np.int64) + arr.offset) * size
            child = arr.values
        else:
            offs = np.asarray(arr.offsets).astype(np.int64)
            child = arr.values
        starts, ends = offs[:-1], offs[1:]
        lens = ends - starts
        pos = np.where(i >= 0, starts + i, ends + i)
        valid = (i >= -lens) & (i < lens) & np.asarray(pc.is_valid(arr))
        pos = np.clip(pos, 0, max(len(child) - 1, 0))
        taken = child.take(pa.array(pos, type=pa.int64())) if len(child) else pa.nulls(len(arr), arr.type.value_type)
        out = pc.if_else(pa.array(valid), taken, pa.nulls(len(arr), taken.type))
        res = Series.from_arrow(out, s.name)
        if default is not None:
            res = res.fill_null(default)
        return res
    # elementwise index
    vals = s.to_pylist()
    ii = idx.to_pylist()
    dv = default.to_pylist()[0] if default is not None else None
    out = []
    for v, i in zip(vals, ii):
        if v is None or i is None or not (-len(v) <= i < len(v)):
            out.append(dv)
        else:
            out.append(v[i])
    return Series.from_pylist(out, s.name)


register("list.get", lambda *a, **k: _list_inner(_req_list(*a)), _list_get)


def _list_slice(s: Series, start: Series, end: Optional[Series] = None) -> Series:
    st = start.to_arrow()[0].as_py()
    en = None if end is None else end.to_arrow()[0].as_py()
    out = [None if v is None else v[st:en] for v in s.to_pylist()]
    return Series.from_pylist(out, s.name, DataType.list(_list_inner(s.dtype)))


register("list.slice", lambda *a, **k: DataType.list(_list_inner(_req_list(*a))), _list_slice)


def _list_chunk(s: Series, size: int) -> Series:
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
        else:
            nfull = len(v) // size
            out.append([v[i * size:(i + 1) * size] for i in range(nfull)])
    inner = DataType.fixed_size_list(_list_inner(s.dtype), size)
    return Series.from_pylist(out, s.name, DataType.list(inner))


register("list.chunk",
         lambda *a, size=0, **k: DataType.list(DataType.fixed_size_list(_list_inner(_req_list(*a)), size)),
         _list_chunk)


def _list_agg(fn_name: str):
    def evaluate(s: Series) -> Series:
        arr = s.to_arrow()
        if not (pa.types.is_list(arr.type) or pa.types.is_large_list(arr.type)):
            arr = arr.cast(pa.large_list(arr.type.value_type))
        lens = pc.list_value_length(arr).fill_null(0).to_numpy(zero_copy_only=False)
        tbl = pa.table({"g": np.repeat(np.arange(len(arr)), lens), "v": arr.flatten()})
        # arrow group-by aggregation over flattened child
        agg = tbl.group_by("g").aggregate([("v", fn_name)])
        got = dict(zip(agg.column("g").to_pylist(), agg.column(f"v_{fn_name}").to_pylist()))
        valid = np.asarray(pc.is_valid(arr))
        out = [got.get(i) if valid[i] else None for i in range(len(arr))]
        return Series.from_pylist(out, s.name)
    return evaluate


register("list.sum", lambda *a, **k: _list_inner(_req_list(*a)), _list_agg("sum"))
register("list.mean", lambda *a, **k: DataType.float64(), _list_agg("mean"))
register("list.min", lambda *a, **k: _list_inner(_req_list(*a)), _list_agg("min"))
register("list.max", lambda *a, **k: _list_inner(_req_list(*a)), _list_agg("max"))


def _list_count(s: Series, mode: str = "valid") -> Series:
    arr = s.to_arrow()
    if mode == "all":
        out = pc.list_value_length(arr)
        return Series.from_arrow(out.cast(pa.uint64()), s.name, DataType.uint64())
    vals = s.to_pylist()
    if mode == "valid":
        out = [None if v is None else sum(x is not None for x in v) for v in vals]
    else:
        out = [None if v is None else sum(x is None for x in v) for v in vals]
    return Series.from_pylist(out, s.name, DataType.uint64())


register("list.count", lambda *a, **k: DataType.uint64(), _list_count)


def _list_sort(s: Series, desc: Optional[Series] = None) -> Series:
    d = False if desc is None else desc.to_arrow()[0].as_py()
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
        else:
            vv = [x for x in v if x is not None]
            nn = [x for x in v if x is None]
            out.append(sorted(vv, reverse=bool(d)) + nn)
    return Series.from_pylist(out, s.name, s.dtype if s.dtype.is_list() else DataType.list(DataType.null()))


register("list.sort", lambda *a, **k: _req_list(*a), _list_sort)


def _list_unique(s: Series) -> Series:
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
        else:
            seen, uniq = set(), []
            for x in v:
                key = x if not isinstance(x, (list, dict)) else repr(x)
                if x is not None and key not in seen:
                    seen.add(key)
                    uniq.append(x)
            out.append(uniq)
    return Series.from_pylist(out, s.name, s.dtype if s.dtype.is_list() else DataType.list(DataType.null()))


register("list.unique", lambda *a, **k: _req_list(*a), _list_unique)


def _list_contains(s: Series, item: Series) -> Series:
    iv = item.to_pylist()
    if len(item) == 1:
        iv = iv * len(s)
    out = [None if v is None else (x in v) for v, x in zip(s.to_pylist(), iv)]
    return Series.from_pylist(out, s.name, DataType.bool())


register("list.contains", lambda *a, **k: DataType.bool(), _list_contains)


# ---------------------------------------------------------------------------
# struct / map namespaces
# ---------------------------------------------------------------------------

def _struct_get_resolve(*arg_dtypes, name: str = "", **_kw):
    dt = arg_dtypes[0]
    if dt.kind != TypeKind.STRUCT:
        raise ValueError(f"expected struct input, got {dt}")
    fields = dt.fields
    if name not in fields:
        raise ValueError(f"struct has no field {name!r}; available: {list(fields)}")
    return fields[name]


def _struct_get(s: Series, name: str = "") -> Series:
    arr = s.to_arrow()
    idx = [f.name for f in arr.type].index(name)
    child = pc.struct_field(arr, [idx])
    return Series.from_arrow(child, name)


register("struct.get", _struct_get_resolve, _struct_get)


def _map_get_resolve(*arg_dtypes, **_kw):
    dt = arg_dtypes[0]
    if dt.kind != TypeKind.MAP:
        raise ValueError(f"expected map input, got {dt}")
    return dt.params[1]


def _map_get(s: Series, key: Series) -> Series:
    k = key.to_pylist()[0]
    out = []
    for row in s.to_pylist():
        if row is None:
            out.append(None)
            continue
        items = row.items() if isinstance(row, dict) else row
        val = None
        for kk, vv in items:
            if kk == k:
                val = vv
                break
        out.append(val)
    return Series.from_pylist(out, s.name)


register("map.get", _map_get_resolve, _map_get)


def _to_struct(*series: Series, names: Optional[List[str]] = None) -> Series:
    names = names or [s.name for s in series]
    n = max(len(s) for s in series)
    arrs = [_broadcast_to(s, n).to_arrow() for s in series]
    out = pa.StructArray.from_arrays(arrs, names)
    return Series.from_arrow(out, "struct")


register(
    "struct.make",
    lambda *a, names=None, **k: DataType.struct(dict(zip(names or [f"f{i}" for i in range(len(a))], a))),
    _to_struct,
)


# ---------------------------------------------------------------------------
# partitioning namespace (reference: daft-dsl functions/partitioning)
# ---------------------------------------------------------------------------

_EPOCH_DAYS_TO_1970 = 0


def _part_temporal(fn, out=DataType.int32()):
    def evaluate(s: Series) -> Series:
        arr = s.to_arrow()
        if pa.types.is_timestamp(arr.type) or pa.types.is_date32(arr.type):
            return Series.from_arrow(fn(arr), s.name, out)
        raise ValueError(f"partitioning transform needs date/timestamp, got {arr.type}")
    return evaluate


register("partitioning.days", _req_temporal_ret(DataType.int32()),
         _part_temporal(lambda a: a.cast(pa.date32()).cast(pa.int32())))
register("partitioning.hours", _req_temporal_ret(DataType.int32()),
         _part_temporal(lambda a: pc.divide(a.cast(pa.timestamp("us")).cast(pa.int64()), 3600_000_000).cast(pa.int32())))
register("partitioning.months", _req_temporal_ret(DataType.int32()),
         _part_temporal(lambda a: pc.add(pc.multiply(pc.subtract(pc.year(a), 1970), 12), pc.subtract(pc.month(a).cast(pa.int32()), 1)).cast(pa.int32())))
register("partitioning.years", _req_temporal_ret(DataType.int32()),
         _part_temporal(lambda a: pc.subtract(pc.year(a), 1970).cast(pa.int32())))


def _iceberg_bucket(s: Series, n: int) -> Series:
    h = s.murmur3_32()
    hv = np.asarray(h.to_arrow(), dtype=np.int32).astype(np.int64)
    b = (hv & 0x7FFFFFFF) % n
    out = pa.array(b.astype(np.int32), from_pandas=True)
    mask = pc.is_valid(s.to_arrow()) if s.to_arrow().null_count else None
    if mask is not None:
        out = pc.if_else(mask, out, pa.nulls(len(out), pa.int32()))
    return Series.from_arrow(out, s.name, DataType.int32())


register("partitioning.iceberg_bucket", lambda *a, n=0, **k: DataType.int32(), _iceberg_bucket)


def _iceberg_truncate(s: Series, w: int) -> Series:
    dt = s.dtype
    if dt.is_integer():
        v = s.to_arrow()
        # floor-mod truncate: v - (((v % w) + w) % w)
        vv = np.asarray(pc.fill_null(v.cast(pa.int64()), 0))
        res = vv - ((vv % w + w) % w)
        out = pa.array(res, from_pandas=True)
        if v.null_count:
            out = pc.if_else(pc.is_valid(v), out, pa.nulls(len(out), out.type))
        return Series.from_arrow(out, s.name)
    if dt.is_string():
        out = [None if x is None else x[:w] for x in s.to_pylist()]
        return Series.from_pylist(out, s.name, DataType.string())
    raise ValueError(f"iceberg_truncate unsupported for {dt}")


register("partitioning.iceberg_truncate", lambda *a, w=0, **k: a[0], _iceberg_truncate)


# ---------------------------------------------------------------------------
# json namespace — JSON query via jq-lite path evaluation
# ---------------------------------------------------------------------------

def _json_query(s: Series, query: str) -> Series:
    import json
    # supports jq-style paths: .a.b[0].c
    parts = re.findall(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]", query)
    out = []
    for v in s.to_pylist():
        if v is None:
            out.append(None)
            continue
        try:
            cur = json.loads(v)
            for key, idx in parts:
                if key:
                    cur = cur[key]
                else:
                    cur = cur[int(idx)]
            out.append(json.dumps(cur) if not isinstance(cur, str) else cur)
        except (KeyError, IndexError, TypeError, ValueError):
            out.append(None)
    return Series.from_pylist(out, s.name, DataType.string())


register("json.query", _req_string, _json_query)


# ---------------------------------------------------------------------------
# embedding / distance
# ---------------------------------------------------------------------------

def _cosine_distance(s: Series, other: Series) -> Series:
    a = s.to_numpy()
    b = other.to_numpy()
    if a.dtype == object or b.dtype == object:
        out = []
        bl = b if len(b) == len(a) else [b[0]] * len(a)
        for x, y in zip(a, bl):
            if x is None or y is None:
                out.append(None)
            else:
                x, y = np.asarray(x, dtype=np.float64), np.asarray(y, dtype=np.float64)
                out.append(1.0 - float(np.dot(x, y) / (np.linalg.norm(x) * np.linalg.norm(y))))
        return Series.from_pylist(out, s.name, DataType.float64())
    a = a.astype(np.float64)
    b = b.astype(np.float64)
    if b.shape[0] == 1 and a.shape[0] != 1:
        b = np.broadcast_to(b, a.shape)
    num = (a * b).sum(axis=1)
    den = np.linalg.norm(a, axis=1) * np.linalg.norm(b, axis=1)
    with np.errstate(invalid="ignore", divide="ignore"):
        out = 1.0 - num / den
    return Series.from_arrow(pa.array(out), s.name, DataType.float64())


register("embedding.cosine_distance", lambda *a, **k: DataType.float64(), _cosine_distance)


def _minhash(s: Series, num_hashes: int = 64, ngram_size: int = 1, seed: int = 1) -> Series:
    from .kernels.sketches import minhash_strings
    out = minhash_strings(s.to_arrow(), num_hashes=num_hashes, ngram_size=ngram_size, seed=seed)
    return Series.from_arrow(out, s.name, DataType.fixed_size_list(DataType.uint32(), num_hashes))


register("minhash",
         lambda *a, num_hashes=64, **k: DataType.fixed_size_list(DataType.uint32(), num_hashes),
         _minhash)


# ---------------------------------------------------------------------------
# sketch finalizers: the final-projection stage of the two-phase approximate
# aggregation decomposition (sketch build -> exchange -> merge -> ESTIMATE;
# see daft_tpu/sketch/). Inputs are merged Binary sketch columns.
# ---------------------------------------------------------------------------

def _resolve_hll_estimate(*arg_dtypes, **_kw):
    dt = arg_dtypes[0]
    if not (dt.is_binary() or dt.is_null()):
        raise ValueError(f"sketch.hll_estimate needs a binary sketch column, got {dt}")
    return DataType.uint64()


def _hll_estimate(s: Series) -> Series:
    from .sketch import hll

    return hll.estimate_series(s)


register("sketch.hll_estimate", _resolve_hll_estimate, _hll_estimate)


def _resolve_quantile_estimate(*arg_dtypes, percentiles=0.5, **_kw):
    dt = arg_dtypes[0]
    if not (dt.is_binary() or dt.is_null()):
        raise ValueError(f"sketch.quantile_estimate needs a binary sketch column, got {dt}")
    if isinstance(percentiles, float):
        return DataType.float64()
    return DataType.list(DataType.float64())


def _quantile_estimate(s: Series, percentiles=0.5) -> Series:
    from .sketch import quantile

    return quantile.estimate_series(s, percentiles)


register("sketch.quantile_estimate", _resolve_quantile_estimate, _quantile_estimate)
