"""Adaptive query execution (AQE): stage-wise re-planning with real stats.

Role-equivalent to the reference's AdaptivePlanner
(src/daft-plan/src/physical_planner/planner.rs:288-351): the plan is cut at
materialization boundaries, each boundary stage is executed, and the remaining
plan is re-optimized with the materialized stage substituted as an in-memory
source carrying REAL row counts and byte sizes. Planner decisions that depend
on size estimates then see the truth instead of propagated guesses:

- join strategy selection (broadcast vs hash) uses actual side sizes — a
  filter or aggregate that shrank a side below the broadcast threshold now
  triggers a broadcast join even though the static estimate was too large;
- tiny materialized stages collapse to one partition, letting the
  DropRepartition rule elide now-pointless shuffles downstream.

Stages are chosen as join children whose subtree can change cardinality
(Filter/Aggregate/Limit/Join/Distinct/Sample) — a bare source's stats are
already as good as materializing it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from .logical import (
    Aggregate,
    Distinct,
    Filter,
    InMemorySource,
    Join,
    Limit,
    LogicalPlan,
    Sample,
)
from .micropartition import MicroPartition
from .optimizer import optimize

_SHRINKING = (Filter, Aggregate, Limit, Join, Distinct, Sample)
_MAX_STAGES = 32  # safety valve; each stage strictly shrinks the plan


def _subtree_can_shrink(p: LogicalPlan) -> bool:
    if isinstance(p, _SHRINKING):
        return True
    return any(_subtree_can_shrink(c) for c in p.children())


def _find_stage(p: LogicalPlan) -> Optional[LogicalPlan]:
    """Deepest join child worth materializing before planning the join.

    Returns the child subplan (not the join) — deepest-first so inner joins
    resolve before the joins above them see their sizes."""
    for c in p.children():
        found = _find_stage(c)
        if found is not None:
            return found
    if isinstance(p, Join) and p.strategy is None:
        for side in (p.right, p.left):  # right first: the usual build side
            if not isinstance(side, InMemorySource) and _subtree_can_shrink(side):
                return side
    return None


def _substitute(p: LogicalPlan, target: LogicalPlan, repl: LogicalPlan) -> LogicalPlan:
    if p is target:
        return repl
    kids = p.children()
    if not kids:
        return p
    new_kids = [_substitute(c, target, repl) for c in kids]
    if all(a is b for a, b in zip(kids, new_kids)):
        return p
    return p.with_children(new_kids)


def _known_subtree_size(p: LogicalPlan) -> Optional[int]:
    """Exact byte size of a subtree whose cardinality is already known:
    materialized in-memory sources, optionally under size-preserving ops
    (Project keeps row count; its size is approximated by the source's)."""
    from .logical import Project

    if isinstance(p, InMemorySource):
        total = 0
        for part in p.partitions:
            s = part.size_bytes()
            if s is None:
                return None
            total += s
        return total
    if isinstance(p, Project):
        return _known_subtree_size(p.input)
    return None


def adapt_shuffle_counts(plan: LogicalPlan, cfg, stats=None) -> LogicalPlan:
    """Shrink shuffle fanouts whose input size is KNOWN (reference: the
    AdaptivePlanner re-plans every stage boundary with materialized stats,
    planner.rs:288-351 — here the analog is re-sizing Repartition nodes to
    ceil(bytes / shuffle_target_partition_bytes), shrink-only so an explicit
    user fanout is never exceeded)."""
    from .logical import Repartition

    kids = plan.children()
    if kids:
        new_kids = [adapt_shuffle_counts(c, cfg, stats) for c in kids]
        if any(a is not b for a, b in zip(kids, new_kids)):
            plan = plan.with_children(new_kids)
    if (isinstance(plan, Repartition) and plan.scheme != "into"
            and plan.num and plan.num > 1):
        size = _known_subtree_size(plan.input)
        if size is not None:
            target = max(int(cfg.shuffle_target_partition_bytes), 1)
            ideal = max(1, -(-size // target))
            if ideal < plan.num:
                if stats is not None:
                    stats.bump("aqe_shuffle_resizes")
                return Repartition(plan.input, plan.scheme, ideal,
                                   plan.by, plan.descending)
    return plan


class AdaptivePlanner:
    """Runs a logical plan stage-by-stage, re-optimizing between stages."""

    def __init__(self, execute_subplan, stats=None, cfg=None):
        # execute_subplan: LogicalPlan -> Iterator[MicroPartition]
        # (the runner's non-adaptive path; AQE stays backend-agnostic)
        self._execute = execute_subplan
        self._stats = stats
        self._cfg = cfg
        self.stage_history: List[Tuple[int, int]] = []  # (rows, bytes) per stage

    def _post_optimize(self, plan: LogicalPlan) -> LogicalPlan:
        plan = optimize(plan)
        if self._cfg is not None:
            plan = adapt_shuffle_counts(plan, self._cfg, self._stats)
        return plan

    def run(self, plan: LogicalPlan) -> Iterator[MicroPartition]:
        plan = self._post_optimize(plan)
        for _ in range(_MAX_STAGES):
            stage = _find_stage(plan)
            if stage is None:
                break
            parts = list(self._execute(stage))
            rows = sum(len(p) for p in parts)
            size = sum(p.size_bytes() or 0 for p in parts)
            self.stage_history.append((rows, size))
            if self._stats is not None:
                self._stats.bump("aqe_stages")
            # collapse tiny stages to one partition so downstream shuffles
            # (keyed on num_partitions) can be elided by DropRepartition
            if len(parts) > 1 and size < (1 << 20):
                merged = MicroPartition.concat(parts)
                parts = [merged]
            plan = _substitute(plan, stage, InMemorySource(stage.schema, parts))
            plan = self._post_optimize(plan)
        return self._execute(plan)
