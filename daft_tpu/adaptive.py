"""Adaptive query execution (AQE): stage-wise re-planning with real stats.

Role-equivalent to the reference's AdaptivePlanner
(src/daft-plan/src/physical_planner/planner.rs:288-351): the plan is cut at
materialization boundaries, each boundary stage is executed, and the remaining
plan is re-optimized with the materialized stage substituted as an in-memory
source carrying REAL row counts and byte sizes. Planner decisions that depend
on size estimates then see the truth instead of propagated guesses:

- join strategy selection (broadcast vs hash) uses actual side sizes — a
  filter or aggregate that shrank a side below the broadcast threshold now
  triggers a broadcast join even though the static estimate was too large;
- tiny materialized stages collapse to one partition, letting the
  DropRepartition rule elide now-pointless shuffles downstream.

Stages are chosen as join children whose subtree can change cardinality
(Filter/Aggregate/Limit/Join/Distinct/Sample) — a bare source's stats are
already as good as materializing it.
"""

from __future__ import annotations

from typing import Iterator, List, Optional, Tuple

from .logical import (
    Aggregate,
    Distinct,
    Filter,
    InMemorySource,
    Join,
    Limit,
    LogicalPlan,
    Sample,
)
from .micropartition import MicroPartition
from .optimizer import optimize

_SHRINKING = (Filter, Aggregate, Limit, Join, Distinct, Sample)
_MAX_STAGES = 32  # safety valve; each stage strictly shrinks the plan


def _subtree_can_shrink(p: LogicalPlan) -> bool:
    if isinstance(p, _SHRINKING):
        return True
    return any(_subtree_can_shrink(c) for c in p.children())


def _find_stage(p: LogicalPlan) -> Optional[LogicalPlan]:
    """Deepest join child worth materializing before planning the join.

    Returns the child subplan (not the join) — deepest-first so inner joins
    resolve before the joins above them see their sizes."""
    for c in p.children():
        found = _find_stage(c)
        if found is not None:
            return found
    if isinstance(p, Join) and p.strategy is None:
        for side in (p.right, p.left):  # right first: the usual build side
            if not isinstance(side, InMemorySource) and _subtree_can_shrink(side):
                return side
    return None


def _substitute(p: LogicalPlan, target: LogicalPlan, repl: LogicalPlan) -> LogicalPlan:
    if p is target:
        return repl
    kids = p.children()
    if not kids:
        return p
    new_kids = [_substitute(c, target, repl) for c in kids]
    if all(a is b for a, b in zip(kids, new_kids)):
        return p
    return p.with_children(new_kids)


class AdaptivePlanner:
    """Runs a logical plan stage-by-stage, re-optimizing between stages."""

    def __init__(self, execute_subplan, stats=None):
        # execute_subplan: LogicalPlan -> Iterator[MicroPartition]
        # (the runner's non-adaptive path; AQE stays backend-agnostic)
        self._execute = execute_subplan
        self._stats = stats
        self.stage_history: List[Tuple[int, int]] = []  # (rows, bytes) per stage

    def run(self, plan: LogicalPlan) -> Iterator[MicroPartition]:
        plan = optimize(plan)
        for _ in range(_MAX_STAGES):
            stage = _find_stage(plan)
            if stage is None:
                break
            parts = list(self._execute(stage))
            rows = sum(len(p) for p in parts)
            size = sum(p.size_bytes() or 0 for p in parts)
            self.stage_history.append((rows, size))
            if self._stats is not None:
                self._stats.bump("aqe_stages")
            # collapse tiny stages to one partition so downstream shuffles
            # (keyed on num_partitions) can be elided by DropRepartition
            if len(parts) > 1 and size < (1 << 20):
                merged = MicroPartition.concat(parts)
                parts = [merged]
            plan = _substitute(plan, stage, InMemorySource(stage.schema, parts))
            plan = optimize(plan)
        return self._execute(plan)
