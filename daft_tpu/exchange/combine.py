# daftlint: migrated
"""Hierarchical exchange: apply the stage-2 combine BEFORE the exchange.

A two-stage aggregation ships one stage-1 partial row per (source
partition x group) through the hash exchange; every destination bucket
then holds P pieces that the reduce side merges. Folding the pieces
headed to the SAME destination with the stage-2 combine as they arrive
(Xorbits' intra-host combine -> inter-host all_to_all shape, PAPERS.md)
shrinks what the exchange buffers, ledgers, spills, and merges from
P x groups rows to ~groups rows per bucket. parallel/mesh_exec.py mirrors
the same pre-combine on the local contribution ahead of the ICI
all_to_all.

Byte-identity contract (``hierarchical_exchange_combine`` off must be
byte-identical): the fold keeps ONE running partial per bucket and always
re-aggregates ``[running_partial, new pieces...]`` with the partial's rows
FIRST, so group output order (first-occurrence) is preserved by
induction. FLOAT SUMS DECLINE the combine entirely: the engine's grouped
sum kernel (threaded acero) reassociates float additions across morsel
boundaries, so folding would shift results at the last ulp — integer/
count sums, min/max, concat, and sketch register merges are exact under
any reassociation and fold freely (any_value ALSO declines: which value
"one" picks is input-shape-dependent, see COMBINABLE_KINDS).

Applicability is decided at translate time (:func:`combine_spec_applicable`):
every stage-2 kind must be a decomposable merge that is exact under
reassociation, and the merge's output schema must equal the exchanged
schema (schema-closed fold).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

from ..micropartition import MicroPartition

# fold cadence: pieces staged per bucket before a re-aggregation pass (the
# running partial always rides first, so cadence only trades fold CPU
# against staged-piece memory — it cannot change results). 16 keeps the
# fold work near ONE extra agg per bucket at typical fan-ins (a low
# cadence measurably doubled the agg work on the bench groupby leg) while
# still bounding staged-piece memory at high partition counts.
FOLD_EVERY = 16

# stage-2 kinds the fold may apply early: order-insensitive exact merges
# (min/max/sketch register max), exact accumulations (integer sums — float
# sums are gated by dtype below), and order-preserving concatenations
# (concat). any_value declines: its "pick one" is input-shape-dependent,
# so a fold could change which value survives.
COMBINABLE_KINDS = {"sum", "min", "max", "concat",
                    "merge_sketch_hll", "merge_sketch_quantile"}

# runtime abandon gate: a fold that keeps more than this fraction of its
# input rows is not reducing (near-unique grouping keys) — the running
# partial would converge to the full bucket contents and sit resident
# OUTSIDE the ledgered/spillable PartitionBuffers until stream end, so
# the combiner abandons and hands everything to the buffers, which can
# spill under the memory budget.
ABANDON_MIN_SHRINK = 0.75


def combine_spec_applicable(stage2, key_cols, exchanged_schema) -> bool:
    """Translate-time gate: True when folding `stage2` early over pieces of
    `exchanged_schema` is closed (output schema == input schema) and every
    aggregation kind is a known-safe merge that is EXACT under
    reassociation — float sums decline (the threaded grouped-sum kernel's
    addition order depends on chunking, so an early fold would drift the
    last ulp and break the byte-identity contract)."""
    from ..expressions import AggExpr, Alias
    from ..physical import _stage_schema

    for e in stage2:
        node = e._node
        while isinstance(node, Alias):
            node = node.child
        if not (isinstance(node, AggExpr) and node.kind in COMBINABLE_KINDS):
            return False
        if node.kind == "sum":
            try:
                dt = node.to_field(exchanged_schema).dtype
            except Exception:
                return False
            if not dt.is_integer():
                return False
    try:
        out_schema = _stage_schema(exchanged_schema, stage2, key_cols)
    except Exception:
        return False
    return out_schema == exchanged_schema


class BucketCombiner:
    """Per-destination running partials for one shuffle's fanout.

    ``add(bucket, piece)`` stages a piece; every FOLD_EVERY staged pieces
    the bucket re-aggregates ``[partial] + staged`` into a new single
    partial. ``finish()`` folds the remainders and yields
    ``(bucket, partial)`` for every touched bucket — the only rows that
    enter the exchange. A fold failure abandons the combiner for the whole
    shuffle: every staged piece (and prior partials — they are valid
    partial aggregations of their inputs) is handed back unfolded, which
    keeps results correct because the reduce-side stage 2 merges partials
    of ANY granularity.

    Staged bytes live outside the spillable PartitionBuffers, so they are
    charged to the query's MemoryLedger while resident (released as they
    fold away or leave) and two runtime gates bound them: a fold that
    shrinks worse than ABANDON_MIN_SHRINK abandons (near-unique keys — the
    partial would converge to the whole bucket), and under a byte budget
    the combiner abandons once its resident payload passes half the budget
    (the remaining headroom belongs to the buffers, which CAN spill)."""

    def __init__(self, aggs, keys, stats=None, ledger=None, budget=None):
        self.aggs = list(aggs)
        self.keys = list(keys)
        self.stats = stats
        self.ledger = ledger
        self.budget = budget
        self._staged: Dict[int, List[MicroPartition]] = {}
        self._staged_bytes: Dict[int, int] = {}
        self._held = 0
        self._failed = False

    @property
    def failed(self) -> bool:
        return self._failed

    def _charge(self, bucket: int, piece: MicroPartition) -> None:
        b = piece.size_bytes() or 0
        if b:
            self._staged_bytes[bucket] = self._staged_bytes.get(bucket, 0) + b
            self._held += b
            if self.ledger is not None:
                self.ledger.add(b)

    def _release(self, bucket: int) -> None:
        b = self._staged_bytes.pop(bucket, 0)
        if b:
            self._held -= b
            if self.ledger is not None:
                self.ledger.sub(b)

    def _abandon(self) -> List[Tuple[int, MicroPartition]]:
        self._failed = True
        out = [(b, p) for b in sorted(self._staged)
               for p in self._staged[b]]
        self._staged = {}
        for b in list(self._staged_bytes):
            self._release(b)
        return out

    def add(self, bucket: int, piece: MicroPartition
            ) -> Optional[List[Tuple[int, MicroPartition]]]:
        """Stage one fanout piece. Returns None normally; on a fold
        failure or an abandon gate (poor shrink / budget pressure),
        returns every staged ``(bucket, partition)`` so the caller
        appends them raw (and stops combining)."""
        staged = self._staged.setdefault(bucket, [])
        staged.append(piece)
        self._charge(bucket, piece)
        if len(staged) >= FOLD_EVERY + 1:
            folded = self._fold(staged)
            if folded is None:
                return self._abandon()
            self._release(bucket)
            self._staged[bucket] = [folded]
            self._charge(bucket, folded)
        if self.budget is not None and self._held > self.budget // 2:
            # staged partials cannot spill: past half this query's byte
            # budget, hand them to the spillable buffers instead
            return self._abandon()
        return None

    def finish(self):
        """Fold remainders; yields (bucket, partial) in bucket order."""
        for b in sorted(self._staged):
            staged = self._staged[b]
            self._release(b)
            if len(staged) == 1:
                yield b, staged[0]
                continue
            folded = self._fold(staged)
            if folded is None:
                for p in staged:
                    yield b, p
                continue
            yield b, folded
        self._staged = {}

    def _fold(self, staged: List[MicroPartition]) -> Optional[MicroPartition]:
        from ..errors import DaftTransientError

        in_rows = sum(len(p) for p in staged)
        try:
            merged = (MicroPartition.concat(staged) if len(staged) > 1
                      else staged[0])
            out = merged.agg(self.aggs, self.keys)
            if out.schema != merged.schema:
                return None  # fold not schema-closed at runtime: abandon
        except DaftTransientError:
            # a transient merge failure (e.g. the sketch.merge fault site)
            # keeps its engine-wide contract — surface to the caller, the
            # same outcome the reduce-side merge would have had; only fold
            # INFEASIBILITY degrades to raw appends
            raise
        except Exception:
            return None
        if len(out) > ABANDON_MIN_SHRINK * in_rows:
            # the fold barely shrank anything — grouping keys are
            # near-unique, so keeping the partial would just accumulate the
            # whole bucket un-spillably; treat as infeasible
            return None
        if self.stats is not None:
            self.stats.bump("exchange_precombined_rows",
                            max(0, in_rows - len(out)))
        return out
