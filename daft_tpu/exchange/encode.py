# daftlint: migrated
"""Encoded exchange payloads: dictionary-encode low-cardinality columns of
fanout buckets BEFORE they enter the spillable PartitionBuffer.

A shuffle bucket holds pieces of many source partitions until the reduce
side merges them; those pieces are what the memory ledger charges and what
spills to disk under a budget. Low-cardinality columns (join keys against
small dimensions, flags, dates, region/status strings) dictionary-encode
to a fraction of their raw width, so both the engine-held bytes and the
spilled IPC bytes shrink — arrow IPC writes dictionary arrays natively, so
a spilled encoded bucket stays encoded on disk (spill.py's writer accepts
the encoded arrow payload via the ``encoded_payload`` task hook).

Per-column cardinality sampling skips hostile columns: a prefix sample's
distinct count must stay under SAMPLE_MAX_RATIO of the sample, and the
encoded column must actually be smaller than the raw one, or the column
ships raw. A piece where no column wins ships fully raw (``None`` from
:func:`encode_exchange_partition`).

Decode happens exactly once, at reduce-merge: the encoded piece is an
UNLOADED MicroPartition whose task materializes by decoding — the same
lazy contract spilled partitions already follow, so drain/readahead/concat
all compose unchanged. Any failure while encoding (including the
``exchange.encode`` fault site) degrades to the raw piece — never a query
failure. Results are byte-identical with ``exchange_payload_encoding``
off: dictionary round-trips are exact.
"""

from __future__ import annotations

from typing import List, Optional

from ..micropartition import MicroPartition

# pieces below this many rows are not worth the encode pass
ENCODE_MIN_ROWS = 64
# cardinality sampling: prefix sample size and the distinct/sample ratio
# above which a column is hostile (near-unique) and ships raw
SAMPLE_ROWS = 1024
SAMPLE_MAX_RATIO = 0.5


class EncodedExchangeTask:
    """Scan-task-shaped holder for one encoded exchange piece: an arrow
    table whose low-cardinality columns are dictionary-encoded, plus the
    engine schema to decode back into. ``read()`` is the decode (runs at
    reduce-merge or unspill); ``encoded_payload()`` is the spill writer's
    hook for writing the encoded representation to disk as-is."""

    def __init__(self, atbl, schema, raw_bytes: int,
                 crc: Optional[int] = None, stats=None):
        self._atbl = atbl
        self.schema = schema
        self.raw_bytes = raw_bytes
        # end-to-end integrity: crc32 over the encoded table's buffer
        # bytes, recorded at encode and re-verified at decode (None =
        # checksums off). The spill round-trip is covered separately by
        # the spill file's own checksum.
        self.crc = crc
        self._rt_stats = stats
        self.stats = None  # scan-task TableStats surface (none)

    # encoded pieces cross process boundaries (dist transport, multihost
    # transport-shuffle): the per-query RuntimeStats handle holds thread
    # locks and must not ride along — the crc does, so the receiving
    # process still verifies (only the counter bump is driver-local)
    def __getstate__(self):
        state = dict(self.__dict__)
        state["_rt_stats"] = None
        return state

    def __setstate__(self, state):
        self.__dict__.update(state)

    # --- ScanTask metadata surface used by MicroPartition ----------------
    @property
    def materialized_schema(self):
        return self.schema

    def num_rows(self) -> Optional[int]:
        return self._atbl.num_rows

    def size_bytes(self) -> Optional[int]:
        return self._atbl.nbytes

    def read(self):
        """Decode back to an engine Table with the exact original dtypes
        (verifying the encode-time checksum first, so a damaged payload
        raises DaftCorruptionError instead of decoding garbage)."""
        import pyarrow as pa

        from ..series import Series
        from ..table import Table

        if self.crc is not None:
            from ..errors import DaftCorruptionError
            from ..integrity.checksum import crc32_table

            got = crc32_table(self._atbl)
            if got != self.crc:
                if self._rt_stats is not None:
                    self._rt_stats.bump("corruption_detected")
                raise DaftCorruptionError(
                    f"encoded exchange piece failed its integrity check "
                    f"(crc {got:#010x} != {self.crc:#010x}, "
                    f"rows={self._atbl.num_rows})")
        cols = []
        for f, name in zip(self.schema, self._atbl.column_names):
            arr = self._atbl.column(name)
            if isinstance(arr, pa.ChunkedArray):
                arr = arr.combine_chunks()
            if pa.types.is_dictionary(arr.type):
                arr = arr.dictionary_decode()
            cols.append(Series.from_arrow(arr, f.name, f.dtype))
        return Table(self.schema, cols)

    def read_chunks(self) -> List:
        return [self.read()]

    def encoded_payload(self) -> List:
        """The encoded arrow tables for the spill writer (IPC preserves
        dictionary encoding, so spilled exchange bytes shrink too)."""
        return [self._atbl]

    # head()/select on unloaded partitions route through pushdowns; exchange
    # pieces never see them in practice, but keep the surface total
    @property
    def pushdowns(self):
        from ..io.scan import Pushdowns

        return Pushdowns()

    def with_pushdowns(self, pd):
        from ..spill import _SpillSlotView

        return _SpillSlotView(self, pd)

    def __repr__(self) -> str:
        return (f"EncodedExchangeTask(rows={self._atbl.num_rows}, "
                f"bytes={self._atbl.nbytes}/{self.raw_bytes})")


def _encode_column(arr):
    """Dictionary-encode one arrow array when sampling says it pays;
    returns the encoded array or None (ship raw)."""
    import pyarrow as pa
    import pyarrow.compute as pc

    t = arr.type
    if (pa.types.is_dictionary(t) or pa.types.is_nested(t)
            or pa.types.is_null(t)):
        return None
    n = len(arr)
    sample = arr.slice(0, min(n, SAMPLE_ROWS))
    try:
        distinct = pc.count_distinct(sample).as_py() or 0
    except Exception:
        return None  # dtype without a distinct kernel: hostile by default
    if distinct > max(16, int(len(sample) * SAMPLE_MAX_RATIO)):
        return None
    enc = arr.dictionary_encode()
    if enc.nbytes >= arr.nbytes:
        return None  # sampling lied (hostile tail): keep raw
    return enc


def encode_exchange_partition(part: MicroPartition, stats=None,
                              integrity: bool = True
                              ) -> Optional[MicroPartition]:
    """Encode one fanout piece; returns the encoded (unloaded, lazily
    decoding) MicroPartition, or None when the piece is too small, has no
    winning column, or holds python-typed data. Raises only for the
    caller's fault-degradation contract (the ShuffleOp wraps this in a
    catch that falls back to the raw piece)."""
    import pyarrow as pa

    from .. import faults

    n = part.num_rows_or_none() or 0
    if n < ENCODE_MIN_ROWS:
        return None
    faults.check("exchange.encode", stats)
    tbl = part.table()
    arrays = []
    won = False
    for s in tbl.columns():
        if s.is_python():
            return None  # no arrow representation: piece ships raw
        arr = s.to_arrow()
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.combine_chunks()
        enc = _encode_column(arr)
        if enc is not None:
            won = True
            arrays.append(enc)
        else:
            arrays.append(arr)
    if not won:
        return None
    atbl = pa.Table.from_arrays(
        arrays, names=[f.name for f in tbl.schema])
    raw = tbl.size_bytes()
    if atbl.nbytes >= raw:
        return None
    crc = None
    if integrity:
        from ..integrity.checksum import crc32_table

        crc = crc32_table(atbl)
    task = EncodedExchangeTask(atbl, part.schema, raw, crc=crc, stats=stats)
    out = MicroPartition.from_scan_task(task)
    out.owner_process = part.owner_process
    # the encoded piece decodes to exactly the raw piece, so the raw
    # piece's lineage recipe (if any) re-derives this one too
    out.lineage_recipe = part.lineage_recipe
    return out
