# daftlint: migrated
"""Runtime join filters: sideways information passing across the exchange.

The co-partitioned hash join shuffles BOTH sides' full raw rows even when
the build side is selective — q3/q5's worst host-path cost (ROADMAP item
4). This module builds a Bloom + min-max filter from the build side's join
keys while they stream through their own exchange, and the probe side's
ShuffleOp (or the BroadcastJoinOp probe stream) prunes non-qualifying rows
BEFORE bucketing, spill, and merge.

Design contract:

- **False-positive tolerant.** The filter only ever *keeps* extra rows;
  the join itself re-checks every surviving row, so correctness never
  depends on the filter. False *negatives* are engineered away: hashes are
  computed over key columns cast to the SAME unified dtype the join's key
  alignment uses, NaN float keys bypass the filter entirely (bit-pattern
  hashing cannot be trusted for them), and null keys are pruned only for
  join types where a null probe key provably never reaches the output.
- **Byte-identical with the knob off.** Pruning drops whole rows before
  the row-local bucket split; surviving rows keep their relative order,
  and the engine's joins emit deterministic (left-index, right-index)
  order — so query results are identical with ``runtime_join_filters``
  on or off.
- **Fails open.** Any failure while building or probing (including the
  ``join.filter`` fault site) degrades to the unfiltered exchange — never
  a query failure.

The probe has a vectorized host numpy path; when device kernels are
enabled and the partition clears ``device_min_rows``, the Bloom gathers
run as one jit program behind the device circuit breaker
(``probe_bits_device``), with the host path as the breaker fallback.
"""

from __future__ import annotations

import functools
from typing import Any, List, Optional, Tuple

import numpy as np

from ..errors import DaftInternalError

# Bloom geometry: bits = next_pow2(rows * BITS_PER_KEY) clamped to
# [MIN_BITS, MAX_BITS]; PROBES probes per key via Kirsch-Mitzenmacher
# double hashing (h1 + i*h2). 8 bits/key x 4 probes ~ 2.4% false-positive
# rate — plenty for a pre-exchange prune whose misses the join re-checks.
BLOOM_BITS_PER_KEY = 8
BLOOM_PROBES = 4
BLOOM_MIN_BITS = 1 << 13
BLOOM_MAX_BITS = 1 << 23
# a build side past this many rows abandons the filter: the accumulated
# hash arrays (16 B/row across both seeds) and the prune win both stop
# being worth it when the "small" side is this large
MAX_BUILD_ROWS = 1 << 22

# second hash seed for the probe stride (any odd constant unrelated to the
# bucket hash seed 0 works; splitmix64's increment is conventional)
_H2_SEED = 0x9E3779B97F4A7C15

# join types whose PROBE side may be pruned, by (how, probe_is_right):
# inner/semi — either side is prunable (dropped probe rows can only be
# non-matching, and non-matching probe rows never reach the output);
# left — only the right side (unmatched right rows are dropped anyway);
# right/anti/outer — the probe side's unmatched rows ARE output: decline.
PRUNABLE = {("inner", True), ("inner", False),
            ("semi", True), ("semi", False),
            ("left", True)}


def prunable(how: str, probe_is_right: bool) -> bool:
    """Whether the probe side of a `how` join may be pruned by a filter
    built from the other side's keys (see PRUNABLE)."""
    return (how, probe_is_right) in PRUNABLE


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p <<= 1
    return p


def _unified_key_dtypes(build_on, probe_on, build_schema, probe_schema):
    """The join's key-alignment dtypes (same unify the hash join applies),
    or None when any pair cannot unify / is python-typed — the filter must
    hash both sides in identical representations or a dtype-width mismatch
    would silently hash the same value to different bits (a false
    negative, i.e. a wrong prune)."""
    from ..datatypes import try_unify

    out = []
    for be, pe in zip(build_on, probe_on):
        try:
            bdt = be._node.to_field(build_schema).dtype
            pdt = pe._node.to_field(probe_schema).dtype
        except Exception:
            return None
        u = try_unify(bdt, pdt)
        if u is None or u.is_python():
            return None
        out.append(u)
    return out


def _key_arrays(tbl, key_exprs, dtypes):
    """Evaluate the key expressions over one table and cast to the unified
    dtypes; returns the arrow arrays (one per key)."""
    cols = []
    for e, dt in zip(key_exprs, dtypes):
        s = e._node.evaluate(tbl)
        if s.is_python():
            raise DaftInternalError("python-typed join key in filter path")
        if len(s) != len(tbl):
            # literal/scalar key: broadcast via the table row count
            from ..table import _broadcast_series

            s = _broadcast_series(s, len(tbl))
        if s.dtype != dt:
            s = s.cast(dt)
        cols.append(s.to_arrow())
    return cols


def _hash_pair(cols) -> Tuple[np.ndarray, np.ndarray]:
    """(h1, h2) uint64 row hashes over the unified key columns — h1 seeds
    from 0 (the same family the bucket hash uses), h2 from an independent
    constant, giving the Kirsch-Mitzenmacher probe stride."""
    from ..kernels.host_hash import hash_table_columns

    return (hash_table_columns(cols, seed=0),
            hash_table_columns(cols, seed=_H2_SEED))


class RuntimeJoinFilter:
    """A sealed, immutable Bloom + min-max filter over build-side keys."""

    __slots__ = ("table", "nbits", "minmax", "dtypes", "build_rows",
                 "_device_bits")

    def __init__(self, table: np.ndarray, minmax: List[Optional[Tuple[Any, Any]]],
                 dtypes, build_rows: int):
        self.table = table  # bool[nbits], nbits a power of two
        self.nbits = len(table)
        self.minmax = minmax  # per key column: (lo, hi) or None
        self.dtypes = dtypes
        self.build_rows = build_rows
        self._device_bits = None  # lazily staged uint8 copy for the jit path

    # ------------------------------------------------------------- probing
    def keep_mask(self, tbl, key_exprs, ctx=None) -> np.ndarray:
        """Boolean keep-mask over ``tbl``'s rows: False rows provably
        cannot match any build-side key (up to the documented NaN bypass).
        ``ctx`` (an ExecutionContext) routes the Bloom gathers through the
        device path when eligible."""
        import pyarrow as pa
        import pyarrow.compute as pc

        n = len(tbl)
        if n == 0:
            return np.zeros(0, dtype=bool)
        cols = _key_arrays(tbl, key_exprs, self.dtypes)
        valid = np.ones(n, dtype=bool)
        bypass = np.zeros(n, dtype=bool)
        rng_ok = np.ones(n, dtype=bool)
        for arr, dt, mm in zip(cols, self.dtypes, self.minmax):
            if arr.null_count:
                valid &= np.asarray(pc.is_valid(arr), dtype=bool)
            if pa.types.is_floating(arr.type):
                # NaN keys: bit-pattern hashing can't be trusted (and the
                # join's own NaN semantics are the arbiter) — bypass
                nanmask = pc.is_nan(arr)
                if arr.null_count:
                    nanmask = pc.fill_null(nanmask, False)
                bypass |= np.asarray(nanmask, dtype=bool)
            elif mm is not None:
                lo, hi = mm
                inr = pc.and_kleene(
                    pc.greater_equal(arr, pa.scalar(lo, type=arr.type)),
                    pc.less_equal(arr, pa.scalar(hi, type=arr.type)))
                rng_ok &= np.asarray(pc.fill_null(inr, False), dtype=bool)
        h1, h2 = _hash_pair(cols)
        hit = self._bloom_hits(h1, h2, ctx)
        # null keys never match for the prunable join types; NaN bypasses
        return valid & (bypass | (hit & rng_ok))

    def _bloom_hits(self, h1: np.ndarray, h2: np.ndarray, ctx) -> np.ndarray:
        mask = np.uint64(self.nbits - 1)
        idx = np.empty((BLOOM_PROBES, len(h1)), dtype=np.int32)
        h = h1.copy()
        for i in range(BLOOM_PROBES):
            idx[i] = (h & mask).astype(np.int32)
            h += h2
        dev = self._bloom_hits_device(idx, ctx)
        if dev is not None:
            return dev
        out = self.table[idx[0]]
        for i in range(1, BLOOM_PROBES):
            out &= self.table[idx[i]]
        return out

    def _bloom_hits_device(self, idx: np.ndarray, ctx) -> Optional[np.ndarray]:
        """One jit program for the k Bloom gathers + AND reduction, behind
        the device circuit breaker. None = take the host path (ineligible,
        breaker open, or the attempt failed and was recorded)."""
        if ctx is None or not getattr(ctx.cfg, "use_device_kernels", False):
            return None
        if idx.shape[1] < getattr(ctx.cfg, "device_min_rows", 4096):
            return None

        def _run():
            out = probe_bits_device(self._staged_bits(), idx)
            return np.asarray(out, dtype=bool)

        out = ctx._device_attempt(_run)
        if out is not None:
            ctx.stats.bump("join_filter_device_probes")
        return out

    def _staged_bits(self) -> np.ndarray:
        if self._device_bits is None:
            self._device_bits = self.table.astype(np.uint8)
        return self._device_bits


@functools.lru_cache(maxsize=1)
def _probe_jitted():
    """The jitted Bloom-probe program, built once: jax's trace cache is
    keyed on the function object, so the callable must outlive the call
    (a per-call closure would retrace+recompile on EVERY pruned
    partition)."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def _probe(bits, ix):
        g = jnp.take(bits, ix, axis=0)  # [k, n] uint8
        return jnp.min(g, axis=0).astype(jnp.bool_)

    return _probe


def probe_bits_device(bits_u8: np.ndarray, idx: np.ndarray):
    """jit'd Bloom membership: gather the k probe positions per row and
    AND-reduce — the whole probe is one device program, compiled once per
    (bits, idx) shape via the module-lived jitted callable."""
    import jax
    import jax.numpy as jnp

    fn = _probe_jitted()
    return jax.device_get(fn(jnp.asarray(bits_u8), jnp.asarray(idx)))


def prune_partition(part, jf: RuntimeJoinFilter, key_exprs, ctx):
    """Prune one probe-side partition with a sealed filter. Fail-open:
    ALWAYS returns a usable partition — the input itself on any failure
    (including the ``join.filter`` fault site). Counters:
    ``join_filter_probe_rows`` (rows inspected) and
    ``join_filter_rows_pruned`` (rows dropped pre-exchange)."""
    from .. import faults
    from ..micropartition import MicroPartition
    from ..series import Series

    try:
        faults.check("join.filter", ctx.stats)
        tabs = part.chunk_tables()
        kept, before, after = [], 0, 0
        for t in tabs:
            nt = len(t)
            before += nt
            if nt == 0:
                continue
            mask = jf.keep_mask(t, key_exprs, ctx)
            if mask.all():
                kept.append(t)
                after += nt
                continue
            ft = t.filter_with_mask(Series.from_numpy(mask, "keep"))
            after += len(ft)
            if len(ft):
                kept.append(ft)
    except Exception:
        ctx.stats.bump("join_filter_errors")
        return part
    ctx.stats.bump("join_filter_probe_rows", before)
    if before != after:
        ctx.stats.bump("join_filter_rows_pruned", before - after)
    if after == before:
        return part
    out = (MicroPartition(part.schema, tables=kept) if kept
           else MicroPartition.empty(part.schema))
    out.owner_process = part.owner_process
    return out


class JoinFilterBuilder:
    """Accumulates build-side key batches; ``seal()`` freezes the filter.

    Hashes are buffered per batch (16 B/row) and the bit table is sized
    once the true build row count is known; past MAX_BUILD_ROWS the
    builder abandons (returns None at seal) rather than ballooning."""

    def __init__(self, key_exprs, dtypes):
        self.key_exprs = list(key_exprs)
        self.dtypes = list(dtypes)
        self._h1: List[np.ndarray] = []
        self._h2: List[np.ndarray] = []
        self._minmax: List[Optional[Tuple[Any, Any]]] = [None] * len(dtypes)
        self._mm_dead: List[bool] = [False] * len(dtypes)
        self._rows = 0
        self._abandoned = False

    def add(self, tbl) -> None:
        """Fold one build-side table's keys into the filter state."""
        import pyarrow as pa
        import pyarrow.compute as pc

        if self._abandoned or len(tbl) == 0:
            return
        self._rows += len(tbl)
        if self._rows > MAX_BUILD_ROWS:
            self._abandoned = True
            self._h1.clear()
            self._h2.clear()
            return
        cols = _key_arrays(tbl, self.key_exprs, self.dtypes)
        h1, h2 = _hash_pair(cols)
        self._h1.append(h1)
        self._h2.append(h2)
        for j, arr in enumerate(cols):
            if self._mm_dead[j] or pa.types.is_floating(arr.type):
                # float min-max would have to reason about NaN ordering;
                # the Bloom leg still covers floats
                self._mm_dead[j] = True
                continue
            if arr.null_count == len(arr):
                continue
            try:
                mm = pc.min_max(arr)
                lo, hi = mm["min"].as_py(), mm["max"].as_py()
            except Exception:
                self._mm_dead[j] = True
                continue
            cur = self._minmax[j]
            if cur is None:
                self._minmax[j] = (lo, hi)
            else:
                self._minmax[j] = (min(cur[0], lo), max(cur[1], hi))

    def seal(self) -> Optional[RuntimeJoinFilter]:
        if self._abandoned:
            return None
        nbits = _next_pow2(max(self._rows * BLOOM_BITS_PER_KEY,
                               BLOOM_MIN_BITS))
        nbits = min(nbits, BLOOM_MAX_BITS)
        table = np.zeros(nbits, dtype=bool)
        mask = np.uint64(nbits - 1)
        for h1, h2 in zip(self._h1, self._h2):
            h = h1.copy()
            for _ in range(BLOOM_PROBES):
                table[(h & mask).astype(np.int64)] = True
                h += h2
        minmax = [None if dead else mm
                  for mm, dead in zip(self._minmax, self._mm_dead)]
        return RuntimeJoinFilter(table, minmax, self.dtypes, self._rows)


class JoinFilterSlot:
    """Translate-time rendezvous between the build side's exchange and the
    probe side's: the build-side ShuffleOp feeds every streamed partition
    into a builder and seals once its input stream is exhausted (the build
    side is fully drained before the probe side's exchange runs — the
    join op's pull order guarantees it); the probe-side ShuffleOp asks
    ``filter()`` and prunes
    when a sealed filter exists. Unsealed/abandoned/failed -> None -> the
    probe runs unfiltered."""

    def __init__(self, build_on, probe_on, build_schema, probe_schema,
                 how: str):
        self.build_on = list(build_on)
        self.probe_on = list(probe_on)
        self.how = how
        self.dtypes = _unified_key_dtypes(build_on, probe_on,
                                          build_schema, probe_schema)
        self._builder: Optional[JoinFilterBuilder] = None
        self._filter: Optional[RuntimeJoinFilter] = None
        self._sealed = False

    @property
    def eligible(self) -> bool:
        return self.dtypes is not None

    def begin(self) -> None:
        """Reset for a (re-)execution of the build side."""
        self._builder = (JoinFilterBuilder(self.build_on, self.dtypes)
                         if self.eligible else None)
        self._filter = None
        self._sealed = False

    def feed(self, tbl) -> None:
        if self._builder is not None:
            self._builder.add(tbl)

    def abandon(self) -> None:
        self._builder = None
        self._filter = None
        self._sealed = True

    def seal(self) -> None:
        if self._builder is not None:
            self._filter = self._builder.seal()
            self._builder = None
        self._sealed = True

    def filter(self) -> Optional[RuntimeJoinFilter]:
        return self._filter if self._sealed else None
