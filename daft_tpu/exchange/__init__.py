# daftlint: migrated
"""Exchange v2: the reduction/encoding pipeline of the all-to-all exchange.

HPTMT's operator-based architecture (PAPERS.md) argues the exchange should
be a first-class operator with its own reduction pipeline rather than a
dumb row mover. This package holds the three legs, each behind its own
``ExecutionConfig`` knob (default on) and each carrying the hard invariant
*results are byte-identical with the knob off*:

- :mod:`joinfilter` — runtime join filters (sideways information passing):
  a Bloom + min-max filter built from the join build side's keys prunes
  probe-side rows BEFORE they are bucketed, spilled, or merged
  (``cfg.runtime_join_filters``);
- :mod:`encode` — dictionary-encoded exchange payloads: low-cardinality
  columns of fanout buckets shrink before they enter the spillable
  PartitionBuffer, and decode only at reduce-merge
  (``cfg.exchange_payload_encoding``);
- :mod:`combine` — hierarchical exchange: map-side pieces headed to the
  same destination fold through the stage-2 combine BEFORE the exchange
  (intra-host combine -> inter-host all_to_all, mirrored on the mesh path
  in parallel/mesh_exec.py) (``cfg.hierarchical_exchange_combine``).

Correctness never depends on any leg: the join filter is false-positive-
tolerant (the join itself re-checks), a failed filter build or payload
encode (fault sites ``join.filter`` / ``exchange.encode``) degrades to the
unfiltered/unencoded exchange, and the combine is gated to schema-closed
decomposable merge stages.
"""

from .combine import BucketCombiner, combine_spec_applicable
from .encode import EncodedExchangeTask, encode_exchange_partition
from .joinfilter import JoinFilterBuilder, JoinFilterSlot, RuntimeJoinFilter

__all__ = [
    "BucketCombiner",
    "combine_spec_applicable",
    "EncodedExchangeTask",
    "encode_exchange_partition",
    "JoinFilterBuilder",
    "JoinFilterSlot",
    "RuntimeJoinFilter",
]
