"""Series: a named, typed column.

Host storage is a single-chunk Arrow array of the logical type's physical arrow mapping
(Arrow C++ is the host kernel library, standing in for the reference's arrow2/daft-core
kernels, src/daft-core/src/series/mod.rs:29). A parallel device path stages numeric
columns as jax arrays (see daft_tpu/kernels/device.py). Python-object columns are stored
as numpy object arrays.
"""

from __future__ import annotations

from typing import Any, Iterable, List, Optional, Sequence

import numpy as np
import pyarrow as pa
import pyarrow.compute as pc

from .datatypes import DataType, TypeKind, infer_datatype, try_unify
from .kernels.host_hash import hash_array


def _stable_value_bytes(v) -> bytes:
    """Cross-process-stable byte representation of a python value for
    hashing. A plain pickle is NOT enough: set/frozenset iteration order
    follows per-process-randomized string hashing, and two ==-equal dicts
    can differ in insertion order — either would bucket the same value
    differently on different worker processes. Containers canonicalize
    recursively (sets/dict items sorted by their own stable bytes);
    opaque leaves pickle at a FIXED protocol so driver and workers agree
    regardless of interpreter defaults. Raises for unpicklable leaves
    (the caller maps that to DaftValueError)."""
    import pickle

    if isinstance(v, (set, frozenset)):
        return b"S(" + b",".join(
            sorted(_stable_value_bytes(x) for x in v)) + b")"
    if isinstance(v, dict):
        items = sorted((_stable_value_bytes(k), _stable_value_bytes(x))
                       for k, x in v.items())
        return b"D(" + b",".join(k + b":" + x for k, x in items) + b")"
    if isinstance(v, (list, tuple)):
        tag = b"L(" if isinstance(v, list) else b"T("
        return tag + b",".join(_stable_value_bytes(x) for x in v) + b")"
    return pickle.dumps(v, protocol=4)


class Series:
    __slots__ = ("_name", "_dtype", "_arrow", "_pyobjs")

    def __init__(self, name: str, dtype: DataType, arrow: Optional[pa.Array], pyobjs: Optional[np.ndarray] = None):
        self._name = name
        self._dtype = dtype
        self._arrow = arrow
        self._pyobjs = pyobjs  # numpy object array when dtype is python

    # ------------------------------------------------------------------ ctors
    @staticmethod
    def from_arrow(arr, name: str = "arrow_series", dtype: Optional[DataType] = None) -> "Series":
        if isinstance(arr, pa.ChunkedArray):
            arr = arr.chunk(0) if arr.num_chunks == 1 else arr.combine_chunks()
        if isinstance(arr, pa.Scalar):
            arr = pa.array([arr.as_py()], type=arr.type)
        if pa.types.is_dictionary(arr.type):
            arr = arr.dictionary_decode()
        inferred = DataType.from_arrow(arr.type)
        if dtype is None:
            dtype = inferred
        else:
            # Canonical storage for every logical dtype is dtype.to_arrow() — temporal
            # logical types keep real arrow temporal storage in all construction paths.
            target = dtype.to_arrow() if not dtype.is_python() else None
            if target is not None and arr.type != target:
                arr = arr.cast(target)
        if dtype.is_string() and not pa.types.is_large_string(arr.type):
            arr = arr.cast(pa.large_string())
        if dtype.kind == TypeKind.BINARY and not pa.types.is_large_binary(arr.type):
            arr = arr.cast(pa.large_binary())
        return Series(name, dtype, arr)

    @staticmethod
    def from_pylist(data: Sequence[Any], name: str = "list_series", dtype: Optional[DataType] = None) -> "Series":
        inferred = dtype is None
        if inferred:
            dt = DataType.null()
            for v in data:
                nxt = infer_datatype(v)
                u = try_unify(dt, nxt)
                if u is None:
                    dt = DataType.python()
                    break
                dt = u
            dtype = dt
        if dtype.is_python():
            return _python_object_series(name, data)
        try:
            arr = pa.array(data, type=dtype.to_arrow())
        except (pa.ArrowInvalid, pa.ArrowTypeError, pa.ArrowNotImplementedError,
                TypeError, OverflowError) as e:
            # an EXPLICITLY requested dtype keeps the original contract:
            # arrow conversion errors fall back to python storage, but
            # python-level failures (overflow of the requested type, ...)
            # propagate rather than silently ignoring the request
            if not inferred and isinstance(e, (TypeError, OverflowError)):
                raise
            # numpy scalars can defeat arrow's sequence converter (e.g. a
            # list holding np.datetime64[D] raises TypeError even with an
            # explicit date32 type): normalize them to python values first
            try:
                cleaned = [v.item() if isinstance(v, np.generic) else v
                           for v in data]
                arr = pa.array(cleaned, type=dtype.to_arrow())
            except Exception:
                return _python_object_series(name, data)
        return Series(name, dtype, arr)

    @staticmethod
    def from_numpy(arr: np.ndarray, name: str = "numpy_series", dtype: Optional[DataType] = None) -> "Series":
        if arr.dtype == object:
            return Series.from_pylist(list(arr), name, dtype)
        if arr.ndim == 1:
            pa_arr = pa.array(arr)
            return Series.from_arrow(pa_arr, name, dtype)
        if arr.ndim >= 2:
            inner = DataType.from_arrow(pa.from_numpy_dtype(arr.dtype))
            shape = arr.shape[1:]
            dt = dtype or DataType.tensor(inner, shape)
            n = 1
            for s in shape:
                n *= s
            flat = pa.FixedSizeListArray.from_arrays(pa.array(arr.reshape(-1)), n)
            return Series(name, dt, flat)
        raise ValueError("cannot create Series from 0-d array")

    @staticmethod
    def from_pandas(s, name: Optional[str] = None, dtype: Optional[DataType] = None) -> "Series":
        arr = pa.Array.from_pandas(s)
        return Series.from_arrow(arr, name or (s.name or "pd_series"), dtype)

    @staticmethod
    def empty(name: str, dtype: DataType) -> "Series":
        if dtype.is_python():
            return Series(name, dtype, None, np.empty(0, dtype=object))
        return Series(name, dtype, pa.array([], type=dtype.to_arrow()))

    @staticmethod
    def full_null(name: str, dtype: DataType, length: int) -> "Series":
        if dtype.is_python():
            return Series(name, dtype, None, np.full(length, None, dtype=object))
        return Series(name, dtype, pa.nulls(length, type=dtype.to_arrow()))

    # ------------------------------------------------------------------ basics
    @property
    def name(self) -> str:
        return self._name

    def datatype(self) -> DataType:
        return self._dtype

    @property
    def dtype(self) -> DataType:
        return self._dtype

    def rename(self, name: str) -> "Series":
        return Series(name, self._dtype, self._arrow, self._pyobjs)

    def __len__(self) -> int:
        return len(self._pyobjs) if self._arrow is None else len(self._arrow)

    def is_python(self) -> bool:
        return self._dtype.is_python()

    def to_arrow(self) -> pa.Array:
        if self._arrow is None:
            raise ValueError("Python-object Series has no arrow representation")
        return self._arrow

    def arrow_or_none(self) -> Optional[pa.Array]:
        return self._arrow

    def to_pylist(self) -> List[Any]:
        if self._arrow is None:
            return list(self._pyobjs)
        return self._arrow.to_pylist()

    def to_numpy(self) -> np.ndarray:
        if self._arrow is None:
            return self._pyobjs
        if self._dtype.kind in (TypeKind.FIXED_SHAPE_TENSOR, TypeKind.EMBEDDING, TypeKind.FIXED_SHAPE_IMAGE):
            arr = self._arrow
            shape = _static_shape(self._dtype)
            size = int(np.prod(shape)) if shape else 1
            # .values keeps slots behind null rows (flatten() would drop them)
            child = arr.values.slice(arr.offset * size, len(arr) * size)
            if child.null_count:
                fill = np.nan if pa.types.is_floating(child.type) else 0
                child = pc.fill_null(child, fill)
            flat = np.asarray(child).reshape((len(self),) + shape)
            if arr.null_count:
                out = np.empty(len(self), dtype=object)
                valid = np.asarray(pc.is_valid(arr))
                for i in range(len(self)):
                    out[i] = flat[i] if valid[i] else None
                return out
            return flat
        try:
            return self._arrow.to_numpy(zero_copy_only=False)
        except pa.ArrowInvalid:
            return np.array(self._arrow.to_pylist(), dtype=object)

    def null_count(self) -> int:
        if self._arrow is None:
            return int(sum(v is None for v in self._pyobjs))
        return self._arrow.null_count

    def size_bytes(self) -> int:
        if self._arrow is None:
            return int(self._pyobjs.nbytes) + 64 * len(self._pyobjs)
        return self._arrow.nbytes

    def __repr__(self) -> str:
        vals = self.to_pylist()
        preview = ", ".join(repr(v) for v in vals[:8]) + (", …" if len(vals) > 8 else "")
        return f"Series[{self._name}: {self._dtype!r}; {len(self)} rows]([{preview}])"

    # ------------------------------------------------------------------ casting
    def cast(self, dtype: DataType) -> "Series":
        if dtype == self._dtype:
            return self
        if dtype.is_python():
            objs = np.empty(len(self), dtype=object)
            for i, v in enumerate(self.to_pylist()):
                objs[i] = v
            return Series(self._name, dtype, None, objs)
        if self.is_python():
            return Series.from_pylist(self.to_pylist(), self._name, dtype)
        target = dtype.to_arrow()
        src = self._arrow
        opts = pc.CastOptions(target_type=target, allow_float_truncate=True, allow_time_truncate=True)
        try:
            out = pc.cast(src, options=opts)
        except (pa.ArrowInvalid, pa.ArrowNotImplementedError):
            if dtype.kind == TypeKind.FIXED_SHAPE_IMAGE and self._dtype.kind == TypeKind.IMAGE:
                from .multimodal import (_fixed_image_series, _mode_channels,
                                         _mode_np_dtype, image_series_to_arrays)

                mode, h, w = dtype.params
                want_c = _mode_channels(mode)
                want_np = _mode_np_dtype(mode)
                arrays = image_series_to_arrays(self)
                for a in arrays:
                    if a is None:
                        continue
                    if a.shape[:2] != (h, w):
                        raise ValueError(
                            f"cannot cast image of shape {a.shape} to fixed shape ({h}, {w})")
                    if a.shape[2] != want_c:
                        raise ValueError(
                            f"cannot cast {a.shape[2]}-channel image to mode {mode!r} "
                            f"({want_c} channels); convert with image.to_mode first")
                    if a.dtype != want_np:
                        raise ValueError(
                            f"cannot cast {a.dtype} image pixels to mode {mode!r} "
                            f"({np.dtype(want_np).name}); convert with image.to_mode first")
                return _fixed_image_series(arrays, self._name, mode, h, w)
            if dtype.kind == TypeKind.IMAGE and self._dtype.kind == TypeKind.FIXED_SHAPE_IMAGE:
                from .multimodal import image_series_from_arrays, image_series_to_arrays

                arrays = image_series_to_arrays(self)
                m = self._dtype.params[0]
                return image_series_from_arrays(arrays, self._name, [m] * len(arrays),
                                                dtype_mode=dtype.params[0])
            if dtype.is_string():
                out = pa.array([None if v is None else str(v) for v in src.to_pylist()], type=pa.large_string())
            elif dtype.is_temporal() and (self._dtype.is_integer() or self._dtype.is_floating()):
                # numeric -> temporal: interpret as epoch count in the target unit
                phys = src.cast(dtype.to_physical().to_arrow())
                out = phys.view(target) if phys.type.bit_width == target.bit_width else phys.cast(target)
            else:
                raise
        return Series(self._name, dtype, out)

    def _require_arrow(self, op: str) -> pa.Array:
        if self._arrow is None:
            raise ValueError(f"{op} is not supported for python-dtype Series (cast first)")
        return self._arrow

    # ------------------------------------------------------------------ arithmetic
    def _binary_numeric(self, other: "Series", fn, name=None, force_dtype: Optional[DataType] = None,
                        unify: bool = True) -> "Series":
        self._require_arrow("arithmetic")
        other._require_arrow("arithmetic")
        l, r = self, other
        if unify and l._dtype != r._dtype and all(
                d.is_numeric() or d.is_boolean() for d in (l._dtype, r._dtype)):
            # bool operands unify to the numeric side (reference binary_ops.rs:
            # (Boolean, numeric) -> numeric)
            u = try_unify(l._dtype, r._dtype)
            if u is not None and u.is_numeric():
                l, r = l.cast(u), r.cast(u)
        out = fn(*_binary_args(l, r))
        s = Series.from_arrow(out, name or self._name)
        if force_dtype is not None and s._dtype != force_dtype:
            s = s.cast(force_dtype)
        return s

    def __add__(self, other: "Series") -> "Series":
        other = _as_series(other)
        if self._dtype.is_string() or other._dtype.is_string():
            self._require_arrow("arithmetic")
            other._require_arrow("arithmetic")
            l, r = _broadcast(self, other)
            return Series.from_arrow(pc.binary_join_element_wise(
                l._arrow.cast(pa.large_string()), r._arrow.cast(pa.large_string()),
                pa.scalar("", pa.large_string())), self._name)
        self._check_temporal_arith("+", other)
        return self._binary_numeric(other, pc.add_checked)

    def __sub__(self, other):
        other = _as_series(other)
        self._check_temporal_arith("-", other)
        return self._binary_numeric(other, pc.subtract_checked)

    def _check_temporal_arith(self, op: str, other: "Series") -> None:
        """Mirror the planner's temporal-pair rules (reference binary_ops.rs:
        e.g. date - timestamp is illegal) — arrow's kernels are more
        permissive than the type system allows."""
        if self._dtype.is_temporal() or other._dtype.is_temporal():
            from .expressions import _temporal_arith_type

            _temporal_arith_type(op, self._dtype, other._dtype)  # raises if illegal

    def __mul__(self, other):
        return self._binary_numeric(_as_series(other), pc.multiply_checked)

    def __truediv__(self, other):
        other = _as_series(other)
        l, r = _broadcast(self.cast(DataType.float64()), other.cast(DataType.float64()))
        return Series.from_arrow(pc.divide(l._arrow, r._arrow), self._name)

    def __floordiv__(self, other):
        other = _as_series(other)
        l, r = _broadcast(self, other)
        if l._dtype.is_floating() or r._dtype.is_floating():
            return Series.from_arrow(pc.floor(pc.divide(l._arrow, r._arrow)), self._name)
        quot = pc.divide_checked(l._arrow, r._arrow)
        rem = pc.subtract_checked(l._arrow, pc.multiply_checked(quot, r._arrow))
        neg = pc.not_equal(pc.sign(l._arrow), pc.sign(r._arrow))
        adjust = pc.and_(neg, pc.not_equal(rem, pa.scalar(0, rem.type)))
        out = pc.if_else(adjust, pc.subtract_checked(quot, pa.scalar(1, quot.type)), quot)
        return Series.from_arrow(out, self._name)

    def __mod__(self, other):
        other = _as_series(other)
        l, r = _broadcast(self, other)
        la, ra = l._arrow, r._arrow
        if pa.types.is_floating(la.type) or pa.types.is_floating(ra.type):
            la = la.cast(pa.float64()); ra = ra.cast(pa.float64())
            ln, rn = np.asarray(pc.fill_null(la, np.nan)), np.asarray(pc.fill_null(ra, np.nan))
            out = pa.array(np.mod(ln, rn), from_pandas=True)
            out = pc.if_else(pc.and_kleene(pc.is_valid(la), pc.is_valid(ra)), out, pa.nulls(len(out), out.type))
            return Series.from_arrow(out, self._name)
        quot = pc.divide_checked(la, ra)
        rem = pc.subtract_checked(la, pc.multiply_checked(quot, ra))
        fix = pc.and_(pc.not_equal(rem, pa.scalar(0, rem.type)), pc.not_equal(pc.sign(la), pc.sign(ra)))
        out = pc.if_else(fix, pc.add_checked(rem, ra), rem)
        return Series.from_arrow(out, self._name)

    def __pow__(self, other):
        other = _as_series(other)
        l, r = _broadcast(self.cast(DataType.float64()), other.cast(DataType.float64()))
        return Series.from_arrow(pc.power(l._arrow, r._arrow), self._name)

    def __neg__(self):
        return Series.from_arrow(pc.negate_checked(self._arrow), self._name)

    def __abs__(self):
        return Series.from_arrow(pc.abs_checked(self._arrow), self._name)

    def left_shift(self, other):
        return self._binary_numeric(_as_series(other), pc.shift_left, unify=False)

    def right_shift(self, other):
        return self._binary_numeric(_as_series(other), pc.shift_right, unify=False)

    # ------------------------------------------------------------------ comparison
    def _cmp(self, other, fn) -> "Series":
        self._require_arrow("comparison")
        other = _as_series(other)
        other._require_arrow("comparison")
        l, r = self, other
        if l._arrow.type != r._arrow.type:
            # ISO-string side of a temporal comparison parses to the temporal
            # type (SQL semantics: date_col <= '1998-09-02')
            if l._dtype.is_temporal() and r._dtype.is_string():
                r = r.cast(l._dtype)
            elif r._dtype.is_temporal() and l._dtype.is_string():
                l = l.cast(r._dtype)
        if l._arrow.type != r._arrow.type:
            sup = try_unify(l._dtype, r._dtype)
            if sup is None:
                raise ValueError(f"cannot compare {l._dtype} with {r._dtype}")
            l = l.cast(sup)
            r = r.cast(sup)
        return Series.from_arrow(fn(*_binary_args(l, r)), self._name, DataType.bool())

    def __eq__(self, other):  # type: ignore[override]
        return self._cmp(other, pc.equal)

    def __ne__(self, other):  # type: ignore[override]
        return self._cmp(other, pc.not_equal)

    def __lt__(self, other):
        return self._cmp(other, pc.less)

    def __le__(self, other):
        return self._cmp(other, pc.less_equal)

    def __gt__(self, other):
        return self._cmp(other, pc.greater)

    def __ge__(self, other):
        return self._cmp(other, pc.greater_equal)

    def eq_null_safe(self, other):
        other = _as_series(other)
        l, r = _broadcast(self, other)
        eq = pc.fill_null(pc.equal(l._arrow, r._arrow), False)
        both_null = pc.and_(pc.is_null(l._arrow), pc.is_null(r._arrow))
        return Series.from_arrow(pc.or_(eq, both_null), self._name, DataType.bool())

    # ------------------------------------------------------------------ logical
    def _logical(self, other, kleene_fn, bit_fn) -> "Series":
        """Kleene logic on bools; bitwise form when both sides are integers
        (matching the planner: mixed bool/int pairs are rejected)."""
        other = _as_series(other)
        l, r = self, other
        if l._dtype.is_integer() and r._dtype.is_integer():
            if l._dtype != r._dtype:
                u = try_unify(l._dtype, r._dtype)
                if u is not None:
                    l, r = l.cast(u), r.cast(u)
            return Series.from_arrow(bit_fn(*_binary_args(l, r)), self._name)
        return Series.from_arrow(kleene_fn(*_binary_args(l, r)), self._name)

    def __and__(self, other):
        return self._logical(other, pc.and_kleene, pc.bit_wise_and)

    def __or__(self, other):
        return self._logical(other, pc.or_kleene, pc.bit_wise_or)

    def __xor__(self, other):
        return self._logical(other, pc.xor, pc.bit_wise_xor)

    def __invert__(self):
        return Series.from_arrow(pc.invert(self._arrow), self._name)

    # ------------------------------------------------------------------ null ops
    def is_null(self) -> "Series":
        if self._arrow is None:
            return Series.from_arrow(pa.array([v is None for v in self._pyobjs]), self._name)
        return Series.from_arrow(pc.is_null(self._arrow), self._name)

    def not_null(self) -> "Series":
        if self._arrow is None:
            return Series.from_arrow(pa.array([v is not None for v in self._pyobjs]), self._name)
        return Series.from_arrow(pc.is_valid(self._arrow), self._name)

    def fill_null(self, fill: "Series") -> "Series":
        fill = _as_series(fill)
        l, r = _broadcast(self, fill)
        return Series.from_arrow(pc.coalesce(l._arrow, r._arrow), self._name, self._dtype)

    def if_else(self, if_true: "Series", if_false: "Series") -> "Series":
        t = _as_series(if_true)
        f = _as_series(if_false)
        n = max(len(self), len(t), len(f))
        cond = _broadcast_to(self, n)
        t = _broadcast_to(t, n)
        f = _broadcast_to(f, n)
        sup = try_unify(t._dtype, f._dtype)
        if sup is None:
            raise ValueError(f"if_else branches have incompatible types {t._dtype} vs {f._dtype}")
        if sup.is_python():
            cm = cond.to_pylist()
            tv, fv = t.to_pylist(), f.to_pylist()
            return Series.from_pylist([None if c is None else (tv[i] if c else fv[i]) for i, c in enumerate(cm)],
                                      t._name, sup)
        out = pc.if_else(cond._arrow, t.cast(sup)._arrow, f.cast(sup)._arrow)
        return Series.from_arrow(out, t._name, sup)

    def is_in(self, items: "Series") -> "Series":
        items = _as_series(items)
        sup = try_unify(self._dtype, items._dtype)
        if sup is None:
            return Series.from_arrow(pa.array([False] * len(self)), self._name)
        lhs = self.cast(sup)
        out = pc.is_in(lhs._arrow, value_set=items.cast(sup)._arrow)
        out = pc.fill_null(out, False)
        out = pc.if_else(pc.is_valid(lhs._arrow), out, pa.nulls(len(out), pa.bool_()))
        return Series.from_arrow(out, self._name, DataType.bool())

    def between(self, lower, upper) -> "Series":
        lo = _as_series(lower)
        hi = _as_series(upper)
        return (self >= lo) & (self <= hi)

    # ------------------------------------------------------------------ selection
    def filter(self, mask: "Series") -> "Series":
        m = mask._arrow if isinstance(mask, Series) else pa.array(mask, type=pa.bool_())
        m = pc.fill_null(m, False)
        if self._arrow is None:
            keep = np.asarray(m)
            return Series(self._name, self._dtype, None, self._pyobjs[keep])
        return Series(self._name, self._dtype, self._arrow.filter(m))

    def take(self, indices: "Series") -> "Series":
        idx = indices._arrow if isinstance(indices, Series) else pa.array(indices)
        if self._arrow is None:
            ii = np.asarray(idx, dtype=np.int64)
            out = self._pyobjs[ii]
            return Series(self._name, self._dtype, None, out)
        return Series(self._name, self._dtype, self._arrow.take(idx))

    def slice(self, start: int, end: int) -> "Series":
        if self._arrow is None:
            return Series(self._name, self._dtype, None, self._pyobjs[start:end])
        return Series(self._name, self._dtype, self._arrow.slice(start, end - start))

    def head(self, n: int) -> "Series":
        return self.slice(0, min(n, len(self)))

    @staticmethod
    def concat(series_list: List["Series"]) -> "Series":
        if not series_list:
            raise ValueError("need at least one series to concat")
        first = series_list[0]
        dt = first._dtype
        for s in series_list[1:]:
            u = try_unify(dt, s._dtype)
            if u is None:
                raise ValueError(f"cannot concat {dt} with {s._dtype}")
            dt = u
        if dt.is_python():
            objs = np.concatenate([np.asarray(s.cast(dt)._pyobjs, dtype=object) for s in series_list])
            return Series(first._name, dt, None, objs)
        arrs = [s.cast(dt)._arrow for s in series_list]
        return Series(first._name, dt, pa.concat_arrays(arrs))

    # ------------------------------------------------------------------ sorting
    def argsort(self, descending: bool = False, nulls_first: Optional[bool] = None) -> "Series":
        self._require_arrow("argsort/sort")
        order = "descending" if descending else "ascending"
        placement = "at_start" if (nulls_first if nulls_first is not None else descending) else "at_end"
        idx = pc.array_sort_indices(self._arrow, order=order, null_placement=placement)
        return Series.from_arrow(idx.cast(pa.uint64()), self._name)

    def sort(self, descending: bool = False, nulls_first: Optional[bool] = None) -> "Series":
        return self.take(self.argsort(descending, nulls_first))

    # ------------------------------------------------------------------ hashing
    def hash(self, seed: Optional["Series"] = None) -> "Series":
        seeds = None
        if seed is not None:
            seeds = np.asarray(seed.cast(DataType.uint64())._arrow).astype(np.uint64)
        if self._arrow is None:
            # python-object columns hash STABLE bytes of the value, never
            # its repr: the default object.__repr__ embeds the memory
            # address, so the same value would bucket differently on
            # different worker processes and a distributed shuffle keyed on
            # such a column would silently mispartition.
            import zlib

            from .errors import DaftValueError

            vals = []
            for v in self._pyobjs:
                if v is None:
                    vals.append(None)
                    continue
                try:
                    buf = _stable_value_bytes(v)
                except Exception as e:
                    raise DaftValueError(
                        f"cannot hash unpicklable python object of type "
                        f"{type(v).__name__} in column {self._name!r}: a "
                        "cross-process-stable hash needs a stable byte "
                        f"representation ({e})") from e
                vals.append(zlib.crc32(buf))
            return Series.from_pylist(vals, self._name, DataType.uint64())
        h = hash_array(self._arrow, seed=seeds)
        return Series.from_arrow(pa.array(h), self._name, DataType.uint64())

    def murmur3_32(self) -> "Series":
        from .kernels.murmur import murmur3_32_arrow

        return Series.from_arrow(murmur3_32_arrow(self._arrow), self._name, DataType.int32())

    # ------------------------------------------------------------------ aggregations
    def _agg_arrow(self, fn_name: str, **kw):
        return pc.call_function(fn_name, [self._arrow], options=None) if not kw else None

    def count(self, mode: str = "valid") -> "Series":
        if self._arrow is None:
            n = len(self._pyobjs) if mode == "all" else int(sum(v is not None for v in self._pyobjs))
            return Series.from_pylist([n], self._name, DataType.uint64())
        n = len(self._arrow) if mode == "all" else len(self._arrow) - self._arrow.null_count
        if mode == "null":
            n = self._arrow.null_count
        return Series.from_pylist([n], self._name, DataType.uint64())

    def sum(self) -> "Series":
        out_dt = _sum_dtype(self._dtype)
        v = pc.sum(self._arrow)
        return Series.from_pylist([v.as_py()], self._name, out_dt)

    def mean(self) -> "Series":
        v = pc.mean(self._arrow)
        return Series.from_pylist([v.as_py()], self._name, DataType.float64())

    def stddev(self) -> "Series":
        v = pc.stddev(self._arrow, ddof=0)
        return Series.from_pylist([v.as_py()], self._name, DataType.float64())

    def min(self) -> "Series":
        v = pc.min(self._arrow)
        return Series.from_pylist([v.as_py()], self._name, self._dtype)

    def max(self) -> "Series":
        v = pc.max(self._arrow)
        return Series.from_pylist([v.as_py()], self._name, self._dtype)

    def any_value(self, ignore_nulls: bool = False) -> "Series":
        vals = self._arrow
        if vals is None:
            lst = [v for v in self._pyobjs if v is not None] if ignore_nulls else list(self._pyobjs)
            return Series.from_pylist(lst[:1] or [None], self._name, self._dtype)
        if ignore_nulls and vals.null_count:
            vals = vals.drop_null()
        out = vals.slice(0, 1) if len(vals) else pa.nulls(1, type=self._arrow.type)
        return Series(self._name, self._dtype, out)

    def agg_list(self) -> "Series":
        if self._arrow is None:
            return Series.from_pylist([list(self._pyobjs)], self._name, DataType.list(DataType.python()))
        offsets = pa.array([0, len(self._arrow)], type=pa.int64())
        lst = pa.LargeListArray.from_arrays(offsets, self._arrow)
        return Series(self._name, DataType.list(self._dtype), lst)

    def agg_concat(self) -> "Series":
        if self._dtype.kind != TypeKind.LIST:
            raise ValueError(f"agg_concat requires list type, got {self._dtype}")
        flat = self._arrow.flatten()
        offsets = pa.array([0, len(flat)], type=pa.int64())
        return Series(self._name, self._dtype, pa.LargeListArray.from_arrays(offsets, flat))

    def approx_count_distinct(self) -> "Series":
        # HLL-backed (sketch/hll.py): the SAME estimator the two-phase
        # sketch->merge plan finalizes, so a query's answer does not depend
        # on how its input happened to be partitioned (HLL register merge is
        # exactly associative).
        from .sketch import hll

        est = hll.count_distinct_estimate(self)
        return Series.from_pylist([est], self._name, DataType.uint64())

    def approx_percentiles(self, percentiles) -> "Series":
        # quantile-sketch-backed (sketch/quantile.py) for the same
        # partition-invariance contract as approx_count_distinct
        from .sketch import quantile

        out = quantile.percentile_estimate(self, percentiles)
        if isinstance(percentiles, float):
            return Series.from_pylist([out], self._name, DataType.float64())
        return Series.from_pylist([out], self._name, DataType.list(DataType.float64()))

    # ------------------------------------------------------------------ numeric fns
    def _unary(self, fn, dtype: Optional[DataType] = None) -> "Series":
        out = fn(self._arrow)
        return Series.from_arrow(out, self._name, dtype)

    def abs(self):
        return self._unary(pc.abs_checked)

    def ceil(self):
        return self._unary(pc.ceil)

    def floor(self):
        return self._unary(pc.floor)

    def sign(self):
        return self._unary(pc.sign)

    def round(self, decimals: int = 0):
        return self._unary(lambda a: pc.round(a, ndigits=decimals))

    def sqrt(self):
        return self.cast(DataType.float64())._unary(pc.sqrt)

    def cbrt(self):
        f = self.cast(DataType.float64())
        vals = np.asarray(pc.fill_null(f._arrow, np.nan))
        out = pa.array(np.cbrt(vals), from_pandas=True)
        out = pc.if_else(pc.is_valid(f._arrow), out, pa.nulls(len(out), pa.float64()))
        return Series.from_arrow(out, self._name)

    def exp(self):
        return self.cast(DataType.float64())._unary(pc.exp)

    def log(self, base: Optional[float] = None):
        f = self.cast(DataType.float64())
        if base is None:
            return f._unary(pc.ln)
        return f._unary(lambda a: pc.logb(a, pa.scalar(float(base))))

    def log2(self):
        return self.cast(DataType.float64())._unary(pc.log2)

    def log10(self):
        return self.cast(DataType.float64())._unary(pc.log10)

    def log1p(self):
        return self.cast(DataType.float64())._unary(pc.log1p)

    def sin(self):
        return self.cast(DataType.float64())._unary(pc.sin)

    def cos(self):
        return self.cast(DataType.float64())._unary(pc.cos)

    def tan(self):
        return self.cast(DataType.float64())._unary(pc.tan)

    def arcsin(self):
        return self.cast(DataType.float64())._unary(pc.asin)

    def arccos(self):
        return self.cast(DataType.float64())._unary(pc.acos)

    def arctan(self):
        return self.cast(DataType.float64())._unary(pc.atan)

    def arctan2(self, other):
        other = _as_series(other)
        l, r = _broadcast(self.cast(DataType.float64()), other.cast(DataType.float64()))
        return Series.from_arrow(pc.atan2(l._arrow, r._arrow), self._name)

    def arctanh(self):
        return self._np_unary(np.arctanh)

    def arccosh(self):
        return self._np_unary(np.arccosh)

    def arcsinh(self):
        return self._np_unary(np.arcsinh)

    def radians(self):
        return self._np_unary(np.radians)

    def degrees(self):
        return self._np_unary(np.degrees)

    def _np_unary(self, np_fn):
        f = self.cast(DataType.float64())
        vals = np.asarray(pc.fill_null(f._arrow, np.nan))
        with np.errstate(all="ignore"):
            out = pa.array(np_fn(vals), from_pandas=True)
        out = pc.if_else(pc.is_valid(f._arrow), out, pa.nulls(len(out), pa.float64()))
        return Series.from_arrow(out, self._name)

    # float namespace
    def float_is_nan(self):
        return self._unary(pc.is_nan, DataType.bool())

    def float_is_inf(self):
        return self._unary(pc.is_inf, DataType.bool())

    def float_not_nan(self):
        return Series.from_arrow(pc.invert(pc.is_nan(self._arrow)), self._name, DataType.bool())

    def float_fill_nan(self, fill: "Series"):
        fill = _as_series(fill).cast(self._dtype)
        l, r = _broadcast(self, fill)
        isnan = pc.fill_null(pc.is_nan(l._arrow), False)
        return Series.from_arrow(pc.if_else(isnan, r._arrow, l._arrow), self._name, self._dtype)

    def shift(self, periods: int = 1) -> "Series":
        self._require_arrow("shift")
        n = len(self)
        if periods == 0 or n == 0:
            return self
        nulls = pa.nulls(min(abs(periods), n), type=self._arrow.type)
        if periods > 0:
            body = self._arrow.slice(0, max(n - periods, 0))
            return Series(self._name, self._dtype, pa.concat_arrays([nulls, body]))
        body = self._arrow.slice(-periods)
        return Series(self._name, self._dtype, pa.concat_arrays([body, nulls]))


def _python_object_series(name: str, data) -> "Series":
    """Python-dtype fallback storage (object array; no arrow representation)."""
    objs = np.empty(len(data), dtype=object)
    for i, v in enumerate(data):
        objs[i] = v
    return Series(name, DataType.python(), None, objs)


def _static_shape(dt: DataType):
    if dt.kind == TypeKind.EMBEDDING:
        return (dt.params[1],)
    return dt.tensor_shape


def _sum_dtype(dt: DataType) -> DataType:
    if dt.is_signed_integer() or dt.is_boolean():
        return DataType.int64()
    if dt.is_unsigned_integer():
        return DataType.uint64()
    return dt


def _as_series(v) -> Series:
    if isinstance(v, Series):
        return v
    return Series.from_pylist([v], "literal")


def _binary_args(a: Series, b: Series):
    """Kernel operands for an elementwise binary op: a length-1 side is passed
    as a pa.Scalar so arrow kernels broadcast natively (no materialized repeat)."""
    na, nb = len(a), len(b)
    if na == nb:
        return a._arrow, b._arrow
    if na == 1:
        return a._arrow[0], b._arrow
    if nb == 1:
        return a._arrow, b._arrow[0]
    raise ValueError(f"length mismatch: {na} vs {nb}")


def _broadcast(a: Series, b: Series):
    if len(a) == len(b):
        return a, b
    if len(a) == 1:
        return _broadcast_to(a, len(b)), b
    if len(b) == 1:
        return a, _broadcast_to(b, len(a))
    raise ValueError(f"length mismatch: {len(a)} vs {len(b)}")


def _broadcast_to(s: Series, n: int) -> Series:
    if len(s) == n:
        return s
    if len(s) != 1:
        raise ValueError(f"cannot broadcast series of length {len(s)} to {n}")
    if s._arrow is None:
        return Series(s._name, s._dtype, None, np.repeat(s._pyobjs, n))
    if n == 0:
        return s.slice(0, 0)
    arr = pa.concat_arrays([s._arrow] * n) if n < 64 else _repeat_arrow(s._arrow, n)
    return Series(s._name, s._dtype, arr)


def _repeat_arrow(arr: pa.Array, n: int) -> pa.Array:
    idx = pa.array(np.zeros(n, dtype=np.int64))
    return arr.take(idx)
