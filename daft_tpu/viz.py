"""HTML preview + user-registrable visualization hooks.

Role-equivalent to the reference's `daft/viz/html_viz_hooks.py:17-27`
(`register_viz_hook`: custom HTML renderers for Python objects in previews)
and `daft/dataframe/display.py` (the `_repr_html_` notebook preview table).
"""

from __future__ import annotations

import base64
import html as _html
import io
from typing import Callable, Dict, Type

_VIZ_HOOKS: Dict[Type, Callable[[object], str]] = {}


def register_viz_hook(klass: Type, hook: Callable[[object], str]) -> None:
    """Register an HTML renderer for values of `klass` in dataframe previews
    (reference: daft/viz/html_viz_hooks.py register_viz_hook)."""
    _VIZ_HOOKS[klass] = hook


def get_viz_hook(obj):
    _ensure_default_hooks()
    for k in type(obj).__mro__:
        if k in _VIZ_HOOKS:
            return _VIZ_HOOKS[k]
    for k, h in _VIZ_HOOKS.items():
        if isinstance(obj, k):
            return h
    return None


def _pil_image_hook(img) -> str:
    """Default hook: PIL images inline as base64 <img> thumbnails (reference
    registers the same default for PIL.Image.Image)."""
    thumb = img.copy()
    thumb.thumbnail((128, 128))
    buf = io.BytesIO()
    thumb.save(buf, format="PNG")
    b64 = base64.b64encode(buf.getvalue()).decode("ascii")
    return f'<img style="max-height:128px" src="data:image/png;base64,{b64}" />'


_DEFAULTS_REGISTERED = False


def _ensure_default_hooks() -> None:
    """Register the PIL default on first preview, not at import — keeps
    `import daft_tpu` free of PIL's import cost."""
    global _DEFAULTS_REGISTERED
    if _DEFAULTS_REGISTERED:
        return
    _DEFAULTS_REGISTERED = True
    try:
        from PIL import Image as _PILImage

        _VIZ_HOOKS.setdefault(_PILImage.Image, _pil_image_hook)
    except ImportError:
        pass


def html_cell(value) -> str:
    """One preview cell: viz hook if registered, escaped str otherwise."""
    if value is None:
        return "<i>None</i>"
    hook = get_viz_hook(value)
    if hook is not None:
        try:
            return hook(value)
        except Exception:
            pass
    s = str(value)
    if len(s) > 80:
        s = s[:77] + "..."
    return _html.escape(s)


def html_table(schema, pydict: dict, preview_rows: int, total_known) -> str:
    """Render a schema-headed preview table (reference: display.py repr)."""
    names = [f.name for f in schema]
    head = "".join(
        f'<th style="text-align:left">{_html.escape(f.name)}<br/>'
        f'<small>{_html.escape(repr(f.dtype))}</small></th>'
        for f in schema)
    nrows = len(pydict[names[0]]) if names and names[0] in pydict else 0
    body = []
    for i in range(min(nrows, preview_rows)):
        cells = "".join(f'<td style="text-align:left">'
                        f'{html_cell(pydict[nm][i])}</td>' for nm in names)
        body.append(f"<tr>{cells}</tr>")
    foot = (f"<small>(Showing first {min(nrows, preview_rows)} of "
            f"{total_known} rows)</small>" if total_known is not None
            else f"<small>(Showing first {min(nrows, preview_rows)} rows)</small>")
    return ('<div><table class="dataframe">'
            f"<thead><tr>{head}</tr></thead>"
            f'<tbody>{"".join(body)}</tbody></table>{foot}</div>')
