"""Engine error hierarchy (reference: DaftError, src/common/error/error.rs).

Every class dual-inherits the builtin exception users would naturally catch,
so `except ValueError` keeps working while `except DaftError` catches all
engine-raised failures. Raise sites adopt these types incrementally; the
public contract is the hierarchy itself."""

from __future__ import annotations


class DaftError(Exception):
    """Base of every engine-raised error (reference: DaftError enum)."""


class DaftTypeError(DaftError, TypeError):
    """Expression/kernel type mismatch (reference: DaftError::TypeError)."""


class DaftValueError(DaftError, ValueError):
    """Invalid argument or value (reference: DaftError::ValueError)."""


class DaftSchemaError(DaftError, ValueError):
    """Schema resolution failure: unknown column, incompatible field
    (reference: DaftError::SchemaMismatch / FieldNotFound)."""


class DaftNotFoundError(DaftError, FileNotFoundError):
    """Missing file/table/catalog object (reference: DaftError::FileNotFound)."""


class DaftIOError(DaftError, IOError):
    """IO failure after retries (reference: DaftError::External on IO)."""


class DaftResourceError(DaftError, RuntimeError):
    """Unsatisfiable resource request (reference: admission failure in
    pyrunner.py:352-370)."""


class DaftOverloadedError(DaftError, RuntimeError):
    """The serving runtime shed this query: the admission queue was full,
    the queue wait exceeded its timeout, or the engine was draining for
    shutdown. Deliberate load shedding, never an engine bug — callers
    back off and retry against a less loaded instance."""


class DaftInternalError(DaftError, RuntimeError):
    """An engine invariant was violated — always a bug in daft_tpu itself,
    never a user or environment error (reference: DaftError::InternalError).
    Raised loudly so defects surface instead of corrupting results."""


class DaftTransientError(DaftError, IOError):
    """Transient, retryable failure (timeouts, 5xx, connection resets, and
    injected faults). Retry policies key on this type: anything else is
    treated as permanent and propagates immediately."""


class DaftCorruptionError(DaftTransientError):
    """A payload failed its end-to-end integrity check — a spill IPC file,
    a transport frame, or an encoded exchange piece came back with bytes
    that do not match the checksum recorded when the payload was produced
    (or the artifact is missing/unparseable at re-entry). Raised INSTEAD of
    surfacing a garbled table or a deep arrow decode error. Classified
    transient: the lineage-recompute and task-retry/re-dispatch layers own
    recovery, and only when both are exhausted does the query fail."""


class DaftTimeoutError(DaftError, TimeoutError):
    """Query exceeded ExecutionConfig.execution_timeout_s. Carries the
    partial RuntimeStats snapshot accumulated before the deadline so
    callers can see how far the query got."""

    def __init__(self, message: str, stats: "dict | None" = None):
        super().__init__(message)
        self.stats = stats or {}
