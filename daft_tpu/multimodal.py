"""Multimodal kernels + expression namespaces: images and URLs.

Role-equivalent to the reference's image kernel set
(src/daft-core/src/array/ops/image.rs, 1,032 LoC: decode/encode/resize/crop/
to_mode over the Image/FixedShapeImage logical types) and the url functions
(src/daft-functions/src/uri/download.rs, upload.rs: batched concurrent GET with
on_error raise|null semantics).

TPU-first split: codecs (jpeg/png decode/encode) are inherently host-side —
PIL plays the role of the reference's `image` crate — while *fixed-shape*
resize is a dense batched op routed through jax.image.resize so it runs on
the accelerator (one (N,H,W,C) program, MXU/VPU friendly); variable-shape
images fall back to per-row host resize exactly like the reference's
per-element kernels.

Storage matches datatypes.DataType.to_physical():
  Image            -> struct{data: list<u8>, channel: u16, height: u32,
                            width: u32, mode: u8}
  FixedShapeImage  -> fixed_size_list<u8|u16|f32>[h*w*c]
"""

from __future__ import annotations

import concurrent.futures
import io
import os
import urllib.request
import uuid
from typing import List, Optional, Sequence, Tuple

import numpy as np
import pyarrow as pa

from .datatypes import _IMAGE_MODE_CHANNELS, IMAGE_MODES, DataType, TypeKind
from .expressions import _Namespace
from .functions import register
from .series import Series

# ---------------------------------------------------------------------------
# mode helpers
# ---------------------------------------------------------------------------

MODE_TO_ID = {m: i for i, m in enumerate(IMAGE_MODES)}
ID_TO_MODE = {i: m for i, m in enumerate(IMAGE_MODES)}

# PIL modes with a faithful equivalent in IMAGE_MODES; anything else (e.g. the
# single-channel float mode "F", palettes, CMYK) converts to RGB on decode.
_PIL_TO_MODE = {"L": "L", "LA": "LA", "RGB": "RGB", "RGBA": "RGBA", "I;16": "L16"}
_MODE_TO_PIL = {"L": "L", "LA": "LA", "RGB": "RGB", "RGBA": "RGBA", "L16": "I;16"}
# modes PIL can round-trip through Image.fromarray; the rest use numpy/jax paths
_PIL_SAFE_MODES = frozenset(["L", "LA", "RGB", "RGBA", "L16"])


def _mode_np_dtype(mode: str):
    if mode.endswith("32F"):
        return np.float32
    if mode.endswith("16"):
        return np.uint16
    return np.uint8


def _mode_channels(mode: str) -> int:
    return _IMAGE_MODE_CHANNELS[mode]


# ---------------------------------------------------------------------------
# Image series <-> numpy
# ---------------------------------------------------------------------------

def image_series_from_arrays(arrays: Sequence[Optional[np.ndarray]], name: str = "image",
                             modes: Optional[Sequence[Optional[str]]] = None,
                             dtype_mode: Optional[str] = None) -> Series:
    """Build a variable-shape Image Series from HxWxC (or HxW) numpy arrays."""
    data_chunks: List[np.ndarray] = []
    offsets = [0]
    channel, height, width, mode_ids, valid = [], [], [], [], []
    total = 0
    for i, a in enumerate(arrays):
        if a is None:
            valid.append(False)
            channel.append(0); height.append(0); width.append(0); mode_ids.append(0)
            offsets.append(total)
            continue
        if a.ndim == 2:
            a = a[:, :, None]
        m = modes[i] if modes is not None and modes[i] is not None else _default_mode(a)
        a = a.astype(_mode_np_dtype(m), copy=False)
        valid.append(True)
        h, w, c = a.shape
        flat = a.reshape(-1).view(np.uint8)
        data_chunks.append(flat)
        total += flat.size
        offsets.append(total)
        channel.append(c); height.append(h); width.append(w); mode_ids.append(MODE_TO_ID[m])
    data = np.concatenate(data_chunks) if data_chunks else np.empty(0, np.uint8)
    dt = DataType.image(dtype_mode)
    storage_t = dt.to_arrow()
    fields = {f.name: f.type for f in storage_t}
    lst = pa.LargeListArray.from_arrays(pa.array(offsets, pa.int64()), pa.array(data, pa.uint8()))
    if not pa.types.is_large_list(fields["data"]):
        lst = lst.cast(fields["data"])
    mask = pa.array([not v for v in valid], pa.bool_())
    struct = pa.StructArray.from_arrays(
        [lst,
         pa.array(channel, fields["channel"]),
         pa.array(height, fields["height"]),
         pa.array(width, fields["width"]),
         pa.array(mode_ids, fields["mode"])],
        names=["data", "channel", "height", "width", "mode"],
        mask=mask)
    if struct.type != storage_t:
        struct = struct.cast(storage_t)
    return Series(name, dt, struct)


def _default_mode(a: np.ndarray) -> str:
    c = a.shape[2] if a.ndim == 3 else 1
    base = {1: "L", 2: "LA", 3: "RGB", 4: "RGBA"}[c]
    if a.dtype == np.uint16:
        return base + "16"
    if a.dtype in (np.float32, np.float64):
        if base in ("RGB", "RGBA"):
            return base + "32F"
        raise ValueError(f"no float image mode for {base}")
    return base


def image_series_to_arrays(s: Series) -> List[Optional[np.ndarray]]:
    """Image/FixedShapeImage Series -> list of HxWxC numpy arrays (None = null)."""
    dt = s.dtype
    if dt.kind == TypeKind.FIXED_SHAPE_IMAGE:
        mode, h, w = dt.params
        c = _mode_channels(mode)
        npdt = _mode_np_dtype(mode)
        arr = s.to_arrow()
        per = h * w * c
        # .values spans the whole child buffer; honor a sliced parent's offset
        flat = np.asarray(arr.values.to_numpy(zero_copy_only=False))
        flat = flat[arr.offset * per:(arr.offset + len(arr)) * per]
        out: List[Optional[np.ndarray]] = []
        valid = np.asarray(arr.is_valid())
        for i in range(len(arr)):
            if not valid[i]:
                out.append(None)
            else:
                out.append(flat[i * per:(i + 1) * per].astype(npdt, copy=False).reshape(h, w, c))
        return out
    if dt.kind != TypeKind.IMAGE:
        raise ValueError(f"expected an image series, got {dt}")
    arr = s.to_arrow()
    data = arr.field("data")
    ch = arr.field("channel").to_numpy(zero_copy_only=False)
    hh = arr.field("height").to_numpy(zero_copy_only=False)
    ww = arr.field("width").to_numpy(zero_copy_only=False)
    mm = arr.field("mode").to_numpy(zero_copy_only=False)
    offs = np.asarray(data.offsets)
    raw = np.asarray(data.values)
    valid = np.asarray(arr.is_valid())
    out = []
    for i in range(len(arr)):
        if not valid[i]:
            out.append(None)
            continue
        m = ID_TO_MODE[int(mm[i])]
        npdt = _mode_np_dtype(m)
        seg = raw[offs[i]:offs[i + 1]].view(npdt)
        out.append(seg.reshape(int(hh[i]), int(ww[i]), int(ch[i])))
    return out


def _to_pil(a: np.ndarray):
    from PIL import Image as PILImage

    if a.shape[2] == 1:
        a = a[:, :, 0]
    return PILImage.fromarray(a)


def _pil_to_np(img) -> Tuple[np.ndarray, str]:
    a = np.asarray(img)
    if a.ndim == 2:
        a = a[:, :, None]
    mode = _PIL_TO_MODE.get(img.mode)
    if mode is None:
        img = img.convert("RGB")
        a = np.asarray(img)
        mode = "RGB"
    return a, mode


# ---------------------------------------------------------------------------
# image kernels
# ---------------------------------------------------------------------------

def image_decode(s: Series, mode: Optional[str] = None, on_error: str = "raise") -> Series:
    """binary -> Image. Reference: image.rs decode + ImageMode conversion."""
    from PIL import Image as PILImage

    if mode is not None and mode not in IMAGE_MODES:
        raise ValueError(f"unknown image mode {mode!r}")
    vals = s.to_pylist()
    arrays: List[Optional[np.ndarray]] = []
    modes: List[Optional[str]] = []
    for v in vals:
        if v is None:
            arrays.append(None); modes.append(None)
            continue
        try:
            img = PILImage.open(io.BytesIO(v))
            if mode is not None:
                img = img.convert(_MODE_TO_PIL.get(mode, mode))
            a, m = _pil_to_np(img)
            arrays.append(a); modes.append(mode or m)
        except Exception:
            if on_error == "null":
                arrays.append(None); modes.append(None)
            else:
                raise
    return image_series_from_arrays(arrays, s.name, modes, dtype_mode=mode)


def image_encode(s: Series, image_format: str) -> Series:
    """Image -> binary in the requested codec (PNG/JPEG/TIFF/BMP/GIF)."""
    fmt = image_format.upper()
    if fmt == "JPG":
        fmt = "JPEG"
    arrays = image_series_to_arrays(s)
    out: List[Optional[bytes]] = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        m = _default_mode(a)
        if m not in _PIL_SAFE_MODES:
            raise ValueError(
                f"cannot encode a {m} image to {fmt}; convert with "
                "image.to_mode to an 8-bit mode (or L16) first")
        img = _to_pil(a)
        if fmt == "JPEG" and img.mode in ("RGBA", "LA"):
            img = img.convert("RGB")
        buf = io.BytesIO()
        img.save(buf, format=fmt)
        out.append(buf.getvalue())
    return Series.from_pylist(out, s.name, DataType.binary())


def image_resize(s: Series, w: int, h: int) -> Series:
    """Resize. Fixed-shape inputs run as ONE batched jax.image.resize program
    (device path); variable-shape images resize per row on host via PIL."""
    dt = s.dtype
    if dt.kind == TypeKind.FIXED_SHAPE_IMAGE:
        return _resize_fixed_device(s, w, h)
    arrays = image_series_to_arrays(s)
    modes: List[Optional[str]] = []
    out: List[Optional[np.ndarray]] = []
    for a in arrays:
        if a is None:
            out.append(None); modes.append(None)
            continue
        m = _default_mode(a)
        if m in _PIL_SAFE_MODES:
            img = _to_pil(a).resize((w, h), resample=_BILINEAR())
            b = np.asarray(img)
            if b.ndim == 2:
                b = b[:, :, None]
        else:  # 16-bit multichannel / float modes: PIL can't, jax can
            b = _resize_one_jax(a, w, h)
        out.append(b); modes.append(m)
    return image_series_from_arrays(out, s.name, modes,
                                    dtype_mode=dt.params[0] if dt.kind == TypeKind.IMAGE else None)


def _BILINEAR():
    from PIL import Image as PILImage

    return PILImage.BILINEAR


def _resize_one_jax(a: np.ndarray, w: int, h: int) -> np.ndarray:
    """Bilinear resize of one HxWxC array (used for the modes PIL's
    fromarray rejects: RGB16/RGBA16/LA16/RGB32F/RGBA32F) — same separable
    weight contraction as the batched fixed-shape path."""
    out = _resize_batch_separable(a.astype(np.float32)[None], h, w)[0]
    if a.dtype != np.float32 and not np.issubdtype(a.dtype, np.floating):
        info = np.iinfo(a.dtype)
        out = np.clip(np.rint(out), info.min, info.max)
    return out.astype(a.dtype)


_RESIZE_W_CACHE: dict = {}


def _resize_weight_mat(src: int, dst: int) -> np.ndarray:
    """(dst, src) row-resize matrix reproducing jax.image.resize's bilinear
    semantics exactly (jax _src/image/scale.py compute_weight_mat):
    half-pixel sample centers, triangle kernel widened by the inverse scale
    when minifying (anti-aliasing), per-output normalization over in-range
    taps, out-of-domain outputs zeroed. Verified ≤2e-3 of jax.image.resize
    across up/down/degenerate shapes."""
    key = (src, dst)
    got = _RESIZE_W_CACHE.get(key)
    if got is not None:
        return got
    scale = src / dst
    kscale = max(scale, 1.0)
    centers = (np.arange(dst) + 0.5) * scale - 0.5
    x = np.abs(centers[:, None] - np.arange(src)[None, :]) / kscale
    wt = np.maximum(0.0, 1.0 - x)
    tot = wt.sum(axis=1, keepdims=True)
    wt = np.where(np.abs(tot) > 1000 * np.finfo(np.float32).eps, wt / tot, 0.0)
    dom = (centers >= -0.5) & (centers <= src - 0.5)
    wt = np.where(dom[:, None], wt, 0.0).astype(np.float32)
    _RESIZE_W_CACHE[key] = wt
    return wt


_RESIZE_CHUNK = 2048


_RS_JIT = None


def _rs_jitted():
    """Process-wide jitted resize program (two einsums over the separable
    weight mats): the jit cache must persist across partitions — a per-call
    closure would recompile every batch. Lazily built so importing this
    module never touches jax."""
    global _RS_JIT
    if _RS_JIT is None:
        import jax
        import jax.numpy as jnp

        @jax.jit
        def _rs(x, a, b):
            # HIGHEST matches jax.image.resize (its internal einsums pin
            # Precision.HIGHEST); the TPU default would run bf16 multiply
            # passes whose ~0.4% error breaks the +-1-count parity gate
            p = jax.lax.Precision.HIGHEST
            t = jnp.einsum("os,nshc->nohc", a, x, precision=p)
            return jnp.einsum("ow,nhwc->nhoc", b, t, precision=p)

        _RS_JIT = _rs
    return _RS_JIT


def _resize_batch_separable(batch: np.ndarray, h: int, w: int) -> np.ndarray:
    """Bilinear resize of an (N, oh, ow, C) float32 batch as two separable
    weight contractions — the resize IS two matmuls, which is exactly what
    the MXU wants on device and what BLAS wants on host. One fused
    jax.image.resize call compiles to a giant gather program that is 2-4x
    slower on the host and scales superlinearly past ~2k images. Chunking
    bounds the float32 intermediates (and on device reuses one compiled
    program per bucket); np.einsum(optimize=True) lowers each chunk's
    contraction to BLAS."""
    import jax
    import jax.numpy as jnp

    n, oh, ow, c = batch.shape
    wh = _resize_weight_mat(oh, h)
    ww = _resize_weight_mat(ow, w)
    if jax.default_backend() == "cpu":
        # write each chunk's second contraction straight into the
        # preallocated result: np.concatenate would copy the full
        # (n, h, w, c) float32 output once more (~6 GB at n=10k)
        out = np.empty((n, h, w, c), np.float32)
        for i in range(0, n, _RESIZE_CHUNK):
            piece = batch[i:i + _RESIZE_CHUNK]
            t = np.einsum("os,nshc->nohc", wh, piece, optimize=True)
            np.einsum("ow,nhwc->nhoc", ww, t, optimize=True,
                      out=out[i:i + len(piece)])
        return out

    rs = _rs_jitted()
    jwh, jww = jnp.asarray(wh), jnp.asarray(ww)
    outs = []
    for i in range(0, n, _RESIZE_CHUNK):
        piece = batch[i:i + _RESIZE_CHUNK]
        if len(piece) < _RESIZE_CHUNK and n > _RESIZE_CHUNK:
            # pad the tail to the chunk shape: one compiled program, not two
            pad = np.zeros((_RESIZE_CHUNK - len(piece),) + piece.shape[1:],
                           np.float32)
            out = np.asarray(jax.device_get(
                rs(jnp.asarray(np.concatenate([piece, pad])), jwh, jww)))
            outs.append(out[:len(piece)])
        else:
            outs.append(np.asarray(jax.device_get(
                rs(jnp.asarray(piece), jwh, jww))))
    return outs[0] if len(outs) == 1 else np.concatenate(outs)


def _resize_fixed_device(s: Series, w: int, h: int) -> Series:
    mode, oh, ow = s.dtype.params
    c = _mode_channels(mode)
    npdt = _mode_np_dtype(mode)
    arr = s.to_arrow()
    n = len(arr)
    per = oh * ow * c
    flat = np.asarray(arr.values.to_numpy(zero_copy_only=False))
    if flat.dtype.kind == "f" and not np.issubdtype(npdt, np.floating):
        # null rows surface as NaN in the float view; NaN→uint cast is UB and
        # warns — zero the lanes (they're masked out by validity downstream)
        flat = np.nan_to_num(flat, nan=0.0, posinf=0.0, neginf=0.0)
    flat = flat.astype(npdt, copy=False)
    flat = flat[arr.offset * per:(arr.offset + n) * per]
    batch = flat.reshape(n, oh, ow, c).astype(np.float32)
    resized = _resize_batch_separable(batch, h, w)
    if npdt != np.float32:
        info = np.iinfo(npdt)
        resized = np.clip(np.rint(resized), info.min, info.max)
    resized = resized.astype(npdt)
    out_dt = DataType.image(mode, h, w)
    storage_t = out_dt.to_arrow()
    values = pa.array(resized.reshape(-1), storage_t.value_type)
    fsl = pa.FixedSizeListArray.from_arrays(values, h * w * c)
    if arr.null_count:
        # reattach the null bitmap without leaving the flat buffer
        validity = np.packbits(np.asarray(arr.is_valid()), bitorder="little")
        fsl = pa.Array.from_buffers(storage_t, n, [pa.py_buffer(validity.tobytes())],
                                    children=[values])
    return Series(s.name, out_dt, fsl)


def image_crop(s: Series, bbox) -> Series:
    """Crop to (x, y, w, h). bbox is a python tuple or a per-row Series of
    4-element lists. Always returns variable-shape Image (reference parity)."""
    arrays = image_series_to_arrays(s)
    n = len(arrays)
    if isinstance(bbox, Series):
        boxes = bbox.to_pylist()
        if len(boxes) == 1:
            boxes = boxes * n
    else:
        boxes = [tuple(bbox)] * n
    out: List[Optional[np.ndarray]] = []
    modes: List[Optional[str]] = []
    for a, b in zip(arrays, boxes):
        if a is None or b is None:
            out.append(None); modes.append(None)
            continue
        x, y, w, h = (int(v) for v in b)
        ih, iw = a.shape[0], a.shape[1]
        crop = a[max(y, 0):min(y + h, ih), max(x, 0):min(x + w, iw)]
        out.append(crop.copy())
        modes.append(_default_mode(a))
    return image_series_from_arrays(out, s.name, modes)


def image_to_mode(s: Series, mode: str) -> Series:
    if mode not in IMAGE_MODES:
        raise ValueError(f"unknown image mode {mode!r}")
    arrays = image_series_to_arrays(s)
    out: List[Optional[np.ndarray]] = []
    for a in arrays:
        if a is None:
            out.append(None)
            continue
        src_mode = _default_mode(a)
        if src_mode in _PIL_SAFE_MODES and mode in _PIL_SAFE_MODES:
            img = _to_pil(a).convert(_MODE_TO_PIL.get(mode, mode))
            b = np.asarray(img)
            if b.ndim == 2:
                b = b[:, :, None]
            out.append(b.astype(_mode_np_dtype(mode), copy=False))
        else:
            out.append(_convert_mode_np(a, mode))
    dt = s.dtype
    if dt.kind == TypeKind.FIXED_SHAPE_IMAGE:
        _, h, w = dt.params
        return _fixed_image_series(out, s.name, mode, h, w)
    return image_series_from_arrays(out, s.name, [mode] * len(out), dtype_mode=mode)


def _convert_mode_np(a: np.ndarray, mode: str) -> np.ndarray:
    """Mode conversion through a normalized [0,1] float representation — covers
    the 16-bit/float modes PIL's fromarray rejects. Luma uses ITU-R 601
    (0.299/0.587/0.114), matching PIL's RGB->L."""
    if np.issubdtype(a.dtype, np.floating):
        f = np.clip(a.astype(np.float32), 0.0, 1.0)
    else:
        f = a.astype(np.float32) / float(np.iinfo(a.dtype).max)
    c = f.shape[2]
    # split into color + alpha in float
    if c == 1:
        rgb, alpha = np.repeat(f, 3, axis=2), None
    elif c == 2:
        rgb, alpha = np.repeat(f[:, :, :1], 3, axis=2), f[:, :, 1:2]
    elif c == 3:
        rgb, alpha = f, None
    else:
        rgb, alpha = f[:, :, :3], f[:, :, 3:4]
    base = mode.rstrip("0123456789F") or mode  # L/LA/RGB/RGBA
    if base in ("L", "LA"):
        gray = (rgb @ np.array([0.299, 0.587, 0.114], np.float32))[:, :, None]
        colors = gray
    else:
        colors = rgb
    want_c = _mode_channels(mode)
    if base in ("LA", "RGBA"):
        if alpha is None:
            alpha = np.ones(colors.shape[:2] + (1,), np.float32)
        outf = np.concatenate([colors, alpha], axis=2)
    else:
        outf = colors
    assert outf.shape[2] == want_c, (outf.shape, mode)
    npdt = _mode_np_dtype(mode)
    if np.issubdtype(npdt, np.floating):
        return outf.astype(npdt)
    mx = float(np.iinfo(npdt).max)
    return np.clip(np.rint(outf * mx), 0, mx).astype(npdt)


def _fixed_image_series(arrays: List[Optional[np.ndarray]], name: str, mode: str,
                        h: int, w: int) -> Series:
    """Pack HxWxC arrays into the fixed_size_list storage through ONE flat
    numpy buffer (pa.array over per-row .tolist() materializes h*w*c python
    ints per row — 27M objects for 1,000 96px images; this path is on the
    LAION rung's critical cast)."""
    dt = DataType.image(mode, h, w)
    c = _mode_channels(mode)
    npdt = _mode_np_dtype(mode)
    per = h * w * c
    n = len(arrays)
    t = dt.to_arrow()
    flat = np.zeros(n * per, dtype=npdt)
    validity = np.ones(n, dtype=bool)
    for i, a in enumerate(arrays):
        if a is None:
            validity[i] = False
        else:
            flat[i * per:(i + 1) * per] = a.reshape(-1)
    values = pa.array(flat, t.value_type)
    fsl = pa.FixedSizeListArray.from_arrays(values, per)
    if not validity.all():
        bits = np.packbits(validity, bitorder="little")
        fsl = pa.Array.from_buffers(t, n, [pa.py_buffer(bits.tobytes())],
                                    children=[values])
    return Series(name, dt, fsl)


# ---------------------------------------------------------------------------
# url kernels
# ---------------------------------------------------------------------------

def _fetch_one(client, url: str, timeout: float) -> bytes:
    # every scheme (s3/http/file) rides the IOClient: retry with backoff,
    # connection budget, IO counters (reference: uri/download.rs bulk GET
    # through the IOClient rather than ad-hoc urllib)
    return client.get(url, timeout=timeout)


def url_download(s: Series, max_connections: int = 32, on_error: str = "raise",
                 timeout: float = 30.0) -> Series:
    """string urls -> binary contents; concurrent like the reference's bulk GET
    (download.rs: max_connections-wide async multiget, ordered results)."""
    from .io.object_store import default_io_client

    urls = s.to_pylist()
    out: List[Optional[bytes]] = [None] * len(urls)
    errs: List[Optional[Exception]] = [None] * len(urls)
    workers = max(1, min(int(max_connections), 64))
    # resolve the client ONCE per batch: default_io_client() re-reads the
    # store configs from env under a lock, and per-url resolution serializes
    # a 10k-wide download on that lock
    client = default_io_client()
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="daft-mm-download") as ex:
        futs = {}
        for i, u in enumerate(urls):
            if u is None:
                continue
            futs[ex.submit(_fetch_one, client, u, timeout)] = i
        for f in concurrent.futures.as_completed(futs):
            i = futs[f]
            try:
                out[i] = f.result()
            except Exception as e:  # noqa: BLE001
                errs[i] = e
    first_err = next((e for e in errs if e is not None), None)
    if first_err is not None and on_error != "null":
        raise first_err
    return Series.from_pylist(out, s.name, DataType.binary())


def url_upload(s: Series, location, on_error: str = "raise",
               max_connections: int = 32) -> Series:
    """binary contents -> written file paths under `location`.

    Remote targets (s3://, any scheme the object-store client routes) and
    local paths alike; writes run max_connections-wide like the reference's
    upload path (uri/upload.rs: async multi-put through IOClient), mirroring
    url_download's concurrency."""
    from .io.object_store import STORAGE

    if isinstance(location, Series):
        locs = location.to_pylist()
        if len(locs) == 1:
            locs = locs * len(s)
    else:
        locs = [location] * len(s)
    vals = s.to_pylist()
    n = len(vals)
    out: List[Optional[str]] = [None] * n
    errs: List[Optional[Exception]] = [None] * n

    def _upload_one(i: int, v, loc: str) -> str:
        data = v if isinstance(v, (bytes, bytearray)) else str(v).encode()
        if loc.startswith("file://"):
            loc = loc[len("file://"):]  # return plain fs paths, as before
        path = STORAGE.join(loc, f"{i}-{uuid.uuid4().hex}.bin")
        if not STORAGE.is_remote(loc):
            STORAGE.makedirs(loc)
        STORAGE.put(path, bytes(data))
        return path

    workers = max(1, min(int(max_connections), 64))
    with concurrent.futures.ThreadPoolExecutor(
            max_workers=workers, thread_name_prefix="daft-mm-upload") as ex:
        futs = {}
        for i, (v, loc) in enumerate(zip(vals, locs)):
            if v is None or loc is None:
                continue
            futs[ex.submit(_upload_one, i, v, loc)] = i
        for f in concurrent.futures.as_completed(futs):
            i = futs[f]
            try:
                out[i] = f.result()
            except Exception as e:  # noqa: BLE001
                errs[i] = e
    first_err = next((e for e in errs if e is not None), None)
    if first_err is not None and on_error != "null":
        raise first_err
    return Series.from_pylist(out, s.name, DataType.string())


# ---------------------------------------------------------------------------
# function registry entries
# ---------------------------------------------------------------------------

def _req_image(dt: DataType, what: str) -> None:
    if dt.kind not in (TypeKind.IMAGE, TypeKind.FIXED_SHAPE_IMAGE):
        raise ValueError(f"{what} expects an image column, got {dt}")


def _res_decode(*dts, mode=None, on_error="raise"):
    if not (dts[0].kind == TypeKind.BINARY or dts[0].is_null()):
        raise ValueError(f"image.decode expects binary, got {dts[0]}")
    return DataType.image(mode)


def _res_encode(*dts, image_format="png"):
    _req_image(dts[0], "image.encode")
    return DataType.binary()


def _res_resize(*dts, w=None, h=None):
    _req_image(dts[0], "image.resize")
    d = dts[0]
    if d.kind == TypeKind.FIXED_SHAPE_IMAGE:
        return DataType.image(d.params[0], h, w)
    return d


def _res_crop(*dts, bbox=None):
    _req_image(dts[0], "image.crop")
    d = dts[0]
    mode = d.params[0] if d.kind != TypeKind.FIXED_SHAPE_IMAGE else None
    return DataType.image(mode)


def _res_to_mode(*dts, mode=None):
    _req_image(dts[0], "image.to_mode")
    d = dts[0]
    if d.kind == TypeKind.FIXED_SHAPE_IMAGE:
        return DataType.image(mode, d.params[1], d.params[2])
    return DataType.image(mode)


def _res_download(*dts, **_kw):
    if not (dts[0].is_string() or dts[0].is_null()):
        raise ValueError(f"url.download expects string urls, got {dts[0]}")
    return DataType.binary()


def _res_upload(*dts, **_kw):
    return DataType.string()


register("image.decode", _res_decode, image_decode)
register("image.encode", _res_encode,
         lambda s, image_format="png": image_encode(s, image_format))
register("image.resize", _res_resize, lambda s, w=None, h=None: image_resize(s, w, h))
register("image.crop", _res_crop,
         lambda s, *args, bbox=None: image_crop(s, args[0] if args else bbox))
register("image.to_mode", _res_to_mode, lambda s, mode=None: image_to_mode(s, mode))
register("url.download", _res_download, url_download)
register("url.upload", _res_upload,
         lambda s, *args, location=None, **kw: url_upload(s, args[0] if args else location, **kw))


# ---------------------------------------------------------------------------
# expression namespaces (reference: ExpressionImageNamespace /
# ExpressionUrlNamespace, daft/expressions/expressions.py:3110,1151)
# ---------------------------------------------------------------------------

class ExprImageNamespace(_Namespace):
    def decode(self, on_error: str = "raise", mode: Optional[str] = None):
        return self._fn("image.decode", mode=mode, on_error=on_error)

    def encode(self, image_format: str):
        return self._fn("image.encode", image_format=image_format)

    def resize(self, w: int, h: int):
        return self._fn("image.resize", w=w, h=h)

    def crop(self, bbox):
        from .expressions import Expression

        if isinstance(bbox, Expression):
            return self._fn("image.crop", bbox)
        return self._fn("image.crop", bbox=tuple(bbox))

    def to_mode(self, mode: str):
        return self._fn("image.to_mode", mode=mode)


class ExprUrlNamespace(_Namespace):
    def download(self, max_connections: int = 32, on_error: str = "raise",
                 io_config=None, use_native_downloader: bool = True):
        return self._fn("url.download", max_connections=max_connections, on_error=on_error)

    def upload(self, location, on_error: str = "raise", max_connections: int = 32,
               io_config=None):
        from .expressions import Expression

        if isinstance(location, Expression):
            return self._fn("url.upload", location, on_error=on_error)
        return self._fn("url.upload", location=location, on_error=on_error)
