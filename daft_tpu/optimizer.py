"""Rule-based logical optimizer.

Role-equivalent to the reference's
src/daft-plan/src/logical_optimization/optimizer.rs:126 rule batches:
PushDownFilter, PushDownProjection (column pruning into sources),
PushDownLimit, DropRepartition, and projection folding. Rules rewrite the
logical tree to a fixed point (bounded passes), then a single column-pruning
pass installs scan pushdowns.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .expressions import Expression, col
from .logical import (
    Aggregate,
    Concat,
    Distinct,
    Explode,
    Filter,
    InMemorySource,
    Join,
    Limit,
    LogicalPlan,
    MonotonicallyIncreasingId,
    Pivot,
    Project,
    Repartition,
    Sample,
    ScanSource,
    Sort,
    Unpivot,
    Write,
    expr_has_special,
    expr_input_columns,
    is_trivial_passthrough,
    substitute_columns,
)


def optimize(plan: LogicalPlan, max_passes: int = 8) -> LogicalPlan:
    for _ in range(max_passes):
        new = _apply_once(plan)
        if new is None:
            break
        plan = new
    plan = _prune_columns(plan, None)
    # pruning may introduce Projects that enable further pushdown
    for _ in range(max_passes):
        new = _apply_once(plan)
        if new is None:
            break
        plan = new
    return plan


def _apply_once(plan: LogicalPlan) -> Optional[LogicalPlan]:
    """One top-down rewrite pass; returns None if nothing changed."""
    changed = False

    def rec(p: LogicalPlan) -> LogicalPlan:
        nonlocal changed
        while True:
            q = _rewrite(p)
            if q is None:
                break
            changed = True
            p = q
        kids = p.children()
        if kids:
            new_kids = [rec(k) for k in kids]
            if any(a is not b for a, b in zip(kids, new_kids)):
                p = p.with_children(new_kids)
        return p

    out = rec(plan)
    return out if changed else None


def _rewrite(p: LogicalPlan) -> Optional[LogicalPlan]:
    for rule in (_push_down_filter, _push_down_limit, _drop_repartition, _fold_projections):
        q = rule(p)
        if q is not None:
            return q
    return None


# ---------------------------------------------------------------------------
# filter pushdown
# ---------------------------------------------------------------------------

def _split_conjuncts(e: Expression) -> List[Expression]:
    from .expressions import BinaryOp

    n = e._node
    if isinstance(n, BinaryOp) and n.op == "&":
        return _split_conjuncts(Expression(n.left)) + _split_conjuncts(Expression(n.right))
    return [e]


def _and_all(preds: List[Expression]) -> Expression:
    out = preds[0]
    for p in preds[1:]:
        out = out & p
    return out


def _push_down_filter(p: LogicalPlan) -> Optional[LogicalPlan]:
    if not isinstance(p, Filter):
        return None
    child = p.input
    pred = p.predicate

    if isinstance(child, Filter):
        return Filter(child.input, child.predicate & pred)

    if isinstance(child, Project):
        # a pure column-pruning Project over an in-memory source is there to
        # narrow the filter's working set — swapping the filter below it would
        # re-widen the filter to every source column for no pushdown benefit
        if isinstance(child.input, InMemorySource) and all(
                is_trivial_passthrough(e) is not None for e in child.exprs):
            return None
        # substitute computed columns into the predicate; abort if any referenced
        # projection expr contains an agg/UDF (not freely movable)
        defs: Dict[str, Expression] = {}
        for e in child.exprs:
            src = is_trivial_passthrough(e)
            if src is not None:
                defs[e.name()] = col(src)
            else:
                if expr_has_special(e):
                    defs[e.name()] = None  # type: ignore[assignment]
                else:
                    defs[e.name()] = e
        needed = expr_input_columns(pred)
        if any(defs.get(c, col(c)) is None for c in needed):
            return None
        subst = substitute_columns(pred, {k: v for k, v in defs.items() if v is not None})
        return Project(Filter(child.input, subst), child.exprs)

    if isinstance(child, (Sort, Repartition, MonotonicallyIncreasingId, Distinct)):
        if isinstance(child, MonotonicallyIncreasingId) and child.column_name in expr_input_columns(pred):
            return None
        moved = Filter(child.children()[0], pred)
        return child.with_children([moved] + child.children()[1:])

    if isinstance(child, Concat):
        return Concat(Filter(child.input, pred), Filter(child.other, pred))

    if isinstance(child, Join):
        return _filter_into_join(p, child)

    if isinstance(child, ScanSource):
        pd = child.pushdowns()
        if pd.limit is not None:
            return None  # limit already applied at scan; filter must stay above it
        if expr_has_special(pred):
            return None
        new_filter = pred._node if pd.filters is None else (Expression(pd.filters) & pred)._node
        return child.with_pushdowns(pd.with_filters(new_filter))

    return None


def _filter_into_join(f: Filter, j: Join) -> Optional[LogicalPlan]:
    if j.how not in ("inner", "semi", "anti", "left", "right"):
        return None
    # map join-output column name -> (side, original name)
    lk = [e.name() for e in j.left_on]
    origin: Dict[str, Tuple[str, str]] = {}
    for i, ln in enumerate(lk):
        origin[ln] = ("key", ln)
    for fld in j.left.schema:
        if fld.name not in origin:
            origin[fld.name] = ("left", fld.name)
    lnames = set(j.left.schema.field_names())
    rk = [e.name() for e in j.right_on]
    for fld in j.right.schema:
        if fld.name in rk:
            continue
        out_name = fld.name if fld.name not in lnames else f"{j.suffix}{fld.name}"
        if out_name not in origin:
            origin[out_name] = ("right", fld.name)

    conjuncts = _split_conjuncts(f.predicate)
    to_left: List[Expression] = []
    to_right: List[Expression] = []
    keep: List[Expression] = []
    for c in conjuncts:
        cols = expr_input_columns(c)
        sides = set()
        ok = True
        for cc in cols:
            o = origin.get(cc)
            if o is None:
                ok = False
                break
            sides.add(o[0])
        if not ok or expr_has_special(c):
            keep.append(c)
            continue
        side_set = sides - {"key"}
        if not side_set:
            # references only join keys; output keys coalesce from the preserved
            # side, so treat as that side (left unless it's a right join)
            side_set = {"right"} if j.how == "right" else {"left"}
        if side_set == {"left"} and j.how in ("inner", "left", "semi", "anti"):
            to_left.append(c)
        elif side_set == {"right"} and j.how in ("inner", "right"):
            # rename output cols back to right-side names
            ren = {out: col(orig) for out, (s, orig) in origin.items() if s == "right"}
            to_right.append(substitute_columns(c, ren))
        else:
            keep.append(c)
    if not to_left and not to_right:
        return None
    # keys referenced by right-side pushdown are left names; remap keys for right side
    new_left = j.left
    new_right = j.right
    if to_left:
        new_left = Filter(new_left, _and_all(to_left))
    if to_right:
        key_map = {ln: j.right_on[i] for i, ln in enumerate(lk)}
        to_right = [substitute_columns(c, key_map) for c in to_right]
        new_right = Filter(new_right, _and_all(to_right))
    new_join = Join(new_left, new_right, j.left_on, j.right_on, j.how, j.strategy, j.suffix)
    if keep:
        return Filter(new_join, _and_all(keep))
    return new_join


# ---------------------------------------------------------------------------
# limit pushdown
# ---------------------------------------------------------------------------

def _push_down_limit(p: LogicalPlan) -> Optional[LogicalPlan]:
    if not isinstance(p, Limit):
        return None
    child = p.input
    if isinstance(child, Limit):
        return Limit(child.input, min(p.limit, child.limit), p.eager)
    if isinstance(child, Project):
        if any(expr_has_special(e) for e in child.exprs):
            return None
        return Project(Limit(child.input, p.limit, p.eager), child.exprs)
    if isinstance(child, ScanSource):
        pd = child.pushdowns()
        if pd.limit is not None and pd.limit <= p.limit:
            return None
        new_limit = p.limit if pd.limit is None else min(pd.limit, p.limit)
        # keep the Limit node: per-task limits still need a global cap
        return Limit(child.with_pushdowns(pd.with_limit(new_limit)), p.limit, p.eager)
    if isinstance(child, Concat):
        a, b = child.input, child.other
        need = (isinstance(a, Limit) and a.limit <= p.limit) and (
            isinstance(b, Limit) and b.limit <= p.limit)
        if need:
            return None
        return Limit(Concat(Limit(a, p.limit, p.eager), Limit(b, p.limit, p.eager)), p.limit, p.eager)
    return None


# ---------------------------------------------------------------------------
# repartition elision
# ---------------------------------------------------------------------------

def _drop_repartition(p: LogicalPlan) -> Optional[LogicalPlan]:
    if not isinstance(p, Repartition):
        return None
    child = p.input
    if isinstance(child, Repartition):
        return Repartition(child.input, p.scheme, p.num, p.by, p.descending)
    if p.scheme in ("into", "random", "hash") and p.num == 1 and child.num_partitions() == 1:
        return child
    return None


# ---------------------------------------------------------------------------
# projection folding
# ---------------------------------------------------------------------------

def _fold_projections(p: LogicalPlan) -> Optional[LogicalPlan]:
    if not isinstance(p, Project):
        return None
    child = p.input
    if isinstance(child, Project):
        defs: Dict[str, Expression] = {}
        for e in child.exprs:
            if expr_has_special(e):
                return None
            defs[e.name()] = e if is_trivial_passthrough(e) is None else col(is_trivial_passthrough(e))
        # inline each outer expr; bail if any inner def would be duplicated into
        # a non-trivial expression more than once (avoid recompute blowup)
        use_count: Dict[str, int] = {}
        for e in p.exprs:
            for c in expr_input_columns(e):
                use_count[c] = use_count.get(c, 0) + 1
        for name, d in defs.items():
            if is_trivial_passthrough(d) is None and use_count.get(name, 0) > 1:
                return None
        new_exprs = [substitute_columns(e, defs).alias(e.name()) for e in p.exprs]
        return Project(child.input, new_exprs)
    # identity projection over the full child schema -> drop
    names = [e.name() for e in p.exprs]
    if names == child.schema.field_names() and all(
        is_trivial_passthrough(e) == e.name() for e in p.exprs
    ):
        return child
    return None


# ---------------------------------------------------------------------------
# column pruning (single deterministic pass)
# ---------------------------------------------------------------------------

def _restrict(required: Optional[List[str]], schema_names: List[str]) -> List[str]:
    if required is None:
        return list(schema_names)
    return [c for c in schema_names if c in required]


def _prune_columns(p: LogicalPlan, required: Optional[List[str]]) -> LogicalPlan:
    """Push the set of needed columns toward sources; install scan column
    pushdowns. required=None means every column is needed."""
    if isinstance(p, ScanSource):
        pd = p.pushdowns()
        want = _restrict(required, p.schema.field_names())
        if required is not None and want != p.schema.field_names():
            return p.with_pushdowns(pd.with_columns(want))
        return p

    if isinstance(p, InMemorySource):
        want = _restrict(required, p.schema.field_names())
        if required is not None and want != p.schema.field_names():
            return Project(p, [col(c) for c in want])
        return p

    if isinstance(p, Project):
        keep = [e for e in p.exprs if required is None or e.name() in required
                or expr_has_special(e)]
        if not keep:
            keep = p.exprs[:1]
        need: List[str] = []
        for e in keep:
            for c in expr_input_columns(e):
                if c not in need:
                    need.append(c)
        need = [c for c in p.input.schema.field_names() if c in need]
        new_child = _prune_columns(p.input, need)
        return Project(new_child, keep)

    if isinstance(p, Filter):
        need = None if required is None else list(required)
        if need is not None:
            for c in expr_input_columns(p.predicate):
                if c not in need:
                    need.append(c)
        new_child = _prune_columns(p.input, need)
        out: LogicalPlan = Filter(new_child, p.predicate)
        if required is not None and [f for f in out.schema.field_names() if f in required] != out.schema.field_names():
            want = _restrict(required, out.schema.field_names())
            out = Project(out, [col(c) for c in want])
        return out

    if isinstance(p, Aggregate):
        need: List[str] = []
        for e in p.groupby + p.aggregations:
            for c in expr_input_columns(e):
                if c not in need:
                    need.append(c)
        need = [c for c in p.input.schema.field_names() if c in need] or p.input.schema.field_names()[:1]
        return Aggregate(_prune_columns(p.input, need), p.aggregations, p.groupby)

    if isinstance(p, Pivot):
        need = []
        for e in p.groupby + [p.pivot_col, p.value_col]:
            for c in expr_input_columns(e):
                if c not in need:
                    need.append(c)
        need = [c for c in p.input.schema.field_names() if c in need]
        return Pivot(_prune_columns(p.input, need), p.groupby, p.pivot_col, p.value_col,
                     p.agg_fn, p.names)

    if isinstance(p, Join):
        lneed: Optional[List[str]] = None
        rneed: Optional[List[str]] = None
        if required is not None:
            lnames = set(p.left.schema.field_names())
            rk = [e.name() for e in p.right_on]
            lneed = []
            rneed = []
            for e in p.left_on:
                for c in expr_input_columns(e):
                    if c not in lneed:
                        lneed.append(c)
            for e in p.right_on:
                for c in expr_input_columns(e):
                    if c not in rneed:
                        rneed.append(c)
            for fld in p.left.schema:
                if fld.name in required and fld.name not in lneed:
                    lneed.append(fld.name)
            for fld in p.right.schema:
                out_name = fld.name if fld.name not in lnames else f"{p.suffix}{fld.name}"
                if (out_name in required or fld.name in required) and fld.name not in rneed:
                    if fld.name in rk and out_name not in required:
                        continue
                    rneed.append(fld.name)
            lneed = [c for c in p.left.schema.field_names() if c in lneed]
            rneed = [c for c in p.right.schema.field_names() if c in rneed]
        new_left = _prune_columns(p.left, lneed)
        new_right = _prune_columns(p.right, rneed)
        return Join(new_left, new_right, p.left_on, p.right_on, p.how, p.strategy, p.suffix)

    if isinstance(p, (Sort, Repartition)):
        need = None if required is None else list(required)
        if need is not None:
            exprs = p.sort_by if isinstance(p, Sort) else p.by
            for e in exprs:
                for c in expr_input_columns(e):
                    if c not in need:
                        need.append(c)
            need = [c for c in p.input.schema.field_names() if c in need]
        return p.with_children([_prune_columns(p.input, need)])

    if isinstance(p, Explode):
        need = None if required is None else list(required)
        if need is not None:
            for e in p.to_explode:
                for c in expr_input_columns(e):
                    if c not in need:
                        need.append(c)
            need = [c for c in p.input.schema.field_names() if c in need]
        return Explode(_prune_columns(p.input, need), p.to_explode)

    if isinstance(p, Unpivot):
        need = []
        for e in p.ids + p.values:
            for c in expr_input_columns(e):
                if c not in need:
                    need.append(c)
        need = [c for c in p.input.schema.field_names() if c in need]
        return Unpivot(_prune_columns(p.input, need), p.ids, p.values,
                       p.variable_name, p.value_name)

    if isinstance(p, Distinct):
        # distinct semantics depend on all visible columns: don't prune below
        return p.with_children([_prune_columns(p.input, None)])

    if isinstance(p, Concat):
        # both sides must keep identical layouts
        need = None if required is None else _restrict(required, p.schema.field_names())
        a = _prune_columns(p.input, need)
        b = _prune_columns(p.other, need)
        if a.schema.field_names() != b.schema.field_names():
            a = _prune_columns(p.input, None)
            b = _prune_columns(p.other, None)
        return Concat(a, b)

    # default: pass full requirement through (Limit, Sample, Write, MonotonicId)
    kids = p.children()
    if not kids:
        return p
    if isinstance(p, (Limit, Sample)):
        return p.with_children([_prune_columns(kids[0], required)])
    return p.with_children([_prune_columns(k, None) for k in kids])
