"""Process-level metrics registry with a Prometheus-text dump.

The span tree (spans.py) is per-query; this registry is the process-wide
view the future serving layer scrapes: counters (monotonic totals),
gauges (last-set values), and histograms (fixed buckets + sum/count).
``METRICS.render_prometheus()`` emits the text exposition format, so a
serving endpoint is one ``return METRICS.render_prometheus()`` away.

``record_query_metrics`` folds one finished query's RuntimeStats into the
standard engine metrics — it runs at every plan execution's end whether or
not per-query profiling was armed, so the registry is always live.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Sequence

__all__ = ["Counter", "Gauge", "Histogram", "MetricsRegistry", "METRICS",
           "record_query_metrics"]

# seconds-scale latency buckets (queries run ms..minutes)
DEFAULT_BUCKETS = (0.005, 0.02, 0.1, 0.5, 1.0, 5.0, 30.0, 120.0, 600.0)

_NAME_OK = set("abcdefghijklmnopqrstuvwxyz"
               "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789_:")


def _check_name(name: str) -> str:
    from ..errors import DaftValueError

    if not name or name[0].isdigit() or not set(name) <= _NAME_OK:
        raise DaftValueError(f"invalid metric name {name!r}")
    return name


class Counter:
    """Monotonic total."""

    kind = "counter"

    def __init__(self, name: str, help_text: str = ""):
        self.name = _check_name(name)
        self.help = help_text
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]


class Gauge:
    """Last-set value (pool depth, ledger balance, breaker state...)."""

    kind = "gauge"

    def __init__(self, name: str, help_text: str = ""):
        self.name = _check_name(name)
        self.help = help_text
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def render(self) -> List[str]:
        return [f"{self.name} {_fmt(self.value)}"]


class Histogram:
    """Fixed-bucket histogram (cumulative counts, Prometheus semantics)."""

    kind = "histogram"

    def __init__(self, name: str, help_text: str = "",
                 buckets: Sequence[float] = DEFAULT_BUCKETS):
        self.name = _check_name(name)
        self.help = help_text
        self.buckets = tuple(sorted(buckets))
        self._lock = threading.Lock()
        self._counts = [0] * len(self.buckets)
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._sum += v
            self._count += 1
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self._counts[i] += 1

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def render(self) -> List[str]:
        with self._lock:
            counts, total, s = list(self._counts), self._count, self._sum
        out = []
        for le, c in zip(self.buckets, counts):
            out.append(f'{self.name}_bucket{{le="{_fmt(le)}"}} {c}')
        out.append(f'{self.name}_bucket{{le="+Inf"}} {total}')
        out.append(f"{self.name}_sum {_fmt(s)}")
        out.append(f"{self.name}_count {total}")
        return out


def _fmt(v: float) -> str:
    if float(v).is_integer():
        return str(int(v))
    return repr(float(v))


class MetricsRegistry:
    """Get-or-create registry; a name re-registered with a different metric
    kind is an error (two subsystems silently sharing a counter would
    corrupt both)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}

    def _get(self, cls, name: str, help_text: str, **kw):
        from ..errors import DaftValueError

        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help_text, **kw)
            elif not isinstance(m, cls):
                raise DaftValueError(
                    f"metric {name!r} already registered as {m.kind}, "
                    f"not {cls.kind}")
            return m

    def counter(self, name: str, help_text: str = "") -> Counter:
        return self._get(Counter, name, help_text)

    def gauge(self, name: str, help_text: str = "") -> Gauge:
        return self._get(Gauge, name, help_text)

    def histogram(self, name: str, help_text: str = "",
                  buckets: Sequence[float] = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help_text, buckets=buckets)

    def snapshot(self) -> Dict[str, float]:
        """Flat name -> value view (histograms expose _sum/_count)."""
        with self._lock:
            metrics = list(self._metrics.values())
        out: Dict[str, float] = {}
        for m in metrics:
            if isinstance(m, Histogram):
                out[f"{m.name}_sum"] = m.sum
                out[f"{m.name}_count"] = m.count
            else:
                out[m.name] = m.value
        return out

    def render_prometheus(self) -> str:
        """Prometheus text exposition of every registered metric."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        lines: List[str] = []
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            lines.extend(m.render())
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        """Drop every metric (tests only)."""
        with self._lock:
            self._metrics.clear()


METRICS = MetricsRegistry()

# RuntimeStats counter -> process counter folded per finished execution
_FOLDED_COUNTERS = {
    "spilled_partitions": "daft_tpu_spilled_partitions_total",
    "spill_write_bytes": "daft_tpu_spill_write_bytes_total",
    "spill_read_bytes": "daft_tpu_spill_read_bytes_total",
    "prefetch_hits": "daft_tpu_prefetch_hits_total",
    "prefetch_misses": "daft_tpu_prefetch_misses_total",
    "faults_injected": "daft_tpu_faults_injected_total",
    "device_breaker_trips": "daft_tpu_device_breaker_trips_total",
    "degraded_completions": "daft_tpu_degraded_completions_total",
    "deadline_expired": "daft_tpu_deadline_expired_total",
    "fused_chains": "daft_tpu_fused_chains_total",
}


def record_query_metrics(stats, wall_ns: int,
                         registry: Optional[MetricsRegistry] = None) -> None:
    """Fold one finished plan execution into the process registry. ``stats``
    is the query's RuntimeStats — cumulative across AQE stages, so only the
    DELTA since the last fold of this handle is added (keeps the process
    counters monotonic without double-counting multi-stage queries)."""
    reg = registry if registry is not None else METRICS
    reg.counter("daft_tpu_queries_total",
                "plan executions completed (AQE stages count "
                "individually)").inc()
    reg.histogram("daft_tpu_query_seconds",
                  "wall time per plan execution").observe(wall_ns / 1e9)
    snap = stats.snapshot()
    counters = snap["counters"]
    prev = getattr(stats, "_metrics_folded", None) or {}
    rows_total = sum(snap["op_rows"].values())
    reg.counter("daft_tpu_io_wait_seconds_total",
                "consumer-thread blocked IO time").inc(max(
        counters.get("io_wait_ns", 0) - prev.get("io_wait_ns", 0), 0) / 1e9)
    reg.counter("daft_tpu_rows_emitted_total",
                "rows emitted by root operators").inc(max(
        rows_total - prev.get("__rows", 0), 0))
    for key, metric in _FOLDED_COUNTERS.items():
        n = counters.get(key, 0) - prev.get(key, 0)
        if n > 0:
            reg.counter(metric).inc(n)
    folded = dict(counters)
    folded["__rows"] = rows_total
    stats._metrics_folded = folded
    try:
        # health + ledger gauges (breaker state, ledger balances incl.
        # prefetch/async-spill in-flight, scheduler window, pool counts,
        # query-log depth) refresh at every query end — metrics_text()
        # carries memory pressure without any profiled run
        from ..obs.health import refresh_health_gauges

        refresh_health_gauges(reg)
    except Exception:
        pass  # obs unavailable during interpreter teardown
