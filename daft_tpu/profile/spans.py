"""Span-tree core of the structured query profiler.

A :class:`Profiler` is a per-query recorder. Every physical-op partition
execution opens a *span* (op name, partition index, parent span) and
background work — scheduler-dispatched tasks, async spill writes, scan
prefetches, unspill readaheads — opens spans under an explicitly *captured*
parent token, so work that hops threads stays attributed to the op that
caused it instead of becoming an orphan interval.

Span kinds:

- ``op``     one partition's worth of operator work (the driver's pull
             wrappers and the scheduler's worker-side task wrapper open
             these; their durations reconcile against RuntimeStats)
- ``phase``  a blocking sub-interval inside an op on the same thread
             (shuffle fanout, join build, sort boundaries, ...)
- ``bg``     background work on another thread (spill.write on the writer
             thread, prefetch.fetch on a pool worker, spill.read on the
             readahead pool), parented via ``capture()``/``activate()``

Besides spans, the profiler records *typed events* (breaker transitions,
fault injections, throttles, fusion outcomes) on the same clock
(``time.perf_counter_ns``), and *phases* — named nanosecond buckets
(io_wait, queue_wait, device_dispatch, jit_compile) attached to the
innermost open span of the current thread.

Cost discipline: the DISARMED singleton is what every RuntimeStats carries
by default. Its ``armed`` flag is False and every method is a constant-time
no-op returning shared singletons — the hot path allocates nothing when
profiling is off (guard-tested), and hot callers additionally gate on
``prof.armed`` so not even a kwargs dict is built.

Buffers are bounded: past ``max_spans``/``max_events`` new entries are
dropped and counted (``dropped_spans``/``dropped_events``) — a pathological
query degrades its own profile, never the process.
"""

from __future__ import annotations

import itertools
import threading
import time
from typing import Any, Dict, List, Optional

__all__ = ["Span", "Profiler", "DISARMED"]

# default buffer caps: ~100k spans is minutes of SF10 execution; a span is
# a few hundred bytes, so the worst-case buffer stays tens of MB
DEFAULT_MAX_SPANS = 100_000
DEFAULT_MAX_EVENTS = 20_000


class Span:
    """One recorded interval. ``dur_ns`` is set at close; ``phases`` maps
    phase name -> accumulated ns (plus ``*_bytes`` entries for transfer
    accounting); ``attrs`` carries small scalars (rows, ...)."""

    __slots__ = ("sid", "parent", "name", "op", "part", "kind", "thread",
                 "t0_ns", "dur_ns", "phases", "attrs")

    def __init__(self, sid: int, parent: Optional[int], name: str,
                 op: Optional[str], part: Optional[int], kind: str,
                 thread: str, t0_ns: int):
        self.sid = sid
        self.parent = parent
        self.name = name
        self.op = op
        self.part = part
        self.kind = kind
        self.thread = thread
        self.t0_ns = t0_ns
        self.dur_ns = 0
        self.phases: Optional[Dict[str, int]] = None
        self.attrs: Optional[Dict[str, Any]] = None

    def add_phase(self, key: str, ns: int) -> None:
        ph = self.phases
        if ph is None:
            ph = self.phases = {}
        ph[key] = ph.get(key, 0) + ns

    def set_attr(self, key: str, value: Any) -> None:
        at = self.attrs
        if at is None:
            at = self.attrs = {}
        at[key] = value

    def as_dict(self) -> dict:
        d = {"id": self.sid, "parent": self.parent, "name": self.name,
             "kind": self.kind, "thread": self.thread,
             "t0_ns": self.t0_ns, "dur_ns": self.dur_ns}
        if self.op is not None:
            d["op"] = self.op
        if self.part is not None:
            d["part"] = self.part
        if self.phases:
            d["phases"] = dict(self.phases)
        if self.attrs:
            d["attrs"] = dict(self.attrs)
        return d

    def __repr__(self) -> str:
        return (f"Span#{self.sid}({self.name!r}, kind={self.kind}, "
                f"dur={self.dur_ns / 1e6:.2f}ms, parent={self.parent})")


class _NoopCtx:
    """Shared do-nothing context manager for the disarmed fast path."""

    __slots__ = ()

    def __enter__(self):
        return None

    def __exit__(self, *exc):
        return False


_NOOP = _NoopCtx()


class _SpanCtx:
    """``with prof.span(...)`` handle (armed path)."""

    __slots__ = ("_prof", "_name", "_op", "_part", "_kind", "_attrs", "sp")

    def __init__(self, prof, name, op, part, kind, attrs):
        self._prof = prof
        self._name = name
        self._op = op
        self._part = part
        self._kind = kind
        self._attrs = attrs
        self.sp = None

    def __enter__(self) -> Span:
        self.sp = self._prof.begin(self._name, op=self._op, part=self._part,
                                   kind=self._kind)
        if self._attrs:
            self.sp.attrs = dict(self._attrs)
        return self.sp

    def __exit__(self, *exc):
        self._prof.end(self.sp)
        return False


class _Activation:
    """``with prof.activate(token)``: spans opened on this thread while the
    activation is live parent to ``token`` (the captured span id of the
    thread that caused this work)."""

    __slots__ = ("_prof", "_token", "_prev")

    def __init__(self, prof, token):
        self._prof = prof
        self._token = token
        self._prev = None

    def __enter__(self):
        tl = self._prof._tl
        self._prev = getattr(tl, "base", None)
        tl.base = self._token
        return self

    def __exit__(self, *exc):
        self._prof._tl.base = self._prev
        return False


class Profiler:
    """Per-query span/event recorder. Construct armed; the module-level
    ``DISARMED`` singleton is the always-off default every RuntimeStats
    starts with."""

    def __init__(self, query_id: Optional[str] = None, armed: bool = True,
                 max_spans: int = DEFAULT_MAX_SPANS,
                 max_events: int = DEFAULT_MAX_EVENTS):
        self.armed = armed
        self.query_id = query_id or f"q-{id(self):x}"
        self.max_spans = max_spans
        self.max_events = max_events
        self.t_start_ns = time.perf_counter_ns()
        self.t_end_ns: Optional[int] = None
        self.started_unix = time.time()
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._events: List[dict] = []
        self.dropped_spans = 0
        self.dropped_events = 0
        # phases recorded while NO span was open on the calling thread
        # (late IO after the stream closed): kept so profile totals still
        # reconcile with RuntimeStats counters
        self._unattributed: Dict[str, int] = {}
        self._seq = itertools.count(1)
        self._tl = threading.local()
        # high-water marks of what the chrome renderer has consumed: an AQE
        # query finishes one execute_plan per stage, and each stage must
        # render only ITS spans/events, never re-emit earlier stages'
        self._chrome_span_mark = 0
        self._chrome_event_mark = 0

    # ------------------------------------------------------------- spans
    def _stack(self) -> List[Span]:
        st = getattr(self._tl, "stack", None)
        if st is None:
            st = self._tl.stack = []
        return st

    def begin(self, name: str, op: Optional[str] = None,
              part: Optional[int] = None, kind: str = "op") -> Optional[Span]:
        """Open a span on this thread (explicit begin/end for driver loops
        where a ``with`` block cannot wrap the measured region)."""
        if not self.armed:
            return None
        st = self._stack()
        if st:
            parent = st[-1].sid
        else:
            parent = getattr(self._tl, "base", None)
        sp = Span(next(self._seq), parent, name, op, part, kind,
                  threading.current_thread().name, time.perf_counter_ns())
        st.append(sp)
        return sp

    def end(self, sp: Optional[Span]) -> None:
        if sp is None:
            return
        sp.dur_ns = time.perf_counter_ns() - sp.t0_ns
        st = self._stack()
        # tolerate a corrupted stack (a span leaked across a generator
        # suspension) by searching instead of asserting — profiles degrade,
        # queries never fail
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:
            st.remove(sp)
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped_spans += 1
            else:
                self._spans.append(sp)

    def cancel(self, sp: Optional[Span]) -> None:
        """Close a begun span WITHOUT recording it (the driver's final
        empty pull — a StopIteration — is not a partition)."""
        if sp is None:
            return
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()
        elif sp in st:
            st.remove(sp)

    def span(self, name: str, op: Optional[str] = None,
             part: Optional[int] = None, kind: str = "phase", **attrs):
        """Context-manager form; disarmed returns a shared no-op."""
        if not self.armed:
            return _NOOP
        return _SpanCtx(self, name, op, part, kind, attrs)

    def current(self) -> Optional[Span]:
        """This thread's innermost open span (None when idle/disarmed)."""
        st = getattr(self._tl, "stack", None)
        return st[-1] if st else None

    # ------------------------------------------ cross-thread propagation
    def capture(self) -> Optional[int]:
        """Token for the innermost open span of THIS thread (or the
        thread's own activation base). Hand it to background work so its
        spans attribute to the op that caused them."""
        if not self.armed:
            return None
        st = getattr(self._tl, "stack", None)
        if st:
            return st[-1].sid
        return getattr(self._tl, "base", None)

    def activate(self, token: Optional[int]):
        """Adopt a captured token as this thread's parent context."""
        if not self.armed:
            return _NOOP
        return _Activation(self, token)

    # ------------------------------------------------------------ phases
    def phase(self, key: str, ns: int) -> None:
        """Add ``ns`` to the named phase bucket of this thread's innermost
        open span (io_wait, queue_wait, device_dispatch, ...)."""
        if not self.armed:
            return
        st = getattr(self._tl, "stack", None)
        if st:
            st[-1].add_phase(key, ns)
        else:
            with self._lock:
                self._unattributed[key] = self._unattributed.get(key, 0) + ns

    # --------------------------------------------- cross-process splicing
    def splice(self, spans: List[dict], events: List[dict],
               parent: Optional[int], offset_ns: int,
               thread: Optional[str] = None) -> int:
        """Adopt a remote profiler's recorded subtree (span/event dicts
        from a worker telemetry fragment, obs/cluster.py): span ids are
        remapped into this profiler's sequence, intra-fragment parent
        links are preserved, fragment roots re-parent to ``parent`` (the
        driver-side span that caused the dispatch), and every timestamp
        shifts by ``offset_ns`` (the two processes' perf_counter clocks
        are unrelated). ``thread`` overrides the recorded thread name —
        the chrome trace renders one lane per worker process from it.

        Remote ``op`` spans are demoted to ``bg``: the driver's own op
        span already covers the remote wall, and a second op-kind span
        would double-count the per-op rollup. Buffer caps apply (overflow
        counts into ``dropped_spans``/``dropped_events``). Returns the
        number of spans adopted."""
        if not self.armed:
            return 0
        adopted = 0
        with self._lock:
            budget = self.max_spans - len(self._spans)
            if budget < len(spans):
                self.dropped_spans += len(spans) - max(0, budget)
                spans = spans[:max(0, budget)]
            # two passes: spans arrive in END order (children before their
            # parents), so the id map must exist before links resolve
            idmap = {d["id"]: next(self._seq) for d in spans}
            for d in spans:
                kind = d.get("kind", "bg")
                sp = Span(idmap[d["id"]],
                          idmap.get(d.get("parent"), parent),
                          d["name"], d.get("op"), d.get("part"),
                          "bg" if kind == "op" else kind,
                          thread or d.get("thread", "remote"),
                          int(d["t0_ns"]) + offset_ns)
                sp.dur_ns = int(d.get("dur_ns", 0))
                if d.get("phases"):
                    sp.phases = dict(d["phases"])
                if d.get("attrs"):
                    sp.attrs = dict(d["attrs"])
                self._spans.append(sp)
                adopted += 1
            for ev in events:
                if len(self._events) >= self.max_events:
                    self.dropped_events += 1
                    continue
                self._events.append({
                    "t_ns": int(ev.get("t_ns", 0)) + offset_ns,
                    "kind": str(ev.get("kind", "remote")),
                    "attrs": dict(ev.get("attrs") or {})})
        return adopted

    # ------------------------------------------------------------ events
    def event(self, kind: str, /, **attrs) -> None:
        """Typed instant on the span timeline (breaker transition, fault
        injection, throttle, fusion outcome, spill, ...). ``kind`` is
        positional-only so an attr may itself be named ``kind``."""
        if not self.armed:
            return
        ev = {"t_ns": time.perf_counter_ns(), "kind": kind, "attrs": attrs}
        with self._lock:
            if len(self._events) >= self.max_events:
                self.dropped_events += 1
            else:
                self._events.append(ev)

    # --------------------------------------------------------- lifecycle
    def finish(self) -> None:
        """Mark query end. Last-wins: an AQE query's shared profiler is
        finished once per stage, and the wall must cover the LAST stage,
        not stop at the first. Late background spans still record."""
        self.t_end_ns = time.perf_counter_ns()

    @property
    def wall_ns(self) -> int:
        end = self.t_end_ns
        if end is None:
            end = time.perf_counter_ns()
        return end - self.t_start_ns

    def spans_snapshot(self) -> List[Span]:
        with self._lock:
            return list(self._spans)

    def events_snapshot(self) -> List[dict]:
        with self._lock:
            return list(self._events)

    def unattributed_phases(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._unattributed)

    def drain_for_chrome(self):
        """(spans, events) not yet handed to the chrome renderer; advances
        the marks so per-stage flushes never duplicate earlier batches."""
        with self._lock:
            spans = self._spans[self._chrome_span_mark:]
            events = self._events[self._chrome_event_mark:]
            self._chrome_span_mark = len(self._spans)
            self._chrome_event_mark = len(self._events)
        return spans, events


# the process-wide "profiling is off" profiler: one shared instance, never
# armed, so the hot path's `stats.profiler.armed` check is one attribute
# load + bool test and every method is a no-op
DISARMED = Profiler(query_id="disarmed", armed=False, max_spans=0,
                    max_events=0)
